"""Expression-fusion layer tests (ops/lazy.py).

The reference batches chained ops into one remote call (DeferredExecution,
ray/common/deferred_execution.py:43); here chains accumulate as LazyExpr DAGs
and compile as ONE jit.  These tests pin the fusion semantics: laziness until
consumption, single compiled program per chain shape, scalar-value cache
sharing, diamond sharing, depth capping, and differential correctness.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.ops import lazy
from tests.utils import create_test_dfs, df_equals

@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("fusion internals require the TpuOnJax execution")


_rng = np.random.default_rng(3)


@pytest.fixture
def dfs():
    data = {
        "a": _rng.normal(size=500),
        "b": _rng.normal(size=500),
        "c": _rng.uniform(1, 2, size=500),
    }
    return create_test_dfs(data)


def _col(obj, i=0):
    return obj._query_compiler._modin_frame._columns[i]


def test_chain_stays_lazy_until_consumed(dfs):
    md, _ = dfs
    s = md["a"] * md["b"] + md["c"]
    assert _col(s).is_lazy
    s2 = (s * 2.0).abs()
    assert _col(s2).is_lazy
    # consumption materializes
    _ = s2.to_numpy()
    assert not _col(s2).is_lazy


def test_map_reduce_fuses_to_one_program(dfs):
    md, pdf = dfs
    before = dict(lazy._FUSED_CACHE)
    out = float((md["a"] * md["b"] + md["c"]).sum())
    new_keys = [k for k in lazy._FUSED_CACHE if k not in before]
    # exactly one new fused executable: mul+add+reduce in a single jit
    assert len(new_keys) == 1
    # key = (fingerprint, tail_key, (mesh shape, device epoch), donated)
    fingerprint, tail_key = new_keys[0][0], new_keys[0][1]
    ops_in_program = [node[0] for node in fingerprint[0]]
    assert ops_in_program == ["mul", "add"]
    assert tail_key[0] == "reduce" and tail_key[1] == "sum"
    expected = (pdf["a"] * pdf["b"] + pdf["c"]).sum()
    np.testing.assert_allclose(out, expected, rtol=1e-12)


def test_scalar_values_share_compilation(dfs):
    md, _ = dfs
    s2 = md["a"] * 2.0
    _ = s2.to_numpy()
    before = len(lazy._FUSED_CACHE)
    s3 = md["a"] * 3.0
    _ = s3.to_numpy()
    # same structure, different scalar: scalar is a runtime argument
    assert len(lazy._FUSED_CACHE) == before


def test_diamond_subexpression_computed_once(dfs):
    md, pdf = dfs
    shared = md["a"] * md["b"]
    out = shared + shared
    before = dict(lazy._FUSED_CACHE)
    result = out.to_numpy()
    new_keys = [k for k in lazy._FUSED_CACHE if k not in before]
    if new_keys:  # may be cached from a prior test run
        fingerprint = new_keys[0][0]
        ops = [node[0] for node in fingerprint[0]]
        assert ops.count("mul") == 1  # diamond: mul appears once
    expected = pdf["a"] * pdf["b"]
    np.testing.assert_allclose(result, (expected + expected).to_numpy())


def test_depth_cap_materializes_eagerly(dfs):
    md, pdf = dfs
    s, ps = md["a"], pdf["a"]
    for _ in range(lazy._MAX_NODES + 10):
        s = s + 1.0
        ps = ps + 1.0
    df_equals(s, ps)


def test_fused_chain_differential(dfs):
    md, pdf = dfs

    def pipeline(df):
        return ((df["a"] + df["b"]) * df["c"] - df["a"].abs()) / (df["c"] + 10.0)

    df_equals(pipeline(md), pipeline(pdf))


def test_fused_reductions_differential(dfs):
    md, pdf = dfs
    derived_md = md * 2.0 + 1.0
    derived_pd = pdf * 2.0 + 1.0
    for agg in ["sum", "mean", "std", "var", "min", "max", "count"]:
        df_equals(getattr(derived_md, agg)(), getattr(derived_pd, agg)())


def test_fused_axis1_reduction(dfs):
    md, pdf = dfs
    df_equals((md * 3.0).sum(axis=1), (pdf * 3.0).sum(axis=1))


def test_filter_syncs_only_a_scalar(dfs):
    # df[df.a > 0] on computed (cache-less) columns must not ship O(n)
    # masks/positions through the host: the only device_get before
    # materialization is the scalar kept-count
    from modin_tpu.parallel.engine import JaxWrapper

    md, pdf = dfs
    derived = md * 2.0  # computed columns: no host_cache anywhere
    fetched_sizes = []
    original = JaxWrapper.materialize.__func__

    def counting(cls, obj):
        import jax

        for leaf in jax.tree_util.tree_leaves(obj):
            fetched_sizes.append(int(np.asarray(leaf).size))
        return original(cls, obj)

    JaxWrapper.materialize = classmethod(counting)
    try:
        filtered = derived[derived["a"] > 0.0]
        assert fetched_sizes == [1], fetched_sizes  # just the count scalar
    finally:
        JaxWrapper.materialize = classmethod(original)
    df_equals(filtered, (pdf * 2.0)[(pdf * 2.0)["a"] > 0.0])


def test_filtered_frame_keeps_padding_invariant(dfs):
    # device compaction must re-pad outputs to pad_len(n_out) so columns
    # added later (padded for the new length) align physically
    md, pdf = dfs
    derived_md, derived_pd = md * 2.0, pdf * 2.0
    f_md = derived_md[derived_md["a"] > 0.5]
    f_pd = derived_pd[derived_pd["a"] > 0.5]
    f_md["d"] = np.arange(float(len(f_pd)))
    f_pd["d"] = np.arange(float(len(f_pd)))
    df_equals(f_md["a"] + f_md["d"], f_pd["a"] + f_pd["d"])
    df_equals(f_md.sum(axis=1), f_pd.sum(axis=1))


def test_dropna_keeps_host_cache_bit_exact():
    # a pure row-drop on cached columns must not round-trip values through
    # the (possibly lossy) device representation
    from modin_tpu.config import Float64Policy

    x = np.random.default_rng(8).normal(size=64)
    with Float64Policy.context("Downcast"):
        md = pd.DataFrame({"a": x})
        out = md.dropna()["a"].to_numpy()
    np.testing.assert_array_equal(out, x)


def test_comparison_and_filter_on_lazy(dfs):
    md, pdf = dfs
    md_out = md[(md["a"] * 2.0) > md["b"]]
    pd_out = pdf[(pdf["a"] * 2.0) > pdf["b"]]
    df_equals(md_out, pd_out)


def test_int_promotion_through_fusion():
    md, pdf = create_test_dfs({"i": np.arange(100, dtype=np.int64)})
    df_equals(md["i"] * 2, pdf["i"] * 2)
    df_equals(md["i"] / 4, pdf["i"] / 4)
    df_equals((md["i"] + 1).cumsum(), (pdf["i"] + 1).cumsum())


def test_non_registry_maps_on_lazy_frames(dfs):
    # fillna/round/clip/isna must accept deferred inputs (regression: they
    # fed LazyExprs straight into non-lazy jitted kernels and crashed)
    md, pdf = create_test_dfs({"a": [1.0, np.nan, 3.0, -4.0]})
    for fn in [
        lambda df: (df * 2.0).fillna(0.0),
        lambda df: (df * 2.0).round(1),
        lambda df: (df * 2.0).clip(lower=-2.5, upper=5.0),
        lambda df: (df * 2.0).isna(),
        lambda df: (df * 2.0).notna(),
        lambda df: (df * 2.0).dropna(),
    ]:
        df_equals(fn(md), fn(pdf))


def test_bool_chain_through_fusion(dfs):
    md, pdf = dfs
    df_equals(
        (md["a"] > 0) & (md["b"] < 0) | (md["c"] > 1.5),
        (pdf["a"] > 0) & (pdf["b"] < 0) | (pdf["c"] > 1.5),
    )


# ---------------------------------------------------------------------- #
# graftplan satellite: edge cases the deferred planner leans on
# ---------------------------------------------------------------------- #


def test_diamond_fingerprint_stability(dfs):
    """Structurally identical graphs (built twice, including a diamond)
    linearize to the SAME fingerprint over the same leaves — the property
    the planner's CSE and the executable cache both rely on."""
    md, _ = dfs
    qc = md._query_compiler

    def build():
        col_a = qc._modin_frame._columns[0].raw
        col_b = qc._modin_frame._columns[1].raw
        shared = lazy.lazy_op("mul", col_a, col_b)
        return lazy.lazy_op("add", shared, shared)

    nodes1, out1, leaves1, _, fp1 = lazy._linearize([build()])
    nodes2, out2, leaves2, _, fp2 = lazy._linearize([build()])
    assert fp1 == fp2
    # diamond: the shared mul node appears once, referenced twice
    assert [n[0] for n in nodes1] == ["mul", "add"]
    add_refs = nodes1[1][1]
    assert add_refs[0] == add_refs[1] == ("n", 0)


def test_max_nodes_diamond_not_overcounted(dfs):
    """A diamond-heavy graph whose cheap ``size`` upper bound overflows
    _MAX_NODES but whose DISTINCT node count does not must stay lazy:
    lazy_op re-measures with _distinct_size before materializing."""
    md, _ = dfs
    qc = md._query_compiler
    col = qc._modin_frame._columns[0].raw
    expr = lazy.lazy_op("add", col, 0.0)
    # doubling a diamond k times gives size ~2^k but only k+1 distinct nodes
    for _ in range(lazy._MAX_NODES.bit_length() + 4):
        expr = lazy.lazy_op("add", expr, expr)
    assert lazy.is_lazy(expr), "diamond sharing was double-counted"
    assert lazy._distinct_size(expr) <= lazy._MAX_NODES


def test_max_nodes_overflow_materializes_midchain(dfs):
    """A genuinely deep chain crosses _MAX_NODES and materializes the
    overflowing expression immediately (bounding XLA program size), and the
    final result stays correct."""
    md, pdf = dfs
    s, ps = md["a"], pdf["a"]
    for i in range(lazy._MAX_NODES + 5):
        s = s + float(i % 3)
        ps = ps + float(i % 3)
    # somewhere mid-chain an expression was forced: the current column is
    # within the fresh window, not one giant graph
    col = _col(s)
    if col.is_lazy:
        assert lazy._distinct_size(col.raw) <= lazy._MAX_NODES
    df_equals(s, ps)


def test_scalar_weak_typing_distinguishes_int_and_float(dfs):
    """df*2 and df*3 share one executable (scalars are runtime args), but
    df*2 and df*2.0 must NOT: jax weak-types Python scalars by class, and
    conflating them would change promotion semantics."""
    md, _ = dfs
    lazy._FUSED_CACHE.clear()  # isolate from shapes cached by earlier tests
    _ = (md["a"] * 2).to_numpy()
    before = len(lazy._FUSED_CACHE)
    _ = (md["a"] * 3).to_numpy()
    assert len(lazy._FUSED_CACHE) == before  # int scalar shares
    _ = (md["a"] * 2.5).to_numpy()
    assert len(lazy._FUSED_CACHE) == before + 1  # float scalar does not


def test_fused_cache_lru_bound_and_eviction_metric():
    """The fused-executable cache respects MODIN_TPU_FUSED_CACHE_SIZE as an
    LRU bound, counts evictions, and recompiles evicted shapes correctly."""
    from modin_tpu.config import FusedCacheSize
    from modin_tpu.logging.metrics import add_metric_handler, clear_metric_handler

    md, pdf = create_test_dfs({"a": np.arange(64, dtype=np.float64)})
    seen = {}

    def on_metric(name, value):
        seen[name] = seen.get(name, 0) + value

    add_metric_handler(on_metric)
    evictions_before = lazy.fused_cache_evictions()
    try:
        with FusedCacheSize.context(2):
            # distinct chain shapes so each pipeline is its own cache entry
            pipelines = [
                lambda df: df["a"] * 2.0,
                lambda df: df["a"] + 1.5,
                lambda df: (df["a"] * 2.0) + 1.5,
                lambda df: df["a"].abs() * 0.5,
            ]
            for fn in pipelines:
                fn(md).to_numpy()
                assert lazy.fused_cache_len() <= 2
            assert lazy.fused_cache_evictions() > evictions_before
            assert seen.get("modin_tpu.fusion.cache.evict", 0) > 0
            # an evicted shape recompiles and stays correct
            df_equals(pipelines[0](md), pipelines[0](pdf))
    finally:
        clear_metric_handler(on_metric)


def test_fused_cache_lru_recency_order():
    """Re-hitting an entry refreshes its recency: the least-recently USED
    entry is evicted, not the least-recently inserted."""
    from modin_tpu.config import FusedCacheSize

    md, _ = create_test_dfs({"a": np.arange(32, dtype=np.float64)})
    with FusedCacheSize.context(0):  # unbounded while we seed
        lazy._FUSED_CACHE.clear()
        (md["a"] * 2.0).to_numpy()   # shape A
        (md["a"] + 1.0).to_numpy()   # shape B
        assert lazy.fused_cache_len() == 2
        keys = list(lazy._FUSED_CACHE)
        (md["a"] * 5.0).to_numpy()   # hit shape A -> A becomes most recent
        assert list(lazy._FUSED_CACHE)[-1] == keys[0]
    with FusedCacheSize.context(2):
        (md["a"].abs()).to_numpy()   # shape C: evicts B (LRU), keeps A
        assert keys[0] in lazy._FUSED_CACHE
        assert keys[1] not in lazy._FUSED_CACHE
