"""Tests for the peripheral public APIs: distributed buffers, extensions,
experimental integrations (reference: modin/tests/pandas/extensions/,
modin/tests/experimental/)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals


class TestDistributedAPI:
    def test_unwrap_and_from_partitions_roundtrip(self):
        from modin_tpu.distributed.dataframe.pandas import (
            from_partitions,
            unwrap_partitions,
        )

        md, pdf = create_test_dfs({"a": np.arange(100.0), "b": np.arange(100)})
        parts = unwrap_partitions(md)
        assert len(parts) == 2
        rebuilt = from_partitions(parts, index=md.index)
        df_equals(rebuilt, pdf)

    def test_unwrap_exposes_device_arrays(self):
        from modin_tpu.distributed.dataframe.pandas import unwrap_partitions
        from modin_tpu.utils import get_current_execution

        if get_current_execution() != "TpuOnJax":
            pytest.skip("device backend only")
        import jax

        md, _ = create_test_dfs({"a": np.arange(64.0)})
        (label, buf), = unwrap_partitions(md)
        assert isinstance(buf, jax.Array)
        # consumer can run jit computations directly on the exported buffer
        assert float(jax.numpy.sum(buf[:64])) == float(np.arange(64.0).sum())

    def test_from_partitions_numpy(self):
        from modin_tpu.distributed.dataframe.pandas import from_partitions

        df = from_partitions([("x", np.arange(10)), ("y", np.arange(10) * 2.0)])
        assert list(df.columns) == ["x", "y"]
        assert df["y"].sum() == 90.0


class TestExtensions:
    def test_register_dataframe_accessor(self):
        from modin_tpu.pandas.api.extensions import register_dataframe_accessor

        @register_dataframe_accessor("testing_acc")
        class MyAccessor:
            def __init__(self, df):
                self._df = df

            def double_sum(self):
                return (self._df * 2).sum()

        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        df_equals(md.testing_acc.double_sum(), (pdf * 2).sum())

    def test_register_series_method(self):
        from modin_tpu.pandas.api.extensions import register_series_accessor

        @register_series_accessor("plus_one")
        def plus_one(self):
            return self + 1

        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        df_equals(md["a"].plus_one(), pdf["a"] + 1)

    def test_register_pd_accessor(self):
        from modin_tpu.pandas.api.extensions import register_pd_accessor

        @register_pd_accessor("my_fn")
        def my_fn():
            return 42

        assert pd.my_fn() == 42


class TestExperimental:
    def test_train_test_split(self):
        from modin_tpu.experimental.sklearn.model_selection import train_test_split

        md, _ = create_test_dfs({"a": np.arange(100), "b": np.arange(100) * 2})
        train, test = train_test_split(md, test_size=0.3, random_state=0)
        assert len(train) == 70 and len(test) == 30
        combined = pd.concat([train, test]).sort_index()
        df_equals(combined, md)

    def test_torch_dataloader(self):
        torch = pytest.importorskip("torch")
        from modin_tpu.experimental.torch import to_dataloader

        md, _ = create_test_dfs({"x1": np.arange(16.0), "x2": np.arange(16.0) * 2})
        loader = to_dataloader(md, batch_size=4)
        batches = list(loader)
        assert len(batches) == 4
        assert batches[0].shape == (4, 2)

    def test_batch_pipeline(self):
        from modin_tpu.experimental.batch import PandasQueryPipeline

        md, pdf = create_test_dfs({"a": np.arange(50.0)})
        pipeline = PandasQueryPipeline(md)
        pipeline.add_query(lambda df: df + 1)
        pipeline.add_query(lambda df: df * 2, is_output=True)
        pipeline.add_query(lambda df: df.sum(), is_output=True)
        out1, out2 = pipeline.compute_batch()
        df_equals(out1, (pdf + 1) * 2)
        df_equals(out2, ((pdf + 1) * 2).sum())

    def test_xgboost_native_available(self):
        # the native trainer works without the xgboost package (see
        # tests/test_xgboost_native.py for training behavior)
        from modin_tpu.experimental import xgboost as mxgb

        md, _ = create_test_dfs({"a": [1.0, 2.0], "y": [0.0, 1.0]})
        dm = mxgb.DMatrix(md[["a"]], label=md["y"])
        assert dm.num_row() == 2 and dm.num_col() == 1


class TestInterchange:
    def test_dataframe_protocol(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3], "b": [1.5, 2.5, 3.5]})
        proto = md.__dataframe__()
        from pandas.api.interchange import from_dataframe

        df_equals(pd.DataFrame(from_dataframe(proto)), pdf)

    def test_from_dataframe_helper(self):
        from modin_tpu.pandas.utils import from_dataframe as modin_from_dataframe

        pdf = pandas.DataFrame({"a": [1, 2]})
        md = modin_from_dataframe(pdf.__dataframe__())
        df_equals(md, pdf)


class TestBackendSwitching:
    def test_mixed_backend_binary_coerces(self):
        import modin_tpu
        from modin_tpu.core.storage_formats.native.query_compiler import (
            NativeQueryCompiler,
        )
        from modin_tpu.utils import get_current_execution

        if get_current_execution() != "TpuOnJax":
            pytest.skip("needs the device default backend")
        md_device = pd.DataFrame({"a": np.arange(20.0)})
        modin_tpu.set_backend("Pandas")
        try:
            md_host = pd.DataFrame({"a": np.ones(20)})
            assert isinstance(md_host._query_compiler, NativeQueryCompiler)
        finally:
            modin_tpu.set_backend("Tpu")
        result = md_device + md_host  # mixed backends -> coerced, not crash
        df_equals(
            result,
            pandas.DataFrame({"a": np.arange(20.0) + 1}),
        )

    def test_cost_calculator_prefers_device_for_big(self):
        from modin_tpu.core.storage_formats.base.query_compiler_calculator import (
            BackendCostCalculator,
        )
        from modin_tpu.core.storage_formats.native.query_compiler import (
            NativeQueryCompiler,
        )
        from modin_tpu.core.storage_formats.tpu.query_compiler import (
            TpuQueryCompiler,
        )

        big_device = pd.DataFrame({"a": np.arange(1000.0)})._query_compiler
        small_host = NativeQueryCompiler(pandas.DataFrame({"a": [1.0] * 10}))
        calc = BackendCostCalculator("add")
        calc.add_query_compiler(big_device)
        calc.add_query_compiler(small_host)
        assert calc.calculate() is type(big_device)


class TestFuzzydata:
    def test_run_workflow(self):
        from modin_tpu.experimental.fuzzydata import run_workflow

        trace = run_workflow(seed=123, steps=6)
        assert len(trace) == 6


class TestFallbackResidue:
    """VERDICT r3 #10: every generated API fallback should reach a NAMED QC
    method; the residue is pinned here so it can only shrink."""

    ALLOWED_DF = {"to_iceberg"}  # needs pyiceberg; no QC value in routing
    ALLOWED_SERIES = {"hist", "info", "sparse"}  # display/accessor-only

    @staticmethod
    def _residue(pandas_cls, modin_cls, routes):
        import inspect

        from modin_tpu.core.storage_formats.base.query_compiler import (
            BaseQueryCompiler,
        )

        out = set()
        for name in dir(modin_cls):
            if name.startswith("_"):
                continue
            raw = inspect.getattr_static(modin_cls, name)
            wrapped = getattr(raw, "__wrapped__", None)
            if wrapped is None or getattr(pandas_cls, name, None) is not wrapped:
                continue  # explicit implementation, not a generated fallback
            qc_name = routes.get(name)
            qc_m = getattr(BaseQueryCompiler, qc_name, None) if qc_name else None
            if qc_m is None or not getattr(
                qc_m, "_pandas_signature_default", False
            ):
                out.add(name)
        return out

    def test_dataframe_residue_pinned(self):
        from modin_tpu.core.storage_formats.base.query_compiler import (
            DATAFRAME_QC_ROUTES,
        )
        from modin_tpu.pandas.dataframe import DataFrame

        residue = self._residue(pandas.DataFrame, DataFrame, DATAFRAME_QC_ROUTES)
        assert residue <= self.ALLOWED_DF, f"new unrouted fallbacks: {residue - self.ALLOWED_DF}"

    def test_series_residue_pinned(self):
        from modin_tpu.core.storage_formats.base.query_compiler import (
            SERIES_QC_ROUTES,
        )
        from modin_tpu.pandas.series import Series

        residue = self._residue(pandas.Series, Series, SERIES_QC_ROUTES)
        assert residue <= self.ALLOWED_SERIES, f"new unrouted fallbacks: {residue - self.ALLOWED_SERIES}"


class TestWriterWiring:
    def test_reindex_like(self):
        from tests.utils import create_test_dfs, eval_general

        md, pdf = create_test_dfs({"a": [1.0, 2, 3], "b": [4.0, 5, 6]})
        other = pandas.DataFrame({"a": [0.0, 0.0], "c": [0.0, 0.0]}, index=[1, 9])
        eval_general(md, pdf, lambda df: df.reindex_like(other))
        eval_general(md["a"], pdf["a"], lambda s: s.reindex_like(other["a"]))

    def test_to_stata_roundtrip(self, tmp_path):
        from tests.utils import create_test_dfs

        md, pdf = create_test_dfs({"a": [1.0, 2, 3], "b": [4, 5, 6]})
        mp_, pp = tmp_path / "m.dta", tmp_path / "p.dta"
        md.to_stata(str(mp_), time_stamp=pandas.Timestamp("2020-01-01"))
        pdf.to_stata(str(pp), time_stamp=pandas.Timestamp("2020-01-01"))
        pandas.testing.assert_frame_equal(
            pandas.read_stata(mp_), pandas.read_stata(pp)
        )

    def test_to_xml_identical(self):
        from tests.utils import create_test_dfs

        md, pdf = create_test_dfs({"a": [1, 2], "b": ["x", "y"]})
        try:
            want = pdf.to_xml()
        except ImportError:
            pytest.skip("no xml writer backend installed")
        assert md.to_xml() == want

    def test_series_to_csv_and_sql(self, tmp_path):
        import sqlite3

        from tests.utils import create_test_dfs

        md, pdf = create_test_dfs({"v": [1.5, 2.5, 3.5]})
        ms, ps = md["v"], pdf["v"]
        assert ms.to_csv() == ps.to_csv()
        # UNNAMED series: pandas emits header/column '0', never the internal
        # unnamed-column sentinel
        mu = ms.rename(None)
        pu = ps.rename(None)
        assert mu.to_csv() == pu.to_csv()
        mdb, pdb = tmp_path / "m.db", tmp_path / "p.db"
        with sqlite3.connect(mdb) as c:
            ms.to_sql("t", c, index=False)
            mu.to_sql("u", c, index=False)
        with sqlite3.connect(pdb) as c:
            ps.to_sql("t", c, index=False)
            pu.to_sql("u", c, index=False)
        with sqlite3.connect(mdb) as c1, sqlite3.connect(pdb) as c2:
            for table in ("t", "u"):
                got = pandas.read_sql(f"SELECT * FROM {table}", c1)
                want = pandas.read_sql(f"SELECT * FROM {table}", c2)
                pandas.testing.assert_frame_equal(got, want)
