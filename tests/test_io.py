"""IO differential tests (modeled on modin/tests/pandas/test_io.py):
round-trips against pandas-written files, chunked-reader parity."""

import io
import os

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.core.io.chunker import (
    _split_record_ranges_py,
    find_header_end,
    split_record_ranges,
)
from tests.utils import df_equals, eval_general, require_tpu_execution


@pytest.fixture
def csv_file(tmp_path):
    rng = np.random.default_rng(11)
    n = 5000
    pdf = pandas.DataFrame(
        {
            "a": rng.integers(0, 1000, n),
            "b": rng.uniform(-1, 1, n).round(6),
            "c": rng.choice(["x", "yy", "z,comma", 'q"uote'], n),
            "d": rng.random(n) < 0.5,
        }
    )
    path = tmp_path / "data.csv"
    pdf.to_csv(path, index=False)
    return str(path), pdf


class TestChunker:
    def test_native_matches_python(self, csv_file):
        path, _ = csv_file
        buf = open(path, "rb").read()
        header_end = find_header_end(buf, 1)
        native = split_record_ranges(buf, header_end, 1000)
        py = _split_record_ranges_py(buf, header_end, 1000, '"', 4096)
        assert native == py
        # full coverage, no gaps/overlaps
        assert native[0][0] == header_end
        assert native[-1][1] == len(buf)
        for (s1, e1), (s2, e2) in zip(native, native[1:]):
            assert e1 == s2

    def test_chunks_align_to_records(self, csv_file):
        path, _ = csv_file
        buf = open(path, "rb").read()
        header_end = find_header_end(buf, 1)
        for start, end in split_record_ranges(buf, header_end, 777):
            assert end == len(buf) or buf[end - 1 : end] == b"\n"

    def test_quoted_newline_not_a_boundary(self):
        buf = b'a,b\n1,"line\nbreak"\n2,plain\n'
        header_end = find_header_end(buf, 1)
        ranges = split_record_ranges(buf, header_end, 5)
        rebuilt = b"".join(buf[s:e] for s, e in ranges)
        assert rebuilt == buf[header_end:]
        # the quoted newline at offset 11 must not end a chunk
        assert all(e != 12 for _, e in ranges)


class TestReadCSV:
    def test_roundtrip(self, csv_file):
        path, pdf = csv_file
        df_equals(pd.read_csv(path), pandas.read_csv(path))

    def test_parallel_path(self, csv_file, monkeypatch):
        import modin_tpu.core.io.text.csv_dispatcher as disp

        monkeypatch.setattr(disp.CSVDispatcher, "MIN_PARALLEL_BYTES", 1)
        path, pdf = csv_file
        df_equals(pd.read_csv(path), pandas.read_csv(path))

    def test_kwargs_passthrough(self, csv_file):
        path, _ = csv_file
        df_equals(
            pd.read_csv(path, usecols=["a", "b"]),
            pandas.read_csv(path, usecols=["a", "b"]),
        )
        df_equals(
            pd.read_csv(path, nrows=10), pandas.read_csv(path, nrows=10)
        )
        df_equals(
            pd.read_csv(path, skiprows=3), pandas.read_csv(path, skiprows=3)
        )
        df_equals(
            pd.read_csv(path, dtype={"a": "float64"}),
            pandas.read_csv(path, dtype={"a": "float64"}),
        )

    def test_buffer_input(self, csv_file):
        path, _ = csv_file
        content = open(path).read()
        df_equals(
            pd.read_csv(io.StringIO(content)), pandas.read_csv(io.StringIO(content))
        )

    def test_index_col(self, csv_file):
        path, _ = csv_file
        df_equals(
            pd.read_csv(path, index_col="a"), pandas.read_csv(path, index_col="a")
        )


class TestWriters:
    def test_to_csv_roundtrip(self, tmp_path, csv_file):
        path, pdf = csv_file
        md = pd.read_csv(path)
        out = tmp_path / "out.csv"
        md.to_csv(out, index=False)
        df_equals(pandas.read_csv(out), pandas.read_csv(path))

    def test_to_csv_string(self, csv_file):
        path, _ = csv_file
        md = pd.read_csv(path).head(5)
        pdf = pandas.read_csv(path).head(5)
        assert md.to_csv() == pdf.to_csv()


class TestParquet:
    def test_roundtrip(self, tmp_path):
        pytest.importorskip("pyarrow")
        pdf = pandas.DataFrame(
            {"x": np.arange(1000), "y": np.arange(1000) * 0.5, "s": ["v"] * 1000}
        )
        path = tmp_path / "data.parquet"
        pdf.to_parquet(path)
        df_equals(pd.read_parquet(str(path)), pandas.read_parquet(path))

    def test_to_parquet(self, tmp_path):
        pytest.importorskip("pyarrow")
        md = pd.DataFrame({"x": [1, 2, 3]})
        path = tmp_path / "out.parquet"
        md.to_parquet(str(path))
        df_equals(pandas.read_parquet(path), md.modin.to_pandas())

    def test_multi_row_group_read_parallel(self, tmp_path, monkeypatch):
        """The row-group-parallel read path must engage on ≥4-group files and
        match pandas exactly (reference: parquet_dispatcher.py:350)."""
        require_tpu_execution()
        pytest.importorskip("pyarrow")
        import modin_tpu.core.io.column_stores.parquet_dispatcher as disp

        rng = np.random.default_rng(7)
        n = 40_000
        pdf = pandas.DataFrame(
            {
                "i": rng.integers(-1000, 1000, n),
                "f": rng.normal(size=n),
                "s": rng.choice(["aa", "b", "ccc", None], n),
                "t": pandas.date_range("2020-01-01", periods=n, freq="s"),
            }
        )
        path = tmp_path / "multi.parquet"
        pdf.to_parquet(path, row_group_size=5000)  # 8 row groups

        calls = {"parallel": 0}
        orig = disp.ParquetDispatcher._read_table_row_group_parallel.__func__

        def spy(cls, p, columns, filters):
            calls["parallel"] += 1
            return orig(cls, p, columns, filters)

        monkeypatch.setattr(
            disp.ParquetDispatcher,
            "_read_table_row_group_parallel",
            classmethod(spy),
        )
        md = pd.read_parquet(str(path))
        df_equals(md, pandas.read_parquet(path))
        assert calls["parallel"] == 1
        # column pruning through the parallel path
        df_equals(
            pd.read_parquet(str(path), columns=["f", "i"]),
            pandas.read_parquet(path, columns=["f", "i"]),
        )

    def test_row_group_splits_balance(self):
        from modin_tpu.core.io.column_stores.parquet_dispatcher import (
            ParquetDispatcher,
        )

        for counts, n_tasks in [
            ([100] * 8, 4),
            ([1, 1, 1, 1000], 2),
            ([5], 4),
            ([10, 20, 30], 16),
            (list(range(1, 20)), 5),
        ]:
            splits = ParquetDispatcher._row_group_splits(counts, n_tasks)
            # exact contiguous cover, no empties, never more than n_tasks
            flat = [i for r in splits for i in r]
            assert flat == list(range(len(counts)))
            assert all(len(r) > 0 for r in splits)
            assert len(splits) <= max(1, min(n_tasks, len(counts)))

    def test_chunked_write_roundtrip(self, tmp_path, monkeypatch):
        """Streamed writer: multiple windows must concatenate into a file
        byte-equal in content to a single-shot pandas write, including a
        non-trivial index (reference: parquet_dispatcher.py:912)."""
        require_tpu_execution()
        pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        import modin_tpu.core.io.column_stores.parquet_dispatcher as disp

        monkeypatch.setattr(disp, "_WRITE_CHUNK_ROWS", 1000)
        rng = np.random.default_rng(13)
        n = 5500
        pdf = pandas.DataFrame(
            {
                "x": rng.integers(0, 100, n),
                "y": rng.normal(size=n),
                "s": rng.choice(["u", "vv", None], n),
            },
            index=pandas.Index(np.arange(n)[::-1], name="rid"),
        )
        md = pd.DataFrame(pdf)
        path = tmp_path / "chunked.parquet"
        md.to_parquet(str(path))
        assert pq.ParquetFile(path).metadata.num_row_groups >= 5
        df_equals(pandas.read_parquet(path), pdf)
        # default RangeIndex round-trips too (dropped then reconstructed)
        md2 = pd.DataFrame({"a": np.arange(2500)})
        path2 = tmp_path / "chunked2.parquet"
        md2.to_parquet(str(path2))
        df_equals(pandas.read_parquet(path2), md2.modin.to_pandas())

    def test_to_parquet_no_fallback_warning(self, tmp_path):
        require_tpu_execution()
        pytest.importorskip("pyarrow")
        import warnings

        md = pd.DataFrame({"x": np.arange(100), "s": ["a"] * 100})
        path = tmp_path / "nowarn.parquet"
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            md.to_parquet(str(path))
        df_equals(pandas.read_parquet(path), md.modin.to_pandas())


class TestOtherFormats:
    def test_json_roundtrip(self, tmp_path):
        pdf = pandas.DataFrame({"a": [1, 2], "b": ["x", "y"]})
        path = tmp_path / "d.json"
        pdf.to_json(path, orient="records", lines=True)
        df_equals(
            pd.read_json(str(path), orient="records", lines=True),
            pandas.read_json(path, orient="records", lines=True),
        )

    def test_pickle_roundtrip(self, tmp_path):
        md = pd.DataFrame({"a": [1, 2, 3]})
        path = tmp_path / "d.pkl"
        md.to_pickle(str(path))
        df_equals(pd.read_pickle(str(path)), md)


class TestParallelPathEngages:
    def test_public_read_uses_parallel_path(self, tmp_path, monkeypatch):
        """Regression: default-bound kwargs (no_default sentinels) must not
        disqualify the chunked path, and the native chunker must accept the
        mmap buffer."""
        _require_tpu()
        import modin_tpu.core.io.text.csv_dispatcher as disp

        rng = np.random.default_rng(3)
        n = 400_000
        pandas.DataFrame(
            {"a": rng.integers(0, 9, n), "b": rng.uniform(0, 1, n)}
        ).to_csv(tmp_path / "big.csv", index=False)

        calls = {"parallel": 0}
        orig = disp.CSVDispatcher._read_parallel.__func__

        def spy(cls, path, kwargs):
            calls["parallel"] += 1
            return orig(cls, path, kwargs)

        monkeypatch.setattr(disp.CSVDispatcher, "_read_parallel", classmethod(spy))
        monkeypatch.setattr(disp.CSVDispatcher, "MIN_PARALLEL_BYTES", 1)
        md = pd.read_csv(str(tmp_path / "big.csv"))
        # under MODIN_TPU_PLAN=Auto the read is deferred into a scan plan;
        # comparing materializes it, and the parallel path must have engaged
        df_equals(md, pandas.read_csv(tmp_path / "big.csv"))
        assert calls["parallel"] == 1

    def test_chunker_no_truncation_many_chunks(self):
        """Regression: bodies larger than max_chunks*target must not lose rows."""
        body = b"x\n" + b"1\n" * 100_000
        ranges = split_record_ranges(bytes(body), 2, 8, max_chunks=16)
        assert ranges[-1][1] == len(body)
        assert sum(e - s for s, e in ranges) == len(body) - 2


def _require_tpu():
    import pytest as _pytest

    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        _pytest.skip("parallel dispatchers are wired to the TpuOnJax factory")


class TestParallelJSONFWF:
    def test_read_json_lines_parallel(self, tmp_path, monkeypatch):
        _require_tpu()
        import modin_tpu.core.io.text.json_dispatcher as disp

        rng = np.random.default_rng(5)
        n = 30_000
        pdf = pandas.DataFrame(
            {
                "a": rng.normal(size=n),
                "b": rng.integers(0, 100, n),
                "s": np.array([f'v_{i % 40}"x' for i in range(n)]),
            }
        )
        path = tmp_path / "data.jsonl"
        pdf.to_json(path, orient="records", lines=True)

        calls = {"parallel": 0}
        orig = disp.JSONDispatcher._read_parallel.__func__

        def spy(cls, p, kwargs):
            calls["parallel"] += 1
            return orig(cls, p, kwargs)

        monkeypatch.setattr(disp.JSONDispatcher, "_read_parallel", classmethod(spy))
        monkeypatch.setattr(disp.JSONDispatcher, "MIN_PARALLEL_BYTES", 1)
        md = pd.read_json(str(path), lines=True)
        assert calls["parallel"] == 1
        df_equals(md, pandas.read_json(path, lines=True))

    def test_read_json_non_lines_falls_back(self, tmp_path):
        pdf = pandas.DataFrame({"a": [1, 2, 3]})
        path = tmp_path / "plain.json"
        pdf.to_json(path)
        df_equals(pd.read_json(str(path)), pandas.read_json(path))

    @pytest.mark.parametrize("colspec_mode", ["infer", "explicit", "widths"])
    def test_read_fwf_parallel(self, tmp_path, monkeypatch, colspec_mode):
        _require_tpu()
        import modin_tpu.core.io.text.fwf_dispatcher as disp

        n = 20_000
        path = tmp_path / "data.fwf"
        with open(path, "w") as f:
            f.write("%-12s%-10s%-14s\n" % ("alpha", "beta", "gamma"))
            for i in range(n):
                f.write("%-12d%-10.3f%-14s\n" % (i, i * 0.5, f"tag{i % 9}"))

        kwargs = {}
        if colspec_mode == "explicit":
            kwargs["colspecs"] = [(0, 12), (12, 22), (22, 36)]
        elif colspec_mode == "widths":
            kwargs["widths"] = [12, 10, 14]

        calls = {"parallel": 0}
        orig = disp.FWFDispatcher._read_parallel.__func__

        def spy(cls, p, kw):
            calls["parallel"] += 1
            return orig(cls, p, kw)

        monkeypatch.setattr(disp.FWFDispatcher, "_read_parallel", classmethod(spy))
        monkeypatch.setattr(disp.FWFDispatcher, "MIN_PARALLEL_BYTES", 1)
        md = pd.read_fwf(str(path), **kwargs)
        assert calls["parallel"] == 1
        df_equals(md, pandas.read_fwf(path, **kwargs))

    def test_read_fwf_skiprows(self, tmp_path, monkeypatch):
        _require_tpu()
        import modin_tpu.core.io.text.fwf_dispatcher as disp

        path = tmp_path / "skip.fwf"
        with open(path, "w") as f:
            f.write("junk line\n")
            f.write("%-8s%-8s\n" % ("x", "y"))
            for i in range(5_000):
                f.write("%-8d%-8d\n" % (i, i * 2))
        monkeypatch.setattr(disp.FWFDispatcher, "MIN_PARALLEL_BYTES", 1)
        df_equals(
            pd.read_fwf(str(path), skiprows=1),
            pandas.read_fwf(path, skiprows=1),
        )


class TestNativeExcel:
    """xlsx IO through the in-tree OOXML parser (no engine installed)."""

    @pytest.fixture
    def frame(self):
        return pd.DataFrame(
            {
                "i": [1, 2, 3],
                "f": [1.5, np.nan, 3.25],
                "s": ["alpha", "beta & <gamma>", "delta"],
                "b": [True, False, True],
                "d": pandas.to_datetime(
                    ["2024-01-02 03:04:05", "2024-06-07 00:00:00", "2025-12-31 23:59:59"]
                ),
            }
        )

    def test_roundtrip(self, frame, tmp_path):
        p = tmp_path / "t.xlsx"
        frame.to_excel(p, index=False)
        back = pd.read_excel(p)._to_pandas()
        want = frame._to_pandas()
        assert back["i"].tolist() == want["i"].tolist()
        np.testing.assert_allclose(back["f"].fillna(-1), want["f"].fillna(-1))
        assert back["s"].tolist() == want["s"].tolist()
        assert back["b"].tolist() == want["b"].tolist()
        assert (back["d"] == want["d"]).all()

    def test_index_and_sheet_name(self, frame, tmp_path):
        p = tmp_path / "t.xlsx"
        frame.to_excel(p, sheet_name="Data")
        back = pd.read_excel(p, sheet_name="Data", index_col=0)
        assert back.shape == (3, 5)
        assert pd.read_excel(p, sheet_name=None).keys() == {"Data"}

    def test_header_skiprows_nrows_usecols(self, frame, tmp_path):
        p = tmp_path / "t.xlsx"
        frame.to_excel(p, index=False)
        assert pd.read_excel(p, skiprows=1, header=None, nrows=2).shape == (2, 5)
        assert list(pd.read_excel(p, usecols=[0, 1]).columns) == ["i", "f"]

    def test_unsupported_kwarg_raises(self, frame, tmp_path):
        p = tmp_path / "t.xlsx"
        frame.to_excel(p, index=False)
        try:
            import openpyxl  # noqa: F401

            pytest.skip("engine installed; fallback not reachable")
        except ImportError:
            pass
        with pytest.raises(ImportError, match="decimal"):
            pd.read_excel(p, decimal=",")

    def test_series_to_excel(self, tmp_path):
        p = tmp_path / "s.xlsx"
        pd.Series([1, 2], name="x").to_excel(p)
        assert pd.read_excel(p, index_col=0).shape == (2, 1)


def test_experimental_sql_query():
    from modin_tpu.experimental import sql

    a = pd.DataFrame({"k": [1, 2, 1, 3], "v": [10.0, 20.0, 30.0, 40.0]})
    b = pd.DataFrame({"k": [1, 2], "lbl": ["x", "y"]})
    r = sql.query(
        "SELECT a.k AS k, SUM(a.v) AS s, b.lbl AS lbl "
        "FROM a JOIN b ON a.k=b.k GROUP BY a.k, b.lbl ORDER BY a.k",
        a=a, b=b,
    )._to_pandas()
    assert r["k"].tolist() == [1, 2]
    assert r["s"].tolist() == [40.0, 20.0]
    assert r["lbl"].tolist() == ["x", "y"]


class TestStreamedTextWriters:
    """to_csv/to_json stream per-window device fetches + appends (reference
    pattern: per-partition writes, parquet_dispatcher.py:912); the streamed
    file must be byte-identical to a single pandas write."""

    @pytest.fixture
    def frame(self, monkeypatch):
        import modin_tpu.core.io.text.csv_dispatcher as csv_mod

        monkeypatch.setattr(csv_mod, "_WRITE_CHUNK_ROWS", 37)
        rng = np.random.default_rng(9)
        n = 211
        data = {
            "i": rng.integers(-100, 100, n),
            "f": rng.normal(size=n),
            "s": [f"v{i},x\"q\"" if i % 7 == 0 else f"p{i}" for i in range(n)],
        }
        return pd.DataFrame(data), pandas.DataFrame(data)

    def test_to_csv_streamed_identical(self, frame, tmp_path):
        md, pdf = frame
        mp_, pp = tmp_path / "m.csv", tmp_path / "p.csv"
        assert md.to_csv(str(mp_)) is None
        pdf.to_csv(str(pp))
        assert mp_.read_bytes() == pp.read_bytes()

    def test_to_csv_options(self, frame, tmp_path):
        md, pdf = frame
        for kw in (
            {"index": False},
            {"sep": ";"},
            {"header": False},
            {"na_rep": "NULL", "float_format": "%.3f"},
            {"columns": ["f", "s"]},
        ):
            mp_, pp = tmp_path / "m.csv", tmp_path / "p.csv"
            md.to_csv(str(mp_), **kw)
            pdf.to_csv(str(pp), **kw)
            assert mp_.read_bytes() == pp.read_bytes(), kw

    def test_to_csv_no_path_returns_string(self, frame):
        md, pdf = frame
        assert md.to_csv() == pdf.to_csv()

    def test_to_csv_compressed_falls_back_correct(self, frame, tmp_path):
        md, pdf = frame
        mp_, pp = tmp_path / "m.csv.gz", tmp_path / "p.csv.gz"
        md.to_csv(str(mp_))
        pdf.to_csv(str(pp))
        assert pandas.read_csv(mp_, index_col=0).equals(pandas.read_csv(pp, index_col=0))

    def test_to_csv_nontrivial_index(self, frame, tmp_path):
        md, pdf = frame
        md = md.set_index("s")
        pdf = pdf.set_index("s")
        mp_, pp = tmp_path / "m.csv", tmp_path / "p.csv"
        md.to_csv(str(mp_))
        pdf.to_csv(str(pp))
        assert mp_.read_bytes() == pp.read_bytes()

    def test_to_json_lines_streamed_identical(self, frame, tmp_path):
        md, pdf = frame
        mp_, pp = tmp_path / "m.jsonl", tmp_path / "p.jsonl"
        assert md.to_json(str(mp_), orient="records", lines=True) is None
        pdf.to_json(str(pp), orient="records", lines=True)
        assert mp_.read_bytes() == pp.read_bytes()

    def test_to_json_other_orients_fall_back_correct(self, frame, tmp_path):
        md, pdf = frame
        mp_, pp = tmp_path / "m.json", tmp_path / "p.json"
        md.to_json(str(mp_))
        pdf.to_json(str(pp))
        assert mp_.read_bytes() == pp.read_bytes()
        assert md.to_json() == pdf.to_json()

    def test_streamed_write_no_full_gather(self, frame, tmp_path, monkeypatch):
        require_tpu_execution()
        # the streamed path must never call qc.to_pandas() on the FULL frame
        md, _ = frame
        qc = md._query_compiler
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        n_full = qc.get_axis_len(0)
        orig = qc_mod.TpuQueryCompiler.to_pandas
        seen = []

        def spy(self, *a, **k):
            seen.append(self.get_axis_len(0))
            return orig(self, *a, **k)

        monkeypatch.setattr(qc_mod.TpuQueryCompiler, "to_pandas", spy)
        md.to_csv(str(tmp_path / "m.csv"))
        assert seen and all(s < n_full for s in seen)

    def test_append_gate_rejects_archives_and_urls(self):
        from modin_tpu.core.io.text.csv_dispatcher import appendable_local_path

        assert appendable_local_path("/tmp/a.csv", "infer")
        assert appendable_local_path("/tmp/a.csv", None)
        # pandas infer_compression is case-insensitive and covers .tar
        # (.tgz is NOT compressed per pandas, so it streams as plain text)
        for bad in ("a.csv.GZ", "a.csv.tar", "a.csv.gz", "a.csv.zip"):
            assert not appendable_local_path(bad, "infer"), bad
        assert not appendable_local_path("s3://bucket/a.csv", "infer")
        assert not appendable_local_path("https://h/a.csv", "infer")
        assert not appendable_local_path(None, "infer")
        assert not appendable_local_path("/tmp/a.csv", "gzip")
        # explicit compression=None writes plain text regardless of suffix
        assert appendable_local_path("/tmp/a.csv.gz", None)

    def test_to_json_lines_without_orient_raises_like_pandas(self, frame, tmp_path):
        md, pdf = frame
        eval_general(
            md, pdf, lambda df, p=tmp_path: df.to_json(str(p / "x.jsonl"), lines=True)
        )

    def test_to_json_explicit_no_compression_streams(self, frame, tmp_path):
        require_tpu_execution()
        md, pdf = frame
        mp_, pp = tmp_path / "m.jsonl", tmp_path / "p.jsonl"
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        n_full = md._query_compiler.get_axis_len(0)
        seen = []
        orig = qc_mod.TpuQueryCompiler.to_pandas

        def spy(self, *a, **k):
            seen.append(self.get_axis_len(0))
            return orig(self, *a, **k)

        import pytest as _pytest
        mp = _pytest.MonkeyPatch()
        try:
            mp.setattr(qc_mod.TpuQueryCompiler, "to_pandas", spy)
            md.to_json(str(mp_), orient="records", lines=True, compression=None)
        finally:
            mp.undo()
        pdf.to_json(str(pp), orient="records", lines=True, compression=None)
        assert mp_.read_bytes() == pp.read_bytes()
        assert seen and all(s < n_full for s in seen)


class TestFeather:
    """Record-batch-parallel read + chunk-streamed write (the IPC analogue
    of the parquet row-group paths)."""

    def test_roundtrip_multibatch(self, tmp_path, monkeypatch):
        require_tpu_execution()
        import modin_tpu.core.io.column_stores.parquet_dispatcher as pq_mod

        monkeypatch.setattr(pq_mod, "_WRITE_CHUNK_ROWS", 50)
        rng = np.random.default_rng(11)
        n = 333
        data = {
            "i": rng.integers(-5, 5, n),
            "f": rng.normal(size=n),
            "s": rng.choice(["ab", "cd", "efg"], n),
        }
        md = pd.DataFrame(data)
        pdf = pandas.DataFrame(data)
        mp_, pp = tmp_path / "m.feather", tmp_path / "p.feather"
        assert md.to_feather(str(mp_)) is None
        pdf.to_feather(str(pp))
        # the streamed file has multiple record batches; both reads agree
        import pyarrow as pa

        with pa.memory_map(str(mp_)) as src:
            assert pa.ipc.open_file(src).num_record_batches >= 2
        got = pd.read_feather(str(mp_))
        want = pandas.read_feather(pp)
        pandas.testing.assert_frame_equal(got._to_pandas(), want)
        # and the parallel reader handles the single-batch pandas file too
        got2 = pd.read_feather(str(pp))
        pandas.testing.assert_frame_equal(got2._to_pandas(), want)

    def test_columns_selection(self, tmp_path):
        pdf = pandas.DataFrame({"a": [1, 2], "b": [3.0, 4.0], "c": ["x", "y"]})
        p = tmp_path / "t.feather"
        pdf.to_feather(p)
        got = pd.read_feather(str(p), columns=["c", "a"])
        pandas.testing.assert_frame_equal(
            got._to_pandas(), pandas.read_feather(p, columns=["c", "a"])
        )

    def test_nondefault_index_raises_like_pandas(self, tmp_path):
        from tests.utils import create_test_dfs, eval_general

        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        md, pdf = md.set_index(md["a"]._to_pandas()), pdf.set_index(pdf["a"])
        eval_general(
            md, pdf, lambda df, p=tmp_path: df.to_feather(str(p / "x.feather"))
        )

    def test_parallel_read_path_actually_engages(self, tmp_path, monkeypatch):
        """The frontend binds every signature default; the parallel reader
        must still engage (it was dead code before the default filter)."""
        require_tpu_execution()
        import modin_tpu.core.io.column_stores.parquet_dispatcher as disp

        rng = np.random.default_rng(3)
        n = 4000
        pdf = pandas.DataFrame(
            {
                "f": rng.normal(size=n),
                "cat": pandas.Categorical(rng.choice(["a", "b", "c"], n)),
            }
        )
        p = tmp_path / "multi.feather"
        import pyarrow as pa
        import pyarrow.feather as feather

        feather.write_feather(pdf, str(p), chunksize=500)  # 8 batches
        with pa.memory_map(str(p)) as src:
            assert pa.ipc.open_file(src).num_record_batches >= 4

        calls = {"n": 0}
        orig = disp.FeatherDispatcher._read_ipc_batch_parallel.__func__

        def spy(cls, path, columns):
            calls["n"] += 1
            return orig(cls, path, columns)

        monkeypatch.setattr(
            disp.FeatherDispatcher, "_read_ipc_batch_parallel", classmethod(spy)
        )
        got = pd.read_feather(str(p))
        assert calls["n"] == 1
        # categorical columns exercise the per-task handle isolation
        pandas.testing.assert_frame_equal(got._to_pandas(), pandas.read_feather(p))
        got2 = pd.read_feather(str(p), columns=["cat"])
        assert calls["n"] == 2
        pandas.testing.assert_frame_equal(
            got2._to_pandas(), pandas.read_feather(p, columns=["cat"])
        )

    def test_use_threads_false_stays_serial(self, tmp_path, monkeypatch):
        import modin_tpu.core.io.column_stores.parquet_dispatcher as disp

        pdf = pandas.DataFrame({"a": range(100)})
        p = tmp_path / "t.feather"
        pdf.to_feather(p)

        def boom(cls, path, columns):
            raise AssertionError("parallel path must not engage")

        monkeypatch.setattr(
            disp.FeatherDispatcher,
            "_read_ipc_batch_parallel",
            classmethod(boom),
        )
        got = pd.read_feather(str(p), use_threads=False)
        pandas.testing.assert_frame_equal(got._to_pandas(), pdf)

    def test_streamed_write_all_null_later_window(self, tmp_path, monkeypatch):
        """A later chunk whose object column is entirely null must keep the
        first window's schema (feather AND parquet)."""
        import modin_tpu.core.io.column_stores.parquet_dispatcher as disp

        monkeypatch.setattr(disp, "_WRITE_CHUNK_ROWS", 100)
        n = 350
        s = ["x"] * 100 + [None] * 250
        md = pd.DataFrame({"a": np.arange(n), "s": s})
        pdf = pandas.DataFrame({"a": np.arange(n), "s": s})
        fp = tmp_path / "m.feather"
        md.to_feather(str(fp))
        pdf.to_feather(tmp_path / "p.feather")
        pandas.testing.assert_frame_equal(
            pandas.read_feather(fp),
            pandas.read_feather(tmp_path / "p.feather"),
        )
        pp = tmp_path / "m.parquet"
        md.to_parquet(str(pp))
        pandas.testing.assert_frame_equal(
            pandas.read_parquet(pp), pdf.reset_index(drop=True)
        )


class TestNullLeadingWindowWrite:
    """ADVICE r4: a sparse object column whose FIRST streamed window is
    entirely null used to pin a pa.null schema, and the first non-null chunk
    then failed the cast.  The writers now detect the null-typed field and
    fall back to the single-shot write, matching pandas' whole-column
    inference."""

    @staticmethod
    def _sparse_frame(n=300):
        vals = np.array([None] * n, dtype=object)
        vals[n - 10 :] = "tail-strings"
        return {"a": np.arange(n), "s": vals}

    def test_parquet_null_leading_window(self, tmp_path, monkeypatch):
        require_tpu_execution()
        import modin_tpu.core.io.column_stores.parquet_dispatcher as pq_mod

        monkeypatch.setattr(pq_mod, "_WRITE_CHUNK_ROWS", 50)
        data = self._sparse_frame()
        md, pdf = pd.DataFrame(data), pandas.DataFrame(data)
        mp_, pp = tmp_path / "m.parquet", tmp_path / "p.parquet"
        md.to_parquet(str(mp_))
        pdf.to_parquet(str(pp))
        pandas.testing.assert_frame_equal(
            pandas.read_parquet(mp_), pandas.read_parquet(pp)
        )

    def test_feather_null_leading_window(self, tmp_path, monkeypatch):
        require_tpu_execution()
        import modin_tpu.core.io.column_stores.parquet_dispatcher as pq_mod

        monkeypatch.setattr(pq_mod, "_WRITE_CHUNK_ROWS", 50)
        data = self._sparse_frame()
        md, pdf = pd.DataFrame(data), pandas.DataFrame(data)
        mp_, pp = tmp_path / "m.feather", tmp_path / "p.feather"
        md.to_feather(str(mp_))
        pdf.to_feather(str(pp))
        pandas.testing.assert_frame_equal(
            pandas.read_feather(mp_), pandas.read_feather(pp)
        )

    def test_parquet_streamed_path_still_chunks(self, tmp_path, monkeypatch):
        # non-null frames keep the multi-row-group streamed write
        require_tpu_execution()
        import pyarrow.parquet as pq

        import modin_tpu.core.io.column_stores.parquet_dispatcher as pq_mod

        monkeypatch.setattr(pq_mod, "_WRITE_CHUNK_ROWS", 50)
        md = pd.DataFrame({"a": np.arange(300)})
        out = tmp_path / "chunked.parquet"
        md.to_parquet(str(out))
        assert pq.ParquetFile(out).num_row_groups >= 2


class TestHDF:
    """HDF dispatcher (core/io/column_stores/hdf_dispatcher.py).  pytables
    does not ship in this image, so the chunked paths are env-gated; the
    no-dependency behavior (pandas' canonical ImportError) is always
    asserted."""

    def test_missing_pytables_error_matches_pandas(self, tmp_path):
        pytest.importorskip("modin_tpu")
        try:
            import tables  # noqa: F401

            pytest.skip("pytables present; error-path not reachable")
        except ImportError:
            pass
        md = pd.DataFrame({"a": [1, 2]})
        pdf = pandas.DataFrame({"a": [1, 2]})
        eval_general(
            md, pdf, lambda df: df.to_hdf(str(tmp_path / "x.h5"), key="k")
        )
        # reader raises the same error type as pandas (pandas checks file
        # existence before the pytables import, so the file must exist)
        stub = tmp_path / "present.h5"
        stub.write_bytes(b"\x89HDF\r\n\x1a\n")
        with pytest.raises(ImportError):
            pandas.read_hdf(str(stub), key="k")
        with pytest.raises(ImportError):
            pd.read_hdf(str(stub), key="k")

    def test_roundtrip_chunked(self, tmp_path, monkeypatch):
        pytest.importorskip("tables")
        require_tpu_execution()
        import modin_tpu.core.io.column_stores.hdf_dispatcher as hdf_mod

        monkeypatch.setattr(hdf_mod, "_HDF_CHUNK_ROWS", 100)
        rng = np.random.default_rng(3)
        n = 512
        data = {"a": rng.integers(0, 9, n), "b": rng.normal(size=n)}
        md, pdf = pd.DataFrame(data), pandas.DataFrame(data)
        mp_, pp = tmp_path / "m.h5", tmp_path / "p.h5"
        md.to_hdf(str(mp_), key="k", format="table")
        pdf.to_hdf(str(pp), key="k", format="table")
        pandas.testing.assert_frame_equal(
            pandas.read_hdf(mp_, key="k"), pandas.read_hdf(pp, key="k")
        )
        got = pd.read_hdf(str(pp), key="k")
        pandas.testing.assert_frame_equal(got._to_pandas(), pandas.read_hdf(pp, key="k"))

    def test_fixed_format_serial(self, tmp_path):
        pytest.importorskip("tables")
        require_tpu_execution()
        pdf = pandas.DataFrame({"a": [1.5, 2.5]})
        pp = tmp_path / "fixed.h5"
        pdf.to_hdf(pp, key="k")  # fixed format
        got = pd.read_hdf(str(pp), key="k")
        pandas.testing.assert_frame_equal(got._to_pandas(), pandas.read_hdf(pp, key="k"))
