"""graftopt tests: the unified cost-based optimizer.

Four layers of coverage:

1. **Differential grid** — ``MODIN_TPU_OPT=Auto`` must be bit-exact against
   ``MODIN_TPU_OPT=Off`` and plain pandas under every forced router leg
   (kernel device/host, fused/staged, resident/windowed): the optimizer
   may re-route, never re-answer.
2. **Plan-time model units** — selectivity heuristics, per-node estimates,
   joint strategy legs (windowed ⇒ staged ⇒ no donation), frozen-table
   kernel crossovers.
3. **Re-plan mechanics** — wall_divergence threshold + noise floor,
   correction clamp and fold-in, once-per-(node, trigger) idempotence,
   recorded EXPLAIN events.
4. **Priors** — PERF_HISTORY ledger → per-row coefficients roundtrip,
   graceful degradation on missing/corrupt ledgers, forced-priors reset.
"""

import json

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import (
    FuseMode,
    KernelRouterMode,
    OptMode,
    OptReplanFactor,
    StreamMode,
)
from modin_tpu.ops import router
from modin_tpu.plan import ir, optimizer
from tests.utils import df_equals


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("graftopt rides the TpuOnJax query compiler")


_rng = np.random.default_rng(20)


@pytest.fixture
def csv_path(tmp_path):
    n = 4000
    pandas.DataFrame(
        {
            "a": _rng.integers(-10, 10, n),
            "b": _rng.uniform(0, 1, n),
            "c": _rng.uniform(-1, 1, n),
            "d": _rng.integers(0, 7, n),
            "e": _rng.uniform(0, 100, n),
        }
    ).to_csv(tmp_path / "opt.csv", index=False)
    return str(tmp_path / "opt.csv")


def _scan(csv_path, columns=("a", "b", "c", "d", "e")):
    from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher

    return ir.Scan(
        TpuCSVDispatcher,
        {"filepath_or_buffer": csv_path},
        pandas.Index(columns),
    )


def _reduce_plan(csv_path, method="sum"):
    scan = _scan(csv_path)
    mask = ir.Map((scan,), "gt", args=(0,), bool_out=True)
    filt = ir.Filter(scan, mask)
    proj = ir.Project(filt, ("b", "c"))
    return ir.Reduce(proj, method, {})


#: a frozen kernel-router calibration table (never measured): sort-shaped
#: device cost dominated by device_sort_s, host splines per family
_FROZEN_TABLE = {
    "rows": 100_000,
    "device_consume_s": 0.001,
    "device_hist_s": 0.002,
    "device_sort_s": 0.010,
    "host_median_low_s": 0.004,
    "host_median_high_s": 0.020,
    "host_quantile_low_s": 0.004,
    "host_quantile_high_s": 0.020,
    "host_nunique_low_s": 0.004,
    "host_nunique_high_s": 0.020,
    "host_mode_low_s": 0.004,
    "host_mode_high_s": 0.020,
}


# ---------------------------------------------------------------------- #
# 1. differential grid: Auto == Off == pandas under every forced leg
# ---------------------------------------------------------------------- #


def _pipeline_frames(csv_path):
    md = pd.read_csv(csv_path).query("a > 0")[["b", "c"]]
    ref = pandas.read_csv(csv_path).query("a > 0")[["b", "c"]]
    return md, ref


def _assert_differential(csv_path, agg):
    md, ref = _pipeline_frames(csv_path)
    auto = getattr(md, agg)().modin.to_pandas()
    with OptMode.context("Off"):
        md_off, _ = _pipeline_frames(csv_path)
        off = getattr(md_off, agg)().modin.to_pandas()
    expected = getattr(ref, agg)()
    pandas.testing.assert_series_equal(auto, expected)
    pandas.testing.assert_series_equal(off, expected)
    pandas.testing.assert_series_equal(auto, off)


@pytest.mark.parametrize("kernel", ["Auto", "Device", "Host"])
@pytest.mark.parametrize("agg", ["sum", "median"])
def test_differential_kernel_legs(csv_path, kernel, agg):
    with KernelRouterMode.context(kernel):
        _assert_differential(csv_path, agg)


@pytest.mark.parametrize("fuse", ["Auto", "Fused", "Staged"])
def test_differential_compile_legs(csv_path, fuse):
    with FuseMode.context(fuse):
        _assert_differential(csv_path, "sum")


@pytest.mark.parametrize("stream", ["Auto", "Resident", "Windowed"])
def test_differential_residency_legs(csv_path, stream):
    with StreamMode.context(stream):
        _assert_differential(csv_path, "sum")


def test_differential_with_frozen_calibration(csv_path):
    """A pre-seeded calibration table changes routing inputs, never
    answers."""
    router.set_calibration(dict(_FROZEN_TABLE))
    try:
        _assert_differential(csv_path, "median")
    finally:
        router.set_calibration(None)


# ---------------------------------------------------------------------- #
# Off really is off
# ---------------------------------------------------------------------- #


def test_off_mode_zero_allocations(csv_path):
    with OptMode.context("Off"):
        assert not optimizer.OPT_ON
        assert router._opt_consult is None
        before = optimizer.opt_alloc_count()
        md, ref = _pipeline_frames(csv_path)
        result = md.sum().modin.to_pandas()
        assert optimizer.opt_alloc_count() == before
    pandas.testing.assert_series_equal(result, ref.sum())
    # back to Auto: the consult hook is reinstalled
    assert optimizer.OPT_ON
    assert router._opt_consult is optimizer._consult


# ---------------------------------------------------------------------- #
# 2. plan-time model units
# ---------------------------------------------------------------------- #


def test_selectivity_heuristics(csv_path):
    scan = _scan(csv_path)

    def mk(method, *children):
        return ir.Map(children or (scan,), method, bool_out=True)

    assert optimizer.estimate_selectivity(mk("eq")) == pytest.approx(0.1)
    assert optimizer.estimate_selectivity(mk("ne")) == pytest.approx(0.9)
    assert optimizer.estimate_selectivity(mk("gt")) == pytest.approx(0.5)
    assert optimizer.estimate_selectivity(mk("isna")) == pytest.approx(0.2)
    assert optimizer.estimate_selectivity(mk("notna")) == pytest.approx(0.8)
    conj = mk("and", mk("gt"), mk("eq"))
    assert optimizer.estimate_selectivity(conj) == pytest.approx(0.05)
    disj = mk("or", mk("notna"), mk("ne"))
    assert optimizer.estimate_selectivity(disj) == pytest.approx(1.0)
    inv = mk("invert", mk("eq"))
    assert optimizer.estimate_selectivity(inv) == pytest.approx(0.9)
    # unknown shapes stay conservative
    assert optimizer.estimate_selectivity(scan) == pytest.approx(0.8)


def test_estimates_flow_bottom_up(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = optimizer.choose(root)
    by_node = {id(n): strategies.by_node[id(n)] for n in ir.walk(root)}
    scan_st = by_node[id(root.children[0].children[0].children[0])]
    red_st = by_node[id(root)]
    assert scan_st.est_bytes and scan_st.est_bytes > 0
    assert scan_st.est_rows and scan_st.est_rows > 0
    # cumulative seconds: the root's estimate includes the whole subtree
    assert red_st.est_s >= scan_st.est_s > 0.0
    # the reduction collapsed the axis
    assert red_st.est_rows == 1


def test_plan_cost_prefers_pruned_scan(csv_path):
    full = ir.Reduce(_scan(csv_path), "sum", {})
    pruned_scan = _scan(csv_path)
    pruned_scan.pruned = ("b",)
    pruned_scan.pushed = True
    pruned = ir.Reduce(pruned_scan, "sum", {})
    assert optimizer.plan_cost(pruned) < optimizer.plan_cost(full)


def test_choose_joint_constraints_windowed(csv_path):
    """windowed residency forces a staged compile and forbids donation."""
    root = _reduce_plan(csv_path, "sum")
    with StreamMode.context("Windowed"):
        strategies = optimizer.choose(root)
    st = strategies.by_node[id(root)]
    assert st.legs["residency"] == "windowed"
    assert st.legs["compile"] == "staged"
    assert {"residency", "compile"} <= st.firm
    assert st.donate is False


def test_choose_annotates_kernel_leg(csv_path):
    root = _reduce_plan(csv_path, "median")
    router.set_calibration(dict(_FROZEN_TABLE))
    try:
        strategies = optimizer.choose(root)
        st = strategies.by_node[id(root)]
        assert st.legs.get("kernel") in ("device", "host", "view")
        assert st.leg_ops["kernel"] == "median"
        assert st.legs["residency"] in ("resident", "windowed")
        # pre-divergence the annotation is advisory, never firm
        assert "kernel" not in st.firm
    finally:
        router.set_calibration(None)


def test_kernel_leg_flips_host_under_correction(csv_path):
    """A correction folding measured device slowness into the model must
    flip the planned kernel leg across the calibrated crossover."""
    root = _reduce_plan(csv_path, "median")
    router.set_calibration(dict(_FROZEN_TABLE))
    try:
        strategies = optimizer.choose(root)
        assert strategies.by_node[id(root)].legs["kernel"] == "device"
        strategies.correction = optimizer.MAX_CORRECTION
        strategies = optimizer.choose(root, state=strategies)
        assert strategies.by_node[id(root)].legs["kernel"] == "host"
    finally:
        router.set_calibration(None)


# ---------------------------------------------------------------------- #
# 3. re-plan mechanics
# ---------------------------------------------------------------------- #


def _installed(root):
    strategies = optimizer.choose(root)
    optimizer.begin(strategies, root, {})
    return strategies


def test_observe_below_factor_never_replans(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = _installed(root)
    try:
        st = strategies.by_node[id(root)]
        st.est_s = 0.010
        with OptReplanFactor.context(4.0):
            optimizer.observe(root, 0.039)
        assert st.measured_s == pytest.approx(0.039)
        assert strategies.replans == []
        assert strategies.correction == 1.0
    finally:
        optimizer.end()


def test_observe_noise_floor(csv_path):
    """Sub-noise-floor walls never re-plan, however wrong the estimate."""
    root = _reduce_plan(csv_path, "sum")
    strategies = _installed(root)
    try:
        st = strategies.by_node[id(root)]
        st.est_s = 1e-9
        optimizer.observe(root, optimizer.REPLAN_NOISE_FLOOR_S)
        assert strategies.replans == []
    finally:
        optimizer.end()


def test_observe_divergence_replans_once(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = _installed(root)
    try:
        st = strategies.by_node[id(root)]
        st.est_s = 0.010
        with OptReplanFactor.context(4.0):
            optimizer.observe(root, 0.060)
            assert len(strategies.replans) == 1
            event = strategies.replans[0]
            assert event["trigger"] == "wall_divergence"
            assert event["correction"] == pytest.approx(6.0)
            assert strategies.correction == pytest.approx(6.0)
            # idempotent per (node, trigger): the same node re-observed
            # slow again must NOT fire a second time
            strategies.by_node[id(root)].est_s = 0.010
            optimizer.observe(root, 0.080)
        assert len(strategies.replans) == 1
    finally:
        optimizer.end()


def test_correction_clamped(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = _installed(root)
    try:
        strategies.by_node[id(root)].est_s = 1e-12
        optimizer.observe(root, 10.0)
        assert strategies.correction <= optimizer.MAX_CORRECTION
        assert len(strategies.replans) == 1
    finally:
        optimizer.end()


def test_replan_excludes_lowered_nodes(csv_path):
    """Already-lowered nodes (the memo) keep their annotations across a
    re-plan; only the remaining segment is re-chosen."""
    root = _reduce_plan(csv_path, "sum")
    scan = root.children[0].children[0].children[0]
    strategies = optimizer.choose(root)
    optimizer.begin(strategies, root, {id(scan): object()})
    try:
        frozen = strategies.by_node[id(scan)]
        frozen.est_s = 123.0  # sentinel: a re-choose would overwrite this
        fired = optimizer._replan(strategies, "wall_divergence", key="t")
        assert fired
        assert strategies.by_node[id(scan)].est_s == 123.0
        assert strategies.replans[0]["remaining_nodes"] == len(
            strategies.by_node
        ) - 1
    finally:
        optimizer.end()


def test_replan_idempotent_per_key_and_trigger(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = optimizer.choose(root)
    assert optimizer._replan(strategies, "ledger_pressure", key="k1")
    assert not optimizer._replan(strategies, "ledger_pressure", key="k1")
    # a different trigger for the same key is a different event
    assert optimizer._replan(strategies, "compile_storm", key="k1")
    assert len(strategies.replans) == 2


def test_compile_storm_pins_remaining_staged(csv_path):
    root = _reduce_plan(csv_path, "sum")
    strategies = optimizer.choose(root)
    st = strategies.by_node[id(root)]
    st.legs["compile"] = "fused"
    optimizer._replan(strategies, "compile_storm", key=("sig", "s0"))
    assert st.legs["compile"] == "staged"
    assert "compile" in st.firm


# ---------------------------------------------------------------------- #
# 4. priors
# ---------------------------------------------------------------------- #


def _ledger(tmp_path, runs):
    path = tmp_path / "PERF_HISTORY.json"
    path.write_text(json.dumps({"runs": runs}))
    return str(path)


def test_priors_roundtrip(tmp_path):
    path = _ledger(
        tmp_path,
        [
            {
                "scale": {"rows": 1000},
                "ops": {
                    "sum": {"modin_tpu_s": 0.5},
                    "median": {"modin_tpu_s": 2.0},
                },
            }
        ],
    )
    priors = optimizer.priors_from_history(path)
    assert priors is not None
    assert priors["s_per_row"]["sum"] == pytest.approx(5e-4)
    assert priors["reduce_s_per_row"] == pytest.approx(5e-4)
    assert priors["sortred_s_per_row"] == pytest.approx(2e-3)
    assert priors["source"] == path
    # defaults survive alongside the derived coefficients
    assert priors["mem_bytes_per_s"] == optimizer.DEFAULT_PRIORS[
        "mem_bytes_per_s"
    ]


def test_priors_later_runs_supersede(tmp_path):
    path = _ledger(
        tmp_path,
        [
            {"scale": {"rows": 1000}, "ops": {"sum": {"modin_tpu_s": 1.0}}},
            {"scale": {"rows": 1000}, "ops": {"sum": {"modin_tpu_s": 0.1}}},
        ],
    )
    priors = optimizer.priors_from_history(path)
    assert priors["reduce_s_per_row"] == pytest.approx(1e-4)


def test_priors_degrade_gracefully(tmp_path):
    assert optimizer.priors_from_history(str(tmp_path / "missing.json")) is None
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert optimizer.priors_from_history(str(bad)) is None
    empty = _ledger(tmp_path, [{"scale": {}, "ops": {}}])
    assert optimizer.priors_from_history(empty) is None


def test_set_priors_forces_and_resets(csv_path):
    root = ir.Reduce(_scan(csv_path), "sum", {})
    optimizer.set_priors(
        {**optimizer.DEFAULT_PRIORS, "scan_s_per_row": 1.0, "s_per_row": {}}
    )
    try:
        forced = optimizer.plan_cost(root)
        # ~1 second per scanned row: the forced prior clearly dominates
        assert forced > 1.0
    finally:
        optimizer.set_priors(None)
    assert optimizer.plan_cost(root) < forced


def test_default_history_path_is_repo_ledger():
    path = optimizer.default_history_path()
    if path is not None:
        assert path.endswith("PERF_HISTORY.json")
        priors = optimizer.priors_from_history(path)
        assert priors is None or "s_per_row" in priors


# ---------------------------------------------------------------------- #
# EXPLAIN surface
# ---------------------------------------------------------------------- #


def test_explain_renders_strategy_and_replans(csv_path):
    from modin_tpu.plan import explain as graftexplain

    root = _reduce_plan(csv_path, "median")
    strategies = optimizer.choose(root)
    rendered = graftexplain.render(root, strategies=strategies)
    assert "[strategy:" in rendered
    assert "est=" in rendered
    assert "residency=" in rendered
    strategies.replans.append(
        {"trigger": "wall_divergence", "est_s": 0.01, "measured_s": 0.08}
    )
    strategies.correction = 8.0
    replans = graftexplain.render_replans(strategies)
    assert "wall_divergence" in replans
    assert "8.0" in replans
