"""Dtype-breadth suite (ported shapes from modin/tests/pandas: categorical,
extension, datetime/timedelta, string, and mixed-dtype behavior)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(77)
N = 48


@pytest.fixture
def mixed():
    data = {
        "f64": _rng.normal(size=N),
        "f32": _rng.normal(size=N).astype(np.float32),
        "i64": _rng.integers(-100, 100, N),
        "i8": _rng.integers(-100, 100, N).astype(np.int8),
        "u32": _rng.integers(0, 100, N).astype(np.uint32),
        "b": _rng.random(N) < 0.5,
        "dt": np.datetime64("2023-06-01", "ns")
        + _rng.integers(0, 10**10, N).astype("timedelta64[ns]"),
        "td": _rng.integers(0, 10**9, N).astype("timedelta64[ns]"),
        "s": np.array([f"word{i % 11}" for i in range(N)]),
    }
    return create_test_dfs(data)


def test_dtypes_property(mixed):
    md, pdf = mixed
    pandas.testing.assert_series_equal(md.dtypes, pdf.dtypes)


@pytest.mark.parametrize(
    "target",
    ["float64", "float32", "int64", "int32", "bool"],
)
def test_astype_numeric(mixed, target):
    md, pdf = mixed
    cols = ["f64", "i64", "u32"]
    eval_general(md[cols], pdf[cols], lambda df: df.astype(target))


def test_astype_per_column(mixed):
    md, pdf = mixed
    spec = {"f64": "float32", "i64": "float64"}
    df_equals(md.astype(spec), pdf.astype(spec))


def test_astype_string_and_category(mixed):
    md, pdf = mixed
    df_equals(md["s"].astype("category"), pdf["s"].astype("category"))
    df_equals(md["i64"].astype(str), pdf["i64"].astype(str))


def test_categorical_roundtrip():
    cats = pandas.Categorical(
        ["lo", "hi", "mid", "lo", "hi"], categories=["lo", "mid", "hi"], ordered=True
    )
    md, pdf = create_test_dfs({"c": cats, "v": np.arange(5.0)})
    df_equals(md, pdf)
    df_equals(md["c"].cat.codes, pdf["c"].cat.codes)
    df_equals(md.sort_values("c"), pdf.sort_values("c"))


def test_categorical_groupby():
    cats = pandas.Categorical(["a", "b", "a", "c", "b", "a"])
    md, pdf = create_test_dfs({"k": cats, "v": np.arange(6.0)})
    eval_general(
        md, pdf, lambda df: df.groupby("k", observed=True)["v"].sum()
    )


def test_datetime_accessors(mixed):
    md, pdf = mixed
    for attr in ("year", "month", "day", "hour", "dayofweek"):
        df_equals(getattr(md["dt"].dt, attr), getattr(pdf["dt"].dt, attr))


def test_datetime_minmax_roundtrip(mixed):
    md, pdf = mixed
    df_equals(md["dt"].min(), pdf["dt"].min())
    df_equals(md["dt"].max(), pdf["dt"].max())
    df_equals(md[["dt"]].sort_values("dt"), pdf[["dt"]].sort_values("dt"))


def test_datetime_nat_handling():
    values = pandas.to_datetime(
        ["2024-01-01", None, "2024-03-01", None, "2024-02-01"]
    )
    md, pdf = create_test_dfs({"dt": values})
    df_equals(md["dt"].isna(), pdf["dt"].isna())
    df_equals(md.dropna(), pdf.dropna())
    df_equals(md["dt"].min(), pdf["dt"].min())


def test_timedelta_ops(mixed):
    md, pdf = mixed
    df_equals(md["td"].sum(), pdf["td"].sum())
    df_equals(md["td"].max(), pdf["td"].max())


def test_string_methods(mixed):
    md, pdf = mixed
    df_equals(md["s"].str.upper(), pdf["s"].str.upper())
    df_equals(md["s"].str.len(), pdf["s"].str.len())
    df_equals(md["s"].str.contains("word1"), pdf["s"].str.contains("word1"))
    df_equals(md["s"].str.replace("word", "W"), pdf["s"].str.replace("word", "W"))
    df_equals(md["s"].str[0:4], pdf["s"].str[0:4])


def test_nullable_extension_dtypes():
    md, pdf = create_test_dfs(
        {
            "ni": pandas.array([1, None, 3], dtype="Int64"),
            "nb": pandas.array([True, None, False], dtype="boolean"),
            "nf": pandas.array([1.5, None, 2.5], dtype="Float64"),
        }
    )
    df_equals(md, pdf)
    df_equals(md.isna(), pdf.isna())
    eval_general(md, pdf, lambda df: df["ni"].sum())


def test_mixed_arithmetic_promotions(mixed):
    md, pdf = mixed
    num_md = md[["f64", "f32", "i64", "i8", "u32"]]
    num_pd = pdf[["f64", "f32", "i64", "i8", "u32"]]
    df_equals(num_md + 1, num_pd + 1)
    df_equals(num_md * 2.5, num_pd * 2.5)
    df_equals(num_md["i8"] + num_md["i64"], num_pd["i8"] + num_pd["i64"])
    df_equals(num_md["f32"] * num_md["f64"], num_pd["f32"] * num_pd["f64"])


def test_int_division_semantics(mixed):
    md, pdf = mixed
    df_equals(md["i64"] / 0, pdf["i64"] / 0)
    df_equals(md["i64"] // 7, pdf["i64"] // 7)
    df_equals(md["i64"] % 7, pdf["i64"] % 7)
    df_equals(md["i64"] // 0, pdf["i64"] // 0)


def test_bool_aggregation_promotion(mixed):
    md, pdf = mixed
    df_equals(md["b"].sum(), pdf["b"].sum())
    df_equals(md["b"].mean(), pdf["b"].mean())
    df_equals(md[["b"]].var(), pdf[["b"]].var())


def test_convert_dtypes_infer_objects():
    md, pdf = create_test_dfs({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    df_equals(md.convert_dtypes(), pdf.convert_dtypes())
    df_equals(md.infer_objects(), pdf.infer_objects())


@pytest.mark.parametrize("how", ["inner", "left"])
def test_merge_on_datetime_keys(how):
    base = np.datetime64("2024-01-01", "ns")
    keys = base + np.array([0, 1, 2, 1, 0]).astype("timedelta64[D]")
    rkeys = base + np.array([1, 2, 9]).astype("timedelta64[D]")
    ml, pl_ = create_test_dfs({"k": keys, "x": np.arange(5.0)})
    mr, pr = create_test_dfs({"k": rkeys, "y": np.arange(3.0)})
    df_equals(ml.merge(mr, on="k", how=how), pl_.merge(pr, on="k", how=how))


def test_value_counts_dtypes(mixed):
    md, pdf = mixed
    df_equals(md["i8"].value_counts(), pdf["i8"].value_counts())
    df_equals(md["s"].value_counts(), pdf["s"].value_counts())
    df_equals(md["b"].value_counts(), pdf["b"].value_counts())


def test_memory_usage_and_info(mixed):
    md, pdf = mixed
    # values differ by backing store; shape/labels must match
    assert list(md.memory_usage().index) == list(pdf.memory_usage().index)
    assert md[["f64", "i64"]].memory_usage(index=False).sum() > 0
