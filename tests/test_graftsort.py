"""graftsort acceptance suite: the shared sorted-representation cache, the
O(n) histogram fast paths for nunique/mode, and the substrate-aware kernel
router.

Covers the PR's satellite checklist:

- sorted-cache invalidation under every buffer mutation (setitem-style
  column replacement, recovery re-seat, spill + restore, ledger spill),
  with results staying bit-exact vs pandas after each;
- dictionary-encoded nunique/mode parity vs pandas (NaN handling, dropna
  both ways, multi-column mixed frames);
- router unit tests with a FORCED calibration table asserting the
  device/host choice flips at the predicted crossover.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import KernelRouterMinRows, KernelRouterMode
from modin_tpu.logging.metrics import add_metric_handler, clear_metric_handler
from modin_tpu.ops import router, sorted_cache

from tests.utils import assert_no_fallback, df_equals, eval_general


@pytest.fixture
def metric_log():
    events = []

    def handler(name, value):
        events.append((name, value))

    add_metric_handler(handler)
    yield events
    clear_metric_handler(handler)


@pytest.fixture
def router_auto():
    """Pin router mode to Auto and restore afterwards."""
    before = KernelRouterMode.get()
    KernelRouterMode.put("Auto")
    yield
    KernelRouterMode.put(before)


def _count(events, name):
    return sum(1 for n, _ in events if n == f"modin_tpu.{name}")


def _device_col(mdf, label):
    frame = mdf._query_compiler._modin_frame
    return frame.get_column(list(frame.columns).index(label))


# --------------------------------------------------------------------- #
# sorted-representation cache
# --------------------------------------------------------------------- #


class TestSortedCache:
    def _frame(self, n=400):
        rng = np.random.default_rng(7)
        pdf = pandas.DataFrame(
            {
                "a": rng.integers(-(1 << 40), 1 << 40, n),
                "b": np.where(
                    rng.random(n) < 0.2, np.nan, rng.normal(size=n)
                ),
            }
        )
        mdf = pd.DataFrame(pdf)
        mdf._query_compiler.execute()
        return mdf, pdf

    def test_one_sort_amortized_across_family(self, metric_log, router_auto):
        mdf, pdf = self._frame()
        got = assert_no_fallback(lambda: mdf.median())
        df_equals(got, pdf.median())
        builds_after_first = _count(metric_log, "sortcache.build")
        assert builds_after_first >= 1
        # quantile + nunique on the same columns consume the cached rep
        got = assert_no_fallback(lambda: mdf.quantile(0.25))
        df_equals(got, pdf.quantile(0.25))
        got = assert_no_fallback(lambda: mdf.nunique())
        df_equals(got, pdf.nunique())
        assert _count(metric_log, "sortcache.build") == builds_after_first
        assert _count(metric_log, "sortcache.hit") >= 2

    def test_invalidate_on_setitem(self, router_auto):
        mdf, pdf = self._frame()
        assert_no_fallback(lambda: mdf.median())
        mdf["b"] = mdf["b"] * 2.0
        pdf["b"] = pdf["b"] * 2.0
        # the replaced column must not serve the stale sorted rep
        eval_general(mdf, pdf, lambda df: df.median())
        eval_general(mdf, pdf, lambda df: df.quantile([0.1, 0.9]))

    def test_invalidate_on_spill_restore(self, router_auto):
        mdf, pdf = self._frame()
        assert_no_fallback(lambda: mdf.median())
        col = _device_col(mdf, "a")
        assert sorted_cache.peek(col)
        assert col.spill() > 0
        assert not sorted_cache.peek(col), "spill must drop the sorted rep"
        assert col.raw is not None  # transparent restore
        assert not sorted_cache.peek(col), "restored buffer != cached source"
        eval_general(mdf, pdf, lambda df: df.median())
        eval_general(mdf, pdf, lambda df: df.nunique())

    def test_invalidate_on_reseat(self, router_auto):
        mdf, pdf = self._frame()
        assert_no_fallback(lambda: mdf.quantile(0.5))
        col = _device_col(mdf, "a")
        assert sorted_cache.peek(col)
        col.reseat_from_host()  # the recovery re-seat path
        assert not sorted_cache.peek(col), "re-seat must drop the sorted rep"
        eval_general(mdf, pdf, lambda df: df.quantile(0.5))

    def test_device_ledger_reclaims_rep(self, router_auto):
        from modin_tpu.core.memory import device_ledger

        mdf, pdf = self._frame()
        assert_no_fallback(lambda: mdf.median())
        col = _device_col(mdf, "a")
        rep = col._sorted_rep
        assert rep is not None and rep._dev_key is not None
        freed = rep.spill()  # what spill_lru invokes on the ledger entry
        assert freed > 0
        assert not sorted_cache.peek(col)
        # rebuilt transparently on the next sort-shaped op, still exact
        eval_general(mdf, pdf, lambda df: df.median())
        assert sorted_cache.peek(col)
        assert device_ledger.deregister(rep) == 0  # already deregistered

    def test_recovery_pass_drops_derived_cache(self, router_auto):
        from modin_tpu.core.execution import recovery

        mdf, pdf = self._frame()
        assert_no_fallback(lambda: mdf.median())
        col = _device_col(mdf, "a")
        rep = col._sorted_rep
        assert rep is not None
        # a reseat pass walks the device ledger: derived caches are dropped,
        # never counted unrecoverable
        assert recovery.recover_column(rep) is None
        assert rep._data is None
        assert not sorted_cache.peek(col)
        eval_general(mdf, pdf, lambda df: df.median())


# --------------------------------------------------------------------- #
# O(n) histogram fast paths
# --------------------------------------------------------------------- #


class TestHistogramPaths:
    def test_bounded_int_nunique_mode_parity(self, router_auto):
        rng = np.random.default_rng(3)
        pdf = pandas.DataFrame(
            {
                "x": rng.integers(0, 50, 500),
                "y": rng.integers(-20, 5, 500),
                "z": rng.random(500) < 0.5,
            }
        )
        mdf = pd.DataFrame(pdf)
        for dropna in (True, False):
            got = assert_no_fallback(lambda d=dropna: mdf.nunique(dropna=d))
            df_equals(got, pdf.nunique(dropna=dropna))
        got = assert_no_fallback(lambda: mdf.mode())
        df_equals(got, pdf.mode())
        got = assert_no_fallback(lambda: mdf.mode(dropna=False))
        df_equals(got, pdf.mode(dropna=False))

    def test_mode_k_bound_dead_on_hist_path(self, router_auto):
        # 2000 distinct values, each once: every value is modal.  The sorted
        # kernel's k_bound=1024 cap would decline this; the histogram path
        # has no cap — the op must stay on device and match pandas exactly.
        values = np.arange(2000, dtype=np.int64)
        rng = np.random.default_rng(0)
        rng.shuffle(values)
        pdf = pandas.DataFrame({"v": values})
        mdf = pd.DataFrame(pdf)
        got = assert_no_fallback(lambda: mdf.mode())
        df_equals(got, pdf.mode())

    def test_wide_range_int_keeps_sorted_path(self, router_auto):
        # range >> HIST_BOUND: planner must fall back to the sort strategy
        rng = np.random.default_rng(4)
        pdf = pandas.DataFrame({"w": rng.integers(0, 1 << 50, 300)})
        mdf = pd.DataFrame(pdf)
        got = assert_no_fallback(lambda: mdf.nunique())
        df_equals(got, pdf.nunique())
        got = assert_no_fallback(lambda: mdf.mode())
        df_equals(got, pdf.mode())


# --------------------------------------------------------------------- #
# dictionary-encoded nunique / mode
# --------------------------------------------------------------------- #


class TestDictEncoded:
    def _frames(self):
        pdf = pandas.DataFrame(
            {
                "city": ["lima", "oslo", None, "lima", "oslo", "lima", None],
                "tag": ["b", "a", "a", "b", None, "a", "b"],
                "n": np.array([3, 1, 1, 3, 2, 1, 3], np.int64),
            }
        )
        mdf = pd.DataFrame(pdf)
        return mdf, pdf

    def test_nunique_dropna_both_ways(self, router_auto):
        mdf, pdf = self._frames()
        for dropna in (True, False):
            got = assert_no_fallback(lambda d=dropna: mdf.nunique(dropna=d))
            df_equals(got, pdf.nunique(dropna=dropna))

    def test_mode_multi_column_mixed(self, router_auto):
        mdf, pdf = self._frames()
        got = assert_no_fallback(lambda: mdf.mode())
        df_equals(got, pdf.mode())

    def test_mode_dropna_false_nan_ties(self, router_auto):
        # NaN count ties the max: pandas keeps NaN in the result, sorted
        # last.  2x lima, 2x None, 1x oslo -> modes [lima, NaN].
        pdf = pandas.DataFrame(
            {"c": ["lima", None, "oslo", "lima", None]}
        )
        mdf = pd.DataFrame(pdf)
        got = assert_no_fallback(lambda: mdf.mode(dropna=False))
        df_equals(got, pdf.mode(dropna=False))
        got = assert_no_fallback(lambda: mdf.mode(dropna=True))
        df_equals(got, pdf.mode(dropna=True))

    def test_mode_string_only_frame(self, router_auto):
        pdf = pandas.DataFrame(
            {
                "a": ["x", "y", "x", "z", "y", "x"],
                "b": ["q", "q", "r", "r", "q", "r"],
            }
        )
        mdf = pd.DataFrame(pdf)
        got = assert_no_fallback(lambda: mdf.mode())
        df_equals(got, pdf.mode())
        # ragged mode counts across columns: concat NaN-pads like pandas
        pdf2 = pandas.DataFrame(
            {"a": ["x", "x", "y"], "b": ["p", "q", "r"]}
        )
        mdf2 = pd.DataFrame(pdf2)
        got = assert_no_fallback(lambda: mdf2.mode())
        df_equals(got, pdf2.mode())


# --------------------------------------------------------------------- #
# kernel router
# --------------------------------------------------------------------- #


#: forced calibration: device sort is 100x slower per row than any host
#: kernel, histogram 10x faster — crossovers land where arithmetic says
_FORCED_TABLE = {
    "version": router._CAL_VERSION,
    "platform": "test",
    "rows": 1000,
    "device_sort_s": 1.0,
    "device_consume_s": 0.001,
    "device_hist_s": 0.0001,
    "host_median_high_s": 0.01,
    "host_median_low_s": 0.01,
    "host_quantile_high_s": 0.01,
    "host_quantile_low_s": 0.01,
    "host_nunique_high_s": 0.01,
    "host_nunique_low_s": 0.001,
    "host_mode_high_s": 0.01,
    "host_mode_low_s": 0.001,
}


class TestKernelRouter:
    @pytest.fixture(autouse=True)
    def _forced_calibration(self):
        min_rows_before = KernelRouterMinRows.get()
        mode_before = KernelRouterMode.get()
        router.set_calibration(dict(_FORCED_TABLE))
        KernelRouterMode.put("Auto")
        yield
        router.set_calibration(None)
        KernelRouterMinRows.put(min_rows_before)
        KernelRouterMode.put(mode_before)

    def test_choice_flips_at_crossover(self):
        KernelRouterMinRows.put(1)
        # device sort costs ~1s/1000 rows vs host median 0.01s/1000 rows:
        # host wins once the absolute gap clears MIN_SAVINGS_S
        assert router.decide("median", 10, ["sort"]) == "device"  # gap tiny
        assert router.decide("median", 100_000, ["sort"]) == "host"
        # histogram strategy: device is 10x cheaper than even the fast
        # low-cardinality host kernel — device keeps it at any size
        assert router.decide("nunique", 10_000_000, ["hist"]) == "device"
        # a cached rep turns the sort into a consume: device wins
        assert router.decide("median", 100_000, ["cached"]) == "device"
        # dict columns are free on device
        assert router.decide("nunique", 10_000_000, ["dict"]) == "device"

    def test_min_rows_short_circuits(self):
        KernelRouterMinRows.put(1_000_000)
        # below the floor the decision is device even where the model
        # would say host (and no calibration would ever be consulted)
        assert router.decide("median", 100_000, ["sort"]) == "device"

    def test_forced_modes_override_model(self):
        KernelRouterMinRows.put(1)
        KernelRouterMode.put("Host")
        assert router.decide("nunique", 10, ["hist"]) == "host"
        KernelRouterMode.put("Device")
        assert router.decide("median", 100_000_000, ["sort"]) == "device"

    def test_uncalibrated_routes_device(self):
        KernelRouterMinRows.put(1)
        router.set_calibration(None)
        # remembered calibration failure — mesh-keyed since graftmesh (a
        # failure under one topology must not poison the next), so the
        # simulated failure must pin the CURRENT mesh
        router._calibration = False
        router._calibration_mesh = router._mesh_key()
        try:
            assert router.decide("median", 100_000_000, ["sort"]) == "device"
        finally:
            router.set_calibration(dict(_FORCED_TABLE))

    def test_decision_metrics_emitted(self, metric_log):
        KernelRouterMinRows.put(1)
        router.decide("median", 100_000, ["sort"])
        assert _count(metric_log, "router.median.host") == 1
        router.decide("median", 100_000, ["cached"])
        assert _count(metric_log, "router.median.device") == 1

    def test_forced_host_skips_planning_probe(self, monkeypatch):
        # Host-forced routing must decline BEFORE any device work: if the
        # planner (device materialize + min/max range probe) ran, this
        # poisoned stand-in would raise
        from modin_tpu.ops import reductions

        KernelRouterMode.put("Host")

        def boom(*a, **k):
            raise AssertionError("planner ran under forced-Host routing")

        monkeypatch.setattr(reductions, "plan_sort_reduce", boom)
        rng = np.random.default_rng(5)
        pdf = pandas.DataFrame({"v": rng.integers(0, 9, 64)})
        mdf = pd.DataFrame(pdf)
        eval_general(mdf, pdf, lambda df: df.nunique())
        eval_general(mdf, pdf, lambda df: df.mode())

    def test_forced_host_gates_describe(self, metric_log):
        # describe's quantile leg is sort-shaped: the router verdict that
        # gates quantile() must gate it too
        KernelRouterMode.put("Host")
        rng = np.random.default_rng(6)
        pdf = pandas.DataFrame({"v": rng.normal(size=128)})
        mdf = pd.DataFrame(pdf)
        eval_general(mdf, pdf, lambda df: df.describe())
        assert _count(metric_log, "router.quantile.host") >= 1
        assert _count(metric_log, "sortcache.build") == 0

    def test_forced_host_end_to_end_stays_exact(self):
        # Host-forced routing must decline every sort-shaped device path
        # and still produce pandas-exact answers through the fallback
        KernelRouterMode.put("Host")
        rng = np.random.default_rng(11)
        pdf = pandas.DataFrame({"v": rng.integers(0, 30, 200)})
        mdf = pd.DataFrame(pdf)
        eval_general(mdf, pdf, lambda df: df.median())
        eval_general(mdf, pdf, lambda df: df.nunique())
        eval_general(mdf, pdf, lambda df: df.mode())
        eval_general(mdf, pdf, lambda df: df.quantile(0.75))


# --------------------------------------------------------------------- #
# median over the sorted rep: skipna semantics
# --------------------------------------------------------------------- #


class TestMedianSorted:
    def test_median_skipna_false_with_nan(self, router_auto):
        pdf = pandas.DataFrame(
            {
                "a": [1.0, np.nan, 3.0, 5.0],
                "b": [2.0, 4.0, 6.0, 8.0],
            }
        )
        mdf = pd.DataFrame(pdf)
        eval_general(mdf, pdf, lambda df: df.median(skipna=False))
        eval_general(mdf, pdf, lambda df: df.median(skipna=True))

    def test_median_int_exact(self, router_auto):
        pdf = pandas.DataFrame({"a": np.array([5, 1, 9, 3], np.int64)})
        mdf = pd.DataFrame(pdf)
        got = assert_no_fallback(lambda: mdf.median())
        df_equals(got, pdf.median())
