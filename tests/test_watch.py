"""graftwatch: rings, sampler lifecycle, SLO burn rates, tripwires, HTTP.

Acceptance bar (ISSUE 15): with ``MODIN_TPU_WATCH=0`` no sampler or
exporter thread exists and the hot path costs one attribute check with
zero allocations; with it on, the sampler folds the telemetry seams into
bounded rings, ``/metrics`` stays parseable under load, per-tenant SLO
burn rates go advisory into ``serving_snapshot()``, tripwires capture
exactly one rate-limited evidence bundle per incident, and a crashed
sampler degrades the service to disabled (``watch.sampler.died``)
instead of taking queries down.
"""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
import modin_tpu.serving as serving
from modin_tpu.config import (
    MetersEnabled,
    ResilienceBackoffS,
    ServingEnabled,
    ServingMaxConcurrent,
    ServingQueueDepth,
    TraceDir,
    TraceEnabled,
    WatchEnabled,
    WatchIntervalS,
    WatchPort,
    WatchSloMs,
)
from modin_tpu.core.execution.resilience import reset_breakers
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import flight_recorder, meters, watch
from modin_tpu.observability.watch import slo as slo_mod
from modin_tpu.observability.watch import timeseries as ts_mod
from modin_tpu.observability.watch import tripwires as tw_mod
from modin_tpu.serving import tenants as serving_tenants
from modin_tpu.serving.gate import gate

_PARAMS = (
    WatchEnabled,
    WatchIntervalS,
    WatchPort,
    WatchSloMs,
    MetersEnabled,
    ServingEnabled,
    ServingMaxConcurrent,
    ServingQueueDepth,
    TraceEnabled,
    TraceDir,
    ResilienceBackoffS,
)


@pytest.fixture(autouse=True)
def _clean_watch_state():
    saved = [(p, p.get()) for p in _PARAMS]
    WatchEnabled.put(False)
    meters.reset()
    yield
    for p, v in saved:
        p.put(v)
    WatchEnabled.put(False)
    meters.reset()
    reset_breakers()
    gate.reset_for_tests()
    serving_tenants.registry.reset()
    service = watch.get_service()
    if service is not None:
        service.rings.reset()
        service.slo.reset()
        service.tripwires.recent.clear()
        for rule in service.tripwires.rules:
            rule.last_tripped = None
    flight_recorder.reset_for_tests()


@pytest.fixture
def metric_names():
    seen = []
    handler = lambda name, value: seen.append(name)  # noqa: E731
    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


def _watch_threads():
    return [
        t.name
        for t in threading.enumerate()
        if t.name.startswith("modin-tpu-watch")
    ]


def _wait_for(predicate, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _get(port, path, timeout=5.0):
    return (
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        )
        .read()
        .decode()
    )


# ====================================================================== #
# disabled-mode contract
# ====================================================================== #


class TestDisabledMode:
    def test_no_threads_and_zero_alloc_when_off(self):
        """MODIN_TPU_WATCH=0: no sampler/exporter thread, and a full
        workload (including serving submits) allocates zero graftwatch
        objects — the hot path is one module-attribute check."""
        assert not watch.WATCH_ON
        assert _watch_threads() == []
        df = pd.DataFrame({"a": np.arange(128.0), "k": np.arange(128) % 5})
        _ = df.groupby("k").sum().modin.to_pandas()  # warm every code path
        ServingEnabled.put(True)
        ResilienceBackoffS.put(0.0)
        before = watch.watch_alloc_count()
        df2 = pd.DataFrame({"a": np.arange(128.0), "k": np.arange(128) % 5})
        _ = serving.submit(
            lambda: df2.groupby("k").sum().modin.to_pandas(), tenant="alice"
        )
        _ = (df2["a"] * 2).sum()
        assert watch.watch_alloc_count() == before
        assert _watch_threads() == []

    def test_observe_query_is_noop_when_off(self):
        watch.observe_query("alice", 1.0)
        service = watch.get_service()
        if service is not None:
            assert service.slo.health().get("alice") is None


# ====================================================================== #
# sampler lifecycle
# ====================================================================== #


class TestSamplerLifecycle:
    def test_start_stop_idempotent(self):
        WatchIntervalS.put(0.02)
        WatchEnabled.put(True)
        service = watch.get_service()
        assert watch.WATCH_ON and service.sampler.is_alive()
        first_thread = service.sampler._thread
        service.start()  # idempotent: the live thread is left running
        service.start()
        assert service.sampler._thread is first_thread
        assert _watch_threads().count(ts_mod.Sampler.THREAD_NAME) == 1
        WatchEnabled.put(False)
        assert not watch.WATCH_ON
        _wait_for(
            lambda: not service.sampler.is_alive(), what="sampler exit"
        )
        service.stop()  # second stop is a no-op
        WatchEnabled.put(False)
        assert _watch_threads() == []
        # re-enable restarts cleanly
        WatchEnabled.put(True)
        assert watch.get_service().sampler.is_alive()
        _wait_for(
            lambda: watch.get_service().sampler.ticks > 0, what="a tick"
        )

    def test_sampler_ticks_fill_rings(self):
        MetersEnabled.put(True)
        WatchIntervalS.put(0.02)
        WatchEnabled.put(True)
        emit_metric("engine.dispatch", 1)
        service = watch.get_service()
        _wait_for(lambda: service.sampler.ticks >= 3, what="3 ticks")
        assert service.rings.get("memory.device.resident_bytes") is not None
        assert service.rings.get("compile.total") is not None
        assert service.rings.get("engine.dispatch") is not None, (
            service.rings.names(),
            list(meters.snapshot()["series"]),
            meters.METERS_ON,
        )

    def test_watch_alone_activates_registry_aggregation(self):
        """MODIN_TPU_WATCH=1 without MODIN_TPU_METERS must still fill the
        registry-fed rings and serve a non-empty /metrics — the service
        holds a registry acquire for its lifetime."""
        assert not MetersEnabled.get() and not meters.METERS_ON
        WatchIntervalS.put(0.02)
        WatchPort.put(-1)
        WatchEnabled.put(True)
        assert meters.METERS_ON  # aggregation active, knob still off
        assert not MetersEnabled.get()
        emit_metric("engine.dispatch", 1)
        assert "engine.dispatch" in meters.snapshot()["series"]
        service = watch.get_service()
        _wait_for(
            lambda: service.rings.get("engine.dispatch") is not None,
            what="registry-fed ring",
        )
        WatchEnabled.put(False)
        assert not meters.METERS_ON  # the hold released with the service

    def test_direct_gauges_never_interleave_stale_registry_copies(self):
        """The registry holds memory.*_bytes gauges updated only at spill
        passes; the sampler's live per-tick reads must not interleave
        with those stale copies in the same ring."""
        MetersEnabled.put(True)
        emit_metric("memory.device.resident_bytes", 123456789)  # stale
        store = ts_mod.RingStore()
        sampler = ts_mod.Sampler(store)
        sampler.sample_once(now=1.0)
        sampler.sample_once(now=2.0)
        ring = store.get("memory.device.resident_bytes")
        assert len(ring) == 2  # one LIVE sample per tick, no duplicates
        assert all(v != 123456789 for _t, v in ring.samples())

    def test_rings_stay_bounded_under_long_run(self, monkeypatch):
        """A long synthetic run never grows a ring past its capacity or
        the store past the cardinality cap."""
        monkeypatch.setattr(ts_mod, "RING_SAMPLES", 32)
        MetersEnabled.put(True)
        store = ts_mod.RingStore()
        sampler = ts_mod.Sampler(store)
        for tick in range(500):
            emit_metric("engine.dispatch", 1)
            emit_metric("io.read.bytes", 1024 * (tick + 1))
            sampler.sample_once(now=float(tick))
        assert sampler.ticks == 500
        for name in store.names():
            assert len(store.get(name)) <= 32, name
        # and the whole-store cardinality guard refuses runaway names
        from modin_tpu.config import MetersMaxSeries

        cap = int(MetersMaxSeries.get())
        for i in range(cap + 50):
            store.observe(f"runaway.{i}", "counter", i, 0.0)
        assert len(store) <= cap
        assert store.dropped_series > 0

    def test_stalled_run_never_revives_after_restart(self, monkeypatch):
        """A run whose stop() join timed out (tick stalled past the join
        budget) must exit when it unstalls — never loop alongside the
        restarted run (start() swaps in a fresh stop event; the stalled
        run holds its own, already-set one)."""
        store = ts_mod.RingStore()
        release = threading.Event()
        calls = []

        def stall_once(self, now=None):
            calls.append(threading.current_thread().name)
            release.wait(10.0)

        monkeypatch.setattr(ts_mod.Sampler, "sample_once", stall_once)
        sampler = ts_mod.Sampler(store)
        sampler.start()
        _wait_for(lambda: calls, what="first stalled tick")
        old_thread = sampler._thread
        sampler.stop(timeout=0.05)  # join times out: the tick is stalled
        assert old_thread.is_alive()
        sampler.start()
        assert sampler._thread is not old_thread
        release.set()  # unstall: the superseded run must exit
        _wait_for(
            lambda: not old_thread.is_alive(), what="superseded run exit"
        )
        assert sampler.is_alive()
        sampler.stop()

    def test_crashed_sampler_degrades_to_disabled(
        self, monkeypatch, metric_names
    ):
        """A sampler crash emits watch.sampler.died and flips the service
        off — queries keep running, nothing propagates."""
        WatchIntervalS.put(0.01)

        def boom(self, now=None):
            raise RuntimeError("synthetic sampler crash")

        monkeypatch.setattr(ts_mod.Sampler, "sample_once", boom)
        WatchEnabled.put(True)
        service = watch.get_service()
        _wait_for(lambda: service.sampler.died, what="sampler death")
        _wait_for(lambda: not watch.WATCH_ON, what="degrade to disabled")
        assert "watch.sampler.died" in [
            n.replace("modin_tpu.", "") for n in metric_names
        ]
        assert service.sampler.error is not None
        _wait_for(
            lambda: not service.exporter.is_alive(), what="exporter stop"
        )
        # queries are untouched
        df = pd.DataFrame({"a": np.arange(32.0)})
        assert float(df["a"].sum()) == float(np.arange(32.0).sum())
        # and an explicit off/on cycle recovers once the fault is gone
        monkeypatch.undo()
        WatchEnabled.put(False)
        WatchEnabled.put(True)
        _wait_for(lambda: watch.get_service().sampler.ticks > 0, what="tick")
        assert watch.WATCH_ON and not watch.get_service().sampler.died

    def test_stale_crash_callback_cannot_degrade_restarted_service(self):
        """_on_sampler_died from a thread that is no longer the current
        sampler run (a crash racing stop()/restart) must be a no-op."""
        WatchIntervalS.put(60.0)
        WatchPort.put(-1)
        WatchEnabled.put(True)
        service = watch.get_service()
        assert watch.WATCH_ON
        # this test thread is NOT the sampler thread: the guard must hold
        service._on_sampler_died(RuntimeError("stale crash"))
        assert watch.WATCH_ON
        assert service.sampler.is_alive()


# ====================================================================== #
# ring math
# ====================================================================== #


class TestRings:
    def test_counter_delta_rate_and_reset_clamp(self):
        ring = ts_mod.Ring("c", "counter")
        for t, v in [(0.0, 100.0), (10.0, 150.0), (20.0, 180.0)]:
            ring.append(t, v)
        assert ring.delta(25.0, now=20.0) == pytest.approx(80.0)
        assert ring.rate(25.0, now=20.0) == pytest.approx(4.0)
        # a registry reset mid-window reads as a restart, never negative
        ring.append(30.0, 5.0)
        assert ring.delta(25.0, now=30.0) == pytest.approx(5.0)
        assert ring.rate(40.0, now=30.0) >= 0.0
        # too little data
        empty = ts_mod.Ring("e", "counter")
        assert empty.delta(10.0) is None and empty.rate(10.0) is None

    def test_gauge_window_minmax(self):
        ring = ts_mod.Ring("g", "gauge")
        for t, v in [(0.0, 5.0), (10.0, 50.0), (20.0, 10.0)]:
            ring.append(t, v)
        assert ring.window_minmax(15.0, now=20.0) == (10.0, 50.0)
        assert ring.window_minmax(100.0, now=20.0) == (5.0, 50.0)

    def test_histogram_windowed_quantile(self):
        bounds = (0.01, 0.1, 1.0)
        ring = ts_mod.Ring("h", "histogram")
        ring.append(0.0, (bounds, (0, 0, 0), 0, 0.0))
        ring.append(10.0, (bounds, (10, 10, 10), 10, 0.05))  # 10 fast obs
        ring.append(20.0, (bounds, (10, 10, 20), 20, 5.0))  # 10 slow obs
        recent = ring.quantile(0.99, 15.0, now=20.0)
        assert recent is not None and recent > 0.5  # the slow bucket
        baseline = ring.quantile(0.99, 10.0, now=20.0, end_offset_s=10.0)
        assert baseline is not None and baseline <= 0.01  # the fast bucket
        assert ring.window_count(15.0, now=20.0) == 10

    def test_histogram_single_sample_bills_full_history(self):
        bounds = (1.0,)
        ring = ts_mod.Ring("h", "histogram")
        ring.append(5.0, (bounds, (7,), 9, 9.0))
        delta = ring.hist_delta(0.0, 10.0)
        assert delta is not None
        _bounds, per_bucket, total = delta
        assert total == 9 and per_bucket == [7, 2]  # 2 overflow

    def test_store_excerpt_is_json_safe(self):
        store = ts_mod.RingStore()
        store.observe("c", "counter", 3, 1.0)
        store.observe(
            "h", "histogram", ((1.0,), (2,), 2, 1.5), 1.0
        )
        excerpt = store.excerpt()
        json.dumps(excerpt)  # serializable
        assert excerpt["h"]["samples"][0][1]["count"] == 2


# ====================================================================== #
# SLO burn rates
# ====================================================================== #


class TestSlo:
    def test_parse_slo_spec(self):
        assert slo_mod.parse_slo_ms("250") == {"default": 0.25}
        assert slo_mod.parse_slo_ms("default=100,alice=20") == {
            "default": 0.1,
            "alice": 0.02,
        }
        assert slo_mod.parse_slo_ms("junk,=5,x=,neg=-2,ok=10") == {
            "ok": 0.01
        }
        assert slo_mod.parse_slo_ms("") == {}

    def test_burn_verdicts_and_min_samples_guard(self):
        WatchSloMs.put("default=50,alice=20")
        tracker = slo_mod.SloTracker()
        now = time.monotonic()
        for _ in range(20):
            tracker.observe("alice", 0.5, now=now)  # all over 20ms
            tracker.observe("bob", 0.001, now=now)  # all under 50ms
        tracker.observe("sparse", 9.9, now=now)  # 1 bad obs only
        health = tracker.health(now=now)
        assert health["alice"]["breaching"]
        assert health["alice"]["fast_burn"] > 1.0
        assert not health["bob"]["breaching"]
        # one unlucky query never pages: below MIN_SAMPLES
        assert not health["sparse"]["breaching"]
        assert tracker.breaching(now=now).keys() == {"alice"}

    def test_no_objectives_no_health(self):
        WatchSloMs.put("")
        tracker = slo_mod.SloTracker()
        tracker.observe("alice", 5.0)
        assert tracker.health() == {}
        assert tracker.latency_stats()["alice"]["count"] == 1

    def test_fast_window_recovery_clears_breach(self):
        WatchSloMs.put("default=50")
        tracker = slo_mod.SloTracker()
        now = time.monotonic()
        old = now - slo_mod.FAST_WINDOW_S - 5
        for _ in range(20):
            tracker.observe("t", 1.0, now=old)  # the incident
        for _ in range(20):
            tracker.observe("t", 0.001, now=now)  # recovered traffic
        health = tracker.health(now=now)
        # slow window still burning, fast window clean -> not breaching
        assert health["t"]["slow_burn"] > 1.0
        assert health["t"]["fast_burn"] == 0.0
        assert not health["t"]["breaching"]

    def test_observations_age_pruned_past_slow_window(self):
        """Samples older than SLOW_WINDOW_S are dropped on the write path
        — no verdict reads past it, and health() copies rings under the
        hot-path lock every tick."""
        tracker = slo_mod.SloTracker()
        now = time.monotonic()
        for i in range(10):
            tracker.observe("t", 0.01, now=now - slo_mod.SLOW_WINDOW_S - 60 + i)
        tracker.observe("t", 0.01, now=now)
        assert len(tracker._observations["t"]) == 1  # stale history gone

    def test_tenant_cardinality_lru_evicts_never_ignores(self, monkeypatch):
        """Past the cap, the LEAST-recently-observed tenant is evicted —
        a new tenant is always tracked (permanently ignoring tenants
        created after the cap would blind SLO tracking to churn)."""
        monkeypatch.setattr(slo_mod, "_MAX_TENANTS", 8)
        tracker = slo_mod.SloTracker()
        for i in range(20):
            tracker.observe(f"tenant{i}", 0.01)
        assert len(tracker._observations) <= 8
        assert tracker.evicted_tenants == 12
        assert "tenant19" in tracker._observations  # newest is tracked
        assert "tenant0" not in tracker._observations  # LRU went first
        # re-observing keeps a tenant warm: touch tenant12, add one more
        tracker.observe("tenant12", 0.01)
        tracker.observe("fresh", 0.01)
        assert "tenant12" in tracker._observations
        assert "fresh" in tracker._observations


# ====================================================================== #
# tripwires
# ====================================================================== #


def _enable_watch_quiet(tmp_path):
    """Watch on with a long interval (tests tick the engine manually)."""
    TraceDir.put(str(tmp_path))
    WatchIntervalS.put(60.0)
    WatchPort.put(-1)
    WatchEnabled.put(True)
    service = watch.get_service()
    service.rings.reset()
    service.slo.reset()
    service.tripwires.recent.clear()
    for rule in service.tripwires.rules:
        rule.last_tripped = None
    flight_recorder.reset_for_tests()
    return service


class TestTripwires:
    def test_latency_shift_trips_and_respects_floor(self, tmp_path):
        service = _enable_watch_quiet(tmp_path)
        bounds = (0.01, 0.1, 1.0)
        ring_name = "serving.query_wall_s"
        now = time.monotonic()
        win = tw_mod.WINDOW_S
        service.rings.observe(
            ring_name, "histogram", (bounds, (0, 0, 0), 0, 0.0),
            now - 2 * win,
        )
        service.rings.observe(
            ring_name, "histogram", (bounds, (10, 10, 10), 10, 0.05),
            now - win,
        )
        service.rings.observe(
            ring_name, "histogram", (bounds, (10, 10, 20), 20, 5.0), now
        )
        detail = tw_mod._latency_shift(service, now)
        assert detail is not None and "p99 shifted" in detail
        # floor: the same shape at microsecond scale is not an incident
        service.rings.reset()
        tiny = (1e-6, 1e-5, 1e-4)
        service.rings.observe(
            ring_name, "histogram", (tiny, (0, 0, 0), 0, 0.0), now - 2 * win
        )
        service.rings.observe(
            ring_name, "histogram", (tiny, (10, 10, 10), 10, 0.0), now - win
        )
        service.rings.observe(
            ring_name, "histogram", (tiny, (10, 10, 20), 20, 0.0), now
        )
        assert tw_mod._latency_shift(service, now) is None

    def test_recompile_storm_growth(self, tmp_path):
        service = _enable_watch_quiet(tmp_path)
        now = time.monotonic()
        service.rings.observe(
            "compile.storm_signatures", "gauge", 0, now - 30
        )
        assert tw_mod._recompile_storm(service, now) is None
        service.rings.observe("compile.storm_signatures", "gauge", 2, now)
        detail = tw_mod._recompile_storm(service, now)
        assert detail is not None and "recompile-storm" in detail

    def test_spill_thrash_requires_falling_hits(self, tmp_path):
        service = _enable_watch_quiet(tmp_path)
        now = time.monotonic()
        win = tw_mod.WINDOW_S
        for t, v in [(now - win, 0), (now, 8)]:
            service.rings.observe("memory.device.spill", "counter", v, t)
        # hits rising: no thrash
        for t, v in [
            (now - 2 * win, 0),
            (now - win - 1, 2),
            (now - win + 1, 2),
            (now, 50),
        ]:
            service.rings.observe("sortcache.hit", "counter", v, t)
        assert tw_mod._spill_thrash(service, now) is None
        # hits falling: thrash
        service.rings.reset()
        for t, v in [(now - win, 0), (now, 8)]:
            service.rings.observe("memory.device.spill", "counter", v, t)
        for t, v in [
            (now - 2 * win, 0),
            (now - win - 1, 40),
            (now - win + 1, 40),
            (now, 41),
        ]:
            service.rings.observe("sortcache.hit", "counter", v, t)
        detail = tw_mod._spill_thrash(service, now)
        assert detail is not None and "spill" in detail

    def test_shed_spike_and_engine_emits_metric(
        self, tmp_path, metric_names, monkeypatch
    ):
        service = _enable_watch_quiet(tmp_path)
        monkeypatch.setattr(flight_recorder, "MIN_DUMP_INTERVAL_S", 0.0)
        now = time.monotonic()
        for t, v in [(now - 30, 0), (now, 10)]:
            service.rings.observe("serving.shed", "counter", v, t)
        service.tripwires.on_tick(now)
        trips = [t["rule"] for t in service.tripwires.snapshot()]
        assert "shed_spike" in trips
        assert "modin_tpu.watch.trip.shed_spike" in metric_names
        assert "modin_tpu.watch.evidence" in metric_names

    def test_evidence_bundle_shape_and_rate_limit(
        self, tmp_path, monkeypatch
    ):
        """One incident -> one bundle; the bundle carries all four legs
        (trace segment, meter snapshot, ring excerpt, slo health)."""
        service = _enable_watch_quiet(tmp_path)
        WatchSloMs.put("default=10")
        now = time.monotonic()
        for _ in range(10):
            service.slo.observe("alice", 5.0, now=now)
        service.tripwires.on_tick(now)
        bundles = glob.glob(str(tmp_path / "watchtrip_*.json"))
        assert len(bundles) == 1
        bundle = json.loads(open(bundles[0]).read())
        assert bundle["rule"] == "slo_burn"
        assert set(bundle) >= {"trace", "metrics", "rings", "slo", "detail"}
        assert bundle["slo"]["alice"]["breaching"]
        # a second tick inside the claim window writes nothing new, even
        # with the rule cooldown gone
        monkeypatch.setattr(tw_mod, "RULE_COOLDOWN_S", 0.0)
        service.tripwires.on_tick(now + 1)
        assert len(glob.glob(str(tmp_path / "watchtrip_*.json"))) == 1

    def test_failed_evidence_write_releases_claim(
        self, tmp_path, monkeypatch
    ):
        service = _enable_watch_quiet(tmp_path)
        blocker = tmp_path / "blocked"
        blocker.write_text("a file where the trace dir should be")
        TraceDir.put(str(blocker))  # mkdir will fail
        assert (
            tw_mod.capture_evidence("unit", "detail", service) is None
        )
        TraceDir.put(str(tmp_path))
        # the failed write released its claim: the next capture succeeds
        path = tw_mod.capture_evidence("unit", "detail", service)
        assert path is not None and os.path.exists(path)

    def test_broken_rule_is_isolated(self, tmp_path):
        service = _enable_watch_quiet(tmp_path)

        def explode(_service, _now):
            raise RuntimeError("broken rule")

        service.tripwires.rules.append(
            tw_mod.Tripwire("broken", "unit", explode)
        )
        service.tripwires.on_tick(time.monotonic())  # must not raise
        assert all(
            t["rule"] != "broken" for t in service.tripwires.snapshot()
        )

    def test_rule_cooldown_spaces_retrips(self, tmp_path, monkeypatch):
        service = _enable_watch_quiet(tmp_path)
        monkeypatch.setattr(flight_recorder, "MIN_DUMP_INTERVAL_S", 0.0)
        now = time.monotonic()
        for t, v in [(now - 30, 0), (now, 10)]:
            service.rings.observe("serving.shed", "counter", v, t)
        service.tripwires.on_tick(now)
        service.tripwires.on_tick(now + 1)  # inside RULE_COOLDOWN_S
        trips = [t["rule"] for t in service.tripwires.snapshot()]
        assert trips.count("shed_spike") == 1


# ====================================================================== #
# the live exporter
# ====================================================================== #


class TestHttpd:
    def test_endpoints_serve_and_parse(self, tmp_path, metric_names):
        MetersEnabled.put(True)
        TraceDir.put(str(tmp_path))
        WatchIntervalS.put(0.05)
        WatchPort.put(0)  # ephemeral
        WatchEnabled.put(True)
        emit_metric("engine.dispatch", 1)
        emit_metric("io.read.bytes", 4096)
        port = watch.httpd_port()
        assert port is not None and port > 0
        # /metrics: Prometheus text the validating parser accepts
        from modin_tpu.observability.exposition import parse_prometheus

        parsed = parse_prometheus(_get(port, "/metrics"))
        assert "modin_tpu_engine_dispatch" in parsed
        # /statusz: the one-pager with every section header
        statusz = _get(port, "/statusz")
        for header in (
            "service", "substrate", "windowed rates", "admission gate",
            "tenants", "recent tripwires",
        ):
            assert f"== {header} ==" in statusz
        # /debug/queries: live scopes
        with meters.query_stats("live-probe"):
            dbg = json.loads(_get(port, "/debug/queries"))
        assert dbg["open_scopes"] == 1
        assert dbg["queries"][0]["label"] == "live-probe"
        assert dbg["queries"][0]["open"] is True
        # index + 404 + scrape accounting
        assert "/metrics" in _get(port, "/")
        with pytest.raises(urllib.error.HTTPError):
            _get(port, "/nope")
        assert "modin_tpu.watch.scrape" in metric_names

    def test_port_minus_one_disables_exporter(self):
        WatchPort.put(-1)
        WatchIntervalS.put(1.0)
        WatchEnabled.put(True)
        assert watch.WATCH_ON
        assert watch.httpd_port() is None
        assert not any("httpd" in n for n in _watch_threads())

    def test_out_of_range_port_degrades_exporter_less(self):
        """An env-sourced port bypasses WatchPort.put validation and
        reaches bind() raising OverflowError (not OSError): start must
        return False, never raise into the service start."""
        from modin_tpu.observability.watch.httpd import Exporter

        exporter = Exporter(object())
        assert exporter.start(70000) is False
        assert exporter.port is None

    def test_port_validation(self):
        with pytest.raises(ValueError):
            WatchPort.put(-2)
        with pytest.raises(ValueError):
            WatchPort.put(70000)
        with pytest.raises(ValueError):
            WatchIntervalS.put(0)


# ====================================================================== #
# serving integration
# ====================================================================== #


class TestServingIntegration:
    def test_submit_feeds_slo_and_snapshot_surfaces_it(self, tmp_path):
        TraceDir.put(str(tmp_path))
        WatchSloMs.put("default=100000")  # everything healthy
        WatchIntervalS.put(60.0)
        WatchPort.put(-1)
        WatchEnabled.put(True)
        ServingEnabled.put(True)
        ResilienceBackoffS.put(0.0)
        df = pd.DataFrame({"a": np.arange(64.0)})
        for _ in range(3):
            serving.submit(
                lambda: float(df["a"].sum()), tenant="alice"
            )
        service = watch.get_service()
        health = service.slo.health()
        assert health["alice"]["fast_samples"] >= 3
        assert not health["alice"]["breaching"]
        snap = serving.serving_snapshot()
        assert "slo" in snap and "alice" in snap["slo"]
        # advisory only: nothing was shed because of it
        assert snap["shed"] == 0

    def test_snapshot_has_no_slo_key_when_watch_off(self):
        ServingEnabled.put(True)
        assert "slo" not in serving.serving_snapshot()

    def test_gate_counter_sample_reaches_span_samples(self):
        from modin_tpu.observability import spans as spans_mod

        queued, running = spans_mod._gate_samples()
        assert queued == 0 and running == 0  # idle gate, serving imported
