"""Regression tests for dtype edge cases caught in review: NaT sentinels,
int-pow semantics, large-mean variance stability, nullable extension dtypes."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals

DT_DATA = {
    "ts": pandas.to_datetime(
        ["2020-01-01", None, "2021-06-15", "2019-03-02", None]
    ),
    "k": [1, 1, 2, 2, 2],
    "v": [1.0, 2.0, 3.0, 4.0, 5.0],
}


def test_isna_with_nat():
    md, pdf = create_test_dfs(DT_DATA)
    df_equals(md.isna(), pdf.isna())
    df_equals(md.notna(), pdf.notna())
    df_equals(md["ts"].isna(), pdf["ts"].isna())


def test_groupby_datetime_min_max_count():
    md, pdf = create_test_dfs(DT_DATA)
    df_equals(md.groupby("k")["ts"].min(), pdf.groupby("k")["ts"].min())
    df_equals(md.groupby("k")["ts"].max(), pdf.groupby("k")["ts"].max())
    df_equals(md.groupby("k")["ts"].count(), pdf.groupby("k")["ts"].count())


def test_sort_datetime_nat_last():
    md, pdf = create_test_dfs(DT_DATA)
    df_equals(
        md.sort_values("ts", kind="stable"), pdf.sort_values("ts", kind="stable")
    )


def test_datetime_reductions():
    md, pdf = create_test_dfs(DT_DATA)
    df_equals(md["ts"].min(), pdf["ts"].min())
    df_equals(md.count(), pdf.count())
    df_equals(md.dropna(), pdf.dropna())


def test_int_negative_pow_matches_pandas():
    md, pdf = create_test_dfs({"a": [2, 3], "b": [-1, 2]})
    with pytest.raises(ValueError):
        pdf["a"] ** pdf["b"]
    with pytest.raises(ValueError):
        md["a"] ** md["b"]
    with pytest.raises(ValueError):
        2 ** pdf["b"]
    with pytest.raises(ValueError):
        2 ** md["b"]
    df_equals(md["a"] ** 3, pdf["a"] ** 3)
    df_equals(md["a"] ** -1.0, pdf["a"] ** -1.0)


def test_groupby_var_large_mean():
    base = 1e8
    md, pdf = create_test_dfs(
        {"k": [1, 1, 1, 1], "v": [base + 1, base + 2, base + 3, base + 4]}
    )
    df_equals(md.groupby("k")["v"].var(), pdf.groupby("k")["v"].var())
    df_equals(md.groupby("k")["v"].std(), pdf.groupby("k")["v"].std())


def test_groupby_numeric_only_nullable_ext():
    md, pdf = create_test_dfs(
        {
            "k": [1, 1, 2],
            "a": pandas.array([1, 2, 3], dtype="Int64"),
            "b": [1.0, 2.0, 3.0],
        }
    )
    df_equals(
        md.groupby("k").sum(numeric_only=True),
        pdf.groupby("k").sum(numeric_only=True),
    )


def test_timedelta_roundtrip_and_ops():
    md, pdf = create_test_dfs(
        {"td": pandas.to_timedelta(["1 days", None, "3 days"])}
    )
    df_equals(md, pdf)
    df_equals(md.isna(), pdf.isna())


def test_groupby_result_is_padded_for_binary_ops():
    # regression: groupby outputs must keep the padded-shard layout so
    # follow-up binary ops against equally-sized frames compile
    md, pdf = create_test_dfs({"k": [1, 1, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    gb_md = md.groupby("k").sum()
    gb_pd = pdf.groupby("k").sum()
    df_equals(gb_md + gb_md, gb_pd + gb_pd)
    df_equals(gb_md.sort_values("v"), gb_pd.sort_values("v"))


def test_round_fillna_preserve_datetime():
    md, pdf = create_test_dfs(DT_DATA)
    df_equals(md.round(1), pdf.round(1))
    df_equals(md.fillna(0.0), pdf.fillna(0.0))


def test_idxmin_all_nan_raises():
    md, pdf = create_test_dfs({"a": [np.nan, np.nan], "b": [1.0, 2.0]})
    with pytest.raises(ValueError):
        pdf.idxmin()
    with pytest.raises(ValueError):
        md.idxmin()
