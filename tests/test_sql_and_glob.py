"""Tests: partitioned SQL reader (sqlite), glob IO, custom text."""

import sqlite3

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.db_conn import ModinDatabaseConnection, UnsupportedDatabaseException
from tests.utils import df_equals


@pytest.fixture
def sqlite_db(tmp_path):
    path = str(tmp_path / "test.db")
    conn = sqlite3.connect(path)
    rng = np.random.default_rng(4)
    pdf = pandas.DataFrame(
        {"id": np.arange(5000), "v": rng.uniform(0, 1, 5000).round(6)}
    )
    pdf.to_sql("items", conn, index=False)
    conn.close()
    return path, pdf


class TestSQL:
    def test_read_sql_plain_connection(self, sqlite_db):
        path, pdf = sqlite_db
        conn = sqlite3.connect(path)
        df_equals(pd.read_sql("SELECT * FROM items", conn), pdf)
        conn.close()

    def test_read_sql_modin_connection(self, sqlite_db):
        path, pdf = sqlite_db
        con = ModinDatabaseConnection("sqlite3", path)
        df_equals(pd.read_sql("SELECT * FROM items", con), pdf)

    def test_read_sql_partitioned(self, sqlite_db, monkeypatch):
        import modin_tpu.core.io.sql.sql_dispatcher as disp

        monkeypatch.setattr(disp, "_MIN_PARALLEL_ROWS", 10)
        path, pdf = sqlite_db
        con = ModinDatabaseConnection("sqlite3", path)
        got = pd.read_sql("SELECT * FROM items", con)
        # LIMIT/OFFSET partitions concatenate in order for sqlite
        df_equals(got.sort_values("id", ignore_index=True), pdf)

    def test_partition_query_shape(self):
        con = ModinDatabaseConnection("sqlite3", ":memory:")
        q = con.partition_query("SELECT * FROM t", 10, 20)
        assert "LIMIT 10 OFFSET 20" in q

    def test_unsupported_lib(self):
        with pytest.raises(UnsupportedDatabaseException):
            ModinDatabaseConnection("mongodb")

    def test_to_sql_roundtrip(self, tmp_path):
        path = str(tmp_path / "w.db")
        md = pd.DataFrame({"a": [1, 2, 3]})
        conn = sqlite3.connect(path)
        md.to_sql("t", conn, index=False)
        back = pandas.read_sql("SELECT * FROM t", conn)
        df_equals(md, back)
        conn.close()


class TestGlobIO:
    def test_read_csv_glob(self, tmp_path):
        import modin_tpu.experimental.pandas as xpd

        rng = np.random.default_rng(5)
        parts = []
        for i in range(3):
            part = pandas.DataFrame({"x": rng.integers(0, 9, 100), "part": i})
            part.to_csv(tmp_path / f"part{i}.csv", index=False)
            parts.append(part)
        got = xpd.read_csv_glob(str(tmp_path / "part*.csv"))
        want = pandas.concat(parts, ignore_index=True)
        df_equals(got, want)

    def test_to_pickle_glob_roundtrip(self, tmp_path):
        import modin_tpu.experimental.pandas as xpd

        md = xpd.DataFrame({"a": np.arange(100)})
        xpd.to_pickle_glob(md, str(tmp_path / "out*.pkl"))
        back = xpd.read_pickle_glob(str(tmp_path / "out*.pkl"))
        df_equals(back, md)

    def test_read_custom_text(self, tmp_path):
        import modin_tpu.experimental.pandas as xpd

        path = tmp_path / "data.txt"
        path.write_text("1|a\n2|b\n3|c\n")

        def parser(handle):
            return [line.strip().split("|") for line in handle]

        got = xpd.read_custom_text(str(path), columns=["num", "ch"], custom_parser=parser)
        df_equals(
            got,
            pandas.DataFrame({"num": ["1", "2", "3"], "ch": ["a", "b", "c"]}),
        )


class TestSQLRegressions:
    def test_index_col_with_modin_connection(self, sqlite_db):
        path, pdf = sqlite_db
        con = ModinDatabaseConnection("sqlite3", path)
        got = pd.read_sql("SELECT * FROM items", con, index_col="id")
        df_equals(got, pdf.set_index("id"))

    def test_chunksize_returns_iterator(self, sqlite_db):
        path, pdf = sqlite_db
        con = ModinDatabaseConnection("sqlite3", path)
        chunks = list(pd.read_sql("SELECT * FROM items", con, chunksize=1000))
        assert len(chunks) == 5
        assert sum(len(c) for c in chunks) == len(pdf)

    def test_experimental_partition_bounds(self, sqlite_db):
        import modin_tpu.experimental.pandas as xpd

        path, pdf = sqlite_db
        con = ModinDatabaseConnection("sqlite3", path)
        got = xpd.read_sql(
            "SELECT * FROM items", con,
            partition_column="id", lower_bound=0, upper_bound=5000,
        )
        df_equals(got.sort_values("id", ignore_index=True), pdf)
