"""graftmesh acceptance: sharded sort/merge/groupby/reduce on the mesh.

Four layers:

1. the differential parity grid — sort / merge / groupby / reduce / the
   sort-shaped reductions at mesh shapes (1,1), (2,1), (4,1), (8,1) with
   the sharded path FORCED, bit-exact vs pandas, including a ragged final
   shard and an all-NaN shard;
2. kernel-level identity — the sharded sorted-representation build and the
   sharded merge positions are byte-identical to their local builds (the
   routing layer can flip freely without observable change);
3. chaos — ``midquery_device_loss`` killing ONE shard re-seats only that
   shard's slice per column (``recovery.reseat.shard``), never the whole
   column, and the query completes bit-exact;
4. routing/accounting units — ``decide_layout`` forced/auto/crossover
   behavior, skew fallback, mesh-keyed sorted-rep invalidation, the
   two-mesh-shape padding-waste accounting, and collective-bytes
   accounting.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import MeshShape, SpmdMode
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from tests.utils import df_equals


@pytest.fixture(autouse=True)
def _require_mesh():
    from modin_tpu.parallel.mesh import num_row_shards
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax" or num_row_shards() < 2:
        pytest.skip("needs TpuOnJax on a multi-device mesh")


@pytest.fixture
def metric_counts():
    seen = {}

    def handler(name, value):
        seen[name] = seen.get(name, 0) + value

    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


@pytest.fixture
def forced_sharded():
    with SpmdMode.context("Sharded"):
        yield


def _restore_default_mesh():
    from modin_tpu.parallel.mesh import reset_mesh

    reset_mesh()


@pytest.fixture
def mesh_reshaper():
    """Reshape the live mesh for a test; always restores the default."""
    from modin_tpu.parallel.mesh import num_row_shards, reset_mesh

    def reshape(shape):
        MeshShape.put(tuple(shape))
        reset_mesh()
        return num_row_shards()

    try:
        yield reshape
    finally:
        MeshShape.put((8, 1))
        _restore_default_mesh()


# ---------------------------------------------------------------------- #
# 1. differential parity grid across mesh shapes
# ---------------------------------------------------------------------- #


def _grid_frames(rng, n=803):
    """Ragged length (803 % 8 != 0) + a NaN run wide enough to fill whole
    shards at every grid shape (an all-NaN shard is the degenerate case
    the shuffle's NaN-routing must survive)."""
    data = {
        "k": rng.normal(size=n),
        "g": rng.integers(0, 7, n).astype(np.int64),
        # unique: pandas' default sort kind is quicksort (tie order is
        # unspecified there), so exactness asserts need tie-free keys
        "v": rng.permutation(n * 3)[:n].astype(np.int64),
    }
    data["k"][700:] = np.nan  # the final shard(s) are all-NaN at S>=8
    return pandas.DataFrame(data), data


@pytest.mark.parametrize("shape", [(1, 1), (2, 1), (4, 1), (8, 1)])
def test_parity_grid_bit_exact_vs_pandas(shape, mesh_reshaper, forced_sharded):
    shards = mesh_reshaper(shape)
    assert shards == shape[0]
    rng = np.random.default_rng(11)
    pdf, data = _grid_frames(rng)
    mdf = pd.DataFrame(data)
    mdf._query_compiler.execute()

    # sort (sharded path when S >= 2; identical local path at (1,1))
    df_equals(mdf.sort_values("k"), pdf.sort_values("k"))
    df_equals(
        mdf.sort_values("v", ascending=False),
        pdf.sort_values("v", ascending=False),
    )
    # groupby + reduce (already-SPMD paths must stay bit-identical)
    df_equals(mdf.groupby("g").sum(), pdf.groupby("g").sum())
    assert int(mdf["v"].sum()) == int(pdf["v"].sum())
    # sort-shaped reductions through the (sharded) sorted-rep build
    m, p = mdf["v"].median(), pdf["v"].median()
    assert m == p
    assert int(mdf["v"].nunique()) == int(pdf["v"].nunique())
    km, kp = mdf["k"].median(), pdf["k"].median()
    assert (np.isnan(km) and np.isnan(kp)) or km == kp

    # merge at this mesh shape
    lk = rng.integers(0, 40, 257).astype(np.int64)
    rk = rng.integers(0, 40, 181).astype(np.int64)
    pl = pandas.DataFrame({"k": lk, "a": np.arange(257)})
    pr = pandas.DataFrame({"k": rk, "b": np.arange(181)})
    ml = pd.DataFrame({"k": lk, "a": np.arange(257)})
    mr = pd.DataFrame({"k": rk, "b": np.arange(181)})
    for how in ("inner", "left", "outer"):
        df_equals(
            ml.merge(mr, on="k", how=how), pl.merge(pr, on="k", how=how)
        )


# ---------------------------------------------------------------------- #
# 2. kernel-level identity vs the local builds
# ---------------------------------------------------------------------- #


def test_sharded_sorted_valid_matches_local_build():
    from modin_tpu.ops.sort import sorted_valid_columns
    from modin_tpu.ops.spmd import sharded_sorted_valid
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper

    rng = np.random.default_rng(3)
    n = 4001
    for values in (
        rng.normal(size=n),
        rng.integers(0, 1 << 30, n).astype(np.int64),
    ):
        if values.dtype.kind == "f":
            values[17:900] = np.nan
            values[5] = np.inf
            values[6] = -np.inf
        dev = JaxWrapper.put(pad_host(values))
        pair = sharded_sorted_valid(dev, n)
        assert pair is not None
        [(local_xs, local_nv)] = sorted_valid_columns([dev], n)
        np.testing.assert_array_equal(np.asarray(pair[0]), np.asarray(local_xs))
        assert int(np.asarray(pair[1])) == int(np.asarray(local_nv))


def test_sharded_merge_positions_match_local():
    from modin_tpu.ops.join import sort_merge_positions
    from modin_tpu.ops.spmd import sharded_merge_positions
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper

    rng = np.random.default_rng(4)
    n_l, n_r = 1501, 907
    lk = rng.uniform(-5, 5, n_l).round(1)  # float keys with heavy ties
    rk = rng.uniform(-5, 5, n_r).round(1)
    lk[3:40] = np.nan  # NaN keys match each other in pandas merge
    rk[10:25] = np.nan
    ldev = JaxWrapper.put(pad_host(lk))
    rdev = JaxWrapper.put(pad_host(rk))
    for how in ("inner", "left"):
        got = sharded_merge_positions(ldev, rdev, n_l, n_r, how)
        assert got is not None
        g_lp, g_rp, g_n, g_miss = got
        e_lp, e_rp, e_n, e_miss = sort_merge_positions(
            ldev, rdev, n_l, n_r, how
        )
        assert (g_n, g_miss) == (e_n, e_miss)
        np.testing.assert_array_equal(
            np.asarray(g_lp)[:g_n], np.asarray(e_lp)[:e_n]
        )
        np.testing.assert_array_equal(
            np.asarray(g_rp)[:g_n], np.asarray(e_rp)[:e_n]
        )


# ---------------------------------------------------------------------- #
# 3. chaos: one lost shard re-seats ONE shard, not the whole column
# ---------------------------------------------------------------------- #


def test_shard_loss_reseats_only_that_shard(metric_counts):
    from modin_tpu.config import ResilienceBackoffS
    from modin_tpu.testing.faults import midquery_device_loss

    rng = np.random.default_rng(9)
    vals = rng.integers(0, 10_000, 4096).astype(np.int64)
    mdf = pd.DataFrame({"a": vals, "b": vals * 3})
    mdf._query_compiler.execute()
    col = mdf._query_compiler._modin_frame.get_column(0)
    try:
        ptrs_before = [
            s.data.unsafe_buffer_pointer()
            for s in sorted(
                col._data.addressable_shards,
                key=lambda s: s.index[0].start or 0,
            )
        ]
    except Exception:
        ptrs_before = None
    expected = pandas.DataFrame({"a": vals, "b": vals * 3}) + 7

    before = dict(metric_counts)
    with ResilienceBackoffS.context(0.0):
        with midquery_device_loss(
            after_deploys=0, times=1, ops=("deploy",), shard_index=2
        ) as inj:
            got = (mdf + 7).modin.to_pandas()
    pandas.testing.assert_frame_equal(got, expected)
    assert inj.injected == 1

    def delta(name):
        key = f"modin_tpu.{name}"
        return metric_counts.get(key, 0) - before.get(key, 0)

    # our two columns both took the single-shard leg (other suites'
    # resident columns may legitimately add more shard/op re-seats)
    assert delta("recovery.reseat.shard") >= 2
    if ptrs_before is not None:
        ptrs_after = [
            s.data.unsafe_buffer_pointer()
            for s in sorted(
                col._data.addressable_shards,
                key=lambda s: s.index[0].start or 0,
            )
        ]
        changed = [
            i for i, (a, b) in enumerate(zip(ptrs_before, ptrs_after))
            if a != b
        ]
        # only the named shard's buffer may have been replaced (the
        # allocator may even reuse the freed address, so it can appear
        # unchanged); the other seven survived IN PLACE — the "re-seat a
        # shard, not a column" contract
        assert set(changed) <= {2}, changed


def test_multi_shard_loss_reseats_each_lost_shard(metric_counts):
    """Two shards lost in ONE recovery pass: the pass walks the lost
    indices, `reseat_from_host_shard` succeeds for each, the surviving
    six shards keep their device buffers in place, and the column reads
    back bit-exact."""
    from modin_tpu.core.execution import recovery

    rng = np.random.default_rng(21)
    vals = rng.integers(0, 10_000, 4096).astype(np.int64)
    mdf = pd.DataFrame({"a": vals, "b": vals * 5})
    mdf._query_compiler.execute()
    mf = mdf._query_compiler._modin_frame
    cols = [mf.get_column(i) for i in range(mf.num_cols)]

    def shard_ptrs(col):
        try:
            return [
                s.data.unsafe_buffer_pointer()
                for s in sorted(
                    col._data.addressable_shards,
                    key=lambda s: s.index[0].start or 0,
                )
            ]
        except Exception:
            return None

    ptrs_before = [shard_ptrs(c) for c in cols]
    lost = (2, 5)

    before = dict(metric_counts)
    # one recovery pass over a loss that named TWO mesh row shards: each
    # column replays each lost shard's slice, never the whole buffer
    for col in cols:
        for shard in lost:
            kind = recovery.recover_column(
                col, force=True, shard_index=shard
            )
            assert kind == "shard", (col.pandas_dtype, shard, kind)

    expected = pandas.DataFrame({"a": vals, "b": vals * 5})
    pandas.testing.assert_frame_equal(mdf.modin.to_pandas(), expected)

    for col, ptrs in zip(cols, ptrs_before):
        if ptrs is None:
            continue
        ptrs_after = shard_ptrs(col)
        changed = [
            i for i, (a, b) in enumerate(zip(ptrs, ptrs_after)) if a != b
        ]
        # only the two lost shards' buffers may differ; the other six
        # survived in place
        assert set(changed) <= set(lost), changed


# ---------------------------------------------------------------------- #
# 4. routing & accounting units
# ---------------------------------------------------------------------- #


def test_decide_layout_forced_and_floor():
    from modin_tpu.ops import router

    with SpmdMode.context("Sharded"):
        assert router.decide_layout("sort", 10) == "sharded"
    with SpmdMode.context("Local"):
        assert router.decide_layout("sort", 10**9) == "local"
    with SpmdMode.context("Auto"):
        # below the SpmdMinRows floor: local without consulting calibration
        assert router.decide_layout("sort", 10) == "local"


def test_decide_layout_crossover_from_forced_table():
    from modin_tpu.ops import router

    base = {
        "version": router._CAL_VERSION,
        "platform": "cpu",
        "rows": 1 << 18,
        "device_sort_s": 1.0,
        "device_consume_s": 0.01,
        "device_hist_s": 0.01,
        "device_shuffle_s": 0.25,
        "collective_bytes_per_s": 1e9,
    }
    try:
        with SpmdMode.context("Auto"):
            router.set_calibration(dict(base))
            n = 1 << 20  # above the min-rows floor
            assert router.decide_layout("sort", n) == "sharded"
            # extra payload columns billed at the collective bandwidth can
            # flip the decision back to local
            slow = dict(base, collective_bytes_per_s=1.0)
            router.set_calibration(slow)
            assert (
                router.decide_layout("sort", n, payload_cols=8) == "local"
            )
            # a table with no sharded entries (single-shard calibration)
            # keeps routing local
            no_sharded = {
                k: v for k, v in base.items() if "shuffle" not in k
            }
            router.set_calibration(no_sharded)
            assert router.decide_layout("sort", n) == "local"
    finally:
        router.set_calibration(None)


def test_merge_skew_falls_back_to_local(monkeypatch, forced_sharded):
    # pathological skew: the shuffle gives up (ShuffleSkewError) and the
    # merge must still answer bit-exact via the local sort-merge kernel
    import modin_tpu.parallel.shuffle as shuffle_mod

    def boom(*args, **kwargs):
        raise shuffle_mod.ShuffleSkewError(
            "range_shuffle: pathological key skew"
        )

    monkeypatch.setattr(shuffle_mod, "range_shuffle", boom)
    rng = np.random.default_rng(13)
    n = 1024
    lk = rng.integers(0, 3, n).astype(np.int64)
    rk = np.full(n, 1, np.int64)
    pl = pandas.DataFrame({"k": lk, "a": np.arange(n)})
    pr = pandas.DataFrame({"k": rk, "b": np.arange(n)})
    ml = pd.DataFrame({"k": lk, "a": np.arange(n)})
    mr = pd.DataFrame({"k": rk, "b": np.arange(n)})
    df_equals(ml.merge(mr, on="k"), pl.merge(pr, on="k"))


def test_sorted_rep_invalidates_on_mesh_reshape(mesh_reshaper):
    from modin_tpu.ops import sorted_cache
    from modin_tpu.ops.sort import sorted_valid_columns

    rng = np.random.default_rng(21)
    vals = rng.integers(0, 1 << 30, 2048).astype(np.int64)
    mdf = pd.DataFrame({"w": vals})
    mdf._query_compiler.execute()
    col = mdf._query_compiler._modin_frame.get_column(0)
    [(xs, nv)] = sorted_valid_columns([col.data], len(vals))
    sorted_cache.attach(col, xs, nv)
    assert sorted_cache.peek(col)
    mesh_reshaper((4, 1))
    # the rep was built under 8x1; a 4x1 mesh must not serve it
    assert not sorted_cache.peek(col)


def test_padding_waste_differs_by_mesh_shape(mesh_reshaper):
    from modin_tpu.config import CostCapture
    from modin_tpu.observability import costs

    n = 1001  # pad_len: 1002 at S=2 (1 pad row), 1008 at S=8 (7 pad rows)
    values = np.arange(n, dtype=np.int64)
    wastes = {}
    with CostCapture.context("On"):
        for shape in ((2, 1), (8, 1)):
            mesh_reshaper(shape)
            before = costs.thread_padding()[1]
            from modin_tpu.ops.structural import pad_host

            pad_host(values)
            wastes[shape] = costs.thread_padding()[1] - before
    assert wastes[(2, 1)] == 1 * values.dtype.itemsize
    assert wastes[(8, 1)] == 7 * values.dtype.itemsize
    assert 0 < wastes[(2, 1)] < wastes[(8, 1)]


def test_collective_bytes_accounted(forced_sharded):
    from modin_tpu.config import CostCapture
    from modin_tpu.observability import costs
    from modin_tpu.ops.spmd import sharded_sorted_valid
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper

    rng = np.random.default_rng(31)
    n = 2048
    dev = JaxWrapper.put(pad_host(rng.integers(0, 1 << 30, n)))
    with CostCapture.context("On"):
        before = costs.thread_collective()
        pair = sharded_sorted_valid(dev, n)
        assert pair is not None
        moved = costs.thread_collective() - before
    assert moved > 0
    snap = costs.get_cost_ledger().snapshot()
    assert snap["collective"].get("shuffle.all_to_all", {}).get("bytes", 0) > 0


def test_shard_valid_counts_prefix_layout(mesh_reshaper):
    # the per-shard valid-row accounting of the padded prefix layout:
    # full shards, one ragged shard, empty pad shards — and it re-answers
    # for the CURRENT mesh after a reshape
    n = 803
    mdf = pd.DataFrame({"v": np.arange(n, dtype=np.int64)})
    mdf._query_compiler.execute()
    col = mdf._query_compiler._modin_frame.get_column(0)
    counts = col.shard_valid_counts()
    assert len(counts) == 8 and int(counts.sum()) == n
    assert list(counts[:-1]) == [101] * 7 and counts[-1] == 96  # pad 808
    mesh_reshaper((2, 1))
    counts2 = col.shard_valid_counts()  # 8x1-laid buffer, 2x1 mesh
    assert len(counts2) == 2 and int(counts2.sum()) == n


def test_spmd_declines_on_single_shard_mesh(mesh_reshaper, forced_sharded):
    from modin_tpu.ops.spmd import sharded_merge_positions, sharded_sorted_valid
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper

    mesh_reshaper((1, 1))
    dev = JaxWrapper.put(pad_host(np.arange(64, dtype=np.int64)))
    assert sharded_sorted_valid(dev, 64) is None
    assert sharded_merge_positions(dev, dev, 64, 64, "inner") is None
