"""graftwal acceptance: WAL, crash-consistent checkpoints, bit-exact replay.

Covers the durability contract end to end:

- round-trip recovery under every fsync policy (PerBatch / GroupCommit /
  Off): a durable feed with registered views closes, reopens, and every
  row, key-index entry, and view answer is bit-exact vs pandas;
- checkpoints bound replay: past ``MODIN_TPU_WAL_MAX_REPLAY_BATCHES``
  a checkpoint lands (temp-file + fsync + atomic rename), covered WAL
  segments are truncated, and recovery replays at most the tail;
- the differential kill -9 grid: a child process ingests a deterministic
  stream and is SIGKILLed at injected points (mid-record torn write,
  mid-checkpoint, mid-truncate — testing/faults.DiskFaultInjector); the
  parent reopens the directory and the recovered state must be bit-exact
  to an uninterrupted control at SOME batch count R with
  acked <= R <= acked+1 — durability never loses an acked batch and
  never invents one;
- torn tails and flipped bytes: garbage or a single flipped bit in a
  segment truncates to the last intact record with ``wal.torn_tail``
  accounting, never a crash;
- disk-fault policy: ENOSPC triggers one retention-driven reclaim then a
  typed ``DurabilityError`` refusal BEFORE any in-memory mutation; EIO
  trips the per-feed breaker into memory-only degraded mode
  (``wal.degraded``) and ingestion keeps working;
- the zero-overhead contract: a non-durable feed never imports the
  durability package (subprocess), allocates nothing
  (``durability_alloc_count``), and carries exactly one ``_wal is None``
  check on the hot path;
- satellite regressions: fleet coordinators export the durability root
  to replica spawn environments, and a flight-recorder dump that dies
  mid-write releases the shared claim window (the next dump of the real
  fault must not be rate-limited away).
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pandas
import pytest

from modin_tpu import ingest
from modin_tpu.config import (
    IngestEnabled,
    WalFsync,
    WalGroupCommitMs,
    WalMaxReplayBatches,
    WalSegmentBytes,
)
from modin_tpu.logging.metrics import add_metric_handler, clear_metric_handler
from modin_tpu.views import registry

from tests.utils import df_equals, require_tpu_execution

_SCHEMA = {"k": "int64", "i": "int64", "x": "float64", "g": "int64"}
_BATCH_ROWS = 16

_PLANS = {
    "total": {"kind": "scalar", "column": "i", "agg": "sum"},
    "by_group": {"kind": "groupby", "by": "g", "column": "i", "agg": "sum"},
}


@pytest.fixture(autouse=True)
def _durability_env(tmp_path):
    require_tpu_execution()
    registry.reset()
    ingest.reset()
    IngestEnabled.enable()
    yield
    ingest.reset()
    registry.reset()
    IngestEnabled.disable()
    WalFsync.put("PerBatch")
    WalGroupCommitMs.put(25.0)
    WalMaxReplayBatches.put(256)
    WalSegmentBytes.put(4_194_304)
    # a test that died inside a DiskFaultInjector context must not leak
    # its hook into the next test
    from modin_tpu.durability import wal

    wal._disk_fault_hook = None


@pytest.fixture
def metric_log():
    events = []

    def handler(name, value):
        events.append((name, value))

    add_metric_handler(handler)
    yield events
    clear_metric_handler(handler)


def _count(events, name):
    return sum(1 for n, _ in events if n == f"modin_tpu.{name}")


def _total(events, name):
    return sum(v for n, v in events if n == f"modin_tpu.{name}")


def _batch(b, n=_BATCH_ROWS, key_start=None):
    rng = np.random.default_rng(7000 + b)
    start = b * n if key_start is None else key_start
    return pandas.DataFrame(
        {
            "k": np.arange(start, start + n, dtype=np.int64),
            "i": rng.integers(-1000, 1000, n),
            "x": rng.normal(size=n),
            "g": rng.integers(0, 5, n),
        }
    )


def _control(nbatches):
    if nbatches == 0:
        return pandas.DataFrame(
            {c: pandas.Series(dtype=d) for c, d in _SCHEMA.items()}
        )
    pdf = pandas.concat(
        [_batch(b) for b in range(nbatches)], ignore_index=True
    )
    return pdf.astype(_SCHEMA)


def _assert_feed_equals(feed, control):
    df_equals(
        feed.frame._to_pandas().reset_index(drop=True),
        control.reset_index(drop=True),
    )


def _assert_views(feed, control):
    assert feed.read("total").value == control["i"].sum()
    got = pandas.Series(feed.read("by_group").value)
    want = control.groupby("g")["i"].sum()
    pandas.testing.assert_series_equal(
        got, want, check_names=False, check_index_type=False
    )


# ====================================================================== #
# round-trip recovery
# ====================================================================== #


class TestRoundTrip:
    @pytest.mark.parametrize("policy", ["PerBatch", "GroupCommit", "Off"])
    def test_recover_bit_exact(self, tmp_path, policy, metric_log):
        WalFsync.put(policy)
        WalGroupCommitMs.put(5.0)
        feed = ingest.open_feed(
            "events", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        for name, plan in _PLANS.items():
            feed.register_view(name, plan)
        for b in range(6):
            feed.append(_batch(b))
        control = _control(6)
        _assert_feed_equals(feed, control)
        ingest.reset()  # clean close: final flush + flusher join

        feed = ingest.open_feed(
            "events", durable=True, durability_dir=str(tmp_path)
        )
        assert feed.rows == 6 * _BATCH_ROWS
        # no checkpoint was due (bound 256), so the whole log replayed:
        # 2 registrations + 6 batches
        assert feed._wal.replayed_batches == 8
        assert _total(metric_log, "wal.replay.batches") == 8
        assert _count(metric_log, "recovery.feed") == 1
        _assert_feed_equals(feed, control)
        _assert_views(feed, control)
        # the recovered feed keeps ingesting — and THAT survives too
        feed.append(_batch(6))
        control = _control(7)
        _assert_views(feed, control)
        ingest.reset()
        feed = ingest.open_feed(
            "events", durable=True, durability_dir=str(tmp_path)
        )
        _assert_feed_equals(feed, control)
        _assert_views(feed, control)

    def test_upsert_key_index_recovered(self, tmp_path):
        feed = ingest.open_feed(
            "keyed", schema=_SCHEMA, key="k", durable=True,
            durability_dir=str(tmp_path),
        )
        for b in range(4):
            feed.append(_batch(b))
        up = _batch(9, n=20, key_start=50)  # 14 updates + 6 new keys
        feed.upsert(up)
        want = feed.frame._to_pandas().reset_index(drop=True)
        ingest.reset()

        feed = ingest.open_feed(
            "keyed", durable=True, durability_dir=str(tmp_path)
        )
        assert feed.key == "k"  # inherited from meta.json
        df_equals(feed.frame._to_pandas().reset_index(drop=True), want)
        # the key index came back: upserting the same keys again updates
        # in place instead of appending
        rows_before = feed.rows
        feed.upsert(up)
        assert feed.rows == rows_before

    def test_checkpoint_bounds_replay(self, tmp_path, metric_log):
        WalMaxReplayBatches.put(4)
        WalSegmentBytes.put(1024)  # force several segments
        feed = ingest.open_feed(
            "ckpt", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        feed.register_view("total", _PLANS["total"])
        for b in range(12):
            feed.append(_batch(b))
        assert _count(metric_log, "checkpoint.write") >= 2
        assert _total(metric_log, "wal.truncate.segments") > 0
        ingest.reset()

        feed = ingest.open_feed(
            "ckpt", durable=True, durability_dir=str(tmp_path)
        )
        assert _count(metric_log, "checkpoint.load") == 1
        # replay is bounded by the checkpoint cadence, not log length;
        # records in the retained active segment already covered by the
        # checkpoint are SKIPPED by sequence number, not re-applied
        assert feed._wal.replayed_batches <= 4
        control = _control(12)
        _assert_feed_equals(feed, control)
        assert feed.read("total").value == control["i"].sum()

    def test_schema_mismatch_refused(self, tmp_path):
        from modin_tpu.durability import DurabilityError

        ingest.open_feed(
            "strict", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        ingest.reset()
        with pytest.raises(DurabilityError) as err:
            ingest.open_feed(
                "strict", schema={"other": "float64"}, durable=True,
                durability_dir=str(tmp_path),
            )
        assert err.value.reason == "schema_mismatch"

    def test_recover_feeds_scans_root(self, tmp_path):
        from modin_tpu import durability

        for name in ("alpha", "beta"):
            feed = ingest.open_feed(
                name, schema=_SCHEMA, durable=True,
                durability_dir=str(tmp_path),
            )
            feed.register_view("total", _PLANS["total"])
            for b in range(3):
                feed.append(_batch(b))
        ingest.reset()

        assert durability.recover_feeds(str(tmp_path)) == 2
        assert set(ingest.feeds()) == {"alpha", "beta"}
        control = _control(3)
        for name in ("alpha", "beta"):
            feed = ingest.get_feed(name)
            _assert_feed_equals(feed, control)
            assert feed.read("total").value == control["i"].sum()
        # idempotent: already-registered feeds are left alone
        assert durability.recover_feeds(str(tmp_path)) == 0


# ====================================================================== #
# torn tails & corruption
# ====================================================================== #


def _segments(feed_dir):
    return sorted(
        os.path.join(feed_dir, f)
        for f in os.listdir(feed_dir)
        if f.startswith("wal_") and f.endswith(".seg")
    )


class TestTornAndCorrupt:
    def test_torn_tail_truncated(self, tmp_path, metric_log):
        feed = ingest.open_feed(
            "torn", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        feed.register_view("total", _PLANS["total"])
        for b in range(5):
            feed.append(_batch(b))
        ingest.reset()

        # a crash mid-write: garbage shorter than a record header
        segs = _segments(str(tmp_path / "torn"))
        with open(segs[-1], "ab") as fh:
            fh.write(b"\x07torn")
        feed = ingest.open_feed(
            "torn", durable=True, durability_dir=str(tmp_path)
        )
        assert _count(metric_log, "wal.torn_tail") == 1
        control = _control(5)
        _assert_feed_equals(feed, control)
        assert feed.read("total").value == control["i"].sum()
        # the truncated segment is adopted and appending continues
        feed.append(_batch(5))
        ingest.reset()
        feed = ingest.open_feed(
            "torn", durable=True, durability_dir=str(tmp_path)
        )
        control = _control(6)
        _assert_feed_equals(feed, control)

    def test_flipped_byte_prefix_recovery(self, tmp_path, metric_log):
        feed = ingest.open_feed(
            "flip", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        for b in range(5):
            feed.append(_batch(b))
        ingest.reset()

        # flip one byte inside the LAST record's payload: its CRC fails,
        # the prefix up to it replays intact
        segs = _segments(str(tmp_path / "flip"))
        data = bytearray(open(segs[-1], "rb").read())
        data[-10] ^= 0xFF
        with open(segs[-1], "wb") as fh:
            fh.write(bytes(data))
        feed = ingest.open_feed(
            "flip", durable=True, durability_dir=str(tmp_path)
        )
        assert _count(metric_log, "wal.torn_tail") == 1
        assert feed.rows == 4 * _BATCH_ROWS  # last batch discarded
        _assert_feed_equals(feed, _control(4))


# ====================================================================== #
# disk-fault policy (ENOSPC / EIO)
# ====================================================================== #


class TestDiskFaults:
    def test_enospc_reclaims_then_succeeds(self, tmp_path, metric_log):
        from modin_tpu.testing import inject_disk_faults

        feed = ingest.open_feed(
            "nospc", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        feed.append(_batch(0))
        with inject_disk_faults("enospc", ops=("wal.write",), times=1):
            feed.append(_batch(1))  # reclaim pass, then the retry lands
        assert _count(metric_log, "wal.enospc.reclaim") == 1
        assert not feed._wal.degraded
        _assert_feed_equals(feed, _control(2))
        ingest.reset()
        feed = ingest.open_feed(
            "nospc", durable=True, durability_dir=str(tmp_path)
        )
        _assert_feed_equals(feed, _control(2))

    def test_enospc_exhausted_is_typed_refusal(self, tmp_path, metric_log):
        from modin_tpu.durability import DurabilityError
        from modin_tpu.testing import inject_disk_faults

        feed = ingest.open_feed(
            "full", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        feed.append(_batch(0))
        with inject_disk_faults("enospc", ops=("wal.write",), times=2):
            with pytest.raises(DurabilityError) as err:
                feed.append(_batch(1))
        assert err.value.reason == "enospc"
        # refused BEFORE any in-memory mutation — and not degraded: a
        # later append (space freed) goes straight back to being durable
        assert feed.rows == _BATCH_ROWS
        assert not feed._wal.degraded
        feed.append(_batch(1))
        _assert_feed_equals(feed, _control(2))
        ingest.reset()
        feed = ingest.open_feed(
            "full", durable=True, durability_dir=str(tmp_path)
        )
        _assert_feed_equals(feed, _control(2))

    def test_eio_degrades_to_memory_only(self, tmp_path, metric_log):
        from modin_tpu.testing import inject_disk_faults

        feed = ingest.open_feed(
            "sick", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        feed.register_view("total", _PLANS["total"])
        feed.append(_batch(0))
        with inject_disk_faults("eio", ops=("wal.write",), times=1):
            feed.append(_batch(1))  # the write dies; ingestion must not
        assert feed._wal.degraded
        assert _count(metric_log, "wal.degraded") == 1
        feed.append(_batch(2))  # memory-only from here on
        control = _control(3)
        _assert_feed_equals(feed, control)
        assert feed.read("total").value == control["i"].sum()
        # the breaker trips ONCE, not per batch
        assert _count(metric_log, "wal.degraded") == 1
        ingest.reset()
        # durability was honestly lost at the breaker: recovery serves
        # exactly the pre-degrade prefix
        feed = ingest.open_feed(
            "sick", durable=True, durability_dir=str(tmp_path)
        )
        _assert_feed_equals(feed, _control(1))

    def test_fsync_failure_degrades(self, tmp_path, metric_log):
        from modin_tpu.testing import inject_disk_faults

        feed = ingest.open_feed(
            "nosync", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        with inject_disk_faults("fsync_fail", ops=("wal.fsync",), times=1):
            feed.append(_batch(0))
        assert feed._wal.degraded
        assert _count(metric_log, "wal.degraded") == 1
        assert feed.rows == _BATCH_ROWS


# ====================================================================== #
# the differential kill -9 grid
# ====================================================================== #

_CHILD = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MODIN_TPU_INGEST"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
import pandas
from modin_tpu import ingest
from modin_tpu.config import WalFsync, WalMaxReplayBatches, WalSegmentBytes
from modin_tpu.testing import DiskFaultInjector

WalFsync.put(os.environ["DUR_FSYNC"])
WalMaxReplayBatches.put(int(os.environ["DUR_MAX_REPLAY"]))
WalSegmentBytes.put(int(os.environ["DUR_SEG_BYTES"]))
feed = ingest.open_feed(
    "grid", schema={"k": "int64", "i": "int64", "x": "float64",
                    "g": "int64"},
    durable=True, durability_dir=os.environ["DUR_DIR"],
)
feed.register_view("total", {"kind": "scalar", "column": "i", "agg": "sum"})
inj = DiskFaultInjector(
    kind=os.environ["DUR_KIND"], ops=(os.environ["DUR_OP"],),
    times=1, skip=int(os.environ["DUR_SKIP"]),
)
inj.__enter__()  # never exits: the injected fault SIGKILLs this process
for b in range(int(os.environ["DUR_TOTAL"])):
    rng = np.random.default_rng(7000 + b)
    n = 16
    feed.append(pandas.DataFrame({
        "k": np.arange(b * n, b * n + n, dtype=np.int64),
        "i": rng.integers(-1000, 1000, n),
        "x": rng.normal(size=n),
        "g": rng.integers(0, 5, n),
    }))
    print("ACKED", b + 1, flush=True)
print("SURVIVED", flush=True)
"""

#: (label, fault kind, faulted op, skip count, fsync policy, max replay)
_KILL_GRID = [
    ("mid_record", "torn_write", "wal.write", 5, "PerBatch", 256),
    ("mid_checkpoint", "kill", "checkpoint.write", 0, "PerBatch", 3),
    ("mid_truncate", "kill", "checkpoint.truncate", 0, "PerBatch", 3),
    ("mid_stream_groupcommit", "kill", "wal.write", 6, "GroupCommit", 256),
]


class TestKillGrid:
    @pytest.mark.parametrize(
        "label,kind,op,skip,fsync,max_replay", _KILL_GRID,
        ids=[row[0] for row in _KILL_GRID],
    )
    def test_kill_recover_bit_exact(
        self, tmp_path, metric_log, label, kind, op, skip, fsync, max_replay
    ):
        total = 10
        env = dict(
            os.environ,
            DUR_DIR=str(tmp_path),
            DUR_FSYNC=fsync,
            DUR_MAX_REPLAY=str(max_replay),
            DUR_SEG_BYTES="1024",
            DUR_KIND=kind,
            DUR_OP=op,
            DUR_SKIP=str(skip),
            DUR_TOTAL=str(total),
        )
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD], env=env,
            capture_output=True, text=True, timeout=180,
        )
        assert "SURVIVED" not in proc.stdout, (
            f"the injected {kind}@{op} never fired:\n{proc.stdout}"
            f"\n{proc.stderr}"
        )
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stdout, proc.stderr
        )
        acked = sum(
            1 for line in proc.stdout.splitlines()
            if line.startswith("ACKED")
        )
        assert acked > 0, (proc.stdout, proc.stderr)

        feed = ingest.open_feed(
            "grid", durable=True, durability_dir=str(tmp_path)
        )
        assert feed.rows % _BATCH_ROWS == 0, (
            f"recovery surfaced a partial batch: {feed.rows} rows"
        )
        recovered = feed.rows // _BATCH_ROWS
        # never lose an acked batch, never invent one: the only ambiguity
        # is the single batch in flight at the kill
        assert acked <= recovered <= min(acked + 1, total), (
            label, acked, recovered
        )
        control = _control(recovered)
        _assert_feed_equals(feed, control)
        assert feed.read("total").value == control["i"].sum()
        assert _count(metric_log, "recovery.feed") == 1


# ====================================================================== #
# zero overhead for non-durable feeds
# ====================================================================== #

_PLAIN_CHILD = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MODIN_TPU_INGEST"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
import numpy as np
import pandas
from modin_tpu import ingest

feed = ingest.open_feed("plain", schema={"i": "int64"})
feed.register_view("total", {"kind": "scalar", "column": "i", "agg": "sum"})
for b in range(3):
    feed.append(pandas.DataFrame({"i": np.arange(8, dtype=np.int64)}))
assert feed.rows == 24
assert feed.read("total").value == 3 * 28
assert feed._wal is None
assert "modin_tpu.durability" not in sys.modules, (
    "the durability package was imported on the non-durable path"
)
print("CLEAN")
"""


class TestZeroOverhead:
    def test_non_durable_never_imports_durability(self):
        proc = subprocess.run(
            [sys.executable, "-c", _PLAIN_CHILD], env=dict(os.environ),
            capture_output=True, text=True, timeout=180,
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert "CLEAN" in proc.stdout

    def test_alloc_counter_contract(self, tmp_path):
        from modin_tpu import durability

        before = durability.durability_alloc_count()
        plain = ingest.create_feed("plain", _SCHEMA)
        for b in range(4):
            plain.append(_batch(b))
        assert plain._wal is None
        assert durability.durability_alloc_count() == before, (
            "a non-durable feed allocated durability machinery"
        )
        # a durable feed allocates exactly its manager + segment writer
        ingest.open_feed(
            "heavy", schema=_SCHEMA, durable=True,
            durability_dir=str(tmp_path),
        )
        assert durability.durability_alloc_count() == before + 2
        assert durability.DURABILITY_ON


# ====================================================================== #
# satellites: fleet env wiring, flight-recorder claim release
# ====================================================================== #


class TestFleetWiring:
    def test_spawn_exports_durability_root(self, monkeypatch, tmp_path):
        from modin_tpu.fleet import coordinator as coord_mod

        captured = {}

        class _FakeProc:
            pid = 12345

        def fake_popen(cmd, env=None, **kwargs):
            captured["env"] = env
            return _FakeProc()

        monkeypatch.setattr(coord_mod.subprocess, "Popen", fake_popen)
        coord = coord_mod.Coordinator(
            replicas=1, durability_dir=str(tmp_path)
        )
        coord._control_port = 0
        coord._spawn(coord._replicas[0])
        env = captured["env"]
        assert env["MODIN_TPU_FLEET_DURABILITY_DIR"] == str(tmp_path)
        assert env["MODIN_TPU_INGEST"] == "1"

        # without a durability root the replica env must NOT carry one
        # (even when the coordinator's own environment does)
        monkeypatch.setenv("MODIN_TPU_FLEET_DURABILITY_DIR", "/stale")
        coord = coord_mod.Coordinator(replicas=1, durability_dir="")
        coord._control_port = 0
        coord._spawn(coord._replicas[0])
        assert "MODIN_TPU_FLEET_DURABILITY_DIR" not in captured["env"]


class TestFlightRecorderClaim:
    def test_partial_write_releases_claim(self, monkeypatch, tmp_path):
        """A dump whose WRITE dies must release the shared claim window:
        the next dump (of the real fault) goes through immediately
        instead of being rate-limited away."""
        import modin_tpu.observability as graftscope
        from modin_tpu.config import TraceDir, TraceEnabled
        from modin_tpu.observability import flight_recorder
        from modin_tpu.utils import atomic_io

        monkeypatch.setattr(
            flight_recorder, "MIN_DUMP_INTERVAL_S", 3600.0
        )
        real = atomic_io.atomic_write_json
        fails = {"n": 1}

        def flaky(path, obj, **kwargs):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError(5, "injected mid-write failure")
            return real(path, obj, **kwargs)

        monkeypatch.setattr(atomic_io, "atomic_write_json", flaky)
        with TraceDir.context(str(tmp_path)), TraceEnabled.context(True):
            flight_recorder.reset_for_tests()
            with graftscope.layer_span("TestDur.claim", "QUERY-COMPILER"):
                pass
            assert flight_recorder.dump_flight_record("dur_fault") is None
            assert not list(tmp_path.glob("*.trace.json")), (
                "a failed dump left a partial artifact"
            )
            # claim released: the retry is NOT rate-limited
            path = flight_recorder.dump_flight_record("dur_fault")
            assert path is not None and os.path.exists(path)
