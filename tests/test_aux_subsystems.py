"""Aux subsystems: DocModule re-sourcing, file-leak tracking, memory budget.

Reference counterparts: modin/tests/config/test_envvars.py (DocModule),
modin/config/envvars.py:893 (TrackFileLeaks), Memory-bounded spill.
"""

import sys
import types
import warnings

import numpy as np
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import Memory, TrackFileLeaks


@pytest.fixture
def docs_module():
    mod = types.ModuleType("_test_docs_module")

    class DataFrame:
        """Test-custom frame doc."""

        def sum(self):
            """Test-custom sum doc."""

    mod.DataFrame = DataFrame
    sys.modules["_test_docs_module"] = mod
    yield mod
    sys.modules.pop("_test_docs_module", None)


class TestDocModule:
    def test_docs_resourced_and_restorable(self, docs_module):
        from modin_tpu.config import DocModule

        pandas_frame_doc = pd.DataFrame.__doc__
        pandas_sum_doc = pd.DataFrame.sum.__doc__
        with DocModule.context("_test_docs_module"):
            assert pd.DataFrame.__doc__ == "Test-custom frame doc."
            assert pd.DataFrame.sum.__doc__ == "Test-custom sum doc."
            # no counterpart in the custom module -> pandas doc stays
            assert "Test-custom" not in (pd.DataFrame.mean.__doc__ or "")
            assert "Test-custom" not in (pd.Series.__doc__ or "")
        # leaving the context reverts to "pandas": originals restored
        assert pd.DataFrame.__doc__ == pandas_frame_doc
        assert pd.DataFrame.sum.__doc__ == pandas_sum_doc

    def test_hand_written_docs_never_clobbered(self, docs_module):
        import pandas

        from modin_tpu.config import DocModule
        from modin_tpu.utils import _inherit_docstrings

        @_inherit_docstrings(pandas.DataFrame)
        class MyFrame:
            def sum(self):
                """Hand-written sum doc."""

            def mean(self):
                pass  # doc inherited from pandas at decoration

        assert MyFrame.mean.__doc__ == pandas.DataFrame.mean.__doc__
        with DocModule.context("_test_docs_module"):
            # the custom module HAS a sum counterpart, but MyFrame.sum's doc
            # was hand-written (not written by inheritance) -> untouched
            assert MyFrame.sum.__doc__ == "Hand-written sum doc."
            assert MyFrame.__doc__ == "Test-custom frame doc."
        assert MyFrame.sum.__doc__ == "Hand-written sum doc."
        assert MyFrame.mean.__doc__ == pandas.DataFrame.mean.__doc__

    def test_missing_module_warns_and_keeps_docs(self):
        from modin_tpu.config import DocModule

        doc_before = pd.DataFrame.__doc__
        with pytest.warns(UserWarning, match="not importable"):
            with DocModule.context("_no_such_docs_module_"):
                assert pd.DataFrame.__doc__ == doc_before


class TestTrackFileLeaks:
    def test_leak_detected(self, tmp_path):
        from modin_tpu.utils.file_leaks import track_file_leaks

        p = tmp_path / "leak.txt"
        p.write_text("x")
        with TrackFileLeaks.context(True):
            with pytest.warns(ResourceWarning, match="leak.txt"):
                with track_file_leaks():
                    handle = open(p)  # noqa: SIM115 - leak on purpose
            handle.close()

    def test_clean_read_no_warning(self, tmp_path):
        csv = tmp_path / "clean.csv"
        csv.write_text("a,b\n1,2\n3,4\n")
        with TrackFileLeaks.context(True):
            with warnings.catch_warnings():
                warnings.simplefilter("error", ResourceWarning)
                df = pd.read_csv(csv)
        assert len(df) == 2

    def test_disabled_is_noop(self, tmp_path):
        from modin_tpu.utils.file_leaks import track_file_leaks

        p = tmp_path / "leak2.txt"
        p.write_text("x")
        with TrackFileLeaks.context(False):
            with warnings.catch_warnings():
                warnings.simplefilter("error", ResourceWarning)
                with track_file_leaks():
                    handle = open(p)  # noqa: SIM115
        handle.close()


@pytest.fixture(autouse=True)
def _require_device_columns(request):
    if "TestMemoryBudget" in request.node.nodeid:
        from modin_tpu.utils import get_current_execution

        if get_current_execution() != "TpuOnJax":
            pytest.skip("host-cache ledger exists only for device columns")


class TestMemoryBudget:
    def test_lru_eviction_under_budget(self):
        from modin_tpu.core.memory import host_cache_bytes, ledger

        base = host_cache_bytes()
        big = np.arange(200_000, dtype=np.int64)  # 1.6 MB
        df1 = pd.DataFrame({"a": big})
        df2 = pd.DataFrame({"b": big + 1})
        assert host_cache_bytes() >= base + 2 * big.nbytes
        col1 = df1._query_compiler._modin_frame._columns[0]
        col2 = df2._query_compiler._modin_frame._columns[0]
        # budget fits only one cache above the pre-existing load
        with Memory.context(base + int(1.5 * big.nbytes)):
            ledger.enforce()
        assert col1.host_cache is None  # oldest evicted
        assert col2.host_cache is not None
        # evicted column still reads exactly from device
        np.testing.assert_array_equal(col1.to_numpy(), big)

    def test_touch_refreshes_lru(self):
        from modin_tpu.core.memory import host_cache_bytes, ledger

        base = host_cache_bytes()
        big = np.arange(200_000, dtype=np.int64)
        df1 = pd.DataFrame({"a": big})
        df2 = pd.DataFrame({"b": big + 1})
        col1 = df1._query_compiler._modin_frame._columns[0]
        col2 = df2._query_compiler._modin_frame._columns[0]
        col1.to_numpy()  # touch: col1 becomes most-recently-used
        with Memory.context(base + int(1.5 * big.nbytes)):
            ledger.enforce()
        assert col1.host_cache is not None
        assert col2.host_cache is None

    def test_downcast_cache_never_evicted(self):
        from modin_tpu.config import Float64Policy
        from modin_tpu.core.memory import host_cache_bytes, ledger

        with Float64Policy.context("Downcast"):
            base = host_cache_bytes()
            values = np.linspace(0.0, 1.0, 200_000)  # f64, stored f32 on device
            df = pd.DataFrame({"a": values})
            col = df._query_compiler._modin_frame._columns[0]
            with Memory.context(max(base - 1, 0)):  # force over-budget
                ledger.enforce()
            # the cache is the only exact copy: must survive
            assert col.host_cache is not None
            np.testing.assert_array_equal(col.to_numpy(), values)

    def test_unset_budget_keeps_everything(self):
        from modin_tpu.core.memory import ledger

        df = pd.DataFrame({"a": np.arange(1000)})
        col = df._query_compiler._modin_frame._columns[0]
        ledger.enforce()
        assert col.host_cache is not None
