"""Indexing edge-case suite (ported shapes from modin/tests/pandas/dataframe/
test_indexing.py, 2,784 LoC): loc/iloc slices and fancy keys, boolean masks,
at/iat, setitem enlargement, MultiIndex, reindex, and alignment corners."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(51)
N = 60


@pytest.fixture
def dfs():
    data = {
        "a": _rng.normal(size=N),
        "b": _rng.integers(0, 100, N),
        "c": np.array([f"s{i % 9}" for i in range(N)]),
        "d": _rng.random(N) < 0.5,
    }
    return create_test_dfs(data)


@pytest.fixture
def labeled():
    data = {"x": np.arange(10.0), "y": np.arange(10) * 2}
    index = list("abcdefghij")
    return create_test_dfs(data, index=index)


LOC_KEYS = [
    3,
    slice(2, 7),
    slice(None, 5),
    slice(5, None),
    slice(None, None, 2),
    [1, 5, 9],
    [9, 1, 5],
]


@pytest.mark.parametrize("key", LOC_KEYS, ids=[str(k) for k in LOC_KEYS])
def test_loc_row_keys(dfs, key):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.loc[key])


ILOC_KEYS = [
    0,
    -1,
    slice(3, 12),
    slice(-5, None),
    slice(None, None, 3),
    slice(None, None, -1),
    [0, 2, 4],
    [-1, -3],
    np.array([5, 1, 3]),
]


@pytest.mark.parametrize("key", ILOC_KEYS, ids=[str(k) for k in ILOC_KEYS])
def test_iloc_row_keys(dfs, key):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.iloc[key])


@pytest.mark.parametrize(
    "cols", [["a"], ["b", "d"], slice("a", "c"), slice(None)], ids=str
)
def test_loc_column_keys(dfs, cols):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.loc[2:8, cols])


@pytest.mark.parametrize("cols", [0, [0, 2], slice(1, 3), [-1]], ids=str)
def test_iloc_column_keys(dfs, cols):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.iloc[2:8, cols])


def test_loc_boolean_array(dfs):
    md, pdf = dfs
    mask = np.asarray(_rng.random(N) < 0.4)
    df_equals(md.loc[mask], pdf.loc[mask])
    df_equals(md.loc[mask, ["a", "c"]], pdf.loc[mask, ["a", "c"]])


def test_loc_boolean_series_aligned(dfs):
    md, pdf = dfs
    df_equals(md.loc[md["d"]], pdf.loc[pdf["d"]])


def test_loc_with_string_labels(labeled):
    md, pdf = labeled
    df_equals(md.loc["c"], pdf.loc["c"])
    df_equals(md.loc["c":"g"], pdf.loc["c":"g"])
    df_equals(md.loc[["b", "e", "i"]], pdf.loc[["b", "e", "i"]])
    df_equals(md.loc["d", "x"], pdf.loc["d", "x"])


def test_loc_missing_label_raises(labeled):
    md, pdf = labeled
    eval_general(md, pdf, lambda df: df.loc["zz"])
    eval_general(md, pdf, lambda df: df.loc[["a", "zz"]])


def test_iloc_out_of_bounds_raises(dfs):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.iloc[N + 5])


def test_at_iat(labeled):
    md, pdf = labeled
    assert md.at["b", "y"] == pdf.at["b", "y"]
    assert md.iat[4, 0] == pdf.iat[4, 0]


def test_setitem_scalar_and_array(dfs):
    md, pdf = dfs
    md["e"], pdf["e"] = 7.5, 7.5
    df_equals(md, pdf)
    values = _rng.normal(size=N)
    md["f"], pdf["f"] = values, values
    df_equals(md, pdf)


def test_setitem_from_own_column(dfs):
    md, pdf = dfs
    md["g"] = md["a"] * 2 + md["b"]
    pdf["g"] = pdf["a"] * 2 + pdf["b"]
    df_equals(md, pdf)


def test_setitem_overwrite_with_dtype_change(dfs):
    md, pdf = dfs
    md["b"] = md["a"]
    pdf["b"] = pdf["a"]
    df_equals(md, pdf)


def test_loc_setitem_region(dfs):
    md, pdf = dfs

    def op(df):
        out = df.copy()
        out.loc[3:6, "a"] = 0.0
        return out

    eval_general(md, pdf, op)


def test_iloc_setitem_region(dfs):
    md, pdf = dfs

    def op(df):
        out = df.copy()
        out.iloc[2:5, 0] = -1.0
        return out

    eval_general(md, pdf, op)


def test_reindex(labeled):
    md, pdf = labeled
    df_equals(md.reindex(["a", "c", "zz"]), pdf.reindex(["a", "c", "zz"]))
    df_equals(
        md.reindex(columns=["y", "x", "missing"]),
        pdf.reindex(columns=["y", "x", "missing"]),
    )


def test_set_reset_index(dfs):
    md, pdf = dfs
    df_equals(md.set_index("b"), pdf.set_index("b"))
    df_equals(md.set_index(["b", "c"]), pdf.set_index(["b", "c"]))
    df_equals(md.set_index("b").reset_index(), pdf.set_index("b").reset_index())


def test_multiindex_loc():
    arrays = [["bar", "bar", "baz", "baz", "foo", "foo"], [1, 2, 1, 2, 1, 2]]
    idx = pandas.MultiIndex.from_arrays(arrays, names=("k1", "k2"))
    data = {"v": np.arange(6.0)}
    md = pd.DataFrame(data, index=idx)
    pdf = pandas.DataFrame(data, index=idx)
    df_equals(md.loc["bar"], pdf.loc["bar"])
    df_equals(md.loc[("baz", 2)], pdf.loc[("baz", 2)])
    df_equals(md.xs("foo"), pdf.xs("foo"))


def test_head_tail_edge_counts(dfs):
    md, pdf = dfs
    for k in (0, 1, -3, N, N + 10):
        df_equals(md.head(k), pdf.head(k))
        df_equals(md.tail(k), pdf.tail(k))


def test_take_axis_both(dfs):
    md, pdf = dfs
    df_equals(md.take([5, 0, -1]), pdf.take([5, 0, -1]))
    df_equals(md.take([2, 0], axis=1), pdf.take([2, 0], axis=1))


def test_filter_items_like_regex(dfs):
    md, pdf = dfs
    df_equals(md.filter(items=["a", "d"]), pdf.filter(items=["a", "d"]))
    df_equals(md.filter(like="b"), pdf.filter(like="b"))
    df_equals(md.filter(regex="^[ac]$"), pdf.filter(regex="^[ac]$"))


def test_series_indexing(dfs):
    md, pdf = dfs
    ms, ps = md["a"], pdf["a"]
    df_equals(ms.iloc[3:9], ps.iloc[3:9])
    df_equals(ms.loc[5], ps.loc[5])
    df_equals(ms[ms > 0], ps[ps > 0])
    df_equals(ms.head(7), ps.head(7))


def test_where_mask(dfs):
    md, pdf = dfs
    num_md, num_pd = md[["a", "b"]], pdf[["a", "b"]]
    eval_general(num_md, num_pd, lambda df: df.where(df > 0))
    eval_general(num_md, num_pd, lambda df: df.where(df > 0, -df))
    eval_general(num_md, num_pd, lambda df: df.mask(df > 0))


def test_pop_and_del(dfs):
    md, pdf = dfs
    got, want = md.pop("b"), pdf.pop("b")
    df_equals(got, want)
    df_equals(md, pdf)
    del md["c"]
    del pdf["c"]
    df_equals(md, pdf)


def test_getitem_columns_duplicate_selection(dfs):
    md, pdf = dfs
    df_equals(md[["a", "a"]], pdf[["a", "a"]])


def test_squeeze():
    md, pdf = create_test_dfs({"only": [1.5, 2.5, 3.5]})
    df_equals(md.squeeze(axis=1), pdf.squeeze(axis=1))
    md1, pdf1 = create_test_dfs({"only": [42.0]})
    assert md1.squeeze() == pdf1.squeeze()


class TestAdviceR4Indexing:
    """Regressions from the r4 advisor review (ADVICE.md)."""

    def test_loc_scalar_row_list_col_keeps_mi_column_levels(self):
        # md.loc[0, ["a"]] on 2-level columns: a LIST col key selects whole
        # level-0 entries; pandas keeps [('a','x'),('a','y')] — the
        # level-drop applies only to scalar/tuple keys
        cols = pandas.MultiIndex.from_product([["a", "b"], ["x", "y"]])
        vals = np.arange(8).reshape(2, 4)
        md = pd.DataFrame(vals, columns=cols)
        pdf = pandas.DataFrame(vals, columns=cols)
        df_equals(md.loc[0, ["a"]], pdf.loc[0, ["a"]])
        # scalar and tuple col keys still drop the looked-up levels
        df_equals(md.loc[0, "a"], pdf.loc[0, "a"])
        df_equals(md.loc[0, ("a", "x")], pdf.loc[0, ("a", "x")])

    def test_loc_missing_full_depth_tuple_raises_keyerror(self):
        # loc[('bar','one',99)] on a 3-level index: pandas raises KeyError,
        # not IndexingError('Too many indexers')
        mi = pandas.MultiIndex.from_tuples(
            [("bar", "one", 1), ("bar", "two", 2), ("foo", "one", 3)]
        )
        md = pd.DataFrame({"v": [1, 2, 3]}, index=mi)
        pdf = pandas.DataFrame({"v": [1, 2, 3]}, index=mi)
        eval_general(md, pdf, lambda df: df.loc[("bar", "one", 99)])
        # the full-depth hit still resolves
        df_equals(md.loc[("bar", "one", 1)], pdf.loc[("bar", "one", 1)])
        # and 4 indexers on a 3-level frame still over-indexes both sides
        eval_general(md, pdf, lambda df: df.loc[("bar", "one", 1, 7)])
