"""Differential tests for modin_tpu.numpy (modeled on modin/tests/numpy/)."""

import numpy
import numpy as np
import pytest

import modin_tpu.numpy as mnp
from tests.utils import df_equals

_rng = numpy.random.default_rng(33)
VEC = _rng.uniform(-10, 10, 100)
MAT = _rng.uniform(-10, 10, (40, 5))


def arr_equals(modin_res, numpy_res, rtol=1e-12):
    modin_np = numpy.asarray(modin_res)
    numpy.testing.assert_allclose(modin_np, numpy_res, rtol=rtol)


def test_construction_shapes():
    a = mnp.array(VEC)
    assert a.shape == VEC.shape and a.ndim == 1
    m = mnp.array(MAT)
    assert m.shape == MAT.shape and m.ndim == 2
    assert m.size == MAT.size
    arr_equals(a, VEC)
    arr_equals(m, MAT)


@pytest.mark.parametrize("op", ["__add__", "__sub__", "__mul__", "__truediv__", "__pow__"])
def test_arith_scalar(op):
    a = mnp.array(VEC)
    arr_equals(getattr(a, op)(2.5), getattr(VEC, op)(2.5))


def test_arith_array():
    a, b = mnp.array(VEC), mnp.array(VEC * 2)
    arr_equals(a + b, VEC + VEC * 2)
    arr_equals(a * b, VEC * (VEC * 2))


def test_comparisons_and_logic():
    a = mnp.array(VEC)
    arr_equals(numpy.asarray(a > 0), VEC > 0)
    arr_equals(numpy.asarray(mnp.logical_and(a > 0, a < 5)), (VEC > 0) & (VEC < 5))


@pytest.mark.parametrize("fn", ["sqrt", "exp", "log", "sin", "cos", "tanh", "floor", "ceil"])
def test_unary_math(fn):
    data = numpy.abs(VEC) + 1.0
    a = mnp.array(data)
    arr_equals(getattr(mnp, fn)(a), getattr(numpy, fn)(data), rtol=1e-12)


@pytest.mark.parametrize("red", ["sum", "mean", "prod", "amin", "amax"])
def test_reductions_vec(red):
    a = mnp.array(numpy.abs(VEC) * 0.1)
    got = getattr(mnp, red)(a)
    want = getattr(numpy, red)(numpy.abs(VEC) * 0.1)
    numpy.testing.assert_allclose(float(got), want, rtol=1e-12)


@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reductions_mat(axis):
    m = mnp.array(MAT)
    got = mnp.sum(m, axis=axis)
    want = numpy.sum(MAT, axis=axis)
    if axis is None:
        numpy.testing.assert_allclose(float(got), want, rtol=1e-12)
    else:
        arr_equals(got, want)


def test_transpose_and_T():
    m = mnp.array(MAT)
    arr_equals(m.T, MAT.T)


def test_creation_helpers():
    arr_equals(mnp.zeros(7), numpy.zeros(7))
    arr_equals(mnp.ones((3, 2)), numpy.ones((3, 2)))
    arr_equals(mnp.arange(10), numpy.arange(10))


def test_astype():
    a = mnp.array(VEC)
    assert numpy.asarray(a.astype("float32")).dtype == numpy.float32


def test_numpy_passthrough():
    assert mnp.pi == numpy.pi
    assert mnp.float64 is numpy.float64


def test_interop_with_dataframe():
    import modin_tpu.pandas as pd

    df = pd.DataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0]})
    m = mnp.array(df)
    assert m.shape == (2, 2)
    arr_equals(m.sum(axis=0), numpy.array([3.0, 7.0]))


class TestExpandedSurface:
    def test_predicates(self):
        a = mnp.array([1.0, np.nan, -np.inf, 4.0])
        np.testing.assert_array_equal(np.asarray(mnp.isnan(a)), [False, True, False, False])
        np.testing.assert_array_equal(np.asarray(mnp.isinf(a)), [False, False, True, False])
        np.testing.assert_array_equal(np.asarray(mnp.isfinite(a)), [True, False, False, True])
        np.testing.assert_array_equal(
            np.asarray(mnp.logical_not(mnp.array([True, False]))), [False, True]
        )
        assert mnp.isscalar(3.0) and not mnp.isscalar(a)

    def test_shaping(self):
        a = mnp.arange(6)
        assert mnp.shape(a) == (6,)
        assert mnp.ravel(a).tolist() == list(range(6))
        parts = mnp.split(a, 3)
        assert [p.tolist() for p in parts] == [[0, 1], [2, 3], [4, 5]]
        assert mnp.hstack([mnp.ones(2), mnp.zeros(2)]).tolist() == [1, 1, 0, 0]
        assert mnp.append(mnp.ones(2), [5.0]).tolist() == [1.0, 1.0, 5.0]

    def test_arg_reductions(self):
        assert int(mnp.argmax(mnp.array([1, 9, 2]))) == 1
        assert int(mnp.argmin(mnp.array([1, 9, -2]))) == 2

    def test_linalg_norm(self):
        assert float(mnp.linalg.norm(mnp.array([3.0, 4.0]))) == 5.0

    def test_constants_and_aliases(self):
        assert mnp.pi == np.pi and mnp.e == np.e and np.isnan(mnp.nan)
        np.testing.assert_array_equal(
            np.asarray(mnp.abs(mnp.array([-1.0, 2.0]))), [1.0, 2.0]
        )
        assert float(mnp.max(mnp.array([1.0, 5.0]))) == 5.0
        assert float(mnp.min(mnp.array([1.0, 5.0]))) == 1.0

    def test_tri(self):
        np.testing.assert_array_equal(np.asarray(mnp.tri(3)), np.tri(3))

    def test_float_power(self):
        np.testing.assert_allclose(
            np.asarray(mnp.float_power(mnp.array([2.0, 3.0]), 2.0)), [4.0, 9.0]
        )


class TestArrayMethodSurface:
    """ref arr.py parity: named methods, ufunc + NEP-18 protocols."""

    def test_named_binary_and_unary(self):
        a = mnp.array([1.0, 4.0, 9.0])
        b = mnp.array([1.0, 2.0, 3.0])
        assert a.multiply(b).tolist() == [1.0, 8.0, 27.0]
        assert a.subtract(b).tolist() == [0.0, 2.0, 6.0]
        assert a.divide(b).tolist() == [1.0, 2.0, 3.0]
        assert a.power(b).tolist() == [1.0, 16.0, 729.0]
        assert a.floor_divide(b).tolist() == [1.0, 2.0, 3.0]
        assert a.remainder(b).tolist() == [0.0, 0.0, 0.0]
        assert a.sqrt().tolist() == [1.0, 2.0, 3.0]
        np.testing.assert_allclose(a.exp().tolist(), np.exp([1.0, 4.0, 9.0]))
        np.testing.assert_allclose(a.tanh().tolist(), np.tanh([1.0, 4.0, 9.0]))

    def test_ufunc_protocol(self):
        a = mnp.array([1.0, 4.0, 9.0])
        assert np.add(a, 1.0).tolist() == [2.0, 5.0, 10.0]
        assert np.subtract(10.0, a).tolist() == [9.0, 6.0, 1.0]
        assert np.less(4.0, a).tolist() == [False, False, True]
        assert np.sqrt(a).tolist() == [1.0, 2.0, 3.0]
        assert isinstance(np.add(a, a), mnp.array)

    def test_array_function_protocol(self):
        a, b = mnp.array([1.0]), mnp.array([2.0])
        r = np.concatenate([a, b])
        assert isinstance(r, mnp.array) and r.tolist() == [1.0, 2.0]
        assert np.stack([a, b]).shape == (2, 1)

    def test_argmax_argmin(self):
        a = mnp.array([3.0, 1.0, 7.0])
        assert a.argmax() == 2 and a.argmin() == 1
        m = mnp.array([[1.0, 9.0], [5.0, 2.0]])
        assert m.argmax(axis=0).tolist() == [1, 0]
        assert m.argmax() == 1

    def test_append_hstack_split(self):
        a = mnp.array([1.0, 2.0])
        assert a.append(mnp.array([3.0])).tolist() == [1.0, 2.0, 3.0]
        assert a.hstack([[3.0], [4.0]]).tolist() == [1.0, 2.0, 3.0, 4.0]
        assert [p.tolist() for p in a.split(2)] == [[1.0], [2.0]]

    def test_where_setitem_matmul(self):
        cond = mnp.array([True, False, True])
        a, b = mnp.array([1.0, 4.0, 9.0]), mnp.array([1.0, 2.0, 3.0])
        assert cond.where(a, b).tolist() == [1.0, 2.0, 9.0]
        m = mnp.array([[1.0, 2.0], [3.0, 4.0]])
        assert (m @ m).tolist() == [[7.0, 10.0], [15.0, 22.0]]
        a[1] = 42.0
        assert a.tolist() == [1.0, 42.0, 9.0]
