"""Differential tests for modin_tpu.polars (vs pandas ground truth;
modeled on modin/tests/polars/)."""

import numpy as np
import pandas
import pytest

import modin_tpu.polars as pl

_rng = np.random.default_rng(17)
N = 300

DATA = {
    "grp": _rng.integers(0, 5, N),
    "val": _rng.uniform(-10, 10, N),
    "qty": _rng.integers(1, 100, N),
}
PDF = pandas.DataFrame(DATA)


@pytest.fixture
def df():
    return pl.DataFrame(DATA)


def eq(pl_df, pandas_df):
    pandas.testing.assert_frame_equal(
        pl_df.to_pandas(), pandas_df.reset_index(drop=True), check_dtype=False
    )


def test_shape_schema(df):
    assert df.shape == PDF.shape
    assert df.columns == list(PDF.columns)
    assert df.height == len(PDF) and df.width == PDF.shape[1]


def test_select_exprs(df):
    eq(df.select("val"), PDF[["val"]])
    eq(
        df.select((pl.col("val") * 2).alias("v2")),
        (PDF[["val"]] * 2).rename(columns={"val": "v2"}),
    )
    out = df.select(pl.col("val").sum().alias("total"))
    np.testing.assert_allclose(out.item(), PDF["val"].sum())


def test_with_columns_filter_sort(df):
    got = (
        df.with_columns((pl.col("val") * pl.col("qty")).alias("rev"))
        .filter(pl.col("rev") > 0)
        .sort("rev", descending=True)
    )
    want = PDF.assign(rev=PDF.val * PDF.qty)
    want = want[want.rev > 0].sort_values("rev", ascending=False, kind="stable")
    eq(got, want)


def test_group_by(df):
    eq(df.group_by("grp").sum(), PDF.groupby("grp").sum().reset_index())
    got = df.group_by("grp").agg(
        pl.col("val").mean().alias("val"), pl.col("qty").sum().alias("qty")
    )
    want = PDF.groupby("grp").agg(val=("val", "mean"), qty=("qty", "sum")).reset_index()
    eq(got, want)
    eq(df.group_by("grp").len(), PDF.groupby("grp").size().to_frame("len").reset_index())


def test_join(df):
    other = pl.DataFrame({"grp": [0, 1, 2], "label": ["a", "b", "c"]})
    got = df.join(other, on="grp", how="inner").sort(["grp", "val"])
    want = PDF.merge(
        pandas.DataFrame({"grp": [0, 1, 2], "label": ["a", "b", "c"]}),
        on="grp", how="inner",
    ).sort_values(["grp", "val"], kind="stable")
    eq(got, want)


def test_head_slice_unique(df):
    eq(df.head(7), PDF.head(7))
    eq(df.slice(10, 5), PDF.iloc[10:15])
    small = pl.DataFrame({"a": [1, 1, 2], "b": [3, 3, 4]})
    eq(small.unique(), pandas.DataFrame({"a": [1, 2], "b": [3, 4]}))


def test_vstack_hstack(df):
    eq(df.vstack(df), pandas.concat([PDF, PDF], ignore_index=True))


def test_series_ops(df):
    s = df["val"]
    np.testing.assert_allclose(s.sum(), PDF.val.sum())
    np.testing.assert_allclose((s * 2).sum(), (PDF.val * 2).sum())
    assert s.name == "val"


def test_lazyframe(df):
    lf = df.lazy().filter(pl.col("val") > 0).with_columns(
        (pl.col("val") * 2).alias("v2")
    ).sort("v2")
    got = lf.collect()
    want = PDF[PDF.val > 0].assign(v2=lambda d: d.val * 2).sort_values("v2", kind="stable")
    eq(got, want)
    # group_by on the lazy chain
    got2 = df.lazy().group_by("grp").agg(pl.col("val").sum().alias("val")).collect()
    want2 = PDF.groupby("grp")["val"].sum().reset_index()
    eq(got2, want2)


def test_read_csv(tmp_path):
    PDF.to_csv(tmp_path / "x.csv", index=False)
    got = pl.read_csv(str(tmp_path / "x.csv"))
    eq(got, PDF)


def test_fill_drop_nulls():
    df = pl.DataFrame({"a": [1.0, None, 3.0]})
    eq(df.fill_null(0.0), pandas.DataFrame({"a": [1.0, 0.0, 3.0]}))
    eq(df.drop_nulls(), pandas.DataFrame({"a": [1.0, 3.0]}))


def test_group_by_agg_alias_and_computed(df):
    # regression: aliased and computed aggregation expressions
    got = df.group_by("grp").agg(
        pl.col("val").sum().alias("total"),
        (pl.col("val") * 2).mean().alias("dbl_mean"),
    )
    want = (
        PDF.assign(_d=PDF.val * 2)
        .groupby("grp")
        .agg(total=("val", "sum"), dbl_mean=("_d", "mean"))
        .reset_index()
    )
    eq(got, want)


def test_select_broadcast_scalar(df):
    # regression: polars broadcasts aggregates alongside full columns
    got = df.select(pl.col("val"), pl.col("qty").sum().alias("qty_total"))
    want = PDF[["val"]].assign(qty_total=PDF.qty.sum())
    eq(got, want)


def test_unique_keep_none():
    small = pl.DataFrame({"a": [1, 1, 2, 3, 3, 4]})
    got = small.unique(keep="none").sort("a")
    eq(got, pandas.DataFrame({"a": [2, 4]}))


class TestExpandedVerbs:
    def test_vertical_aggs(self, df):
        eq(df.median(), PDF.median().to_frame().T)
        eq(df.product(), PDF.prod().to_frame().T)
        eq(df.n_unique(), PDF.nunique().to_frame().T)
        eq(df.null_count(), PDF.isna().sum().to_frame().T)
        eq(df.std(), PDF.std().to_frame().T)
        eq(df.var(ddof=0), PDF.var(ddof=0).to_frame().T)

    def test_horizontal_aggs(self, df):
        np.testing.assert_allclose(
            df.sum_horizontal().to_numpy(), PDF.sum(axis=1).to_numpy()
        )
        np.testing.assert_allclose(
            df.max_horizontal().to_numpy(), PDF.max(axis=1).to_numpy()
        )

    def test_unpivot_pivot(self, df):
        eq(
            df.unpivot(on=["val", "qty"], index="grp"),
            PDF.melt(id_vars="grp", value_vars=["val", "qty"]),
        )
        # polars: unnamed index role takes the remaining columns (qty here)
        got = df.pivot(on="grp", index="qty", values="val", aggregate_function="mean")
        want = (
            PDF.pivot_table(index="qty", columns="grp", values="val", aggfunc="mean")
            .reset_index()
        )
        got_pdf = got.to_pandas()
        assert "qty" in got_pdf.columns
        for grp_val in [c for c in want.columns if c != "qty"]:
            np.testing.assert_allclose(
                got_pdf.sort_values("qty")[grp_val].to_numpy(),
                want.sort_values("qty")[grp_val].to_numpy(),
                equal_nan=True,
            )

    def test_reverse_and_rows(self, df):
        eq(df.reverse(), PDF.iloc[::-1])
        assert df.row(3) == tuple(PDF.iloc[3])
        assert df.rows()[:2] == [tuple(r) for r in PDF.head(2).itertuples(index=False)]
        assert df.to_dicts()[0] == dict(PDF.iloc[0])

    def test_to_dict_series(self, df):
        d = df.to_dict()
        assert set(d) == set(PDF.columns)
        np.testing.assert_allclose(d["val"].to_numpy(), PDF["val"].to_numpy())
        assert df.to_series(1).name == "val"

    def test_column_surgery(self, df):
        s = pl.Series("extra", np.arange(N))
        out = df.insert_column(1, s)
        assert out.columns == ["grp", "extra", "val", "qty"]
        rep = df.replace_column(0, pl.Series("g2", np.arange(N)))
        assert rep.columns[0] == "g2"
        d2 = pl.DataFrame(DATA)
        dropped = d2.drop_in_place("qty")
        assert dropped.name == "qty" and d2.columns == ["grp", "val"]
        assert df.get_column_index("qty") == 2

    def test_partition_by(self, df):
        parts = df.partition_by("grp")
        assert sum(len(p) for p in parts) == N
        as_dict = df.partition_by("grp", as_dict=True)
        assert len(as_dict) == PDF["grp"].nunique()

    def test_misc(self, df):
        assert df.estimated_size("kb") > 0
        assert df.pipe(lambda d: d.height) == N
        acc = df.select(["val", "qty"]).fold(lambda a, b: a + b)
        np.testing.assert_allclose(
            acc.to_numpy(), (PDF["val"] + PDF["qty"]).to_numpy()
        )
        assert df.clear().height == 0
        eq(df.corr(), PDF.corr())


class TestSeriesSurface:
    """ref modin/polars/series.py parity: the expanded verb surface."""

    def test_math_and_predicates(self):
        s = pl.Series("v", [3.0, 1.0, None, 7.0])
        assert s.null_count() == 1 and s.has_nulls()
        assert s.n_unique() == 4
        assert s.fill_null(0.0).to_list() == [3.0, 1.0, 0.0, 7.0]
        assert s.is_between(1.0, 4.0).to_list() == [True, True, False, False]
        assert pl.Series("x", [4.0]).sqrt().to_list() == [2.0]
        assert pl.Series("x", [1, -2]).abs().to_list() == [1, 2]
        assert pl.Series("x", [1.0, 2.0]).dot(pl.Series("y", [3.0, 4.0])) == 11.0

    def test_order_and_positions(self):
        s = pl.Series("v", [3.0, 1.0, 7.0])
        assert s.arg_max() == 2 and s.arg_min() == 1
        assert s.arg_sort().to_list() == [1, 0, 2]
        assert s.reverse().to_list() == [7.0, 1.0, 3.0]
        assert pl.Series("x", [1, 2, 3]).is_sorted()
        assert pl.Series("b", [False, True, False]).arg_true().to_list() == [1]

    def test_cumulative_and_rolling(self):
        s = pl.Series("v", [1.0, 2.0, 3.0])
        assert s.cum_sum().to_list() == [1.0, 3.0, 6.0]
        assert s.cum_sum(reverse=True).to_list() == [6.0, 5.0, 3.0]
        assert s.rolling_sum(2).to_list()[1:] == [3.0, 5.0]
        assert s.diff().to_list()[1:] == [1.0, 1.0]

    def test_runs_and_counts(self):
        assert pl.Series("x", [1, 1, 2, 2, 2, 1]).rle_id().to_list() == [0, 0, 1, 1, 1, 2]
        vc = pl.Series("x", [1, 1, 2]).value_counts().to_pandas()
        assert vc["count"].tolist() == [2, 1]
        rle = pl.Series("x", [5, 5, 6]).rle().to_pandas()
        assert rle["len"].tolist() == [2, 1] and rle["value"].tolist() == [5, 6]

    def test_remap_and_set_ops(self):
        s = pl.Series("x", [1, 2, 3])
        assert s.replace({1: 10}).to_list() == [10, 2, 3]
        assert s.replace_strict({1: 10}, default=0).to_list() == [10, 0, 0]
        assert s.scatter([0], [9]).to_list() == [9, 2, 3]
        assert s.is_in([2, 3]).to_list() == [False, True, True]
        mask = pl.Series("m", [True, False, True])
        other = pl.Series("o", [7, 8, 9])
        assert s.zip_with(mask, other).to_list() == [1, 8, 3]

    def test_namespaces(self):
        s = pl.Series("t", ["ab", "CD"])
        assert s.str.to_uppercase().to_list() == ["AB", "CD"]
        assert s.str.contains("a").to_list() == [True, False]
        assert s.str.len_chars().to_list() == [2, 2]
        d = pl.Series("d", np.array(["2024-01-01", "2024-03-05"], dtype="datetime64[ns]"))
        assert d.dt.year().to_list() == [2024, 2024]
        assert d.dt.weekday().to_list() == [1, 2]  # polars: Monday=1

    def test_append_extend_implode(self):
        s = pl.Series("x", [1, 2])
        assert s.append(pl.Series("y", [3])).to_list() == [1, 2, 3]
        assert s.extend_constant(0, 2).to_list() == [1, 2, 0, 0]
        assert s.implode().to_list() == [[1, 2]]


class TestDataFrameSurface:
    def test_row_index_and_melt(self):
        df = pl.DataFrame({"k": [1, 1, 2], "v": [1.0, 3.0, 5.0]})
        assert df.with_row_index().to_pandas().columns.tolist() == ["index", "k", "v"]
        assert df.melt(id_vars="k").to_pandas().shape == (3, 3)

    def test_groupby_expansion(self):
        df = pl.DataFrame({"k": [1, 1, 2], "v": [1.0, 3.0, 5.0]})
        med = df.group_by("k").median().to_pandas()
        assert med["v"].tolist() == [2.0, 5.0]
        assert df.group_by("k").n_unique().to_pandas()["v"].tolist() == [2, 1]
        assert df.group_by("k").all().to_pandas()["v"].tolist() == [[1.0, 3.0], [5.0]]

    def test_join_asof_and_merge_sorted(self):
        left = pl.DataFrame({"t": [1.0, 2.0, 3.0]})
        right = pl.DataFrame({"t": [1.5, 2.5], "lbl": ["a", "b"]})
        asof = left.join_asof(right, on="t").to_pandas()
        assert asof["lbl"].tolist()[1:] == ["a", "b"]
        ms = pl.DataFrame({"t": [1, 3]}).merge_sorted(pl.DataFrame({"t": [2]}), "t")
        assert ms.to_pandas()["t"].tolist() == [1, 2, 3]

    def test_serialize_sql_update_unnest(self):
        df = pl.DataFrame({"a": [1, 2], "b": [3.0, 4.0]})
        assert pl.DataFrame.deserialize(df.serialize()).to_pandas().equals(df.to_pandas())
        assert df.sql("SELECT SUM(a) AS s FROM self").to_pandas()["s"].tolist() == [3]
        upd = df.update(pl.DataFrame({"b": [np.nan, 9.0]})).to_pandas()
        assert upd["b"].tolist() == [3.0, 9.0]
        dfn = pl.DataFrame({"s": [{"x": 1}, {"x": 2}], "z": [0.5, 0.7]})
        assert dfn.unnest("s").to_pandas().columns.tolist() == ["x", "z"]

    def test_rows_by_key_and_slices(self):
        df = pl.DataFrame({"k": [1, 1, 2], "v": [1.0, 3.0, 5.0]})
        assert df.rows_by_key("k") == {1: [(1.0,), (3.0,)], 2: [(5.0,)]}
        assert [len(c.to_pandas()) for c in df.iter_slices(2)] == [2, 1]
