"""Series differential tests (modeled on modin/tests/pandas/test_series.py,
the reference's largest suite)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_series, df_equals

_rng = np.random.default_rng(55)

SERIES_DATA = {
    "int": _rng.integers(-100, 100, 64),
    "float_nan": np.where(_rng.random(64) < 0.2, np.nan, _rng.uniform(-5, 5, 64)),
    "bool": _rng.random(64) < 0.5,
    "str": _rng.choice(["alpha", "Beta", "g_amma", ""], 64),
}


@pytest.fixture(params=list(SERIES_DATA), ids=list(SERIES_DATA))
def kind(request):
    return request.param


@pytest.fixture
def pair(kind):
    return create_test_series(SERIES_DATA[kind], name="s")


class TestSeriesCore:
    def test_construction(self, pair):
        ms, ps = pair
        df_equals(ms, ps)
        assert ms.name == ps.name
        assert ms.dtype == ps.dtype
        assert ms.shape == ps.shape

    def test_repr(self, pair):
        ms, ps = pair
        assert repr(ms) == repr(ps)

    def test_rename_and_name(self, pair):
        ms, ps = pair
        df_equals(ms.rename("other"), ps.rename("other"))
        ms2 = ms.copy()
        ms2.name = "zzz"
        ps2 = ps.copy()
        ps2.name = "zzz"
        df_equals(ms2, ps2)

    def test_head_tail_take(self, pair):
        ms, ps = pair
        df_equals(ms.head(3), ps.head(3))
        df_equals(ms.tail(3), ps.tail(3))
        df_equals(ms.take([0, 5, 9]), ps.take([0, 5, 9]))

    def test_getitem(self, pair):
        ms, ps = pair
        df_equals(ms[3:9], ps[3:9])
        df_equals(ms.iloc[[1, 2, 5]], ps.iloc[[1, 2, 5]])
        df_equals(ms.loc[4], ps.loc[4])


class TestSeriesNumeric:
    @pytest.fixture
    def num(self):
        return create_test_series(SERIES_DATA["float_nan"], name="x")

    @pytest.mark.parametrize("op", ["sum", "mean", "min", "max", "std", "var",
                                     "median", "count", "prod", "skew", "kurt", "sem"])
    def test_reductions(self, num, op):
        ms, ps = num
        got, want = getattr(ms, op)(), getattr(ps, op)()
        np.testing.assert_allclose(got, want, rtol=1e-12, equal_nan=True)

    def test_arith(self, num):
        ms, ps = num
        df_equals(ms * 2 + 1, ps * 2 + 1)
        df_equals(ms / ms, ps / ps)
        df_equals(ms ** 2, ps ** 2)
        df_equals(-ms, -ps)
        df_equals(ms.abs(), ps.abs())

    def test_comparisons_and_filtering(self, num):
        ms, ps = num
        df_equals(ms[ms > 0], ps[ps > 0])
        df_equals(ms.between(-1, 1), ps.between(-1, 1))
        df_equals(ms.clip(-1, 1), ps.clip(-1, 1))

    def test_cumulative(self, num):
        ms, ps = num
        df_equals(ms.cumsum(), ps.cumsum())
        df_equals(ms.cummax(), ps.cummax())

    def test_sort_and_rank(self, num):
        ms, ps = num
        df_equals(ms.sort_values(kind="stable"), ps.sort_values(kind="stable"))
        df_equals(ms.rank(), ps.rank())

    def test_fill_missing(self, num):
        ms, ps = num
        df_equals(ms.fillna(0.0), ps.fillna(0.0))
        df_equals(ms.dropna(), ps.dropna())
        df_equals(ms.isna(), ps.isna())
        df_equals(ms.ffill(), ps.ffill())

    def test_unique_nunique(self, num):
        ms, ps = num
        np.testing.assert_array_equal(np.sort(ms.unique()), np.sort(ps.unique()))
        assert ms.nunique() == ps.nunique()

    def test_idxmin_idxmax(self, num):
        ms, ps = num
        assert ms.idxmin() == ps.idxmin()
        assert ms.idxmax() == ps.idxmax()

    def test_round_astype(self, num):
        ms, ps = num
        df_equals(ms.round(2), ps.round(2))
        df_equals(ms.astype("float32"), ps.astype("float32"))

    def test_shift_diff(self, num):
        ms, ps = num
        df_equals(ms.shift(1), ps.shift(1))
        df_equals(ms.diff(), ps.diff())

    def test_rolling(self, num):
        ms, ps = num
        df_equals(ms.rolling(4).sum(), ps.rolling(4).sum())
        df_equals(ms.rolling(4).mean(), ps.rolling(4).mean())


class TestSeriesString:
    @pytest.fixture
    def strs(self):
        return create_test_series(SERIES_DATA["str"], name="t")

    @pytest.mark.parametrize("op", ["upper", "lower", "len", "title", "strip", "capitalize"])
    def test_str_unary(self, strs, op):
        ms, ps = strs
        df_equals(getattr(ms.str, op)(), getattr(ps.str, op)())

    def test_str_contains_startswith(self, strs):
        ms, ps = strs
        df_equals(ms.str.contains("a"), ps.str.contains("a"))
        df_equals(ms.str.startswith("B"), ps.str.startswith("B"))
        df_equals(ms.str.replace("a", "@"), ps.str.replace("a", "@"))
        df_equals(ms.str.split("_"), ps.str.split("_"))

    def test_value_counts_str(self, strs):
        ms, ps = strs
        df_equals(ms.value_counts(), ps.value_counts())

    def test_str_getitem(self, strs):
        ms, ps = strs
        df_equals(ms.str[0:2], ps.str[0:2])


class TestSeriesDatetime:
    @pytest.fixture
    def dt(self):
        base = pandas.to_datetime("2023-05-01 10:00:00")
        vals = base + pandas.to_timedelta(_rng.integers(0, 10**6, 40), unit="s")
        return create_test_series(vals, name="ts")

    @pytest.mark.parametrize("prop", ["year", "month", "day", "hour", "dayofweek", "quarter"])
    def test_dt_props(self, dt, prop):
        ms, ps = dt
        df_equals(getattr(ms.dt, prop), getattr(ps.dt, prop))

    def test_dt_methods(self, dt):
        ms, ps = dt
        df_equals(ms.dt.floor("h"), ps.dt.floor("h"))
        df_equals(ms.dt.day_name(), ps.dt.day_name())

    def test_dt_arithmetic(self, dt):
        ms, ps = dt
        df_equals(ms.min(), ps.min())
        df_equals(ms.max(), ps.max())


class TestSeriesMisc:
    def test_map_apply(self):
        ms, ps = create_test_series([1, 2, 3], name="m")
        df_equals(ms.map({1: "a", 2: "b", 3: "c"}), ps.map({1: "a", 2: "b", 3: "c"}))
        df_equals(ms.apply(lambda x: x * 10), ps.apply(lambda x: x * 10))

    def test_isin(self):
        ms, ps = create_test_series([1, 2, 3, 4], name="m")
        df_equals(ms.isin([2, 4]), ps.isin([2, 4]))

    def test_concat_series(self):
        ms, ps = create_test_series([1, 2], name="m")
        df_equals(pd.concat([ms, ms]), pandas.concat([ps, ps]))
        df_equals(
            pd.concat([ms, ms], axis=1), pandas.concat([ps, ps], axis=1)
        )

    def test_to_frame_roundtrip(self):
        ms, ps = create_test_series([1.5, 2.5], name="m")
        df_equals(ms.to_frame(), ps.to_frame())
        df_equals(ms.to_frame("renamed"), ps.to_frame("renamed"))

    def test_where_mask(self):
        ms, ps = create_test_series([1.0, -2.0, 3.0], name="m")
        df_equals(ms.where(ms > 0), ps.where(ps > 0))
        df_equals(ms.mask(ms > 0, 0.0), ps.mask(ps > 0, 0.0))

    def test_index_alignment_binary(self):
        ms1, ps1 = create_test_series([1, 2, 3], name="a")
        ms2 = pd.Series([10, 20, 30], index=[2, 1, 0])
        ps2 = pandas.Series([10, 20, 30], index=[2, 1, 0])
        df_equals(ms1 + ms2, ps1 + ps2)

    def test_string_cat_with_plus(self):
        ms, ps = create_test_series(["a", "b"], name="s")
        df_equals(ms + "_suffix", ps + "_suffix")
