"""Kaggle-notebook stress pipelines as differential tests.

The reference runs 16 real Kaggle notebooks end-to-end as its stress suite
(stress_tests/test_kaggle_ipynb.py over stress_tests/kaggle/kaggle*.py).
These are the same pipelines re-derived on synthetic data — plotting cells
skipped, keras cells replaced with the sklearn models the notebooks also
use — each run twice (modin_tpu vs pandas) and compared on their final
artifacts.  They deliberately stress the mixed-dtype fallback seams:
string columns, get_dummies, .loc column slices, apply over columns,
sklearn interop via __array__, and to_csv round-trips.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as mpd
from tests.utils import df_equals

IMPLS = [mpd, pandas]


def _both(fn, *args):
    """Run a pipeline under both implementations; return (modin, pandas)."""
    out = []
    for impl in IMPLS:
        out.append(fn(impl, *args))
    return out


def _to_host(obj):
    return obj._to_pandas() if hasattr(obj, "_to_pandas") else obj


@pytest.fixture
def iris_csv(tmp_path):
    rng = np.random.default_rng(0)
    n = 600
    species = rng.choice(["setosa", "versicolor", "virginica"], n)
    df = pandas.DataFrame(
        {
            "Id": np.arange(1, n + 1),
            "SepalLengthCm": rng.normal(5.8, 0.8, n).round(1),
            "SepalWidthCm": rng.normal(3.0, 0.4, n).round(1),
            "PetalLengthCm": rng.normal(3.7, 1.7, n).round(1),
            "PetalWidthCm": rng.normal(1.2, 0.7, n).round(1),
            "Species": species,
        }
    )
    p = tmp_path / "Iris.csv"
    df.to_csv(p, index=False)
    return str(p)


def test_kaggle13_iris(iris_csv):
    """kaggle13: read, value_counts, per-species boxplot data (groupby
    describe), drop."""

    def pipeline(impl, path):
        iris = impl.read_csv(path)
        head = iris.head()
        counts = iris["Species"].value_counts()
        by_species = iris.drop("Id", axis=1).groupby("Species").describe()
        return head, counts, by_species

    (mh, mc, mg), (ph, pc, pg) = _both(pipeline, iris_csv)
    df_equals(mh, ph)
    df_equals(mc, pc)
    df_equals(mg, pg)


@pytest.fixture
def house_csvs(tmp_path):
    rng = np.random.default_rng(1)

    def make(n, with_price):
        df = pandas.DataFrame(
            {
                "Id": np.arange(1, n + 1),
                "LotArea": rng.integers(1_000, 20_000, n),
                "OverallQual": rng.integers(1, 11, n),
                "YearBuilt": rng.integers(1900, 2010, n),
                "TotRmsAbvGrd": rng.integers(2, 12, n),
            }
        )
        if with_price:
            df["SalePrice"] = (
                df["LotArea"] * 3
                + df["OverallQual"] * 20_000
                + rng.normal(0, 5_000, n).astype(int)
            )
        return df

    train_p, test_p = tmp_path / "train.csv", tmp_path / "test.csv"
    make(800, True).to_csv(train_p, index=False)
    make(200, False).to_csv(test_p, index=False)
    return str(train_p), str(test_p), tmp_path


def test_kaggle8_house_prices_random_forest(house_csvs):
    """kaggle8: csv -> column selection -> sklearn RandomForest ->
    submission csv; the submission files must match byte-for-byte."""
    from sklearn.ensemble import RandomForestRegressor

    train_p, test_p, tmp = house_csvs

    def pipeline(impl, tag):
        train = impl.read_csv(train_p)
        train_y = train.SalePrice
        predictor_cols = ["LotArea", "OverallQual", "YearBuilt", "TotRmsAbvGrd"]
        train_X = train[predictor_cols]
        model = RandomForestRegressor(n_estimators=20, random_state=0)
        model.fit(np.asarray(train_X), np.asarray(train_y))
        test = impl.read_csv(test_p)
        predicted = model.predict(np.asarray(test[predictor_cols]))
        sub = impl.DataFrame({"Id": test.Id, "SalePrice": predicted})
        out = tmp / f"submission_{tag}.csv"
        sub.to_csv(str(out), index=False)
        return out.read_bytes()

    m_bytes = pipeline(mpd, "modin")
    p_bytes = pipeline(pandas, "pandas")
    assert m_bytes == p_bytes


def test_kaggle17_melbourne(tmp_path):
    """kaggle17: column attribute access + two-column describe."""
    rng = np.random.default_rng(2)
    n = 500
    pandas.DataFrame(
        {
            "Price": rng.integers(200_000, 2_000_000, n).astype(float),
            "Landsize": rng.integers(0, 4_000, n).astype(float),
            "BuildingArea": np.where(
                rng.random(n) < 0.2, np.nan, rng.integers(50, 500, n)
            ),
            "Suburb": rng.choice(["Kew", "Richmond", "Carlton"], n),
        }
    ).to_csv(tmp_path / "melb_data.csv", index=False)
    path = str(tmp_path / "melb_data.csv")

    def pipeline(impl, p):
        melb = impl.read_csv(p)
        cols = list(melb.columns)
        price_head = melb.Price.head()
        described = melb[["Landsize", "BuildingArea"]].describe()
        return cols, price_head, described

    (mc, mh, md), (pc, ph, pd_) = _both(pipeline, path)
    assert mc == pc
    df_equals(mh, ph)
    df_equals(md, pd_)


def test_kaggle22_toxic_comments_nlp(tmp_path):
    """kaggle22: text stats, fillna, row-wise label max, tfidf + logistic
    regression per label, concat submission."""
    from sklearn.feature_extraction.text import TfidfVectorizer
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(3)
    words = ["good", "bad", "awful", "great", "toxic", "nice", "meh", "rude"]
    n = 400
    comments = [
        " ".join(rng.choice(words, rng.integers(3, 12))) if rng.random() > 0.05 else np.nan
        for _ in range(n)
    ]
    label_cols = ["toxic", "insult"]
    base = {"comment_text": comments}
    for c in label_cols:
        base[c] = rng.integers(0, 2, n)
    pandas.DataFrame(base).to_csv(tmp_path / "train.csv", index=False)
    path = str(tmp_path / "train.csv")

    def pipeline(impl, p):
        train = impl.read_csv(p)
        lens = train.comment_text.str.len()
        stats = (float(lens.mean()), float(lens.std()), float(lens.max()))
        train["none"] = 1 - train[label_cols].max(axis=1)
        train["comment_text"] = train["comment_text"].fillna("unknown")
        vec = TfidfVectorizer(min_df=2)
        x = vec.fit_transform(np.asarray(train["comment_text"]))
        preds = np.zeros((len(train), len(label_cols)))
        for i, j in enumerate(label_cols):
            m = LogisticRegression(C=4, random_state=0)
            m.fit(x, np.asarray(train[j]))
            preds[:, i] = m.predict_proba(x)[:, 1]
        submission = impl.concat(
            [train[["none"]], impl.DataFrame(preds, columns=label_cols)], axis=1
        )
        return stats, submission

    (ms, msub), (ps, psub) = _both(pipeline, path)
    np.testing.assert_allclose(ms, ps)
    df_equals(msub, psub)


def test_kaggle9_house_prices_feature_engineering(tmp_path):
    """kaggle9: concat train/test with a .loc column slice, log1p of
    skewed numeric features, get_dummies, fillna(mean), Ridge ensemble."""
    from sklearn.linear_model import Ridge

    rng = np.random.default_rng(4)

    def make(n, with_price):
        df = pandas.DataFrame(
            {
                "Id": np.arange(n),
                "MSSubClass": rng.integers(20, 190, n),
                "LotArea": (rng.lognormal(9, 0.5, n)).astype(int),
                "Neighborhood": rng.choice(["A", "B", "C", "D"], n),
                "GrLivArea": rng.integers(400, 4_000, n),
                "SaleCondition": rng.choice(["Normal", "Abnorml", "Partial"], n),
            }
        )
        if with_price:
            df["SalePrice"] = df["GrLivArea"] * 100 + rng.integers(0, 50_000, n)
        return df

    make(600, True).to_csv(tmp_path / "train.csv", index=False)
    make(150, False).to_csv(tmp_path / "test.csv", index=False)

    def pipeline(impl, tmp):
        train = impl.read_csv(str(tmp / "train.csv"))
        test = impl.read_csv(str(tmp / "test.csv"))
        all_data = impl.concat(
            (
                train.loc[:, "MSSubClass":"SaleCondition"],
                test.loc[:, "MSSubClass":"SaleCondition"],
            )
        )
        train["SalePrice"] = np.log1p(train["SalePrice"])
        # the notebook's `dtypes != "object"` predates pandas-3 str dtype
        numeric_feats = all_data.select_dtypes(include=[np.number]).columns
        skewed = train[numeric_feats].apply(lambda x: x.dropna().skew())
        skewed = skewed[skewed > 0.75].index
        all_data[skewed] = np.log1p(all_data[skewed])
        all_data = impl.get_dummies(all_data)
        all_data = all_data.fillna(all_data.mean())
        X_train = all_data[: train.shape[0]]
        X_test = all_data[train.shape[0] :]
        y = train.SalePrice
        model = Ridge(alpha=5.0)
        model.fit(np.asarray(X_train), np.asarray(y))
        preds = np.expm1(model.predict(np.asarray(X_test)))
        solution = impl.DataFrame({"id": test.Id, "SalePrice": preds})
        return _to_host(solution)

    m_sol = pipeline(mpd, tmp_path)
    p_sol = pipeline(pandas, tmp_path)
    pandas.testing.assert_frame_equal(m_sol, p_sol)


def test_kaggle6_digit_recognizer_prep(tmp_path):
    """kaggle6: label split, isnull().any().describe(), normalization,
    reshape to images, stratified split — the CNN itself is out of scope."""
    from sklearn.model_selection import train_test_split

    rng = np.random.default_rng(5)
    n, px = 300, 16
    data = {"label": rng.integers(0, 10, n)}
    for i in range(px):
        data[f"pixel{i}"] = rng.integers(0, 256, n)
    pandas.DataFrame(data).to_csv(tmp_path / "train.csv", index=False)

    def pipeline(impl, tmp):
        train = impl.read_csv(str(tmp / "train.csv"))
        Y_train = train["label"]
        X_train = train.drop(labels=["label"], axis=1)
        counts = Y_train.value_counts()
        null_desc = X_train.isnull().any().describe()
        X_train = X_train / 255.0
        arr = np.asarray(X_train).reshape(-1, 4, 4, 1)
        X_tr, X_val, Y_tr, Y_val = train_test_split(
            arr, np.asarray(Y_train), test_size=0.1, random_state=2
        )
        return counts, null_desc, X_tr.sum(), Y_val

    (mc, mn, ms, my), (pc, pn, ps, py) = _both(pipeline, tmp_path)
    df_equals(mc, pc)
    df_equals(mn, pn)
    np.testing.assert_allclose(ms, ps)
    np.testing.assert_array_equal(my, py)


# --------------------------------------------------------------------- #
# r5 ports: the remaining 10 notebooks (VERDICT r4 item 5), str/datetime-
# heavy ones first.  Each pipeline re-derives its notebook's pandas-op mix
# on synthetic data (reference: stress_tests/kaggle/kaggle{N}.py).
# --------------------------------------------------------------------- #


@pytest.fixture
def titanic_csv(tmp_path):
    rng = np.random.default_rng(7)
    n = 600
    names = [
        f"{ln}, {t}. {fn}"
        for ln, t, fn in zip(
            rng.choice(["Braund", "Cumings", "Allen", "Moran", "Smith"], n),
            rng.choice(["Mr", "Mrs", "Miss", "Master", "Dr"], n),
            rng.choice(["John", "Anna", "Elsa", "Owen", "Maria"], n),
        )
    ]
    df = pandas.DataFrame(
        {
            "PassengerId": np.arange(1, n + 1),
            "Survived": rng.integers(0, 2, n),
            "Pclass": rng.integers(1, 4, n),
            "Name": names,
            "Sex": rng.choice(["male", "female"], n),
            "Age": np.where(rng.random(n) < 0.2, np.nan, rng.uniform(1, 80, n).round(1)),
            "SibSp": rng.integers(0, 5, n),
            "Parch": rng.integers(0, 4, n),
            "Fare": rng.uniform(5, 500, n).round(2),
            "Embarked": np.where(
                rng.random(n) < 0.02, None, rng.choice(["S", "C", "Q"], n)
            ),
            "Cabin": np.where(rng.random(n) < 0.7, None, rng.choice(["C85", "E46", "B28"], n)),
        }
    )
    p = tmp_path / "titanic.csv"
    df.to_csv(p, index=False)
    return str(p)


def test_kaggle3_pokemon_and_timeseries(tmp_path):
    """kaggle3: corr of numeric frame, logical-indexing filters, apply over a
    column, datetime index + resample interpolation, loc slices
    (stress_tests/kaggle/kaggle3.py)."""
    rng = np.random.default_rng(3)
    n = 300
    df = pandas.DataFrame(
        {
            "Name": rng.choice(["Bulbasaur", "Charmander", "Squirtle", "Pidgey"], n),
            "Type 1": rng.choice(["Grass", "Fire", "Water", "Normal"], n),
            "Attack": rng.integers(5, 190, n),
            "Defense": rng.integers(5, 230, n),
            "Speed": rng.integers(5, 180, n),
            "HP": rng.integers(1, 255, n),
            "Legendary": rng.random(n) < 0.08,
        }
    )
    p = tmp_path / "pokemon.csv"
    df.to_csv(p, index=False)

    def pipeline(impl, path):
        data = impl.read_csv(path)
        corr = data[["Attack", "Defense", "Speed", "HP"]].corr()
        filtered = data[(data["Defense"] > 200) | (data["Attack"] > 100)]
        data["speed_level"] = data["Speed"].apply(
            lambda s: "high" if s > 90 else "low"
        )
        levels = data["speed_level"].value_counts()
        ts = impl.DataFrame(
            {"v": np.arange(10.0)},
            index=impl.to_datetime(
                [f"2020-01-{d:02d}" for d in range(1, 11)]
            ),
        )
        monthly = ts.resample("ME").mean()
        return corr, filtered, levels, monthly, data.loc[:20, ["Attack", "Defense"]]

    (mc, mf, ml, mm, mloc), (pc, pf, pl, pm, ploc) = _both(pipeline, str(p))
    df_equals(mc, pc)
    df_equals(mf, pf)
    df_equals(ml, pl)
    df_equals(mm, pm)
    df_equals(mloc, ploc)


def test_kaggle4_titanic_fillna_modes(titanic_csv):
    """kaggle4: mode-based fillna of str/numeric columns, get_dummies,
    numeric corr, groupby survival rates (stress_tests/kaggle/kaggle4.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        nulls = df.isnull().sum()
        df["Embarked"] = df["Embarked"].fillna(df["Embarked"].mode()[0])
        df["Age"] = df["Age"].fillna(df["Age"].median())
        df["Fare"] = df["Fare"].fillna(df["Fare"].mode()[0])
        df = df.drop(["Cabin"], axis=1)
        rates = (
            df[["Sex", "Survived"]]
            .groupby("Sex", as_index=False)
            .mean()
            .sort_values(by="Survived", ascending=False)
        )
        dummies = impl.get_dummies(df["Embarked"], prefix="Emb")
        corr = df[["Survived", "Pclass", "Age", "Fare"]].corr()
        return nulls, df, rates, dummies, corr

    (mn, md, mr, mdum, mc), (pn, pdf_, pr, pdum, pc) = _both(pipeline, titanic_csv)
    df_equals(mn, pn)
    df_equals(md, pdf_)
    df_equals(mr, pr)
    df_equals(mdum, pdum)
    df_equals(mc, pc)


def test_kaggle5_titanic_feature_engineering(titanic_csv):
    """kaggle5: str.extract of titles, map/replace recodes, qcut fare bands,
    loc age banding, groupby means (stress_tests/kaggle/kaggle5.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        df["Title"] = df["Name"].str.extract(r" ([A-Za-z]+)\.", expand=False)
        df["Title"] = df["Title"].replace(["Dr"], "Rare")
        df["Title"] = df["Title"].map(
            {"Mr": 1, "Miss": 2, "Mrs": 3, "Master": 4, "Rare": 5}
        ).fillna(0).astype(int)
        title_rate = (
            df[["Title", "Survived"]].groupby("Title", as_index=False).mean()
        )
        df["Sex"] = df["Sex"].map({"female": 1, "male": 0}).astype(int)
        df = df.drop(["Name", "PassengerId", "Cabin"], axis=1)
        df["Age"] = df["Age"].fillna(df["Age"].median())
        df.loc[df["Age"] <= 16, "Age"] = 0
        df.loc[(df["Age"] > 16) & (df["Age"] <= 32), "Age"] = 1
        df.loc[(df["Age"] > 32) & (df["Age"] <= 48), "Age"] = 2
        df.loc[df["Age"] > 48, "Age"] = 3
        df["FareBand"] = impl.qcut(df["Fare"], 4, labels=[0, 1, 2, 3])
        band_rate = (
            df[["FareBand", "Survived"]]
            .groupby("FareBand", as_index=False, observed=False)
            .mean()
            .sort_values(by="FareBand", ascending=True)
        )
        df["IsAlone"] = ((df["SibSp"] + df["Parch"]) == 0).astype(int)
        alone_rate = df[["IsAlone", "Survived"]].groupby("IsAlone", as_index=False).mean()
        return title_rate, band_rate, alone_rate, df.head(20)

    (mt, mb, ma, mh), (pt, pb, pa, ph) = _both(pipeline, titanic_csv)
    df_equals(mt, pt)
    df_equals(mb, pb)
    df_equals(ma, pa)
    df_equals(mh, ph)


def test_kaggle7_house_merge_dummies(tmp_path):
    """kaggle7: two-frame merge, get_dummies over a categorical, corr-driven
    feature ranking, replace + sort_values (stress_tests/kaggle/kaggle7.py)."""
    rng = np.random.default_rng(77)
    n = 500
    main = pandas.DataFrame(
        {
            "Id": np.arange(n),
            "Neighborhood": rng.choice(["NAmes", "CollgCr", "OldTown", "Edwards"], n),
            "OverallQual": rng.integers(1, 11, n),
            "GrLivArea": rng.integers(400, 4000, n),
            "SalePrice": rng.integers(50_000, 500_000, n),
        }
    )
    lookup = pandas.DataFrame(
        {
            "Neighborhood": ["NAmes", "CollgCr", "OldTown", "Edwards"],
            "SchoolRating": [7, 9, 5, 4],
        }
    )
    mp_, lp = tmp_path / "main.csv", tmp_path / "lookup.csv"
    main.to_csv(mp_, index=False)
    lookup.to_csv(lp, index=False)

    def pipeline(impl, main_path, lookup_path):
        df = impl.read_csv(main_path)
        lk = impl.read_csv(lookup_path)
        merged = df.merge(lk, on="Neighborhood")
        corr = merged[["OverallQual", "GrLivArea", "SalePrice", "SchoolRating"]].corr()
        ranked = corr["SalePrice"].sort_values(ascending=False)
        dummies = impl.get_dummies(merged["Neighborhood"])
        merged["QualBand"] = merged["OverallQual"].replace(
            {1: "low", 2: "low", 3: "low", 4: "mid", 5: "mid", 6: "mid"}
        )
        counts = merged["QualBand"].value_counts()
        desc = merged[["GrLivArea", "SalePrice"]].describe()
        return merged.sort_values("SalePrice").head(15), ranked, dummies.head(), counts, desc

    (mm, mr, mdm, mc, mdsc), (pm, pr, pdm, pc, pdsc) = _both(pipeline, str(mp_), str(lp))
    df_equals(mm, pm)
    df_equals(mr, pr)
    df_equals(mdm, pdm)
    df_equals(mc, pc)
    df_equals(mdsc, pdsc)


def test_kaggle10_loc_column_slices(titanic_csv):
    """kaggle10: .loc label/column slicing drills, iloc windows, get_dummies,
    describe (stress_tests/kaggle/kaggle10.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        a = df.loc[:, "Name":"Age"]
        b = df.loc[df["Sex"] == "female", ["Name", "Age", "Survived"]]
        c = df.iloc[10:20, 2:6]
        d = df.loc[df["Age"] > 60, :]
        dummies = impl.get_dummies(df["Pclass"], prefix="class")
        desc = df.describe()
        counts = df["Embarked"].value_counts(dropna=False)
        return a.head(25), b.head(25), c, d, dummies.head(10), desc, counts

    outs_m, outs_p = _both(pipeline, titanic_csv)
    for m, p in zip(outs_m, outs_p):
        df_equals(m, p)


def test_kaggle12_map_concat_dummies(titanic_csv):
    """kaggle12: train/test concat, map recodes, get_dummies + concat of
    frames, iloc re-split, numeric corr (stress_tests/kaggle/kaggle12.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        train, test = df.iloc[:400], df.iloc[400:]
        both = impl.concat([train, test], ignore_index=True)
        both["Sex"] = both["Sex"].map({"male": 0, "female": 1})
        both["Embarked"] = both["Embarked"].fillna("S").map({"S": 0, "C": 1, "Q": 2})
        nulls = both.isnull().sum()
        pclass_d = impl.get_dummies(both["Pclass"], prefix="P")
        both2 = impl.concat([both[["Sex", "Embarked", "Age", "Fare"]], pclass_d], axis=1)
        both2["Age"] = both2["Age"].fillna(both2["Age"].median())
        corr = both2.corr()
        re_train = both2.iloc[:400].reset_index(drop=True)
        return nulls, both2.head(30), corr, re_train.describe()

    (mn, mh, mc, md), (pn, ph, pc, pdsc) = _both(pipeline, titanic_csv)
    df_equals(mn, pn)
    df_equals(mh, ph)
    df_equals(mc, pc)
    df_equals(md, pdsc)


def test_kaggle14_banding_and_extract(titanic_csv):
    """kaggle14: str.extract titles, replace-consolidation, loc band
    assignment, qcut, per-band survival, numeric corr
    (stress_tests/kaggle/kaggle14.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        df["Title"] = df["Name"].str.extract(r" ([A-Za-z]+)\.", expand=False)
        tcounts = impl.crosstab(df["Title"], df["Sex"]) if hasattr(impl, "crosstab") else None
        df["Title"] = df["Title"].replace(["Dr", "Master"], "Other")
        rate = df[["Title", "Survived"]].groupby("Title").mean().sort_values("Survived")
        df["AgeBand"] = impl.cut(df["Age"], 5)
        band = (
            df[["AgeBand", "Survived"]]
            .groupby("AgeBand", observed=False)
            .mean()
            .sort_values("AgeBand")
        )
        df.loc[df["Fare"] <= 100, "Fare"] = 0
        df.loc[df["Fare"] > 100, "Fare"] = 1
        fare_counts = df["Fare"].value_counts()
        corr = df[["Survived", "Pclass", "SibSp", "Parch", "Fare"]].corr()
        return tcounts, rate, band, fare_counts, corr

    (mt, mr, mb, mf, mc), (pt, pr, pb, pf, pc) = _both(pipeline, titanic_csv)
    if mt is not None and pt is not None:
        df_equals(mt, pt)
    df_equals(mr, pr)
    df_equals(mb, pb)
    df_equals(mf, pf)
    df_equals(mc, pc)


def test_kaggle18_categorical_profiling(titanic_csv):
    """kaggle18: value_counts ladders, nunique, map + apply feature codes,
    deterministic sample, reset_index chains (stress_tests/kaggle/kaggle18.py)."""

    def pipeline(impl, path):
        df = impl.read_csv(path)
        vc = df["Pclass"].value_counts()
        vc_norm = df["Embarked"].value_counts(normalize=True)
        uniq = df[["Sex", "Embarked", "Pclass"]].nunique()
        df["SexCode"] = df["Sex"].map({"male": 0, "female": 1})
        df["FamilySize"] = df.apply(lambda r: r["SibSp"] + r["Parch"] + 1, axis=1)
        fam = df["FamilySize"].value_counts().reset_index()
        samp = df.sample(n=25, random_state=42).reset_index(drop=True)
        top = (
            df.groupby("Pclass")["Fare"]
            .mean()
            .sort_values(ascending=False)
            .reset_index()
        )
        return vc, vc_norm, uniq, fam, samp, top

    outs_m, outs_p = _both(pipeline, titanic_csv)
    for m, p in zip(outs_m, outs_p):
        df_equals(m, p)


def test_kaggle19_cut_and_corr(tmp_path):
    """kaggle19: pd.cut age bins, fillna ladder, groupby bins, corr ranking
    (stress_tests/kaggle/kaggle19.py)."""
    rng = np.random.default_rng(19)
    n = 400
    df = pandas.DataFrame(
        {
            "age": np.where(rng.random(n) < 0.1, np.nan, rng.uniform(18, 90, n).round()),
            "balance": rng.normal(1200, 800, n).round(2),
            "duration": rng.integers(10, 3000, n),
            "outcome": rng.integers(0, 2, n),
        }
    )
    p = tmp_path / "bank.csv"
    df.to_csv(p, index=False)

    def pipeline(impl, path):
        d = impl.read_csv(path)
        d["age"] = d["age"].fillna(d["age"].median())
        d["age_group"] = impl.cut(
            d["age"], bins=[0, 30, 45, 60, 100], labels=["young", "mid", "senior", "old"]
        )
        grp = d.groupby("age_group", observed=False)["outcome"].mean()
        corr = d[["age", "balance", "duration", "outcome"]].corr()
        ranked = corr["outcome"].sort_values(ascending=False)
        return grp, corr, ranked, d.sort_values("balance").head(10)

    (mg, mc, mr, mh), (pg, pc, pr, ph) = _both(pipeline, str(p))
    df_equals(mg, pg)
    df_equals(mc, pc)
    df_equals(mr, pr)
    df_equals(mh, ph)


def test_kaggle20_melt_concat(tmp_path):
    """kaggle20: iloc splits, melt to long form, concat rows/cols, corr,
    describe (stress_tests/kaggle/kaggle20.py)."""
    rng = np.random.default_rng(20)
    n = 240
    df = pandas.DataFrame(
        {
            "country": rng.choice(["ar", "br", "cl", "pe"], n),
            "y2019": rng.normal(100, 20, n).round(1),
            "y2020": rng.normal(95, 25, n).round(1),
            "y2021": rng.normal(105, 22, n).round(1),
        }
    )
    p = tmp_path / "gdp.csv"
    df.to_csv(p, index=False)

    def pipeline(impl, path):
        d = impl.read_csv(path)
        top, bottom = d.iloc[:120], d.iloc[120:]
        stacked = impl.concat([top, bottom], ignore_index=True)
        long = stacked.melt(
            id_vars="country", var_name="year", value_name="gdp"
        )
        side = impl.concat([d["y2019"], d["y2020"]], axis=1)
        corr = d[["y2019", "y2020", "y2021"]].corr()
        return long.head(30), long["year"].value_counts(), side.describe(), corr

    (ml, mv, ms, mc), (pl, pv, ps, pc) = _both(pipeline, str(p))
    df_equals(ml, pl)
    df_equals(mv, pv)
    df_equals(ms, ps)
    df_equals(mc, pc)
