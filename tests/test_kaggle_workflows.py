"""Kaggle-notebook stress pipelines as differential tests.

The reference runs 16 real Kaggle notebooks end-to-end as its stress suite
(stress_tests/test_kaggle_ipynb.py over stress_tests/kaggle/kaggle*.py).
These are the same pipelines re-derived on synthetic data — plotting cells
skipped, keras cells replaced with the sklearn models the notebooks also
use — each run twice (modin_tpu vs pandas) and compared on their final
artifacts.  They deliberately stress the mixed-dtype fallback seams:
string columns, get_dummies, .loc column slices, apply over columns,
sklearn interop via __array__, and to_csv round-trips.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as mpd
from tests.utils import df_equals

IMPLS = [mpd, pandas]


def _both(fn, *args):
    """Run a pipeline under both implementations; return (modin, pandas)."""
    out = []
    for impl in IMPLS:
        out.append(fn(impl, *args))
    return out


def _to_host(obj):
    return obj._to_pandas() if hasattr(obj, "_to_pandas") else obj


@pytest.fixture
def iris_csv(tmp_path):
    rng = np.random.default_rng(0)
    n = 600
    species = rng.choice(["setosa", "versicolor", "virginica"], n)
    df = pandas.DataFrame(
        {
            "Id": np.arange(1, n + 1),
            "SepalLengthCm": rng.normal(5.8, 0.8, n).round(1),
            "SepalWidthCm": rng.normal(3.0, 0.4, n).round(1),
            "PetalLengthCm": rng.normal(3.7, 1.7, n).round(1),
            "PetalWidthCm": rng.normal(1.2, 0.7, n).round(1),
            "Species": species,
        }
    )
    p = tmp_path / "Iris.csv"
    df.to_csv(p, index=False)
    return str(p)


def test_kaggle13_iris(iris_csv):
    """kaggle13: read, value_counts, per-species boxplot data (groupby
    describe), drop."""

    def pipeline(impl, path):
        iris = impl.read_csv(path)
        head = iris.head()
        counts = iris["Species"].value_counts()
        by_species = iris.drop("Id", axis=1).groupby("Species").describe()
        return head, counts, by_species

    (mh, mc, mg), (ph, pc, pg) = _both(pipeline, iris_csv)
    df_equals(mh, ph)
    df_equals(mc, pc)
    df_equals(mg, pg)


@pytest.fixture
def house_csvs(tmp_path):
    rng = np.random.default_rng(1)

    def make(n, with_price):
        df = pandas.DataFrame(
            {
                "Id": np.arange(1, n + 1),
                "LotArea": rng.integers(1_000, 20_000, n),
                "OverallQual": rng.integers(1, 11, n),
                "YearBuilt": rng.integers(1900, 2010, n),
                "TotRmsAbvGrd": rng.integers(2, 12, n),
            }
        )
        if with_price:
            df["SalePrice"] = (
                df["LotArea"] * 3
                + df["OverallQual"] * 20_000
                + rng.normal(0, 5_000, n).astype(int)
            )
        return df

    train_p, test_p = tmp_path / "train.csv", tmp_path / "test.csv"
    make(800, True).to_csv(train_p, index=False)
    make(200, False).to_csv(test_p, index=False)
    return str(train_p), str(test_p), tmp_path


def test_kaggle8_house_prices_random_forest(house_csvs):
    """kaggle8: csv -> column selection -> sklearn RandomForest ->
    submission csv; the submission files must match byte-for-byte."""
    from sklearn.ensemble import RandomForestRegressor

    train_p, test_p, tmp = house_csvs

    def pipeline(impl, tag):
        train = impl.read_csv(train_p)
        train_y = train.SalePrice
        predictor_cols = ["LotArea", "OverallQual", "YearBuilt", "TotRmsAbvGrd"]
        train_X = train[predictor_cols]
        model = RandomForestRegressor(n_estimators=20, random_state=0)
        model.fit(np.asarray(train_X), np.asarray(train_y))
        test = impl.read_csv(test_p)
        predicted = model.predict(np.asarray(test[predictor_cols]))
        sub = impl.DataFrame({"Id": test.Id, "SalePrice": predicted})
        out = tmp / f"submission_{tag}.csv"
        sub.to_csv(str(out), index=False)
        return out.read_bytes()

    m_bytes = pipeline(mpd, "modin")
    p_bytes = pipeline(pandas, "pandas")
    assert m_bytes == p_bytes


def test_kaggle17_melbourne(tmp_path):
    """kaggle17: column attribute access + two-column describe."""
    rng = np.random.default_rng(2)
    n = 500
    pandas.DataFrame(
        {
            "Price": rng.integers(200_000, 2_000_000, n).astype(float),
            "Landsize": rng.integers(0, 4_000, n).astype(float),
            "BuildingArea": np.where(
                rng.random(n) < 0.2, np.nan, rng.integers(50, 500, n)
            ),
            "Suburb": rng.choice(["Kew", "Richmond", "Carlton"], n),
        }
    ).to_csv(tmp_path / "melb_data.csv", index=False)
    path = str(tmp_path / "melb_data.csv")

    def pipeline(impl, p):
        melb = impl.read_csv(p)
        cols = list(melb.columns)
        price_head = melb.Price.head()
        described = melb[["Landsize", "BuildingArea"]].describe()
        return cols, price_head, described

    (mc, mh, md), (pc, ph, pd_) = _both(pipeline, path)
    assert mc == pc
    df_equals(mh, ph)
    df_equals(md, pd_)


def test_kaggle22_toxic_comments_nlp(tmp_path):
    """kaggle22: text stats, fillna, row-wise label max, tfidf + logistic
    regression per label, concat submission."""
    from sklearn.feature_extraction.text import TfidfVectorizer
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(3)
    words = ["good", "bad", "awful", "great", "toxic", "nice", "meh", "rude"]
    n = 400
    comments = [
        " ".join(rng.choice(words, rng.integers(3, 12))) if rng.random() > 0.05 else np.nan
        for _ in range(n)
    ]
    label_cols = ["toxic", "insult"]
    base = {"comment_text": comments}
    for c in label_cols:
        base[c] = rng.integers(0, 2, n)
    pandas.DataFrame(base).to_csv(tmp_path / "train.csv", index=False)
    path = str(tmp_path / "train.csv")

    def pipeline(impl, p):
        train = impl.read_csv(p)
        lens = train.comment_text.str.len()
        stats = (float(lens.mean()), float(lens.std()), float(lens.max()))
        train["none"] = 1 - train[label_cols].max(axis=1)
        train["comment_text"] = train["comment_text"].fillna("unknown")
        vec = TfidfVectorizer(min_df=2)
        x = vec.fit_transform(np.asarray(train["comment_text"]))
        preds = np.zeros((len(train), len(label_cols)))
        for i, j in enumerate(label_cols):
            m = LogisticRegression(C=4, random_state=0)
            m.fit(x, np.asarray(train[j]))
            preds[:, i] = m.predict_proba(x)[:, 1]
        submission = impl.concat(
            [train[["none"]], impl.DataFrame(preds, columns=label_cols)], axis=1
        )
        return stats, submission

    (ms, msub), (ps, psub) = _both(pipeline, path)
    np.testing.assert_allclose(ms, ps)
    df_equals(msub, psub)


def test_kaggle9_house_prices_feature_engineering(tmp_path):
    """kaggle9: concat train/test with a .loc column slice, log1p of
    skewed numeric features, get_dummies, fillna(mean), Ridge ensemble."""
    from sklearn.linear_model import Ridge

    rng = np.random.default_rng(4)

    def make(n, with_price):
        df = pandas.DataFrame(
            {
                "Id": np.arange(n),
                "MSSubClass": rng.integers(20, 190, n),
                "LotArea": (rng.lognormal(9, 0.5, n)).astype(int),
                "Neighborhood": rng.choice(["A", "B", "C", "D"], n),
                "GrLivArea": rng.integers(400, 4_000, n),
                "SaleCondition": rng.choice(["Normal", "Abnorml", "Partial"], n),
            }
        )
        if with_price:
            df["SalePrice"] = df["GrLivArea"] * 100 + rng.integers(0, 50_000, n)
        return df

    make(600, True).to_csv(tmp_path / "train.csv", index=False)
    make(150, False).to_csv(tmp_path / "test.csv", index=False)

    def pipeline(impl, tmp):
        train = impl.read_csv(str(tmp / "train.csv"))
        test = impl.read_csv(str(tmp / "test.csv"))
        all_data = impl.concat(
            (
                train.loc[:, "MSSubClass":"SaleCondition"],
                test.loc[:, "MSSubClass":"SaleCondition"],
            )
        )
        train["SalePrice"] = np.log1p(train["SalePrice"])
        # the notebook's `dtypes != "object"` predates pandas-3 str dtype
        numeric_feats = all_data.select_dtypes(include=[np.number]).columns
        skewed = train[numeric_feats].apply(lambda x: x.dropna().skew())
        skewed = skewed[skewed > 0.75].index
        all_data[skewed] = np.log1p(all_data[skewed])
        all_data = impl.get_dummies(all_data)
        all_data = all_data.fillna(all_data.mean())
        X_train = all_data[: train.shape[0]]
        X_test = all_data[train.shape[0] :]
        y = train.SalePrice
        model = Ridge(alpha=5.0)
        model.fit(np.asarray(X_train), np.asarray(y))
        preds = np.expm1(model.predict(np.asarray(X_test)))
        solution = impl.DataFrame({"id": test.Id, "SalePrice": preds})
        return _to_host(solution)

    m_sol = pipeline(mpd, tmp_path)
    p_sol = pipeline(pandas, tmp_path)
    pandas.testing.assert_frame_equal(m_sol, p_sol)


def test_kaggle6_digit_recognizer_prep(tmp_path):
    """kaggle6: label split, isnull().any().describe(), normalization,
    reshape to images, stratified split — the CNN itself is out of scope."""
    from sklearn.model_selection import train_test_split

    rng = np.random.default_rng(5)
    n, px = 300, 16
    data = {"label": rng.integers(0, 10, n)}
    for i in range(px):
        data[f"pixel{i}"] = rng.integers(0, 256, n)
    pandas.DataFrame(data).to_csv(tmp_path / "train.csv", index=False)

    def pipeline(impl, tmp):
        train = impl.read_csv(str(tmp / "train.csv"))
        Y_train = train["label"]
        X_train = train.drop(labels=["label"], axis=1)
        counts = Y_train.value_counts()
        null_desc = X_train.isnull().any().describe()
        X_train = X_train / 255.0
        arr = np.asarray(X_train).reshape(-1, 4, 4, 1)
        X_tr, X_val, Y_tr, Y_val = train_test_split(
            arr, np.asarray(Y_train), test_size=0.1, random_state=2
        )
        return counts, null_desc, X_tr.sum(), Y_val

    (mc, mn, ms, my), (pc, pn, ps, py) = _both(pipeline, tmp_path)
    df_equals(mc, pc)
    df_equals(mn, pn)
    np.testing.assert_allclose(ms, ps)
    np.testing.assert_array_equal(my, py)
