"""DataFrame interchange protocol tests (native-buffer producer)."""

import numpy as np
import pandas
import pytest
from pandas.api.interchange import from_dataframe

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs

_rng = np.random.default_rng(21)
N = 500


def _require_tpu():
    import pytest as _pytest

    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        _pytest.skip("device-buffer internals require TpuOnJax")


@pytest.fixture
def frames():
    data = {
        "f": np.where(_rng.random(N) < 0.1, np.nan, _rng.normal(size=N)),
        "i": _rng.integers(-100, 100, N),
        "u": _rng.integers(0, 100, N).astype(np.uint32),
        "b": _rng.random(N) < 0.5,
        "dt": np.datetime64("2024-01-01", "ns")
        + _rng.integers(0, 10**9, N).astype("timedelta64[ns]"),
        "s": np.array([f"name_{i % 7}" for i in range(N)]),
    }
    return create_test_dfs(data)


def test_round_trip_matches_pandas_producer(frames):
    md, pdf = frames
    got = from_dataframe(md.__dataframe__())
    want = from_dataframe(pdf.__dataframe__())
    pandas.testing.assert_frame_equal(got, want)


def test_zero_copy_over_host_cache(frames):
    _require_tpu()
    md, _ = frames
    dfx = md.__dataframe__()
    buf, _dtype = dfx.get_column_by_name("i").get_buffers()["data"]
    cache = md._query_compiler._modin_frame.get_column(1).host_cache
    assert buf.ptr == cache.__array_interface__["data"][0]


def test_no_full_frame_materialization(frames):
    # consuming one column must not call to_pandas on the whole frame
    _require_tpu()
    md, _ = frames
    qc = md._query_compiler
    called = {"n": 0}
    original = type(qc._modin_frame).to_pandas

    def spy(self):
        called["n"] += 1
        return original(self)

    type(qc._modin_frame).to_pandas = spy
    try:
        col = md.__dataframe__().get_column_by_name("f")
        _ = col.get_buffers()
    finally:
        type(qc._modin_frame).to_pandas = original
    assert called["n"] == 0


def test_computed_columns_interchange(frames):
    md, pdf = frames
    derived_md = md[["f"]] * 2.0
    got = from_dataframe(derived_md.__dataframe__())
    np.testing.assert_allclose(
        got["f"].to_numpy(), (pdf[["f"]] * 2.0)["f"].to_numpy()
    )


def test_select_columns(frames):
    md, pdf = frames
    sub = md.__dataframe__().select_columns_by_name(["i", "b"])
    got = from_dataframe(sub)
    want = from_dataframe(pdf[["i", "b"]].__dataframe__())
    pandas.testing.assert_frame_equal(got, want)


def test_from_interchange_consumer(frames):
    # our side as CONSUMER of a foreign protocol object
    _, pdf = frames
    md = pd.api.interchange.from_dataframe(pdf.__dataframe__())
    pandas.testing.assert_frame_equal(
        md.modin.to_pandas(), from_dataframe(pdf.__dataframe__())
    )
