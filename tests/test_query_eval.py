"""Differential tests for the device-native query/eval expression engine."""

import warnings

import numpy as np
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals

_rng = np.random.default_rng(21)
N = 500

QE_DATA = {
    "a": _rng.uniform(-50, 50, N),
    "b": _rng.integers(0, 10, N),
    "c d": _rng.uniform(0, 1, N),  # space -> needs backticks
    "s": _rng.choice(["x", "y", "z"], N),
}


@pytest.fixture
def dfs():
    return create_test_dfs(QE_DATA)


@pytest.mark.parametrize(
    "expr",
    [
        "a > 0",
        "a > 0 and b < 5",
        "a > 0 & (b == 3)",
        "(a + b) * 2 >= 10",
        "b in [1, 2, 3]",
        "b not in [1, 2, 3]",
        "not a > 0",
        "0 < a < 20",
        "a ** 2 > 100",
        "`c d` > 0.5",
        "b % 2 == 0",
        "-a > 5",
        "a > 3 or b < 2",
        "s == 'x'",
    ],
)
def test_query(dfs, expr):
    md, pdf = dfs
    df_equals(md.query(expr), pdf.query(expr))


def test_query_local_variable(dfs):
    md, pdf = dfs
    threshold = 10
    df_equals(md.query("a > @threshold"), pdf.query("a > @threshold"))


def test_query_local_resolved_in_direct_caller(dfs):
    # @locals must resolve in the frame that calls .query (pandas level
    # semantics), including when that frame is a user helper function.
    md, pdf = dfs

    def helper(frame):
        lim = 20
        return frame.query("a > @lim")

    df_equals(helper(md), helper(pdf))


def test_query_runs_on_device(dfs):
    from tests.utils import assert_no_fallback

    md, _ = dfs
    numeric = md[["a", "b"]]
    result = assert_no_fallback(lambda: numeric.query("a > 0 & b < 5"))
    assert len(result) > 0


@pytest.mark.parametrize(
    "expr",
    [
        "a + b",
        "a * 2 - b",
        "e = a + b",
        "`c d` * 10",
    ],
)
def test_eval(dfs, expr):
    md, pdf = dfs
    df_equals(md.eval(expr), pdf.eval(expr))


def test_eval_inplace(dfs):
    md, pdf = dfs
    md.eval("f = a - b", inplace=True)
    pdf.eval("f = a - b", inplace=True)
    df_equals(md, pdf)


def test_query_inplace(dfs):
    md, pdf = dfs
    md.query("a > 0", inplace=True)
    pdf.query("a > 0", inplace=True)
    df_equals(md, pdf)


def test_query_fallback_exotic(dfs):
    md, pdf = dfs
    # .str accessor forces the pandas fallback but stays correct
    df_equals(
        md.query("s.str.contains('x')", engine="python"),
        pdf.query("s.str.contains('x')", engine="python"),
    )


def test_query_undefined_name_raises(dfs):
    md, pdf = dfs
    with pytest.raises(Exception):
        pdf.query("nope > 1")
    with pytest.raises(Exception):
        md.query("nope > 1")


def test_query_eval_local_dict_reaches_fallback():
    """@-locals must resolve on the FALLBACK path too (the pandas call runs
    deep inside the QC layers where frame-walking cannot see user locals).
    Exercised by forcing an expression rowwise_query cannot compile."""
    from tests.utils import create_test_dfs, eval_general

    md, pdf = create_test_dfs({"s": ["ab", "cd", "ef"], "v": [1.0, 2.0, 3.0]})
    pat = "c"

    eval_general(md, pdf, lambda df: df.query("s.str.contains(@pat)"))
    lo = 1.5
    eval_general(md, pdf, lambda df: df.eval("v + @lo"))
