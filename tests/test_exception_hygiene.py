"""Tier-1 wiring for the EXC-HYGIENE graftlint rule.

Broad ``except Exception`` around device dispatch swallows XlaRuntimeError
and misreads infrastructure failures as semantic fallbacks (the round-5
failure class).  The rule (modin_tpu/lint/rules/exc_hygiene.py — it ports
and subsumes the old scripts/check_exception_hygiene.py) walks the audited
trees and fails on any broad handler without a reasoned
``# graftlint: disable=EXC-HYGIENE`` pragma; the framework's
GL-PRAGMA-UNUSED finding prunes pragmas whose handler was fixed or deleted
(the job of the old ``test_allowlist_entries_still_exist``).
"""

import pathlib

from modin_tpu.lint import run_lint
from modin_tpu.lint.rules.exc_hygiene import AUDITED_PREFIXES

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_no_new_broad_exception_handlers():
    result = run_lint(
        ["modin_tpu"], root=REPO_ROOT, select=["EXC-HYGIENE"]
    )
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        "exception-hygiene violations (narrow the handler to the semantic "
        "types, or vet it with an inline "
        "'# graftlint: disable=EXC-HYGIENE -- <reason>' pragma):\n" + rendered
    )


def test_audited_trees_have_vetted_handlers():
    """The known vetted handlers stay suppressed BY PRAGMA, not by silence.

    If this count drops to zero the rule is probably scanning nothing —
    guard against the audit silently going dark (the suppressed list only
    counts findings the rule actually produced and a pragma excused).
    """
    result = run_lint(
        ["modin_tpu"], root=REPO_ROOT, select=["EXC-HYGIENE"]
    )
    suppressed = [f for f in result.suppressed if f.rule == "EXC-HYGIENE"]
    assert len(suppressed) >= 10, (
        "expected the vetted broad handlers (resilience layer, IO driver "
        f"probes, ...) to be pragma-suppressed; got {len(suppressed)} — did "
        "the audited trees change?"
    )
    for f in suppressed:
        assert f.path.startswith(AUDITED_PREFIXES)


def test_unused_exc_hygiene_pragmas_are_flagged():
    """Dead pragmas hide future violations — the full run must prune them
    (replaces the old allowlist-pruning test, generically)."""
    result = run_lint(["modin_tpu"], root=REPO_ROOT)
    unused = [f for f in result.findings if f.rule == "GL-PRAGMA-UNUSED"]
    assert not unused, "\n".join(f.render() for f in unused)
