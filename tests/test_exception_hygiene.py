"""Tier-1 wiring for scripts/check_exception_hygiene.py.

Broad ``except Exception`` around device dispatch swallows XlaRuntimeError
and misreads infrastructure failures as semantic fallbacks (the round-5
failure class).  The lint walks modin_tpu/core/ and modin_tpu/parallel/ and
fails on any broad handler not in its vetted allowlist.
"""

import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO_ROOT / "scripts" / "check_exception_hygiene.py"


def test_no_new_broad_exception_handlers():
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)], capture_output=True, text=True
    )
    assert proc.returncode == 0, (
        "exception-hygiene violations (narrow the handler to the semantic "
        "types, or vet + allowlist it in the script):\n" + proc.stdout
    )


def test_allowlist_entries_still_exist():
    """Dead allowlist entries hide future violations — prune them."""
    sys.path.insert(0, str(SCRIPT.parent))
    try:
        import check_exception_hygiene as lint
    finally:
        sys.path.pop(0)
    import ast

    for (rel, func), _reason in lint.ALLOWLIST.items():
        path = REPO_ROOT / rel
        assert path.exists(), f"allowlisted file no longer exists: {rel}"
        tree = ast.parse(path.read_text())
        owner = lint._enclosing_function(tree)
        broad_owners = {
            owner.get(node)
            for node in ast.walk(tree)
            if isinstance(node, ast.ExceptHandler) and lint._is_broad(node)
        }
        assert func in broad_owners, (
            f"allowlist entry ({rel}, {func}) matches no broad handler "
            "anymore — remove it"
        )
