"""Backend caster + cost-driven auto-switch tests.

Mirrors the reference suite's shape (modin/tests/pandas/test_backend.py):
mixed-backend arguments coerce to the cheapest common backend through the
per-method QC caster, and AutoSwitchBackend relocates frames around
operations when the cost model says so.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import AutoSwitchBackend
from modin_tpu.core.storage_formats.native.query_compiler import (
    NativeQueryCompiler,
)
from modin_tpu.core.storage_formats.tpu.query_compiler import TpuQueryCompiler
from tests.utils import df_equals


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("backend switch tests need the TpuOnJax default")


def _native_df(data):
    qc = NativeQueryCompiler.from_pandas(pandas.DataFrame(data))
    return pd.DataFrame(query_compiler=qc)


def _backend(df):
    return type(df._query_compiler).__name__


def test_mixed_backend_binary_op_coerces():
    big = pd.DataFrame({"a": np.arange(50_000.0)})
    small = _native_df({"a": np.ones(50_000)})
    assert _backend(big) == "TpuQueryCompiler"
    assert _backend(small) == "NativeQueryCompiler"
    out = big + small
    # the device operand is cheaper to keep: the native one moves to it
    assert _backend(out) == "TpuQueryCompiler"
    df_equals(out, pandas.DataFrame({"a": np.arange(50_000.0) + 1.0}))


def test_mixed_backend_merge_coerces():
    left = pd.DataFrame({"k": np.arange(1000) % 7, "x": np.arange(1000.0)})
    right = _native_df({"k": np.arange(7), "y": np.arange(7.0)})
    out = left.merge(right, on="k")
    assert _backend(out) == "TpuQueryCompiler"
    pl_ = pandas.DataFrame({"k": np.arange(1000) % 7, "x": np.arange(1000.0)})
    pr = pandas.DataFrame({"k": np.arange(7), "y": np.arange(7.0)})
    df_equals(out, pl_.merge(pr, on="k"))


def test_mixed_backend_concat_coerces():
    a = pd.DataFrame({"a": np.arange(100.0)})
    b = _native_df({"a": np.arange(100.0)})
    out = pd.concat([a, b], ignore_index=True)
    df_equals(
        out,
        pandas.concat(
            [pandas.DataFrame({"a": np.arange(100.0)})] * 2, ignore_index=True
        ),
    )


def test_auto_switch_moves_fallback_op_to_native():
    # a small device frame running an op with no device kernel should
    # relocate to the Native backend when AutoSwitchBackend is on
    # (melt has no TpuQC override; mode — the op used before r05 — grew a
    # device kernel and stays on Tpu)
    md = pd.DataFrame({"a": [3.0, 1.0, 2.0, 1.0]})
    assert _backend(md) == "TpuQueryCompiler"
    with AutoSwitchBackend.context(True):
        out = md.melt()
    assert _backend(out) == "NativeQueryCompiler"
    df_equals(out, pandas.DataFrame({"a": [3.0, 1.0, 2.0, 1.0]}).melt())


def test_no_auto_switch_when_disabled():
    md = pd.DataFrame({"a": [3.0, 1.0, 2.0, 1.0]})
    with AutoSwitchBackend.context(False):
        out = md.mode()
    assert _backend(out) == "TpuQueryCompiler"


def test_auto_switch_keeps_device_ops_on_device():
    md = pd.DataFrame({"a": np.arange(1000.0)})
    with AutoSwitchBackend.context(True):
        out = md * 2.0
    assert _backend(out) == "TpuQueryCompiler"


def test_set_backend_round_trip():
    md = pd.DataFrame({"a": np.arange(16.0)})
    native = md.modin.set_backend("Pandas")
    assert _backend(native) == "NativeQueryCompiler"
    back = native.modin.set_backend("Tpu")
    assert _backend(back) == "TpuQueryCompiler"
    df_equals(back, pandas.DataFrame({"a": np.arange(16.0)}))


def test_mixed_backend_getitem_mask():
    big = pd.DataFrame({"a": np.arange(200.0)})
    mask_native = _native_df({"m": np.arange(200) % 2 == 0})["m"]
    out = big[mask_native]
    pdf = pandas.DataFrame({"a": np.arange(200.0)})
    df_equals(out, pdf[np.arange(200) % 2 == 0])
