"""graftcost acceptance: static cost capture, padding-waste accounting,
roofline join, and the zero-overhead-when-off contract.

Acceptance bar (ISSUE 8): cost capture degrades gracefully (a backend
returning None / empty / key-less analyses yields "unknown", never a
crash); a forced-Device groupby at two bucket sizes reports DIFFERENT
padding-waste numbers (the accounting sees real padding, not a constant);
``explain(analyze=True)`` renders per-node estimated flops/bytes, padding
share, and roofline fraction; the disabled mode (``MODIN_TPU_METERS=0`` /
``MODIN_TPU_TRACE=0``) stays zero-allocation with cost capture compiled
in; and the Chrome-trace export carries the two new counter tracks.
"""

import numpy as np
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import BenchmarkMode, CostCapture, MetersEnabled, TraceEnabled
from modin_tpu.observability import costs, meters, spans
from modin_tpu.observability.chrome_trace import COUNTER_TRACKS, to_chrome_trace


@pytest.fixture(autouse=True)
def _clean_cost_state():
    """Every test starts and ends with meters off, Auto capture, and empty
    cost/meter state; BenchmarkMode (some tests force sync timing) is
    restored so the leak cannot slow every later suite down."""
    bench_before = BenchmarkMode.get()
    MetersEnabled.put(False)
    CostCapture.put("Auto")
    meters.reset()
    costs.reset()
    yield
    MetersEnabled.put(False)
    CostCapture.put("Auto")
    BenchmarkMode.put(bench_before)
    meters.reset()
    costs.reset()


def _require_tpu_on_jax():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("device cost capture requires the TpuOnJax execution")


# ====================================================================== #
# graceful degradation: the backend may answer with anything
# ====================================================================== #


class TestExtractGracefulDegradation:
    @pytest.mark.parametrize("raw", [None, {}, [], (), [[]], "nonsense", 0])
    def test_cost_analysis_junk_yields_unknown(self, raw):
        out = costs.extract_cost(raw)
        assert out == {
            "flops": "unknown",
            "bytes_accessed": "unknown",
            "transcendentals": "unknown",
        }

    def test_cost_analysis_dict_form(self):
        out = costs.extract_cost({"flops": 12.0, "bytes accessed": 96})
        assert out["flops"] == 12.0
        assert out["bytes_accessed"] == 96.0
        assert out["transcendentals"] == "unknown"

    def test_cost_analysis_list_form_and_missing_keys(self):
        out = costs.extract_cost([{"transcendentals": 3.0}])
        assert out["flops"] == "unknown"
        assert out["bytes_accessed"] == "unknown"
        assert out["transcendentals"] == 3.0

    def test_cost_analysis_negative_values_are_unknown(self):
        out = costs.extract_cost({"flops": -1.0})
        assert out["flops"] == "unknown"

    def test_memory_analysis_none_and_attrless(self):
        for stats in (None, object()):
            out = costs.extract_memory(stats)
            assert set(out) == {
                "argument_bytes", "output_bytes", "temp_bytes", "peak_bytes",
            }
            assert all(v == "unknown" for v in out.values())

    def test_memory_analysis_component_sum_fallback(self):
        class Stats:
            argument_size_in_bytes = 100
            output_size_in_bytes = 10
            temp_size_in_bytes = 5

        out = costs.extract_memory(Stats())
        assert out["peak_bytes"] == 115.0

    def test_memory_analysis_explicit_peak_wins(self):
        class Stats:
            argument_size_in_bytes = 100
            output_size_in_bytes = 10
            temp_size_in_bytes = 5
            peak_memory_in_bytes = 512

        assert costs.extract_memory(Stats())["peak_bytes"] == 512.0

    def test_capture_static_on_unlowerable_func(self):
        # a plain callable has no .lower: capture declines to unknown
        out = costs.capture_static(lambda x: x, (1,), None)
        assert out["flops"] == "unknown"

    def test_capture_static_on_raising_lower(self):
        class Evil:
            def lower(self, *a, **k):
                raise RuntimeError("no AOT for you")

        out = costs.capture_static(Evil(), (), None)
        assert out["flops"] == "unknown"

    def test_merge_known_never_clobbers_with_unknown(self):
        # Full-mode regression: a compiled analysis that cannot answer a
        # field must not erase the lowered analysis's answer
        cost = {"flops": 10.0, "bytes_accessed": 20.0}
        costs._merge_known(cost, costs.extract_cost(None))
        assert cost["flops"] == 10.0 and cost["bytes_accessed"] == 20.0
        costs._merge_known(cost, {"flops": 99.0, "bytes_accessed": "unknown"})
        assert cost["flops"] == 99.0 and cost["bytes_accessed"] == 20.0

    def test_arg_key_sees_numpy_shapes_and_kwargs(self):
        a = np.zeros(4)
        b = np.zeros(8)
        assert costs._arg_key((a,), None) != costs._arg_key((b,), None)
        assert costs._arg_key((a,), {"k": 1}) != costs._arg_key((a,), {"k": 2})
        assert costs._arg_key((a,), {"k": 1}) == costs._arg_key((a,), {"k": 1})

    def test_ledger_keeps_unknowns_and_never_raises(self):
        ledger = costs.CostLedger()
        ledger.record_capture("sig", dict(costs._UNKNOWN_COST))
        ledger.record_dispatch("sig", 0.01)
        eff = ledger.efficiency("sig")
        assert eff["achieved_flops_per_s"] == "unknown"
        assert eff["achieved_bytes_per_s"] == "unknown"
        assert eff["roofline_fraction"] == "unknown"
        assert ledger.efficiency("never-dispatched") is None


# ====================================================================== #
# the capture seam (deploy) + warm re-billing
# ====================================================================== #


class TestCaptureSeam:
    def test_cold_dispatch_captures_and_warm_rebills(self):
        _require_tpu_on_jax()
        BenchmarkMode.put(True)
        values = np.arange(4096.0)

        def workload():
            df = pd.DataFrame({"a": values, "b": values[::-1].copy()})
            out = (df["a"] * 2.0 + df["b"]).sum()
            _ = out.modin.to_pandas() if hasattr(out, "modin") else float(out)

        with meters.query_stats("cold") as cold:
            workload()
        assert cold.dispatches >= 1
        assert cold.est_flops > 0, "cold dispatch captured no flop estimate"
        assert cold.est_bytes > 0
        # same shapes again: no compile fires, the memoized cost re-bills
        with meters.query_stats("warm") as warm:
            workload()
        assert warm.compiles == 0, "expected a fully warm run"
        assert warm.est_flops > 0, "warm dispatch did not re-bill costs"
        snap = meters.snapshot()  # meters off: registry untouched is fine
        ledger = costs.get_cost_ledger().snapshot()
        assert ledger["signatures"], "cost ledger recorded nothing"
        assert snap is not None

    def test_registry_series_under_meters(self):
        _require_tpu_on_jax()
        BenchmarkMode.put(True)
        MetersEnabled.put(True)
        meters.reset()
        costs.reset()
        df = pd.DataFrame({"a": np.arange(2048.0)})
        out = (df["a"] + 1.0).sum()
        _ = out.modin.to_pandas() if hasattr(out, "modin") else float(out)
        series = meters.snapshot()["series"]
        assert series.get("engine.cost.flops", {}).get("total", 0) > 0
        assert series.get("engine.cost.bytes", {}).get("total", 0) > 0


# ====================================================================== #
# padding-waste accounting
# ====================================================================== #


class TestPaddingAccounting:
    def test_note_padding_rolls_into_query_stats(self):
        with meters.query_stats("q") as qs:
            costs.note_padding("unit.test", 1000, 800)
            costs.note_padding("unit.test", 24, 24)
        assert qs.padded_bytes == 1024
        assert qs.padding_waste_bytes == 200
        d = qs.as_dict()
        assert d["padded_bytes"] == 1024
        assert d["padding_waste_bytes"] == 200
        assert "padding waste: 200 of 1024" in qs.summary()
        per_site = costs.get_cost_ledger().snapshot()["padding"]["unit.test"]
        assert per_site == {
            "events": 2, "padded_bytes": 1024, "waste_bytes": 200,
        }

    def test_note_padding_clamps_negative_waste(self):
        with meters.query_stats("q") as qs:
            costs.note_padding("unit.clamp", 10, 99)
        assert qs.padding_waste_bytes == 0

    def test_forced_device_groupby_two_bucket_sizes_differ(self):
        """The acceptance proof that the accounting sees REAL padding: the
        same rows grouped into 3 vs 61 groups pad their output buckets to
        different shard multiples, so the two runs must report different
        (and nonzero) padding-waste numbers."""
        _require_tpu_on_jax()
        BenchmarkMode.put(True)
        n = 4096
        rng = np.random.default_rng(3)
        values = rng.random(n)

        def grouped_sum(num_groups):
            df = pd.DataFrame(
                {
                    "k": rng.integers(0, num_groups, n),
                    "v": values,
                }
            )
            df._query_compiler.execute()
            with meters.query_stats(f"gb{num_groups}") as qs:
                out = df.groupby("k").sum()
                out._query_compiler.execute()
            return qs

        small = grouped_sum(3)
        large = grouped_sum(61)
        assert small.padded_bytes > 0 and large.padded_bytes > 0
        assert small.padding_waste_bytes > 0
        assert large.padding_waste_bytes > 0
        assert small.padding_waste_bytes != large.padding_waste_bytes, (
            "two bucket sizes reported identical padding waste — the "
            "accounting is not seeing the real group-bucket padding"
        )
        sites = costs.get_cost_ledger().snapshot()["padding"]
        assert "groupby.reduce.groups" in sites

    def test_sort_padding_site_reports(self):
        _require_tpu_on_jax()
        BenchmarkMode.put(True)
        # 100 rows pad to the 8-shard multiple of 104: lexsort must see it
        df = pd.DataFrame({"a": np.random.default_rng(0).random(100)})
        df._query_compiler.execute()
        with meters.query_stats("sort"):
            out = df.sort_values("a")
            out._query_compiler.execute()
        sites = costs.get_cost_ledger().snapshot()["padding"]
        assert sites.get("sort.lexsort", {}).get("waste_bytes", 0) > 0


# ====================================================================== #
# zero-overhead-when-off (re-asserted with cost capture compiled in)
# ====================================================================== #


class TestDisabledMode:
    def test_off_means_off_and_allocates_nothing(self):
        _require_tpu_on_jax()
        df = pd.DataFrame({"a": np.arange(64.0), "b": np.arange(64.0)})
        _ = (df + 1).sum().modin.to_pandas()  # warm every code path
        assert not costs.COST_ON
        meter_alloc = meters.meter_alloc_count()
        span_alloc = spans.span_alloc_count()
        # the per-thread counters are monotonic for the process lifetime;
        # the disabled-mode contract is that they do not MOVE
        cost_before = costs.thread_cost()
        pad_before = costs.thread_padding()
        df2 = pd.DataFrame({"a": np.arange(64.0), "b": np.arange(64.0)})
        _ = (df2 * 2).sum().modin.to_pandas()
        _ = df2.shape
        assert meters.meter_alloc_count() == meter_alloc
        assert spans.span_alloc_count() == span_alloc
        assert costs.thread_cost() == cost_before
        assert costs.thread_padding() == pad_before
        snap = costs.get_cost_ledger().snapshot()
        assert not snap["signatures"] and not snap["padding"]
        assert costs.counter_sample() == (0, 0)

    def test_mode_off_wins_over_accounting(self):
        CostCapture.put("Off")
        MetersEnabled.put(True)
        assert meters.ACCOUNTING_ON and not costs.COST_ON
        with meters.query_stats("q"):
            assert not costs.COST_ON

    def test_mode_on_without_accounting(self):
        CostCapture.put("On")
        assert costs.COST_ON and not meters.ACCOUNTING_ON

    def test_auto_follows_query_stats_scope(self):
        assert not costs.COST_ON
        with meters.query_stats("q"):
            assert costs.COST_ON
        assert not costs.COST_ON


# ====================================================================== #
# roofline
# ====================================================================== #


class TestRoofline:
    def test_substrate_peaks_answer_on_cpu(self):
        peaks = costs.substrate_peaks()
        assert peaks is not None
        assert peaks["flops_per_s"] > 0 and peaks["bytes_per_s"] > 0

    def test_fraction_bounds_and_unknowns(self):
        assert costs.roofline_fraction(1e6, 1e6, 0.0) is None
        assert costs.roofline_fraction(None, None, 1.0) is None
        fraction = costs.roofline_fraction(1e6, 8e6, 1.0)
        assert fraction is not None and 0 < fraction < 1

    def test_pure_movement_uses_bandwidth_roof(self):
        peaks = costs.substrate_peaks()
        fraction = costs.roofline_fraction(None, peaks["bytes_per_s"], 1.0)
        assert fraction == pytest.approx(1.0)


# ====================================================================== #
# EXPLAIN ANALYZE per-node rendering
# ====================================================================== #


class TestExplainAnalyzeCost:
    def test_nodes_render_cost_padding_and_roofline(self, tmp_path):
        _require_tpu_on_jax()
        from modin_tpu.config import PlanMode

        if PlanMode.get() == "Off":
            pytest.skip("needs deferred planning")
        path = tmp_path / "costs.csv"
        rng = np.random.default_rng(5)
        import pandas as pandas_mod

        pandas_mod.DataFrame(
            {
                "a": rng.integers(-50, 50, 500),
                "b": rng.uniform(0, 1, 500),
                "c": rng.uniform(-1, 1, 500),
            }
        ).to_csv(path, index=False)
        md = pd.read_csv(str(path))
        if md._query_compiler._plan is None:
            pytest.skip("read did not defer")
        analyzed = md.query("a > 0")[["b"]].modin.explain(analyze=True)
        assert "status: analyzed" in analyzed
        node_lines = [
            ln for ln in analyzed.splitlines()
            if "(actual:" in ln
        ]
        assert node_lines
        for field in ("est_flops=", "est_bytes=", "padding=", "roofline="):
            assert all(field in ln for ln in node_lines), (
                f"annotation missing {field!r}: {node_lines}"
            )
        assert "est cost:" in analyzed  # the rollup block's cost line


# ====================================================================== #
# Chrome-trace counter tracks (satellite)
# ====================================================================== #


class TestCostCounterTracks:
    def test_new_tracks_declared(self):
        assert "engine.cost.padding_waste_bytes" in COUNTER_TRACKS
        assert "engine.cost.achieved_bw_bytes_s" in COUNTER_TRACKS

    def test_samples_render_as_counter_events(self):
        samples = [(10.0, (100, 50, 2, 4096, 1_000_000))]
        trace = to_chrome_trace([], counters=samples)
        counter_events = [
            e for e in trace["traceEvents"] if e.get("ph") == "C"
        ]
        by_name = {e["name"]: e["args"]["value"] for e in counter_events}
        assert by_name["engine.cost.padding_waste_bytes"] == 4096
        assert by_name["engine.cost.achieved_bw_bytes_s"] == 1_000_000

    def test_short_legacy_samples_omit_new_tracks(self):
        trace = to_chrome_trace([], counters=[(1.0, (1, 2, 3))])
        names = {
            e["name"] for e in trace["traceEvents"] if e.get("ph") == "C"
        }
        assert "engine.cost.padding_waste_bytes" not in names

    def test_profile_export_carries_padding_track(self):
        _require_tpu_on_jax()
        BenchmarkMode.put(True)
        MetersEnabled.put(True)  # Auto capture on -> padding accumulates
        import modin_tpu.observability as graftscope

        prev = TraceEnabled.get()
        try:
            with graftscope.profile() as prof:
                df = pd.DataFrame({"a": np.random.default_rng(1).random(100)})
                out = df.sort_values("a")
                out._query_compiler.execute()
            trace = prof.to_chrome_trace()
        finally:
            TraceEnabled.put(prev)
        pad_events = [
            e
            for e in trace["traceEvents"]
            if e.get("ph") == "C"
            and e["name"] == "engine.cost.padding_waste_bytes"
        ]
        assert pad_events, "no padding-waste counter track in the export"
        assert any(e["args"]["value"] > 0 for e in pad_events)
