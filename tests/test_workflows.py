"""End-to-end workflow stress tests.

Counterpart of the reference's stress suite (stress_tests/test_kaggle_ipynb.py
— real notebook pipelines run against both implementations) and the fuzzydata
random-workflow harness (modin/experimental/fuzzydata).
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import df_equals


def make_taxi_like(tmp_path, n=20_000):
    """A mixed-dtype dataset shaped like the NYC-taxi workload."""
    rng = np.random.default_rng(99)
    pdf = pandas.DataFrame(
        {
            "vendor": rng.choice(["A", "B", "C"], n),
            "passengers": rng.integers(1, 7, n),
            "distance": rng.gamma(2.0, 2.0, n).round(2),
            "fare": rng.gamma(3.0, 5.0, n).round(2),
            "tip": rng.uniform(0, 20, n).round(2),
            "pickup": pandas.to_datetime("2024-01-01")
            + pandas.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
            "payment": rng.choice(["card", "cash"], n),
        }
    )
    path = tmp_path / "taxi.csv"
    pdf.to_csv(path, index=False)
    return str(path)


class TestTaxiWorkflow:
    """read_csv -> derive -> filter -> groupby -> merge -> sort, both impls."""

    def test_full_pipeline(self, tmp_path):
        path = make_taxi_like(tmp_path)

        def pipeline(lib, read_csv):
            df = read_csv(path, parse_dates=["pickup"])
            df["total"] = df["fare"] + df["tip"]
            df["tip_pct"] = df["tip"] / df["fare"].clip(lower=0.01)
            busy = df[df["passengers"] >= 2]
            by_vendor = busy.groupby("vendor", as_index=False).agg(
                {"total": "sum", "distance": "mean", "tip_pct": "mean"}
            )
            lookup = lib.DataFrame(
                {"vendor": ["A", "B", "C"], "fleet": [120, 80, 45]}
            )
            joined = by_vendor.merge(lookup, on="vendor")
            joined["per_cab"] = joined["total"] / joined["fleet"]
            return joined.sort_values("per_cab", ascending=False, kind="stable")

        got = pipeline(pd, pd.read_csv)
        want = pipeline(pandas, pandas.read_csv)
        df_equals(got, want)

    def test_datetime_features(self, tmp_path):
        path = make_taxi_like(tmp_path)
        md = pd.read_csv(path, parse_dates=["pickup"])
        pdf = pandas.read_csv(path, parse_dates=["pickup"])
        md["hour"] = md["pickup"].dt.hour
        pdf["hour"] = pdf["pickup"].dt.hour
        df_equals(
            md.groupby("hour")["fare"].mean(), pdf.groupby("hour")["fare"].mean()
        )

    def test_value_counts_and_describe(self, tmp_path):
        path = make_taxi_like(tmp_path)
        md = pd.read_csv(path)
        pdf = pandas.read_csv(path)
        df_equals(md["payment"].value_counts(), pdf["payment"].value_counts())
        df_equals(md.describe(), pdf.describe())


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_random_workflow(seed):
    """fuzzydata-style: a random op chain must match pandas step by step."""
    from modin_tpu.experimental.fuzzydata import run_workflow

    trace = run_workflow(seed=seed, steps=8)
    assert len(trace) == 8
