"""End-to-end workflow stress tests.

Counterpart of the reference's stress suite (stress_tests/test_kaggle_ipynb.py
— real notebook pipelines run against both implementations) and the fuzzydata
random-workflow harness (modin/experimental/fuzzydata).
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import df_equals


def make_taxi_like(tmp_path, n=20_000):
    """A mixed-dtype dataset shaped like the NYC-taxi workload."""
    rng = np.random.default_rng(99)
    pdf = pandas.DataFrame(
        {
            "vendor": rng.choice(["A", "B", "C"], n),
            "passengers": rng.integers(1, 7, n),
            "distance": rng.gamma(2.0, 2.0, n).round(2),
            "fare": rng.gamma(3.0, 5.0, n).round(2),
            "tip": rng.uniform(0, 20, n).round(2),
            "pickup": pandas.to_datetime("2024-01-01")
            + pandas.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
            "payment": rng.choice(["card", "cash"], n),
        }
    )
    path = tmp_path / "taxi.csv"
    pdf.to_csv(path, index=False)
    return str(path)


class TestTaxiWorkflow:
    """read_csv -> derive -> filter -> groupby -> merge -> sort, both impls."""

    def test_full_pipeline(self, tmp_path):
        path = make_taxi_like(tmp_path)

        def pipeline(lib, read_csv):
            df = read_csv(path, parse_dates=["pickup"])
            df["total"] = df["fare"] + df["tip"]
            df["tip_pct"] = df["tip"] / df["fare"].clip(lower=0.01)
            busy = df[df["passengers"] >= 2]
            by_vendor = busy.groupby("vendor", as_index=False).agg(
                {"total": "sum", "distance": "mean", "tip_pct": "mean"}
            )
            lookup = lib.DataFrame(
                {"vendor": ["A", "B", "C"], "fleet": [120, 80, 45]}
            )
            joined = by_vendor.merge(lookup, on="vendor")
            joined["per_cab"] = joined["total"] / joined["fleet"]
            return joined.sort_values("per_cab", ascending=False, kind="stable")

        got = pipeline(pd, pd.read_csv)
        want = pipeline(pandas, pandas.read_csv)
        df_equals(got, want)

    def test_datetime_features(self, tmp_path):
        path = make_taxi_like(tmp_path)
        md = pd.read_csv(path, parse_dates=["pickup"])
        pdf = pandas.read_csv(path, parse_dates=["pickup"])
        md["hour"] = md["pickup"].dt.hour
        pdf["hour"] = pdf["pickup"].dt.hour
        df_equals(
            md.groupby("hour")["fare"].mean(), pdf.groupby("hour")["fare"].mean()
        )

    def test_value_counts_and_describe(self, tmp_path):
        path = make_taxi_like(tmp_path)
        md = pd.read_csv(path)
        pdf = pandas.read_csv(path)
        df_equals(md["payment"].value_counts(), pdf["payment"].value_counts())
        df_equals(md.describe(), pdf.describe())


OPS = [
    ("head", lambda df, rng: df.head(max(1, len(df) // 2))),
    ("filter", lambda df, rng: df[df[df.columns[0]] > df[df.columns[0]].mean()]
        if df.dtypes.iloc[0].kind in "if" and len(df) else df),
    ("sort", lambda df, rng: df.sort_values(df.columns[-1], kind="stable")),
    ("fillna", lambda df, rng: df.fillna(0)),
    ("add", lambda df, rng: df + 1 if all(d.kind in "if" for d in df.dtypes) else df),
    ("abs", lambda df, rng: df.abs() if all(d.kind in "if" for d in df.dtypes) else df),
    ("reset", lambda df, rng: df.reset_index(drop=True)),
    ("sample_cols", lambda df, rng: df[list(rng.choice(df.columns, size=max(1, len(df.columns) - 1), replace=False))]),
    ("cumsum", lambda df, rng: df.cumsum() if all(d.kind == "i" for d in df.dtypes) else df),
    ("rename", lambda df, rng: df.rename(columns={df.columns[0]: "renamed0"})),
]


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_random_workflow(seed):
    """fuzzydata-style: a random op chain must match pandas step by step."""
    rng = np.random.default_rng(seed)
    data = {
        "i0": rng.integers(-100, 100, 120),
        "f0": np.where(rng.random(120) < 0.15, np.nan, rng.uniform(-5, 5, 120)),
        "f1": rng.uniform(0, 1, 120),
    }
    md = pd.DataFrame(data)
    pdf = pandas.DataFrame(data)
    trace = []
    for step in range(8):
        name, op = OPS[int(rng.integers(0, len(OPS)))]
        trace.append(name)
        op_seed = int(rng.integers(0, 2**32))
        md = op(md, np.random.default_rng(op_seed))
        pdf = op(pdf, np.random.default_rng(op_seed))
        try:
            df_equals(md, pdf)
        except AssertionError as err:
            raise AssertionError(f"diverged after {trace}: {err}") from err
