"""graftguard chaos suite: lineage recovery + device-memory admission.

Acceptance bar (ISSUE 4): an injected mid-query ``DeviceLost`` recovers via
lineage (bit-exact vs the fault-free run), an injected RESOURCE_EXHAUSTED
burst is absorbed by evict-then-retry without falling back to pandas, and
the admission controller spills cold columns *before* an over-budget
dispatch.  Unit layers below the chaos scenarios: lineage attachment kinds,
spill/restore round-trips, depth cut-points, and the sequenced injectors.
"""

import gc

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import (
    DeviceMemoryBudget,
    LineageMaxDepth,
    RecoveryMode,
    ResilienceBackoffS,
    ResilienceBreakerThreshold,
    ResilienceMode,
    ResilienceRetries,
    SpillRetries,
    SpillTargetFraction,
)
from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn
from modin_tpu.core.execution import recovery, resilience
from modin_tpu.core.execution.resilience import (
    DeviceOOM,
    engine_call,
    reset_breakers,
)
from modin_tpu.core.memory import device_ledger, device_resident_bytes
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.testing import (
    OomBurstInjector,
    SequencedFaultInjector,
    inject_faults,
    make_device_error,
    midquery_device_loss,
    oom_burst_until_eviction,
)

from tests.utils import df_equals

_SAVED_PARAMS = (
    RecoveryMode,
    ResilienceMode,
    ResilienceRetries,
    ResilienceBackoffS,
    ResilienceBreakerThreshold,
    LineageMaxDepth,
    SpillRetries,
    SpillTargetFraction,
)


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("graftguard chaos tests require the TpuOnJax execution")


@pytest.fixture(autouse=True)
def _clean_recovery_state():
    """Recovery on, fresh breakers/epoch, zero backoff, knobs restored."""
    saved = [(p, p.get()) for p in _SAVED_PARAMS]
    reset_breakers()
    recovery.reset_for_tests()
    ResilienceBackoffS.put(0.0)
    RecoveryMode.put("Enable")
    yield
    for p, v in saved:
        p.put(v)
    reset_breakers()
    recovery.reset_for_tests()


@pytest.fixture
def metrics():
    seen = []

    def handler(name, value):
        seen.append((name, value))

    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


def _names(metrics):
    return [n for n, _ in metrics]


_N = 512


def _frames(seed=0):
    rng = np.random.default_rng(seed)
    data = {
        "a": rng.normal(size=_N),
        "b": rng.integers(0, 1000, _N).astype(np.int64),
        "key": rng.integers(0, 7, _N).astype(np.int64),
    }
    pdf = pandas.DataFrame(data)
    mdf = pd.DataFrame(data)
    mdf._query_compiler.execute()  # ingest outside any fault window
    return mdf, pdf


def _col(values):
    return DeviceColumn.from_numpy(np.asarray(values))


# ====================================================================== #
# lineage records
# ====================================================================== #


class TestLineageAttachment:
    def test_host_materialization_kind(self):
        col = _col(np.arange(32, dtype=np.int64))
        assert col.lineage is not None
        assert col.lineage.kind == recovery.KIND_HOST

    def test_op_replay_kind_for_deployed_output(self):
        import jax

        from modin_tpu.parallel.engine import JaxWrapper

        base = _col(np.arange(64, dtype=np.int64))
        out = JaxWrapper.deploy(jax.jit(lambda x: x * 2), (base.raw,))
        col = DeviceColumn(out, np.dtype(np.int64), length=64)
        assert col.lineage.kind == recovery.KIND_OP
        assert col.lineage.depth == 1

    def test_lazy_column_gets_lineage_on_materialization(self):
        mdf, _ = _frames(seed=3)
        result = mdf["a"] + mdf["b"]
        qc = result._query_compiler
        frame = qc._modin_frame
        frame.materialize_device()
        cols = [c for c in frame._columns if c.is_device]
        assert cols and all(c.lineage is not None for c in cols)

    def test_depth_cut_point_host_checkpoints(self, metrics):
        import jax

        from modin_tpu.parallel.engine import JaxWrapper

        LineageMaxDepth.put(2)
        fn = jax.jit(lambda x: x + 1)
        arr = _col(np.arange(64, dtype=np.int64)).raw
        kinds = []
        for _ in range(4):
            arr = JaxWrapper.deploy(fn, (arr,))
            col = DeviceColumn(arr, np.dtype(np.int64), length=64)
            kinds.append(col.lineage.kind)
            arr = col.raw
        # chain depths 1,2 stay op-replay; 3 would exceed the bound and is
        # host-checkpointed — which restarts the chain, so the NEXT link
        # is op-replay at depth 1 again
        assert kinds == [
            recovery.KIND_OP,
            recovery.KIND_OP,
            recovery.KIND_HOST,
            recovery.KIND_OP,
        ]
        assert "modin_tpu.recovery.checkpoint_cut" in _names(metrics)

    def test_io_source_lineage_from_read(self, tmp_path):
        path = tmp_path / "lineage.csv"
        src = pandas.DataFrame(
            {"x": np.arange(100, dtype=np.int64), "y": np.linspace(0, 1, 100)}
        )
        src.to_csv(path, index=False)
        mdf = pd.read_csv(path)
        frame = mdf._query_compiler._modin_frame
        device_cols = [c for c in frame._columns if c.is_device]
        assert device_cols
        assert all(c.lineage.kind == recovery.KIND_IO for c in device_cols)
        # the io record can rebuild the exact values even with the host
        # cache gone (evicted under the Memory budget)
        col = device_cols[0]
        expected = col.to_numpy().copy()
        col.host_cache = None
        kind = recovery.recover_column(col, force=True)
        assert kind == recovery.KIND_IO
        assert np.array_equal(col.to_numpy(), expected)


# ====================================================================== #
# re-seat from lineage
# ====================================================================== #


class TestReseat:
    def test_reseat_all_is_bit_exact(self, metrics):
        values = np.random.default_rng(5).normal(size=256)
        col = _col(values)
        old = col._data
        assert recovery.reseat_all("unit") >= 1
        assert col._data is not old  # a genuinely fresh buffer
        assert np.array_equal(col.to_numpy(), values)
        assert "modin_tpu.recovery.reseat.host" in _names(metrics)

    def test_op_replay_reseat_without_host_cache(self):
        import jax

        from modin_tpu.parallel.engine import JaxWrapper

        base = _col(np.arange(128, dtype=np.int64))
        out = JaxWrapper.deploy(jax.jit(lambda x: x * 3 + 1), (base.raw,))
        col = DeviceColumn(out, np.dtype(np.int64), length=128)
        assert col.host_cache is None
        old = col._data
        kind = recovery.recover_column(col, force=True)
        assert kind == recovery.KIND_OP
        assert col._data is not old
        assert np.array_equal(
            col.to_numpy()[:128], np.arange(128, dtype=np.int64) * 3 + 1
        )

    def test_unrecoverable_without_lineage(self, metrics):
        import jax.numpy as jnp

        from modin_tpu.ops.structural import pad_host

        RecoveryMode.put("Disable")  # adopt a buffer with no provenance
        arr = jnp.asarray(pad_host(np.arange(32, dtype=np.int64)))
        col = DeviceColumn(arr, np.dtype(np.int64), length=32)
        RecoveryMode.put("Enable")
        with pytest.raises(recovery.Unrecoverable):
            recovery.recover_column(col, force=True)

    def test_recovery_disabled_is_noop(self):
        RecoveryMode.put("Disable")
        _col(np.arange(8))
        assert recovery.reseat_all("unit") == 0


# ====================================================================== #
# chaos: mid-query DeviceLost
# ====================================================================== #


class TestMidQueryDeviceLost:
    def test_groupby_merge_recovers_bit_exact(self, metrics):
        mdf, pdf = _frames(seed=11)
        expected = pdf.groupby("key").sum().merge(
            pdf.groupby("key").mean(), on="key", suffixes=("_s", "_m")
        )
        with midquery_device_loss(
            after_deploys=2, times=1, ops=("deploy", "materialize")
        ) as inj:
            got = mdf.groupby("key").sum().merge(
                mdf.groupby("key").mean(), on="key", suffixes=("_s", "_m")
            )
            df_equals(got, expected)
        assert inj.injected == 1, "the loss never fired mid-query"
        names = _names(metrics)
        assert "modin_tpu.recovery.device_lost" in names
        assert any(n.startswith("modin_tpu.recovery.reseat.") for n in names)

    def test_retry_after_reseat_absorbs_the_loss(self, metrics):
        """When the engine retry after a re-seat succeeds, the device path
        answers — no pandas fallback at all."""
        ResilienceBreakerThreshold.put(50)
        mdf, pdf = _frames(seed=13)
        # an elementwise chain materializes through ONE fused deploy: the
        # loss lands exactly on it, the re-seat + retry answer on device
        with midquery_device_loss(after_deploys=0, times=1) as inj:
            df_equals(mdf["a"] * 2 + mdf["b"], pdf["a"] * 2 + pdf["b"])
        assert inj.injected == 1
        names = _names(metrics)
        assert "modin_tpu.recovery.retry.device_lost" in names
        assert not any(".fallback." in n for n in names)

    def test_sequenced_losses_across_phases(self, metrics):
        """Two separate loss windows in one query sequence: each recovers."""
        mdf, pdf = _frames(seed=17)
        with SequencedFaultInjector(
            [("clean", 1), ("device_lost", 1), ("clean", 2), ("device_lost", 1)],
            ops=("deploy", "materialize"),
        ) as inj:
            df_equals(mdf.sum(numeric_only=True), pdf.sum(numeric_only=True))
            df_equals(
                mdf.groupby("key").sum(), pdf.groupby("key").sum()
            )
        assert inj.injected >= 1
        assert "modin_tpu.recovery.device_lost" in _names(metrics)


# ====================================================================== #
# chaos: RESOURCE_EXHAUSTED absorbed by evict-then-retry
# ====================================================================== #


class TestOomEvictThenRetry:
    def test_engine_call_evicts_and_retries(self, metrics):
        # something spillable must be resident (kept referenced so the
        # evictor has at least this column to free)
        col = _col(np.random.default_rng(0).normal(size=4096))
        spills_before = device_ledger.spill_count()
        with oom_burst_until_eviction(ops=("deploy",)) as inj:
            result = engine_call("deploy", lambda: "computed")
        assert result == "computed"
        assert inj.injected >= 1
        assert device_ledger.spill_count() > spills_before
        names = _names(metrics)
        assert "modin_tpu.recovery.retry.oom" in names
        assert "modin_tpu.memory.device.spill" in names
        assert np.array_equal(col.to_numpy(), col.to_numpy())  # still readable

    def test_query_absorbs_burst_without_fallback(self, metrics):
        ResilienceBreakerThreshold.put(50)
        mdf, pdf = _frames(seed=23)
        # cold ballast the evictor can spill (the query's own inputs would
        # not free anything mid-dispatch)
        ballast_values = np.random.default_rng(1).normal(size=8192)
        ballast = _col(ballast_values)
        with oom_burst_until_eviction(
            ops=("deploy", "materialize")
        ) as inj:
            df_equals(
                (mdf["a"] * 2 + mdf["b"]).sum(), (pdf["a"] * 2 + pdf["b"]).sum()
            )
        assert inj.injected >= 1
        names = _names(metrics)
        assert "modin_tpu.recovery.retry.oom" in names
        assert not any(".fallback." in n for n in names)
        assert np.array_equal(ballast.to_numpy(), ballast_values)  # exact

    def test_spill_retries_zero_keeps_oom_terminal(self, metrics):
        SpillRetries.put(0)
        _col(np.arange(1024, dtype=np.float64))

        def oom():
            raise make_device_error("oom")

        with pytest.raises(DeviceOOM):
            engine_call("deploy", oom)
        assert "modin_tpu.recovery.retry.oom" not in _names(metrics)


# ====================================================================== #
# admission control & the device ledger
# ====================================================================== #


class TestAdmissionControl:
    def test_deploy_spills_cold_columns_before_dispatch(self, metrics):
        import jax

        from modin_tpu.parallel.engine import JaxWrapper

        cold = _col(np.arange(20_000, dtype=np.int64))  # 160 KB, coldest
        hot = _col(np.arange(20_000, dtype=np.int64))
        with DeviceMemoryBudget.context(device_resident_bytes() + 8_000):
            # projected output (~160 KB) overflows: admission must spill
            # the cold column but never the op's own input
            out = JaxWrapper.deploy(jax.jit(lambda x: x + 1), (hot.raw,))
            assert cold.is_spilled
            assert not hot.is_spilled
            assert "modin_tpu.memory.device.spill" in _names(metrics)
            assert np.array_equal(
                np.asarray(out)[:20_000], np.arange(20_000) + 1
            )
        # a host read is served straight from the exact host copy ...
        assert np.array_equal(cold.to_numpy(), np.arange(20_000))
        assert "modin_tpu.memory.device.restore" not in _names(metrics)
        # ... and the next DEVICE access transparently re-seats the buffer
        assert cold.raw is not None
        assert not cold.is_spilled
        assert "modin_tpu.memory.device.restore" in _names(metrics)

    def test_ledger_tracks_registration_and_death(self):
        before = device_resident_bytes()
        col = _col(np.arange(4096, dtype=np.int64))
        assert device_resident_bytes() > before
        del col
        gc.collect()
        assert device_resident_bytes() <= before + 1  # entry died with it

    def test_spill_restore_roundtrip_float64_downcast(self):
        from modin_tpu.config import Float64Policy

        with Float64Policy.context("Downcast"):
            values = np.random.default_rng(2).normal(size=512)
            col = _col(values)
            assert str(col.raw.dtype) == "float32"
            col.host_cache = None  # drop the ingest cache: spill must fetch
            assert col.spill() > 0
            # the fetched host copy widened losslessly; restore downcasts
            # back to the identical f32 buffer
            assert np.array_equal(
                col.to_numpy(), values.astype(np.float32).astype(np.float64)
            )
            assert str(col.raw.dtype) == "float32"


# ====================================================================== #
# review regressions: spill safety, input protection, arg rebind, io purge
# ====================================================================== #


class TestRecoveryEdges:
    def test_spill_under_tight_host_budget_keeps_sole_copy(self, monkeypatch):
        """Registering the fetched host copy must not let the host ledger
        evict it before the device buffer is dropped (the copy is the SOLE
        copy the moment spill completes)."""
        from modin_tpu.core.memory import _HostCacheLedger

        monkeypatch.setattr(_HostCacheLedger, "budget", lambda self: 1)
        values = np.arange(1024, dtype=np.int64)
        col = _col(values)
        col.host_cache = None  # spill must fetch, register, and survive
        assert col.spill() > 0
        assert col.host_cache is not None
        assert np.array_equal(col.to_numpy(), values)

    def test_evict_for_oom_protects_op_inputs(self):
        cold = _col(np.arange(4096, dtype=np.int64))
        hot = _col(np.arange(4096, dtype=np.int64))
        SpillTargetFraction.put(1.0)
        freed = recovery.evict_for_oom("deploy", exclude_ids={id(hot._data)})
        assert freed > 0
        assert cold.is_spilled
        assert not hot.is_spilled

    def test_recover_args_rebinds_to_reseated_buffers(self):
        """After a re-seat the old arrays are stale; recover_args must hand
        back the columns' fresh buffers for a re-dispatch."""
        values = np.arange(256, dtype=np.int64)
        col = _col(values)
        old = col._data
        assert recovery.reseat_all("unit") >= 1
        fresh_args = recovery.recover_args(((old,), 2.0))
        assert fresh_args is not None
        (leaf,), scalar = fresh_args
        assert scalar == 2.0
        assert leaf is col._data and leaf is not old

    def test_io_replay_cache_purged_after_pass(self, tmp_path):
        path = tmp_path / "purge.csv"
        pandas.DataFrame({"x": np.arange(64, dtype=np.int64)}).to_csv(
            path, index=False
        )
        mdf = pd.read_csv(path)
        frame = mdf._query_compiler._modin_frame
        col = next(c for c in frame._columns if c.is_device)
        col.host_cache = None
        assert recovery.recover_column(col, force=True) == recovery.KIND_IO
        replayer = col.lineage.replay.func.__self__
        recovery._purge_io_caches()
        assert replayer._cache is None
        assert np.array_equal(col.to_numpy(), np.arange(64))


# ====================================================================== #
# sequenced injectors
# ====================================================================== #


class TestSequencedInjectors:
    def test_schedule_orders_and_exhausts(self):
        inj = SequencedFaultInjector(
            [("clean", 2), ("transient", 1), ("clean", 1)], ops=("deploy",)
        )
        fired = []
        with inj:
            for i in range(6):
                try:
                    resilience._fault_hook("deploy")
                    fired.append("clean")
                except Exception:
                    fired.append("fault")
        assert fired == ["clean", "clean", "fault", "clean", "clean", "clean"]
        assert inj.injected == 1 and inj.calls == 6

    def test_non_matching_ops_pass_through(self):
        with midquery_device_loss(after_deploys=0, times=1) as inj:
            resilience._fault_hook("materialize")  # not a deploy: clean
        assert inj.injected == 0

    def test_rejects_unknown_kinds(self):
        with pytest.raises(ValueError):
            SequencedFaultInjector([("nonsense", 1)])

    def test_oom_burst_clears_after_spill(self):
        col = _col(np.arange(2048, dtype=np.int64))
        with OomBurstInjector(ops=("deploy",), spills=1) as inj:
            with pytest.raises(Exception):
                resilience._fault_hook("deploy")
            # the eviction the burst waits for (everything spillable)
            assert device_ledger.spill_lru(10**12) > 0
            assert col.is_spilled
            resilience._fault_hook("deploy")  # pressure cleared: clean
        assert inj.injected == 1

    def test_exclusive_with_plain_injector(self):
        with inject_faults("oom"):
            with pytest.raises(RuntimeError):
                with midquery_device_loss(after_deploys=1):
                    pass
