"""Range-partitioning shuffle tests on the virtual 8-device mesh.

Counterpart of the reference's shuffle/split internals tests
(modin/tests/core/storage_formats/pandas/test_internals.py:926-1038).
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import RangePartitioning
from tests.utils import create_test_dfs, df_equals


@pytest.fixture(autouse=True)
def _require_mesh():
    from modin_tpu.parallel.mesh import num_row_shards
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax" or num_row_shards() < 2:
        pytest.skip("needs TpuOnJax on a multi-device mesh")


def test_range_shuffle_kernel_roundtrip():
    import jax.numpy as jnp

    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import range_shuffle

    rng = np.random.default_rng(5)
    n = 10_000
    keys = rng.uniform(-100, 100, n)
    vals = rng.integers(0, 1000, n)
    key_dev = JaxWrapper.put(pad_host(keys))
    val_dev = JaxWrapper.put(pad_host(vals))
    key_out, cols_out, counts, pivots = range_shuffle(key_dev, [val_dev], n)
    assert int(counts.sum()) == n
    k = np.asarray(key_out)[:n]
    v = np.asarray(cols_out[0])[:n]
    # all rows survive with their payloads attached
    order_in = np.lexsort((vals, keys))
    order_out = np.lexsort((v, k))
    np.testing.assert_array_equal(k[order_out], keys[order_in])
    np.testing.assert_array_equal(v[order_out], vals[order_in])


def test_range_shuffle_local_sort_is_global_sort():
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import range_shuffle

    rng = np.random.default_rng(6)
    n = 8_001  # uneven on purpose
    keys = rng.normal(0, 50, n)
    key_dev = JaxWrapper.put(pad_host(keys))
    key_out, _, counts, _ = range_shuffle(key_dev, [], n, local_sort=True)
    k = np.asarray(key_out)[:n]
    np.testing.assert_array_equal(k, np.sort(keys))


def test_range_shuffle_skewed_keys_retry():
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import range_shuffle

    # 90% identical keys forces destination overflow and the slack retry
    rng = np.random.default_rng(7)
    n = 4_000
    keys = np.where(rng.random(n) < 0.9, 7.0, rng.uniform(0, 1000, n))
    key_dev = JaxWrapper.put(pad_host(keys))
    key_out, _, counts, _ = range_shuffle(key_dev, [], n, local_sort=True)
    np.testing.assert_array_equal(np.asarray(key_out)[:n], np.sort(keys))


def test_sort_values_range_partitioning_config():
    rng = np.random.default_rng(8)
    data = {
        "a": rng.uniform(-10, 10, 3000),
        "b": rng.integers(0, 100, 3000),
    }
    md, pdf = create_test_dfs(data)
    with RangePartitioning.context(True):
        df_equals(
            md.sort_values("a", kind="stable"),
            pdf.sort_values("a", kind="stable"),
        )
        df_equals(
            md.sort_values("b", ascending=False, kind="stable").reset_index(drop=True),
            pdf.sort_values("b", ascending=False, kind="stable").reset_index(drop=True),
        )


def test_sort_values_all_equal_keys_completes():
    # All-equal keys make every row target one shard; the slack retry loop
    # must still converge on this mesh and produce a correct sort.
    md, pdf = create_test_dfs({"a": np.full(2048, 3.0), "b": np.arange(2048.0)})
    with RangePartitioning.context(True):
        df_equals(
            md.sort_values("a", kind="stable"), pdf.sort_values("a", kind="stable")
        )


def test_sort_values_skew_overflow_falls_back(monkeypatch):
    # On wide meshes the slack retry can exhaust (RuntimeError); sort_values
    # must fall back to the global argsort path instead of surfacing it.
    import modin_tpu.parallel.shuffle as shuffle_mod

    def boom(*args, **kwargs):
        raise shuffle_mod.ShuffleSkewError("range_shuffle: pathological key skew")

    monkeypatch.setattr(shuffle_mod, "range_shuffle", boom)
    md, pdf = create_test_dfs({"a": np.full(512, 3.0), "b": np.arange(512.0)})
    with RangePartitioning.context(True):
        df_equals(
            md.sort_values("a", kind="stable"), pdf.sort_values("a", kind="stable")
        )


def test_range_shuffle_sort_with_nan_and_inf():
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import range_shuffle

    rng = np.random.default_rng(9)
    n = 5_000
    keys = rng.uniform(-10, 10, n)
    keys[rng.choice(n, 200, replace=False)] = np.nan
    keys[rng.choice(n, 50, replace=False)] = np.inf
    keys[rng.choice(n, 50, replace=False)] = -np.inf
    key_dev = JaxWrapper.put(pad_host(keys))
    key_out, _, counts, _ = range_shuffle(key_dev, [], n, local_sort=True)
    k = np.asarray(key_out)[:n]
    n_nan = int(np.isnan(keys).sum())
    expected = np.concatenate([np.sort(keys[~np.isnan(keys)]), [np.nan] * n_nan])
    np.testing.assert_array_equal(k, expected)


def test_range_shuffle_descending_nan_last():
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import range_shuffle

    rng = np.random.default_rng(10)
    n = 3_000
    keys = rng.uniform(-5, 5, n)
    keys[rng.choice(n, 100, replace=False)] = np.nan
    key_dev = JaxWrapper.put(pad_host(keys))
    key_out, _, counts, _ = range_shuffle(
        key_dev, [], n, descending=True, local_sort=True
    )
    k = np.asarray(key_out)[:n]
    n_nan = int(np.isnan(keys).sum())
    expected = np.concatenate(
        [np.sort(keys[~np.isnan(keys)])[::-1], [np.nan] * n_nan]
    )
    np.testing.assert_array_equal(k, expected)


def test_range_shuffle_exhausted_slack_raises_skew_error():
    # Real exhaustion (not a faked error): all-equal keys route every row to
    # one shard, so per-destination capacity can never fit them under a
    # clamped max_slack — the retry loop must double, give up, and raise the
    # SEMANTIC ShuffleSkewError (never a raw RuntimeError, never a
    # DeviceFailure), with the retry/fallback counters emitted.
    from modin_tpu.logging import add_metric_handler, clear_metric_handler
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper
    from modin_tpu.parallel.shuffle import ShuffleSkewError, range_shuffle

    seen = []

    def handler(name, value):
        seen.append(name)

    add_metric_handler(handler)
    try:
        n = 2048
        keys = np.full(n, 3.0)
        key_dev = JaxWrapper.put(pad_host(keys))
        with pytest.raises(ShuffleSkewError):
            range_shuffle(key_dev, [], n, max_slack=2.0)
    finally:
        clear_metric_handler(handler)
    assert "modin_tpu.resilience.shuffle.slack_retry" in seen
    assert "modin_tpu.resilience.shuffle.skew_fallback" in seen


def test_sort_values_real_skew_exhaustion_falls_back(monkeypatch):
    # End-to-end satellite check: the REAL range_shuffle runs, really
    # exhausts its capacity-slack retries on pathologically skewed keys
    # (max_slack clamped low), and sort_values degrades to the non-shuffle
    # global-argsort path with pandas-identical results.
    import functools

    import modin_tpu.parallel.shuffle as shuffle_mod

    real = shuffle_mod.range_shuffle
    monkeypatch.setattr(
        shuffle_mod, "range_shuffle", functools.partial(real, max_slack=2.0)
    )
    md, pdf = create_test_dfs({"a": np.full(2048, 3.0), "b": np.arange(2048.0)})
    with RangePartitioning.context(True):
        df_equals(
            md.sort_values("a", kind="stable"), pdf.sort_values("a", kind="stable")
        )
        df_equals(
            md.sort_values("a", ascending=False, kind="stable", ignore_index=True),
            pdf.sort_values("a", ascending=False, kind="stable", ignore_index=True),
        )
