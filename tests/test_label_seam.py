"""The loc/iloc label seam: API -> qc.take_2d_labels/get_positions_from_labels
-> take_2d_positional (reference modin/pandas/indexing.py:698 ->
base/query_compiler.py:4809,4844), plus the setitem routes through
qc.write_items / qc.setitem_bool and df.query through qc.rowwise_query.

Scenario shapes ported from modin/tests/pandas/dataframe/test_indexing.py."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals, eval_general, require_tpu_execution

_rng = np.random.default_rng(77)


@pytest.fixture
def mi_dfs():
    idx = pandas.MultiIndex.from_product(
        [["bar", "baz", "foo", "qux"], ["one", "two"], [1, 2]],
        names=["k1", "k2", "k3"],
    )
    data = {"v": np.arange(16.0), "w": np.arange(16) * 3}
    return create_test_dfs(data, index=idx)


@pytest.fixture
def mi_col_dfs():
    cols = pandas.MultiIndex.from_product([["a", "b"], ["x", "y"]])
    data = _rng.normal(size=(8, 4))
    md = pd.DataFrame(data, columns=cols)
    pdf = pandas.DataFrame(data, columns=cols)
    return md, pdf


class TestMultiIndexLoc:
    def test_partial_scalar_key_drops_level(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc["bar"])
        eval_general(md, pdf, lambda df: df.loc["qux"])

    def test_partial_tuple_key(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc[("baz", "one")])

    def test_full_tuple_key_returns_series(self, mi_dfs):
        md, pdf = mi_dfs
        m, p = md.loc[("foo", "two", 1)], pdf.loc[("foo", "two", 1)]
        assert m.name == p.name
        df_equals(m, p)

    def test_full_key_and_column(self, mi_dfs):
        md, pdf = mi_dfs
        assert md.loc[("foo", "two", 1), "v"] == pdf.loc[("foo", "two", 1), "v"]

    def test_scalar_key_and_column_list(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc["bar", ["v"]])

    def test_level0_label_list_keeps_levels(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc[["bar", "foo"]])

    def test_list_of_full_tuples(self, mi_dfs):
        md, pdf = mi_dfs
        key = [("bar", "one", 1), ("qux", "two", 2)]
        eval_general(md, pdf, lambda df: df.loc[key])

    def test_per_level_selectors_with_slice(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc[("baz", slice(None), 2), :])

    def test_label_slice_over_level0(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc["baz":"foo"])

    def test_missing_key_raises(self, mi_dfs):
        md, pdf = mi_dfs
        eval_general(md, pdf, lambda df: df.loc["nope"])
        eval_general(md, pdf, lambda df: df.loc[("bar", "three")])

    def test_series_multiindex_loc(self, mi_dfs):
        md, pdf = mi_dfs
        ms, ps = md["v"], pdf["v"]
        df_equals(ms.loc["bar"], ps.loc["bar"])
        assert ms.loc[("foo", "two", 1)] == ps.loc[("foo", "two", 1)]
        df_equals(ms.loc[("baz", "one")], ps.loc[("baz", "one")])

    def test_no_wholesale_fallback(self, mi_dfs):
        """MultiIndex loc must route through the QC seam, not default to
        pandas (the round-3 gap this seam exists to close)."""
        require_tpu_execution()
        md, _ = mi_dfs
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)
            md.loc["bar"]
            md.loc[("baz", "one")]
            md.loc[["bar", "foo"]]
            md.loc["baz":"foo"]


class TestMultiIndexColumns:
    def test_partial_column_tuple_drops_level(self, mi_col_dfs):
        md, pdf = mi_col_dfs
        eval_general(md, pdf, lambda df: df.loc[:, ("a",)])

    def test_full_column_tuple(self, mi_col_dfs):
        md, pdf = mi_col_dfs
        eval_general(md, pdf, lambda df: df.loc[:, ("a", "y")])

    def test_column_label_list(self, mi_col_dfs):
        md, pdf = mi_col_dfs
        eval_general(md, pdf, lambda df: df.loc[:, [("a", "x"), ("b", "y")]])

    def test_rows_and_column_level0(self, mi_col_dfs):
        md, pdf = mi_col_dfs
        eval_general(md, pdf, lambda df: df.loc[2:5, ("b",)])


class TestPositionsFromLabels:
    """Direct unit coverage of the QC seam (the round-3 dead methods)."""

    @pytest.fixture
    def qc(self):
        return pd.DataFrame(
            {"x": np.arange(8.0), "y": np.arange(8) * 2},
            index=[10, 20, 30, 40, 50, 60, 70, 80],
        )._query_compiler

    def test_full_slices_stay_symbolic(self, qc):
        rows, cols = qc.get_positions_from_labels(slice(None), slice(None))
        assert rows == slice(None) and cols == slice(None)

    def test_label_slice_closed(self, qc):
        rows, _ = qc.get_positions_from_labels(slice(20, 50), slice(None))
        assert list(rows) == [1, 2, 3, 4]

    def test_range_is_labels_not_positions(self, qc):
        # ADVICE r3: pandas .loc treats range as list-like LABELS and raises
        # KeyError for missing ones — not a positional window
        with pytest.raises(KeyError):
            qc.get_positions_from_labels(range(2, 5), slice(None))
        rows, _ = qc.get_positions_from_labels(range(10, 40, 10), slice(None))
        assert list(rows) == [0, 1, 2]

    def test_scalar_and_missing(self, qc):
        rows, _ = qc.get_positions_from_labels(30, slice(None))
        assert list(rows) == [2]
        with pytest.raises(KeyError):
            qc.get_positions_from_labels(35, slice(None))

    def test_bool_mask_length_checked(self, qc):
        with pytest.raises(IndexError):
            qc.get_positions_from_labels([True, False], slice(None))
        rows, _ = qc.get_positions_from_labels(
            np.arange(8) % 3 == 0, slice(None)
        )
        assert list(rows) == [0, 3, 6]

    def test_duplicate_labels(self):
        qc = pd.DataFrame(
            {"x": [1.0, 2.0, 3.0, 4.0]}, index=["a", "b", "a", "c"]
        )._query_compiler
        rows, _ = qc.get_positions_from_labels("a", slice(None))
        assert list(rows) == [0, 2]

    def test_partial_string_datetime(self):
        idx = pandas.date_range("2021-01-30", periods=6, freq="D")
        qc = pd.DataFrame({"x": np.arange(6.0)}, index=idx)._query_compiler
        rows, _ = qc.get_positions_from_labels("2021-02", slice(None))
        assert list(rows) == [2, 3, 4, 5]

    def test_take_2d_labels_matches_loc(self, qc):
        out = qc.take_2d_labels([20, 60], ["y"]).to_pandas()
        assert list(out.index) == [20, 60]
        assert list(out.columns) == ["y"]
        assert list(out["y"]) == [2, 10]

    def test_lookup(self, qc):
        vals = qc.lookup([20, 40, 80], ["x", "y", "x"])
        assert list(vals) == [1.0, 6.0, 7.0]


class TestSetitemRouting:
    def test_loc_scalar_set(self):
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6) * 2})

        def op(df):
            df = df.copy()
            df.loc[3, "a"] = 99.0
            return df

        eval_general(md, pdf, op)

    def test_loc_array_set(self):
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6) * 2})

        def op(df):
            df = df.copy()
            df.loc[[1, 4], "b"] = np.array([-1, -2])
            return df

        eval_general(md, pdf, op)

    def test_loc_slice_rows_all_cols(self):
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6.0)})

        def op(df):
            df = df.copy()
            df.loc[2:4] = 0.0
            return df

        eval_general(md, pdf, op)

    def test_iloc_set(self):
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6) * 2})

        def op(df):
            df = df.copy()
            df.iloc[[0, 5], 1] = 7
            return df

        eval_general(md, pdf, op)

        def op2(df):
            df = df.copy()
            df.iloc[1:3, :] = 0.5
            return df

        eval_general(md, pdf, op2)

    def test_bool_mask_routes_setitem_bool(self, monkeypatch):
        """df.loc[mask, col] = scalar is the reference's named-QC hot path
        (indexing.py:954)."""
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6.0)})
        qc_cls = type(md._query_compiler)
        calls = {"n": 0}
        orig = qc_cls.setitem_bool

        def spy(self, row_loc, col_loc, item):
            calls["n"] += 1
            return orig(self, row_loc, col_loc, item)

        monkeypatch.setattr(qc_cls, "setitem_bool", spy)
        md.loc[md["a"] > 2, "b"] = -5.0
        pdf.loc[pdf["a"] > 2, "b"] = -5.0
        assert calls["n"] == 1
        df_equals(md, pdf)

    def test_enlargement_still_correct(self):
        md, pdf = create_test_dfs({"a": [1.0, 2.0]}, index=["x", "y"])

        def op(df):
            df = df.copy()
            df.loc["z"] = 9.0
            return df

        eval_general(md, pdf, op)

    def test_loc_set_aligned_series_value(self):
        md, pdf = create_test_dfs({"a": np.arange(4.0), "b": np.arange(4.0)})
        value = pandas.Series([10.0, 20.0], index=[2, 0])

        def op(df):
            df = df.copy()
            df.loc[[0, 2], "a"] = value
            return df

        eval_general(md, pdf, op)


class TestRowwiseQuery:
    def test_query_routes_through_qc(self, monkeypatch):
        md, pdf = create_test_dfs(
            {"a": _rng.normal(size=50), "b": _rng.integers(0, 5, 50)}
        )
        qc_cls = type(md._query_compiler)
        if not hasattr(qc_cls, "rowwise_query"):
            pytest.skip("backend has no rowwise_query")
        calls = {"n": 0}
        orig = qc_cls.rowwise_query

        def spy(self, expr, **kw):
            calls["n"] += 1
            return orig(self, expr, **kw)

        monkeypatch.setattr(qc_cls, "rowwise_query", spy)
        df_equals(md.query("a > 0 and b < 3"), pdf.query("a > 0 and b < 3"))
        assert calls["n"] == 1

    def test_query_local_variable(self):
        md, pdf = create_test_dfs({"a": np.arange(20.0)})
        lim = 12.5
        df_equals(md.query("a > @lim"), pdf.query("a > @lim"))

    def test_query_fallback_still_works(self):
        md, pdf = create_test_dfs({"a": np.arange(10.0)})
        eval_general(md, pdf, lambda df: df.query("index > 4"))


class TestLocParityBreadth:
    """Extra shapes from the reference indexing suite."""

    def test_loc_bool_series_unalignable_raises(self):
        md, pdf = create_test_dfs({"a": np.arange(4.0)})
        mask = pandas.Series([True, False, True], index=[0, 1, 9])
        eval_general(md, pdf, lambda df: df.loc[mask])

    def test_loc_datetime_partial_string(self):
        idx = pandas.date_range("2022-03-28", periods=10, freq="D")
        md, pdf = create_test_dfs({"v": np.arange(10.0)}, index=idx)
        eval_general(md, pdf, lambda df: df.loc["2022-04"])
        eval_general(md, pdf, lambda df: df.loc["2022-03-29":"2022-04-02"])

    def test_loc_duplicate_index_scalar(self):
        md, pdf = create_test_dfs(
            {"v": np.arange(5.0)}, index=["a", "b", "a", "c", "a"]
        )
        eval_general(md, pdf, lambda df: df.loc["a"])
        eval_general(md, pdf, lambda df: df.loc["b"])

    def test_loc_tuple_label_on_flat_index(self):
        idx = pandas.Index([("a", 1), ("b", 2), ("c", 3)], tupleize_cols=False)
        md, pdf = create_test_dfs({"v": [1.0, 2.0, 3.0]}, index=idx)
        eval_general(md, pdf, lambda df: df.loc[[("b", 2)]])

    def test_loc_empty_list(self):
        md, pdf = create_test_dfs({"a": np.arange(4.0)})
        eval_general(md, pdf, lambda df: df.loc[[]])

    def test_loc_callable(self):
        md, pdf = create_test_dfs({"a": np.arange(6.0), "b": np.arange(6.0)})
        eval_general(md, pdf, lambda df: df.loc[lambda d: d["a"] > 2])

    def test_loc_index_key_preserves_freq(self):
        idx = pandas.date_range("2020-01-01", periods=8, freq="D")
        md, pdf = create_test_dfs({"v": np.arange(8.0)}, index=idx)
        key = idx[2:5]
        m, p = md.loc[key], pdf.loc[key]
        df_equals(m, p)
        assert m.index.freq == p.index.freq


class TestReviewRegressions:
    """Shapes caught in round-4 review: over-squeeze of single-match partial
    MultiIndex keys, level drops keyed to the wrong axis, and 1-D values
    written into single-column positional selections."""

    def test_partial_scalar_single_match_stays_frame(self):
        mi = pandas.MultiIndex.from_tuples([("a", 1), ("b", 1), ("b", 2)])
        md, pdf = create_test_dfs(
            {"x": [1.0, 2, 3], "y": [4.0, 5, 6]}, index=mi
        )
        eval_general(md, pdf, lambda df: df.loc["a"])

    def test_series_partial_tuple_single_match_stays_series(self):
        mi = pandas.MultiIndex.from_tuples(
            [("a", "b", 1), ("a", "c", 2), ("d", "e", 3)]
        )
        ps = pandas.Series([1.0, 2, 3], index=mi)
        ms = pd.Series(ps)
        eval_general(ms, ps, lambda s: s.loc[("a", "b")])
        eval_general(ms, ps, lambda s: s.loc[("a", "b", 1)])

    def test_mi_columns_partial_single_subcolumn_stays_frame(self):
        cols = pandas.MultiIndex.from_tuples([("a", "p"), ("q", "r")])
        data = [[1.0, 2.0], [3.0, 4.0]]
        md = pd.DataFrame(data, columns=cols)
        pdf = pandas.DataFrame(data, columns=cols)
        eval_general(md, pdf, lambda df: df.loc[:, "a"])
        eval_general(md, pdf, lambda df: df.loc[0, "a"])

    def test_col_label_coinciding_with_row_level_value(self):
        mi = pandas.MultiIndex.from_tuples([("v", 1), ("v", 2), ("w", 1)])
        md, pdf = create_test_dfs(
            {"v": [1.0, 2, 3], "z": [4.0, 5, 6]}, index=mi
        )
        eval_general(md, pdf, lambda df: df.loc[["v"], "v"])

    def test_setitem_single_column_list_value(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3], "b": [4, 5, 6]})
        def set_loc(df):
            df = df.copy()
            df.loc[:, "b"] = [7, 8, 9]
            return df
        def set_iloc(df):
            df = df.copy()
            df.iloc[:, 1] = [10, 11, 12]
            return df
        def set_subset(df):
            df = df.copy()
            df.iloc[[0, 2], 0] = [77, 88]
            return df
        def set_broadcast(df):
            df = df.copy()
            df.iloc[:, [0, 1]] = [1, 2]
            return df
        for op in (set_loc, set_iloc, set_subset, set_broadcast):
            eval_general(md, pdf, op)


class TestLocBreadthPort:
    """Scenario shapes ported from the reference indexing suite
    (modin/tests/pandas/dataframe/test_indexing.py:367-975)."""

    @pytest.fixture
    def dfs(self):
        rng = np.random.default_rng(55)
        data = {f"col{i}": rng.integers(0, 100, 20) for i in range(7)}
        data["colf"] = rng.normal(size=20)
        return create_test_dfs(data)

    def test_loc_core_shapes(self, dfs):
        md, pdf = dfs
        key1, key2 = pdf.columns[0], pdf.columns[1]
        for op in (
            lambda df: df.loc[0, key1],
            lambda df: df.loc[0],
            lambda df: df.loc[1:, key1],
            lambda df: df.loc[1:2, key1],
            lambda df: df.loc[:, key1],
            lambda df: df.loc[[1, 2]],
            lambda df: df.loc[1:2, key1:key2],
            lambda df: df.loc[:, [key2, key1]],
            lambda df: df.loc[[2, 1], :],
            lambda df: df.loc[:, key1 : pdf.columns[-2]],
        ):
            eval_general(md, pdf, op)

    def test_loc_boolean_lists(self, dfs):
        md, pdf = dfs
        indices = [i % 3 == 0 for i in range(len(pdf.index))]
        columns = [i % 5 == 0 for i in range(len(pdf.columns))]
        eval_general(md, pdf, lambda df: df.loc[indices, columns])
        eval_general(md, pdf, lambda df: df.loc[:, columns])
        eval_general(md, pdf, lambda df: df.loc[indices])

    def test_loc_boolean_series_keys(self, dfs):
        md, pdf = dfs
        indices = [i % 3 == 0 for i in range(len(pdf.index))]
        columns = [i % 5 == 0 for i in range(len(pdf.columns))]
        m = md.loc[pd.Series(indices), pd.Series(columns, index=md.columns)]
        p = pdf.loc[
            pandas.Series(indices), pandas.Series(columns, index=pdf.columns)
        ]
        df_equals(m, p)

    def test_loc_write_rows(self, dfs):
        md, pdf = dfs
        md, pdf = md.copy(), pdf.copy()
        md.loc[[1, 2]] = 42
        pdf.loc[[1, 2]] = 42
        df_equals(md, pdf)

    def test_loc_mask_then_transform_assignment(self):
        md, pdf = create_test_dfs({"a": [1, 2], "b": [3.0, 4.0]})
        pdf.loc[pdf["a"] > 1, "b"] = np.log(pdf["b"])
        md.loc[md["a"] > 1, "b"] = np.log(md["b"])
        df_equals(md, pdf)

    @pytest.mark.parametrize("locator_name", ["loc", "iloc"])
    @pytest.mark.parametrize(
        "slice_indexer",
        [
            slice(None, None, -2),
            slice(1, 10, None),
            slice(None, 10, None),
            slice(10, None, None),
            slice(10, None, -2),
            slice(-10, None, -2),
            slice(None, 1_000_000_000, None),
        ],
    )
    def test_slice_indexers_shifted_index(self, locator_name, slice_indexer):
        rng = np.random.default_rng(5)
        md, pdf = create_test_dfs({"v": rng.normal(size=30), "w": rng.integers(0, 9, 30)})
        shifted = pandas.RangeIndex(1, 31)
        md.index = shifted
        pdf.index = shifted
        eval_general(
            md, pdf, lambda df: getattr(df, locator_name)[slice_indexer]
        )

    def test_loc_empty_frame(self):
        md, pdf = create_test_dfs({})
        eval_general(md, pdf, lambda df: df.loc[[]])

    def test_at_iat(self, dfs):
        md, pdf = dfs
        assert md.at[3, "col2"] == pdf.at[3, "col2"]
        assert md.iat[3, 2] == pdf.iat[3, 2]
        md, pdf = md.copy(), pdf.copy()
        md.at[3, "col2"] = -7
        pdf.at[3, "col2"] = -7
        df_equals(md, pdf)
        md.iat[0, 0] = -9
        pdf.iat[0, 0] = -9
        df_equals(md, pdf)

    def test_loc_enlargement_falls_back_correct(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        md, pdf = md.copy(), pdf.copy()
        md.loc[99] = 7
        pdf.loc[99] = 7
        df_equals(md, pdf)
        md.loc[:, "new"] = 1.5
        pdf.loc[:, "new"] = 1.5
        df_equals(md, pdf)


class TestLocSetOrderAndEdges:
    """More reference scenarios: unsorted/repeated positional writes,
    MultiIndex on both axes, empty frames (test_indexing.py:704-760,2715)."""

    @pytest.mark.parametrize("indexer", ["loc", "iloc"])
    def test_set_order_unsorted_repeated(self, indexer):
        rng = np.random.default_rng(0)
        is_loc = indexer == "loc"
        data = {"col": rng.integers(0, 100, size=100)}
        row_indexer = rng.integers(0, 100, size=20)
        col_indexer = "col" if is_loc else 0
        set_data = list(range(100, 120))
        md, pdf = create_test_dfs(data)

        def get(df):
            return getattr(df, indexer)[row_indexer, col_indexer]

        eval_general(md, pdf, get)
        getattr(md, indexer)[row_indexer, col_indexer] = set_data
        getattr(pdf, indexer)[row_indexer, col_indexer] = set_data
        df_equals(md, pdf)
        eval_general(md, pdf, get)

    def test_multiindex_both_axes(self):
        mi = pandas.MultiIndex.from_tuples(
            [("r0", "rA"), ("r1", "rB")], names=["Courses", "Fee"]
        )
        cols = pandas.MultiIndex.from_tuples(
            [("Gasoline", "Toyota"), ("Gasoline", "Ford"),
             ("Electric", "Tesla"), ("Electric", "Nio")]
        )
        data = [[100, 300, 900, 400], [200, 500, 300, 600]]
        md = pd.DataFrame(data, columns=cols, index=mi)
        pdf = pandas.DataFrame(data, columns=cols, index=mi)
        eval_general(md, pdf, lambda df: df.loc[("r0", "rA"), :])
        eval_general(md, pdf, lambda df: df.loc[:, ("Gasoline", "Toyota")])
        eval_general(md, pdf, lambda df: df.loc[("r1", "rB"), ("Electric", "Nio")])

    def test_loc_empty_columns_frame(self):
        md = pd.DataFrame(index=range(5))
        pdf = pandas.DataFrame(index=range(5))
        df_equals(md.loc[1], pdf.loc[1])
        md.loc[1] = 3
        pdf.loc[1] = 3
        df_equals(md, pdf)

    def test_loc_missing_label_raises(self):
        md, pdf = create_test_dfs({"a": [1.0, 2, 3]}, index=["x", "y", "z"])
        eval_general(md, pdf, lambda df: df.loc["missing"])
        eval_general(md, pdf, lambda df: df.loc[["x", "missing"]])
        eval_general(md, pdf, lambda df: df.loc[:, "nocol"])

    def test_fallback_get_casts_modin_mask(self):
        # empty frames take the wholesale pandas fallback; a modin boolean
        # Series key must still align like a pandas one
        md = pd.DataFrame(index=[0, 1, 2])
        pdf = pandas.DataFrame(index=[0, 1, 2])
        m_mask = pd.Series([True, False, False], index=[2, 1, 0])
        p_mask = pandas.Series([True, False, False], index=[2, 1, 0])
        df_equals(md.loc[m_mask], pdf.loc[p_mask])
