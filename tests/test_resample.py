"""Device resample tests (time-bucket codes + segment aggregation)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import assert_no_fallback, create_test_dfs, df_equals

_rng = np.random.default_rng(41)
N = 1500


@pytest.fixture
def frames():
    idx = pandas.DatetimeIndex(
        pandas.Timestamp("2024-03-01 06:30")
        + pandas.to_timedelta(np.sort(_rng.integers(0, 86400 * 3, N)), unit="s")
    )
    data = {
        "v": np.where(_rng.random(N) < 0.15, np.nan, _rng.normal(size=N)),
        "q": _rng.integers(0, 100, N),
    }
    return create_test_dfs(data, index=idx)


@pytest.mark.parametrize("rule", ["5min", "h", "1D", "90s", "2h"])
@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "var", "std"])
def test_resample_device_matrix(frames, rule, agg):
    md, pdf = frames
    got = assert_no_fallback(lambda: getattr(md.resample(rule), agg)())
    df_equals(got, getattr(pdf.resample(rule), agg)())


def test_resample_size(frames):
    md, pdf = frames
    df_equals(md.resample("h").size(), pdf.resample("h").size())


def test_resample_series(frames):
    md, pdf = frames
    df_equals(md["v"].resample("h").mean(), pdf["v"].resample("h").mean())


def test_resample_empty_buckets_int_promotion():
    idx = pandas.DatetimeIndex(["2024-01-01", "2024-01-05", "2024-01-02 13:00"])
    md, pdf = create_test_dfs({"q": [1, 2, 3]}, index=idx)
    for agg in ("sum", "min", "max", "count", "mean"):
        df_equals(
            getattr(md.resample("1D"), agg)(), getattr(pdf.resample("1D"), agg)()
        )


def test_resample_calendar_rules_fall_back(frames):
    md, pdf = frames
    df_equals(md.resample("ME").sum(), pdf.resample("ME").sum())
    df_equals(md.resample("W").mean(), pdf.resample("W").mean())


def test_resample_kwargs_fall_back(frames):
    md, pdf = frames
    df_equals(
        md.resample("h", closed="right").sum(),
        pdf.resample("h", closed="right").sum(),
    )
    df_equals(
        md.resample("h", label="right").sum(),
        pdf.resample("h", label="right").sum(),
    )


def test_resample_ohlc_and_agg(frames):
    md, pdf = frames
    df_equals(md["v"].resample("6h").ohlc(), pdf["v"].resample("6h").ohlc())
    df_equals(
        md.resample("6h").agg({"v": "mean", "q": "sum"}),
        pdf.resample("6h").agg({"v": "mean", "q": "sum"}),
    )


@pytest.fixture
def calendar_frames():
    idx = pandas.date_range("2023-11-07", periods=400, freq="31h")
    data = {
        "v": np.where(_rng.random(400) < 0.1, np.nan, _rng.normal(size=400)),
        "q": _rng.integers(0, 50, 400),
    }
    return create_test_dfs(data, index=idx)


@pytest.mark.parametrize("rule", ["ME", "MS", "W", "W-TUE", "QE", "YE", "B", "2W"])
@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max"])
def test_resample_calendar_rules_device(calendar_frames, rule, agg):
    md, pdf = calendar_frames
    got = assert_no_fallback(lambda: getattr(md.resample(rule), agg)())
    df_equals(got, getattr(pdf.resample(rule), agg)())


@pytest.mark.parametrize(
    "kwargs",
    [
        {"closed": "left"},
        {"label": "left"},
        {"closed": "left", "label": "left"},
    ],
)
def test_resample_calendar_closed_label_device(calendar_frames, kwargs):
    md, pdf = calendar_frames
    got = assert_no_fallback(lambda: md.resample("ME", **kwargs).sum())
    df_equals(got, pdf.resample("ME", **kwargs).sum())


@pytest.mark.parametrize("kwargs", [{"origin": "epoch"}, {"offset": "17min"}])
def test_resample_tick_origin_offset_device(calendar_frames, kwargs):
    md, pdf = calendar_frames
    got = assert_no_fallback(lambda: md.resample("3h", **kwargs).mean())
    df_equals(got, pdf.resample("3h", **kwargs).mean())


def test_resample_tz_aware_device(calendar_frames):
    md, pdf = calendar_frames
    md = md.set_index(md.index.tz_localize("US/Pacific") if hasattr(md.index, "tz_localize") else md.index)
    pdf = pdf.set_index(pdf.index.tz_localize("US/Pacific"))
    got = md.resample("ME").sum()
    df_equals(got, pdf.resample("ME").sum())


def test_resample_non_monotonic_falls_back(calendar_frames):
    md, pdf = calendar_frames
    md, pdf = md.iloc[::-1], pdf.iloc[::-1]
    # correctness through the fallback (device path must decline)
    df_equals(md.resample("ME").sum(), pdf.resample("ME").sum())


def test_resample_quarter_series_device(calendar_frames):
    md, pdf = calendar_frames
    df_equals(md["v"].resample("QE").mean(), pdf["v"].resample("QE").mean())


def test_pandas_grouper_time_bins_api_pin():
    """Pin the private pandas API the device resample path depends on.

    query_compiler.py's device resample calls ``Grouper._get_time_bins``
    (guarded by a broad fallback); if a pandas upgrade removes or reshapes
    it, this test fails loudly instead of silently degrading every rule to
    the host path.
    """
    idx = pandas.date_range("2024-01-01", periods=10, freq="h")
    grouper = pandas.Grouper(freq="2h")
    binner, bins, labels = grouper._get_time_bins(idx)
    assert isinstance(labels, pandas.DatetimeIndex)
    assert list(np.asarray(bins, dtype=np.int64)) == [2, 4, 6, 8, 10]
