"""Device paths for the top fallback ops from the Kaggle-workflow census
(r5): reset_index, describe, setitem_bool (loc-mask banding), series_map.

Differential vs pandas with path-taken assertions."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import assert_no_fallback, create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(59)


class TestResetIndexDevice:
    def test_drop_true_metadata_only(self):
        md, pdf = create_test_dfs({"a": _rng.normal(size=40)})
        got = assert_no_fallback(lambda: md.reset_index(drop=True))
        df_equals(got, pdf.reset_index(drop=True))

    def test_default_prepends_index(self):
        md, pdf = create_test_dfs({"a": _rng.normal(size=40)})
        got = assert_no_fallback(lambda: md.reset_index())
        df_equals(got, pdf.reset_index())

    def test_named_and_str_index(self):
        for idx in (
            pandas.Index([10, 20, 30], name="id"),
            pandas.Index(["x", "y", "z"]),
        ):
            md = pd.DataFrame({"a": [1.0, 2.0, 3.0]}, index=idx)
            pdf = pandas.DataFrame({"a": [1.0, 2.0, 3.0]}, index=idx)
            got = assert_no_fallback(lambda: md.reset_index())
            df_equals(got, pdf.reset_index())

    def test_multiindex_levels_become_columns(self):
        mi = pandas.MultiIndex.from_product([["p", "q"], ["r", "s"]], names=["u", None])
        md = pd.DataFrame({"a": [1.0, 2, 3, 4]}, index=mi)
        pdf = pandas.DataFrame({"a": [1.0, 2, 3, 4]}, index=mi)
        got = assert_no_fallback(lambda: md.reset_index())
        df_equals(got, pdf.reset_index())

    def test_groupby_chain(self):
        md, pdf = create_test_dfs(
            {"k": _rng.integers(0, 5, 60), "v": _rng.normal(size=60)}
        )
        got = assert_no_fallback(lambda: md.groupby("k").sum().reset_index())
        df_equals(got, pdf.groupby("k").sum().reset_index())

    def test_conflicting_name_matches_pandas(self):
        md, pdf = create_test_dfs({"index": [1, 2, 3]})
        eval_general(md, pdf, lambda df: df.reset_index())

    def test_level_kwarg_falls_back_correct(self):
        mi = pandas.MultiIndex.from_product([["p", "q"], ["r", "s"]], names=["u", "w"])
        md = pd.DataFrame({"a": [1.0, 2, 3, 4]}, index=mi)
        pdf = pandas.DataFrame({"a": [1.0, 2, 3, 4]}, index=mi)
        eval_general(md, pdf, lambda df: df.reset_index(level="u"))


class TestDescribeDevice:
    @pytest.fixture
    def dfs(self):
        n = 300
        return create_test_dfs(
            {
                "a": _rng.normal(size=n),
                "k": _rng.integers(0, 9, n),
                "c": np.where(_rng.random(n) < 0.1, np.nan, _rng.uniform(0, 10, n)),
            }
        )

    def test_default(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.describe())
        df_equals(got, pdf.describe())

    def test_custom_percentiles(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.describe(percentiles=[0.1, 0.9]))
        df_equals(got, pdf.describe(percentiles=[0.1, 0.9]))

    def test_mixed_frame_falls_back_correct(self):
        md, pdf = create_test_dfs(
            {
                "a": _rng.normal(size=30),
                "s": np.array(["x", "y"], dtype=object)[_rng.integers(0, 2, 30)],
            }
        )
        eval_general(md, pdf, lambda df: df.describe())
        eval_general(md, pdf, lambda df: df.describe(include="all"))


class TestSetitemBoolDevice:
    def test_float_banding_chain(self):
        data = {"age": _rng.uniform(0, 80, 200).round(1)}
        md, pdf = create_test_dfs(data)

        def band(d):
            d.loc[d["age"] <= 16, "age"] = 0
            d.loc[(d["age"] > 16) & (d["age"] <= 32), "age"] = 1
            d.loc[d["age"] > 32, "age"] = 2

        assert_no_fallback(lambda: band(md))
        band(pdf)
        df_equals(md, pdf)

    def test_int_scalar_and_nan(self):
        data = {"k": _rng.integers(0, 9, 100), "f": _rng.normal(size=100)}
        md, pdf = create_test_dfs(data)
        for d in (md, pdf):
            d.loc[d["k"] > 4, "k"] = 99
            d.loc[d["f"] > 1, "f"] = np.nan
        df_equals(md, pdf)

    def test_incompatible_scalar_raises_like_pandas(self):
        md, pdf = create_test_dfs({"k": [1, 2, 3]})

        def set_bad(d):
            d.loc[d["k"] > 1, "k"] = 2.5
            return d

        eval_general(md, pdf, set_bad)


class TestSeriesMapDevice:
    def test_str_recode_to_int(self):
        sex = np.array(["male", "female"], dtype=object)[_rng.integers(0, 2, 300)]
        md, ps = pd.Series(sex), pandas.Series(sex)
        got = assert_no_fallback(lambda: md.map({"male": 0, "female": 1}))
        df_equals(got, ps.map({"male": 0, "female": 1}))

    def test_partial_and_nan_rows_give_float(self):
        emb = np.array(["S", "C", "Q"], dtype=object)[_rng.integers(0, 3, 200)].copy()
        emb[_rng.random(200) < 0.1] = np.nan
        md, ps = pd.Series(emb), pandas.Series(emb)
        got = assert_no_fallback(lambda: md.map({"S": 0, "C": 1}))
        df_equals(got, ps.map({"S": 0, "C": 1}))

    def test_numeric_keys_lookup(self):
        ints = _rng.integers(0, 5, 200)
        md, ps = pd.Series(ints), pandas.Series(ints)
        full = {i: i * 10 for i in range(5)}
        got = assert_no_fallback(lambda: md.map(full))
        df_equals(got, ps.map(full))
        eval_general(md, ps, lambda s: s.map({0: 10, 2: 12}))

    def test_bool_values_keep_bool_dtype(self):
        sex = np.array(["male", "female"], dtype=object)[_rng.integers(0, 2, 100)]
        md, ps = pd.Series(sex), pandas.Series(sex)
        got = md.map({"male": True, "female": False})
        df_equals(got, ps.map({"male": True, "female": False}))

    def test_object_values_fall_back_correct(self):
        sex = np.array(["male", "female"], dtype=object)[_rng.integers(0, 2, 100)]
        md, ps = pd.Series(sex), pandas.Series(sex)
        eval_general(md, ps, lambda s: s.map({"male": "M", "female": "F"}))

    def test_callable_fall_back_correct(self):
        md, ps = pd.Series(np.arange(20)), pandas.Series(np.arange(20))
        eval_general(md, ps, lambda s: s.map(lambda x: x + 1))


class TestCategoricalKeyGroupBy:
    """cut/qcut-produced categorical keys groupby on device via their
    existing codes (ops/dictionary.encode_categorical_column)."""

    @pytest.fixture
    def dfs(self):
        n = 400
        age = _rng.uniform(0, 99, n)
        md = pd.DataFrame({"age": age, "v": _rng.normal(size=n), "o": _rng.integers(0, 2, n)})
        pdf = pandas.DataFrame({"age": age, "v": np.asarray(md["v"]._to_pandas()), "o": np.asarray(md["o"]._to_pandas())})
        md["grp"] = pd.cut(md["age"], bins=[0, 30, 60, 100], labels=["y", "m", "o"])
        pdf["grp"] = pandas.cut(pdf["age"], bins=[0, 30, 60, 100], labels=["y", "m", "o"])
        return md, pdf

    @pytest.mark.parametrize("observed", [True, False])
    def test_mean_categorical_index(self, dfs, observed):
        md, pdf = dfs
        got = assert_no_fallback(
            lambda: md.groupby("grp", observed=observed)["v"].mean()
        )
        df_equals(got, pdf.groupby("grp", observed=observed)["v"].mean())

    def test_multi_with_numeric(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(
            lambda: md.groupby(["grp", "o"], observed=True)["v"].sum()
        )
        df_equals(got, pdf.groupby(["grp", "o"], observed=True)["v"].sum())

    def test_unobserved_categories_fall_back_correct(self, dfs):
        md, pdf = dfs
        md2, pdf2 = md[md["age"] < 55], pdf[pdf["age"] < 55]
        eval_general(
            md2, pdf2,
            lambda df: df.groupby("grp", observed=False)["v"].mean(),
        )

    def test_interval_categories_external_key(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(
            lambda: md.groupby(pd.cut(md["age"], 4), observed=False)["o"].mean()
        )
        df_equals(
            got, pdf.groupby(pandas.cut(pdf["age"], 4), observed=False)["o"].mean()
        )


class TestFillnaMapping:
    @pytest.fixture
    def dfs(self):
        n = 300
        return create_test_dfs(
            {
                "a": np.where(_rng.random(n) < 0.2, np.nan, _rng.normal(size=n)),
                "b": _rng.integers(0, 9, n),
                "c": np.where(_rng.random(n) < 0.1, np.nan, _rng.uniform(size=n)),
            }
        )

    def test_fillna_mean_series(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.fillna(md.mean()))
        df_equals(got, pdf.fillna(pdf.mean()))

    def test_fillna_dict(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.fillna({"a": 0.0, "c": 9.5}))
        df_equals(got, pdf.fillna({"a": 0.0, "c": 9.5}))

    def test_fillna_dict_str_value_falls_back_correct(self):
        md, pdf = create_test_dfs(
            {"s": np.array(["x", None, "y"], dtype=object), "a": [1.0, np.nan, 3.0]}
        )
        eval_general(md, pdf, lambda df: df.fillna({"s": "zz"}))


class TestConcatAxis1Device:
    def test_aligned_frames_and_series(self):
        n = 200
        d1 = {"a": _rng.normal(size=n), "b": _rng.integers(0, 5, n)}
        d2 = {"c": _rng.normal(size=n)}
        md1, pdf1 = create_test_dfs(d1)
        md2, pdf2 = create_test_dfs(d2)
        got = assert_no_fallback(lambda: pd.concat([md1, md2], axis=1))
        df_equals(got, pandas.concat([pdf1, pdf2], axis=1))
        got2 = assert_no_fallback(lambda: pd.concat([md1["a"], md2["c"]], axis=1))
        df_equals(got2, pandas.concat([pdf1["a"], pdf2["c"]], axis=1))

    def test_misaligned_falls_back_correct(self):
        md1, pdf1 = create_test_dfs({"a": [1.0, 2, 3]})
        md2, pdf2 = create_test_dfs({"z": [1.0, 2]})
        eval_general(
            md1, pdf1,
            lambda df: pd.concat([df, md2], axis=1)
            if df is md1
            else pandas.concat([df, pdf2], axis=1),
        )


class TestGroupbyDescribeDevice:
    def test_composite_device(self):
        n = 400
        md, pdf = create_test_dfs(
            {
                "k": _rng.integers(0, 6, n),
                "v": np.where(_rng.random(n) < 0.15, np.nan, _rng.normal(size=n)),
                "w": _rng.integers(0, 40, n),
            }
        )
        got = assert_no_fallback(lambda: md.groupby("k").describe())
        df_equals(got, pdf.groupby("k").describe())

    def test_str_key(self):
        n = 300
        s = np.array(["a", "b", "c"], dtype=object)[_rng.integers(0, 3, n)]
        md, pdf = create_test_dfs({"s": s, "v": _rng.normal(size=n)})
        got = assert_no_fallback(lambda: md.groupby("s").describe())
        df_equals(got, pdf.groupby("s").describe())

    def test_custom_percentiles_falls_back_correct(self):
        md, pdf = create_test_dfs(
            {"k": _rng.integers(0, 4, 100), "v": _rng.normal(size=100)}
        )
        eval_general(
            md, pdf, lambda df: df.groupby("k").describe(percentiles=[0.1])
        )


class TestStrLutOps:
    """String predicates/measures via the dictionary LUT (_try_str_lut):
    the pandas op runs once per category, results gather by code on device.
    String-output ops (.str.lower etc) stay host by design."""

    _WORDS = np.array(["Tokyo", "oslo9", "LIMA", "ca iro", "  x", "77"], dtype=object)

    @pytest.fixture
    def clean(self):
        vals = self._WORDS[_rng.integers(0, 6, 400)]
        return pd.Series(vals), pandas.Series(vals)

    @pytest.fixture
    def dirty(self):
        vals = self._WORDS[_rng.integers(0, 6, 400)].copy()
        vals[_rng.random(400) < 0.12] = np.nan
        return pd.Series(vals), pandas.Series(vals)

    @pytest.mark.parametrize(
        "op",
        [
            lambda s: s.str.len(),
            lambda s: s.str.contains("o"),
            lambda s: s.str.contains(r"\d", regex=True),
            lambda s: s.str.startswith("T"),
            lambda s: s.str.endswith("o"),
            lambda s: s.str.count("o"),
            lambda s: s.str.isdigit(),
            lambda s: s.str.isupper(),
            lambda s: s.str.match(r"[A-Z]"),
            lambda s: s.str.find("o"),
        ],
    )
    def test_clean_device(self, clean, op):
        md, ps = clean
        got = assert_no_fallback(lambda: op(md))
        df_equals(got, op(ps))

    @pytest.mark.parametrize(
        "op",
        [
            lambda s: s.str.len(),
            lambda s: s.str.contains("o"),
            lambda s: s.str.contains("o", na=False),
            lambda s: s.str.endswith("o"),
            lambda s: s.str.isupper(),
        ],
    )
    def test_nan_rows(self, dirty, op):
        md, ps = dirty
        got = op(md)
        df_equals(got, op(ps))

    def test_object_dtype_nan_mixed_output_falls_back_correct(self):
        s = pandas.Series(["ab", np.nan, "cd"], dtype=object)
        md = pd.Series(s)
        eval_general(md, s, lambda x: x.str.contains("a"))
        eval_general(md, s, lambda x: x.str.len())

    def test_string_output_ops_stay_correct(self, clean):
        md, ps = clean
        eval_general(md, ps, lambda s: s.str.lower())
        eval_general(md, ps, lambda s: s.str.strip())


class TestObjectDtypeRoundTrip:
    """pandas 3 infers str for plain object string arrays; to_pandas must
    reconstruct object columns as OBJECT (NumpyEADtype('object') also fails
    == np.dtype(object), so gates go through is_object_dtype)."""

    def test_object_series_round_trip(self):
        s = pandas.Series(["ab", np.nan, "cd"], dtype=object)
        md = pd.Series(s)
        assert md.dtype == s.dtype
        pandas.testing.assert_series_equal(md._to_pandas(), s)

    def test_object_mixed_bool_nan_result(self):
        s = pandas.Series([True, np.nan, False], dtype=object)
        md = pd.Series(s)
        pandas.testing.assert_series_equal(md._to_pandas(), s)


class TestGetDummiesDevice:
    """Series one-hot via dictionary/categorical codes (one equality kernel
    per category); numeric series keep the pandas path."""

    _CITIES3 = np.array(["tokyo", "oslo", "lima"], dtype=object)

    def _mk(self, nan=False, n=400):
        vals = self._CITIES3[_rng.integers(0, 3, n)].copy()
        if nan:
            vals[_rng.random(n) < 0.1] = np.nan
        return vals

    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"prefix": "c"},
            {"drop_first": True},
            {"dtype": np.int64},
        ],
    )
    def test_str_series(self, kw):
        vals = self._mk()
        got = assert_no_fallback(lambda: pd.get_dummies(pd.Series(vals), **kw))
        df_equals(got, pandas.get_dummies(pandas.Series(vals), **kw))

    @pytest.mark.parametrize("dummy_na", [False, True])
    def test_nan_rows(self, dummy_na):
        vals = self._mk(nan=True)
        got = assert_no_fallback(
            lambda: pd.get_dummies(pd.Series(vals), dummy_na=dummy_na)
        )
        df_equals(got, pandas.get_dummies(pandas.Series(vals), dummy_na=dummy_na))

    def test_categorical_includes_unobserved(self):
        cat = pandas.Categorical(
            self._mk(), categories=["tokyo", "oslo", "lima", "unused"]
        )
        got = assert_no_fallback(lambda: pd.get_dummies(pd.Series(cat)))
        df_equals(got, pandas.get_dummies(pandas.Series(cat)))

    def test_numeric_series_correct(self):
        ints = np.asarray(_rng.integers(0, 3, 60))
        df_equals(
            pd.get_dummies(pd.Series(ints)),
            pandas.get_dummies(pandas.Series(ints)),
        )


class TestStrLutExtensionDtypes:
    def test_na_backed_string_dtype_keeps_extension_results(self):
        # 'string' (NA-backed) produces Int64/boolean EXTENSION dtypes in
        # pandas; the LUT path must defer (r5 review)
        s = pandas.Series(["ab", "c"], dtype="string")
        md = pd.Series(s)
        df_equals(md.str.len(), s.str.len())
        assert md.str.len().dtype == s.str.len().dtype
        df_equals(md.str.contains("a"), s.str.contains("a"))

    def test_categorical_dummy_na_categorical_columns(self):
        cat = pandas.Categorical(["a", "b", None, "a"], categories=["a", "b", "u"])
        m = pd.get_dummies(pd.Series(cat), dummy_na=True)
        p = pandas.get_dummies(pandas.Series(cat), dummy_na=True)
        df_equals(m, p)
        assert type(m.columns).__name__ == type(p.columns).__name__
