"""Extensions API: per-backend accessor overrides + pre/post-op switch points.

Reference surface: modin/pandas/api/extensions/extensions.py:135-371 (the
``backend=`` parameter) and modin/core/storage_formats/pandas/
query_compiler_caster.py:660,1222 (post-op switch registration).
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.core.storage_formats.base.query_compiler_caster import (
    _POST_OP_SWITCH_POINTS,
    _PRE_OP_SWITCH_POINTS,
    register_function_for_post_op_switch,
    register_function_for_pre_op_switch,
)
from modin_tpu.core.storage_formats.native.query_compiler import (
    NativeQueryCompiler,
)
from modin_tpu.pandas.api.extensions import (
    register_dataframe_accessor,
    register_pd_accessor,
    register_series_accessor,
)
from modin_tpu.pandas.api.extensions.extensions import _EXTENSIONS, _SHADOWED


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("extension backend tests need the TpuOnJax default")


def _native_df(data):
    qc = NativeQueryCompiler.from_pandas(pandas.DataFrame(data))
    return pd.DataFrame(query_compiler=qc)


@pytest.fixture
def _clean_registry():
    """Snapshot + restore extension/switch registries around a test."""
    from modin_tpu.pandas.api.extensions.extensions import (
        _PD_EXTENSIONS,
        _PD_SHADOWED,
    )

    ext = {k: dict(v) for k, v in _EXTENSIONS.items()}
    shadowed = dict(_SHADOWED)
    pd_ext = {k: dict(v) for k, v in _PD_EXTENSIONS.items()}
    pd_shadowed = dict(_PD_SHADOWED)
    pre = set(_PRE_OP_SWITCH_POINTS)
    post = set(_POST_OP_SWITCH_POINTS)
    new_keys_before = set(_EXTENSIONS)
    yield
    for name in set(_PD_EXTENSIONS) - set(pd_ext):
        orig = _PD_SHADOWED.get(name)
        if orig is not None:
            pd.__dict__[name] = orig
    _PD_EXTENSIONS.clear()
    _PD_EXTENSIONS.update(pd_ext)
    _PD_SHADOWED.clear()
    _PD_SHADOWED.update(pd_shadowed)
    for key in set(_EXTENSIONS) - new_keys_before:
        cls, name = key
        orig = _SHADOWED.get(key)
        if orig is None:
            if name in cls.__dict__:
                delattr(cls, name)
        else:
            setattr(cls, name, orig)
    _EXTENSIONS.clear()
    _EXTENSIONS.update(ext)
    _SHADOWED.clear()
    _SHADOWED.update(shadowed)
    _PRE_OP_SWITCH_POINTS.clear()
    _PRE_OP_SWITCH_POINTS.update(pre)
    _POST_OP_SWITCH_POINTS.clear()
    _POST_OP_SWITCH_POINTS.update(post)


def test_accessor_all_backends(_clean_registry):
    @register_dataframe_accessor("total_cells")
    def total_cells(self):
        return int(self.shape[0] * self.shape[1])

    df = pd.DataFrame({"a": [1, 2, 3], "b": [4, 5, 6]})
    assert df.total_cells() == 6
    ndf = _native_df({"a": [1.0]})
    assert ndf.total_cells() == 1


def test_accessor_backend_scoped_invisible_elsewhere(_clean_registry):
    @register_dataframe_accessor("tpu_only_tag", backend="Tpu")
    def tpu_only_tag(self):
        return "on-device"

    tpu_df = pd.DataFrame({"a": [1, 2, 3]})
    assert tpu_df.tpu_only_tag() == "on-device"

    native_df = _native_df({"a": [1.0]})
    with pytest.raises(AttributeError):
        native_df.tpu_only_tag()


def test_accessor_backend_override_beats_all_backend(_clean_registry):
    @register_dataframe_accessor("which_backend")
    def which_any(self):
        return "any"

    @register_dataframe_accessor("which_backend", backend="Pandas")
    def which_native(self):
        return "native"

    assert pd.DataFrame({"a": [1]}).which_backend() == "any"
    assert _native_df({"a": [1.0]}).which_backend() == "native"


def test_accessor_override_existing_method_per_backend(_clean_registry):
    # overriding a REAL method for one backend keeps the stock behavior on
    # the other
    @register_series_accessor("sum", backend="Pandas")
    def fake_sum(self, *args, **kwargs):
        return -1

    native_s = _native_df({"a": [1.0, 2.0]})["a"]
    assert native_s.sum() == -1
    tpu_s = pd.Series([1.0, 2.0])
    assert float(tpu_s.sum()) == 3.0


def test_register_pd_accessor_backend_scoped(_clean_registry):
    @register_pd_accessor("read_tpu_tag", backend="Tpu")
    def read_tpu_tag():
        return "tpu-reader"

    assert pd.read_tpu_tag() == "tpu-reader"


def test_register_pd_accessor_non_callable(_clean_registry):
    """ADVICE r3: attribute access must return the object itself, not a
    callable shim (reference extensions.py:300)."""
    register_pd_accessor("tpu_answer", backend="Tpu")(42)
    assert pd.tpu_answer == 42
    register_pd_accessor("global_const")({"k": "v"})
    assert pd.global_const == {"k": "v"}


def test_register_pd_accessor_shadow_restores_original(_clean_registry):
    """A backend-scoped override of a stock function must fall back to the
    original on other backends."""
    original = pd.read_csv
    register_pd_accessor("read_csv", backend="Pandas")(lambda *a, **k: "native")
    # session backend is Tpu: the Pandas-scoped override must NOT apply
    assert pd.read_csv is original


def test_accessor_class_cached(_clean_registry):
    class MyAccessor:
        def __init__(self, obj):
            self._obj = obj

        def ncols(self):
            return self._obj.shape[1]

    register_dataframe_accessor("myacc")(MyAccessor)
    df = pd.DataFrame({"a": [1], "b": [2]})
    assert df.myacc.ncols() == 2


def test_post_op_switch_moves_small_result(_clean_registry):
    # melt has no TpuQC override, so the post-op point re-prices its small
    # fallback result and hands it to the in-process backend (describe, the
    # op used before r05, grew a device kernel whose zero stay-cost keeps
    # results on-device)
    register_function_for_post_op_switch(
        class_name=None, backend="Tpu", method="melt"
    )
    df = pd.DataFrame({"a": np.arange(100.0)})
    out = df.melt()
    assert type(out._query_compiler).__name__ == "NativeQueryCompiler"
    expected = pandas.DataFrame({"a": np.arange(100.0)}).melt()
    pandas.testing.assert_frame_equal(out._to_pandas(), expected)


def test_no_post_op_switch_without_registration(_clean_registry):
    df = pd.DataFrame({"a": np.arange(100.0)})
    out = df.describe()
    assert type(out._query_compiler).__name__ == "TpuQueryCompiler"


def test_pre_op_switch_point_moves_before_op(_clean_registry):
    register_function_for_pre_op_switch(
        class_name=None, backend="Tpu", method="nsmallest"
    )
    df = pd.DataFrame({"a": np.arange(50.0)})
    out = df.nsmallest(3, "a")
    expected = pandas.DataFrame({"a": np.arange(50.0)}).nsmallest(3, "a")
    pandas.testing.assert_frame_equal(
        out._to_pandas().astype(float), expected.astype(float)
    )
