"""Direct unit coverage for the memory ledgers (core/memory.py).

The host-cache ledger was previously exercised only indirectly through
frame workloads; these tests pin its contracts down in isolation —
weakref-callback reentrancy, LRU eviction order under a budget shrink, and
the ``Float64Policy=Downcast`` no-evict guard — plus the graftguard
device ledger's registration, LRU spill, and admission arithmetic.
"""

import gc

import numpy as np
import pytest

from modin_tpu.config import Float64Policy
from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn
from modin_tpu.core.memory import (
    _DeviceLedger,
    _HostCacheLedger,
    _evictable,
)


class _StubRaw:
    """Stands in for a device buffer: just a dtype and a byte size."""

    def __init__(self, dtype, nbytes=0):
        self.dtype = np.dtype(dtype)
        self.nbytes = nbytes


class _StubCol:
    """Minimal column satisfying both ledgers' protocols."""

    def __init__(self, nbytes, device_dtype="int64", pandas_dtype="int64"):
        self.host_cache = np.zeros(nbytes, dtype=np.uint8)
        self._data = _StubRaw(device_dtype, nbytes)
        self.pandas_dtype = np.dtype(pandas_dtype)
        self.is_lazy = False
        self._ledger_key = None
        self._dev_key = None
        self.spilled_calls = 0

    @property
    def raw(self):
        return self._data

    @property
    def is_spilled(self):
        return self._data is None

    def spill(self):
        if self._data is None:
            return 0
        freed = self._data.nbytes
        self._data = None
        self.spilled_calls += 1
        return freed


def _ledger_with_budget(monkeypatch, budget):
    ledger = _HostCacheLedger()
    monkeypatch.setattr(type(ledger), "budget", lambda self: budget)
    return ledger


# ====================================================================== #
# _HostCacheLedger
# ====================================================================== #


class TestHostCacheLedger:
    def test_register_and_total(self, monkeypatch):
        ledger = _ledger_with_budget(monkeypatch, None)
        cols = [_StubCol(100), _StubCol(50)]
        for c in cols:
            ledger.register(c)
        assert ledger.total_bytes() == 150

    def test_weakref_callback_reentrancy(self, monkeypatch):
        """A GC-fired callback runs ``_forget`` on the SAME thread that may
        already hold the ledger lock — the RLock must let it through, and
        the accounting must come out right."""
        ledger = _ledger_with_budget(monkeypatch, None)
        keep = _StubCol(100)
        doomed = _StubCol(70)
        ledger.register(keep)
        ledger.register(doomed)
        with ledger._lock:  # simulate "inside a ledger operation"
            del doomed
            gc.collect()  # fires the weakref callback -> _forget -> RLock
        assert ledger.total_bytes() == 100

    def test_eviction_order_under_budget_shrink(self, monkeypatch):
        """Insertion order is the LRU order; ``touch`` refreshes it, and a
        shrunk budget evicts the coldest evictable caches first."""
        budget = {"value": 1000}
        ledger = _HostCacheLedger()
        monkeypatch.setattr(
            type(ledger), "budget", lambda self: budget["value"]
        )
        a, b, c = _StubCol(100), _StubCol(100), _StubCol(100)
        for col in (a, b, c):
            ledger.register(col)
        ledger.touch(a)  # a is now the HOTTEST despite being oldest
        budget["value"] = 250  # shrink: ~one cache must go
        ledger.enforce()
        assert b.host_cache is None  # coldest evicted first
        assert a.host_cache is not None
        assert c.host_cache is not None
        budget["value"] = 150  # shrink again
        ledger.enforce()
        assert c.host_cache is None
        assert a.host_cache is not None  # the touched one survives longest
        assert ledger.total_bytes() == 100

    def test_downcast_no_evict_guard(self):
        """A logical float64 stored f32 on device (Float64Policy=Downcast)
        must never lose its host cache: the cache IS the exact copy."""
        with Float64Policy.context("Downcast"):
            col = DeviceColumn.from_numpy(
                np.random.default_rng(0).normal(size=64)
            )
            assert str(col.raw.dtype) == "float32"
            assert col.pandas_dtype == np.float64
            assert _evictable(col) is False
        # exact round-trip columns ARE evictable
        int_col = DeviceColumn.from_numpy(np.arange(64, dtype=np.int64))
        assert _evictable(int_col) is True

    def test_spilled_column_cache_is_never_evicted(self, monkeypatch):
        """After a graftguard spill the host copy is the ONLY copy —
        dropping it would lose data, budget pressure or not."""
        ledger = _ledger_with_budget(monkeypatch, 10)
        col = _StubCol(100)
        col._data = None  # spilled
        ledger.register(col)
        ledger.enforce()
        assert col.host_cache is not None

    def test_lazy_column_not_evicted(self, monkeypatch):
        ledger = _ledger_with_budget(monkeypatch, 10)
        col = _StubCol(100)
        col.is_lazy = True
        ledger.register(col)
        ledger.enforce()
        assert col.host_cache is not None

    def test_no_budget_never_evicts(self, monkeypatch):
        ledger = _ledger_with_budget(monkeypatch, None)
        cols = [_StubCol(10**6) for _ in range(3)]
        for c in cols:
            ledger.register(c)
        ledger.enforce()
        assert all(c.host_cache is not None for c in cols)


# ====================================================================== #
# _DeviceLedger (graftguard)
# ====================================================================== #


class TestDeviceLedger:
    def test_register_deregister_accounting(self):
        ledger = _DeviceLedger()
        col = _StubCol(4096)
        ledger.register(col)
        assert ledger.total_bytes() == 4096
        assert ledger.deregister(col) == 4096
        assert ledger.total_bytes() == 0
        assert ledger.deregister(col) == 0  # idempotent

    def test_reregistration_replaces_entry(self):
        ledger = _DeviceLedger()
        col = _StubCol(100)
        ledger.register(col)
        col._data = _StubRaw("int64", 300)  # buffer replaced (restore/reseat)
        ledger.register(col)
        assert ledger.total_bytes() == 300  # not 400

    def test_entry_dies_with_column(self):
        ledger = _DeviceLedger()
        col = _StubCol(512)
        ledger.register(col)
        del col
        gc.collect()
        assert ledger.total_bytes() == 0

    def test_spill_lru_cold_first_and_counts(self):
        ledger = _DeviceLedger()
        a, b, c = _StubCol(100), _StubCol(100), _StubCol(100)
        for col in (a, b, c):
            ledger.register(col)
        ledger.touch(a)
        freed = ledger.spill_lru(150)  # needs two spills, coldest first
        assert freed == 200
        assert b.spilled_calls == 1 and c.spilled_calls == 1
        assert a.spilled_calls == 0
        assert ledger.spill_count() == 2

    def test_spill_lru_excludes_op_inputs(self):
        ledger = _DeviceLedger()
        cold = _StubCol(100)
        pinned = _StubCol(100)
        ledger.register(cold)
        ledger.register(pinned)
        freed = ledger.spill_lru(10**9, exclude_ids={id(pinned.raw)})
        assert cold.spilled_calls == 1
        assert pinned.spilled_calls == 0
        assert freed == 100

    def test_admission_spills_only_on_projected_overflow(self, monkeypatch):
        import modin_tpu.core.memory as memory_mod

        ledger = _DeviceLedger()
        col = _StubCol(1000)
        ledger.register(col)
        monkeypatch.setattr(memory_mod, "_DEVICE_BUDGET", 2000)
        ledger.admit(500)  # 1000 + 500 fits
        assert col.spilled_calls == 0
        ledger.admit(1500)  # 1000 + 1500 overflows by 500
        assert col.spilled_calls == 1

    def test_admission_noop_without_budget(self, monkeypatch):
        import modin_tpu.core.memory as memory_mod

        ledger = _DeviceLedger()
        col = _StubCol(1000)
        ledger.register(col)
        monkeypatch.setattr(memory_mod, "_DEVICE_BUDGET", None)
        ledger.admit(10**12)
        assert col.spilled_calls == 0

    def test_live_columns_snapshot(self):
        ledger = _DeviceLedger()
        cols = [_StubCol(10) for _ in range(3)]
        for c in cols:
            ledger.register(c)
        assert set(map(id, ledger.live_columns())) == set(map(id, cols))
