"""Device paths for sort/search-shaped ops: nunique, quantile,
nlargest/nsmallest (lax.top_k), isin(value list).

Differential vs pandas, with path-taken assertions via the fallback
warning (tests.utils.assert_no_fallback)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import assert_no_fallback, create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(67)


@pytest.fixture
def dfs():
    n = 500
    v = _rng.normal(size=n)
    v[[3, 77, 200]] = np.nan
    data = {
        "k": _rng.integers(-10, 10, n),
        "v": v,
        "b": _rng.random(n) < 0.5,
    }
    return create_test_dfs(data)


class TestNunique:
    def test_frame_and_series(self, dfs):
        md, pdf = dfs
        for dropna in (True, False):
            got = assert_no_fallback(lambda: md.nunique(dropna=dropna))
            df_equals(got, pdf.nunique(dropna=dropna))
        assert md["k"].nunique() == pdf["k"].nunique()
        assert md["v"].nunique(dropna=False) == pdf["v"].nunique(dropna=False)

    def test_all_nan_and_constant(self):
        md, pdf = create_test_dfs({"a": [np.nan] * 6, "c": [2.5] * 6})
        eval_general(md, pdf, lambda df: df.nunique())
        eval_general(md, pdf, lambda df: df.nunique(dropna=False))


class TestQuantileDevice:
    @pytest.fixture
    def num_dfs(self, dfs):
        md, pdf = dfs
        return md[["k", "v"]], pdf[["k", "v"]]

    @pytest.mark.parametrize(
        "interpolation", ["linear", "lower", "higher", "midpoint", "nearest"]
    )
    def test_interpolations(self, num_dfs, interpolation):
        md, pdf = num_dfs
        got = assert_no_fallback(
            lambda: md.quantile(0.35, interpolation=interpolation)
        )
        df_equals(got, pdf.quantile(0.35, interpolation=interpolation))

    def test_list_q(self, num_dfs):
        md, pdf = num_dfs
        eval_general(md, pdf, lambda df: df.quantile([0.0, 0.25, 0.5, 1.0]))

    def test_bool_column_raises_like_pandas(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df.quantile(0.5))

    def test_series_and_edges(self, dfs):
        md, pdf = dfs
        assert np.isclose(md["v"].quantile(0.8), pdf["v"].quantile(0.8))
        # all-NaN -> NaN like pandas
        ma, pa = create_test_dfs({"a": [np.nan, np.nan]})
        eval_general(ma, pa, lambda df: df.quantile(0.5))

    def test_numeric_only_with_string_column(self):
        md, pdf = create_test_dfs({"a": [3.0, 1.0, 2.0], "s": ["x", "y", "z"]})
        eval_general(md, pdf, lambda df: df.quantile(0.5, numeric_only=True))


class TestTopK:
    def test_frame_nlargest_nsmallest(self, dfs):
        md, pdf = dfs
        for op in ("nlargest", "nsmallest"):
            got = assert_no_fallback(lambda: getattr(md, op)(7, "v"))
            df_equals(got, getattr(pdf, op)(7, "v"))
            eval_general(md, pdf, lambda df: getattr(df, op)(4, "k"))

    def test_series_topk(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df["v"].nlargest(6))
        eval_general(md, pdf, lambda df: df["k"].nsmallest(6))

    def test_nan_excluded_and_k_exceeds_valid(self):
        md, pdf = create_test_dfs({"v": [1.0, np.nan, 3.0, np.nan, 2.0]})
        eval_general(md, pdf, lambda df: df.nlargest(5, "v"))
        eval_general(md, pdf, lambda df: df["v"].nsmallest(10))

    def test_ties_keep_first(self):
        md, pdf = create_test_dfs({"v": [2.0, 1.0, 2.0, 2.0, 1.0]})
        eval_general(md, pdf, lambda df: df.nlargest(2, "v"))
        eval_general(md, pdf, lambda df: df["v"].nsmallest(1))

    def test_int64_extremes(self):
        lo, hi = np.iinfo(np.int64).min, np.iinfo(np.int64).max
        md, pdf = create_test_dfs({"v": [0, lo, hi, lo + 1, hi - 1, 5]})
        eval_general(md, pdf, lambda df: df.nlargest(3, "v"))
        eval_general(md, pdf, lambda df: df.nsmallest(3, "v"))

    def test_keep_variants_fall_back_correct(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df.nlargest(3, "k", keep="last"))
        eval_general(md, pdf, lambda df: df["k"].nlargest(3, keep="all"))

    def test_multi_column_falls_back_correct(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df.nlargest(5, ["k", "v"]))


class TestIsinDevice:
    def test_frame_and_series(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.isin([1, 2, -3]))
        df_equals(got, pdf.isin([1, 2, -3]))
        eval_general(md, pdf, lambda df: df["v"].isin([0.5]))

    def test_nan_matches_nan(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df["v"].isin([np.nan]))
        eval_general(md, pdf, lambda df: df.isin([np.nan, 1.0]))

    def test_bool_and_mixed_values(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df["b"].isin([True]))
        eval_general(md, pdf, lambda df: df.isin([True, 2, 0.5]))

    def test_nonscalar_values_fall_back_correct(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df["k"].isin(["x", 1]))
        eval_general(
            md, pdf, lambda df: df.isin({"k": [1, 2], "v": [0.5]})
        )
        # Series-valued isin aligns on index in DataFrame.isin
        eval_general(md, pdf, lambda df: df["k"].isin(df["k"].head(10)))


class TestReviewScenarios:
    """Exact shapes from review: NaN vs real infinities in top_k, uint64
    ordering, half-to-even nearest, int64-exact quantile and isin."""

    def test_topk_nan_vs_real_infinities(self):
        md, pdf = create_test_dfs({"v": [1.0, np.nan, -np.inf, np.inf]})
        eval_general(md, pdf, lambda df: df["v"].nlargest(4))
        eval_general(md, pdf, lambda df: df["v"].nsmallest(4))
        eval_general(md, pdf, lambda df: df.nlargest(3, "v"))

    def test_topk_uint64_above_sign_bit(self):
        vals = np.array([1, 2**63, 2**64 - 1, 7], dtype=np.uint64)
        md, pdf = create_test_dfs({"v": vals})
        eval_general(md, pdf, lambda df: df.nlargest(2, "v"))
        eval_general(md, pdf, lambda df: df.nsmallest(2, "v"))

    def test_quantile_nearest_half_to_even(self):
        md, pdf = create_test_dfs({"v": [10.0, 20.0, 30.0]})
        eval_general(
            md, pdf, lambda df: df["v"].quantile(0.75, interpolation="nearest")
        )
        eval_general(
            md, pdf, lambda df: df.quantile(0.25, interpolation="nearest")
        )

    def test_quantile_int64_exact_element_select(self):
        big = 2**53 + 1
        md, pdf = create_test_dfs({"v": [big, 5, big + 2]})
        for interp in ("lower", "higher", "nearest"):
            eval_general(
                md, pdf, lambda df, i=interp: df.quantile(1.0, interpolation=i)
            )
            got = md["v"].quantile(1.0, interpolation=interp)
            want = pdf["v"].quantile(1.0, interpolation=interp)
            assert got == want and type(got) is type(want), (got, want)

    def test_isin_int64_beyond_f64_precision(self):
        big = 2**53
        md, pdf = create_test_dfs({"v": np.array([big, big + 1], dtype=np.int64)})
        # all-int value lists compare exactly (numpy int promotion)...
        eval_general(md, pdf, lambda df: df["v"].isin([big]))
        eval_general(md, pdf, lambda df: df["v"].isin([big + 1]))
        # ...while a float in the list promotes the whole comparison to
        # float64, lossy — exactly as pandas behaves
        eval_general(md, pdf, lambda df: df["v"].isin([0.5, big]))


class TestDuplicatedDevice:
    """Device duplicated/drop_duplicates via rank-fold row codes."""

    @pytest.fixture
    def dup_dfs(self):
        rng = np.random.default_rng(91)
        n = 400
        v = rng.normal(size=n).round(1)
        v[::13] = np.nan
        return create_test_dfs(
            {"k": rng.integers(0, 6, n), "v": v, "b": rng.random(n) < 0.5}
        )

    @pytest.mark.parametrize("keep", ["first", "last", False])
    def test_duplicated_keeps(self, dup_dfs, keep):
        md, pdf = dup_dfs
        got = assert_no_fallback(lambda: md.duplicated(keep=keep))
        df_equals(got, pdf.duplicated(keep=keep))

    def test_subset_and_nan_equality(self, dup_dfs):
        md, pdf = dup_dfs
        eval_general(md, pdf, lambda df: df.duplicated(subset=["k"]))
        eval_general(md, pdf, lambda df: df.duplicated(subset=["v", "k"]))
        # every NaN is a duplicate of every other NaN, like pandas
        ma, pa = create_test_dfs({"x": [np.nan, 1.0, np.nan, np.nan]})
        eval_general(ma, pa, lambda df: df.duplicated())

    @pytest.mark.parametrize("keep", ["first", "last"])
    def test_drop_duplicates(self, dup_dfs, keep):
        md, pdf = dup_dfs
        got = assert_no_fallback(lambda: md.drop_duplicates(keep=keep))
        df_equals(got, pdf.drop_duplicates(keep=keep))
        eval_general(
            md, pdf,
            lambda df: df.drop_duplicates(subset=["k"], ignore_index=True),
        )

    def test_series_duplicated_keeps_name(self, dup_dfs):
        md, pdf = dup_dfs
        eval_general(md, pdf, lambda df: df["v"].duplicated())
        eval_general(md, pdf, lambda df: df["k"].duplicated(keep=False))

    def test_missing_subset_label_raises(self, dup_dfs):
        md, pdf = dup_dfs
        eval_general(md, pdf, lambda df: df.duplicated(subset=["nope"]))

    def test_string_column_falls_back_correct(self):
        md, pdf = create_test_dfs({"s": ["a", "b", "a"], "v": [1.0, 2.0, 1.0]})
        eval_general(md, pdf, lambda df: df.duplicated())
        eval_general(md, pdf, lambda df: df.drop_duplicates())

    def test_arraylike_subset_and_ignore_index_residency(self, dup_dfs):
        md, pdf = dup_dfs
        eval_general(md, pdf, lambda df: df.duplicated(subset=np.array(["k", "v"])))
        eval_general(md, pdf, lambda df: df.duplicated(subset=pandas.Index(["k"])))
        # ignore_index must not bounce through a pandas round trip
        got = assert_no_fallback(
            lambda: md.drop_duplicates(subset=["k"], ignore_index=True)
        )
        df_equals(got, pdf.drop_duplicates(subset=["k"], ignore_index=True))
        assert all(
            c.is_device for c in got._query_compiler._modin_frame._columns
            if c.pandas_dtype.kind in "biuf"
        )


class TestRankDevice:
    """Device rank: sorted tie-group statistics with pandas NaN zones."""

    @pytest.fixture
    def rank_dfs(self):
        rng = np.random.default_rng(101)
        n = 300
        v = rng.normal(size=n).round(1)
        v[::11] = np.nan
        return create_test_dfs(
            {"k": rng.integers(-4, 4, n), "v": v, "b": rng.random(n) < 0.5}
        )

    @pytest.mark.parametrize("method", ["average", "min", "max", "first", "dense"])
    @pytest.mark.parametrize("ascending", [True, False])
    def test_methods(self, rank_dfs, method, ascending):
        md, pdf = rank_dfs
        got = assert_no_fallback(
            lambda: md.rank(method=method, ascending=ascending)
        )
        df_equals(got, pdf.rank(method=method, ascending=ascending))

    @pytest.mark.parametrize("na_option", ["keep", "top", "bottom"])
    @pytest.mark.parametrize("pct", [False, True])
    def test_na_and_pct(self, rank_dfs, na_option, pct):
        md, pdf = rank_dfs
        eval_general(
            md, pdf, lambda df: df.rank(na_option=na_option, pct=pct)
        )
        eval_general(
            md, pdf,
            lambda df: df["v"].rank(
                method="dense", na_option=na_option, pct=pct
            ),
        )

    def test_numeric_only_and_string_fallback(self):
        md, pdf = create_test_dfs({"a": [3.0, 1.0, 2.0], "s": ["x", "z", "y"]})
        eval_general(md, pdf, lambda df: df.rank(numeric_only=True))
        eval_general(md, pdf, lambda df: df.rank())  # lexical string ranks
        eval_general(md, pdf, lambda df: df.rank(axis=1))

    def test_all_nan_and_ties(self):
        md, pdf = create_test_dfs({"a": [np.nan, np.nan], "t": [1.0, 1.0]})
        eval_general(md, pdf, lambda df: df.rank())
        eval_general(md, pdf, lambda df: df.rank(method="dense", pct=True))

    def test_uint64_above_sign_bit(self):
        vals = np.array([2**63, 1, 2**64 - 1, 5], dtype=np.uint64)
        md, pdf = create_test_dfs({"u": vals})
        eval_general(md, pdf, lambda df: df.rank())
        eval_general(md, pdf, lambda df: df.rank(ascending=False, method="min"))

    @pytest.mark.parametrize("keep", ["first", "last", False])
    def test_series_drop_duplicates(self, keep):
        rng = np.random.default_rng(71)
        n = 200
        v = rng.normal(size=n).round(1)
        v[::9] = np.nan
        md, pdf = create_test_dfs({"k": rng.integers(0, 5, n), "v": v})
        eval_general(md, pdf, lambda df: df["v"].drop_duplicates(keep=keep))
        eval_general(
            md, pdf,
            lambda df: df["k"].drop_duplicates(keep=keep, ignore_index=True),
        )

    def test_series_drop_duplicates_string_fallback(self):
        ms = pd.Series(["a", "b", "a"], name="s")
        ps = pandas.Series(["a", "b", "a"], name="s")
        eval_general(ms, ps, lambda s: s.drop_duplicates())


class TestModeDevice:
    """Device mode kernels (ops/reductions.mode_columns / mode_axis1).

    Parity surface: pandas DataFrame.mode, both axes (the reference defaults
    mode to a full-column fold — modin/core/storage_formats/pandas/
    query_compiler.py)."""

    @pytest.fixture
    def int_dfs(self):
        return create_test_dfs(
            {f"c{i}": _rng.integers(0, 10, 400) for i in range(4)}
        )

    @pytest.fixture
    def nan_dfs(self):
        data = {
            f"c{i}": np.where(
                _rng.random(400) < 0.15,
                np.nan,
                _rng.integers(0, 8, 400).astype(float),
            )
            for i in range(3)
        }
        return create_test_dfs(data)

    def test_axis0_int(self, int_dfs):
        md, pdf = int_dfs
        got = assert_no_fallback(lambda: md.mode())
        df_equals(got, pdf.mode())

    def test_axis0_nan(self, nan_dfs):
        md, pdf = nan_dfs
        got = assert_no_fallback(lambda: md.mode())
        df_equals(got, pdf.mode())

    def test_axis0_bool(self):
        md, pdf = create_test_dfs(
            {"a": _rng.random(100) < 0.5, "b": _rng.random(100) < 0.2}
        )
        got = assert_no_fallback(lambda: md.mode())
        df_equals(got, pdf.mode())

    def test_axis0_ties_ascending(self):
        md, pdf = create_test_dfs(
            {"a": [1, 1, 2, 2, 3], "b": [5, 5, 5, 1, 1]}
        )
        got = assert_no_fallback(lambda: md.mode())
        df_equals(got, pdf.mode())

    def test_axis1_int(self, int_dfs):
        md, pdf = int_dfs
        got = assert_no_fallback(lambda: md.mode(axis=1))
        df_equals(got, pdf.mode(axis=1))

    def test_axis1_nan(self, nan_dfs):
        md, pdf = nan_dfs
        got = assert_no_fallback(lambda: md.mode(axis=1))
        df_equals(got, pdf.mode(axis=1))

    def test_axis1_mixed_dtypes(self):
        data = {
            "a": _rng.integers(0, 5, 300),
            "b": _rng.random(300).round(1),
            "c": np.where(
                _rng.random(300) < 0.05,
                np.nan,
                _rng.integers(0, 3, 300).astype(float),
            ),
        }
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: df.mode(axis=1))

    def test_dropna_false_falls_back_correct(self, nan_dfs):
        md, pdf = nan_dfs
        eval_general(md, pdf, lambda df: df.mode(dropna=False))

    def test_all_nan_column_falls_back_correct(self):
        md, pdf = create_test_dfs({"a": [np.nan] * 5, "b": [1.0] * 5})
        eval_general(md, pdf, lambda df: df.mode())


class TestNuniqueAxis1:
    def test_int(self):
        md, pdf = create_test_dfs(
            {f"c{i}": _rng.integers(0, 4, 300) for i in range(5)}
        )
        got = assert_no_fallback(lambda: md.nunique(axis=1))
        df_equals(got, pdf.nunique(axis=1))

    def test_nan_both_dropna(self):
        data = {
            f"c{i}": np.where(
                _rng.random(300) < 0.2,
                np.nan,
                _rng.integers(0, 4, 300).astype(float),
            )
            for i in range(4)
        }
        md, pdf = create_test_dfs(data)
        for dropna in (True, False):
            got = assert_no_fallback(lambda: md.nunique(axis=1, dropna=dropna))
            df_equals(got, pdf.nunique(axis=1, dropna=dropna))

    def test_all_nan_row(self):
        md, pdf = create_test_dfs(
            {"a": [np.nan, 1.0], "b": [np.nan, 2.0]}
        )
        eval_general(md, pdf, lambda df: df.nunique(axis=1))
        eval_general(md, pdf, lambda df: df.nunique(axis=1, dropna=False))


class TestTransposeWide:
    def test_wide_result_correct(self):
        md, pdf = create_test_dfs(
            {f"c{i}": _rng.integers(0, 10, 5000) for i in range(3)}
        )
        df_equals(md.T, pdf.T)

    def test_wide_result_fast(self):
        """A 1e5-row transpose must not build 1e5 per-column objects (was
        ~20s before the Native escape; now bounded by one host gather)."""
        import time

        md, _ = create_test_dfs(
            {f"c{i}": _rng.integers(0, 10, 100_000) for i in range(3)}
        )
        md._query_compiler.execute()
        t0 = time.time()
        res = md.T
        res._query_compiler.execute()
        assert time.time() - t0 < 5.0
        assert res.shape == (3, 100_000)

    def test_small_roundtrip_unchanged(self):
        md, pdf = create_test_dfs({"a": [1, 2], "b": [3, 4]})
        df_equals(md.T, pdf.T)
        df_equals(md.T.T, pdf)
