"""Direct unit tests for the NaN-adaptive single-shard reduction kernels.

The suite's virtual mesh is 8 devices, so the QC never takes the adaptive
path (it is gated on num_row_shards() == 1 — the single-chip bench
topology).  These tests jit the kernel directly on unsharded arrays and
compare both adaptive and masked forms against pandas.
"""

import jax
import numpy as np
import pandas
import pytest

from modin_tpu.ops.reductions import _reduce_one
from tests.utils import require_tpu_execution

OPS = ["sum", "prod", "count", "min", "max", "mean", "var", "std", "sem"]

CASES = {
    "clean": np.random.default_rng(0).uniform(-10, 10, 64),
    "with_nans": np.where(
        np.random.default_rng(1).random(64) < 0.3,
        np.nan,
        np.random.default_rng(2).normal(size=64),
    ),
    "all_nan": np.full(16, np.nan),
    "single": np.array([3.5]),
    "single_nan": np.array([np.nan]),
}


def _pandas_ref(op, values, ddof=1):
    s = pandas.Series(values)
    if op in ("var", "std", "sem"):
        return getattr(s, op)(ddof=ddof)
    return getattr(s, op)()


@pytest.mark.parametrize("case", list(CASES))
@pytest.mark.parametrize("op", OPS)
@pytest.mark.parametrize("dtype", [np.float64, np.float32])
def test_adaptive_matches_pandas(op, case, dtype):
    values = CASES[case].astype(dtype)
    n = len(values)
    c = jax.numpy.asarray(values)
    fn = jax.jit(lambda c: _reduce_one(op, c, n, True, 1, adaptive=True))
    got = np.asarray(fn(c))
    expected = _pandas_ref(op, pandas.Series(values))
    rtol = 1e-5 if dtype == np.float32 else 1e-12
    if isinstance(expected, float) and np.isnan(expected):
        assert np.isnan(got), (op, case, got)
    else:
        np.testing.assert_allclose(got, expected, rtol=rtol)


@pytest.mark.parametrize("case", list(CASES))
@pytest.mark.parametrize("op", OPS)
def test_adaptive_agrees_with_masked(op, case):
    values = CASES[case]
    n = len(values)
    c = jax.numpy.asarray(values)
    adaptive = np.asarray(jax.jit(lambda c: _reduce_one(op, c, n, True, 1, adaptive=True))(c))
    masked = np.asarray(jax.jit(lambda c: _reduce_one(op, c, n, True, 1, adaptive=False))(c))
    np.testing.assert_allclose(adaptive, masked, rtol=1e-12, equal_nan=True)


@pytest.mark.parametrize("agg", ["sum", "mean", "count"])
@pytest.mark.parametrize("case", ["clean", "with_nans", "all_nan"])
def test_adaptive_segment_agg_matches_masked(agg, case):
    """The single-shard NaN-adaptive groupby kernel must match the masked
    segment kernel (the suite's 8-shard mesh never exercises adaptive=True)."""
    import jax.numpy as jnp

    from modin_tpu.ops.groupby import _jit_segment_agg

    rng = np.random.default_rng(4)
    n, groups = 512, 9
    codes = jnp.asarray(rng.integers(0, groups, n))
    base = rng.normal(size=n)
    if case == "with_nans":
        base = np.where(rng.random(n) < 0.3, np.nan, base)
    elif case == "all_nan":
        base = np.full(n, np.nan)
    cols = (
        jnp.asarray(base),
        jnp.asarray(rng.normal(size=n)),
        jnp.asarray(base.astype(np.float32)),  # cond branch dtype parity
        jnp.asarray(rng.integers(0, 50, n)),  # int routing via masked path
    )
    ns, p_out = groups + 1, groups
    got = _jit_segment_agg(agg, 4, ns, 1, p_out, True)(cols, codes)
    want = _jit_segment_agg(agg, 4, ns, 1, p_out, False)(cols, codes)
    for g, w in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=1e-12, equal_nan=True
        )


class TestShardedAdaptive:
    """Multi-shard NaN-adaptive form: the lax.cond runs per shard inside
    shard_map (a global cond over sharded operands miscompiles under SPMD),
    partials combine outside.  Runs on the suite's 8-device virtual mesh."""

    SHARDED_OPS = ["sum", "prod", "count", "min", "max", "mean"]

    @pytest.mark.parametrize("op", SHARDED_OPS)
    @pytest.mark.parametrize(
        "case", ["clean", "with_nans", "all_nan_shard", "all_nan", "one_nan"]
    )
    def test_matches_pandas_on_8_shards(self, op, case):
        from modin_tpu.ops.reductions import _reduce_adaptive_sharded
        from modin_tpu.parallel.mesh import num_row_shards, row_sharding

        S = num_row_shards()
        if S < 2:
            pytest.skip("needs a multi-device mesh")
        n = 16 * S
        rng = np.random.default_rng(7)
        values = rng.uniform(-10, 10, n)
        if case == "with_nans":
            values[rng.random(n) < 0.3] = np.nan
        elif case == "all_nan_shard":
            values[: n // S] = np.nan  # shard 0 entirely NaN
        elif case == "all_nan":
            values[:] = np.nan
        elif case == "one_nan":
            values[n // 2] = np.nan
        c = jax.device_put(jax.numpy.asarray(values), row_sharding())
        fn = jax.jit(lambda c: _reduce_adaptive_sharded(op, c, n))
        got = np.asarray(fn(c))
        expected = _pandas_ref(op, pandas.Series(values))
        if isinstance(expected, float) and np.isnan(expected):
            assert np.isnan(got), (op, case, got)
        else:
            np.testing.assert_allclose(
                got, expected, rtol=1e-12, err_msg=f"{op} {case}"
            )

    def test_qc_reduction_takes_sharded_adaptive_path(self, monkeypatch):
        """df.sum() on an evenly-sharded float frame must route through the
        shard_map formulation (and agree with pandas)."""
        require_tpu_execution()
        import modin_tpu.ops.reductions as red
        from modin_tpu.parallel.mesh import num_row_shards

        if num_row_shards() < 2:
            pytest.skip("needs a multi-device mesh")
        import modin_tpu.pandas as pd

        calls = []
        orig = red._reduce_adaptive_sharded

        def spy(op, c, n):
            out = orig(op, c, n)
            if out is not None:
                calls.append(op)
            return out

        monkeypatch.setattr(red, "_reduce_adaptive_sharded", spy)
        # the spy fires at TRACE time; drop the fused-program cache so a
        # same-fingerprint reduction from an earlier test cannot skip it
        from modin_tpu.ops import lazy

        lazy._FUSED_CACHE.clear()
        n = 64 * num_row_shards()
        vals = np.random.default_rng(3).normal(size=n)
        vals[5] = np.nan
        md = pd.DataFrame({"a": vals})
        got = md.sum()._to_pandas()
        want = pandas.DataFrame({"a": vals}).sum()
        assert calls, "sharded adaptive path not taken"
        np.testing.assert_allclose(got.to_numpy(), want.to_numpy(), rtol=1e-12)
