"""Differential groupby tests (modeled on modin/tests/pandas/test_groupby.py)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import assert_no_fallback, create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(7)
N = 200

GB_DATA = {
    "int_key": _rng.integers(0, 10, N),
    "sparse_key": _rng.choice([3, 70, 1000, -5], N),
    "float_key": _rng.choice([0.5, 1.25, np.nan, 7.0], N),
    "val_int": _rng.integers(-50, 50, N),
    "val_float": np.where(_rng.random(N) < 0.2, np.nan, _rng.uniform(-1, 1, N)),
    "val_bool": _rng.random(N) < 0.5,
}

AGGS = ["sum", "count", "mean", "min", "max", "prod", "var", "std", "sem", "any", "all"]


@pytest.fixture
def dfs():
    return create_test_dfs(GB_DATA)


@pytest.mark.parametrize("agg", AGGS)
@pytest.mark.parametrize("key", ["int_key", "sparse_key", "float_key"])
def test_groupby_agg(dfs, agg, key):
    md, pdf = dfs
    df_equals(
        getattr(md.groupby(key), agg)(),
        getattr(pdf.groupby(key), agg)(),
    )


@pytest.mark.parametrize("agg", ["sum", "mean", "count"])
def test_groupby_multikey(dfs, agg):
    md, pdf = dfs
    df_equals(
        getattr(md.groupby(["int_key", "sparse_key"]), agg)(),
        getattr(pdf.groupby(["int_key", "sparse_key"]), agg)(),
    )


def test_groupby_size(dfs):
    md, pdf = dfs
    df_equals(md.groupby("int_key").size(), pdf.groupby("int_key").size())


def test_groupby_selection(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")["val_float"].sum(),
        pdf.groupby("int_key")["val_float"].sum(),
    )
    df_equals(
        md.groupby("int_key")[["val_int", "val_float"]].mean(),
        pdf.groupby("int_key")[["val_int", "val_float"]].mean(),
    )


def test_groupby_as_index_false(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key", as_index=False).sum(),
        pdf.groupby("int_key", as_index=False).sum(),
    )


def test_groupby_dropna_false(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("float_key", dropna=False).sum(),
        pdf.groupby("float_key", dropna=False).sum(),
    )


def test_groupby_external_series(dfs):
    md, pdf = dfs
    df_equals(
        md["val_float"].groupby(md["int_key"]).sum(),
        pdf["val_float"].groupby(pdf["int_key"]).sum(),
    )


def test_groupby_numeric_only_with_strings():
    md, pdf = create_test_dfs(
        {"k": [1, 1, 2], "v": [1.0, 2.0, 3.0], "s": ["a", "b", "c"]}
    )
    df_equals(
        md.groupby("k").sum(numeric_only=True),
        pdf.groupby("k").sum(numeric_only=True),
    )
    # numeric_only=False concatenates strings — host fallback path
    df_equals(md.groupby("k").sum(), pdf.groupby("k").sum())


def test_groupby_min_count(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key").sum(min_count=15),
        pdf.groupby("int_key").sum(min_count=15),
    )


def test_groupby_median_quantile(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")[["val_int", "val_float"]].median(),
        pdf.groupby("int_key")[["val_int", "val_float"]].median(),
    )
    df_equals(
        md.groupby("int_key")[["val_int", "val_float"]].quantile(0.25),
        pdf.groupby("int_key")[["val_int", "val_float"]].quantile(0.25),
    )


@pytest.mark.parametrize("interp", ["linear", "lower", "higher", "midpoint", "nearest"])
@pytest.mark.parametrize("q", [0.1, 0.25, 0.5, 0.75, 0.9])
def test_groupby_quantile_device(dfs, q, interp):
    # device path: no default-to-pandas fallback permitted
    md, pdf = dfs
    assert_no_fallback(lambda: df_equals(
            md.groupby("int_key")[["val_int", "val_float"]].quantile(q, interpolation=interp),
            pdf.groupby("int_key")[["val_int", "val_float"]].quantile(q, interpolation=interp),
    ))


@pytest.mark.parametrize("agg", ["median", "nunique", "first", "last"])
@pytest.mark.parametrize("key", ["int_key", "sparse_key", "float_key"])
def test_groupby_order_aggs_device(dfs, agg, key):
    md, pdf = dfs
    assert_no_fallback(lambda: df_equals(
            getattr(md.groupby(key)[["val_int", "val_float"]], agg)(),
            getattr(pdf.groupby(key)[["val_int", "val_float"]], agg)(),
    ))


@pytest.mark.parametrize("agg", ["median", "nunique", "first", "last"])
def test_groupby_order_aggs_multikey(dfs, agg):
    md, pdf = dfs
    assert_no_fallback(lambda: df_equals(
            getattr(md.groupby(["int_key", "sparse_key"])[["val_int", "val_float"]], agg)(),
            getattr(pdf.groupby(["int_key", "sparse_key"])[["val_int", "val_float"]], agg)(),
    ))


def test_groupby_nunique_dropna(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")["val_float"].nunique(dropna=False),
        pdf.groupby("int_key")["val_float"].nunique(dropna=False),
    )


def test_groupby_apply_transform(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")["val_int"].transform("mean"),
        pdf.groupby("int_key")["val_int"].transform("mean"),
    )


def test_groupby_agg_dict(dfs):
    md, pdf = dfs
    spec = {"val_int": "sum", "val_float": "mean"}
    df_equals(md.groupby("int_key").agg(spec), pdf.groupby("int_key").agg(spec))


def test_groupby_iteration(dfs):
    md, pdf = dfs
    for (mk, mg), (pk, pg) in zip(md.groupby("int_key"), pdf.groupby("int_key")):
        assert mk == pk
        df_equals(mg, pg)


def test_groupby_sort_false(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key", sort=False).sum().sort_index(),
        pdf.groupby("int_key", sort=False).sum().sort_index(),
    )


def test_groupby_bool_key(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("val_bool").sum(),
        pdf.groupby("val_bool").sum(),
    )


def test_groupby_cumulative(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")["val_int"].cumsum(),
        pdf.groupby("int_key")["val_int"].cumsum(),
    )


@pytest.mark.parametrize("agg", ["sum", "count", "mean", "min", "max", "prod", "any", "all"])
def test_groupby_masked_scan_kernel_matches(agg, monkeypatch):
    """The TPU masked-scan kernel must match the segment kernel numerics."""
    from modin_tpu.ops import groupby as gb_ops
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("device kernels")
    md, pdf = create_test_dfs(GB_DATA)
    monkeypatch.setattr(gb_ops, "_FORCE_KERNEL", "masked_scan")
    df_equals(
        getattr(md.groupby("int_key"), agg)(),
        getattr(pdf.groupby("int_key"), agg)(),
    )


def test_pallas_bincount_matches_scatter(monkeypatch):
    """The pallas histogram must agree with the XLA scatter path (interpret
    mode exercises the kernel on CPU)."""
    import jax.numpy as jnp

    from modin_tpu.ops.pallas.groupby_kernels import pallas_bincount
    from modin_tpu.ops.groupby import _jit_scatter_counts

    rng = np.random.default_rng(1)
    for n, width in [(777, 3), (50_000, 100), (12_345, 512)]:
        ids_np = rng.integers(0, width + 1, n)
        ids = jnp.asarray(ids_np)
        got = np.asarray(pallas_bincount(ids, width, interpret=True))
        want = np.asarray(_jit_scatter_counts(width)(ids))
        np.testing.assert_array_equal(got, want)


def test_groupby_agg_list_device(dfs):
    md, pdf = dfs
    got = assert_no_fallback(
        lambda: md.groupby("int_key")[["val_int", "val_float"]].agg(["sum", "mean", "median"])
    )
    df_equals(got, pdf.groupby("int_key")[["val_int", "val_float"]].agg(["sum", "mean", "median"]))


def test_groupby_agg_dict_device(dfs):
    md, pdf = dfs
    spec = {"val_int": "max", "val_float": "mean"}
    got = assert_no_fallback(lambda: md.groupby("int_key").agg(spec))
    df_equals(got, pdf.groupby("int_key").agg(spec))


def test_groupby_series_agg_list_device(dfs):
    md, pdf = dfs
    got = assert_no_fallback(lambda: md.groupby("int_key")["val_float"].agg(["sum", "max"]))
    df_equals(got, pdf.groupby("int_key")["val_float"].agg(["sum", "max"]))


def test_groupby_agg_callable_falls_back(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")[["val_float"]].agg(["sum", lambda s: s.max()]),
        pdf.groupby("int_key")[["val_float"]].agg(["sum", lambda s: s.max()]),
    )


def test_groupby_agg_single_element_list_is_frame(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")["val_float"].agg(["sum"]),
        pdf.groupby("int_key")["val_float"].agg(["sum"]),
    )


def test_groupby_agg_duplicate_names_raise(dfs):
    md, pdf = dfs
    from tests.utils import eval_general

    eval_general(
        md, pdf,
        lambda df: df.groupby("int_key")[["val_float"]].agg(["sum", "sum"]),
    )


@pytest.mark.parametrize("agg", ["sum", "mean", "min", "max", "count", "var", "std"])
def test_groupby_transform_device(dfs, agg):
    md, pdf = dfs
    got = assert_no_fallback(
        lambda: md.groupby("int_key")[["val_int", "val_float"]].transform(agg)
    )
    df_equals(got, pdf.groupby("int_key")[["val_int", "val_float"]].transform(agg))


def test_groupby_series_transform_device(dfs):
    md, pdf = dfs
    got = assert_no_fallback(lambda: md.groupby("int_key")["val_float"].transform("mean"))
    df_equals(got, pdf.groupby("int_key")["val_float"].transform("mean"))


def test_groupby_transform_callable_falls_back(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("int_key")[["val_float"]].transform(lambda s: s - s.mean()),
        pdf.groupby("int_key")[["val_float"]].transform(lambda s: s - s.mean()),
    )


def test_groupby_transform_float_key_falls_back(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("float_key")[["val_float"]].transform("sum"),
        pdf.groupby("float_key")[["val_float"]].transform("sum"),
    )


@pytest.mark.parametrize("op", ["cumsum", "cumprod", "cummax", "cummin"])
def test_groupby_cumulative_device(dfs, op):
    md, pdf = dfs
    got = assert_no_fallback(
        lambda: getattr(md.groupby("int_key")[["val_int", "val_float"]], op)()
    )
    df_equals(got, getattr(pdf.groupby("int_key")[["val_int", "val_float"]], op)())


def test_groupby_series_cumsum_device(dfs):
    md, pdf = dfs
    got = assert_no_fallback(lambda: md.groupby("int_key")["val_float"].cumsum())
    df_equals(got, pdf.groupby("int_key")["val_float"].cumsum())


def test_groupby_cumulative_float_key_falls_back(dfs):
    md, pdf = dfs
    df_equals(
        md.groupby("float_key")[["val_float"]].cumsum(),
        pdf.groupby("float_key")[["val_float"]].cumsum(),
    )


def test_groupby_cumsum_narrow_int_promotes():
    # pandas 3 promotes signed sub-int64 cumsum/cumprod to int64 (no wrap)
    md, pdf = create_test_dfs(
        {"k": [0, 0, 1], "v": np.array([100, 100, 7], dtype=np.int8)}
    )
    df_equals(md.groupby("k").cumsum(), pdf.groupby("k").cumsum())
    df_equals(md.groupby("k").cummax(), pdf.groupby("k").cummax())


@pytest.mark.parametrize("agg", ["sum", "count", "mean"])
@pytest.mark.parametrize("adaptive", [False, True])
@pytest.mark.parametrize("has_sizes", [False, True])
@pytest.mark.parametrize("with_nan", [False, True])
def test_masked_scan_smc_kernel_direct(agg, adaptive, has_sizes, with_nan):
    """The shared-histogram sum/mean/count scan matches numpy for every
    (adaptive, provided-sizes, NaN-present) combination and mixed dtypes."""
    import jax.numpy as jnp

    from modin_tpu.ops.groupby import _jit_masked_scan_smc
    from modin_tpu.ops.structural import pad_len

    if has_sizes and agg == "sum":
        pytest.skip("sizes operand is only wired for mean/count")
    rng = np.random.default_rng(7)
    n, n_groups = 10_000, 13
    codes_np = rng.integers(0, n_groups, n)
    f = rng.uniform(-5, 5, n)
    if with_nan:
        f[rng.integers(0, n, 500)] = np.nan
    i = rng.integers(-100, 100, n)
    f32 = f.astype(np.float32)

    ns = n_groups + 1
    p_out = pad_len(n_groups)
    fn = _jit_masked_scan_smc(agg, 3, ns, p_out, 1024, adaptive, has_sizes)
    cols = (jnp.asarray(f), jnp.asarray(i), jnp.asarray(f32))
    codes = jnp.asarray(codes_np)
    if has_sizes:
        sizes = np.bincount(codes_np, minlength=n_groups).astype(np.int64)
        out = fn(cols, codes, jnp.asarray(np.append(sizes, 1)))
    else:
        out = fn(cols, codes)

    import pandas as pandas_mod

    pdf = pandas_mod.DataFrame({"f": f, "i": i, "f32": f32, "k": codes_np})
    want = getattr(pdf.groupby("k"), agg)()
    for ci, name in enumerate(["f", "i", "f32"]):
        got = np.asarray(out[ci])[:n_groups]
        # near-zero group sums of +/- uniforms make pure-relative checks
        # meaningless; bound the summation-order error absolutely too
        np.testing.assert_allclose(
            got.astype(np.float64), want[name].to_numpy(np.float64),
            rtol=1e-5 if name == "f32" else 1e-9,
            atol=1e-3 if name == "f32" else 1e-9,
            err_msg=f"col={name}",
        )
    if agg == "mean":
        # f32 means must stay f32 (pandas dtype parity)
        assert out[2].dtype == jnp.float32


class TestShuffleGroupbyApply:
    """Non-reducible UDFs through the range-partition shuffle (reference
    dataframe.py:4163,2565): groups never span chunks, host memory is
    O(chunk), results match the full-frame pandas oracle."""

    @pytest.fixture
    def big(self, monkeypatch):
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        monkeypatch.setattr(qc_mod, "_SHUFFLE_APPLY_MIN_ROWS", 100)
        rng = np.random.default_rng(29)
        n = 6000
        data = {
            "k": rng.integers(0, 40, n),
            "v": rng.normal(size=n),
            "w": rng.integers(-5, 5, n),
        }
        return create_test_dfs(data)

    def _spy(self, monkeypatch):
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        calls = {"n": 0}
        orig = qc_mod.TpuQueryCompiler._try_shuffle_groupby_apply

        def wrapper(self, *a, **k):
            out = orig(self, *a, **k)
            if out is not None:
                calls["n"] += 1
            return out

        monkeypatch.setattr(
            qc_mod.TpuQueryCompiler, "_try_shuffle_groupby_apply", wrapper
        )
        return calls

    def test_apply_scalar_per_group(self, big, monkeypatch):
        from modin_tpu.utils import get_current_execution

        if get_current_execution() != "TpuOnJax":
            pytest.skip("shuffle path needs the sharded backend")
        calls = self._spy(monkeypatch)
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k")[["v", "w"]].apply(
                lambda g: g["v"].max() - g["w"].min()
            ),
        )
        assert calls["n"] >= 1

    def test_apply_frame_per_group(self, big, monkeypatch):
        from modin_tpu.utils import get_current_execution

        if get_current_execution() != "TpuOnJax":
            pytest.skip("shuffle path needs the sharded backend")
        calls = self._spy(monkeypatch)
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k")[["v"]].apply(lambda g: g.head(2)),
        )
        assert calls["n"] >= 1

    def test_agg_lambda(self, big):
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k")["v"].agg(lambda s: (s > 0).sum()),
        )

    def test_float_key(self, big):
        md, pdf = big
        md = md.assign(fk=md["w"] * 0.5)
        pdf = pdf.assign(fk=pdf["w"] * 0.5)
        eval_general(
            md, pdf,
            lambda df: df.groupby("fk")[["v"]].apply(lambda g: g["v"].sum()),
        )

    def test_sort_false_falls_back_correct(self, big):
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k", sort=False)[["v"]].apply(
                lambda g: g["v"].mean()
            ),
        )

    def test_with_nan_keys(self, big):
        md, pdf = big
        md = md.assign(fk=md["w"].where(md["w"] > -3, np.nan))
        pdf = pdf.assign(fk=pdf["w"].where(pdf["w"] > -3, np.nan))
        eval_general(
            md, pdf,
            lambda df: df.groupby("fk")[["v"]].apply(lambda g: g["v"].sum()),
        )


class TestRowShapedCallablesBypassShuffle:
    """transform/filter lambdas and group_keys=False apply keep the ORIGINAL
    frame row order; the key-ordered shuffle concat must never claim them."""

    @pytest.fixture
    def big(self, monkeypatch):
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        monkeypatch.setattr(qc_mod, "_SHUFFLE_APPLY_MIN_ROWS", 100)
        rng = np.random.default_rng(41)
        n = 5000
        data = {"k": rng.integers(0, 30, n), "v": rng.normal(size=n)}
        return create_test_dfs(data)

    def test_transform_lambda_original_order(self, big):
        md, pdf = big
        eval_general(
            md, pdf, lambda df: df.groupby("k").transform(lambda s: s - s.mean())
        )

    def test_filter_original_order(self, big):
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k").filter(lambda g: g["v"].mean() > 0),
        )

    def test_apply_group_keys_false_original_order(self, big):
        md, pdf = big
        eval_general(
            md, pdf,
            lambda df: df.groupby("k", group_keys=False)[["v"]].apply(
                lambda g: g - g.mean()
            ),
        )


def test_groupby_describe_and_corrwith():
    rng = np.random.default_rng(13)
    n = 200
    data = {
        "k": rng.integers(0, 5, n),
        "v": rng.normal(size=n),
        "w": rng.normal(size=n),
    }
    md, pdf = create_test_dfs(data)
    eval_general(md, pdf, lambda df: df.groupby("k").describe())
    eval_general(md, pdf, lambda df: df.groupby("k")["v"].describe())
    other = pdf[["v", "w"]] * 2
    eval_general(
        md, pdf, lambda df: df.groupby("k")[["v", "w"]].corrwith(other)
    )


class TestShuffleGroupbyApplyWidened:
    """r5 widening of the shuffle groupby-apply (VERDICT r4 item 4):
    multi-key, dict-encoded string keys, by-Series, sort=False appearance
    reorder, as_index=False conversion, and the single-group-chunk
    Series-widening normalization."""

    @pytest.fixture
    def big(self, monkeypatch):
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        monkeypatch.setattr(qc_mod, "_SHUFFLE_APPLY_MIN_ROWS", 100)
        rng = np.random.default_rng(31)
        n = 6000
        cities = np.array(["tokyo", "oslo", "lima", "cairo"], dtype=object)
        data = {
            "k": rng.integers(0, 12, n),
            "j": rng.integers(0, 3, n),
            "city": cities[rng.integers(0, 4, n)],
            "v": rng.normal(size=n),
        }
        return create_test_dfs(data)

    def _spy(self, monkeypatch):
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        calls = {"n": 0}
        orig = qc_mod.TpuQueryCompiler._try_shuffle_groupby_apply

        def wrapper(self, *a, **k):
            out = orig(self, *a, **k)
            if out is not None:
                calls["n"] += 1
            return out

        monkeypatch.setattr(
            qc_mod.TpuQueryCompiler, "_try_shuffle_groupby_apply", wrapper
        )
        return calls

    def _check(self, big, monkeypatch, fn, want_shuffle=True):
        from modin_tpu.utils import get_current_execution

        md, pdf = big
        if get_current_execution() != "TpuOnJax":
            eval_general(md, pdf, fn)
            return
        calls = self._spy(monkeypatch)
        eval_general(md, pdf, fn)
        if want_shuffle:
            assert calls["n"] >= 1, "expected the shuffle path to claim this"

    def test_multi_key(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby(["k", "j"]).apply(lambda g: g["v"].mean()),
        )

    def test_str_key(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("city").apply(lambda g: g["v"].std()),
        )

    def test_str_plus_int_key(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby(["city", "j"]).apply(lambda g: g["v"].sum()),
        )

    def test_sort_false_appearance_order(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("k", sort=False).apply(lambda g: g["v"].sum()),
        )

    def test_sort_false_multikey(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby(["k", "j"], sort=False).apply(
                lambda g: g["v"].sum()
            ),
        )

    def test_as_index_false_scalar(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("k", as_index=False).apply(
                lambda g: g["v"].sum()
            ),
        )

    def test_as_index_false_and_sort_false(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("k", sort=False, as_index=False).apply(
                lambda g: g["v"].sum()
            ),
        )

    def test_by_external_series(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby(df["city"]).apply(lambda g: g["v"].sum()),
        )

    def test_series_udf_single_group_chunks(self, monkeypatch):
        # n_groups <= shards: every chunk holds ONE group, pandas widens each
        # like-indexed Series result; the restack must reproduce the oracle
        import modin_tpu.core.storage_formats.tpu.query_compiler as qc_mod

        monkeypatch.setattr(qc_mod, "_SHUFFLE_APPLY_MIN_ROWS", 100)
        rng = np.random.default_rng(33)
        n = 4000
        md, pdf = create_test_dfs(
            {"k": rng.integers(0, 4, n), "v": rng.normal(size=n)}
        )
        eval_general(md, pdf, lambda df: df.groupby("k").apply(lambda g: g["v"] * 2))

    def test_constant_index_series_udf(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("k").apply(
                lambda g: pandas.Series({"lo": g["v"].min(), "hi": g["v"].max()})
            ),
        )

    def test_constant_index_series_as_index_false(self, big, monkeypatch):
        self._check(
            big, monkeypatch,
            lambda df: df.groupby("k", as_index=False).apply(
                lambda g: pandas.Series({"lo": g["v"].min(), "hi": g["v"].max()})
            ),
        )

    def test_nan_keys_dropna_false(self, big, monkeypatch):
        md, pdf = big
        md = md.assign(fk=md["k"].where(md["k"] > 2, np.nan))
        pdf = pdf.assign(fk=pdf["k"].where(pdf["k"] > 2, np.nan))
        eval_general(
            md, pdf,
            lambda df: df.groupby("fk", dropna=False).apply(lambda g: g["v"].sum()),
        )
