"""Differential tests: DataFrame core operations vs pandas.

Modeled on the reference suite (modin/tests/pandas/dataframe/*): same data in
both implementations, same op, assert equality.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import (
    create_test_dfs,
    df_equals,
    eval_general,
    test_data_keys,
    test_data_values,
)


@pytest.fixture(params=test_data_values, ids=test_data_keys)
def data(request):
    return request.param


class TestConstruction:
    def test_from_dict(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md, pdf)

    def test_from_ndarray(self):
        arr = np.arange(12).reshape(3, 4)
        md, pdf = create_test_dfs(arr, columns=list("abcd"))
        df_equals(md, pdf)

    def test_from_pandas(self):
        pdf = pandas.DataFrame({"a": [1, 2], "b": [3.0, 4.0]})
        df_equals(pd.DataFrame(pdf), pdf)

    def test_empty(self):
        md, pdf = create_test_dfs({})
        df_equals(md, pdf)
        assert md.empty

    def test_shape_size_ndim(self, data):
        md, pdf = create_test_dfs(data)
        assert md.shape == pdf.shape
        assert md.size == pdf.size
        assert md.ndim == pdf.ndim
        assert len(md) == len(pdf)

    def test_with_index_and_columns(self):
        md, pdf = create_test_dfs(
            np.ones((4, 3)), index=list("wxyz"), columns=list("abc")
        )
        df_equals(md, pdf)


class TestArithmetic:
    @pytest.mark.parametrize(
        "op",
        ["add", "sub", "mul", "truediv", "floordiv", "mod", "pow"],
    )
    def test_binary_scalar(self, data, op):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: getattr(df, op)(3))

    @pytest.mark.parametrize("op", ["add", "sub", "mul", "truediv"])
    def test_binary_frame(self, data, op):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: getattr(df, op)(df))

    def test_dunder_ops(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md + md, pdf + pdf)
        df_equals(md - md, pdf - pdf)
        df_equals(md * 2, pdf * 2)
        df_equals(2 * md, 2 * pdf)
        df_equals(md / 7, pdf / 7)
        df_equals(-md, -pdf)
        df_equals(abs(md), abs(pdf))

    @pytest.mark.parametrize("op", ["eq", "ne", "lt", "le", "gt", "ge"])
    def test_comparison(self, data, op):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: getattr(df, op)(50))

    def test_mixed_frame_series_binary(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3], "b": [4, 5, 6]})
        df_equals(md + md["a"], pdf + pdf["a"])
        df_equals(md.add(md["a"], axis=0), pdf.add(pdf["a"], axis=0))


class TestReductions:
    @pytest.mark.parametrize(
        "op", ["sum", "mean", "min", "max", "count", "prod", "var", "std", "median"]
    )
    @pytest.mark.parametrize("axis", [0, 1])
    def test_stat(self, data, op, axis):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: getattr(df, op)(axis=axis))

    @pytest.mark.parametrize("op", ["sum", "mean", "min", "max"])
    def test_stat_skipna_false(self, data, op):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: getattr(df, op)(skipna=False))

    def test_any_all(self, data):
        md, pdf = create_test_dfs(data)
        df_equals((md > 50).any(), (pdf > 50).any())
        df_equals((md > 50).all(), (pdf > 50).all())

    def test_idxmin_idxmax(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.idxmin(), pdf.idxmin())
        df_equals(md.idxmax(), pdf.idxmax())

    def test_nunique(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.nunique(), pdf.nunique())

    def test_scalar_reduce_chain(self, data):
        md, pdf = create_test_dfs(data)
        np.testing.assert_allclose(md.sum().sum(), pdf.sum().sum())


class TestMaps:
    def test_abs_round(self, data):
        md, pdf = create_test_dfs(data)
        df_equals((md - 50).abs(), (pdf - 50).abs())
        df_equals(md.round(2), pdf.round(2))

    def test_isna_notna(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.isna(), pdf.isna())
        df_equals(md.notna(), pdf.notna())

    def test_fillna(self):
        md, pdf = create_test_dfs({"a": [1.0, np.nan, 3.0], "b": [np.nan, 5.0, 6.0]})
        df_equals(md.fillna(0), pdf.fillna(0))
        df_equals(md.fillna(-1.5), pdf.fillna(-1.5))

    def test_dropna(self):
        md, pdf = create_test_dfs({"a": [1.0, np.nan, 3.0], "b": [np.nan, 5.0, 6.0]})
        df_equals(md.dropna(), pdf.dropna())
        df_equals(md.dropna(axis=1), pdf.dropna(axis=1))

    def test_astype(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.astype("float32"), pdf.astype("float32"))
        df_equals(md.astype("int64", errors="ignore"), pdf.astype("int64", errors="ignore"))

    def test_clip(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.clip(10, 60), pdf.clip(10, 60))

    def test_cumsum_cummax(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.cumsum(), pdf.cumsum())
        df_equals(md.cummax(), pdf.cummax())

    def test_diff_shift(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.diff(), pdf.diff())
        df_equals(md.shift(2), pdf.shift(2))

    def test_rank(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.rank(), pdf.rank())


class TestIndexing:
    def test_head_tail(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.head(), pdf.head())
        df_equals(md.tail(3), pdf.tail(3))
        df_equals(md.head(0), pdf.head(0))
        df_equals(md.head(100000), pdf.head(100000))

    def test_getitem_column(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md["col1"], pdf["col1"])
        df_equals(md[["col1", "col3"]], pdf[["col1", "col3"]])

    def test_getitem_bool_mask(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md[md["col0"] > 50], pdf[pdf["col0"] > 50])

    def test_getitem_bool_mask_misaligned_index(self, data):
        # pandas aligns a boolean-Series mask by index, not position
        md, pdf = create_test_dfs(data)
        md_mask = (md["col0"] > 50).iloc[::-1]
        pd_mask = (pdf["col0"] > 50).iloc[::-1]
        df_equals(md[md_mask], pdf[pd_mask])

    def test_getitem_bool_mask_wrong_length_raises(self, data):
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: df[np.asarray([True, False])])

    def test_loc(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.loc[5], pdf.loc[5])
        df_equals(md.loc[3:9], pdf.loc[3:9])
        df_equals(md.loc[:, "col2"], pdf.loc[:, "col2"])
        df_equals(md.loc[[1, 5, 7], ["col0", "col2"]], pdf.loc[[1, 5, 7], ["col0", "col2"]])
        df_equals(md.loc[5, "col3"], pdf.loc[5, "col3"])

    def test_iloc(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.iloc[5], pdf.iloc[5])
        df_equals(md.iloc[2:7], pdf.iloc[2:7])
        df_equals(md.iloc[:, 1], pdf.iloc[:, 1])
        df_equals(md.iloc[[1, 3], [0, 2]], pdf.iloc[[1, 3], [0, 2]])
        df_equals(md.iloc[5, 3], pdf.iloc[5, 3])
        df_equals(md.iloc[-3:], pdf.iloc[-3:])

    def test_at_iat(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.at[3, "col1"], pdf.at[3, "col1"])
        df_equals(md.iat[3, 1], pdf.iat[3, 1])

    def test_setitem_column(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        md["b"] = [7, 8, 9]
        pdf["b"] = [7, 8, 9]
        df_equals(md, pdf)
        md["a"] = md["b"] * 2
        pdf["a"] = pdf["b"] * 2
        df_equals(md, pdf)

    def test_insert_pop_del(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3], "b": [4, 5, 6]})
        md.insert(1, "c", [9, 9, 9])
        pdf.insert(1, "c", [9, 9, 9])
        df_equals(md, pdf)
        df_equals(md.pop("c"), pdf.pop("c"))
        df_equals(md, pdf)
        del md["b"]
        del pdf["b"]
        df_equals(md, pdf)

    def test_take(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.take([0, 3, 5]), pdf.take([0, 3, 5]))
        df_equals(md.take([-1, -2], axis=1), pdf.take([-1, -2], axis=1))

    def test_attr_access(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        df_equals(md.a, pdf.a)


class TestStructure:
    def test_transpose(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.T, pdf.T)

    def test_sort_values(self, data):
        # device sort is always stable; compare against pandas' stable kind
        # (tie order under pandas' default quicksort is an impl detail the
        # reference doesn't reproduce across partitions either)
        md, pdf = create_test_dfs(data)
        df_equals(
            md.sort_values("col0", kind="stable"),
            pdf.sort_values("col0", kind="stable"),
        )
        df_equals(
            md.sort_values(["col0", "col1"], ascending=[False, True], kind="stable"),
            pdf.sort_values(["col0", "col1"], ascending=[False, True], kind="stable"),
        )
        df_equals(
            md.sort_values("col1", ascending=False, kind="stable"),
            pdf.sort_values("col1", ascending=False, kind="stable"),
        )

    def test_sort_index(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(
            md.sort_values("col0").sort_index(), pdf.sort_values("col0").sort_index()
        )

    def test_drop(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.drop(columns=["col0"]), pdf.drop(columns=["col0"]))
        df_equals(md.drop(index=[1, 2]), pdf.drop(index=[1, 2]))
        eval_general(md, pdf, lambda df: df.drop(columns=["nope"]))

    def test_rename(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(
            md.rename(columns={"col0": "X"}), pdf.rename(columns={"col0": "X"})
        )

    def test_reset_index(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.reset_index(), pdf.reset_index())
        df_equals(md.reset_index(drop=True), pdf.reset_index(drop=True))

    def test_set_index(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3], "b": [4, 5, 6]})
        df_equals(md.set_index("a"), pdf.set_index("a"))

    def test_reindex(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.reindex([5, 3, 1]), pdf.reindex([5, 3, 1]))

    def test_concat_axis0(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(pd.concat([md, md]), pandas.concat([pdf, pdf]))
        df_equals(
            pd.concat([md, md], ignore_index=True),
            pandas.concat([pdf, pdf], ignore_index=True),
        )

    def test_concat_axis1(self, data):
        md, pdf = create_test_dfs(data)
        md2 = md.rename(columns=lambda c: f"{c}_r")
        pd2 = pdf.rename(columns=lambda c: f"{c}_r")
        df_equals(pd.concat([md, md2], axis=1), pandas.concat([pdf, pd2], axis=1))

    def test_duplicates(self):
        md, pdf = create_test_dfs({"a": [1, 1, 2, 2, 3], "b": [1, 1, 2, 9, 3]})
        df_equals(md.duplicated(), pdf.duplicated())
        df_equals(md.drop_duplicates(), pdf.drop_duplicates())

    def test_nlargest_nsmallest(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.nlargest(5, "col0"), pdf.nlargest(5, "col0"))
        df_equals(md.nsmallest(5, "col0"), pdf.nsmallest(5, "col0"))

    def test_melt(self):
        md, pdf = create_test_dfs({"a": [1, 2], "b": [3, 4], "c": [5, 6]})
        df_equals(
            md.melt(id_vars=["a"]).sort_values(["variable", "value"]).reset_index(drop=True),
            pdf.melt(id_vars=["a"]).sort_values(["variable", "value"]).reset_index(drop=True),
        )


class TestCombining:
    def test_merge(self):
        md1, pd1 = create_test_dfs({"k": [1, 2, 3, 4], "v1": list("abcd")})
        md2, pd2 = create_test_dfs({"k": [2, 3, 5], "v2": list("xyz")})
        for how in ("inner", "left", "right", "outer"):
            df_equals(md1.merge(md2, on="k", how=how), pd1.merge(pd2, on="k", how=how))

    def test_join(self):
        md1, pd1 = create_test_dfs({"v1": [1, 2, 3]})
        md2, pd2 = create_test_dfs({"v2": [4, 5]})
        df_equals(md1.join(md2), pd1.join(pd2))

    def test_where_mask(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.where(md > 50), pdf.where(pdf > 50))
        df_equals(md.mask(md > 50), pdf.mask(pdf > 50))

    def test_isin(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.isin([1, 2, 3]), pdf.isin([1, 2, 3]))

    def test_update(self):
        md1, pd1 = create_test_dfs({"a": [1.0, 2.0, 3.0]})
        md2, pd2 = create_test_dfs({"a": [9.0, np.nan, 7.0]})
        md1.update(md2)
        pd1.update(pd2)
        df_equals(md1, pd1)


class TestMisc:
    def test_repr(self, data):
        md, pdf = create_test_dfs(data)
        assert repr(md) == repr(pdf)

    def test_repr_large(self):
        md, pdf = create_test_dfs({"a": np.arange(200), "b": np.arange(200) * 1.5})
        assert repr(md) == repr(pdf)

    def test_to_numpy(self, data):
        md, pdf = create_test_dfs(data)
        np.testing.assert_array_equal(md.to_numpy(), pdf.to_numpy())

    def test_copy_deep(self, data):
        md, _ = create_test_dfs(data)
        md2 = md.copy()
        md2["col0"] = 0
        assert not (md["col0"] == 0).all()

    def test_apply(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.apply(lambda c: c + 1), pdf.apply(lambda c: c + 1))
        df_equals(md.apply("sum"), pdf.apply("sum"))

    def test_pickle_roundtrip(self, data):
        import pickle

        md, pdf = create_test_dfs(data)
        md2 = pickle.loads(pickle.dumps(md))
        df_equals(md2, pdf)

    def test_dtypes(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.dtypes, pdf.dtypes)

    def test_describe(self, data):
        md, pdf = create_test_dfs(data)
        df_equals(md.describe(), pdf.describe())

    def test_fallback_long_tail(self, data):
        """Methods with no explicit implementation go through generated fallbacks."""
        md, pdf = create_test_dfs(data)
        df_equals(md.kurtosis(), pdf.kurtosis())
        df_equals(md.sem(), pdf.sem())
        df_equals(md.pct_change().dropna(), pdf.pct_change().dropna())

    def test_assign(self):
        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        df_equals(md.assign(b=lambda d: d.a * 2), pdf.assign(b=lambda d: d.a * 2))

    def test_iteration(self):
        md, pdf = create_test_dfs({"a": [1, 2], "b": [3, 4]})
        assert list(md) == list(pdf)
        assert "a" in md
        for (mk, mv), (pk, pv) in zip(md.items(), pdf.items()):
            assert mk == pk
            df_equals(mv, pv)


def test_core_frame_implements_abstract_contract():
    """SURVEY #5: the structural-algebra ABC (reference
    modin/core/dataframe/base/dataframe/dataframe.py:26) is real and
    TpuDataframe satisfies it."""
    from modin_tpu.core.dataframe.base.dataframe import BaseDataframe
    from modin_tpu.core.dataframe.tpu.dataframe import TpuDataframe

    assert issubclass(TpuDataframe, BaseDataframe)
    abstract = {
        name
        for name in dir(BaseDataframe)
        if getattr(getattr(BaseDataframe, name), "__isabstractmethod__", False)
    }
    assert {
        "from_pandas", "to_pandas", "to_numpy", "select_columns_by_position",
        "rename_columns", "with_columns", "take_rows_positional",
        "filter_rows_mask", "concat_rows", "copy", "finalize", "free",
    } <= abstract
    assert not TpuDataframe.__abstractmethods__

    class Partial(BaseDataframe):
        pass

    with pytest.raises(TypeError):
        Partial()
