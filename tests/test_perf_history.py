"""perf-history ledger acceptance: deterministic seeding/regeneration and
a regression gate that actually rejects regressions.

Acceptance bar (ISSUE 8): ``PERF_HISTORY.json`` seeds deterministically
from ``BENCH_r01..r05`` with backfilled provenance; PERF.md's per-op
tables regenerate byte-identically from the ledger; folding an honest run
passes the gate while a 2x wall inflation is rejected; and comparisons
never cross substrate or scale boundaries.
"""

import json
import os

import pytest

from modin_tpu.config import PerfGateNoiseFloorS, PerfGateTolerance
from modin_tpu.observability import perf_history as ph

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stream(ops, substrate="cpu", rows=120000, sha="abc1234", extra_scale=None):
    """A synthetic bench stdout stream: one section line + aggregate."""
    scale = {"rows": rows, "repeats": 1}
    scale.update(extra_scale or {})
    provenance = {
        "git_sha": sha,
        "substrate": substrate,
        "jax": "0.4.37",
        "pandas": "2.3.3",
        "scale": scale,
    }
    lines = [
        json.dumps(
            {
                "section": "graftsort",
                "elapsed_s": 1.0,
                "run_provenance": provenance,
            }
        ),
        json.dumps(
            {
                "metric": "x",
                "value": 1.0,
                "rows": rows,
                "detail": {
                    op: {
                        "modin_tpu_s": wall,
                        "pandas_s": wall * 1.1,
                        "speedup": 1.1,
                    }
                    for op, wall in ops.items()
                },
                "run_provenance": provenance,
            }
        ),
    ]
    return "\n".join(lines)


class TestSeeding:
    def test_seed_is_deterministic(self):
        a = ph.dump_ledger(ph.seed_ledger(REPO_ROOT))
        b = ph.dump_ledger(ph.seed_ledger(REPO_ROOT))
        assert a == b

    def test_committed_ledger_matches_fresh_seed(self):
        # only the seeded entries (carrying a `source` round file) must
        # match: folded runs are allowed to accumulate after them
        with open(os.path.join(REPO_ROOT, "PERF_HISTORY.json")) as f:
            committed = json.load(f)
        prefix = {
            "schema": committed["schema"],
            "runs": [r for r in committed["runs"] if r.get("source")],
        }
        assert ph.dump_ledger(prefix) == ph.dump_ledger(
            ph.seed_ledger(REPO_ROOT)
        )

    def test_backfill_provenance_and_substrates(self):
        ledger = ph.seed_ledger(REPO_ROOT)
        runs = {r["run"]: r for r in ledger["runs"]}
        assert runs["r02"]["provenance"]["substrate"] == "tpu"
        assert runs["r03"]["provenance"]["substrate"] == "tpu"
        assert runs["r01"]["provenance"]["substrate"] == "cpu"
        assert "backfill" in runs["r03"]["provenance"]["git_sha"]
        assert runs["r05"]["failed"] is True
        assert runs["r03"]["ops"]["sum"]["speedup"] == 6.03

    def test_round_file_without_parse_records_failure(self, tmp_path):
        path = tmp_path / "BENCH_r99.json"
        path.write_text(json.dumps({"n": 99, "rc": 124, "parsed": None}))
        run = ph.seed_run_from_round_file(str(path))
        assert run["failed"] is True and run["ops"] == {}


class TestStreamParsing:
    def test_parse_carries_provenance_sections_and_ops(self):
        run = ph.parse_bench_stream(_stream({"gs_median": 0.5}))
        assert run["provenance"]["git_sha"] == "abc1234"
        assert run["provenance"]["substrate"] == "cpu"
        assert run["scale"]["rows"] == 120000
        assert run["sections"]["graftsort"]["elapsed_s"] == 1.0
        assert run["ops"]["gs_median"]["modin_tpu_s"] == 0.5
        assert "truncated" not in run

    def test_truncated_stream_is_flagged(self):
        text = _stream({"gs_median": 0.5}).splitlines()[0]  # no aggregate
        run = ph.parse_bench_stream(text)
        assert run["truncated"] is True and run["ops"] == {}


class TestGate:
    def _ledger_with(self, ops, **kwargs):
        ledger = ph.empty_ledger()
        run = ph.parse_bench_stream(_stream(ops, **kwargs))
        assert not ph.fold_run(ledger, run, "base-001")
        return ledger

    def test_first_evidence_passes_trivially(self):
        ledger = ph.empty_ledger()
        run = ph.parse_bench_stream(_stream({"gs_median": 0.5}))
        assert ph.check_regression(ledger, run) == []

    def test_honest_rerun_passes_and_2x_fails(self):
        ledger = self._ledger_with({"gs_median": 0.5, "gs_mode": 0.8})
        honest = ph.parse_bench_stream(
            _stream({"gs_median": 0.52, "gs_mode": 0.79})
        )
        assert ph.check_regression(ledger, honest) == []
        inflated = ph.parse_bench_stream(
            _stream({"gs_median": 1.0, "gs_mode": 1.6})
        )
        failures = ph.check_regression(ledger, inflated)
        assert len(failures) == 2
        assert any("gs_median" in f for f in failures)

    def test_tolerance_knob_is_respected(self):
        ledger = self._ledger_with({"gs_median": 0.5})
        run = ph.parse_bench_stream(_stream({"gs_median": 0.9}))
        assert ph.check_regression(ledger, run)  # 1.8x > default 1.5
        prev = PerfGateTolerance.get()
        PerfGateTolerance.put(2.0)
        try:
            assert ph.check_regression(ledger, run) == []
        finally:
            PerfGateTolerance.put(prev)

    def test_tolerance_below_one_rejected(self):
        with pytest.raises(ValueError):
            PerfGateTolerance.put(0.5)

    def test_sub_floor_jitter_is_not_a_regression(self):
        # 1.75x ratio on a sub-millisecond wall is timer jitter: the
        # absolute delta (0.6ms) is below the 5ms noise floor, so the
        # gate must stay green.
        ledger = self._ledger_with({"gs_median": 0.0008})
        jittered = ph.parse_bench_stream(_stream({"gs_median": 0.0014}))
        assert ph.check_regression(ledger, jittered) == []

    def test_noise_floor_knob_is_respected(self):
        ledger = self._ledger_with({"gs_median": 0.0008})
        jittered = ph.parse_bench_stream(_stream({"gs_median": 0.0014}))
        prev = PerfGateNoiseFloorS.get()
        PerfGateNoiseFloorS.put(0.0)
        try:
            # with the floor disabled the pure ratio check fires again
            assert ph.check_regression(ledger, jittered)
        finally:
            PerfGateNoiseFloorS.put(prev)

    def test_noise_floor_negative_rejected(self):
        with pytest.raises(ValueError):
            PerfGateNoiseFloorS.put(-0.001)

    def test_regression_past_floor_still_fails(self):
        # a real regression clears both the ratio and the absolute floor
        ledger = self._ledger_with({"gs_median": 0.0008})
        slow = ph.parse_bench_stream(_stream({"gs_median": 0.02}))
        assert ph.check_regression(ledger, slow)

    def test_no_cross_scale_comparison(self):
        ledger = self._ledger_with({"gs_median": 0.5}, rows=120000)
        big = ph.parse_bench_stream(_stream({"gs_median": 50.0}, rows=10**7))
        assert ph.check_regression(ledger, big) == []

    def test_seeded_round_is_comparable_baseline_for_scaled_runs(self):
        # a backfilled round records only the headline row count; a new run
        # with the full scale config at the same headline rows MUST still
        # be gated against it (review regression: whole-config fingerprints
        # made every new run incomparable to r01-r05)
        ledger = ph.empty_ledger()
        ledger["runs"].append(
            {
                "run": "r03",
                "source": "BENCH_r03.json",
                "rows": 100000000,
                "provenance": {"substrate": "tpu"},
                "ops": {"sum": {"modin_tpu_s": 0.18, "speedup": 6.0}},
            }
        )
        slow = ph.parse_bench_stream(
            _stream(
                {"sum": 1.8},
                substrate="tpu",
                rows=100000000,
                extra_scale={"sort_rows": 10**7, "repeats": 3},
            )
        )
        assert ph.check_regression(ledger, slow), (
            "10x regression vs the seeded TPU baseline folded green"
        )

    def test_op_scale_field_routing(self):
        run = {
            "rows": 100,
            "scale": {
                "rows": 100,
                "sort_rows": 7,
                "axis1_rows": 8,
                "mode1_rows": 9,
                "udf_rows": 11,
            },
        }
        assert ph.op_scale_key(run, "gs_median") == "rows=7"
        assert ph.op_scale_key(run, "sum1") == "rows=8"
        assert ph.op_scale_key(run, "mode1") == "rows=9"
        assert ph.op_scale_key(run, "apply1") == "rows=11"
        assert ph.op_scale_key(run, "sum") == "rows=100"

    def test_spmd_ops_keyed_by_rows_and_mesh(self):
        run = {
            "rows": 100,
            "scale": {"rows": 100, "spmd_rows": 60000, "spmd_mesh": "8x1"},
        }
        assert (
            ph.op_scale_key(run, "spmd_sort_sharded")
            == "rows=60000@mesh=8x1"
        )
        # the per-mode map form: each leg carries its OWN topology (the
        # "single" leg genuinely runs on a (1,1) mesh)
        mapped = {
            "rows": 100,
            "scale": {
                "rows": 100,
                "spmd_rows": 60000,
                "spmd_mesh": {
                    "sharded": "8x1", "local": "8x1", "single": "1x1"
                },
            },
        }
        assert (
            ph.op_scale_key(mapped, "spmd_sort_sharded")
            == "rows=60000@mesh=8x1"
        )
        assert (
            ph.op_scale_key(mapped, "spmd_sort_single")
            == "rows=60000@mesh=1x1"
        )
        # without a recorded mesh the key still isolates (unknown bucket)
        bare = {"rows": 100, "scale": {"rows": 100, "spmd_rows": 60000}}
        assert (
            ph.op_scale_key(bare, "spmd_sort_sharded")
            == "rows=60000@mesh=unknown"
        )

    def test_spmd_walls_never_gate_across_mesh_shapes(self):
        # the same op at the same row count on a 1-dev vs 8-dev mesh is a
        # different substrate topology: a 100x wall delta must NOT gate
        ledger = self._ledger_with(
            {"spmd_sort_sharded": 0.05},
            extra_scale={"spmd_rows": 60000, "spmd_mesh": "8x1"},
        )
        other_mesh = ph.parse_bench_stream(
            _stream(
                {"spmd_sort_sharded": 5.0},
                extra_scale={"spmd_rows": 60000, "spmd_mesh": "1x1"},
            )
        )
        assert ph.check_regression(ledger, other_mesh) == []
        # same mesh shape DOES gate
        same_mesh = ph.parse_bench_stream(
            _stream(
                {"spmd_sort_sharded": 5.0},
                extra_scale={"spmd_rows": 60000, "spmd_mesh": "8x1"},
            )
        )
        assert ph.check_regression(ledger, same_mesh), (
            "a 100x same-mesh spmd regression folded green"
        )

    def test_serving_ops_keyed_by_watch_mode(self):
        run = {"rows": 100, "scale": {"rows": 100, "serving_rows": 2000000}}
        assert (
            ph.op_scale_key(run, "serving_p50")
            == "rows=2000000@watch=off"
        )
        assert (
            ph.op_scale_key(run, "serving_watch_p50")
            == "rows=2000000@watch=on"
        )
        # the committed r09 records compute the same @watch=off key, so
        # history stays comparable across the key-schema change
        legacy = {"rows": 2000000, "scale": {"serving_rows": 2000000}}
        assert ph.op_scale_key(legacy, "serving_p99").endswith("@watch=off")

    def test_serving_walls_never_gate_across_watch_modes(self):
        # the same saturation workload with the graftwatch sampler live is
        # a different workload: its (bounded) overhead must never gate
        # against the watch-off wall, and vice versa
        ledger = self._ledger_with(
            {"serving_p50": 0.05}, extra_scale={"serving_rows": 2000000}
        )
        watch_on = ph.parse_bench_stream(
            _stream(
                {"serving_watch_p50": 5.0},
                extra_scale={"serving_rows": 2000000},
            )
        )
        assert ph.check_regression(ledger, watch_on) == []
        same_mode = ph.parse_bench_stream(
            _stream(
                {"serving_p50": 5.0},
                extra_scale={"serving_rows": 2000000},
            )
        )
        assert ph.check_regression(ledger, same_mode), (
            "a 100x same-mode serving regression folded green"
        )

    def test_oocore_ops_keyed_by_rows_and_window(self):
        mapped = {
            "rows": 100,
            "scale": {
                "rows": 100,
                "oocore_rows": 200000,
                "oocore_window": {
                    "stream": 65536, "serial": 65536, "resident": "resident"
                },
            },
        }
        assert (
            ph.op_scale_key(mapped, "oocore_stream")
            == "rows=200000@window=65536"
        )
        # the resident leg has no window: its key says so explicitly
        assert (
            ph.op_scale_key(mapped, "oocore_resident")
            == "rows=200000@window=resident"
        )
        bare = {"rows": 100, "scale": {"rows": 100, "oocore_rows": 200000}}
        assert (
            ph.op_scale_key(bare, "oocore_stream")
            == "rows=200000@window=unknown"
        )

    def test_oocore_walls_never_gate_across_window_sizes(self):
        # the same streamed op at the same row count but a different window
        # size is a different workload (mirrors the spmd mesh key): a
        # 100x wall delta must NOT gate; the same window size MUST
        ledger = self._ledger_with(
            {"oocore_stream": 0.05},
            extra_scale={
                "oocore_rows": 200000, "oocore_window": {"stream": 65536}
            },
        )
        other_window = ph.parse_bench_stream(
            _stream(
                {"oocore_stream": 5.0},
                extra_scale={
                    "oocore_rows": 200000, "oocore_window": {"stream": 4096}
                },
            )
        )
        assert ph.check_regression(ledger, other_window) == []
        resident = ph.parse_bench_stream(
            _stream(
                {"oocore_stream": 5.0},
                extra_scale={
                    "oocore_rows": 200000,
                    "oocore_window": {"stream": "resident"},
                },
            )
        )
        assert ph.check_regression(ledger, resident) == []
        same_window = ph.parse_bench_stream(
            _stream(
                {"oocore_stream": 5.0},
                extra_scale={
                    "oocore_rows": 200000, "oocore_window": {"stream": 65536}
                },
            )
        )
        assert ph.check_regression(ledger, same_window), (
            "a 100x same-window oocore regression folded green"
        )

    def test_gs_ops_isolated_by_sort_rows_not_headline(self):
        ledger = self._ledger_with(
            {"gs_median": 0.5}, extra_scale={"sort_rows": 120000}
        )
        other = ph.parse_bench_stream(
            _stream({"gs_median": 50.0}, extra_scale={"sort_rows": 10**7})
        )
        assert ph.check_regression(ledger, other) == []

    def test_no_cross_substrate_comparison(self):
        ledger = self._ledger_with({"gs_median": 5.0}, substrate="cpu")
        tpu = ph.parse_bench_stream(
            _stream({"gs_median": 50.0}, substrate="tpu")
        )
        assert ph.check_regression(ledger, tpu) == []

    def test_fold_records_red_runs_visibly(self):
        ledger = self._ledger_with({"gs_median": 0.5})
        bad = ph.parse_bench_stream(_stream({"gs_median": 5.0}))
        failures = ph.fold_run(ledger, bad, "bad-001")
        assert failures
        recorded = ledger["runs"][-1]
        assert recorded["run"] == "bad-001"
        assert recorded["gate_failures"] == failures
        assert "GATE-RED" in ph.render_tables(ledger)

    def test_duplicate_run_id_rejected(self):
        ledger = self._ledger_with({"gs_median": 0.5})
        run = ph.parse_bench_stream(_stream({"gs_median": 0.5}))
        with pytest.raises(ValueError):
            ph.fold_run(ledger, run, "base-001")

    def test_next_run_id_monotonic(self):
        ledger = self._ledger_with({"gs_median": 0.5})
        assert ph.next_run_id(ledger) == "run-001"
        run = ph.parse_bench_stream(_stream({"gs_median": 0.5}))
        ph.fold_run(ledger, run, "run-001")
        assert ph.next_run_id(ledger) == "run-002"


class TestRegeneration:
    def test_committed_perf_md_matches_ledger(self):
        with open(os.path.join(REPO_ROOT, "PERF_HISTORY.json")) as f:
            ledger = json.load(f)
        with open(os.path.join(REPO_ROOT, "PERF.md")) as f:
            perf_md = f.read()
        assert ph.regenerate_perf_md(ledger, perf_md) == perf_md

    def test_regen_is_idempotent_after_fold(self):
        ledger = ph.empty_ledger()
        run = ph.parse_bench_stream(_stream({"gs_median": 0.5}))
        ph.fold_run(ledger, run, "run-001")
        doc = (
            f"# title\n\n{ph.BEGIN_MARKER}\nstale\n{ph.END_MARKER}\n\ntail\n"
        )
        once = ph.regenerate_perf_md(ledger, doc)
        assert ph.regenerate_perf_md(ledger, once) == once
        assert "| gs_median | cpu |" in once
        assert "stale" not in once
        assert once.endswith("tail\n")

    def test_missing_markers_raise(self):
        with pytest.raises(ValueError):
            ph.regenerate_perf_md(ph.empty_ledger(), "no markers here")

    def test_best_and_latest_tracked_separately(self):
        ledger = ph.empty_ledger()
        ph.fold_run(
            ledger,
            ph.parse_bench_stream(_stream({"op": 1.0})),
            "run-001",
        )
        ph.fold_run(
            ledger,
            ph.parse_bench_stream(_stream({"op": 1.2})),
            "run-002",
        )
        table = ph.render_tables(ledger)
        row = next(
            ln for ln in table.splitlines() if ln.startswith("| op | cpu |")
        )
        assert "| 1.0000 |" in row and "run-001" in row
        assert "| 1.2000 |" in row and "run-002" in row
