"""White-box tests: the hot ops must run on DEVICE (no pandas fallback).

Counterpart of the reference's internals tests
(modin/tests/core/storage_formats/pandas/test_internals.py): asserts the
device fast paths actually engage and stay sharded.
"""

import warnings

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.core.storage_formats.tpu.query_compiler import TpuQueryCompiler
from tests.utils import df_equals


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("device-path tests require the TpuOnJax execution")


def make_df(n=1000, cols=3, seed=0):
    rng = np.random.default_rng(seed)
    data = {f"c{i}": rng.uniform(-10, 10, n) for i in range(cols)}
    data["k"] = rng.integers(0, 5, n)
    return pd.DataFrame(data)


def assert_no_fallback(fn):
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        return fn()


def test_frame_is_device_backed():
    df = make_df()
    qc = df._query_compiler
    assert isinstance(qc, TpuQueryCompiler)
    assert all(c.is_device for c in qc._modin_frame._columns)


def test_columns_are_padded_and_sharded():
    from modin_tpu.parallel.mesh import num_row_shards

    df = make_df(n=1001)
    col = df._query_compiler._modin_frame.get_column(0)
    assert col.length == 1001
    assert col.data.shape[0] % num_row_shards() == 0
    assert col.data.shape[0] >= 1001


def test_binary_no_fallback():
    df = make_df()
    result = assert_no_fallback(lambda: df + df)
    assert all(c.is_device for c in result._query_compiler._modin_frame._columns)
    result2 = assert_no_fallback(lambda: df * 2.5)
    df_equals(result2, df._to_pandas() * 2.5)


def test_reduce_no_fallback():
    df = make_df()
    s = assert_no_fallback(lambda: df.sum())
    df_equals(s, df._to_pandas().sum())
    assert_no_fallback(lambda: df.mean())
    assert_no_fallback(lambda: df.max(axis=1))


def test_groupby_sum_no_fallback():
    df = make_df()
    result = assert_no_fallback(lambda: df.groupby("k").sum())
    df_equals(result, df._to_pandas().groupby("k").sum())
    # the aggregation result itself stays on device
    assert all(
        c.is_device for c in result._query_compiler._modin_frame._columns
    )


def test_sort_no_fallback():
    df = make_df()
    result = assert_no_fallback(lambda: df.sort_values("c0"))
    df_equals(result, df._to_pandas().sort_values("c0", kind="stable"))


def test_filter_no_fallback():
    df = make_df()
    result = assert_no_fallback(lambda: df[df["c0"] > 0])
    df_equals(result, (lambda p: p[p["c0"] > 0])(df._to_pandas()))


def test_computed_column_drops_host_cache():
    df = make_df()
    out = df + 1
    col = out._query_compiler._modin_frame.get_column(0)
    assert col.host_cache is None
    src = df._query_compiler._modin_frame.get_column(0)
    assert src.host_cache is not None


def test_fallback_roundtrips_to_device():
    # a defaulted op must return a Tpu-backed compiler again
    df = make_df()
    result = df.rank()
    assert isinstance(result._query_compiler, TpuQueryCompiler)


def test_sharding_spans_mesh():
    from modin_tpu.parallel.mesh import get_mesh, num_row_shards

    if num_row_shards() < 2:
        pytest.skip("needs a multi-device mesh")
    df = make_df(n=4096)
    col = df._query_compiler._modin_frame.get_column(0)
    assert len(col.data.sharding.device_set) == num_row_shards()


def test_reduction_over_sharded_matches(enable_benchmark_mode):
    df = make_df(n=4096)
    df_equals(df.sum(), df._to_pandas().sum())


def test_rolling_device_path():
    import warnings

    df = make_df(n=500)
    num = df[["c0", "c1", "c2"]]
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        r_sum = num.rolling(7).sum()
        r_mean = num.rolling(7, min_periods=3).mean()
        r_count = num.rolling(7).count()
    p = num._to_pandas()
    df_equals(r_sum, p.rolling(7).sum())
    df_equals(r_mean, p.rolling(7, min_periods=3).mean())
    df_equals(r_count, p.rolling(7).count())


def test_rolling_with_nan():
    import pandas as real_pandas

    data = {"a": [1.0, np.nan, 3.0, 4.0, np.nan, 6.0, 7.0, 8.0]}
    md = pd.DataFrame(data)
    p = real_pandas.DataFrame(data)
    df_equals(md.rolling(3).sum(), p.rolling(3).sum())
    df_equals(md.rolling(3, min_periods=1).mean(), p.rolling(3, min_periods=1).mean())


def test_float_cumulative_device():
    import warnings

    data = {"a": [1.0, np.nan, 3.0, -2.0], "b": [0.5, 1.5, np.nan, 2.5]}
    md = pd.DataFrame(data)
    p = md._to_pandas()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        got_sum = md.cumsum()
        got_max = md.cummax()
        got_min = md.cummin()
        got_prod = md.cumprod()
    df_equals(got_sum, p.cumsum())
    df_equals(got_max, p.cummax())
    df_equals(got_min, p.cummin())
    df_equals(got_prod, p.cumprod())


def test_rolling_min_periods_zero_and_invalid():
    import pandas as real_pandas

    data = {"a": [np.nan, 1.0, np.nan, np.nan, 2.0]}
    md = pd.DataFrame(data)
    p = real_pandas.DataFrame(data)
    df_equals(md.rolling(2, min_periods=0).sum(), p.rolling(2, min_periods=0).sum())
    with pytest.raises(ValueError):
        p.rolling(2, min_periods=5).sum()
    with pytest.raises(ValueError):
        md.rolling(2, min_periods=5).sum()


def test_dropna_device_path():
    import warnings

    data = {
        "a": [1.0, np.nan, 3.0, 4.0],
        "b": [np.nan, np.nan, 30.0, 40.0],
        "t": pandas.to_datetime(["2020-01-01", None, None, "2020-01-04"]),
    }
    md = pd.DataFrame(data)
    p = md._to_pandas()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        got_any = md.dropna()
        got_all = md.dropna(how="all")
        got_sub = md.dropna(subset=["a"])
    df_equals(got_any, p.dropna())
    df_equals(got_all, p.dropna(how="all"))
    df_equals(got_sub, p.dropna(subset=["a"]))


def test_value_counts_device_path():
    import warnings

    rng = np.random.default_rng(3)
    s = pd.Series(rng.integers(0, 7, 500), name="v")
    p = s._to_pandas()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        got = s.value_counts()
        got_norm = s.value_counts(normalize=True)
        got_asc = s.value_counts(ascending=True)
    df_equals(got, p.value_counts())
    df_equals(got_norm, p.value_counts(normalize=True))
    df_equals(got_asc, p.value_counts(ascending=True))


def test_value_counts_float_with_nan():
    vals = [1.5, 1.5, np.nan, 2.5, np.nan, np.nan]
    md = pd.Series(vals)
    p = md._to_pandas()
    df_equals(md.value_counts(), p.value_counts())
    df_equals(md.value_counts(dropna=False), p.value_counts(dropna=False))


def test_value_counts_sort_false_first_appearance():
    md = pd.Series([3, 1, 1, 2, 3, 3])
    p = md._to_pandas()
    df_equals(md.value_counts(sort=False), p.value_counts(sort=False))


def test_dropna_arraylike_subset():
    md = pd.DataFrame({"a": [1.0, np.nan], "b": [np.nan, 2.0]})
    p = md._to_pandas()
    df_equals(md.dropna(subset=np.array(["a"])), p.dropna(subset=np.array(["a"])))
    df_equals(md.dropna(subset=pandas.Index(["b"])), p.dropna(subset=pandas.Index(["b"])))


def test_shift_diff_device():
    import warnings

    data = {"a": [1.0, 2.0, np.nan, 4.0, 5.0], "b": [10, 20, 30, 40, 50]}
    md = pd.DataFrame(data)
    p = md._to_pandas()
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        got_shift = md.shift(2)
        got_nshift = md.shift(-1)
        got_diff = md.diff()
        got_ndiff = md.diff(-2)
    df_equals(got_shift, p.shift(2))
    df_equals(got_nshift, p.shift(-1))
    df_equals(got_diff, p.diff())
    df_equals(got_ndiff, p.diff(-2))


def test_shift_diff_edge_periods():
    data = {"a": [1.0, 2.0, 3.0], "b": [10, 20, 30]}
    md = pd.DataFrame(data)
    p = md._to_pandas()
    df_equals(md.shift(0), p.shift(0))           # dtype preserved
    df_equals(md.diff(0), p.diff(0))
    df_equals(md.shift(50), p.shift(50))         # beyond length -> all NaN
    df_equals(md.shift(-50), p.shift(-50))
    df_equals(md.diff(-50), p.diff(-50))


def test_float64_policy_downcast():
    """Float64Policy=Downcast: f32 device storage, exact host round-trip."""
    import numpy as np

    from modin_tpu.config import Float64Policy

    x = np.random.default_rng(0).normal(size=800)
    with Float64Policy.context("Downcast"):
        md = pd.DataFrame({"a": x})
        col = md._query_compiler._modin_frame.get_column(0)
        assert str(col.data.dtype) == "float32"
        assert col.pandas_dtype == np.float64
        # untouched column round-trips bit-exact via host_cache
        np.testing.assert_array_equal(md["a"].to_numpy(), x)
        # computed results carry f32 precision (the policy's tradeoff)
        got = float((md["a"] * 2.0).sum())
        np.testing.assert_allclose(got, (x.astype(np.float32) * 2).sum(), rtol=1e-5)
