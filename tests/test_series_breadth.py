"""Series method breadth (ported shapes from modin/tests/pandas/test_series.py,
5,274 LoC / 366 tests: unary/stat/transform methods across dtype fixtures)."""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_series, df_equals, eval_general

_rng = np.random.default_rng(91)
N = 80

SERIES_DATA = {
    "floats": _rng.normal(size=N) * 10,
    "floats_nan": np.where(_rng.random(N) < 0.25, np.nan, _rng.normal(size=N)),
    "ints": _rng.integers(-50, 50, N),
    "bools": _rng.random(N) < 0.5,
}


@pytest.fixture(params=list(SERIES_DATA), ids=list(SERIES_DATA))
def series_pair(request):
    return create_test_series(SERIES_DATA[request.param])


STAT_METHODS = [
    "sum", "mean", "min", "max", "count", "prod", "median", "std", "var",
    "sem", "skew", "kurt", "nunique", "any", "all",
]


@pytest.mark.parametrize("method", STAT_METHODS)
def test_series_stats(series_pair, method):
    ms, ps = series_pair
    eval_general(ms, ps, lambda s: getattr(s, method)())


@pytest.mark.parametrize("method", ["sum", "mean", "min", "max", "std", "var"])
def test_series_stats_no_skipna(series_pair, method):
    ms, ps = series_pair
    eval_general(ms, ps, lambda s: getattr(s, method)(skipna=False))


TRANSFORMS = [
    lambda s: s.abs(),
    lambda s: s.round(1),
    lambda s: s.rank(),
    lambda s: s.rank(method="min"),
    lambda s: s.rank(pct=True),
    lambda s: s.clip(-5, 5),
    lambda s: s.cumsum(),
    lambda s: s.cummax(),
    lambda s: s.cummin(),
    lambda s: s.cumprod(),
    lambda s: s.diff(),
    lambda s: s.diff(-2),
    lambda s: s.shift(3),
    lambda s: s.shift(-1),
    lambda s: s.pct_change(),
    lambda s: s.fillna(0),
    lambda s: s.ffill(),
    lambda s: s.bfill(),
    lambda s: s.dropna(),
    lambda s: s.drop_duplicates(),
    lambda s: s.sort_values(kind="stable"),
    lambda s: s.sort_values(ascending=False, kind="stable"),
    lambda s: s.sort_index(ascending=False),
    lambda s: s.nlargest(5),
    lambda s: s.nsmallest(5),
    lambda s: s.mode(),
    lambda s: s.unique(),
    lambda s: s.between(-1, 1),
    lambda s: s.isin([1, 2, 3]),
    lambda s: s.replace(1, 99),
    lambda s: s.astype(str),
    lambda s: s.to_frame(),
    lambda s: s.reset_index(drop=True),
    lambda s: s.idxmax(),
    lambda s: s.idxmin(),
    lambda s: s.value_counts(),
    lambda s: s.value_counts(normalize=True),
    lambda s: s.quantile(0.3),
    lambda s: s.quantile([0.1, 0.9]),
    lambda s: s.describe(),
    lambda s: len(s.sample(10, random_state=0)),
    lambda s: s.memory_usage() > 0,
    lambda s: s.nbytes > 0,
    lambda s: s.duplicated(),
    lambda s: s.autocorr() if s.dtype.kind == "f" else None,
    lambda s: s.is_monotonic_increasing,
    lambda s: s.is_unique,
    lambda s: s.hasnans,
]


@pytest.mark.parametrize("op", TRANSFORMS, ids=range(len(TRANSFORMS)))
def test_series_transforms(series_pair, op):
    ms, ps = series_pair
    eval_general(ms, ps, op)


def test_series_apply_map():
    ms, ps = create_test_series(SERIES_DATA["floats"])
    eval_general(ms, ps, lambda s: s.apply(lambda v: v * 2 + 1))
    eval_general(ms, ps, lambda s: s.map(lambda v: abs(v)))


def test_series_agg_lists():
    ms, ps = create_test_series(SERIES_DATA["floats"])
    eval_general(ms, ps, lambda s: s.agg(["sum", "mean", "max"]))


def test_series_combine():
    a_md, a_pd = create_test_series(SERIES_DATA["floats"])
    b_md, b_pd = create_test_series(SERIES_DATA["ints"])
    df_equals(a_md.combine(b_md, max), a_pd.combine(b_pd, max))
    df_equals(a_md.combine_first(b_md), a_pd.combine_first(b_pd))


def test_series_align_on_different_index():
    a_md, a_pd = create_test_series([1.0, 2.0, 3.0], index=[0, 1, 2])
    b_md, b_pd = create_test_series([10.0, 20.0, 30.0], index=[1, 2, 3])
    df_equals(a_md + b_md, a_pd + b_pd)
    df_equals(a_md.mul(b_md, fill_value=0), a_pd.mul(b_pd, fill_value=0))


def test_series_repeat_explode():
    ms, ps = create_test_series([1, 2, 3])
    eval_general(ms, ps, lambda s: s.repeat(2))
    ml, pl_ = create_test_series([[1, 2], [3], []])
    eval_general(ml, pl_, lambda s: s.explode())


# --------------------------------------------------------------------- #
# graftview invisibility grid: agg x dtype x skipna, Auto vs Off, warm
# and appended.  The derived-artifact cache (modin_tpu/views/) must be
# invisible to correctness: a warm re-run (whole-result hit) and a re-run
# after an appended batch (incremental fold where the op is algebraic)
# must answer exactly what a cold MODIN_TPU_VIEWS=Off run answers.
# --------------------------------------------------------------------- #

VIEW_GRID_AGGS = [
    "sum", "mean", "min", "max", "count", "prod", "var", "std", "median",
    "nunique", "any", "all",
]

#: folds of these aggs re-associate a floating-point accumulation (the
#: graftstream window-combiner contract); everything else is bit-exact
_FP_REASSOCIATING = {"sum", "mean", "prod", "var", "std"}


def _views_off_result(data, append, agg, skipna_kw):
    from modin_tpu.config import ViewsMode
    from modin_tpu.views import registry as view_registry

    before = ViewsMode.get()
    ViewsMode.put("Off")
    try:
        view_registry.reset()
        s = pd.Series(data)
        if append:
            s = pd.concat([s, pd.Series(data[: len(data) // 3])],
                          ignore_index=True)
        return getattr(s, agg)(**skipna_kw)
    finally:
        ViewsMode.put(before)


@pytest.mark.parametrize("append", [False, True], ids=["flat", "appended"])
@pytest.mark.parametrize("skipna", [True, False, None],
                         ids=["skipna", "no_skipna", "default"])
@pytest.mark.parametrize("agg", VIEW_GRID_AGGS)
@pytest.mark.parametrize("dtype", list(SERIES_DATA), ids=list(SERIES_DATA))
def test_views_grid_auto_vs_off(dtype, agg, skipna, append):
    if skipna is not None and agg in ("count", "nunique", "any", "all"):
        pytest.skip("agg takes no skipna")
    data = SERIES_DATA[dtype]
    skipna_kw = {} if skipna is None else {"skipna": skipna}
    pandas_s = pandas.Series(data)
    if append:
        pandas_s = pandas.concat(
            [pandas_s, pandas.Series(data[: len(data) // 3])],
            ignore_index=True,
        )
    expect_pd = getattr(pandas_s, agg)(**skipna_kw)

    # Auto: cold run seeds the artifacts, warm run must hit, and the
    # appended variant folds (or honestly invalidates) — then everything
    # is compared against Off AND pandas
    base = pd.Series(data)
    getattr(base, agg)(**skipna_kw)  # seed artifacts on the base frame
    if append:
        target = pd.concat([base, pd.Series(data[: len(data) // 3])],
                           ignore_index=True)
    else:
        target = base
    auto_1 = getattr(target, agg)(**skipna_kw)
    auto_2 = getattr(target, agg)(**skipna_kw)  # warm: artifact hit
    off = _views_off_result(data, append, agg, skipna_kw)

    df_equals(auto_1, expect_pd)
    df_equals(auto_2, expect_pd)
    df_equals(auto_1, off)
    # bit-exactness holds everywhere EXCEPT the appended fp-reassociating
    # folds: mean always accumulates in float64, and float sum/prod folds
    # combine segment partials (the graftstream window-combiner contract).
    # Non-foldable aggs (var/std/median/nunique) recompute cold after an
    # append, so they are bit-exact even appended.
    fp_fold = append and (
        agg == "mean"
        or (agg in ("sum", "prod") and dtype not in ("ints", "bools"))
    )
    if not fp_fold:
        assert repr(auto_1) == repr(off) == repr(auto_2), (auto_1, off)


def test_arrow_list_struct_accessors():
    pa = pytest.importorskip("pyarrow")
    s = pd.Series(
        pandas.Series([[1, 2], [3]], dtype=pandas.ArrowDtype(pa.list_(pa.int64())))
    )
    assert s.list.len().tolist() == [2, 1]
    assert s.list[0].tolist() == [1, 3]
    assert s.list.flatten().tolist() == [1, 2, 3]
    st = pd.Series(
        pandas.Series(
            [{"a": 1, "b": "x"}, {"a": 2, "b": "y"}],
            dtype=pandas.ArrowDtype(pa.struct([("a", pa.int64()), ("b", pa.string())])),
        )
    )
    assert st.struct.field("a").tolist() == [1, 2]
    exploded = st.struct.explode()
    assert list(exploded.columns) == ["a", "b"]
    assert exploded.shape == (2, 2)
