"""Device sort-merge join tests (differential vs pandas)."""

import warnings

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals, eval_general


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("device merge tests need TpuOnJax")


def assert_no_fallback(fn):
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        return fn()


@pytest.mark.parametrize("how", ["inner", "left"])
@pytest.mark.parametrize("key_dtype", ["int64", "float64"])
def test_merge_device_path(how, key_dtype):
    rng = np.random.default_rng(41)
    left = {
        "k": rng.integers(0, 50, 500).astype(key_dtype),
        "lv": rng.uniform(-1, 1, 500),
    }
    right = {
        "k": rng.integers(0, 60, 200).astype(key_dtype),
        "rv": rng.integers(0, 1000, 200),
    }
    ml, pl_ = create_test_dfs(left)
    mr, pr = create_test_dfs(right)
    got = assert_no_fallback(lambda: ml.merge(mr, on="k", how=how))
    want = pl_.merge(pr, on="k", how=how)
    df_equals(got, want)


def test_merge_duplicate_right_keys_order():
    ml, pl_ = create_test_dfs({"k": [3, 1, 3, 2], "lv": [10, 20, 30, 40]})
    mr, pr = create_test_dfs({"k": [3, 2, 3, 3], "rv": [100, 200, 300, 400]})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k"))
    df_equals(got, pl_.merge(pr, on="k"))


def test_merge_nan_keys_never_match():
    ml, pl_ = create_test_dfs({"k": [1.0, np.nan, 2.0], "lv": [1, 2, 3]})
    mr, pr = create_test_dfs({"k": [np.nan, 2.0], "rv": [9, 8]})
    for how in ("inner", "left"):
        got = assert_no_fallback(lambda: ml.merge(mr, on="k", how=how))
        df_equals(got, pl_.merge(pr, on="k", how=how))


def test_merge_left_promotes_int_on_miss():
    ml, pl_ = create_test_dfs({"k": [1, 2, 3]})
    mr, pr = create_test_dfs({"k": [1], "rv": [7]})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k", how="left"))
    want = pl_.merge(pr, on="k", how="left")
    df_equals(got, want)
    assert got["rv"].dtype == np.float64


def test_merge_suffixes():
    ml, pl_ = create_test_dfs({"k": [1, 2], "v": [10, 20]})
    mr, pr = create_test_dfs({"k": [1, 2], "v": [30, 40]})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k"))
    df_equals(got, pl_.merge(pr, on="k"))
    got2 = assert_no_fallback(lambda: ml.merge(mr, on="k", suffixes=("_l", "_r")))
    df_equals(got2, pl_.merge(pr, on="k", suffixes=("_l", "_r")))


def test_merge_left_on_right_on():
    ml, pl_ = create_test_dfs({"ka": [1, 2, 3], "lv": [1.0, 2.0, 3.0]})
    mr, pr = create_test_dfs({"kb": [2, 3, 4], "rv": [20.0, 30.0, 40.0]})
    got = assert_no_fallback(lambda: ml.merge(mr, left_on="ka", right_on="kb"))
    df_equals(got, pl_.merge(pr, left_on="ka", right_on="kb"))


def test_merge_empty_result():
    ml, pl_ = create_test_dfs({"k": [1, 2], "lv": [1.0, 2.0]})
    mr, pr = create_test_dfs({"k": [5, 6], "rv": [9.0, 9.0]})
    got = ml.merge(mr, on="k")
    df_equals(got, pl_.merge(pr, on="k"))


def test_merge_fallback_paths_still_work():
    # multi-key and outer joins route through the pandas default
    ml, pl_ = create_test_dfs({"a": [1, 1, 2], "b": [1, 2, 2], "v": [1, 2, 3]})
    mr, pr = create_test_dfs({"a": [1, 2], "b": [2, 2], "w": [10, 20]})
    df_equals(
        ml.merge(mr, on=["a", "b"], how="outer").sort_values(["a", "b", "v"]).reset_index(drop=True),
        pl_.merge(pr, on=["a", "b"], how="outer").sort_values(["a", "b", "v"]).reset_index(drop=True),
    )


def test_merge_large_random():
    rng = np.random.default_rng(77)
    ml, pl_ = create_test_dfs(
        {"k": rng.integers(0, 300, 5000), "x": rng.uniform(0, 1, 5000)}
    )
    mr, pr = create_test_dfs(
        {"k": rng.integers(0, 300, 2000), "y": rng.uniform(0, 1, 2000)}
    )
    for how in ("inner", "left"):
        got = assert_no_fallback(lambda: ml.merge(mr, on="k", how=how))
        df_equals(got, pl_.merge(pr, on="k", how=how))


def test_merge_negative_zero_key():
    # regression: XLA folds x+0.0 to x; -0.0 must still equal 0.0 as a key
    ml, pl_ = create_test_dfs({"k": [0.0, -0.0, np.nan], "a": [1, 2, 3]})
    mr, pr = create_test_dfs({"k": [0.0, np.nan], "b": [10, 20]})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k"))
    df_equals(got, pl_.merge(pr, on="k"))


def test_merge_same_left_on_right_on_collapses():
    ml, pl_ = create_test_dfs({"a": [1, 2], "v": [1.0, 2.0]})
    mr, pr = create_test_dfs({"a": [2, 3], "w": [9.0, 8.0]})
    df_equals(
        ml.merge(mr, left_on="a", right_on="a"),
        pl_.merge(pr, left_on="a", right_on="a"),
    )


def test_merge_arraylike_key_falls_back():
    ml, pl_ = create_test_dfs({"a": [1, 2, 3], "v": [1.0, 2.0, 3.0]})
    mr, pr = create_test_dfs({"kb": [1, 2], "w": [10.0, 20.0]})
    key = np.array([1, 2, 9])
    df_equals(
        ml.merge(mr, left_on=key, right_on="kb"),
        pl_.merge(pr, left_on=key, right_on="kb"),
    )


def test_merge_colliding_suffixes_raise_like_pandas():
    ml, pl_ = create_test_dfs({"k": [1], "v": [1.0], "v_s": [2.0]})
    mr, pr = create_test_dfs({"k": [1], "v": [3.0]})
    with pytest.raises(Exception):
        pl_.merge(pr, on="k", suffixes=("_s", "_r"))
    with pytest.raises(Exception):
        ml.merge(mr, on="k", suffixes=("_s", "_r"))


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
@pytest.mark.parametrize("on", ["k", ["k", "k2"]])
def test_merge_how_keys_matrix(how, on):
    rng = np.random.default_rng(17)
    nl, nr = 400, 250
    left = {
        "k": rng.integers(0, 40, nl),
        "k2": rng.integers(0, 4, nl),
        "x": rng.normal(size=nl),
    }
    right = {
        "k": rng.integers(0, 40, nr),
        "k2": rng.integers(0, 4, nr),
        "y": rng.normal(size=nr),
    }
    ml, pl_ = create_test_dfs(left)
    mr, pr = create_test_dfs(right)
    got = assert_no_fallback(lambda: ml.merge(mr, on=on, how=how))
    df_equals(got, pl_.merge(pr, on=on, how=how))


@pytest.mark.parametrize("how", ["inner", "left", "right"])
def test_merge_left_on_right_on_matrix(how):
    rng = np.random.default_rng(19)
    ml, pl_ = create_test_dfs({"a": rng.integers(0, 15, 300), "x": rng.normal(size=300)})
    mr, pr = create_test_dfs({"b": rng.integers(0, 15, 120), "y": rng.normal(size=120)})
    got = assert_no_fallback(lambda: ml.merge(mr, left_on="a", right_on="b", how=how))
    df_equals(got, pl_.merge(pr, left_on="a", right_on="b", how=how))


@pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
def test_merge_nan_float_keys_matrix(how):
    ml, pl_ = create_test_dfs({"k": [1.0, np.nan, 2.0, np.nan, 5.0], "x": np.arange(5.0)})
    mr, pr = create_test_dfs({"k": [np.nan, 2.0, 7.0], "y": np.arange(3.0)})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k", how=how))
    df_equals(got, pl_.merge(pr, on="k", how=how))


@pytest.mark.parametrize("how", ["right", "outer"])
def test_merge_promotes_left_int_on_miss(how):
    ml, pl_ = create_test_dfs({"k": [1, 2], "lv": [10, 20]})
    mr, pr = create_test_dfs({"k": [2, 9], "rv": [7, 8]})
    got = assert_no_fallback(lambda: ml.merge(mr, on="k", how=how))
    df_equals(got, pl_.merge(pr, on="k", how=how))


def test_merge_multikey_mixed_dtypes():
    rng = np.random.default_rng(23)
    n = 300
    left = {
        "ki": rng.integers(0, 10, n),
        "kf": rng.choice([0.5, 1.5, np.nan, 2.5], n),
        "x": rng.normal(size=n),
    }
    right = {
        "ki": rng.integers(0, 10, 100),
        "kf": rng.choice([0.5, 1.5, np.nan], 100),
        "y": rng.normal(size=100),
    }
    ml, pl_ = create_test_dfs(left)
    mr, pr = create_test_dfs(right)
    for how in ("inner", "left", "right", "outer"):
        got = assert_no_fallback(lambda: ml.merge(mr, on=["ki", "kf"], how=how))
        df_equals(got, pl_.merge(pr, on=["ki", "kf"], how=how))


def test_merge_three_keys():
    rng = np.random.default_rng(29)
    n = 500
    cols = lambda n: {
        "a": rng.integers(0, 6, n),
        "b": rng.integers(0, 6, n),
        "c": rng.integers(0, 6, n),
    }
    ml, pl_ = create_test_dfs({**cols(n), "x": rng.normal(size=n)})
    mr, pr = create_test_dfs({**cols(200), "y": rng.normal(size=200)})
    for how in ("inner", "left", "right", "outer"):
        got = assert_no_fallback(lambda: ml.merge(mr, on=["a", "b", "c"], how=how))
        df_equals(got, pl_.merge(pr, on=["a", "b", "c"], how=how))


class TestJoinMergePort:
    """Scenario shapes ported from the reference join/merge suite
    (modin/tests/pandas/dataframe/test_join_sort.py:184-560)."""

    @pytest.mark.parametrize("how", ["left", "right", "inner", "outer"])
    def test_join_empty(self, how):
        md, pdf = create_test_dfs({"a": [1, 2, 3]})
        me = pd.DataFrame(columns=["b"])
        pe = pandas.DataFrame(columns=["b"])
        df_equals(md.join(me, how=how), pdf.join(pe, how=how))

    def test_join_cross_with_lsuffix(self):
        data = [[7, 8, 9], [10, 11, 12]]
        md, pdf = create_test_dfs(data, columns=["x", "y", "z"])
        m = md.join(md[["x"]].set_axis(["p", "q"], axis=0), how="cross", lsuffix="p")
        p = pdf.join(pdf[["x"]].set_axis(["p", "q"], axis=0), how="cross", lsuffix="p")
        df_equals(m, p)

    def test_join_list_with_on_raises(self):
        data = np.ones([2, 4])
        pairs = [create_test_dfs(data, columns=list("abcd")) for _ in range(3)]
        mds, pds = zip(*pairs)
        for dfs in (mds, pds):
            with pytest.raises(
                ValueError,
                match="Joining multiple DataFrames only supported for joining on index",
            ):
                dfs[0].join([dfs[1], dfs[2]], how="inner", on="a")

    def test_join_series_rename(self):
        abbrev_m = pd.Series(
            ["Major League Baseball", "National Basketball Association"],
            index=["MLB", "NBA"],
        )
        abbrev_p = pandas.Series(
            ["Major League Baseball", "National Basketball Association"],
            index=["MLB", "NBA"],
        )
        data = {
            "name": ["Mariners", "Lakers"] * 50,
            "league_abbreviation": ["MLB", "NBA"] * 50,
        }
        md, pdf = create_test_dfs(data)
        m = md.set_index("league_abbreviation").join(abbrev_m.rename("league_name"))
        p = pdf.set_index("league_abbreviation").join(abbrev_p.rename("league_name"))
        df_equals(m, p)

    @pytest.mark.parametrize("how", ["left", "right", "inner", "outer"])
    def test_merge_empty_frames(self, how):
        md, pdf = create_test_dfs({"k": [1, 2], "v": [1.0, 2.0]})
        me = pd.DataFrame(columns=["k", "w"])
        pe = pandas.DataFrame(columns=["k", "w"])
        eval_general(
            (md, me), (pdf, pe), lambda dfs: dfs[0].merge(dfs[1], on="k", how=how)
        )

    def test_merge_with_mi_columns(self):
        md1, pd1 = create_test_dfs(
            {("col0", "a"): [1, 2, 3, 4], ("col0", "b"): [2, 3, 4, 5]}
        )
        md2, pd2 = create_test_dfs(
            {("col0", "a"): [1, 2, 3, 4], ("col0", "c"): [2, 3, 4, 5]}
        )
        df_equals(
            md1.merge(md2, on=[("col0", "a")]), pd1.merge(pd2, on=[("col0", "a")])
        )

    @pytest.mark.parametrize(
        "kwargs",
        [
            {"left_index": True, "right_index": True},
            {"left_index": True, "right_on": "k2"},
            {"left_on": "k", "right_index": True},
        ],
    )
    def test_merge_on_single_index(self, kwargs):
        md1, pd1 = create_test_dfs({"k": [3, 1, 2], "v": [1.0, 2.0, 3.0]})
        md2, pd2 = create_test_dfs({"k2": [1, 2, 9], "w": [5.0, 6.0, 7.0]})
        eval_general(
            (md1, md2), (pd1, pd2), lambda dfs: dfs[0].merge(dfs[1], **kwargs)
        )
