"""Dictionary-encoded string columns (ops/dictionary.py): device groupby and
merge on string/object keys via float64 codes + host categories.

SURVEY §7's staged string answer; the reference instead ships whole object
partitions to workers (modin/core/storage_formats/pandas/query_compiler.py
groupby/merge on object keys).  Differential vs pandas with path-taken
assertions (tests.utils.assert_no_fallback).
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import (
    assert_no_fallback,
    create_test_dfs,
    df_equals,
    eval_general,
    require_tpu_execution,
)

_rng = np.random.default_rng(41)
_CITIES = np.array(
    ["tokyo", "oslo", "lima", "cairo", "perth", "quito", "dakar"], dtype=object
)


def _str_frame(n=1500, nan_frac=0.0, seed=0):
    rng = np.random.default_rng(seed)
    key = _CITIES[rng.integers(0, len(_CITIES), n)].copy()
    if nan_frac:
        key[rng.random(n) < nan_frac] = np.nan
    return {
        "city": key,
        "v": rng.normal(size=n),
        "w": rng.integers(0, 9, n),
    }


class TestDictGroupBy:
    @pytest.mark.parametrize("agg", ["sum", "mean", "count", "size", "median", "min", "max"])
    def test_str_key_aggs_device(self, agg):
        md, pdf = create_test_dfs(_str_frame())
        got = assert_no_fallback(lambda: getattr(md.groupby("city"), agg)())
        df_equals(got, getattr(pdf.groupby("city"), agg)())

    def test_str_key_selection(self):
        md, pdf = create_test_dfs(_str_frame())
        got = assert_no_fallback(lambda: md.groupby("city")["v"].mean())
        df_equals(got, pdf.groupby("city")["v"].mean())

    @pytest.mark.parametrize("dropna", [True, False])
    def test_nan_keys(self, dropna):
        md, pdf = create_test_dfs(_str_frame(nan_frac=0.1))
        got = assert_no_fallback(
            lambda: md.groupby("city", dropna=dropna).sum()
        )
        df_equals(got, pdf.groupby("city", dropna=dropna).sum())

    def test_multi_key_str_plus_int(self):
        md, pdf = create_test_dfs(_str_frame())
        got = assert_no_fallback(lambda: md.groupby(["city", "w"])["v"].sum())
        df_equals(got, pdf.groupby(["city", "w"])["v"].sum())

    def test_by_external_str_series(self):
        md, pdf = create_test_dfs(_str_frame())
        got = assert_no_fallback(lambda: md["v"].groupby(md["city"]).sum())
        df_equals(got, pdf["v"].groupby(pdf["city"]).sum())

    def test_sort_false_appearance_order(self):
        md, pdf = create_test_dfs(_str_frame())
        eval_general(
            md, pdf, lambda df: df.groupby("city", sort=False).sum()
        )

    def test_as_index_false(self):
        md, pdf = create_test_dfs(_str_frame())
        eval_general(
            md, pdf, lambda df: df.groupby("city", as_index=False).sum()
        )

    def test_unorderable_mixed_key_falls_back_correct(self):
        data = {
            "k": np.array([1, "a", 2.5, "a", 1] * 20, dtype=object),
            "v": np.arange(100.0),
        }
        md, pdf = create_test_dfs(data)
        eval_general(md, pdf, lambda df: df.groupby("k")["v"].sum())

    def test_encoding_cached_across_aggs(self):
        require_tpu_execution()
        md, pdf = create_test_dfs(_str_frame())
        col = md._query_compiler._modin_frame.get_column(0)
        assert_no_fallback(lambda: md.groupby("city").sum())
        first = col._dict_cache
        assert first not in (None, False)
        assert_no_fallback(lambda: md.groupby("city").mean())
        assert col._dict_cache is first  # same encoding object: no re-factorize


class TestDictMerge:
    @pytest.mark.parametrize("how", ["inner", "left", "right", "outer"])
    def test_str_key_merge_device(self, how):
        L = _str_frame(n=1200, seed=1)
        R = {
            "city": _CITIES[np.random.default_rng(2).integers(1, 7, 900)],
            "z": np.random.default_rng(2).normal(size=900),
        }
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        got = assert_no_fallback(lambda: md_l.merge(md_r, on="city", how=how))
        df_equals(got, pdf_l.merge(pdf_r, on="city", how=how))

    @pytest.mark.parametrize("how", ["inner", "left", "outer"])
    def test_nan_keys_match_like_pandas(self, how):
        # pandas joins NaN keys to NaN keys; the IEEE total order the join
        # kernels share makes NaN codes behave identically
        L = _str_frame(n=800, nan_frac=0.1, seed=3)
        R = _str_frame(n=700, nan_frac=0.1, seed=4)
        R = {"city": R["city"], "z": R["v"]}
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        got = assert_no_fallback(lambda: md_l.merge(md_r, on="city", how=how))
        df_equals(got, pdf_l.merge(pdf_r, on="city", how=how))

    def test_two_str_keys(self):
        rng = np.random.default_rng(5)
        L = {
            "city": _CITIES[rng.integers(0, 6, 1000)],
            "tag": np.array(["x", "y"], dtype=object)[rng.integers(0, 2, 1000)],
            "v": rng.normal(size=1000),
        }
        R = {
            "city": _CITIES[rng.integers(1, 7, 800)],
            "tag": np.array(["x", "y", "z"], dtype=object)[rng.integers(0, 3, 800)],
            "w": rng.integers(0, 5, 800),
        }
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        got = assert_no_fallback(lambda: md_l.merge(md_r, on=["city", "tag"]))
        df_equals(got, pdf_l.merge(pdf_r, on=["city", "tag"]))

    def test_left_on_right_on_str(self):
        rng = np.random.default_rng(6)
        L = {"a_city": _CITIES[rng.integers(0, 6, 500)], "v": rng.normal(size=500)}
        R = {"b_city": _CITIES[rng.integers(1, 7, 400)], "w": rng.integers(0, 5, 400)}
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        got = assert_no_fallback(
            lambda: md_l.merge(md_r, left_on="a_city", right_on="b_city")
        )
        df_equals(got, pdf_l.merge(pdf_r, left_on="a_city", right_on="b_city"))

    def test_str_payload_columns_gather_on_host(self):
        rng = np.random.default_rng(8)
        L = _str_frame(n=600, seed=8)
        L["note"] = np.array(["a", "bb", "ccc"], dtype=object)[
            rng.integers(0, 3, 600)
        ]
        R = {"city": _CITIES[rng.integers(0, 7, 500)], "z": rng.normal(size=500)}
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        for how in ("inner", "left", "outer"):
            got = assert_no_fallback(lambda: md_l.merge(md_r, on="city", how=how))
            df_equals(got, pdf_l.merge(pdf_r, on="city", how=how))

    def test_mixed_numeric_and_str_key_dtypes_fall_back_correct(self):
        # str key on one side, numeric on the other: pandas raises
        L = {"k": _CITIES[np.random.default_rng(1).integers(0, 3, 50)]}
        R = {"k": np.arange(50)}
        md_l, pdf_l = create_test_dfs(L)
        md_r, pdf_r = create_test_dfs(R)
        eval_general(md_l, pdf_l, lambda df: df.merge(md_r if df is md_l else pdf_r, on="k"))


class TestDictEncodingUnit:
    def test_codes_order_isomorphic(self):
        require_tpu_execution()
        from modin_tpu.ops.dictionary import encode_host_column

        md, _ = create_test_dfs({"s": np.array(["b", "a", "c", "a"], dtype=object)})
        col = md._query_compiler._modin_frame.get_column(0)
        enc = encode_host_column(col)
        assert enc is not None
        assert list(enc.categories) == ["a", "b", "c"]
        assert enc.has_nan is False
        codes = np.asarray(enc.codes.data)[:4]
        assert codes.tolist() == [1.0, 0.0, 2.0, 0.0]

    def test_union_categories_preserves_order(self):
        from modin_tpu.ops.dictionary import union_categories

        u, lm, rm = union_categories(
            np.array(["a", "c"], dtype=object), np.array(["b", "c"], dtype=object)
        )
        assert list(u) == ["a", "b", "c"]
        assert lm.tolist() == [0.0, 2.0] and rm.tolist() == [1.0, 2.0]

    def test_non_string_column_not_encoded(self):
        require_tpu_execution()
        from modin_tpu.ops.dictionary import encode_host_column

        md, _ = create_test_dfs({"x": pandas.array([1, 2, None], dtype="Int64")})
        col = md._query_compiler._modin_frame.get_column(0)
        assert encode_host_column(col) is None


class TestDictSort:
    """sort_values by string keys (dictionary codes are order-isomorphic)
    and host payload columns reordered by the fetched permutation."""

    @pytest.fixture
    def dfs(self):
        rng = np.random.default_rng(11)
        n = 800
        data = {
            "city": _CITIES[rng.integers(0, 6, n)],
            "v": rng.normal(size=n),
            "w": rng.integers(0, 50, n),
            "note": np.array(["a", "bb", "ccc"], dtype=object)[
                rng.integers(0, 3, n)
            ],
        }
        return create_test_dfs(data)

    def test_sort_by_str(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.sort_values("city", kind="stable"))
        df_equals(got, pdf.sort_values("city", kind="stable"))

    def test_sort_by_str_descending(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(
            lambda: md.sort_values("city", ascending=False, kind="stable")
        )
        df_equals(got, pdf.sort_values("city", ascending=False, kind="stable"))

    def test_sort_str_then_numeric(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(
            lambda: md.sort_values(["city", "w"], kind="stable")
        )
        df_equals(got, pdf.sort_values(["city", "w"], kind="stable"))

    def test_sort_numeric_with_str_payload(self, dfs):
        # the gap the r5 verify drive exposed: a str payload column forced
        # the whole sort to fall back
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.sort_values("v"))
        df_equals(got, pdf.sort_values("v"))

    def test_sort_str_nan_last(self):
        rng = np.random.default_rng(12)
        n = 400
        k = _CITIES[rng.integers(0, 4, n)].copy()
        k[rng.random(n) < 0.1] = np.nan
        md, pdf = create_test_dfs({"city": k, "v": rng.normal(size=n)})
        got = assert_no_fallback(lambda: md.sort_values("city", kind="stable"))
        df_equals(got, pdf.sort_values("city", kind="stable"))

    def test_sort_ignore_index(self, dfs):
        md, pdf = dfs
        eval_general(
            md, pdf,
            lambda df: df.sort_values("city", kind="stable", ignore_index=True),
        )


class TestDictValueCountsNuniqueIsin:
    @pytest.fixture
    def dfs(self):
        rng = np.random.default_rng(13)
        n = 900
        k = _CITIES[rng.integers(0, 4, n)].copy()
        k[rng.random(n) < 0.06] = np.nan
        return create_test_dfs(
            {"city": k, "v": rng.normal(size=n), "w": rng.integers(0, 9, n)}
        )

    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"normalize": True},
            {"dropna": False},
            {"ascending": True},
            {"sort": False},
        ],
    )
    def test_value_counts_str(self, dfs, kw):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md["city"].value_counts(**kw))
        df_equals(got, pdf["city"].value_counts(**kw))

    @pytest.mark.parametrize("dropna", [True, False])
    def test_nunique_mixed_frame(self, dfs, dropna):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.nunique(dropna=dropna))
        df_equals(got, pdf.nunique(dropna=dropna))

    def test_isin_mixed_values_frame(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.isin(["tokyo", "lima", 3]))
        df_equals(got, pdf.isin(["tokyo", "lima", 3]))

    def test_isin_series_variants(self, dfs):
        md, pdf = dfs
        for vals in (["oslo"], ["oslo", np.nan], ["zzz"]):
            got = assert_no_fallback(lambda: md["city"].isin(vals))
            df_equals(got, pdf["city"].isin(vals))


class TestIsinNoneVsNan:
    """r5 review: object dtype keeps None and np.nan DISTINCT in isin; both
    encode to NaN codes, so that combination must fall back; the str dtype
    unifies missing values and keeps the device path."""

    def test_object_none_vs_nan_distinct(self):
        md, pdf = create_test_dfs(
            {"s": np.array(["a", np.nan, None, "b"], dtype=object)}
        )
        for vals in ([np.nan], [None], ["a", np.nan]):
            eval_general(md, pdf, lambda df: df["s"].isin(vals))

    def test_str_dtype_missing_unified_device(self):
        s = pandas.Series(["a", np.nan, None, "b"], dtype="str")
        md = pd.DataFrame({"s": s})
        pdf = pandas.DataFrame({"s": s})
        for vals in ([np.nan], [None], ["a", np.nan]):
            got = md["s"].isin(vals)
            df_equals(got, pdf["s"].isin(vals))


class TestDictValueColumns:
    """String VALUE columns in aggregations via codes: groupby
    min/max/first/last/count/nunique, frame-level min/max/count, and
    appearance-ordered Series.unique (r5 batch)."""

    @pytest.fixture
    def dfs(self):
        rng = np.random.default_rng(23)
        n = 900
        vals = _CITIES[rng.integers(0, 4, n)].copy()
        vals[rng.random(n) < 0.1] = np.nan
        return create_test_dfs(
            {"k": rng.integers(0, 7, n), "s": vals, "v": rng.normal(size=n)}
        )

    @pytest.mark.parametrize("agg", ["min", "max", "first", "last", "count", "nunique"])
    def test_groupby_str_values(self, dfs, agg):
        md, pdf = dfs
        got = assert_no_fallback(lambda: getattr(md.groupby("k"), agg)())
        df_equals(got, getattr(pdf.groupby("k"), agg)())

    def test_groupby_str_key_and_values(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.groupby("s").first())
        df_equals(got, pdf.groupby("s").first())

    @pytest.mark.parametrize("op", ["min", "max", "count"])
    def test_frame_reduce_mixed(self, dfs, op):
        md, pdf = dfs
        got = assert_no_fallback(lambda: getattr(md, op)())
        df_equals(got, getattr(pdf, op)())

    def test_frame_min_skipna_false_object_dtype(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df[["s", "v"]].min(skipna=False))
        eval_general(md, pdf, lambda df: df[["s"]].min(skipna=False))

    def test_sum_with_str_falls_back_correct(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df.sum())

    def test_unique_appearance_order(self):
        vals = np.array(
            ["oslo", "tokyo", "lima", "oslo", np.nan, "cairo", "tokyo"],
            dtype=object,
        )
        md, ps = pd.Series(vals), pandas.Series(vals)
        got = assert_no_fallback(lambda: md.unique())
        want = np.asarray(ps.unique(), dtype=object)
        assert [str(x) for x in got] == [str(x) for x in want]


class TestDictDuplicated:
    @pytest.fixture
    def dfs(self):
        rng = np.random.default_rng(29)
        n = 600
        vals = _CITIES[rng.integers(0, 3, n)].copy()
        vals[rng.random(n) < 0.08] = np.nan
        return create_test_dfs(
            {"s": vals, "k": rng.integers(0, 4, n), "v": rng.normal(size=n)}
        )

    @pytest.mark.parametrize("keep", ["first", "last", False])
    def test_duplicated_str_keys(self, dfs, keep):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.duplicated(subset=["s", "k"], keep=keep))
        df_equals(got, pdf.duplicated(subset=["s", "k"], keep=keep))

    def test_drop_duplicates_str_subset(self, dfs):
        md, pdf = dfs
        got = assert_no_fallback(lambda: md.drop_duplicates(subset=["s"]))
        df_equals(got, pdf.drop_duplicates(subset=["s"]))
        eval_general(
            md, pdf,
            lambda df: df.drop_duplicates(subset="s", ignore_index=True),
        )

    def test_nan_keys_count_as_duplicates(self, dfs):
        md, pdf = dfs
        eval_general(md, pdf, lambda df: df.duplicated(subset="s"))


class TestAllMissingAndNAEdges:
    """r5 review: all-missing object columns (empty categories) and
    NA-backed string dtypes through the dict value paths."""

    def test_all_nan_object_column_reductions(self):
        s = pandas.Series([np.nan] * 5, dtype=object)
        md, pdf = pd.DataFrame({"s": s}), pandas.DataFrame({"s": s})
        eval_general(md, pdf, lambda df: df.min())
        eval_general(md, pdf, lambda df: df.count())
        assert len(pd.Series(s).unique()) == len(pandas.Series(s).unique())

    def test_all_nan_groupby_first(self):
        data = {"k": [1, 1, 2], "s": pandas.Series([np.nan] * 3, dtype=object)}
        md, pdf = pd.DataFrame(data), pandas.DataFrame(data)
        eval_general(md, pdf, lambda df: df.groupby("k").first())

    def test_string_na_unique_preserved(self):
        ss = pandas.Series(["a", pandas.NA, "a"], dtype="string")
        got = pd.Series(ss).unique()
        want = np.asarray(pandas.Series(ss).unique(), dtype=object)
        assert [repr(x) for x in got] == [repr(x) for x in want]


class TestDictStringComparisons:
    """String-scalar eq/ne/lt/le/gt/ge on dict-encoded columns: one
    code-threshold device compare (missing rows False except ne=True)."""

    @pytest.fixture
    def series(self):
        rng = np.random.default_rng(33)
        vals = np.array(["berlin", "lima", "oslo", "tokyo"], dtype=object)[
            rng.integers(0, 4, 800)
        ].copy()
        vals[rng.random(800) < 0.07] = np.nan
        return pd.Series(vals), pandas.Series(vals)

    @pytest.mark.parametrize(
        "fn",
        [
            lambda s: s == "oslo",
            lambda s: s == "zzz",
            lambda s: s != "oslo",
            lambda s: s != "zzz",
            lambda s: s < "m",
            lambda s: s <= "lima",
            lambda s: s > "lima",
            lambda s: s >= "m",
        ],
    )
    def test_ops(self, series, fn):
        md, ps = series
        got = assert_no_fallback(lambda: fn(md))
        df_equals(got, fn(ps))

    def test_filter_chain(self, series):
        md, ps = series
        rng = np.random.default_rng(2)
        mdf = pd.DataFrame({"s": np.asarray(md._to_pandas()), "v": rng.normal(size=len(ps))})
        pdf = pandas.DataFrame({"s": np.asarray(ps), "v": np.asarray(mdf["v"]._to_pandas())})
        df_equals(mdf[mdf["s"] == "tokyo"], pdf[pdf["s"] == "tokyo"])


def test_na_string_comparisons_keep_extension_dtype():
    # NA-backed 'string' yields boolean extension results with NA; the
    # device compare path must defer (r5 review)
    ss = pandas.Series(["a", pandas.NA, "b"], dtype="string")
    md = pd.Series(ss)
    for fn in (lambda s: s == "a", lambda s: s != "a", lambda s: s < "b"):
        df_equals(fn(md), fn(ss))
