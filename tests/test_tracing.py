"""graftscope acceptance: spans, export, compile ledger, flight recorder.

Acceptance bar (ISSUE 3): ``profile()`` around a groupby+merge workload
exports chrome://tracing-loadable JSON with nested spans from the API,
query-compiler, engine-seam, and shuffle layers plus host/device/compile
rollups; with tracing disabled the same workload allocates ZERO span
objects; the compile ledger counts a forced recompile; and the flight
recorder dumps on an injected terminal fault.  Plus the satellite
regression: ``configure_logging`` is race-free (one sampler thread, one
handler set, under concurrent first calls).
"""

import json
import subprocess
import sys
import threading

import numpy as np
import pytest

import modin_tpu.observability as graftscope
import modin_tpu.pandas as pd
from modin_tpu.config import (
    RangePartitioning,
    ResilienceRetries,
    TraceDir,
    TraceEnabled,
)
from modin_tpu.core.execution import resilience
from modin_tpu.core.execution.resilience import DeviceOOM, reset_breakers
from modin_tpu.observability import flight_recorder
from modin_tpu.observability.compile_ledger import get_compile_ledger
from modin_tpu.observability.spans import API_LAYERS
from modin_tpu.testing import inject_faults


@pytest.fixture(autouse=True)
def _tracing_off_between_tests():
    """Every test starts and ends with tracing disabled and a clean ring."""
    TraceEnabled.put(False)
    yield
    TraceEnabled.put(False)
    flight_recorder.reset_for_tests()


def _require_tpu_on_jax():
    """Engine-seam span assertions only hold on the device execution; the
    PandasOnPython / NativeOnNative gates skip instead of failing."""
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("engine-seam spans require the TpuOnJax execution")


def _workload():
    """A small groupby+merge pipeline exercising all the instrumented
    layers; returns the final (executed) result."""
    df = pd.DataFrame(
        {"k": [i % 13 for i in range(512)], "v": np.arange(512, dtype=np.float64)}
    )
    dim = pd.DataFrame({"k": list(range(13)), "w": [i * 2.0 for i in range(13)]})
    merged = df.merge(dim, on="k", how="left")
    agg = merged.groupby("k").sum()
    agg._query_compiler.execute()
    return agg


# ====================================================================== #
# span nesting & propagation
# ====================================================================== #


class TestSpanNesting:
    def test_profile_collects_nested_spans_across_layers(self):
        _require_tpu_on_jax()
        with graftscope.profile() as prof:
            _workload()
        layers = {sp.layer for sp in prof.spans}
        assert "PANDAS-API" in layers
        assert "QUERY-COMPILER" in layers
        assert "JAX-ENGINE" in layers

    def test_engine_attempt_nests_under_compiler_and_api(self):
        """The seam chain: an engine attempt span must have QUERY-COMPILER
        and PANDAS-API ancestors — context propagated across all layers."""
        _require_tpu_on_jax()
        with graftscope.profile() as prof:
            _workload()
        attempts = prof.find("engine.")
        assert attempts, "no engine-seam attempt spans collected"
        chained = 0
        for sp in attempts:
            ancestor_layers = {a.layer for a in prof.ancestors(sp)}
            if "QUERY-COMPILER" in ancestor_layers and (
                ancestor_layers & API_LAYERS
            ):
                chained += 1
        assert chained > 0, "no attempt span nested under compiler + API"

    def test_manual_span_nesting_and_attrs(self):
        with graftscope.profile() as prof:
            with graftscope.span("shuffle.range_shuffle", layer="SHUFFLE", rows=4) as outer:
                assert outer is graftscope.current_span()
                with graftscope.layer_span("inner.op", "QUERY-COMPILER") as inner:
                    assert inner.parent_id == outer.span_id
        by_name = {sp.name: sp for sp in prof.spans}
        assert by_name["inner.op"].parent_id == by_name["shuffle.range_shuffle"].span_id
        assert by_name["shuffle.range_shuffle"].attrs["rows"] == 4
        assert by_name["shuffle.range_shuffle"].dur_us >= by_name["inner.op"].dur_us

    def test_span_error_status_on_exception(self):
        with graftscope.profile() as prof:
            with pytest.raises(ValueError):
                with graftscope.span("io.read", layer="CORE-IO"):
                    raise ValueError("boom")
        (sp,) = prof.spans
        assert sp.status == "error"
        assert sp.attrs["exc"] == "ValueError"

    def test_watchdog_thread_adopts_parent_context(self):
        """Spans/attribution on the resilience watchdog thread chain to the
        span that issued the engine call."""
        from modin_tpu.config import ResilienceWatchdogS

        seen = {}

        def thunk():
            from modin_tpu.observability.spans import attribution_signature

            seen["sig"] = attribution_signature()
            return 1

        with ResilienceWatchdogS.context(5.0):
            with graftscope.profile():
                with graftscope.layer_span("Outer.op", "QUERY-COMPILER"):
                    resilience.engine_call("materialize", thunk, watchdog=True)
        assert seen["sig"] == "Outer.op"


# ====================================================================== #
# chrome trace export
# ====================================================================== #


class TestChromeTraceExport:
    def test_groupby_merge_export_is_schema_valid(self, tmp_path):
        with graftscope.profile() as prof:
            _workload()
        path = tmp_path / "trace.json"
        prof.export_chrome_trace(path)
        trace = json.loads(path.read_text())
        assert isinstance(trace["traceEvents"], list)
        complete = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert complete
        for event in complete:
            assert isinstance(event["name"], str)
            assert isinstance(event["cat"], str)
            assert isinstance(event["ts"], (int, float))
            assert isinstance(event["dur"], (int, float))
            assert isinstance(event["pid"], int)
            assert isinstance(event["tid"], int)
            assert isinstance(event["args"], dict)
            assert "span_id" in event["args"]
        # parent ids reference exported spans (the nesting survives export)
        ids = {e["args"]["span_id"] for e in complete}
        child_links = [
            e for e in complete if e["args"].get("parent_id") in ids
        ]
        assert child_links, "no parent->child links in the export"
        # thread metadata present
        assert any(e.get("ph") == "M" for e in trace["traceEvents"])
        # rollup rides along
        rollup = trace["otherData"]["rollup"]
        for key in ("wall_s", "host_s", "device_s", "compile_s", "spans"):
            assert key in rollup

    def test_rollup_accounting(self):
        with graftscope.profile() as prof:
            _workload()
        rollup = prof.rollup()
        assert rollup["spans"] == len(prof.spans) > 0
        assert rollup["wall_s"] > 0
        # engine time is part of the wall, host is the rest
        assert rollup["engine_s"] <= rollup["wall_s"] + 1e-6
        assert rollup["host_s"] == pytest.approx(
            max(rollup["wall_s"] - rollup["engine_s"], 0.0), abs=1e-6
        )
        assert set(rollup["by_layer_self_s"]) == {sp.layer for sp in prof.spans}


# ====================================================================== #
# disabled mode: zero allocation
# ====================================================================== #


class TestDisabledMode:
    def test_workload_allocates_no_spans_when_disabled(self):
        assert not graftscope.trace_enabled()
        _workload()  # warm any lazy imports/caches outside the window
        before = graftscope.span_alloc_count()
        _workload()
        assert graftscope.span_alloc_count() == before, (
            "span objects were allocated while MODIN_TPU_TRACE=0"
        )

    def test_span_api_returns_null_handle_when_disabled(self):
        before = graftscope.span_alloc_count()
        with graftscope.span("io.read", layer="CORE-IO") as sp:
            assert sp is None
        with graftscope.layer_span("X.y", "PANDAS-API") as sp:
            assert sp is None
        assert graftscope.span_alloc_count() == before

    def test_enable_disable_roundtrip(self):
        assert not graftscope.trace_enabled()
        TraceEnabled.put(True)
        try:
            assert graftscope.trace_enabled()
            with graftscope.span("io.read", layer="CORE-IO") as sp:
                assert sp is not None
        finally:
            TraceEnabled.put(False)
        assert not graftscope.trace_enabled()


# ====================================================================== #
# compile ledger
# ====================================================================== #


class TestCompileLedger:
    def test_forced_recompile_is_counted_and_attributed(self):
        import jax
        import jax.numpy as jnp

        ledger = get_compile_ledger()

        # a fresh (never-jitted) function forces a backend compile
        def fresh(x):
            return x * 3 + 1.5

        jitted = jax.jit(fresh)
        arg = jnp.arange(8, dtype=jnp.float64)
        with graftscope.profile():
            with graftscope.layer_span("TestLedger.fresh_op", "QUERY-COMPILER"):
                before = ledger.snapshot()
                np.asarray(jitted(arg))
                after = ledger.snapshot()
        sig = "TestLedger.fresh_op"
        assert after["total_compiles"] > before["total_compiles"]
        assert sig in after["signatures"]
        assert after["signatures"][sig]["compiles"] >= 1
        assert after["signatures"][sig]["compile_s"] > 0

        # second call hits the executable cache: compile count flat
        before = ledger.snapshot()["signatures"][sig]["compiles"]
        with graftscope.profile():
            with graftscope.layer_span(sig, "QUERY-COMPILER"):
                np.asarray(jitted(arg))
        assert ledger.snapshot()["signatures"][sig]["compiles"] == before

    def test_deploy_cache_hits_recorded_through_engine_seam(self):
        """Dispatching the same op twice through the traced engine seam
        records a cache hit for its signature on the second dispatch."""
        import jax
        import jax.numpy as jnp

        from modin_tpu.parallel.engine import JaxWrapper

        jitted = jax.jit(lambda x: x - 7)
        arg = jnp.arange(16, dtype=jnp.float64)
        ledger = get_compile_ledger()
        sig = "TestLedger.hit_op"
        with graftscope.profile():
            for _ in range(2):
                with graftscope.layer_span(sig, "QUERY-COMPILER"):
                    JaxWrapper.wait(JaxWrapper.deploy(jitted, (arg,)))
        entry = ledger.snapshot()["signatures"][sig]
        assert entry["dispatches"] >= 2
        assert entry["cache_hits"] >= 1

    def test_recompile_storm_report(self):
        ledger = get_compile_ledger()
        for _ in range(3):
            ledger.record_compile("stormy_op", 0.25)
        assert ledger.recompile_storms(min_compiles=3).get("stormy_op", 0) >= 3

    def test_compile_time_attributed_to_open_span(self):
        import jax
        import jax.numpy as jnp

        def fresh(x):
            return jnp.sqrt(x) + 2

        jitted = jax.jit(fresh)
        with graftscope.profile() as prof:
            with graftscope.layer_span("TestLedger.span_attr", "QUERY-COMPILER"):
                np.asarray(jitted(jnp.arange(4, dtype=jnp.float64)))
        total_compile = sum(sp.attrs.get("compile_s", 0.0) for sp in prof.spans)
        assert total_compile > 0
        assert prof.rollup()["compile_s"] == pytest.approx(total_compile)


# ====================================================================== #
# flight recorder
# ====================================================================== #


class TestFlightRecorder:
    @pytest.fixture(autouse=True)
    def _fast_dumps(self, monkeypatch):
        monkeypatch.setattr(flight_recorder, "MIN_DUMP_INTERVAL_S", 0.0)
        reset_breakers()
        # cyclic garbage from earlier suites (plan graphs pin compilers until
        # a full gc pass) can leave columns in the device ledger, and a
        # resident ledger turns the injected terminal OOM below into a
        # successful evict-then-retry — collect so the injection is terminal
        import gc

        gc.collect()
        yield
        reset_breakers()

    def test_dump_fires_on_injected_terminal_fault(self, tmp_path):
        """An injected OOM at the engine seam is terminal: the ring of
        recent spans must land on disk as a loadable chrome trace."""
        import jax.numpy as jnp

        from modin_tpu.parallel.engine import JaxWrapper

        with TraceDir.context(str(tmp_path)), TraceEnabled.context(True):
            flight_recorder.reset_for_tests()
            with graftscope.layer_span("TestFlight.query", "QUERY-COMPILER"):
                with inject_faults("oom", ops=("materialize",), times=1):
                    with pytest.raises(DeviceOOM):
                        JaxWrapper.materialize(jnp.arange(4))
            dumps = sorted(tmp_path.glob("flightrec_terminal_oom_*.trace.json"))
            assert dumps, f"no flight dump written under {tmp_path}"
            trace = json.loads(dumps[0].read_text())
            assert trace["otherData"]["reason"] == "terminal_oom"
            names = [e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"]
            assert any(n == "engine.materialize.attempt" for n in names)

    def test_dump_fires_when_breaker_opens(self, tmp_path):
        from modin_tpu.config import ResilienceBreakerThreshold
        from modin_tpu.core.execution.resilience import get_breaker

        with TraceDir.context(str(tmp_path)), TraceEnabled.context(True):
            flight_recorder.reset_for_tests()
            with graftscope.span("io.read", layer="CORE-IO"):
                pass  # something in the ring
            with ResilienceBreakerThreshold.context(2):
                breaker = get_breaker("probe_flight")
                breaker.record_failure()
                breaker.record_failure()
            dumps = sorted(
                tmp_path.glob("flightrec_breaker_open_probe_flight_*.trace.json")
            )
            assert dumps, "no dump on breaker open"

    def test_no_dump_when_tracing_disabled(self, tmp_path):
        import jax.numpy as jnp

        from modin_tpu.parallel.engine import JaxWrapper

        assert not graftscope.trace_enabled()
        with TraceDir.context(str(tmp_path)):
            with inject_faults("oom", ops=("materialize",), times=1):
                with pytest.raises(DeviceOOM):
                    JaxWrapper.materialize(jnp.arange(4))
        assert not list(tmp_path.glob("*.trace.json"))

    def test_flight_ring_resizes_on_config_change(self):
        from modin_tpu.config import TraceFlightRecorderSize

        with TraceEnabled.context(True):
            with TraceFlightRecorderSize.context(4):
                for i in range(10):
                    with graftscope.layer_span(f"resize{i}", "QUERY-COMPILER"):
                        pass
                snap = flight_recorder.flight_snapshot()
                assert len(snap) == 4
                assert snap[-1].name == "resize9"

    def test_flight_snapshot_bounded_by_ring(self):
        from modin_tpu.config import TraceFlightRecorderSize

        size = int(TraceFlightRecorderSize.get())
        with TraceEnabled.context(True):
            flight_recorder.reset_for_tests()
            for i in range(size + 50):
                with graftscope.layer_span(f"op{i}", "QUERY-COMPILER"):
                    pass
            snap = flight_recorder.flight_snapshot()
            assert len(snap) == size
            # oldest dropped, newest retained
            assert snap[-1].name == f"op{size + 49}"


# ====================================================================== #
# retries appear as sibling attempt spans with failure kinds
# ====================================================================== #


class TestResilienceComposition:
    def test_retried_transient_shows_failed_and_clean_attempts(self):
        with ResilienceRetries.context(2):
            with graftscope.profile() as prof:
                with inject_faults("transient", ops=("put",), times=1):
                    from modin_tpu.parallel.engine import JaxWrapper

                    JaxWrapper.put(np.arange(32, dtype=np.float64))
        attempts = [sp for sp in prof.spans if sp.name == "engine.put.attempt"]
        assert len(attempts) >= 2
        failed = [sp for sp in attempts if sp.status == "error"]
        clean = [sp for sp in attempts if sp.status == "ok"]
        assert failed and clean
        assert failed[0].attrs["failure_kind"] == "transient"
        assert failed[0].attrs["attempt"] == 0

    def test_base_exception_unwind_pops_attempt_span(self):
        """A non-Exception unwind (Ctrl-C, the bench SIGALRM) through the
        engine seam must not leave the attempt span on the thread stack."""

        class Unwind(BaseException):
            pass

        def thunk():
            raise Unwind()

        with graftscope.profile() as prof:
            with pytest.raises(Unwind):
                resilience.engine_call("wait", thunk)
            assert graftscope.current_span() is None
        (sp,) = prof.find("engine.wait.attempt")
        assert sp.status == "error"

    def test_device_path_fallback_emits_fallback_span(self):
        from modin_tpu.core.execution.resilience import device_path

        class Probe:
            @device_path("probe_span_unit")
            def _try_thing(self):
                raise resilience.TransientDeviceError("DEADLINE_EXCEEDED")

        with graftscope.profile() as prof:
            assert Probe()._try_thing() is None
        falls = prof.find("fallback.probe_span_unit")
        assert len(falls) == 1
        assert falls[0].attrs["reason"] == "transient"


# ====================================================================== #
# satellite: configure_logging race regression
# ====================================================================== #

_RACE_SNIPPET = r"""
import threading
import modin_tpu.logging.config as cfg
from modin_tpu.config import LogMode

LogMode.put("Enable")
barrier = threading.Barrier(8)
def hammer():
    barrier.wait()
    cfg.get_logger()
threads = [threading.Thread(target=hammer) for _ in range(8)]
for t in threads: t.start()
for t in threads: t.join()

import logging
handlers = logging.getLogger("modin_tpu.logger").handlers
samplers = [
    t for t in threading.enumerate() if t.name == "modin-tpu-memory-sampler"
]
print("HANDLERS", len(handlers), "SAMPLERS", len(samplers),
      "CONFIGURED", cfg.__LOGGER_CONFIGURED__, flush=True)
# skip interpreter teardown: the daemon sampler thread may be inside jax
# C++ when the runtime is torn down, which aborts an otherwise-passed run
import os
os._exit(0)
"""


class TestConfigureLoggingRace:
    def test_concurrent_first_configuration_happens_once(self, tmp_path):
        """Eight threads race get_logger(); exactly one handler set and one
        memory-sampler daemon must exist (subprocess: fresh module state)."""
        import os
        import pathlib

        repo_root = pathlib.Path(__file__).resolve().parent.parent
        env = dict(os.environ)
        env["PYTHONPATH"] = str(repo_root) + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", _RACE_SNIPPET],
            capture_output=True,
            text=True,
            timeout=120,
            cwd=tmp_path,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr
        line = [l for l in proc.stdout.splitlines() if l.startswith("HANDLERS")][-1]
        assert line == "HANDLERS 1 SAMPLERS 1 CONFIGURED True", line

    def test_reconfigure_is_noop_and_keeps_sampler_handle(self):
        import modin_tpu.logging.config as cfg

        from modin_tpu.concurrency.lockdep import DepLock

        lock = cfg._configure_lock
        # a registry-named non-reentrant mutex (graftdep wraps the raw lock)
        assert isinstance(lock, DepLock) and not lock.reentrant
        assert lock.name == "logging.configure"
        # simulate "already configured": the body must not run again
        saved = cfg.__LOGGER_CONFIGURED__
        cfg.__LOGGER_CONFIGURED__ = True
        try:
            sampler_before = cfg._mem_sampler
            cfg.configure_logging()
            assert cfg._mem_sampler is sampler_before
        finally:
            cfg.__LOGGER_CONFIGURED__ = saved
