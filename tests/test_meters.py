"""graftmeter acceptance: aggregation, per-query accounting, exposition.

Acceptance bar (ISSUE 7): counters stay exact under multi-threaded
increments; histogram percentiles are accurate on known distributions;
``QueryStats`` scopes are isolated across interleaved queries on two
threads; disabled mode (``MODIN_TPU_METERS=0``) allocates ZERO aggregation
objects across a real workload; the Prometheus/JSON exposition round-trips
through its validating parser; the metrics_smoke efficiency gate actually
fails on an inflated dispatch count; flight-recorder dumps embed a metrics
snapshot (including on the rate-limited path); and
``explain(analyze=True)`` annotates every executed plan node while staying
bit-exact.
"""

import importlib.util
import json
import os
import sys
import threading

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import MetersEnabled, MetersMaxSeries, TraceDir, TraceEnabled
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import exposition, flight_recorder, meters
from modin_tpu.observability.chrome_trace import COUNTER_TRACKS, to_chrome_trace


@pytest.fixture(autouse=True)
def _meters_off_between_tests():
    """Every test starts and ends with meters off and an empty registry."""
    MetersEnabled.put(False)
    meters.reset()
    yield
    MetersEnabled.put(False)
    meters.reset()


@pytest.fixture(autouse=True, scope="module")
def _collect_cyclic_residue():
    """The analyze tests build plan graphs whose reference cycles keep dead
    frames (and their device-ledger entries) alive until a full gc pass;
    collect at module teardown so later suites see an empty ledger."""
    yield
    import gc

    gc.collect()


def _require_tpu_on_jax():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("planned execution requires the TpuOnJax execution")


def _smoke_module():
    """Import scripts/metrics_smoke.py (not a package) for its helpers."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "metrics_smoke.py",
    )
    spec = importlib.util.spec_from_file_location("metrics_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ====================================================================== #
# meter correctness
# ====================================================================== #


class TestCounters:
    def test_multithreaded_increments_are_exact(self):
        MetersEnabled.put(True)
        threads, per_thread = 8, 5000
        barrier = threading.Barrier(threads)

        def worker():
            barrier.wait()
            for _ in range(per_thread):
                emit_metric("resilience.shuffle.slack_retry", 1)

        ts = [threading.Thread(target=worker) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        series = meters.snapshot()["series"]["resilience.shuffle.slack_retry"]
        assert series["kind"] == "counter"
        assert series["total"] == threads * per_thread
        assert series["count"] == threads * per_thread

    def test_kind_resolution_from_registry(self):
        MetersEnabled.put(True)
        emit_metric("resilience.engine.deploy.oom", 1)  # counter (wildcard)
        emit_metric("io.read.bytes", 2048)  # histogram
        emit_metric("memory.device.resident_bytes", 512)  # gauge
        emit_metric("some.adhoc.test.name", 3)  # undeclared -> counter
        series = meters.snapshot()["series"]
        assert series["resilience.engine.deploy.oom"]["kind"] == "counter"
        assert series["io.read.bytes"]["kind"] == "histogram"
        assert series["memory.device.resident_bytes"]["kind"] == "gauge"
        assert series["some.adhoc.test.name"]["kind"] == "counter"

    def test_max_series_cardinality_guard(self):
        MetersEnabled.put(True)
        old = MetersMaxSeries.get()
        MetersMaxSeries.put(4)
        try:
            for i in range(10):
                emit_metric(f"cardinality.burst.k{i}", 1)
            emit_metric("cardinality.burst.k9", 1)  # repeat a dropped name
            snap = meters.snapshot()
            assert len(snap["series"]) == 4
            # distinct refused names vs raw refused emissions
            assert snap["dropped_series"] == 6
            assert snap["dropped_observations"] == 7
        finally:
            MetersMaxSeries.put(old)

    def test_reset_clears_registry(self):
        MetersEnabled.put(True)
        emit_metric("sortcache.hit", 1)
        assert meters.snapshot()["series"]
        meters.reset()
        assert meters.snapshot()["series"] == {}


class TestGauge:
    def test_last_value_min_max(self):
        gauge = meters.Gauge()
        for v in (5, 1, 9, 3):
            gauge.add(v)
        snap = gauge.snapshot()
        assert snap == {"kind": "gauge", "value": 3, "min": 1, "max": 9, "count": 4}


class TestHistogram:
    def test_percentiles_on_known_uniform(self):
        bounds = tuple(float(b) for b in range(100, 1100, 100))
        hist = meters.Histogram(bounds)
        for v in range(1, 1001):  # exact uniform over (0, 1000]
            hist.add(v)
        snap = hist.snapshot()
        assert snap["count"] == 1000
        assert snap["sum"] == sum(range(1, 1001))
        assert snap["min"] == 1 and snap["max"] == 1000
        # linear interpolation inside 100-wide buckets: within one bucket
        assert abs(snap["p50"] - 500) <= 100
        assert abs(snap["p95"] - 950) <= 100
        assert abs(snap["p99"] - 990) <= 100
        # cumulative bucket counts are monotone and end at count
        cums = [c for _b, c in snap["buckets"]]
        assert cums == sorted(cums) and cums[-1] == 1000

    def test_overflow_bucket_and_percentile_clamp(self):
        hist = meters.Histogram((1.0, 2.0))
        for v in (0.5, 1.5, 10.0, 20.0):
            hist.add(v)
        snap = hist.snapshot()
        assert snap["count"] == 4
        # overflow values pull the high percentiles above the last bound
        assert snap["p99"] > 2.0
        assert snap["p99"] <= 20.0

    def test_empty_percentile_is_none(self):
        hist = meters.Histogram((1.0,))
        assert hist.percentile(0.5) is None
        assert hist.snapshot()["p50"] is None

    def test_single_value_percentiles_degenerate(self):
        hist = meters.Histogram((1.0, 10.0))
        hist.add(5.0)
        assert hist.snapshot()["p50"] == pytest.approx(5.0)


# ====================================================================== #
# disabled-mode contract
# ====================================================================== #


class TestDisabledMode:
    def test_zero_alloc_without_meters(self):
        df = pd.DataFrame({"a": np.arange(64.0), "b": np.arange(64.0)})
        _ = (df + 1).sum().modin.to_pandas()  # warm every code path
        before = meters.meter_alloc_count()
        df2 = pd.DataFrame({"a": np.arange(64.0), "b": np.arange(64.0)})
        _ = (df2 * 2).sum().modin.to_pandas()
        _ = df2.shape
        assert meters.meter_alloc_count() == before
        # the hook itself is uninstalled, not just inert
        from modin_tpu.logging import metrics as metrics_mod

        assert metrics_mod._aggregate is None
        assert not meters.ACCOUNTING_ON

    def test_enable_disable_flips_fast_path(self):
        assert not meters.ACCOUNTING_ON
        MetersEnabled.put(True)
        assert meters.ACCOUNTING_ON and meters.METERS_ON
        MetersEnabled.put(False)
        assert not meters.ACCOUNTING_ON and not meters.METERS_ON


# ====================================================================== #
# per-query accounting
# ====================================================================== #


class TestQueryStats:
    def test_scope_accounts_without_meters_enabled(self):
        assert not meters.METERS_ON
        with meters.query_stats("adhoc") as qs:
            assert meters.ACCOUNTING_ON  # scope flips the fast path
            emit_metric("engine.dispatch", 1)
            emit_metric("engine.compile", 1)
            emit_metric("engine.compile_s", 0.25)
            emit_metric("io.read.bytes", 4096)
            emit_metric("fusion.cache.hit", 1)
            emit_metric("recovery.device_lost", 1)
        assert not meters.ACCOUNTING_ON  # restored on exit
        assert qs.dispatches == 1
        assert qs.compiles == 1
        assert qs.compile_s == pytest.approx(0.25)
        assert qs.bytes_parsed == 4096 and qs.io_reads == 1
        assert qs.cache_hits["fused"] == 1
        assert qs.recoveries == 1
        assert qs.wall_s > 0
        # the ad-hoc scope left nothing in the (disabled) registry
        assert meters.snapshot()["series"] == {}

    def test_nested_scopes_both_account(self):
        with meters.query_stats("outer") as outer:
            emit_metric("engine.dispatch", 1)
            with meters.query_stats("inner") as inner:
                emit_metric("engine.dispatch", 1)
        assert outer.dispatches == 2
        assert inner.dispatches == 1

    def test_isolation_across_interleaved_threads(self):
        """Two queries interleaved on two threads never cross-bill."""
        results = {}
        b1, b2 = threading.Barrier(2), threading.Barrier(2)

        def query(name, dispatches, read_bytes):
            with meters.query_stats(name) as qs:
                b1.wait()  # both scopes open before either emits
                for _ in range(dispatches):
                    emit_metric("engine.dispatch", 1)
                emit_metric("io.read.bytes", read_bytes)
                b2.wait()  # both emitted before either scope closes
            results[name] = qs

        t1 = threading.Thread(target=query, args=("q1", 3, 100))
        t2 = threading.Thread(target=query, args=("q2", 5, 999))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert results["q1"].dispatches == 3
        assert results["q1"].bytes_parsed == 100
        assert results["q2"].dispatches == 5
        assert results["q2"].bytes_parsed == 999

    def test_watchdog_worker_thread_bills_owning_scope(self):
        """Metrics emitted on the resilience watchdog's daemon thread roll
        into the query_stats scope open on the calling thread (the compile
        listener fires inside the watched thunk, i.e. on the worker)."""
        from modin_tpu.core.execution.resilience import _run_with_watchdog

        def thunk():
            emit_metric("engine.compile", 1)
            emit_metric("engine.compile_s", 0.5)
            return "ok"

        with meters.query_stats("watched") as qs:
            assert _run_with_watchdog("materialize", thunk, 30.0) == "ok"
        assert qs.compiles == 1
        assert qs.compile_s == pytest.approx(0.5)

    def test_abandoned_worker_cannot_mutate_closed_scope(self):
        """A seeded worker the owner abandoned (watchdog timeout) emits
        after the scope closed: the late emission must not land in the
        rollup the owner already read."""
        MetersEnabled.put(True)  # keep the emit hook installed post-close
        release, seeded = threading.Event(), threading.Event()

        def worker(scopes):
            meters.seed_thread_scopes(scopes)
            seeded.set()
            release.wait(5)
            emit_metric("engine.compile", 1)  # fires after scope exit

        with meters.query_stats("abandoned") as qs:
            emit_metric("engine.compile", 1)
            t = threading.Thread(
                target=worker, args=(meters.snapshot_scopes(),), daemon=True
            )
            t.start()
            assert seeded.wait(5)
        release.set()
        t.join(5)
        # the registry saw both compiles; the closed scope only the first
        assert meters.snapshot()["series"]["engine.compile"]["total"] == 2
        assert qs.compiles == 1

    def test_as_dict_and_summary_are_complete(self):
        with meters.query_stats("q") as qs:
            emit_metric("engine.dispatch", 1)
        d = qs.as_dict()
        for key in (
            "wall_s",
            "dispatches",
            "compiles",
            "compile_s",
            "bytes_parsed",
            "spills",
            "restores",
            "recoveries",
            "cache_hits",
            "hbm_high_water",
        ):
            assert key in d
        text = qs.summary()
        assert "device dispatches: 1" in text


# ====================================================================== #
# exposition
# ====================================================================== #


class TestExposition:
    def _snapshot_with_all_kinds(self):
        MetersEnabled.put(True)
        emit_metric("sortcache.hit", 2)  # counter
        emit_metric("memory.device.resident_bytes", 1024)  # gauge
        emit_metric("io.read.bytes", 4096)  # histogram
        emit_metric("io.read.bytes", 1 << 22)
        return meters.snapshot()

    def test_prometheus_round_trip(self):
        snap = self._snapshot_with_all_kinds()
        text = exposition.to_prometheus(snap)
        parsed = exposition.parse_prometheus(text)
        assert parsed["modin_tpu_sortcache_hit"]["type"] == "counter"
        assert parsed["modin_tpu_sortcache_hit"]["samples"][
            "modin_tpu_sortcache_hit"
        ] == 2
        assert parsed["modin_tpu_memory_device_resident_bytes"]["type"] == "gauge"
        hist = parsed["modin_tpu_io_read_bytes"]
        assert hist["type"] == "histogram"
        assert hist["samples"]["modin_tpu_io_read_bytes_count"] == 2
        assert hist["samples"]["modin_tpu_io_read_bytes_sum"] == 4096 + (1 << 22)
        assert any("_bucket" in k for k in hist["samples"])

    def test_help_lines_carry_registry_descriptions(self):
        """# HELP text comes from the METRICS registry 3-tuples and
        survives the parse roundtrip (the graftwatch satellite)."""
        from modin_tpu.logging.metrics import METRICS

        snap = self._snapshot_with_all_kinds()
        text = exposition.to_prometheus(snap)
        parsed = exposition.parse_prometheus(text)
        declared = {
            entry[0]: " ".join(str(entry[2]).split()) for entry in METRICS
        }
        # exact-name family: description verbatim
        assert (
            parsed["modin_tpu_io_read_bytes"]["help"]
            == declared["io.read.bytes"]
        )
        # wildcard family resolves through fnmatch
        assert (
            parsed["modin_tpu_sortcache_hit"]["help"]
            == declared["sortcache.*"]
        )
        # an ad-hoc name not in the registry keeps the generic fallback
        emit_metric("adhoc.testonly.name", 1)
        text = exposition.to_prometheus(meters.snapshot())
        parsed = exposition.parse_prometheus(text)
        assert (
            parsed["modin_tpu_adhoc_testonly_name"]["help"]
            == "modin_tpu metric adhoc.testonly.name"
        )

    def test_help_text_escapes_newlines_and_backslashes(self, monkeypatch):
        import modin_tpu.logging.metrics as metrics_mod

        patched = metrics_mod.METRICS + (
            ("unit.help.escape", "counter", "path C:\\tmp\nsecond line"),
        )
        monkeypatch.setattr(metrics_mod, "METRICS", patched)
        # a registry description: whitespace (the newline included)
        # normalizes to single spaces, then backslashes escape per the
        # Prometheus text format
        text = exposition.help_text("unit.help.escape")
        assert text == "path C:\\\\tmp second line"
        assert "\n" not in text
        # the generic fallback escapes a hostile snapshot name too (names
        # from exposition callers are arbitrary, unlike emit_metric's)
        evil = exposition.help_text("adhoc\nhostile.name")
        assert "\n" not in evil and "\\n" in evil

    def test_parser_rejects_malformed_help(self):
        with pytest.raises(ValueError):
            exposition.parse_prometheus("# HELP \nx 1")

    def test_json_round_trip(self):
        snap = self._snapshot_with_all_kinds()
        loaded = json.loads(exposition.to_json(snap))
        assert loaded["series"].keys() == snap["series"].keys()
        assert loaded["series"]["io.read.bytes"]["p50"] is not None

    @pytest.mark.parametrize(
        "bad_text",
        [
            "not a metric line at all {",
            "# TYPE modin_tpu_x sketchy\nmodin_tpu_x 1",
            "modin_tpu_orphan 1",  # sample before TYPE declaration
            # non-cumulative histogram buckets
            "# TYPE modin_tpu_h histogram\n"
            'modin_tpu_h_bucket{le="1"} 5\n'
            'modin_tpu_h_bucket{le="2"} 3\n',
        ],
    )
    def test_parser_rejects_malformed(self, bad_text):
        with pytest.raises(ValueError):
            exposition.parse_prometheus(bad_text)

    def test_meter_rollup_schema_stable_on_empty(self):
        rollup = exposition.meter_rollup({"series": {}})
        assert rollup["dispatches"] == 0
        assert rollup["bytes_parsed"] == 0
        assert rollup["cache_hits"] == {"fused": 0, "sorted_rep": 0, "plan_scan": 0}

    def test_meter_rollup_reads_series(self):
        snap = self._snapshot_with_all_kinds()
        rollup = exposition.meter_rollup(snap)
        assert rollup["bytes_parsed"] == 4096 + (1 << 22)
        assert rollup["io_reads"] == 2
        assert rollup["cache_hits"]["sorted_rep"] == 2


# ====================================================================== #
# the efficiency-invariant gate
# ====================================================================== #


class TestMetricsSmokeGate:
    def test_gate_fails_on_inflated_dispatch_count(self):
        """The acceptance demonstration: a refactor that silently doubles
        the pipeline's dispatch count turns the gate red."""
        smoke = _smoke_module()
        baseline = {
            "max": {"dispatches": 2, "compiles": 2, "io_reads": 1},
            "min": {"pruned_columns": 3},
        }
        ok = {"dispatches": 2, "compiles": 2, "io_reads": 1, "pruned_columns": 3}
        assert smoke.check_invariants(ok, baseline) == []
        inflated = dict(ok, dispatches=4)
        failures = smoke.check_invariants(inflated, baseline)
        assert failures and "dispatches" in failures[0]

    def test_gate_fails_on_lost_pruning_and_missing_keys(self):
        smoke = _smoke_module()
        baseline = {"max": {"dispatches": 2}, "min": {"pruned_columns": 3}}
        failures = smoke.check_invariants(
            {"dispatches": 2, "pruned_columns": 0}, baseline
        )
        assert any("pruned_columns" in f for f in failures)
        failures = smoke.check_invariants({"pruned_columns": 3}, baseline)
        assert any("not measured" in f for f in failures)

    def test_bytes_tolerance_is_applied(self):
        smoke = _smoke_module()
        baseline = {"max": {"bytes_parsed": 1000}, "min": {}}
        assert smoke.check_invariants({"bytes_parsed": 1015}, baseline) == []
        assert smoke.check_invariants({"bytes_parsed": 1100}, baseline)

    def test_recorded_baseline_exists_and_is_wellformed(self):
        smoke = _smoke_module()
        baseline = smoke.load_baseline()
        assert set(baseline["max"]) == {
            "dispatches",
            "compiles",
            "io_reads",
            "bytes_parsed",
        }
        assert baseline["min"]["pruned_columns"] >= 1


# ====================================================================== #
# counter tracks + flight recorder embedding
# ====================================================================== #


class TestCounterTracks:
    def test_chrome_trace_counter_events_from_samples(self):
        samples = [
            (10.0, (111, 222, 3, 40, 1000, 2, 1)),
            (20.0, (444, 555, 6, 80, 2000, 5, 4)),
        ]
        trace = to_chrome_trace([], counters=samples)
        cevents = [e for e in trace["traceEvents"] if e["ph"] == "C"]
        assert len(cevents) == len(samples) * len(COUNTER_TRACKS)
        by_name = {}
        for e in cevents:
            by_name.setdefault(e["name"], []).append(e["args"]["value"])
        assert by_name["memory.device.resident_bytes"] == [111, 444]
        assert by_name["memory.host.cache_bytes"] == [222, 555]
        assert by_name["spans.live"] == [3, 6]
        assert by_name["engine.cost.padding_waste_bytes"] == [40, 80]
        assert by_name["engine.cost.achieved_bw_bytes_s"] == [1000, 2000]
        assert by_name["serving.gate.queued"] == [2, 5]
        assert by_name["serving.gate.running"] == [1, 4]

    def test_legacy_samples_render_without_gate_tracks(self):
        """Pre-graftwatch 5-tuple samples still render — zip stops short,
        the gate tracks are simply absent (the documented contract)."""
        trace = to_chrome_trace([], counters=[(10.0, (1, 2, 3, 4, 5))])
        names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
        assert "engine.cost.achieved_bw_bytes_s" in names
        assert "serving.gate.queued" not in names

    def test_profile_export_carries_counter_tracks(self):
        import modin_tpu.observability as graftscope

        flight_recorder.reset_for_tests()
        with graftscope.profile() as prof:
            df = pd.DataFrame({"k": [i % 5 for i in range(128)], "v": np.arange(128.0)})
            agg = df.groupby("k").sum()
            agg._query_compiler.execute()
        trace = prof.to_chrome_trace()
        tracks = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
        assert set(COUNTER_TRACKS) <= tracks
        json.dumps(trace)  # loadable


class TestFlightRecorderMetricsSnapshot:
    @pytest.fixture(autouse=True)
    def _tracing_reset(self):
        TraceEnabled.put(False)
        flight_recorder.reset_for_tests()
        yield
        TraceEnabled.put(False)
        flight_recorder.reset_for_tests()

    def _arm_and_span(self, tmp_path):
        TraceDir.put(str(tmp_path))
        TraceEnabled.put(True)
        from modin_tpu.observability import spans as graftscope_spans

        with graftscope_spans.span("io.read", layer="CORE-IO"):
            pass

    def test_dump_embeds_metrics_snapshot(self, tmp_path):
        MetersEnabled.put(True)
        emit_metric("sortcache.hit", 7)
        self._arm_and_span(tmp_path)
        path = flight_recorder.dump_flight_record("unit_metrics")
        assert path is not None
        data = json.loads(open(path).read())
        embedded = data["otherData"]["metrics"]
        assert embedded["enabled"] is True
        assert embedded["series"]["sortcache.hit"]["total"] == 7

    def test_rate_limited_path_regression(self, tmp_path):
        """The metrics embedding must not break rate limiting: the second
        dump inside the window stays suppressed, and the limiter window is
        still released on a failed write."""
        MetersEnabled.put(True)
        emit_metric("sortcache.hit", 1)
        self._arm_and_span(tmp_path)
        first = flight_recorder.dump_flight_record("unit_rate")
        assert first is not None
        assert flight_recorder.dump_flight_record("unit_rate") is None
        # outside the window it dumps again, still with the snapshot
        flight_recorder._last_dump = 0.0
        second = flight_recorder.dump_flight_record("unit_rate2")
        assert second is not None and second != first
        assert "metrics" in json.loads(open(second).read())["otherData"]

    def test_dump_with_meters_off_records_disabled_snapshot(self, tmp_path):
        self._arm_and_span(tmp_path)
        path = flight_recorder.dump_flight_record("unit_off")
        assert path is not None
        embedded = json.loads(open(path).read())["otherData"]["metrics"]
        assert embedded["enabled"] is False


# ====================================================================== #
# EXPLAIN ANALYZE
# ====================================================================== #


class TestExplainAnalyze:
    def _csv(self, tmp_path, rows=200):
        path = str(tmp_path / "t.csv")
        rng = np.random.default_rng(3)
        pandas.DataFrame(
            {
                "a": rng.integers(-10, 10, rows),
                "b": rng.uniform(0, 1, rows),
                "c": rng.uniform(0, 1, rows),
                "d": rng.integers(0, 5, rows),
            }
        ).to_csv(path, index=False)
        return path

    def test_analyze_annotates_every_node_and_stays_bit_exact(self, tmp_path):
        _require_tpu_on_jax()
        from modin_tpu.config import PlanMode

        path = self._csv(tmp_path)
        with PlanMode.context("Auto"):
            md = pd.read_csv(path).query("a > 0")[["b", "c"]]
            if md._query_compiler._plan is None:
                pytest.skip("read did not defer under this configuration")
            text = md.modin.explain(analyze=True)
            result = md.agg("sum").modin.to_pandas()
        assert "status: analyzed" in text
        after = text.split("with actuals) ==")[1].split("rewrites:")[0]
        node_lines = [ln for ln in after.splitlines() if ln.strip().startswith("#")]
        assert node_lines
        for ln in node_lines:
            assert "(actual:" in ln, ln
            for field in ("time=", "rows=", "bytes=", "dispatches="):
                assert field in ln, ln
        assert "== query rollup ==" in text
        reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
        pandas.testing.assert_series_equal(result, reference)

    def test_analyze_attributes_dispatches_and_wall_time(self, tmp_path):
        _require_tpu_on_jax()
        from modin_tpu.config import PlanMode
        from modin_tpu.plan import runtime

        path = self._csv(tmp_path)
        with PlanMode.context("Auto"):
            md = pd.read_csv(path).query("a > 0")[["b", "c"]]
            if md._query_compiler._plan is None:
                pytest.skip("read did not defer under this configuration")
            analyzed = runtime.explain_analyze(md._query_compiler)
        assert analyzed is not None
        stats, actuals, (_root, _optimized, _applied) = analyzed
        assert stats.dispatches >= 1
        assert stats.wall_s > 0
        assert stats.bytes_parsed > 0
        # dispatch attribution: per-node self dispatches sum to the rollup
        assert sum(m["dispatches"] for m in actuals.values()) == stats.dispatches
        # every actual entry has a measured time
        assert all(m["total_s"] >= m["self_s"] >= 0 for m in actuals.values())

    def test_analyze_on_plain_eager_compiler_reports_eager(self):
        df = pd.DataFrame({"a": [1, 2, 3]})
        text = df.modin.explain(analyze=True)
        assert text.startswith("status: eager")

    def test_analyze_tolerates_non_graftplan_compiler(self):
        """A compiler without _plan/_plan_explain (any non-Tpu backend) gets
        the eager note, not an AttributeError — same as analyze=False."""
        from modin_tpu.plan import runtime
        from modin_tpu.plan.explain import explain_qc

        assert runtime.explain_analyze(object()) is None
        assert explain_qc(object(), analyze=True).startswith("status: eager")

    def test_alloc_free_when_analyze_not_used(self, tmp_path):
        """explain(analyze=False) keeps the old contract: no QueryStats."""
        df = pd.DataFrame({"a": [1, 2, 3]})
        before = meters.meter_alloc_count()
        _ = df.modin.explain()
        assert meters.meter_alloc_count() == before
