"""graftfuse acceptance: whole-plan fused compilation, donation, buckets.

Four layers of coverage:

1. **differential grid** — deferred ``read_csv -> filter/map/project ->
   reduce | groupby_agg`` pipelines executed three ways (MODIN_TPU_FUSE=
   Fused / Staged, plus plain pandas) must agree: int/float/bool columns,
   NaN values, empty sources, filters keeping zero rows, groupby at high
   and low key cardinality, and ragged physical sizes at bucket
   boundaries.
2. **donation** — the fused dispatch consumes sole-consumer input buffers;
   the owning DeviceColumns transparently restore via lineage on the next
   access (host round-trip AND a later device op), and a shared buffer is
   never donated.
3. **program-cache identity** — the fused-executable cache key carries the
   mesh shape + device epoch: an in-process ``MeshShape`` flip must never
   reuse a program traced for another topology (the ``_jit_shuffle``
   stale-program class).
4. **routing/bucket units** — ``decide_compile`` forced modes + min-rows
   floor, and the storm-feedback padding quantizer's escalation levels.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import FuseMinRows, FuseMode, MeshShape, PlanMode
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.plan import fuse


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("graftfuse rides the TpuOnJax query compiler")


@pytest.fixture(autouse=True)
def _clean_storm_state():
    fuse.reset_storm_state()
    yield
    fuse.reset_storm_state()


@pytest.fixture
def metric_counts():
    seen = {}

    def handler(name, value):
        seen[name] = seen.get(name, 0) + value

    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


_rng = np.random.default_rng(23)


def _write_csv(tmp_path, n, name="fuse.csv", nan_frac=0.0):
    b = _rng.uniform(0, 1, n)
    c = _rng.uniform(-1, 1, n)
    if nan_frac and n:
        idx = _rng.random(n) < nan_frac
        b = b.copy()
        b[idx] = np.nan
    pandas.DataFrame(
        {
            "a": _rng.integers(-10, 10, n),
            "b": b,
            "c": c,
            "k": _rng.integers(0, 5, n),
            "g": _rng.integers(0, 2000, n),
            "t": _rng.integers(0, 2, n).astype(bool),
        }
    ).to_csv(tmp_path / name, index=False)
    return str(tmp_path / name)


def _both_modes(pipeline):
    """(fused result, staged result) of one deferred-pipeline callable."""
    with FuseMode.context("Fused"):
        fused = pipeline().modin.to_pandas()
    with FuseMode.context("Staged"):
        staged = pipeline().modin.to_pandas()
    return fused, staged


# ---------------------------------------------------------------------- #
# 1. differential grid: fused vs staged vs pandas
# ---------------------------------------------------------------------- #


@pytest.mark.parametrize(
    "agg", ["sum", "mean", "min", "max", "count", "prod", "var", "std"]
)
@pytest.mark.parametrize("nan_frac", [0.0, 0.3])
def test_filter_reduce_grid(tmp_path, agg, nan_frac):
    path = _write_csv(tmp_path, 3000, nan_frac=nan_frac)

    def pipeline():
        return pd.read_csv(path).query("a > 0")[["b", "c"]].agg(agg)

    fused, staged = _both_modes(pipeline)
    reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg(agg)
    pandas.testing.assert_series_equal(fused, reference)
    pandas.testing.assert_series_equal(staged, reference)


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max"])
def test_mixed_dtype_reduce(tmp_path, agg):
    path = _write_csv(tmp_path, 2500)

    def pipeline():
        # int + float + bool columns through the same masked tail
        return pd.read_csv(path).query("a > 0")[["a", "b", "t"]].agg(agg)

    fused, staged = _both_modes(pipeline)
    reference = (
        pandas.read_csv(path).query("a > 0")[["a", "b", "t"]].agg(agg)
    )
    pandas.testing.assert_series_equal(fused, reference)
    pandas.testing.assert_series_equal(staged, reference)


def test_map_chain_into_reduce(tmp_path):
    path = _write_csv(tmp_path, 2000)

    def pipeline():
        md = pd.read_csv(path)
        kept = md[md["a"] > 0]
        return ((kept["b"] * 2 + kept["c"]) * kept["b"]).sum()

    with FuseMode.context("Fused"):
        fused = float(pipeline())
    with FuseMode.context("Staged"):
        staged = float(pipeline())
    pdf = pandas.read_csv(path)
    kept = pdf[pdf["a"] > 0]
    reference = float(((kept["b"] * 2 + kept["c"]) * kept["b"]).sum())
    assert fused == pytest.approx(reference, rel=1e-12)
    assert staged == pytest.approx(reference, rel=1e-12)


def test_stacked_filters(tmp_path):
    path = _write_csv(tmp_path, 2000)

    def pipeline():
        md = pd.read_csv(path)
        return md[md["a"] > 0][md[md["a"] > 0]["b"] > 0.5][["b", "c"]].agg("sum")

    def pipeline_pd():
        df = pandas.read_csv(path)
        return df[df["a"] > 0][df[df["a"] > 0]["b"] > 0.5][["b", "c"]].agg("sum")

    fused, staged = _both_modes(pipeline)
    pandas.testing.assert_series_equal(fused, pipeline_pd())
    pandas.testing.assert_series_equal(staged, pipeline_pd())


def test_filter_to_zero_rows_and_empty_frame(tmp_path):
    path = _write_csv(tmp_path, 1000)
    empty_path = _write_csv(tmp_path, 0, name="empty.csv")

    def zero_rows():
        return pd.read_csv(path).query("a > 99")[["b", "c"]].agg("sum")

    def empty():
        return pd.read_csv(empty_path)[["b", "c"]].agg("sum")

    for pipeline, pd_frame in ((zero_rows, path), (empty, empty_path)):
        fused, staged = _both_modes(pipeline)
        if pipeline is zero_rows:
            reference = (
                pandas.read_csv(pd_frame).query("a > 99")[["b", "c"]].agg("sum")
            )
        else:
            reference = pandas.read_csv(pd_frame)[["b", "c"]].agg("sum")
        pandas.testing.assert_series_equal(fused, reference)
        pandas.testing.assert_series_equal(staged, reference)


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "prod"])
@pytest.mark.parametrize("key", ["k", "g", "t"])  # low / high cardinality / bool
def test_filter_groupby_grid(tmp_path, agg, key):
    path = _write_csv(tmp_path, 3000, nan_frac=0.2)

    def pipeline():
        return pd.read_csv(path).query("a > 0").groupby(key).agg(agg)

    fused, staged = _both_modes(pipeline)
    reference = pandas.read_csv(path).query("a > 0").groupby(key).agg(agg)
    pandas.testing.assert_frame_equal(fused, reference)
    pandas.testing.assert_frame_equal(staged, reference)


def test_groupby_without_filter(tmp_path):
    path = _write_csv(tmp_path, 2000)

    def pipeline():
        return pd.read_csv(path)[["k", "b", "c"]].groupby("k").agg("mean")

    fused, staged = _both_modes(pipeline)
    reference = (
        pandas.read_csv(path)[["k", "b", "c"]].groupby("k").agg("mean")
    )
    pandas.testing.assert_frame_equal(fused, reference)
    pandas.testing.assert_frame_equal(staged, reference)


def test_groupby_wide_key_range_declines_to_staged(tmp_path, metric_counts):
    from modin_tpu.ops import groupby as gb

    n = 1500
    pandas.DataFrame(
        {
            "k": _rng.integers(0, 2**40, n),  # range >> FUSED_MAX_GROUPS
            "v": _rng.uniform(0, 1, n),
        }
    ).to_csv(tmp_path / "wide.csv", index=False)
    path = str(tmp_path / "wide.csv")
    assert 2**40 > gb.FUSED_MAX_GROUPS
    with FuseMode.context("Fused"):
        got = pd.read_csv(path).groupby("k").agg("sum").modin.to_pandas()
    reference = pandas.read_csv(path).groupby("k").agg("sum")
    pandas.testing.assert_frame_equal(got, reference)
    # the fused leg probed, found the range over the bucket cap, declined
    assert metric_counts.get("modin_tpu.fuse.decline", 0) >= 1


@pytest.mark.parametrize("n", [1023, 1024, 1025])
def test_ragged_bucket_boundaries(tmp_path, n):
    """Physical sizes straddling a bucket edge under FORCED quantization
    stay exact (the bucket only changes padding, never values)."""
    path = _write_csv(tmp_path, n, name=f"ragged{n}.csv")
    # force level-2 (pow2) buckets for every signature
    for _ in range(3 * fuse._STORM_COMPILES):
        fuse.note_fused_compiles("__test_all__", n, 1)

    real_level = fuse.storm_level

    def pipeline():
        return pd.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")

    try:
        fuse.storm_level = lambda sig: 2
        with FuseMode.context("Fused"):
            fused = pipeline().modin.to_pandas()
    finally:
        fuse.storm_level = real_level
    reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
    pandas.testing.assert_series_equal(fused, reference)


# ---------------------------------------------------------------------- #
# 2. donation
# ---------------------------------------------------------------------- #


def test_use_after_donate_restores_via_lineage(tmp_path, metric_counts):
    path = _write_csv(tmp_path, 2000)
    with FuseMode.context("Fused"):
        md = pd.read_csv(path)
        got = md.query("a > 0")[["b", "c"]].agg("sum").modin.to_pandas()
        assert metric_counts.get("modin_tpu.fuse.donated", 0) >= 1
        # the scan compiler's columns were consumed by the donated
        # dispatch: they read as spilled-with-host-copy (donated flag set)
        scan_qc = next(
            iter(md._query_compiler._plan.origin.cache.values())
        )[0]
        donated = [
            c
            for c in scan_qc._modin_frame._columns
            if getattr(c, "donated", False)
        ]
        assert donated, "no column was marked donated"
        for col in donated:
            assert col.is_spilled and col.host_cache is not None
        # device access FIRST (md still deferred, so the pruned donated
        # compiler serves): the column transparently re-seats via lineage
        # and the computation answers exactly, recorded as a donated
        # restore
        dev = float((md["b"] * 3).sum())
        assert dev == pytest.approx(
            float((pandas.read_csv(path)["b"] * 3).sum()), rel=1e-12
        )
        assert metric_counts.get("modin_tpu.fuse.donated_restore", 0) >= 1
        # host access: the full-width force re-reads what the pruned parse
        # never carried and serves donated columns from their host copies
        pandas.testing.assert_frame_equal(
            md.modin.to_pandas(), pandas.read_csv(path)
        )
    reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
    pandas.testing.assert_series_equal(got, reference)


def test_donated_dispatch_emits_no_user_warning(tmp_path):
    """Reduce tails output scalars, so no output aliases a donated input
    and jax would warn 'Some donated buffers were not usable' per compile;
    run_fused suppresses it for the donated dispatch only."""
    import warnings

    path = _write_csv(tmp_path, 2000, name="warn.csv")
    with FuseMode.context("Fused"):
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            pd.read_csv(path).query("a > 0")[["b", "c"]].agg(
                "sum"
            ).modin.to_pandas()
    assert not [
        w for w in caught if "donated buffers" in str(w.message)
    ], [str(w.message) for w in caught]


def test_shared_buffer_is_never_donated():
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn

    values = np.arange(4096, dtype=np.float64)
    col = DeviceColumn.from_numpy(values)
    assert col.donation_safe()
    twin = DeviceColumn(col.raw, col.pandas_dtype, length=col.length)
    # two live ledger entries hold the same buffer: neither may donate
    assert not col.donation_safe()
    assert not twin.donation_safe()
    del twin
    import gc

    gc.collect()
    assert col.donation_safe()


def test_donation_requires_host_copy():
    import jax.numpy as jnp

    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn
    from modin_tpu.ops.structural import pad_host

    data = pad_host(np.arange(100, dtype=np.float64))
    col = DeviceColumn(jnp.asarray(data), np.dtype(np.float64), length=100)
    assert col.host_cache is None
    assert not col.donation_safe()  # nothing to restore from


def test_fused_dispatch_in_query_stats(tmp_path):
    from modin_tpu.observability import meters

    path = _write_csv(tmp_path, 2000)
    with FuseMode.context("Fused"):
        md = pd.read_csv(path)
        with meters.query_stats("fuse-test") as stats:
            md.query("a > 0")[["b", "c"]].agg("sum").modin.to_pandas()
    assert stats.fused_dispatches == 1
    assert stats.donated_bytes > 0
    assert stats.dispatches == 1


# ---------------------------------------------------------------------- #
# 3. program-cache identity: mesh shape + device epoch in the key
# ---------------------------------------------------------------------- #


def test_mesh_flip_never_reuses_fused_program():
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn
    from modin_tpu.ops import lazy
    from modin_tpu.parallel.mesh import num_row_shards, reset_mesh

    if num_row_shards() < 2:
        pytest.skip("needs the multi-device virtual mesh")

    values = _rng.uniform(0, 1, 4096)

    def dispatch_once():
        col = DeviceColumn.from_numpy(values)
        expr = lazy.lazy_op("mul", col.raw, 2.0)
        before = set(lazy._FUSED_CACHE)
        out = lazy.run_fused([expr])[0]
        new = [k for k in lazy._FUSED_CACHE if k not in before]
        np.testing.assert_allclose(np.asarray(out), values * 2.0)
        return new

    first = dispatch_once()
    assert len(first) == 1
    try:
        MeshShape.put((4, 1))
        reset_mesh()
        second = dispatch_once()
        # the same forest under another topology is a NEW cache entry —
        # the executable traced for the 8-way layout is never reused
        assert len(second) == 1
        assert second[0] != first[0]
        assert second[0][2] != first[0][2]  # the (mesh, epoch) component
    finally:
        MeshShape.put((8, 1))
        reset_mesh()


def test_device_epoch_in_fused_key():
    from modin_tpu.core.execution import recovery
    from modin_tpu.ops import lazy

    key = lazy._cache_epoch_key()
    assert key[1] == recovery.current_epoch()


# ---------------------------------------------------------------------- #
# 4. routing + bucket units
# ---------------------------------------------------------------------- #


def test_decide_compile_modes(metric_counts):
    from modin_tpu.ops.router import decide_compile

    with FuseMode.context("Staged"):
        assert decide_compile("sig", 10**9) == "staged"
    with FuseMode.context("Fused"):
        assert decide_compile("sig", 1) == "fused"
    with FuseMode.context("Auto"):
        floor = int(FuseMinRows.get())
        assert decide_compile("sig", floor - 1) == "staged"
        assert decide_compile("sig", floor) == "fused"
    assert metric_counts.get("modin_tpu.router.fuse.fused", 0) >= 2
    assert metric_counts.get("modin_tpu.router.fuse.staged", 0) >= 2


def test_auto_keeps_tiny_frames_staged(tmp_path, metric_counts):
    path = _write_csv(tmp_path, 500)  # far below the 32768 default floor
    with FuseMode.context("Auto"):
        got = (
            pd.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
        ).modin.to_pandas()
    reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
    pandas.testing.assert_series_equal(got, reference)
    assert metric_counts.get("modin_tpu.fuse.dispatch", 0) == 0
    assert metric_counts.get("modin_tpu.router.fuse.staged", 0) >= 1


def test_storm_level_escalation():
    sig = ("test-sig",)
    assert fuse.storm_level(sig) == 0
    fuse.note_fused_compiles(sig, 2048, fuse._STORM_COMPILES)
    assert fuse.storm_level(sig) == 1
    fuse.note_fused_compiles(sig, 4096, 2 * fuse._STORM_COMPILES)
    assert fuse.storm_level(sig) == 2


def test_storm_registry_is_bounded():
    """Per-request literal operands mint fresh signatures (Map payloads
    embed scalar reprs); the registry must stay capped, LRU-evicted."""
    for i in range(fuse._MAX_STORM_SIGS + 100):
        fuse.note_fused_compiles(("sig", i), 2048, 1)
    assert len(fuse._sig_state) == fuse._MAX_STORM_SIGS
    # the oldest signatures were evicted, the newest survive
    assert ("sig", 0) not in fuse._sig_state
    assert ("sig", fuse._MAX_STORM_SIGS + 99) in fuse._sig_state


def test_cold_compiles_of_distinct_plans_never_storm():
    """Three unrelated plans cold-compiling once each bill the SAME
    'fuse.lower' ledger signature; that alone must not escalate a
    signature that has not itself re-compiled across sizes."""
    sig = ("healthy",)
    fuse.note_fused_compiles(sig, 2048, 1)
    fuse.note_fused_compiles(sig, 4096, 0)  # second size, cache hit
    # two shapes but only ONE own compile: no escalation regardless of
    # what the shared ledger entry looks like
    assert fuse.storm_level(sig) == 0


def test_quantize_padded_levels():
    # level 0: exact, always
    assert fuse.quantize_padded(5000, 0) == 5000
    # below the floor: exact at every level (unit-test frames untouched)
    assert fuse.quantize_padded(1000, 2) == 1000
    # level 1: eighth-octave steps (<= 12.5% waste)
    q1 = fuse.quantize_padded(5000, 1)
    assert q1 >= 5000 and (q1 - 5000) / 5000 <= 0.125
    assert q1 % (8192 // 8) == 0
    # level 2: pow2
    assert fuse.quantize_padded(5000, 2) == 8192
    assert fuse.quantize_padded(8192, 2) == 8192


def test_pad_bucket_scope_unit():
    from modin_tpu.ops.structural import pad_bucket_scope, pad_host, pad_len

    v = np.arange(3000, dtype=np.float64)
    assert len(pad_host(v)) == pad_len(3000)
    with pad_bucket_scope(lambda p: fuse.quantize_padded(p, 2)):
        assert len(pad_host(v)) == 4096
    assert len(pad_host(v)) == pad_len(3000)  # scope restored
    with pad_bucket_scope(None):  # no-op scope
        assert len(pad_host(v)) == pad_len(3000)


def test_quantizer_applies_to_scan_upload(tmp_path, metric_counts):
    """Under a stormed signature the scan's columns upload at the bucketed
    physical size (fuse.bucket.quantized fires); results stay exact."""
    n = 3000
    path = _write_csv(tmp_path, n, name="bucketed.csv")
    real_level = fuse.storm_level
    try:
        fuse.storm_level = lambda sig: 2
        with FuseMode.context("Fused"):
            got = (
                pd.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
            ).modin.to_pandas()
    finally:
        fuse.storm_level = real_level
    assert metric_counts.get("modin_tpu.fuse.bucket.quantized", 0) > 0
    reference = pandas.read_csv(path).query("a > 0")[["b", "c"]].agg("sum")
    pandas.testing.assert_series_equal(got, reference)


def test_segment_signature_stable_across_leaves(tmp_path):
    """Two queries with the same shape over different files share one
    signature (the storm counters aggregate by plan shape, not by file)."""
    p1 = _write_csv(tmp_path, 1200, name="s1.csv")
    p2 = _write_csv(tmp_path, 1700, name="s2.csv")

    def plan_of(path):
        md = pd.read_csv(path).query("a > 0")[["b", "c"]]
        from modin_tpu.plan import ir, rules

        root = ir.Reduce(md._query_compiler._plan, "sum", {})
        optimized, _ = rules.optimize(root)
        return fuse.segment_signature(optimized)

    with PlanMode.context("Auto"):
        assert plan_of(p1) == plan_of(p2)
