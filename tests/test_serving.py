"""graftgate serving layer: admission, deadlines, fairness, degradation.

Acceptance bar (ISSUE 9): serving disabled is bit-for-bit the single-query
behavior with zero allocations; serving enabled gives bounded concurrency
with typed load shedding, deadline enforcement with bounded overshoot
(backoff sleeps never outlive the budget), per-tenant throttling and
quarantine that never punish the healthy tenants, and degraded routing to
the host path when the device is sick — every outcome typed, nothing
hanging, completions bit-exact vs pandas.
"""

import threading
import time

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
import modin_tpu.serving as serving
from modin_tpu.config import (
    DeviceMemoryBudget,
    RecoveryMode,
    ResilienceBackoffS,
    ResilienceBreakerCooldownS,
    ResilienceBreakerThreshold,
    ResilienceMode,
    ResilienceRetries,
    ServingDefaultDeadlineMs,
    ServingDegradedHighWater,
    ServingEnabled,
    ServingMaxConcurrent,
    ServingQueueDepth,
    ServingTenantWeights,
)
from modin_tpu.core.execution import recovery, resilience
from modin_tpu.core.execution.resilience import get_breaker, reset_breakers
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.serving import context as serving_context
from modin_tpu.serving import tenants as serving_tenants
from modin_tpu.serving.gate import gate
from modin_tpu.testing import inject_faults

_PARAMS = (
    ServingEnabled,
    ServingMaxConcurrent,
    ServingQueueDepth,
    ServingDefaultDeadlineMs,
    ServingTenantWeights,
    ServingDegradedHighWater,
    ResilienceMode,
    ResilienceRetries,
    ResilienceBackoffS,
    ResilienceBreakerThreshold,
    ResilienceBreakerCooldownS,
    RecoveryMode,
)


@pytest.fixture(autouse=True)
def _lockdep_validated():
    """The serving suite runs under the runtime lock-order validator
    (the gate/tenants/breaker nesting is exactly what LOCK_ORDER
    declares); any recorded violation fails the test that caused it."""
    from modin_tpu.concurrency import lockdep

    lockdep.enable(strict=True)
    yield
    recorded = lockdep.violations()
    lockdep.disable()
    assert not recorded, "\n".join(v.render() for v in recorded)


@pytest.fixture(autouse=True)
def _clean_serving_state():
    """Fresh gate/tenants/breakers, zero backoff, restored knobs per test."""
    saved = [(p, p.get()) for p in _PARAMS]
    reset_breakers()
    gate.reset_for_tests()
    serving_tenants.registry.reset()
    ResilienceBackoffS.put(0.0)
    yield
    for p, v in saved:
        p.put(v)
    reset_breakers()
    gate.reset_for_tests()
    serving_tenants.registry.reset()


@pytest.fixture
def metrics():
    seen = []
    handler = lambda name, value: seen.append((name, value))  # noqa: E731
    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


def _names(seen):
    return [name for name, _value in seen]


@pytest.fixture
def small_df():
    rng = np.random.default_rng(3)
    data = {
        "a": rng.normal(size=512),
        "b": rng.integers(0, 50, 512).astype(np.int64),
        "key": rng.integers(0, 7, 512).astype(np.int64),
    }
    mdf = pd.DataFrame(data)
    mdf._query_compiler.execute()
    return mdf, pandas.DataFrame(data)


# ---------------------------------------------------------------------- #
# disabled mode: bit-for-bit passthrough, zero allocations
# ---------------------------------------------------------------------- #


def test_disabled_is_transparent_and_allocates_nothing(small_df):
    mdf, pdf = small_df
    assert not ServingEnabled.get()
    direct = mdf.groupby("key").sum().modin.to_pandas()
    alloc0 = serving.context_alloc_count()
    via_submit = serving.submit(
        lambda: mdf.groupby("key").sum().modin.to_pandas(),
        tenant="anyone",
        deadline_ms=5,  # ignored while off: no token is ever created
    )
    assert serving.context_alloc_count() == alloc0
    assert not serving_context.CONTEXT_ON
    pandas.testing.assert_frame_equal(via_submit, direct)
    pandas.testing.assert_frame_equal(via_submit, pdf.groupby("key").sum())
    # the gate itself was never touched
    assert gate.snapshot()["admitted"] == 0


def test_disabled_seam_checks_are_one_attribute_read():
    # the contract the seams rely on: no context => flag False => no calls
    assert serving_context.CONTEXT_ON is False
    assert serving_context.current_token() is None
    assert serving_context.degraded_active() is False


# ---------------------------------------------------------------------- #
# admission + backpressure
# ---------------------------------------------------------------------- #


def _submit_in_threads(jobs):
    """Run [(kwargs, fn)] each in its own thread; returns (results, errors)."""
    results = [None] * len(jobs)
    errors = [None] * len(jobs)

    def run(i, fn, kwargs):
        try:
            results[i] = serving.submit(fn, **kwargs)
        except Exception as err:  # noqa: BLE001 - tests assert on the type
            errors[i] = err

    threads = [
        threading.Thread(target=run, args=(i, fn, kwargs), daemon=True)
        for i, (kwargs, fn) in enumerate(jobs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "serving test hang"
    return results, errors


def test_concurrency_cap_and_bounded_queue():
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(2)
    ServingQueueDepth.put(2)
    release = threading.Event()
    started = threading.Barrier(3, timeout=30)  # 2 blockers + the test

    def blocker():
        started.wait()
        assert release.wait(timeout=30)
        return "done"

    holders = threading.Thread(
        target=lambda: _submit_in_threads(
            [({"tenant": "t"}, blocker), ({"tenant": "t"}, blocker)]
        ),
        daemon=True,
    )
    holders.start()
    started.wait()  # both slots genuinely occupied
    # wait until the waiter below is visibly queued
    waiter_results = []

    def queued_query():
        return "queued-done"

    waiter = threading.Thread(
        target=lambda: waiter_results.append(
            serving.submit(queued_query, tenant="t")
        ),
        daemon=True,
    )
    waiter.start()
    deadline = time.monotonic() + 10
    while gate.snapshot()["queued"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    snap = gate.snapshot()
    assert snap["running"] == 2
    assert snap["queued"] == 1
    release.set()
    waiter.join(timeout=30)
    holders.join(timeout=30)
    assert waiter_results == ["queued-done"]
    assert gate.snapshot()["running"] == 0


def test_queue_full_sheds_typed_with_retry_hint():
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(1)
    ServingQueueDepth.put(0)  # never queue: shed at saturation
    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        assert release.wait(timeout=30)
        return 1

    holder = threading.Thread(
        target=lambda: serving.submit(blocker, tenant="t"), daemon=True
    )
    holder.start()
    assert started.wait(timeout=30)
    with pytest.raises(serving.QueryRejected) as exc_info:
        serving.submit(lambda: 2, tenant="t")
    release.set()
    holder.join(timeout=30)
    assert exc_info.value.reason == "queue_full"
    assert exc_info.value.retry_after_s is not None
    assert exc_info.value.retry_after_s > 0
    assert gate.snapshot()["shed"] == 1


def test_shed_emits_serving_metrics(metrics):
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(1)
    ServingQueueDepth.put(0)
    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        assert release.wait(timeout=30)

    holder = threading.Thread(
        target=lambda: serving.submit(blocker, tenant="t"), daemon=True
    )
    holder.start()
    assert started.wait(timeout=30)
    with pytest.raises(serving.QueryRejected):
        serving.submit(lambda: None, tenant="t")
    release.set()
    holder.join(timeout=30)
    names = _names(metrics)
    assert "modin_tpu.serving.shed" in names
    assert "modin_tpu.serving.tenant.t.queue_full" in names
    assert "modin_tpu.serving.admit" in names


# ---------------------------------------------------------------------- #
# deadlines + cancellation
# ---------------------------------------------------------------------- #


def test_deadline_expires_in_queue_typed():
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(1)
    ServingQueueDepth.put(4)
    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        assert release.wait(timeout=30)

    holder = threading.Thread(
        target=lambda: serving.submit(blocker, tenant="t"), daemon=True
    )
    holder.start()
    assert started.wait(timeout=30)
    t0 = time.perf_counter()
    with pytest.raises(serving.DeadlineExceeded) as exc_info:
        serving.submit(lambda: None, tenant="t", deadline_ms=150)
    queued_wall = time.perf_counter() - t0
    release.set()
    holder.join(timeout=30)
    assert exc_info.value.where == "serving.queue"
    assert queued_wall < 5.0  # aborted typed, not held until the slot opened


def test_backoff_sleeps_never_outlive_the_budget(small_df, metrics):
    """A 200ms-budget query under persistent transient faults with a 5s
    base backoff must abort typed in well under one backoff period."""
    mdf, _pdf = small_df
    ServingEnabled.put(True)
    ResilienceBackoffS.put(5.0)
    ResilienceRetries.put(3)
    RecoveryMode.put("Disable")

    def query():
        return mdf.sum().modin.to_pandas()

    with inject_faults("transient", ops=("deploy",), times=None):
        t0 = time.perf_counter()
        with pytest.raises(serving.DeadlineExceeded):
            serving.submit(query, tenant="t", deadline_ms=200)
        wall = time.perf_counter() - t0
    assert wall < 2.5, (
        f"{wall:.2f}s: the 5s backoff outlived the 200ms budget"
    )
    assert "modin_tpu.serving.deadline_exceeded" in _names(metrics)


def test_deadline_overshoot_bounded_by_one_attempt(small_df):
    mdf, _pdf = small_df
    ServingEnabled.put(True)
    with inject_faults("slow_kernel", ops=("deploy",), times=None, slow_s=0.06):
        t0 = time.perf_counter()
        with pytest.raises(serving.DeadlineExceeded) as exc_info:
            serving.submit(
                lambda: mdf.sum().modin.to_pandas(), tenant="t", deadline_ms=30
            )
        wall = time.perf_counter() - t0
    # contract: overshoot <= max(2 x D, one engine attempt); generous slack
    # for CI scheduling noise, but far below "ran to completion anyway"
    assert wall < 1.5, f"overshoot {wall:.3f}s"
    assert exc_info.value.deadline_s == pytest.approx(0.03)


def test_default_deadline_knob_applies():
    ServingEnabled.put(True)
    ServingDefaultDeadlineMs.put(40.0)
    with pytest.raises(serving.DeadlineExceeded):
        # deadline_ms omitted -> knob applies; the query outsleeps it and
        # the explicit seam check observes expiry
        serving.submit(
            lambda: (time.sleep(0.1), serving_context.check_deadline("test"))
        )
    # explicit deadline_ms=0 overrides the knob back to unbounded
    assert serving.submit(lambda: "ok", deadline_ms=0) == "ok"


def test_manual_cancellation_token():
    token = serving_context.CancellationToken(None, "manual")
    assert token.remaining_s() is None
    token.cancel()
    assert token.expired()
    with pytest.raises(serving.DeadlineExceeded):
        token.check("unit")


# ---------------------------------------------------------------------- #
# per-tenant fairness + health
# ---------------------------------------------------------------------- #


def test_tenant_token_bucket_throttles_only_the_hammering_tenant():
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(2)
    clock = [1000.0]
    real_now = serving_tenants._now
    serving_tenants._now = lambda: clock[0]
    try:
        # bucket capacity = weight * max_concurrent * burst = 8 tokens
        # under a frozen clock: the burst admits, then throttling engages
        for _ in range(8):
            assert serving.submit(lambda: 1, tenant="hammer") == 1
        with pytest.raises(serving.QueryRejected) as exc_info:
            serving.submit(lambda: 1, tenant="hammer")
        assert exc_info.value.reason == "throttled"
        assert exc_info.value.retry_after_s > 0
        # the polite tenant is untouched
        assert serving.submit(lambda: 2, tenant="polite") == 2
        # refill: advance the clock past the hint and the tenant flows again
        clock[0] += 1.0
        assert serving.submit(lambda: 3, tenant="hammer") == 3
    finally:
        serving_tenants._now = real_now


def test_tenant_weights_parse_and_size_buckets():
    assert serving_tenants.parse_weights("a=3,b=1.5, c = 2") == {
        "a": 3.0,
        "b": 1.5,
        "c": 2.0,
    }
    assert serving_tenants.parse_weights("junk,=,x=nan2,ok=1")["ok"] == 1.0
    assert "junk" not in serving_tenants.parse_weights("junk")
    # non-positive weights clamp instead of dividing by zero later
    assert serving_tenants.parse_weights("z=0")["z"] > 0
    ServingTenantWeights.put("fat=4")
    ServingMaxConcurrent.put(2)
    state = serving_tenants.registry.get("fat")
    assert state.refill_per_s == 8.0
    assert state.capacity == 8.0 * serving_tenants._BURST


def test_unhealthy_tenant_quarantined_not_the_system(metrics):
    ServingEnabled.put(True)
    ResilienceBreakerThreshold.put(2)
    ResilienceBreakerCooldownS.put(60.0)

    def striking_query():
        # a query whose device paths keep striking breakers (completes
        # correct via fallback — health is orthogonal to correctness)
        emit_metric("resilience.breaker.binary.strike", 1)
        return "answer"

    # consecutive trip-y queries strike the tenant breaker to its threshold
    for _ in range(2):
        assert serving.submit(striking_query, tenant="sick") == "answer"
    assert get_breaker("tenant_sick").state == "open"
    with pytest.raises(serving.QueryRejected) as exc_info:
        serving.submit(lambda: 1, tenant="sick")
    assert exc_info.value.reason == "unhealthy"
    assert exc_info.value.retry_after_s == pytest.approx(60.0)
    # every other tenant flows
    assert serving.submit(lambda: 2, tenant="fine") == 2
    assert get_breaker("tenant_fine").state == "closed"
    assert "modin_tpu.serving.tenant.sick.unhealthy" in _names(metrics)


def test_weighted_fair_wake_order_under_saturation():
    """With the gate saturated by tenant L, a queued heavy-weight tenant
    wakes before L's own next query even though it arrived later."""
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(2)
    ServingQueueDepth.put(4)
    ServingTenantWeights.put("heavy=8,light=1")
    releases = [threading.Event(), threading.Event()]
    started = threading.Barrier(3, timeout=30)
    order = []
    order_lock = threading.Lock()

    def blocker(i):
        def fn():
            started.wait()
            assert releases[i].wait(timeout=30)

        return fn

    def tagged(tag):
        def fn():
            with order_lock:
                order.append(tag)

        return fn

    holders = [
        threading.Thread(
            target=lambda i=i: serving.submit(blocker(i), tenant="light"),
            daemon=True,
        )
        for i in range(2)
    ]
    for h in holders:
        h.start()
    started.wait()  # both slots held by tenant light
    light_waiter = threading.Thread(
        target=lambda: serving.submit(tagged("light"), tenant="light"),
        daemon=True,
    )
    light_waiter.start()
    deadline = time.monotonic() + 10
    while gate.snapshot()["queued"] < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    heavy_waiter = threading.Thread(
        target=lambda: serving.submit(tagged("heavy"), tenant="heavy"),
        daemon=True,
    )
    heavy_waiter.start()
    while gate.snapshot()["queued"] < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    # free ONE slot: light still holds the other, so the weighted-fair
    # head is heavy (0 in flight / weight 8) over light (1 in flight / 1)
    # even though light's waiter queued first
    releases[0].set()
    heavy_waiter.join(timeout=30)
    releases[1].set()
    light_waiter.join(timeout=30)
    for h in holders:
        h.join(timeout=30)
    assert order[0] == "heavy", order


def test_runtime_weight_changes_retune_existing_tenants():
    """Review regression: raising a tenant's weight (or MAX_CONCURRENT) at
    runtime must apply to already-seen tenants, not freeze first-touch
    values forever."""
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(2)
    state = serving_tenants.registry.get("alice")
    assert state.refill_per_s == 2.0  # weight 1 * mc 2
    ServingTenantWeights.put("alice=8")
    assert serving_tenants.registry.get("alice").refill_per_s == 16.0
    ServingMaxConcurrent.put(4)
    assert serving_tenants.registry.get("alice").refill_per_s == 32.0
    # a retune clamps tokens to the new capacity, never tops them up
    ServingTenantWeights.put("alice=0.1")
    retuned = serving_tenants.registry.get("alice")
    assert retuned.tokens <= retuned.capacity


def test_tenant_registry_bounded_with_breaker_cleanup(monkeypatch):
    """Review regression: per-user tenant ids must not grow the tenant
    registry (or the breaker registry) without bound; idle closed-breaker
    tenants evict LRU-first, active/quarantined tenants survive."""
    monkeypatch.setattr(serving_tenants, "_MAX_TENANTS", 6)
    ServingEnabled.put(True)
    ResilienceBreakerThreshold.put(1)
    # one quarantined tenant: must survive eviction pressure
    serving_tenants.registry.get("sick")
    serving_tenants.breaker_for("sick").record_failure()
    assert get_breaker("tenant_sick").state == "open"
    for i in range(20):
        assert serving.submit(lambda: i, tenant=f"user{i}") is not None
    registry_names = set(serving_tenants.registry.snapshot())
    assert len(registry_names) <= 6 + 1  # cap (+ the protected sick tenant)
    assert "sick" in registry_names
    # evicted tenants' breakers are gone from the breaker registry too
    from modin_tpu.core.execution.resilience import breaker_snapshot

    tenant_breakers = {
        n for n in breaker_snapshot() if n.startswith("tenant_user")
    }
    assert len(tenant_breakers) <= 6
    assert get_breaker("tenant_sick").state == "open"


def test_cost_ewma_feeds_admission_estimates():
    ServingEnabled.put(True)
    serving_tenants.registry.observe("known", 1_000_000.0, 0.5)
    assert serving_tenants.registry.cost_estimate("known", 123.0) == pytest.approx(
        1_000_000.0
    )
    # unknown tenants get the conservative default, never zero
    assert serving_tenants.registry.cost_estimate("new", 123.0) == 123.0
    # EWMA moves, does not jump
    serving_tenants.registry.observe("known", 0.0, 0.1)  # zero-cost ignored
    assert serving_tenants.registry.cost_estimate("known", 0.0) == pytest.approx(
        1_000_000.0
    )
    serving_tenants.registry.observe("known", 2_000_000.0, 0.5)
    est = serving_tenants.registry.cost_estimate("known", 0.0)
    assert 1_000_000.0 < est < 2_000_000.0


def test_byte_headroom_gates_admission_under_budget():
    """With a device budget set, a tenant whose EWMA says 'huge' cannot be
    co-admitted with another runner — but always runs ALONE (admit-one)."""
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(4)
    ServingQueueDepth.put(0)
    budget = 1 << 20
    serving_tenants.registry.observe("whale", float(budget), 0.1)
    with DeviceMemoryBudget.context(budget):
        release = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            assert release.wait(timeout=30)
            return "w1"

        holder = threading.Thread(
            target=lambda: serving.submit(blocker, tenant="whale"),
            daemon=True,
        )
        holder.start()
        assert started.wait(timeout=30)
        # second whale query: slots are free (4), but reserved bytes are
        # the whole budget -> queue_full shed at depth 0
        with pytest.raises(serving.QueryRejected):
            serving.submit(lambda: "w2", tenant="whale")
        release.set()
        holder.join(timeout=30)
        # alone, the whale is admitted even though its estimate fills the
        # budget (the deploy-seam spill machinery owns the rest)
        assert serving.submit(lambda: "w3", tenant="whale") == "w3"


# ---------------------------------------------------------------------- #
# degraded mode
# ---------------------------------------------------------------------- #


def test_degraded_routes_to_host_on_open_breaker(small_df, metrics):
    mdf, pdf = small_df
    ServingEnabled.put(True)
    breaker = get_breaker("binary")
    ResilienceBreakerThreshold.put(1)
    breaker.record_failure()
    assert breaker.state == "open"
    got = serving.submit(
        lambda: mdf.groupby("key").sum().modin.to_pandas(), tenant="t"
    )
    pandas.testing.assert_frame_equal(got, pdf.groupby("key").sum())
    names = _names(metrics)
    assert "modin_tpu.serving.degraded" in names
    assert "modin_tpu.serving.degraded.fallback" in names
    assert gate.snapshot()["degraded"] == 1


def test_degraded_routes_on_ledger_high_water(small_df, metrics):
    mdf, pdf = small_df
    from modin_tpu.core.memory import device_resident_bytes

    resident = device_resident_bytes()
    assert resident > 0  # the ingested frame is resident
    ServingEnabled.put(True)
    ServingDegradedHighWater.put(0.5)
    # budget such that resident is already past half of it
    with DeviceMemoryBudget.context(int(resident * 1.5)):
        got = serving.submit(lambda: float(mdf["a"].sum()), tenant="t")
    assert got == pytest.approx(float(pdf["a"].sum()))
    assert "modin_tpu.serving.degraded" in _names(metrics)


def test_not_degraded_when_healthy(small_df, metrics):
    mdf, _pdf = small_df
    ServingEnabled.put(True)
    serving.submit(lambda: float(mdf["a"].sum()), tenant="t")
    assert "modin_tpu.serving.degraded" not in _names(metrics)


# ---------------------------------------------------------------------- #
# introspection + plumbing
# ---------------------------------------------------------------------- #


def test_snapshot_shape_and_tenant_rollup():
    ServingEnabled.put(True)
    serving.submit(lambda: 1, tenant="alice")
    snap = serving.serving_snapshot()
    for key in ("running", "queued", "admitted", "shed", "degraded", "tenants"):
        assert key in snap
    alice = snap["tenants"]["alice"]
    assert alice["admitted"] == 1
    assert alice["breaker"] == "closed"
    assert alice["wall_ewma_s"] is not None


def test_context_seeding_replaces_stale_context():
    token = serving_context.CancellationToken(10.0, "q1")
    ctx = serving_context.QueryContext(token, degraded=True, tenant="a")
    seen = {}

    def worker():
        serving_context.seed_thread_context(ctx)
        seen["first"] = serving_context.degraded_active()
        # pooled-worker reuse: re-seeding with None must CLEAR, not keep
        serving_context.seed_thread_context(None)
        seen["second"] = serving_context.degraded_active()
        seen["token"] = serving_context.current_token()

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=10)
    assert seen == {"first": True, "second": False, "token": None}


def test_nested_submit_composes():
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(2)

    def outer():
        assert serving_context.CONTEXT_ON
        return serving.submit(lambda: "inner", tenant="t2")

    assert serving.submit(outer, tenant="t1") == "inner"
    assert not serving_context.CONTEXT_ON
    assert gate.snapshot()["running"] == 0


def test_nested_submit_at_saturation_does_not_deadlock():
    """Review regression: with ONE slot, an admitted query submitting
    another query must run it under its own permit, not queue behind the
    slot it holds (that was a permanent hang with no deadline set)."""
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(1)
    ServingQueueDepth.put(0)
    done = []

    def outer():
        inner = serving.submit(lambda: "inner-ran", tenant="t")
        done.append(inner)
        return "outer-ran"

    t = threading.Thread(
        target=lambda: done.append(serving.submit(outer, tenant="t")),
        daemon=True,
    )
    t.start()
    t.join(timeout=20)
    assert not t.is_alive(), "nested submit deadlocked at saturation"
    assert done == ["inner-ran", "outer-ran"]
    snap = gate.snapshot()
    assert snap["running"] == 0
    assert snap["admitted"] == 1  # one slot consumed, inner rode the permit
    # the inner deadline still applies on the nested frame
    with pytest.raises(serving.DeadlineExceeded):
        serving.submit(
            lambda: serving.submit(
                lambda: (
                    time.sleep(0.05),
                    serving_context.check_deadline("nested"),
                ),
                tenant="t",
                deadline_ms=10,
            ),
            tenant="t",
        )


def test_queue_full_shed_refunds_the_rate_token():
    """Review regression: a queue_full shed is a CAPACITY verdict — it must
    refund the tenant's rate token, or a polite retrying client drains its
    bucket into a bogus 'throttled' quarantine."""
    ServingEnabled.put(True)
    ServingMaxConcurrent.put(1)
    ServingQueueDepth.put(0)
    clock = [500.0]
    real_now = serving_tenants._now
    serving_tenants._now = lambda: clock[0]  # frozen: no refill
    try:
        release = threading.Event()
        started = threading.Event()

        def blocker():
            started.set()
            assert release.wait(timeout=30)

        holder = threading.Thread(
            target=lambda: serving.submit(blocker, tenant="t"), daemon=True
        )
        holder.start()
        assert started.wait(timeout=30)
        # capacity = 1 * 1 * burst(4) tokens; the blocker spent one.  Far
        # more queue_full sheds than remaining tokens must ALL come back
        # as queue_full, never flip to throttled
        for _ in range(10):
            with pytest.raises(serving.QueryRejected) as exc_info:
                serving.submit(lambda: None, tenant="t")
            assert exc_info.value.reason == "queue_full"
        release.set()
        holder.join(timeout=30)
    finally:
        serving_tenants._now = real_now


def test_package_gate_attribute_is_always_the_module():
    """Review regression: serving.gate's type must not depend on access
    order (submodule import binds the module to the package attribute)."""
    import types

    import modin_tpu.serving as serving_pkg
    from modin_tpu.serving.gate import AdmissionGate

    assert isinstance(serving_pkg.gate, types.ModuleType)
    assert isinstance(serving_pkg.gate.gate, AdmissionGate)
    assert isinstance(serving_pkg.tenants, types.ModuleType)


def test_nested_tenant_strike_does_not_cascade_to_outer(metrics):
    """Review regression: the tenant-health breaker strike a nested submit
    records (resilience.breaker.tenant_*.strike, emitted while the outer
    scope is open) is a serving verdict, not device sickness — it must not
    count as the OUTER query's breaker_trips."""
    ServingEnabled.put(True)
    ResilienceBreakerThreshold.put(1)

    def outer():
        # simulate exactly what _finish_accounting emits for a sick inner
        # tenant, on this thread, inside the outer query's open scope
        emit_metric("resilience.breaker.tenant_inner.strike", 1)
        return "ok"

    assert serving.submit(outer, tenant="outer_tenant") == "ok"
    assert get_breaker("tenant_outer_tenant").state == "closed", (
        "a nested tenant's health strike cascaded into the outer tenant"
    )


def test_untyped_query_errors_propagate_and_release():
    ServingEnabled.put(True)

    class UserBug(ValueError):
        pass

    def bad():
        raise UserBug("semantic error, not the serving layer's business")

    with pytest.raises(UserBug):
        serving.submit(bad, tenant="t")
    snap = gate.snapshot()
    assert snap["running"] == 0
    assert snap["completed"] == 1
    # a semantic error is not a health strike
    assert get_breaker("tenant_t").state == "closed"


def test_device_failure_strikes_tenant_health(small_df):
    mdf, _pdf = small_df
    ServingEnabled.put(True)
    ResilienceMode.put("Disable")  # raw failures propagate (no fallback)
    ResilienceBreakerThreshold.put(1)
    with inject_faults("device_lost", ops=("deploy",), times=None):
        with pytest.raises(Exception):
            serving.submit(
                lambda: mdf.sum().modin.to_pandas(), tenant="crasher"
            )
    assert get_breaker("tenant_crasher").state == "open"
