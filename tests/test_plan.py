"""graftplan tests: IR, rewrite rules, deferred execution, and parity.

Three layers of coverage:

1. **IR mechanics** — node schema answers, DAG sharing through transform,
   structural keys (the CSE merge criterion).
2. **Rewrite rules** — each rule's positive and negative cases as pure
   ``Plan -> Plan | None`` functions, plus the fixpoint engine's pass budget.
3. **End-to-end parity** — deferred pipelines over a real CSV must be
   bit-exact against ``MODIN_TPU_PLAN=Off`` (eager) and plain pandas, across
   materialization points (repr, index, to_pandas, unplanned ops), pushdown
   gates, Force-mode Source re-planning, and the EXPLAIN surface.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import PlanMaxPasses, PlanMode
from modin_tpu.plan import ir, rules
from modin_tpu.plan import runtime as plan_runtime
from tests.utils import df_equals


@pytest.fixture(autouse=True)
def _require_tpu_backend():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("graftplan rides the TpuOnJax query compiler")


_rng = np.random.default_rng(11)


@pytest.fixture
def csv_path(tmp_path):
    n = 4000
    pandas.DataFrame(
        {
            "a": _rng.integers(-10, 10, n),
            "b": _rng.uniform(0, 1, n),
            "c": _rng.uniform(-1, 1, n),
            "d": _rng.integers(0, 7, n),
            "e": _rng.uniform(0, 100, n),
        }
    ).to_csv(tmp_path / "plan.csv", index=False)
    return str(tmp_path / "plan.csv")


def _scan(columns=("a", "b", "c")):
    from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher

    return ir.Scan(
        TpuCSVDispatcher, {"filepath_or_buffer": "x.csv"}, pandas.Index(columns)
    )


# ---------------------------------------------------------------------- #
# IR mechanics
# ---------------------------------------------------------------------- #


def test_ir_schema_and_row_keys():
    scan = _scan()
    proj = ir.Project(scan, ("a",), out_hint="column")
    mask = ir.Map((proj,), "gt", (0,), out_columns=proj.columns, bool_out=True)
    filt = ir.Filter(scan, mask)
    assert list(filt.columns) == ["a", "b", "c"]
    assert list(mask.columns) == ["a"]
    assert mask.known_dtypes().iloc[0] == bool
    assert scan.known_dtypes() is None  # only a full parse could know
    # projects/maps preserve row lineage; filters/sorts break it
    assert proj.row_key() == scan.row_key() == mask.row_key()
    assert filt.row_key() != scan.row_key()
    assert ir.Sort(scan, "a", True, {}).row_key() != scan.row_key()


def test_transform_preserves_diamond_sharing():
    scan = _scan()
    left = ir.Project(scan, ("a",))
    right = ir.Project(scan, ("b",))
    mask = ir.Map((right,), "gt", (0,), out_columns=right.columns, bool_out=True)
    root = ir.Filter(left, mask)
    rebuilt, changes = ir.transform(
        root, lambda n: None
    )
    assert changes == 0 and rebuilt is root
    # a rewrite that touches the shared scan rewrites it ONCE
    new_scan = _scan(("a", "b", "c"))

    def swap(node):
        return new_scan if isinstance(node, ir.Scan) else None

    rebuilt, changes = ir.transform(root, swap)
    assert changes == 1
    assert rebuilt.children[0].children[0] is rebuilt.children[1].children[0].children[0]


def test_structural_key_identity_vs_structure():
    scan = _scan()
    p1 = ir.Project(scan, ("a",))
    p2 = ir.Project(scan, ("a",))
    p3 = ir.Project(scan, ("b",))
    memo = {}
    assert ir.structural_key(p1, memo) == ir.structural_key(p2, memo)
    assert ir.structural_key(p1, memo) != ir.structural_key(p3, memo)
    # different leaves never merge
    other = ir.Project(_scan(), ("a",))
    assert ir.structural_key(other, memo) != ir.structural_key(p1, memo)


# ---------------------------------------------------------------------- #
# Rewrite rules
# ---------------------------------------------------------------------- #


def test_rule_push_filter_below_project_and_map():
    scan = _scan()
    mask = ir.Map(
        (ir.Project(scan, ("a",)),), "gt", (0,),
        out_columns=pandas.Index(["a"]), bool_out=True,
    )
    root = ir.Filter(ir.Project(scan, ("b", "c")), mask)
    new_root = rules.push_filter_down(root)
    assert isinstance(new_root, ir.Project)
    assert isinstance(new_root.children[0], ir.Filter)
    assert new_root.children[0].children[0] is scan
    # single-input maps commute too
    mroot = ir.Filter(
        ir.Map((scan,), "abs", out_columns=scan.columns), mask
    )
    new_mroot = rules.push_filter_down(mroot)
    assert isinstance(new_mroot, ir.Map)
    assert isinstance(new_mroot.children[0], ir.Filter)
    # a filter already on the scan is a no-op
    assert rules.push_filter_down(ir.Filter(scan, mask)) is None


def test_rule_cse_merges_identical_subtrees():
    scan = _scan()
    m1 = ir.Map(
        (ir.Project(scan, ("a",)),), "gt", (0,),
        out_columns=pandas.Index(["a"]), bool_out=True,
    )
    m2 = ir.Map(
        (ir.Project(scan, ("a",)),), "gt", (0,),
        out_columns=pandas.Index(["a"]), bool_out=True,
    )
    root = ir.Map((m1, m2), "__and__", (ir.Ref(1),), out_columns=m1.columns)
    new_root = rules.common_subexpression_elimination(root)
    assert new_root is not None
    assert new_root.children[0] is new_root.children[1]
    # different payloads never merge
    m3 = ir.Map(
        (ir.Project(scan, ("a",)),), "gt", (1,),
        out_columns=pandas.Index(["a"]), bool_out=True,
    )
    root2 = ir.Map((m1, m3), "__and__", (ir.Ref(1),), out_columns=m1.columns)
    merged = rules.common_subexpression_elimination(root2)
    if merged is not None:  # the two projects still merge
        assert merged.children[0] is not merged.children[1]


def test_rule_prune_columns_unions_all_consumers():
    scan = _scan(("a", "b", "c", "d", "e"))
    mask = ir.Map(
        (ir.Project(scan, ("a",)),), "gt", (0,),
        out_columns=pandas.Index(["a"]), bool_out=True,
    )
    root = ir.Reduce(
        ir.Project(ir.Filter(scan, mask), ("b", "c")), "sum", {}
    )
    new_root = rules.prune_dead_columns(root)
    assert new_root is not None
    pruned_scan = new_root.children[0].children[0].children[0]
    assert set(pruned_scan.pruned) == {"a", "b", "c"}
    # the mask branch shares the SAME pruned scan node
    assert new_root.children[0].children[0].children[1].children[0].children[0] is pruned_scan
    # a plan whose root is the scan itself requires everything: no pruning
    assert rules.prune_dead_columns(scan) is None


def test_rule_pushdown_gate_blocks_unsafe_kwargs():
    from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher

    safe = ir.Scan(
        TpuCSVDispatcher, {"filepath_or_buffer": "x.csv"},
        pandas.Index(["a", "b"]), pruned=("a",),
    )
    assert plan_runtime.scan_supports_pushdown(safe)
    for blocker in (
        {"index_col": "a"},
        {"converters": {"a": int}},
        {"parse_dates": ["a"]},
        {"usecols": lambda c: True},
        {"names": ["x", "y"]},
        {"skipfooter": 2},
    ):
        scan = ir.Scan(
            TpuCSVDispatcher, {"filepath_or_buffer": "x.csv", **blocker},
            pandas.Index(["a", "b"]), pruned=("a",),
        )
        assert not plan_runtime.scan_supports_pushdown(scan), blocker


def test_rule_fuse_map_reduce_counts_chain():
    scan = _scan()
    m1 = ir.Map((scan,), "add", (1,), out_columns=scan.columns)
    m2 = ir.Map((m1,), "mul", (2,), out_columns=scan.columns)
    root = ir.Reduce(m2, "sum", {})
    fused = rules.fuse_map_reduce(root)
    assert fused is not None and fused.fused and fused.fused_maps == 2
    assert rules.fuse_map_reduce(fused) is None  # idempotent
    assert rules.fuse_map_reduce(ir.Reduce(scan, "sum", {})) is None


def test_optimize_respects_pass_budget():
    calls = []

    def hungry_rule(root):
        calls.append(1)
        # always "improves": without the budget this would never stop
        return ir.Project(root, tuple(root.columns))

    scan = _scan()
    original_rules = rules.RULES
    rules.RULES = (("hungry", hungry_rule),)
    try:
        optimized, applied = rules.optimize(scan, max_passes=3)
        assert len(applied) == 3
        assert len(calls) == 3
    finally:
        rules.RULES = original_rules
    with PlanMaxPasses.context(2):
        root, applied = rules.optimize(scan)
        assert applied == []  # real catalog: scan-only plan is a fixpoint


# ---------------------------------------------------------------------- #
# End-to-end: deferral, parity, materialization points
# ---------------------------------------------------------------------- #


def _pandas_frame(csv_path):
    return pandas.read_csv(csv_path)


def test_read_defers_and_metadata_stays_cheap(csv_path):
    md = pd.read_csv(csv_path)
    qc = md._query_compiler
    assert qc._plan is not None
    # columns come from the header sniff without materializing
    assert list(md.columns) == ["a", "b", "c", "d", "e"]
    assert qc._plan is not None, "columns access must not force the plan"
    # row count is NOT derivable from the plan: it forces
    assert len(md) == len(_pandas_frame(csv_path))
    assert qc._plan is None


def test_acceptance_pipeline_bit_exact(csv_path):
    planned = pd.read_csv(csv_path).query("a > 0")[["b", "c"]].agg("sum")
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path).query("a > 0")[["b", "c"]].agg("sum")
    reference = _pandas_frame(csv_path).query("a > 0")[["b", "c"]].agg("sum")
    pandas.testing.assert_series_equal(planned.modin.to_pandas(), reference)
    pandas.testing.assert_series_equal(eager.modin.to_pandas(), reference)


@pytest.mark.parametrize(
    "pipeline",
    [
        lambda df: df[["b", "e"]],
        lambda df: df.query("a > 2 and e < 50.0"),
        lambda df: df.query("a > 0")[["b"]].mean(),
        lambda df: (df[["b", "c"]] * 2.0).sum(),
        lambda df: df[df["a"] > 0][["c"]].abs().sum(),
        lambda df: df.query("a > 0").sort_values("e")[["b", "c"]],
        lambda df: df[["a", "b"]].count(),
        lambda df: df.query("d in [1, 2, 3]")[["e"]].max(),
    ],
    ids=["project", "filter", "filter-project-mean", "map-sum", "mask-abs-sum",
         "filter-sort-project", "count", "isin-max"],
)
def test_deferred_pipelines_match_eager_and_pandas(csv_path, pipeline):
    planned = pipeline(pd.read_csv(csv_path))
    with PlanMode.context("Off"):
        eager = pipeline(pd.read_csv(csv_path))
    reference = pipeline(_pandas_frame(csv_path))
    df_equals(planned, reference)
    df_equals(eager, reference)


def test_materialization_points_force(csv_path):
    pdf = _pandas_frame(csv_path)
    # repr
    md = pd.read_csv(csv_path)
    repr(md)
    assert md._query_compiler._plan is None
    # index access
    md = pd.read_csv(csv_path)
    assert list(md.index[:3]) == [0, 1, 2]
    assert md._query_compiler._plan is None
    # an op with no plan node (head -> row_slice)
    md = pd.read_csv(csv_path)
    df_equals(md.head(7), pdf.head(7))
    # scan dtypes are unknowable without a parse: .dtypes forces
    md = pd.read_csv(csv_path)
    assert md._query_compiler._plan is not None
    pandas.testing.assert_series_equal(md.dtypes, pdf.dtypes)
    assert md._query_compiler._plan is None


def test_mask_dtype_answered_without_forcing(csv_path):
    md = pd.read_csv(csv_path)
    mask = md["a"] > 0
    assert mask.dtype == np.dtype(bool)
    assert mask._query_compiler._plan is not None, (
        "a comparison's dtype is exactly known; it must not force"
    )
    filtered = md[mask]
    assert filtered._query_compiler._plan is not None
    df_equals(filtered, _pandas_frame(csv_path)[_pandas_frame(csv_path)["a"] > 0])


def test_compound_mask_stays_deferred(csv_path):
    md = pd.read_csv(csv_path)
    out = md[(md["a"] > 0) & (md["e"] < 75.0)][["b", "d"]]
    assert out._query_compiler._plan is not None
    pdf = _pandas_frame(csv_path)
    df_equals(out, pdf[(pdf["a"] > 0) & (pdf["e"] < 75.0)][["b", "d"]])


def test_groupby_agg_through_plan(csv_path):
    md = pd.read_csv(csv_path)[["d", "b"]]
    assert md._query_compiler._plan is not None
    out = md.groupby("d").sum()
    df_equals(out, _pandas_frame(csv_path)[["d", "b"]].groupby("d").sum())


def test_pushdown_composes_with_user_usecols(csv_path):
    planned = pd.read_csv(csv_path, usecols=["a", "b", "c"]).query("a > 0")[
        ["b"]
    ].sum()
    reference = pandas.read_csv(csv_path, usecols=["a", "b", "c"]).query(
        "a > 0"
    )[["b"]].sum()
    pandas.testing.assert_series_equal(planned.modin.to_pandas(), reference)


def test_unsafe_kwargs_skip_pushdown_but_stay_correct(csv_path):
    # index_col blocks reader-level pruning; the pipeline must still be exact
    planned = pd.read_csv(csv_path, index_col="d")[["b", "c"]].sum()
    reference = pandas.read_csv(csv_path, index_col="d")[["b", "c"]].sum()
    pandas.testing.assert_series_equal(planned.modin.to_pandas(), reference)


def test_off_mode_never_defers(csv_path):
    with PlanMode.context("Off"):
        md = pd.read_csv(csv_path)
        assert md._query_compiler._plan is None
        s = md["a"] > 0
        assert s._query_compiler._plan is None


def test_force_mode_replans_after_materialization(csv_path):
    with PlanMode.context("Force"):
        md = pd.read_csv(csv_path)
        len(md)  # materialization point
        qc = md._query_compiler
        assert qc._plan is None
        out = md[["b", "c"]]
        # Force re-entered planning from a Source leaf
        assert out._query_compiler._plan is not None
        explain = out._query_compiler.explain()
        assert "source" in explain
        df_equals(out, _pandas_frame(csv_path)[["b", "c"]])


def test_defer_frame_helper(csv_path):
    with PlanMode.context("Off"):
        md = pd.read_csv(csv_path)  # eager
    deferred = plan_runtime.defer_frame(md)
    assert deferred._query_compiler._plan is not None
    out = deferred.query("a > 0")[["b"]].sum()
    reference = _pandas_frame(csv_path).query("a > 0")[["b"]].sum()
    pandas.testing.assert_series_equal(out.modin.to_pandas(), reference)


def test_planned_meets_eager_falls_back_correctly(csv_path):
    md = pd.read_csv(csv_path)
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path)
    # mixing a planned frame with an eager one is not plannable: it must
    # materialize and produce the eager result
    out = md[["b"]] + eager[["b"]]
    pdf = _pandas_frame(csv_path)
    df_equals(out, pdf[["b"]] + pdf[["b"]])


def test_explain_lifecycle(csv_path):
    md = pd.read_csv(csv_path).query("a > 0")[["b", "c"]]
    before = md.modin.explain()
    assert "status: deferred" in before
    assert "scan[" in before and "filter" in before
    md._query_compiler.execute()
    after = md.modin.explain()
    assert "status: materialized" in after
    assert "pruned" in after and "rewrites:" in after
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path)
        assert "status: eager" in eager.modin.explain()


def test_second_reduce_reuses_adopted_frame(csv_path, monkeypatch):
    """After one reduction materializes, the compiler keeps the lowered
    input frame — a second aggregation must not re-read the file."""
    import modin_tpu.core.io.text.csv_dispatcher as disp

    reads = {"n": 0}
    orig = disp.CSVDispatcher.read_fn

    def counting(*args, **kwargs):
        if kwargs.get("nrows") != 0:
            reads["n"] += 1
        return orig(*args, **kwargs)

    monkeypatch.setattr(disp.CSVDispatcher, "read_fn", staticmethod(counting))
    md = pd.read_csv(csv_path)[["b", "c"]]
    first = md.sum()
    second = md.mean()
    assert reads["n"] == 1
    pdf = _pandas_frame(csv_path)[["b", "c"]]
    pandas.testing.assert_series_equal(first.modin.to_pandas(), pdf.sum())
    pandas.testing.assert_series_equal(second.modin.to_pandas(), pdf.mean())


def test_sniff_failure_declines_to_eager(tmp_path):
    missing = str(tmp_path / "missing.csv")
    with pytest.raises(FileNotFoundError):
        pd.read_csv(missing)


def test_deep_chain_hits_depth_cap_not_recursion(csv_path):
    """A pathological op loop must materialize at MAX_PLAN_DEPTH (exactly
    like ops/lazy.py's _MAX_NODES window), never RecursionError."""
    from modin_tpu.plan.ir import MAX_PLAN_DEPTH

    s = pd.read_csv(csv_path)["b"]
    ps = _pandas_frame(csv_path)["b"]
    for _ in range(MAX_PLAN_DEPTH + 50):
        s = s + 1.0
        ps = ps + 1.0
    plan = s._query_compiler._plan
    if plan is not None:
        assert plan.depth <= MAX_PLAN_DEPTH
    pandas.testing.assert_series_equal(s.modin.to_pandas(), ps)


def test_extension_dtype_requests_stay_eager(csv_path):
    """dtype={'a': 'Int64'} (like dtype_backend) declines deferral: the IR
    cannot claim plain-bool comparisons over extension columns."""
    md = pd.read_csv(csv_path, dtype={"a": "Int64"})
    assert md._query_compiler._plan is None
    mask = md["a"] > 0
    with PlanMode.context("Off"):
        eager_mask = pd.read_csv(csv_path, dtype={"a": "Int64"})["a"] > 0
    assert str(mask.dtype) == str(eager_mask.dtype) == "boolean"


def test_force_mode_extension_frame_keeps_exact_dtype():
    """Under Force, a Source over an Int64 frame knows its dtypes exactly:
    the comparison must not claim plain bool."""
    with PlanMode.context("Force"):
        md = pd.DataFrame({"x": pandas.array([1, 2, None], dtype="Int64")})
        mask = md["x"] > 1
        with PlanMode.context("Off"):
            eager = pd.DataFrame(
                {"x": pandas.array([1, 2, None], dtype="Int64")}
            )["x"] > 1
        assert str(mask.dtype) == str(eager.dtype)
        df_equals(mask, eager)


def test_index_col_zero_blocks_pushdown_and_stays_exact(csv_path):
    """index_col=0 is NOT 'no index column' — pandas resolves positional
    index_col within the usecols subset, so pushdown must be blocked."""
    from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher

    scan = ir.Scan(
        TpuCSVDispatcher, {"filepath_or_buffer": "x.csv", "index_col": 0},
        pandas.Index(["a", "b"]), pruned=("b",),
    )
    assert not plan_runtime.scan_supports_pushdown(scan)
    planned = pd.read_csv(csv_path, index_col=0)[["b", "c"]].sum()
    reference = pandas.read_csv(csv_path, index_col=0)[["b", "c"]].sum()
    pandas.testing.assert_series_equal(planned.modin.to_pandas(), reference)


def test_multiindex_header_never_pushes_tuple_usecols(tmp_path):
    """Tuple labels from a MultiIndex header cannot go into usecols; the
    pipeline must still match eager/pandas exactly."""
    path = str(tmp_path / "mi.csv")
    frame = pandas.DataFrame(
        _rng.uniform(0, 1, (50, 4)),
        columns=pandas.MultiIndex.from_product([["a", "b"], ["x", "y"]]),
    )
    frame.to_csv(path, index=False)
    planned = pd.read_csv(path, header=[0, 1])[[("a", "x")]].sum()
    reference = pandas.read_csv(path, header=[0, 1])[[("a", "x")]].sum()
    pandas.testing.assert_series_equal(planned.modin.to_pandas(), reference)


def test_branching_reads_parse_once_per_projection(csv_path, monkeypatch):
    """Two materializations branching off one deferred read must serve from
    the scan's lowered-read cache, not re-parse the file."""
    import modin_tpu.core.io.text.csv_dispatcher as disp

    reads = []
    orig = disp.CSVDispatcher.read_fn

    def counting(*args, **kwargs):
        if kwargs.get("nrows") != 0:
            reads.append(kwargs.get("usecols"))
        return orig(*args, **kwargs)

    monkeypatch.setattr(disp.CSVDispatcher, "read_fn", staticmethod(counting))
    md = pd.read_csv(csv_path)
    first = md["b"].sum()
    second = md["c"].sum()
    # the first reduce pruned to {b}; the second needs {c}: at most one
    # parse per distinct projection, and identical projections are free
    assert len(reads) <= 2
    third = md["b"].mean()  # covered by the cached {b} parse
    assert len(reads) <= 2
    # the guarantee is planned == eager (bit-exact); pandas may differ in
    # the last ulp because the device reduction order differs
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path)
        assert float(first) == float(eager["b"].sum())
        assert float(second) == float(eager["c"].sum())
        assert float(third) == float(eager["b"].mean())


def test_numeric_projection_out_of_range_declines(csv_path):
    md = pd.read_csv(csv_path)
    qc = md._query_compiler
    assert plan_runtime.defer_project(qc, [99], numeric=True) is None
    assert plan_runtime.defer_project(qc, [1], numeric=True) is not None


def test_free_on_pending_plan_drops_and_errors_clearly(csv_path):
    md = pd.read_csv(csv_path)
    qc = md._query_compiler
    assert qc._plan is not None
    qc.free()
    assert qc._plan is None
    with pytest.raises(RuntimeError, match="after free"):
        qc.to_pandas()


def test_scan_read_cache_is_bounded(csv_path):
    """A long-lived deferred read forced under many distinct projections
    must not hoard one materialized compiler per projection forever: the
    cache is bounded by its entries' MEASURED bytes
    (MODIN_TPU_PLAN_SCAN_CACHE_BYTES), evicting coldest-first."""
    from modin_tpu.config import PlanScanCacheBytes

    md = pd.read_csv(csv_path)
    scan = md._query_compiler._plan
    assert isinstance(scan, ir.Scan)
    results = {c: float(md[c].sum()) for c in ("a", "b", "c", "d", "e")}
    assert scan.origin.cache is not None
    cached_bytes = sum(b for _qc, b in scan.origin.cache.values())
    assert cached_bytes <= int(PlanScanCacheBytes.get())
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path)
        for c, value in results.items():
            assert value == float(eager[c].sum())


def test_lowering_error_names_the_plan_node(tmp_path):
    """Deferral moves eager call-site errors to the materialization point;
    the surfaced exception must name the failing logical node."""
    path = tmp_path / "strings.csv"
    pandas.DataFrame({"s": ["x", "y", "z"], "n": [1, 2, 3]}).to_csv(
        path, index=False
    )
    md = pd.read_csv(str(path))
    assert md._query_compiler._plan is not None
    mask = md["s"] > 3  # eager raises TypeError here; deferred at force time
    with pytest.raises(TypeError, match="materializing deferred plan node"):
        mask.modin.to_pandas()


def test_positional_dtype_keys_block_pushdown_and_stay_exact(
    csv_path, monkeypatch
):
    """pandas resolves int dtype-dict keys positionally against the FULL
    column set; the pushed projection filters that dict by label, so such
    reads must keep the full-width parse (and stay bit-exact vs eager)."""
    import modin_tpu.core.io.text.csv_dispatcher as disp

    body_usecols = []
    orig = disp.CSVDispatcher.read_fn

    def spying(*args, **kwargs):
        if kwargs.get("nrows") != 0:
            body_usecols.append(kwargs.get("usecols"))
        return orig(*args, **kwargs)

    monkeypatch.setattr(disp.CSVDispatcher, "read_fn", staticmethod(spying))
    md = pd.read_csv(csv_path, dtype={1: "float32"})
    assert md._query_compiler._plan is not None
    planned = float(md[md["a"] > 0]["b"].sum())
    assert all(u is None for u in body_usecols), body_usecols
    with PlanMode.context("Off"):
        eager = pd.read_csv(csv_path, dtype={1: "float32"})
        assert planned == float(eager[eager["a"] > 0]["b"].sum())


def test_force_mode_defers_filters_and_binaries():
    """Force-mode guards must hand every consumer of one compiler the same
    Source leaf, or identity row keys never match and filters/series-series
    binaries silently stay eager."""
    src = pandas.DataFrame(
        {"a": [1.0, -2.0, 3.0, -4.0], "b": [4.0, 5.0, 6.0, 7.0]}
    )
    with PlanMode.context("Force"):
        md = pd.DataFrame(src)
        mask = md["a"] > 0
        assert mask._query_compiler._plan is not None
        filtered = md[mask]
        assert filtered._query_compiler._plan is not None
        added = md["a"] + md["b"]
        assert added._query_compiler._plan is not None
        df_equals(filtered, src[src["a"] > 0])
        pandas.testing.assert_series_equal(
            added.modin.to_pandas(), src["a"] + src["b"]
        )
