"""Differential tests: rolling / expanding windows vs pandas.

Modeled on the reference suite (modin/tests/pandas/test_rolling.py and
test_expanding.py): same data, same window op, assert equality.
"""

import numpy as np
import pytest

from tests.utils import create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(11)


@pytest.fixture
def dfs():
    data = {
        "a": _rng.uniform(-50, 50, 200),
        "b": np.where(_rng.random(200) < 0.2, np.nan, _rng.uniform(0, 10, 200)),
        "c": _rng.integers(0, 100, 200),
    }
    return create_test_dfs(data)


@pytest.mark.parametrize("window", [1, 3, 10])
@pytest.mark.parametrize(
    "agg", ["sum", "mean", "count", "min", "max", "std", "var", "median"]
)
def test_rolling_aggs(dfs, window, agg):
    md, pdf = dfs
    df_equals(getattr(md.rolling(window), agg)(), getattr(pdf.rolling(window), agg)())


@pytest.mark.parametrize("min_periods", [None, 1, 5])
def test_rolling_min_periods(dfs, min_periods):
    md, pdf = dfs
    df_equals(
        md.rolling(7, min_periods=min_periods).sum(),
        pdf.rolling(7, min_periods=min_periods).sum(),
    )


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "std", "var"])
def test_expanding_aggs(dfs, agg):
    md, pdf = dfs
    df_equals(getattr(md.expanding(), agg)(), getattr(pdf.expanding(), agg)())


def test_expanding_min_periods(dfs):
    md, pdf = dfs
    df_equals(md.expanding(min_periods=4).sum(), pdf.expanding(min_periods=4).sum())


def test_expanding_method_kwarg_passed_through(dfs):
    # method='table' without a numba engine raises in pandas; the wrapper must
    # forward the kwarg so both sides agree (it was previously dropped).
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.expanding(method="table").sum())


def test_rolling_series(dfs):
    md, pdf = dfs
    df_equals(md["a"].rolling(5).mean(), pdf["a"].rolling(5).mean())
    df_equals(md["a"].expanding().sum(), pdf["a"].expanding().sum())
