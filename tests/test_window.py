"""Differential tests: rolling / expanding windows vs pandas.

Modeled on the reference suite (modin/tests/pandas/test_rolling.py and
test_expanding.py): same data, same window op, assert equality.
"""

import numpy as np
import pytest

from tests.utils import create_test_dfs, df_equals, eval_general

_rng = np.random.default_rng(11)


@pytest.fixture
def dfs():
    data = {
        "a": _rng.uniform(-50, 50, 200),
        "b": np.where(_rng.random(200) < 0.2, np.nan, _rng.uniform(0, 10, 200)),
        "c": _rng.integers(0, 100, 200),
    }
    return create_test_dfs(data)


@pytest.mark.parametrize("window", [1, 3, 10])
@pytest.mark.parametrize(
    "agg", ["sum", "mean", "count", "min", "max", "std", "var", "median"]
)
def test_rolling_aggs(dfs, window, agg):
    md, pdf = dfs
    df_equals(getattr(md.rolling(window), agg)(), getattr(pdf.rolling(window), agg)())


@pytest.mark.parametrize("min_periods", [None, 1, 5])
def test_rolling_min_periods(dfs, min_periods):
    md, pdf = dfs
    df_equals(
        md.rolling(7, min_periods=min_periods).sum(),
        pdf.rolling(7, min_periods=min_periods).sum(),
    )


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "std", "var"])
def test_expanding_aggs(dfs, agg):
    md, pdf = dfs
    df_equals(getattr(md.expanding(), agg)(), getattr(pdf.expanding(), agg)())


def test_expanding_min_periods(dfs):
    md, pdf = dfs
    df_equals(md.expanding(min_periods=4).sum(), pdf.expanding(min_periods=4).sum())


def test_expanding_method_kwarg_passed_through(dfs):
    # method='table' without a numba engine raises in pandas; the wrapper must
    # forward the kwarg so both sides agree (it was previously dropped).
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.expanding(method="table").sum())


def test_rolling_series(dfs):
    md, pdf = dfs
    df_equals(md["a"].rolling(5).mean(), pdf["a"].rolling(5).mean())
    df_equals(md["a"].expanding().sum(), pdf["a"].expanding().sum())


def _no_fallback(fn):
    from tests.utils import assert_no_fallback

    return assert_no_fallback(fn)


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "std", "var", "sem"])
def test_rolling_device_no_fallback(dfs, agg):
    md, pdf = dfs
    got = _no_fallback(lambda: getattr(md.rolling(9, min_periods=2), agg)())
    df_equals(got, getattr(pdf.rolling(9, min_periods=2), agg)())


@pytest.mark.parametrize("agg", ["sum", "mean", "count", "min", "max", "std", "var", "sem"])
def test_expanding_device_no_fallback(dfs, agg):
    md, pdf = dfs
    got = _no_fallback(lambda: getattr(md.expanding(min_periods=3), agg)())
    df_equals(got, getattr(pdf.expanding(min_periods=3), agg)())


@pytest.mark.parametrize("window", [2, 7, 64, 150, 500])
def test_rolling_minmax_window_sizes(window):
    # exercises the van Herk block algorithm across window/block alignments
    rng = np.random.default_rng(5)
    n = 300
    data = {"a": np.where(rng.random(n) < 0.3, np.nan, rng.normal(size=n))}
    md, pdf = create_test_dfs(data)
    for agg in ("min", "max"):
        df_equals(
            getattr(md.rolling(window, min_periods=1), agg)(),
            getattr(pdf.rolling(window, min_periods=1), agg)(),
        )


def test_rolling_var_large_offset():
    # global centering must keep windowed variance accurate at large offsets
    rng = np.random.default_rng(6)
    x = 1e9 + rng.normal(size=256)
    md, pdf = create_test_dfs({"a": x})
    df_equals(md.rolling(16).var(), pdf.rolling(16).var())


@pytest.mark.parametrize("ddof", [0, 1, 2])
def test_rolling_expanding_ddof(dfs, ddof):
    md, pdf = dfs
    df_equals(md.rolling(10).var(ddof=ddof), pdf.rolling(10).var(ddof=ddof))
    df_equals(md.expanding().std(ddof=ddof), pdf.expanding().std(ddof=ddof))


def test_rolling_inf_treated_as_missing():
    # pandas _prep_values converts +/-inf to NaN in every window agg
    md, pdf = create_test_dfs({"a": [1.0, -np.inf, np.nan, 5.0, np.inf, 2.0]})
    for agg in ("min", "max", "sum", "mean", "var"):
        df_equals(
            getattr(md.rolling(2, min_periods=1), agg)(),
            getattr(pdf.rolling(2, min_periods=1), agg)(),
        )


def test_rolling_ddof_on_non_var_raises():
    md, pdf = create_test_dfs({"a": [1.0, 2.0, 3.0, 4.0]})
    eval_general(md, pdf, lambda df: df.rolling(2).sum(ddof=2))


# --------------------------------------------------------------------- #
# Exponentially weighted windows (reference modin/pandas/window.py
# ExponentialMovingWindow; modin/tests/pandas/test_rolling.py shapes)
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("agg", ["mean", "sum", "var", "std"])
@pytest.mark.parametrize("adjust", [True, False])
@pytest.mark.parametrize("ignore_na", [False, True])
def test_ewm_aggs(dfs, agg, adjust, ignore_na):
    if agg == "sum" and not adjust:
        pytest.skip("pandas raises NotImplementedError for sum with adjust=False")
    md, pdf = dfs
    df_equals(
        getattr(md.ewm(alpha=0.35, adjust=adjust, ignore_na=ignore_na), agg)(),
        getattr(pdf.ewm(alpha=0.35, adjust=adjust, ignore_na=ignore_na), agg)(),
    )


@pytest.mark.parametrize("decay", [{"com": 2.5}, {"span": 9}, {"halflife": 4.0}, {"alpha": 0.08}])
def test_ewm_decay_params(dfs, decay):
    md, pdf = dfs
    df_equals(md.ewm(**decay).mean(), pdf.ewm(**decay).mean())


@pytest.mark.parametrize("min_periods", [0, 1, 6])
def test_ewm_min_periods(dfs, min_periods):
    md, pdf = dfs
    df_equals(
        md.ewm(span=5, min_periods=min_periods).mean(),
        pdf.ewm(span=5, min_periods=min_periods).mean(),
    )


@pytest.mark.parametrize("bias", [False, True])
def test_ewm_var_bias(dfs, bias):
    md, pdf = dfs
    df_equals(md.ewm(alpha=0.5).var(bias=bias), pdf.ewm(alpha=0.5).var(bias=bias))
    df_equals(md.ewm(alpha=0.5).std(bias=bias), pdf.ewm(alpha=0.5).std(bias=bias))


def test_ewm_series(dfs):
    md, pdf = dfs
    df_equals(md["b"].ewm(alpha=0.2).mean(), pdf["b"].ewm(alpha=0.2).mean())
    df_equals(
        md["b"].ewm(com=3, adjust=False).var(), pdf["b"].ewm(com=3, adjust=False).var()
    )


@pytest.mark.parametrize("agg", ["mean", "sum", "var", "std"])
def test_ewm_device_no_fallback(dfs, agg):
    md, pdf = dfs
    got = _no_fallback(lambda: getattr(md.ewm(alpha=0.3, min_periods=2), agg)())
    df_equals(got, getattr(pdf.ewm(alpha=0.3, min_periods=2), agg)())


def test_ewm_sum_adjust_false_raises(dfs):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.ewm(alpha=0.4, adjust=False).sum())


def test_ewm_corr_cov_fallback(dfs):
    md, pdf = dfs
    df_equals(md.ewm(alpha=0.4).corr(), pdf.ewm(alpha=0.4).corr())
    df_equals(md.ewm(alpha=0.4).cov(), pdf.ewm(alpha=0.4).cov())


def test_ewm_times_falls_back_correct(dfs):
    md, pdf = dfs
    import pandas

    times = pandas.date_range("2021-01-01", periods=len(pdf), freq="D")
    df_equals(
        md.ewm(halflife="2 days", times=times).mean(),
        pdf.ewm(halflife="2 days", times=times).mean(),
    )


def test_ewm_all_nan_column():
    md, pdf = create_test_dfs({"a": [np.nan] * 12, "b": np.arange(12.0)})
    df_equals(md.ewm(alpha=0.6).mean(), pdf.ewm(alpha=0.6).mean())
    df_equals(md.ewm(alpha=0.6, adjust=False).std(), pdf.ewm(alpha=0.6, adjust=False).std())


def test_ewm_invalid_params(dfs):
    md, pdf = dfs
    eval_general(md, pdf, lambda df: df.ewm().mean())  # no decay param
    eval_general(md, pdf, lambda df: df.ewm(alpha=0.3, com=2).mean())  # two
    eval_general(md, pdf, lambda df: df.ewm(alpha=1.5).mean())  # out of range


def test_ewm_alpha_one_carries_through_nans():
    md, pdf = create_test_dfs({"a": [1.0, np.nan, 2.0, np.nan, np.nan]})
    df_equals(md.ewm(alpha=1.0).mean(), pdf.ewm(alpha=1.0).mean())
    df_equals(md.ewm(com=0).mean(), pdf.ewm(com=0).mean())


def test_ewm_alpha_sweep_no_recompile(dfs):
    # distinct alphas must reuse one compiled kernel (alpha is traced)
    from modin_tpu.ops import window as w

    md, pdf = dfs
    md.ewm(alpha=0.11).mean()._query_compiler.execute()
    before = w._jit_ewm.cache_info().currsize
    for a in (0.22, 0.33, 0.44):
        df_equals(md.ewm(alpha=a).mean(), pdf.ewm(alpha=a).mean())
    assert w._jit_ewm.cache_info().currsize == before


def test_ewm_aggregate_and_online():
    md, pdf = create_test_dfs({"a": np.arange(10.0)})
    eval_general(md, pdf, lambda df: df.ewm(alpha=0.3).aggregate("mean"))
    eval_general(md, pdf, lambda df: df.ewm(alpha=0.3).agg(["mean", "std"]))
    with pytest.raises(AttributeError):
        md.ewm(alpha=0.3).not_a_real_method


class TestEwmPairwise:
    """Device ewm cov/corr under joint validity (scan pair kernel)."""

    @pytest.fixture
    def pair(self):
        rng = np.random.default_rng(23)
        n = 300
        x = rng.normal(size=n)
        y = 0.5 * x + rng.normal(size=n)
        x[[3, 4, 50]] = np.nan
        y[[5, 50, 120]] = np.nan
        return create_test_dfs({"x": x, "y": y})

    @pytest.mark.parametrize("adjust", [True, False])
    @pytest.mark.parametrize("ignore_na", [False, True])
    def test_series_cov_corr(self, pair, adjust, ignore_na):
        md, pdf = pair
        kw = dict(alpha=0.3, adjust=adjust, ignore_na=ignore_na)
        eval_general(
            md, pdf, lambda df: df["x"].ewm(**kw).cov(df["y"])
        )
        eval_general(
            md, pdf, lambda df: df["x"].ewm(**kw).corr(df["y"])
        )

    @pytest.mark.parametrize("bias", [False, True])
    def test_series_cov_bias(self, pair, bias):
        md, pdf = pair
        eval_general(
            md, pdf, lambda df: df["x"].ewm(span=7).cov(df["y"], bias=bias)
        )

    def test_self_cov_equals_var(self, pair):
        md, pdf = pair
        eval_general(md, pdf, lambda df: df.ewm(alpha=0.4).cov())
        eval_general(md, pdf, lambda df: df["x"].ewm(alpha=0.4).cov())

    def test_frame_vs_frame_matched(self, pair):
        md, pdf = pair
        m2, p2 = md * 2, pdf * 2
        df_equals(
            md.ewm(alpha=0.25).cov(m2, pairwise=False),
            pdf.ewm(alpha=0.25).cov(p2, pairwise=False),
        )
        df_equals(
            md.ewm(alpha=0.25).corr(m2, pairwise=False),
            pdf.ewm(alpha=0.25).corr(p2, pairwise=False),
        )

    def test_pairwise_true_falls_back_correct(self, pair):
        md, pdf = pair
        df_equals(md.ewm(alpha=0.4).cov(), pdf.ewm(alpha=0.4).cov())
        df_equals(
            md.ewm(alpha=0.4).corr(pairwise=True),
            pdf.ewm(alpha=0.4).corr(pairwise=True),
        )

    def test_min_periods_gate(self, pair):
        md, pdf = pair
        eval_general(
            md, pdf,
            lambda df: df["x"].ewm(alpha=0.3, min_periods=5).cov(df["y"]),
        )

    def test_device_no_fallback_series_pair(self, pair):
        md, pdf = pair
        got = _no_fallback(lambda: md["x"].ewm(alpha=0.3).cov(md["y"]))
        df_equals(got, pdf["x"].ewm(alpha=0.3).cov(pdf["y"]))


class TestGroupByWindows:
    """groupby().{rolling,expanding,ewm}() handles (reference
    modin/pandas/window.py RollingGroupby), Series and frame shapes."""

    @pytest.fixture
    def gdfs(self):
        rng = np.random.default_rng(31)
        n = 120
        return create_test_dfs(
            {"k": rng.integers(0, 4, n), "v": rng.normal(size=n),
             "w": rng.normal(size=n)}
        )

    def test_groupby_rolling_frame(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").rolling(3).sum())
        eval_general(
            md, pdf, lambda df: df.groupby("k").rolling(5, min_periods=2).mean()
        )

    def test_groupby_rolling_series(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k")["v"].rolling(2).sum())

    def test_groupby_expanding(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").expanding().sum())
        eval_general(
            md, pdf, lambda df: df.groupby("k")["v"].expanding(min_periods=3).mean()
        )

    def test_groupby_ewm(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").ewm(alpha=0.4).mean())
        eval_general(md, pdf, lambda df: df.groupby("k")["v"].ewm(span=5).std())

    def test_groupby_rolling_selection_list(self, gdfs):
        md, pdf = gdfs
        eval_general(
            md, pdf, lambda df: df.groupby("k")[["v", "w"]].rolling(4).max()
        )

    def test_series_groupby_window_returns_series(self, gdfs):
        md, pdf = gdfs
        eval_general(
            md, pdf, lambda df: df["v"].groupby(df["k"]).rolling(2).sum()
        )
        eval_general(
            md, pdf, lambda df: df["v"].groupby(df["k"]).ewm(alpha=0.5).mean()
        )

    def test_positional_min_periods(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").rolling(3, 2).sum())

    def test_positional_ewm_com(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").ewm(0.5).mean())

    def test_full_surface_via_getattr(self, gdfs):
        md, pdf = gdfs
        eval_general(md, pdf, lambda df: df.groupby("k").rolling(4).skew())
        eval_general(md, pdf, lambda df: df.groupby("k").expanding().kurt())
        eval_general(
            md, pdf,
            lambda df: df.groupby("k")[["v", "w"]].rolling(4).corr(),
        )
        with pytest.raises(AttributeError):
            md.groupby("k").rolling(3).not_a_method


class TestBlockedLinearScan:
    """The two-level blocked _linear_scan must be bit-identical to the flat
    scan (map composition is exact) and to pandas at sizes past the block
    threshold (r5: the ewm work-term reduction for 1e8-row frames)."""

    def test_blocked_equals_flat_and_pandas(self, monkeypatch):
        import jax.lax as lax
        import jax.numpy as jnp

        from modin_tpu.ops import window as W

        monkeypatch.setattr(W, "_USE_BLOCKED_SCAN", True)  # CPU defaults flat
        rng = np.random.default_rng(3)
        n = 3 * W._SCAN_BLOCK + 17  # forces the blocked path + tail padding
        a = jnp.asarray(rng.uniform(0.5, 1.0, n))
        b = jnp.asarray(rng.normal(size=n))
        blocked = np.asarray(W._linear_scan(a, b))
        flat = np.asarray(lax.associative_scan(W._scan_combine, (a, b))[1])
        np.testing.assert_allclose(blocked, flat, rtol=1e-12)

    def test_large_ewm_matches_pandas(self):
        # was skipped for an XLA:CPU late-process compile segfault; the
        # periodic jax.clear_caches() in conftest addresses the root cause
        rng = np.random.default_rng(4)
        n = 9_000
        vals = np.where(rng.random(n) < 0.05, np.nan, rng.normal(size=n))
        md, pdf = create_test_dfs({"v": vals})
        for adjust in (True, False):
            got = md.ewm(alpha=0.15, adjust=adjust).mean()
            df_equals(got, pdf.ewm(alpha=0.15, adjust=adjust).mean())
        df_equals(
            md.ewm(alpha=0.15).var(), pdf.ewm(alpha=0.15).var()
        )
