"""Shared-state thread safety under concurrent queries (graftgate audit).

The serving layer makes "many threads, one process" a supported workload,
so every cache concurrent queries share must hold up under mixed
read/write/invalidate load: the sorted-representation cache
(ops/sorted_cache.py), the fused-executable LRU (ops/lazy.py), and the
plan scan read cache (plan/lowering.py).  This suite also pins the
single-owner fixes the audit surfaced: query-stats scope seeding on
pooled workers, flight-recorder rate-limiting under simultaneous
breaker-opens, and graftguard's reseat-once handshake when multiple
threads observe the same device loss.
"""

import threading
import time

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import (
    FusedCacheSize,
    RecoveryMode,
    ResilienceBackoffS,
    ServingEnabled,
    TraceDir,
    TraceEnabled,
    TraceFlightRecorderSize,
)
from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn
from modin_tpu.core.execution import recovery, resilience
from modin_tpu.core.execution.resilience import engine_call, reset_breakers
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import flight_recorder, meters
from modin_tpu.ops import lazy as ops_lazy
from modin_tpu.ops import sorted_cache
from modin_tpu.serving.gate import gate
from modin_tpu.testing import make_device_error

THREADS = 8


@pytest.fixture(autouse=True)
def _lockdep_validated():
    """Every test in this suite runs under the runtime lock-order
    validator: an inversion raises at the acquisition site, and any
    violation recorded by a worker thread (where the raise may be
    swallowed) fails the test here."""
    from modin_tpu.concurrency import lockdep

    lockdep.enable(strict=True)
    yield
    recorded = lockdep.violations()
    lockdep.disable()
    assert not recorded, "\n".join(v.render() for v in recorded)


@pytest.fixture(autouse=True)
def _clean_state():
    saved = [
        (p, p.get())
        for p in (RecoveryMode, ResilienceBackoffS, ServingEnabled, FusedCacheSize)
    ]
    reset_breakers()
    gate.reset_for_tests()
    ResilienceBackoffS.put(0.0)
    yield
    for p, v in saved:
        p.put(v)
    reset_breakers()
    gate.reset_for_tests()


def _run_threads(workers, timeout_s=120):
    """Run callables concurrently; re-raise the first failure; no hangs."""
    errors = []
    lock = threading.Lock()

    def wrap(fn):
        try:
            fn()
        except BaseException as err:  # noqa: BLE001 - surfaced to the test
            with lock:
                errors.append(err)

    threads = [
        threading.Thread(target=wrap, args=(fn,), daemon=True) for fn in workers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s)
    assert not any(t.is_alive() for t in threads), "worker thread hung"
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------- #
# sorted-representation cache under mixed load
# ---------------------------------------------------------------------- #


def test_sorted_cache_stress_no_torn_pairs():
    """8 threads attach/get/invalidate one column's rep: a reader must
    never observe a (data, n_valid) pair mixed from two attaches."""
    import jax.numpy as jnp

    values = np.arange(1024, dtype=np.float64)
    col = DeviceColumn.from_numpy(values)
    base = jnp.sort(col.raw)  # computed once, on one thread
    base0 = float(np.asarray(base)[0])
    # per-attacher payloads: n_valid encodes which xs was attached, so a
    # torn pair is detectable from the values themselves
    payloads = {t: (base + float(t), 1000 + t) for t in range(3)}
    stop = time.monotonic() + 3.0

    def attacher(t):
        def fn():
            while time.monotonic() < stop:
                xs, n = payloads[t]
                sorted_cache.attach(col, xs, n)

        return fn

    def invalidator():
        while time.monotonic() < stop:
            sorted_cache.invalidate(col)

    def reader():
        while time.monotonic() < stop:
            got = sorted_cache.get(col)
            if got is None:
                continue
            data, n = got
            assert data is not None and n is not None, "torn rep: dropped half"
            tag = n - 1000
            assert tag in payloads, f"unknown n_valid {n}"
            head = float(np.asarray(data[0]))
            assert head == pytest.approx(base0 + tag), (
                f"torn pair: n_valid says attach #{tag}, data says "
                f"{head - base0:.1f}"
            )

    _run_threads(
        [attacher(t) for t in range(3)]
        + [invalidator, invalidator]
        + [reader, reader, reader]
    )
    # steady state afterwards: one more attach+get round-trips exactly
    sorted_cache.attach(col, base, 1000)
    data, n = sorted_cache.get(col)
    assert n == 1000
    np.testing.assert_array_equal(np.asarray(data), np.asarray(base))
    sorted_cache.invalidate(col)


def test_sorted_cache_spill_races_reader():
    """The device-ledger spill path drops reps concurrently with readers;
    a reader holding the pair keeps valid arrays (never half-None)."""
    import jax.numpy as jnp

    values = np.arange(512, dtype=np.float64)
    col = DeviceColumn.from_numpy(values)
    xs = jnp.sort(col.raw)
    stop = time.monotonic() + 2.0

    def spiller():
        while time.monotonic() < stop:
            rep = getattr(col, "_sorted_rep", None)
            if rep is not None:
                rep.spill()  # the ledger's reclaim path (drop, no copy)

    def attacher():
        while time.monotonic() < stop:
            sorted_cache.attach(col, xs, 512)

    def reader():
        while time.monotonic() < stop:
            got = sorted_cache.get(col)
            if got is not None:
                data, n = got
                assert data is not None and n == 512

    _run_threads([spiller, attacher, reader, reader])


# ---------------------------------------------------------------------- #
# fused-executable LRU under mixed load
# ---------------------------------------------------------------------- #


def test_fused_cache_lru_stress_direct():
    """Raw get/put hammering with a tiny bound: the OrderedDict's internal
    linkage survives (no KeyError/RuntimeError from torn move_to_end vs
    popitem) and the bound holds."""
    with FusedCacheSize.context(4):
        evict0 = ops_lazy.fused_cache_evictions()
        stop = time.monotonic() + 2.0

        def worker(t):
            def fn():
                i = 0
                while time.monotonic() < stop:
                    key = ("stress", t, i % 7)
                    if ops_lazy._fused_cache_get(key) is None:
                        ops_lazy._fused_cache_put(key, object())
                    i += 1

            return fn

        _run_threads([worker(t) for t in range(THREADS)])
        assert ops_lazy.fused_cache_len() <= 4
        assert ops_lazy.fused_cache_evictions() > evict0


def test_fused_chains_bit_exact_under_concurrent_submit():
    """Concurrent queries with varying fusion depths stay bit-exact while
    the bounded cache constantly evicts and recompiles."""
    rng = np.random.default_rng(11)
    n = 2048
    base = rng.integers(0, 100, n).astype(np.int64)
    mdf = pd.DataFrame({"b": base})
    mdf._query_compiler.execute()
    expected_base = int(base.sum())
    import modin_tpu.serving as serving

    ServingEnabled.put(True)
    with FusedCacheSize.context(2):

        def worker(t):
            def query(depth):
                def fn():
                    s = mdf["b"]
                    for _ in range(depth):
                        s = s + 1
                    return int(s.sum())

                return fn

            def fn():
                for i in range(6):
                    depth = 1 + (t + i) % 4
                    got = serving.submit(
                        query(depth), tenant=f"t{t}", deadline_ms=0
                    )
                    assert got == expected_base + depth * n, (
                        f"depth {depth}: {got}"
                    )

            return fn

        _run_threads([worker(t) for t in range(6)])
        assert ops_lazy.fused_cache_len() <= 2


# ---------------------------------------------------------------------- #
# plan scan read cache under mixed load
# ---------------------------------------------------------------------- #


def test_scan_cache_stress_shared_origin(tmp_path):
    """8 threads force pruned scans sharing ONE origin: the byte-bounded
    read cache stays coherent (right columns out, bound held)."""
    from modin_tpu.config import PlanScanCacheBytes
    from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher
    from modin_tpu.plan import ir
    from modin_tpu.plan.lowering import lower

    rng = np.random.default_rng(5)
    path = tmp_path / "scan.csv"
    cols = list("abcdef")
    pandas.DataFrame(
        {c: rng.integers(0, 100, 512) for c in cols}
    ).to_csv(path, index=False)
    origin = ir.Scan(
        TpuCSVDispatcher,
        {"filepath_or_buffer": str(path)},
        pandas.Index(cols),
    )
    projections = [
        ("a", "b"), ("b", "c"), ("c", "d"), ("d", "e"), ("e", "f"),
        ("a", "f"), ("b", "e"),
    ]

    def worker(t):
        def fn():
            for i in range(6):
                keep = projections[(t + i) % len(projections)]
                scan = ir.Scan(
                    TpuCSVDispatcher,
                    {"filepath_or_buffer": str(path)},
                    pandas.Index(cols),
                    pruned=keep,
                    colarg="usecols",
                    pushed=True,
                    origin=origin,
                )
                qc = lower(scan)
                assert list(qc.get_columns()) == list(keep), (
                    f"thread {t} iter {i}: wrong columns {list(qc.get_columns())}"
                )

        return fn

    _run_threads([worker(t) for t in range(THREADS)])
    assert origin.cache is not None
    cached_bytes = sum(b for _qc, b in origin.cache.values())
    assert cached_bytes <= int(PlanScanCacheBytes.get())


# ---------------------------------------------------------------------- #
# query-stats scope seeding (pooled-worker reuse)
# ---------------------------------------------------------------------- #


def test_seed_thread_scopes_clears_stale_seeding():
    with meters.query_stats("owner") as qs:
        snap = meters.snapshot_scopes()
        assert snap and snap[0] is qs

        def reused_worker():
            meters.seed_thread_scopes(snap)
            # pooled-thread reuse for UNSCOPED work: must clear, not keep
            meters.seed_thread_scopes(None)
            emit_metric("engine.dispatch", 1)

        t = threading.Thread(target=reused_worker)
        t.start()
        t.join(timeout=10)
    assert qs.dispatches == 0, (
        "a worker re-seeded with None still routed into the stale scope"
    )
    # positive control: a properly seeded worker DOES route
    with meters.query_stats("owner2") as qs2:
        snap2 = meters.snapshot_scopes()

        def seeded_worker():
            meters.seed_thread_scopes(snap2)
            emit_metric("engine.dispatch", 1)

        t = threading.Thread(target=seeded_worker)
        t.start()
        t.join(timeout=10)
    assert qs2.dispatches == 1


def test_seed_thread_scopes_empty_list_clears():
    with meters.query_stats("q") as qs:
        snap = meters.snapshot_scopes()
        seen = {}

        def worker():
            meters.seed_thread_scopes(snap)
            meters.seed_thread_scopes([])
            seen["scopes"] = meters.snapshot_scopes()

        t = threading.Thread(target=worker)
        t.start()
        t.join(timeout=10)
    assert seen["scopes"] is None
    assert qs.dispatches == 0


# ---------------------------------------------------------------------- #
# flight-recorder rate limiting under simultaneous dumps
# ---------------------------------------------------------------------- #


@pytest.fixture
def _traced(tmp_path):
    with TraceEnabled.context(True), TraceFlightRecorderSize.context(64), \
            TraceDir.context(str(tmp_path / "traces")):
        flight_recorder.reset_for_tests()
        from modin_tpu.observability import spans as graftscope

        for i in range(4):  # something in the ring to dump
            with graftscope.span(f"engine.warm{i}.attempt", layer="JAX-ENGINE"):
                pass
        yield tmp_path
    flight_recorder.reset_for_tests()


def test_simultaneous_breaker_open_dumps_write_exactly_one(_traced):
    saved = flight_recorder.MIN_DUMP_INTERVAL_S
    flight_recorder.MIN_DUMP_INTERVAL_S = 3600.0
    try:
        barrier = threading.Barrier(THREADS, timeout=30)
        paths = []
        lock = threading.Lock()

        def opener(t):
            def fn():
                barrier.wait()
                path = flight_recorder.dump_flight_record(f"breaker_open_x{t}")
                with lock:
                    paths.append(path)

            return fn

        _run_threads([opener(t) for t in range(THREADS)])
        written = [p for p in paths if p is not None]
        assert len(written) == 1, (
            f"{len(written)} dumps for ONE incident: rate limiter raced"
        )
    finally:
        flight_recorder.MIN_DUMP_INTERVAL_S = saved


def test_failed_dump_releases_only_its_own_claim(_traced):
    """A slow failing dump must not zero a NEWER successful claim — that
    re-opened the window and double-dumped the same incident."""
    saved_interval = flight_recorder.MIN_DUMP_INTERVAL_S
    real_to_chrome = flight_recorder.to_chrome_trace
    flight_recorder.MIN_DUMP_INTERVAL_S = 0.05
    slow_entered = threading.Event()
    slow_release = threading.Event()

    def hooked(spans, other_data=None, counters=None):
        if threading.current_thread().name == "slow-failing-dump":
            slow_entered.set()
            assert slow_release.wait(timeout=30)
            raise RuntimeError("disk full")
        return real_to_chrome(spans, other_data=other_data, counters=counters)

    flight_recorder.to_chrome_trace = hooked
    try:
        results = {}

        def slow_dump():
            results["slow"] = flight_recorder.dump_flight_record("slow_fail")

        t = threading.Thread(
            target=slow_dump, name="slow-failing-dump", daemon=True
        )
        t.start()
        assert slow_entered.wait(timeout=30)  # claim taken, write in flight
        time.sleep(0.06)  # the 0.05s window expires
        ok_path = flight_recorder.dump_flight_record("newer_claim")
        assert ok_path is not None  # newer claim, successful write
        slow_release.set()
        t.join(timeout=30)
        assert results["slow"] is None  # the failed dump wrote nothing
        # the regression: the failed dump's cleanup must NOT have zeroed
        # the newer claim — an immediate third dump stays rate-limited
        assert flight_recorder.dump_flight_record("third") is None
    finally:
        flight_recorder.to_chrome_trace = real_to_chrome
        flight_recorder.MIN_DUMP_INTERVAL_S = saved_interval


# ---------------------------------------------------------------------- #
# graftguard reseat-once under concurrent observers
# ---------------------------------------------------------------------- #


@pytest.fixture
def metrics():
    seen = []
    handler = lambda name, value: seen.append(name)  # noqa: E731
    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


def test_reseat_once_piggyback_semantics():
    RecoveryMode.put("Enable")
    values = np.arange(256, dtype=np.float64)
    col = DeviceColumn.from_numpy(values)  # host-cache lineage: recoverable
    e0 = recovery.current_epoch()
    n1 = recovery.reseat_all("first_observer", observed_epoch=e0)
    assert recovery.current_epoch() == e0 + 1
    assert n1 >= 1
    # a second observer of the SAME loss (same observed epoch) piggybacks:
    # no second pass, no second epoch bump, same answer for its retry logic
    n2 = recovery.reseat_all("second_observer", observed_epoch=e0)
    assert recovery.current_epoch() == e0 + 1
    assert n2 == n1
    # a genuinely NEW loss (observed in the recovered epoch) recovers again
    n3 = recovery.reseat_all("new_loss", observed_epoch=e0 + 1)
    assert recovery.current_epoch() == e0 + 2
    assert n3 >= 1
    assert np.array_equal(col.to_numpy(), values)


def test_reseat_with_dispatch_lock_held_no_deadlock():
    """Lock-order regression: a device-path thread reaches reseat_all while
    HOLDING the serving dispatch lock, while another thread reseats
    concurrently.  The globally-consistent order (dispatch -> reseat)
    must make this converge, never deadlock."""
    from modin_tpu.serving import context as serving_context

    RecoveryMode.put("Enable")
    DeviceColumn.from_numpy(np.arange(128, dtype=np.float64))
    e0 = recovery.current_epoch()

    def holder_path():
        # a guarded kernel family holds the dispatch lock for its whole
        # call; a terminal DeviceLost inside it triggers the reseat
        with serving_context.dispatch_lock:
            recovery.reseat_all("holder", observed_epoch=e0)

    def bare_observer():
        recovery.reseat_all("observer", observed_epoch=e0)

    _run_threads([holder_path, bare_observer], timeout_s=60)
    assert recovery.current_epoch() == e0 + 1  # and reseat-once held too


def test_reseat_once_concurrent_engine_calls(metrics):
    """Two threads fail the same epoch's deploys simultaneously: exactly
    one recovery pass runs, both calls succeed after it."""
    RecoveryMode.put("Enable")
    values = np.arange(512, dtype=np.float64)
    col = DeviceColumn.from_numpy(values)
    barrier = threading.Barrier(2, timeout=30)
    fired = [0]
    fire_lock = threading.Lock()

    def hook(op):
        if op != "deploy":
            return
        with fire_lock:
            if fired[0] >= 2:
                return
            fired[0] += 1
        # both threads are INSIDE an attempt (epochs captured) before
        # either raises: the deterministic same-loss shape
        barrier.wait()
        raise make_device_error("device_lost")

    assert resilience._fault_hook is None
    resilience._fault_hook = hook
    e0 = recovery.current_epoch()
    try:
        results = [None, None]

        def worker(i):
            def fn():
                results[i] = engine_call("deploy", lambda: 40 + i)

            return fn

        _run_threads([worker(0), worker(1)])
    finally:
        resilience._fault_hook = None
    assert results == [40, 41]
    assert fired[0] == 2
    assert recovery.current_epoch() == e0 + 1, (
        "two observers of one loss ran two recovery passes"
    )
    assert metrics.count("modin_tpu.recovery.device_lost") == 1
    assert np.array_equal(col.to_numpy(), values)


# --------------------------------------------------------------------- #
# graftview: the lookup -> delta-epoch/commit stale-read class
# --------------------------------------------------------------------- #


def test_view_artifact_commit_loses_to_concurrent_buffer_mutation():
    """Barrier-aligned graftview tear regression (the PR 9 sorted-rep tear
    class, one layer up): thread A snapshots an artifact between lookup
    and commit while thread B mutates the column's buffer (a concurrent
    append's spill/invalidate).  A's commit must become a no-op — never a
    stale artifact claiming the new buffer — and the registry must stay
    consistent for the next query."""
    from modin_tpu.views import registry as view_registry

    view_registry.reset()
    values = np.arange(4096, dtype=np.int64)
    col = DeviceColumn.from_numpy(values)
    params = ("sum", True, 1, False)
    assert view_registry.store(
        col, "reduce", params, {"r": np.int64(values.sum())}, can_fold=True
    )
    barrier = threading.Barrier(2, timeout=30)
    done = threading.Barrier(2, timeout=30)
    out = {}

    def reader():
        outcome, state, _ = view_registry.lookup(col, "reduce", params)
        out["outcome"] = outcome
        out["state"] = dict(state) if state else None
        barrier.wait()  # B mutates the buffer here
        done.wait()
        out["committed"] = view_registry.store(
            col, "reduce", params, out["state"], can_fold=True
        )

    def mutator():
        barrier.wait()
        out["freed"] = col.spill()
        done.wait()

    ts = [threading.Thread(target=reader), threading.Thread(target=mutator)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out["outcome"] == "hit"
    assert out["freed"] > 0
    assert out["committed"] is False, (
        "a commit against a spilled buffer must decline"
    )
    # spill invalidated the bucket; nothing may claim the column
    assert view_registry.lookup(col, "reduce", params)[0] == "miss"
    # the column itself stays correct (transparent restore)
    assert np.array_equal(col.to_numpy(), values)


def test_view_fold_lookup_race_with_append_branching():
    """Two threads fold from ONE parent artifact into two different
    appended children at a barrier: each commit lands under its own child
    token with its own tail, so neither branch can serve the other's
    answer (the delta-epoch check the ISSUE names, exercised at the
    registry layer where the interleaving is deterministic)."""
    from modin_tpu.views import incremental, registry as view_registry

    view_registry.reset()
    base = np.arange(1000, dtype=np.int64)
    parent = DeviceColumn.from_numpy(base)
    params = ("sum", True, 1, False)
    assert view_registry.store(
        parent, "reduce", params, {"r": np.int64(base.sum())}, can_fold=True
    )

    def make_child(tail):
        child = DeviceColumn.from_numpy(np.concatenate([base, tail]))
        view_registry.note_append(child, parent)
        return child

    tail_a = np.full(100, 7, dtype=np.int64)
    tail_b = np.full(250, -3, dtype=np.int64)
    child_a, child_b = make_child(tail_a), make_child(tail_b)
    barrier = threading.Barrier(2, timeout=30)
    out = {}

    def fold(name, child, tail):
        outcome, state, n0 = view_registry.lookup(child, "reduce", params)
        assert outcome == "fold" and n0 == len(base)
        barrier.wait()  # both threads hold the SAME parent snapshot
        folded = incremental.combine_scalar(
            "sum", True, state["r"], np.int64(tail.sum())
        )
        view_registry.store(
            child, "reduce", params, {"r": folded}, can_fold=True,
            folded=True,
        )
        out[name] = folded

    ts = [
        threading.Thread(target=fold, args=("a", child_a, tail_a)),
        threading.Thread(target=fold, args=("b", child_b, tail_b)),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert out["a"] == base.sum() + tail_a.sum()
    assert out["b"] == base.sum() + tail_b.sum()
    # each child answers with ITS branch's artifact
    oa, sa, _ = view_registry.lookup(child_a, "reduce", params)
    ob, sb, _ = view_registry.lookup(child_b, "reduce", params)
    assert (oa, sa["r"]) == ("hit", out["a"])
    assert (ob, sb["r"]) == ("hit", out["b"])


# ---------------------------------------------------------------------- #
# graftdep: the runtime lockdep validator (concurrency/lockdep.py)
# ---------------------------------------------------------------------- #


def test_lockdep_self_deadlock_raises_instead_of_hanging():
    from modin_tpu.concurrency import lockdep, named_lock
    from modin_tpu.concurrency.lockdep import LockdepViolation

    lock = named_lock("plan.storm")
    lock.acquire()
    try:
        with pytest.raises(LockdepViolation) as exc:
            lock.acquire()  # the raw acquire would hang forever
        assert exc.value.kind == "self-deadlock"
    finally:
        lock.release()
    assert [v.kind for v in lockdep.violations()] == ["self-deadlock"]
    lockdep.enable(strict=True)  # fresh validator: the fixture must see 0


def test_lockdep_reentrant_rlock_reacquire_is_clean():
    from modin_tpu.concurrency import lockdep, named_rlock

    r = named_rlock("recovery.provenance")
    with r:
        with r:  # owned re-acquire: legal, no new edges
            assert "recovery.provenance" in lockdep.held_locks()
    assert lockdep.held_locks() == []
    assert not lockdep.violations()


def test_lockdep_instance_pair_flagged_unless_nestable():
    from modin_tpu.concurrency import lockdep, named_lock
    from modin_tpu.concurrency.lockdep import LockdepViolation

    a, b = named_lock("plan.storm"), named_lock("plan.storm")
    with a:
        with pytest.raises(LockdepViolation) as exc:
            b.acquire()  # second instance of the same name: torn-pair class
    assert exc.value.kind == "instance-pair"

    lockdep.enable(strict=True)
    n1, n2 = named_lock("meters.query_stats"), named_lock("meters.query_stats")
    with n1:
        with n2:  # declared NESTABLE: scope-fold nesting is legal
            pass
    assert not lockdep.violations()


def test_lockdep_release_out_of_order_is_legal():
    from modin_tpu.concurrency import lockdep, named_lock, named_rlock

    outer = named_lock("serving.gate")
    inner = named_rlock("resilience.dispatch")
    outer.acquire()
    inner.acquire()
    outer.release()  # released mid-stack (the gate's wake-order pattern)
    assert lockdep.held_locks() == ["resilience.dispatch"]
    inner.release()
    assert lockdep.held_locks() == []
    assert not lockdep.violations()
    # the nesting itself landed as an observed edge, matching the declared
    # PR-9 direction
    assert ("serving.gate", "resilience.dispatch") in lockdep.observed_edges()


def test_lockdep_declared_contradiction_detected_and_metered():
    from modin_tpu.concurrency import lockdep, named_lock, named_rlock
    from modin_tpu.concurrency.lockdep import LockdepViolation

    seen = []
    handler = lambda name, value: seen.append(name)  # noqa: E731
    add_metric_handler(handler)
    try:
        dispatch = named_rlock("resilience.dispatch")
        gate_lock = named_lock("serving.gate")
        with dispatch:
            with pytest.raises(LockdepViolation) as exc:
                gate_lock.acquire()  # declared order: gate BEFORE dispatch
        assert exc.value.kind == "declared-contradiction"
        assert "serving.gate" in str(exc.value)
        assert [v.kind for v in lockdep.violations()] == [
            "declared-contradiction"
        ]
        assert "modin_tpu.concurrency.lockdep.violation" in seen
    finally:
        clear_metric_handler(handler)
    lockdep.enable(strict=True)


def test_lockdep_observed_inversion_needs_each_order_only_once():
    from modin_tpu.concurrency import lockdep, named_lock
    from modin_tpu.concurrency.lockdep import LockdepViolation

    x = named_lock("plan.storm")
    y = named_lock("io.chunker")  # no declared relation to plan.storm

    def first_order():
        with x:
            with y:
                pass

    t = threading.Thread(
        target=first_order, name="lockdep-abba-witness", daemon=True
    )
    t.start()
    t.join()
    assert ("plan.storm", "io.chunker") in lockdep.observed_edges()

    # the other interleaving never has to actually deadlock — merely
    # happening once, on any thread, is enough to convict
    with y:
        with pytest.raises(LockdepViolation) as exc:
            x.acquire()
    assert exc.value.kind == "observed-inversion"
    lockdep.enable(strict=True)


def test_lockdep_per_thread_stacks_independent():
    from modin_tpu.concurrency import lockdep, named_lock

    g = named_lock("serving.gate")
    observed = {}

    def probe():
        observed["held"] = lockdep.held_locks()

    with g:
        t = threading.Thread(
            target=probe, name="lockdep-stack-probe", daemon=True
        )
        t.start()
        t.join()
        assert lockdep.held_locks() == ["serving.gate"]
    assert observed["held"] == []
    assert not lockdep.violations()


def test_lockdep_disabled_mode_is_zero_allocation():
    """The TRACE/METERS contract: off means one module-attribute check in
    front of the raw C acquire — no validator-side object is ever built."""
    from modin_tpu.concurrency import lockdep, named_lock

    lockdep.disable()
    try:
        assert not lockdep.enabled()
        lock = named_lock("serving.gate")
        before = lockdep.lockdep_alloc_count()
        for _ in range(1000):
            with lock:
                pass
        assert lockdep.lockdep_alloc_count() == before
        assert lockdep.violations() == []
        assert lockdep.observed_edges() == {}
        assert lockdep.held_locks() == []
    finally:
        lockdep.enable(strict=True)


def test_lockdep_construction_enforces_the_registry():
    from modin_tpu.concurrency import named_lock, named_rlock

    with pytest.raises(ValueError, match="not declared"):
        named_lock("app.never.declared")
    with pytest.raises(ValueError, match="rlock"):
        named_lock("resilience.dispatch")  # declared reentrant
    with pytest.raises(ValueError, match="lock"):
        named_rlock("serving.gate")  # declared non-reentrant


def test_lockdep_leaf_out_edges_are_gc_artifacts_not_violations():
    """A weakref death callback can run while a leaf lock is held and
    acquire another lock; the validator must neither record nor convict
    on an edge OUT of a leaf (only GC timing can create one)."""
    from modin_tpu.concurrency import lockdep, named_lock, named_rlock

    ledger = named_rlock("memory.device_ledger")
    other = named_lock("plan.storm")
    with ledger:
        with other:  # the GC-artifact direction: skipped entirely
            pass
    assert (
        "memory.device_ledger",
        "plan.storm",
    ) not in lockdep.observed_edges()
    # the coded direction still records normally — and does NOT read as
    # an inversion of the artifact nesting above
    with other:
        with ledger:
            pass
    assert ("plan.storm", "memory.device_ledger") in lockdep.observed_edges()
    assert not lockdep.violations()


def test_lockdep_inversion_fanout_does_not_self_deadlock():
    """The violation fan-out (metric emission into a live QueryStats
    aggregation, the flight dump) acquires DepLocks itself; detecting an
    observed inversion must raise, not re-enter the validator's raw edge
    serialization and hang."""
    from modin_tpu.concurrency import lockdep, named_lock
    from modin_tpu.concurrency.lockdep import LockdepViolation

    x = named_lock("plan.storm")
    y = named_lock("io.chunker")

    def witness():
        with x:
            with y:
                pass

    t = threading.Thread(
        target=witness, name="lockdep-fanout-witness", daemon=True
    )
    t.start()
    t.join()

    outcome = {}

    def invert():
        with meters.query_stats("lockdep-fanout"):  # aggregation live
            with y:
                try:
                    x.acquire()
                except LockdepViolation as err:
                    outcome["kind"] = err.kind

    w = threading.Thread(
        target=invert, name="lockdep-fanout-invert", daemon=True
    )
    w.start()
    w.join(timeout=30)
    assert not w.is_alive(), "violation fan-out deadlocked the validator"
    assert outcome.get("kind") == "observed-inversion"
    lockdep.enable(strict=True)


def test_lockdep_gc_reentrancy_guard_skips_nested_validation():
    """GC runs at ANY allocation point — including inside the validator's
    own raw ``_edge_lock`` region — and weakref death callbacks acquire
    DepLocks (provenance forget, cache evictions).  The ``in_validator``
    thread-local guard must make such a nested acquire skip validation
    entirely: re-taking the raw ``_edge_lock`` on the same thread would
    wedge every validated acquire in the process (the fleet_smoke replica
    hang this test pins)."""
    from modin_tpu.concurrency import lockdep, named_lock

    lockdep.enable(strict=True)
    outer = named_lock("plan.storm")
    inner = named_lock("io.chunker")
    v = lockdep._validator
    # what check_acquire sets while it holds the raw edge serialization
    # (not holding the raw lock here keeps a regression a clean assertion
    # failure instead of a hang: an unguarded nested acquire would record
    # the edge below)
    v._tls.in_validator = True
    try:
        with outer:
            with inner:  # would normally record plan.storm -> io.chunker
                pass
    finally:
        v._tls.in_validator = False
    assert ("plan.storm", "io.chunker") not in lockdep.observed_edges()
    assert not lockdep.violations()
