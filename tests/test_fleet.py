"""graftfleet units: wire framing, routing, typed decode, zero overhead.

Acceptance bar (ISSUE 16): with ``MODIN_TPU_FLEET=0`` (the default) the
fleet is one module-attribute check — ``fleet.submit`` is a bit-for-bit
passthrough to the local serving path with zero fleet allocations and
zero fleet threads; the coordinator's routing, drain/redistribute
weighting, and reply decoding are all typed and deterministic.  The live
multi-process legs (kill -9 under load, respawn warm-state, crash
during respawn) run in scripts/fleet_smoke.py, the seventeenth
check_all gate — these tests stay single-process so tier-1 stays fast.
"""

import pickle
import socket
import threading

import numpy as np
import pandas
import pytest

from modin_tpu.config import (
    FleetEnabled,
    FleetHeartbeatS,
    FleetReplicas,
    FleetRespawn,
    ServingEnabled,
)
from modin_tpu.serving.errors import DeadlineExceeded, QueryRejected

import modin_tpu.fleet as fleet
from modin_tpu.fleet import queries as fleet_queries
from modin_tpu.fleet import wire

_PARAMS = (FleetEnabled, FleetReplicas, FleetHeartbeatS, FleetRespawn,
           ServingEnabled)


@pytest.fixture(autouse=True)
def _lockdep_validated():
    """The fleet suite runs under the runtime lock-order validator:
    coordinator/replica-slot nesting plus every lock the serving stack
    acquires underneath; violations recorded in any thread fail here."""
    from modin_tpu.concurrency import lockdep

    lockdep.enable(strict=True)
    yield
    recorded = lockdep.violations()
    lockdep.disable()
    assert not recorded, "\n".join(v.render() for v in recorded)


@pytest.fixture(autouse=True)
def _clean_fleet_state():
    saved = [(p, p.get()) for p in _PARAMS]
    yield
    fleet.reset_for_tests()
    for p, v in saved:
        p.put(v)


def _fleet_threads():
    return [
        t.name
        for t in threading.enumerate()
        if t.name.startswith("modin-tpu-fleet")
    ]


# ---------------------------------------------------------------------- #
# disabled mode: one attribute check, zero allocations, bit-exact
# ---------------------------------------------------------------------- #


class TestDisabledMode:
    def test_passthrough_bit_exact_zero_alloc(self, tmp_path):
        ServingEnabled.put(True)
        rng = np.random.default_rng(5)
        pdf = pandas.DataFrame(
            {
                "k": rng.integers(0, 7, 500).astype(np.int64),
                "i": rng.normal(size=500),
            }
        )
        csv = str(tmp_path / "ds.csv")
        pdf.to_csv(csv, index=False)
        expect = pandas.read_csv(csv)

        allocs_before = fleet.fleet_alloc_count()
        assert not fleet.FLEET_ON
        fleet.register_dataset("ds", "read_csv", csv)
        got_sum = fleet.submit("ds", "sum", tenant="t0")
        pandas.testing.assert_series_equal(got_sum, expect.sum())
        got_gb = fleet.submit("ds", "groupby_sum", tenant="t1")
        pandas.testing.assert_frame_equal(got_gb, expect.groupby("k").sum())
        # the zero-overhead-when-off contract: no fleet object was ever
        # allocated and no fleet thread exists
        assert fleet.fleet_alloc_count() == allocs_before
        assert not _fleet_threads()

    def test_unknown_dataset_is_typed(self):
        ServingEnabled.put(True)
        with pytest.raises(QueryRejected) as exc:
            fleet.submit("never_registered", "sum")
        assert exc.value.reason == "unknown_dataset"

    def test_unknown_reader_is_typed(self):
        with pytest.raises(ValueError, match="unknown modin_tpu.pandas"):
            fleet.register_dataset("ds", "read_nonsense", "/nowhere")

    def test_start_fleet_requires_enabled(self):
        assert not fleet.FLEET_ON
        with pytest.raises(RuntimeError, match="MODIN_TPU_FLEET"):
            fleet.start_fleet()

    def test_snapshot_shape_when_off(self):
        snap = fleet.fleet_snapshot()
        assert snap["enabled"] is False
        assert snap["active"] is False
        assert "replicas" not in snap

    def test_flag_follows_config(self):
        assert not fleet.FLEET_ON
        FleetEnabled.put(True)
        assert fleet.FLEET_ON
        FleetEnabled.put(False)
        assert not fleet.FLEET_ON


# ---------------------------------------------------------------------- #
# the query catalog: picklable by reference, typed resolution
# ---------------------------------------------------------------------- #


class TestQueryCatalog:
    def test_every_op_pickles_by_reference(self):
        for name, fn in fleet_queries.QUERIES.items():
            assert pickle.loads(pickle.dumps(fn)) is fn, name

    def test_resolve_name_and_callable(self):
        assert fleet_queries.resolve("sum") is fleet_queries.q_sum
        assert fleet_queries.resolve(fleet_queries.q_max) is fleet_queries.q_max

    def test_resolve_unknown_is_typed(self):
        with pytest.raises(KeyError, match="unknown fleet query"):
            fleet_queries.resolve("no_such_op")

    def test_ops_answer_host_results(self):
        pdf = pandas.DataFrame({"k": [1, 1, 2], "i": [1.0, -2.0, 3.0]})
        got = fleet_queries.QUERIES["filter_sum"](pdf)
        pandas.testing.assert_series_equal(got, pdf[pdf["i"] > 0].sum())


# ---------------------------------------------------------------------- #
# wire protocol: framing, caps, interruptible reads
# ---------------------------------------------------------------------- #


class TestWire:
    def test_roundtrip(self):
        a, b = socket.socketpair()
        try:
            payload = {"x": np.arange(1000), "s": "hello"}
            wire.send_msg(a, payload)
            got = wire.recv_msg(b)
            np.testing.assert_array_equal(got["x"], payload["x"])
            assert got["s"] == "hello"
        finally:
            a.close()
            b.close()

    def test_announced_oversize_frame_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(wire._LEN.pack(wire.MAX_FRAME_BYTES + 1))
            with pytest.raises(wire.WireError, match="cap exceeded"):
                wire.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_peer_close_mid_frame_is_wire_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(wire._LEN.pack(1 << 20) + b"partial")
            a.close()
            with pytest.raises(wire.WireError, match="closed mid-frame"):
                wire.recv_msg(b)
        finally:
            b.close()

    def test_poll_can_abort_a_blocked_read(self):
        a, b = socket.socketpair()
        try:
            b.settimeout(0.01)

            class Lost(Exception):
                pass

            def poll():
                raise Lost()

            with pytest.raises(Lost):
                wire.recv_msg(b, poll=poll)
        finally:
            a.close()
            b.close()


# ---------------------------------------------------------------------- #
# coordinator units (no processes spawned: start() is never called)
# ---------------------------------------------------------------------- #


def _coordinator(n=3, up=(), shed=None):
    from modin_tpu.fleet.coordinator import Coordinator

    coord = Coordinator(replicas=n)
    for idx in up:
        coord._replicas[idx].state = "up"
    for idx, rate in (shed or {}).items():
        coord._replicas[idx].shed_rate = rate
    return coord


class TestCoordinatorUnits:
    def test_route_is_sticky_and_least_loaded(self):
        coord = _coordinator(up=(0, 1, 2))
        first = coord._route("tA")
        assert coord._route("tA") is first  # sticky
        seen = {coord._route(f"t{i}").index for i in range(6)}
        assert seen == {0, 1, 2}  # load spread across all three

    def test_route_avoids_shedding_replica(self):
        # replica 0 sheds hard: a fresh tenant lands elsewhere even
        # though 0 is the lowest index
        coord = _coordinator(up=(0, 1, 2), shed={0: 0.9})
        assert coord._route("tFresh").index != 0

    def test_route_no_replicas_is_typed(self):
        coord = _coordinator(up=())
        with pytest.raises(QueryRejected) as exc:
            coord._route("tA")
        assert exc.value.reason == "no_replicas"
        assert exc.value.retry_after_s > 0

    def test_redistribute_drains_onto_survivors(self):
        coord = _coordinator(up=(0, 1, 2))
        coord._assignments = {"a": 0, "b": 0, "c": 0, "d": 1}
        coord._replicas[0].state = "lost"
        coord._redistribute(0)
        moved_to = {coord._assignments[t] for t in ("a", "b", "c")}
        assert moved_to <= {1, 2}
        assert coord._assignments["d"] == 1  # untouched survivor tenant
        assert coord.redistributed_count == 3
        # weighted-fair: neither survivor absorbed all three
        loads = list(coord._assignments.values())
        assert loads.count(1) < 4 and loads.count(2) >= 1

    def test_redistribute_respects_shed_backpressure(self):
        # survivor 1 is shedding at 90%: the first drained tenant prefers
        # the idle survivor 2 (weight 1.0 vs 1.9); the SECOND lands on 1
        # because raw load now dominates (2 * 1.0 vs 1 * 1.9) — shed is
        # backpressure, not exclusion
        coord = _coordinator(up=(0, 1, 2), shed={1: 0.9})
        coord._assignments = {"a": 0, "b": 0}
        coord._replicas[0].state = "lost"
        coord._redistribute(0)
        assert coord._assignments["a"] == 2
        assert coord._assignments["b"] == 1

    def test_redistribute_with_no_survivors_unassigns(self):
        coord = _coordinator(up=(0,))
        coord._assignments = {"a": 0}
        coord._replicas[0].state = "lost"
        coord._redistribute(0)
        assert coord._assignments == {}

    def test_declare_lost_is_idempotent(self):
        coord = _coordinator(up=(0, 1))
        rep = coord._replicas[0]
        coord._declare_lost(rep, "test")
        coord._declare_lost(rep, "test")
        assert rep.state == "lost"
        assert coord.lost_count == 1

    def test_register_dataset_survives_replica_death_mid_warm(self):
        # a replica dying under the warm RPC is a supervision event, not a
        # registration failure: the internal dead-socket signal must never
        # leak to the caller (the recorded manifest re-warms the slot on
        # respawn)
        from modin_tpu.core.execution import recovery

        coord = _coordinator(up=(0,))
        rep = coord._replicas[0]
        probe = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        probe.bind(("127.0.0.1", 0))
        rep.rpc_port = probe.getsockname()[1]
        probe.close()  # nothing listens there: connect dies like a killed peer
        coord.register_dataset(
            "fleet_warm_death_ds", "read_csv", ("/nonexistent.csv",), {}
        )
        assert rep.state == "lost"
        assert coord.lost_count == 1
        names = [e["name"] for e in recovery.dataset_manifest()]
        assert "fleet_warm_death_ds" in names

    def test_decode_ok(self):
        from modin_tpu.fleet.coordinator import Coordinator

        assert Coordinator._decode({"ok": True, "result": 42}) == 42

    def test_decode_rejected_is_exact(self):
        from modin_tpu.fleet.coordinator import Coordinator

        with pytest.raises(QueryRejected) as exc:
            Coordinator._decode(
                {
                    "ok": False,
                    "error": "rejected",
                    "message": "queue full on replica",
                    "reason": "queue_full",
                    "retry_after_s": 1.5,
                }
            )
        assert exc.value.reason == "queue_full"
        assert exc.value.retry_after_s == 1.5

    def test_decode_deadline_is_exact(self):
        from modin_tpu.fleet.coordinator import Coordinator

        with pytest.raises(DeadlineExceeded) as exc:
            Coordinator._decode(
                {
                    "ok": False,
                    "error": "deadline",
                    "message": "blew the budget",
                    "deadline_s": 0.25,
                    "where": "gate.dispatch",
                }
            )
        assert exc.value.where == "gate.dispatch"

    def test_decode_internal_error_is_typed(self):
        from modin_tpu.fleet.coordinator import Coordinator

        with pytest.raises(QueryRejected) as exc:
            Coordinator._decode(
                {"ok": False, "error": "internal", "message": "boom"}
            )
        assert exc.value.reason == "replica_error"

    def test_snapshot_rows(self):
        coord = _coordinator(up=(0, 1, 2))
        coord._assignments = {"a": 0}
        snap = coord.snapshot()
        assert len(snap["replicas"]) == 3
        row = snap["replicas"][0]
        for key in ("index", "state", "generation", "watch_port",
                    "rpc_port", "tenants", "shed_rate"):
            assert key in row, key
        assert snap["assignments"] == {"a": 0}


# ---------------------------------------------------------------------- #
# fleet metric families are registered (graftlint REGISTRY-DRIFT)
# ---------------------------------------------------------------------- #


def test_fleet_metric_families_registered():
    from modin_tpu.logging.metrics import METRICS

    names = {m[0] for m in METRICS}
    for family in (
        "fleet.replica.spawn",
        "fleet.replica.lost",
        "fleet.replica.heartbeat_miss",
        "fleet.replica.respawned",
        "fleet.query.routed",
        "fleet.query.redispatch",
        "fleet.drain.redistributed",
        "fleet.warm.dataset",
        "view.export",
        "view.ingest",
    ):
        assert family in names, family
