"""Fault-tolerant device execution: taxonomy, retry, breaker, fault harness.

Acceptance bar (ISSUE 1): with injected DeviceOOM / DeviceLost / slow-kernel
faults at the JaxWrapper seam, representative queries across >= 5 ``_try_*``
families return pandas-identical results (no crash, no hang); breakers trip
open after the configured threshold, route to the fallback, and recover via
half-open probe — all transitions visible through emit_metric counters.
"""

import time

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import (
    RangePartitioning,
    RecoveryMode,
    ResilienceBackoffS,
    ResilienceBreakerCooldownS,
    ResilienceBreakerThreshold,
    ResilienceLatencyBudgetS,
    ResilienceMode,
    ResilienceRetries,
    ResilienceWatchdogS,
)
from modin_tpu.core.execution import resilience
from modin_tpu.core.execution.resilience import (
    CircuitBreaker,
    DeviceFailure,
    DeviceLost,
    DeviceOOM,
    TransientDeviceError,
    WatchdogTimeout,
    classify_device_error,
    engine_call,
    get_breaker,
    reset_breakers,
)
from modin_tpu.logging import add_metric_handler, clear_metric_handler
from modin_tpu.testing import inject_faults, make_device_error

from tests.utils import df_equals

_RESILIENCE_PARAMS = (
    ResilienceMode,
    ResilienceRetries,
    ResilienceBackoffS,
    ResilienceWatchdogS,
    ResilienceBreakerThreshold,
    ResilienceBreakerCooldownS,
    ResilienceLatencyBudgetS,
    RecoveryMode,
)


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    """Fresh breakers, zero backoff sleeps, restored knobs around each test.

    RecoveryMode is pinned Disable: this suite asserts the PR-1 retry /
    breaker / fallback semantics in isolation — lineage re-seat and
    evict-then-retry would otherwise absorb the injected faults
    nondeterministically (whatever columns older tests left alive would be
    re-seated first).  The recovery legs are covered by tests/test_recovery.py.
    """
    saved = [(p, p.get()) for p in _RESILIENCE_PARAMS]
    reset_breakers()
    ResilienceBackoffS.put(0.0)
    RecoveryMode.put("Disable")
    yield
    for p, v in saved:
        p.put(v)
    reset_breakers()


@pytest.fixture
def metrics():
    """Collect emitted metric names (values are all counters of 1 here)."""
    seen = []

    def handler(name, value):
        seen.append((name, value))

    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


def _names(metrics):
    return [n for n, _ in metrics]


# ====================================================================== #
# taxonomy
# ====================================================================== #


class TestTaxonomy:
    def test_oom(self):
        err = make_device_error("oom")
        assert isinstance(classify_device_error(err), DeviceOOM)

    def test_device_lost(self):
        err = make_device_error("device_lost")
        assert isinstance(classify_device_error(err), DeviceLost)

    def test_transient(self):
        err = make_device_error("transient")
        assert isinstance(classify_device_error(err), TransientDeviceError)

    def test_unknown_runtime_error_is_transient(self):
        from modin_tpu.testing.faults import _runtime_error_type

        err = _runtime_error_type()("INTERNAL: something novel")
        assert isinstance(classify_device_error(err), TransientDeviceError)

    def test_semantic_signals_are_not_device_failures(self):
        from modin_tpu.parallel.shuffle import ShuffleSkewError
        from modin_tpu.utils import ModinAssumptionError

        for exc in (
            ShuffleSkewError("skew"),
            ModinAssumptionError("nope"),
            ValueError("RESOURCE_EXHAUSTED"),  # message alone is not enough
            TypeError("x"),
        ):
            assert classify_device_error(exc) is None

    def test_device_failure_passthrough(self):
        oom = DeviceOOM("already classified")
        assert classify_device_error(oom) is oom

    def test_watchdog_is_device_lost(self):
        assert issubclass(WatchdogTimeout, DeviceLost)
        assert issubclass(DeviceOOM, DeviceFailure)


# ====================================================================== #
# engine_call: retry / backoff / watchdog
# ====================================================================== #


class TestEngineCall:
    def test_transient_retried_to_success(self):
        ResilienceRetries.put(2)
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise make_device_error("transient")
            return "ok"

        assert engine_call("deploy", flaky) == "ok"
        assert len(attempts) == 3

    def test_transient_exhausted_raises_classified(self):
        ResilienceRetries.put(1)
        attempts = []

        def always_flaky():
            attempts.append(1)
            raise make_device_error("transient")

        with pytest.raises(TransientDeviceError):
            engine_call("deploy", always_flaky)
        assert len(attempts) == 2  # 1 try + 1 retry

    def test_oom_not_retried(self):
        ResilienceRetries.put(5)
        attempts = []

        def oom():
            attempts.append(1)
            raise make_device_error("oom")

        with pytest.raises(DeviceOOM):
            engine_call("deploy", oom)
        assert len(attempts) == 1

    def test_device_lost_not_retried(self):
        attempts = []

        def lost():
            attempts.append(1)
            raise make_device_error("device_lost")

        with pytest.raises(DeviceLost):
            engine_call("materialize", lost)
        assert len(attempts) == 1

    def test_non_device_error_propagates_unchanged(self):
        def bug():
            raise KeyError("not a device problem")

        with pytest.raises(KeyError):
            engine_call("deploy", bug)

    def test_disable_mode_propagates_raw(self):
        ResilienceMode.put("Disable")

        def oom():
            raise make_device_error("oom")

        with pytest.raises(Exception) as info:
            engine_call("deploy", oom)
        assert not isinstance(info.value, DeviceFailure)
        assert "RESOURCE_EXHAUSTED" in str(info.value)

    def test_watchdog_times_out_blocking_fetch(self):
        ResilienceWatchdogS.put(0.1)

        def wedged():
            time.sleep(5.0)
            return "never"

        t0 = time.monotonic()
        with pytest.raises(WatchdogTimeout):
            engine_call("materialize", wedged, watchdog=True)
        assert time.monotonic() - t0 < 2.0  # did not wait the full 5s

    def test_watchdog_off_by_default(self):
        assert engine_call("wait", lambda: "done", watchdog=True) == "done"

    def test_retry_metrics(self, metrics):
        ResilienceRetries.put(1)
        state = []

        def flaky_once():
            state.append(1)
            if len(state) == 1:
                raise make_device_error("transient")
            return "ok"

        engine_call("put", flaky_once)
        names = _names(metrics)
        assert "modin_tpu.resilience.engine.put.transient" in names
        assert "modin_tpu.resilience.engine.put.retry" in names


# ====================================================================== #
# circuit breaker state machine
# ====================================================================== #


class TestCircuitBreaker:
    def test_trips_after_threshold(self, metrics):
        ResilienceBreakerThreshold.put(3)
        b = CircuitBreaker("unit")
        for _ in range(2):
            b.record_failure()
            assert b.allow()
        b.record_failure()
        assert b.state == "open"
        assert not b.allow()
        assert "modin_tpu.resilience.breaker.unit.open" in _names(metrics)

    def test_success_resets_strikes(self):
        ResilienceBreakerThreshold.put(2)
        b = CircuitBreaker("unit")
        b.record_failure()
        b.record_success(0.0)
        b.record_failure()
        assert b.state == "closed"  # never two consecutive

    def test_half_open_probe_closes_on_success(self, metrics, monkeypatch):
        ResilienceBreakerThreshold.put(1)
        ResilienceBreakerCooldownS.put(10.0)
        clock = [100.0]
        monkeypatch.setattr(resilience, "_now", lambda: clock[0])
        b = CircuitBreaker("unit")
        b.record_failure()
        assert b.state == "open" and not b.allow()
        clock[0] += 11.0  # cooldown elapses
        assert b.allow()  # the half-open probe
        assert b.state == "half_open"
        assert not b.allow()  # only one probe at a time
        b.record_success(0.0)
        assert b.state == "closed" and b.allow()
        names = _names(metrics)
        assert "modin_tpu.resilience.breaker.unit.half_open" in names
        assert "modin_tpu.resilience.breaker.unit.closed" in names

    def test_half_open_probe_reopens_on_failure(self, monkeypatch):
        ResilienceBreakerThreshold.put(1)
        ResilienceBreakerCooldownS.put(10.0)
        clock = [50.0]
        monkeypatch.setattr(resilience, "_now", lambda: clock[0])
        b = CircuitBreaker("unit")
        b.record_failure()
        clock[0] += 11.0
        assert b.allow()
        b.record_failure()  # the probe failed
        assert b.state == "open"
        assert not b.allow()  # fresh cooldown
        clock[0] += 11.0
        assert b.allow()  # next probe window

    def test_aborted_probe_reopens_instead_of_sticking(self, monkeypatch):
        """An unclassified exception during the HALF_OPEN probe must return
        the breaker to OPEN (fresh cooldown), not leave it stuck HALF_OPEN
        short-circuiting the family forever."""
        from modin_tpu.core.execution.resilience import device_path

        ResilienceBreakerThreshold.put(1)
        ResilienceBreakerCooldownS.put(10.0)
        clock = [0.0]
        monkeypatch.setattr(resilience, "_now", lambda: clock[0])

        class Probe:
            mode = "fail_device"

            @device_path("probe_unit")
            def _try_thing(self):
                if self.mode == "fail_device":
                    raise make_device_error("oom")
                raise TypeError("a bug, not the device")

        p = Probe()
        assert p._try_thing() is None  # device failure -> trip open
        b = get_breaker("probe_unit")
        assert b.state == "open"
        clock[0] += 11.0
        p.mode = "bug"
        with pytest.raises(TypeError):
            p._try_thing()  # the half-open probe dies of a non-device bug
        assert b.state == "open"  # re-opened, not stuck half_open
        clock[0] += 11.0
        assert b.allow()  # a later probe window still comes

    def test_latency_budget_violation_strikes(self, metrics):
        ResilienceBreakerThreshold.put(2)
        ResilienceLatencyBudgetS.put(0.5)
        b = CircuitBreaker("unit")
        b.record_success(1.0)  # completed, but over budget
        b.record_success(2.0)
        assert b.state == "open"
        assert "modin_tpu.resilience.breaker.unit.slow" in _names(metrics)

    def test_registry(self):
        assert get_breaker("a") is get_breaker("a")
        assert get_breaker("a") is not get_breaker("b")


# ====================================================================== #
# fault injection end-to-end: >= 5 _try_* families, pandas-identical
# ====================================================================== #

_N = 512


def _frames(seed=0, datetime_index=False):
    rng = np.random.default_rng(seed)
    data = {
        "a": rng.normal(size=_N),
        "b": rng.integers(0, 1000, _N).astype(np.int64),
        "key": rng.integers(0, 7, _N).astype(np.int64),
    }
    kwargs = {}
    if datetime_index:
        kwargs["index"] = pandas.date_range("2024-01-01", periods=_N, freq="h")
    pdf = pandas.DataFrame(data, **kwargs)
    mdf = pd.DataFrame(data, **kwargs)
    mdf._query_compiler.execute()  # ingest outside any fault window
    return mdf, pdf


# (family breaker name, needs datetime index, query)
FAMILY_QUERIES = [
    ("top_k", False, lambda df: df.nlargest(5, "a")),
    ("reduce", False, lambda df: df.median(numeric_only=True)),
    ("groupby", False, lambda df: df.groupby("key").sum()),
    ("merge", False, lambda df: df.merge(df, on="key", suffixes=("_l", "_r"))),
    ("resample", True, lambda df: df.resample("D").sum()),
]


class TestFaultInjectionEndToEnd:
    @pytest.mark.parametrize("kind", ["oom", "device_lost"])
    @pytest.mark.parametrize(
        "family,dt_index,query",
        FAMILY_QUERIES,
        ids=[f[0] for f in FAMILY_QUERIES],
    )
    def test_family_fallback_is_pandas_identical(
        self, family, dt_index, query, kind, metrics
    ):
        ResilienceBreakerThreshold.put(50)  # stay closed: test the fallback leg
        mdf, pdf = _frames(seed=hash((family, kind)) % 2**32, datetime_index=dt_index)
        with inject_faults(kind, times=4) as inj:
            result = query(mdf)
            df_equals(result, query(pdf))
        assert inj.injected >= 1, "fault never reached the engine seam"
        fallback_names = [
            n for n in _names(metrics)
            if n.startswith(f"modin_tpu.resilience.fallback.{family}.")
        ]
        assert fallback_names, (
            f"no fallback recorded for family {family}: "
            f"{sorted(set(_names(metrics)))}"
        )

    def test_sort_shuffle_family_fallback(self, metrics):
        ResilienceBreakerThreshold.put(50)
        RangePartitioning.put(True)
        try:
            mdf, pdf = _frames(seed=99)
            # times=1: the fault lands on the shuffle's pivot fetch inside
            # the family; the non-shuffle fallback it degrades to is itself
            # a DEVICE path (global argsort), which must then run clean
            with inject_faults("oom", times=1) as inj:
                df_equals(
                    mdf.sort_values("a", ignore_index=True),
                    pdf.sort_values("a", ignore_index=True),
                )
            assert inj.injected >= 1
            assert any(
                n.startswith("modin_tpu.resilience.fallback.sort_shuffle.")
                for n in _names(metrics)
            )
        finally:
            RangePartitioning.put(False)

    def test_transient_fault_retries_without_fallback(self, metrics):
        """One transient hiccup: the retry absorbs it, the device answers."""
        ResilienceRetries.put(2)
        mdf, pdf = _frames(seed=7)
        with inject_faults("transient", ops=("materialize",), times=1) as inj:
            df_equals(mdf.nlargest(5, "a"), pdf.nlargest(5, "a"))
        assert inj.injected == 1
        names = _names(metrics)
        assert "modin_tpu.resilience.engine.materialize.retry" in names
        assert not any(".fallback." in n for n in names)

    def test_slow_kernel_trips_watchdog_then_falls_back(self, metrics):
        ResilienceWatchdogS.put(0.1)
        ResilienceBreakerThreshold.put(50)
        mdf, pdf = _frames(seed=13)
        with inject_faults(
            "slow_kernel", ops=("materialize",), times=2, slow_s=1.0
        ) as inj:
            df_equals(mdf.nlargest(5, "a"), pdf.nlargest(5, "a"))
        assert inj.injected >= 1
        names = _names(metrics)
        assert "modin_tpu.resilience.watchdog.materialize.timeout" in names
        assert any(
            n.startswith("modin_tpu.resilience.fallback.")
            and n.endswith(".watchdog_timeout")
            for n in names
        )

    def test_breaker_trips_short_circuits_and_recovers(self, metrics, monkeypatch):
        """The acceptance scenario: strike to open, fallback while open,
        half-open probe on cooldown, clean probe closes."""
        ResilienceBreakerThreshold.put(2)
        ResilienceBreakerCooldownS.put(30.0)
        mdf, pdf = _frames(seed=21)
        expected = pdf.nlargest(5, "a")

        # 2 failing calls trip the breaker
        with inject_faults("oom", ops=("materialize",), times=None) as inj:
            df_equals(mdf.nlargest(5, "a"), expected)
            df_equals(mdf.nlargest(5, "a"), expected)
            assert get_breaker("top_k").state == "open"
            faults_used = inj.injected

            # open: short-circuits to pandas without touching the device
            df_equals(mdf.nlargest(5, "a"), expected)
            assert inj.injected == faults_used  # no new engine-seam attempts
        names = _names(metrics)
        assert "modin_tpu.resilience.breaker.top_k.open" in names
        assert "modin_tpu.resilience.breaker.top_k.short_circuit" in names

        # cooldown elapses (simulated clock) -> half-open probe, device is
        # healthy again -> closed
        real_now = resilience._now
        monkeypatch.setattr(resilience, "_now", lambda: real_now() + 31.0)
        df_equals(mdf.nlargest(5, "a"), expected)
        assert get_breaker("top_k").state == "closed"
        names = _names(metrics)
        assert "modin_tpu.resilience.breaker.top_k.half_open" in names
        assert "modin_tpu.resilience.breaker.top_k.closed" in names

    def test_latency_budget_degrades_slow_path(self, metrics):
        """A slow (but succeeding) kernel exhausts its budget strikes and the
        family degrades to pandas — the VERDICT r5 sort-regression scenario."""
        ResilienceBreakerThreshold.put(2)
        ResilienceLatencyBudgetS.put(1e-9)  # everything is over budget
        mdf, pdf = _frames(seed=34)
        expected = pdf.nlargest(5, "a")
        df_equals(mdf.nlargest(5, "a"), expected)  # strike 1 (slow success)
        df_equals(mdf.nlargest(5, "a"), expected)  # strike 2 -> open
        assert get_breaker("top_k").state == "open"
        df_equals(mdf.nlargest(5, "a"), expected)  # short-circuit, same answer
        names = _names(metrics)
        assert "modin_tpu.resilience.breaker.top_k.slow" in names
        assert "modin_tpu.resilience.breaker.top_k.short_circuit" in names

    def test_disable_mode_bypasses_breakers(self):
        ResilienceMode.put("Disable")
        mdf, pdf = _frames(seed=55)
        # an open breaker is ignored when the layer is off
        get_breaker("top_k").record_failure()
        df_equals(mdf.nlargest(5, "a"), pdf.nlargest(5, "a"))

    def test_injector_is_exclusive(self):
        with inject_faults("oom"):
            with pytest.raises(RuntimeError):
                with inject_faults("transient"):
                    pass

    def test_injector_restores_hook(self):
        with inject_faults("oom", times=0):
            assert resilience._fault_hook is not None
        assert resilience._fault_hook is None
