"""TPU-native boosted-trees trainer (experimental.xgboost.native)."""

import numpy as np
import pytest

import modin_tpu.pandas as pd
from modin_tpu.experimental import xgboost as mxgb


@pytest.fixture(scope="module")
def regression_data():
    rng = np.random.default_rng(0)
    n = 800
    X = pd.DataFrame(
        {"x1": rng.uniform(-3, 3, n), "x2": rng.uniform(-3, 3, n)}
    )
    y_true = np.sin(X["x1"].to_numpy()) * 2 + 0.5 * X["x2"].to_numpy() ** 2
    y = pd.Series(y_true + rng.normal(0, 0.05, n))
    return X, y, y_true


def test_regression_learns(regression_data):
    X, y, y_true = regression_data
    dtrain = mxgb.DMatrix(X, label=y)
    res = {}
    bst = mxgb.train(
        {"max_depth": 3, "eta": 0.3}, dtrain, num_boost_round=12, evals_result=res
    )
    rmse = res["train"]["rmse"]
    assert rmse[-1] < rmse[0] * 0.5  # loss halves at minimum
    assert rmse[-1] < np.std(y_true) * 0.4  # far better than the mean predictor
    pred = bst.predict(dtrain)
    assert isinstance(pred, pd.Series) and len(pred) == len(y)
    assert np.corrcoef(pred.to_numpy(), y_true)[0, 1] > 0.95


def test_predict_on_fresh_frame(regression_data):
    X, y, _ = regression_data
    dtrain = mxgb.DMatrix(X, label=y)
    bst = mxgb.train({"max_depth": 3}, dtrain, num_boost_round=6)
    head = X.head(50)
    pred = bst.predict(head)
    assert len(pred) == 50
    full = bst.predict(dtrain).to_numpy()[:50]
    np.testing.assert_allclose(pred.to_numpy(), full, rtol=1e-6)


def test_binary_logistic():
    rng = np.random.default_rng(1)
    n = 800
    X = pd.DataFrame(
        {"a": rng.normal(size=n), "b": rng.normal(size=n)}
    )
    y = pd.Series((X["a"].to_numpy() + X["b"].to_numpy() > 0).astype(float))
    dm = mxgb.DMatrix(X, label=y)
    res = {}
    bst = mxgb.train(
        {"max_depth": 3, "eta": 0.4, "objective": "binary:logistic"},
        dm, num_boost_round=10, evals_result=res,
    )
    p = bst.predict(dm).to_numpy()
    assert ((p >= 0) & (p <= 1)).all()  # probabilities, not margins
    assert np.mean((p > 0.5) == (y.to_numpy() > 0.5)) > 0.9
    assert res["train"]["logloss"][-1] < res["train"]["logloss"][0]


def test_nan_features_and_param_aliases():
    rng = np.random.default_rng(2)
    n = 500
    x = rng.uniform(-2, 2, n)
    x[rng.integers(0, n, 60)] = np.nan
    X = pd.DataFrame({"x": x})
    y = pd.Series(np.where(np.isnan(x), 3.0, x * 2.0))
    dm = mxgb.DMatrix(X, label=y)
    bst = mxgb.train(
        {"max_depth": 2, "learning_rate": 0.5, "reg_lambda": 0.5},
        dm, num_boost_round=8,
    )
    pred = bst.predict(dm).to_numpy()
    assert np.corrcoef(pred, y.to_numpy())[0, 1] > 0.9


def test_dmatrix_introspection(regression_data):
    X, y, _ = regression_data
    dm = mxgb.DMatrix(X, label=y)
    assert dm.num_row() == len(X._to_pandas())
    assert dm.num_col() == 2
    assert dm.feature_names == ["x1", "x2"]
    assert len(dm.get_label()) == dm.num_row()


def test_unsupported_objective_raises(regression_data):
    X, y, _ = regression_data
    dm = mxgb.DMatrix(X, label=y)
    with pytest.raises(ValueError, match="objective"):
        mxgb.train({"objective": "multi:softmax"}, dm, num_boost_round=2)
