"""Differential-testing helpers.

Reference design: /root/reference/modin/tests/pandas/utils.py (``df_equals``
:768, ``eval_general``, ``create_test_dfs``): build the same data as a
modin_tpu object and a pandas object, run the same operation on both, assert
equality.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np
import pandas
from pandas.testing import assert_frame_equal, assert_index_equal, assert_series_equal

import modin_tpu.pandas as pd
from modin_tpu.utils import try_cast_to_pandas

RAND_LOW = 0
RAND_HIGH = 100
NROWS = 64
NCOLS = 8

_rng = np.random.default_rng(42)

test_data = {
    "int_data": {
        f"col{i}": _rng.integers(RAND_LOW, RAND_HIGH, size=NROWS) for i in range(NCOLS)
    },
    "float_nan_data": {
        f"col{i}": [
            x if j % 4 else np.nan
            for j, x in enumerate(_rng.uniform(RAND_LOW, RAND_HIGH, size=NROWS))
        ]
        for i in range(NCOLS)
    },
}

test_data_values = list(test_data.values())
test_data_keys = list(test_data.keys())


def categories_equals(left: pandas.Categorical, right: pandas.Categorical) -> None:
    assert (left.ordered and right.ordered) or (not left.ordered and not right.ordered)
    assert_index_equal(left.categories, right.categories)


def df_equals(df1: Any, df2: Any, check_dtypes: bool = True) -> None:
    """Assert two (modin_tpu or pandas) objects are equal."""
    types_for_almost_equals = (pandas.core.indexes.range.RangeIndex, pandas.Index)

    df1 = try_cast_to_pandas(df1)
    df2 = try_cast_to_pandas(df2)

    if isinstance(df1, pandas.DataFrame) and isinstance(df2, pandas.DataFrame):
        assert_frame_equal(
            df1, df2, check_dtype=check_dtypes, check_categorical=False,
            check_freq=False,
        )
    elif isinstance(df1, pandas.Series) and isinstance(df2, pandas.Series):
        assert_series_equal(
            df1, df2, check_dtype=check_dtypes, check_categorical=False,
            check_freq=False,
        )
    elif isinstance(df1, types_for_almost_equals) and isinstance(
        df2, types_for_almost_equals
    ):
        assert_index_equal(df1, df2)
    elif isinstance(df1, pandas.Categorical) and isinstance(df2, pandas.Categorical):
        categories_equals(df1, df2)
    elif isinstance(df1, np.ndarray) and isinstance(df2, np.ndarray):
        np.testing.assert_array_equal(df1, df2)
    elif isinstance(df1, (float, np.floating)) and np.isnan(df1):
        assert np.isnan(df2), f"{df1} != {df2}"
    elif isinstance(df1, dict) and isinstance(df2, dict):
        assert df1.keys() == df2.keys()
        for k in df1:
            df_equals(df1[k], df2[k], check_dtypes=check_dtypes)
    else:
        if isinstance(df1, (float, np.floating)) or isinstance(df2, (float, np.floating)):
            np.testing.assert_allclose(df1, df2, rtol=1e-12)
        else:
            assert df1 == df2, f"{df1} != {df2}"


def create_test_dfs(*args: Any, **kwargs: Any):
    """Build the same DataFrame as (modin_tpu, pandas)."""
    return pd.DataFrame(*args, **kwargs), pandas.DataFrame(*args, **kwargs)


def create_test_series(*args: Any, **kwargs: Any):
    return pd.Series(*args, **kwargs), pandas.Series(*args, **kwargs)


def assert_no_fallback(fn: Callable):
    """Run ``fn`` asserting no default-to-pandas warning fires.

    Device-path assertions only make sense on the TpuOnJax execution; other
    executions (``--execution NativeOnNative``) skip instead of failing.
    """
    import warnings

    import pytest

    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("device-path assertion requires TpuOnJax")
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        return fn()


def eval_general(
    modin_obj: Any,
    pandas_obj: Any,
    operation: Callable,
    comparator: Callable = df_equals,
    check_exception_type: bool = True,
    **kwargs: Any,
) -> None:
    """Run ``operation`` against both objects and compare results or exceptions."""
    md_kwargs, pd_kwargs = {}, {}

    def execute_callable(fn, inplace=False, md_kwargs={}, pd_kwargs={}):
        try:
            pd_result = fn(pandas_obj, **pd_kwargs)
        except Exception as pd_e:
            try:
                if check_exception_type:
                    try:
                        md_result = fn(modin_obj, **md_kwargs)
                    except Exception as md_e:
                        assert isinstance(
                            md_e, type(pd_e)
                        ) or isinstance(pd_e, type(md_e)), (
                            f"Different exceptions: pandas={pd_e!r} modin_tpu={md_e!r}"
                        )
                        return None
                    raise AssertionError(
                        f"pandas raised {pd_e!r} but modin_tpu returned {md_result!r}"
                    )
            finally:
                pass
            return None
        md_result = fn(modin_obj, **md_kwargs)
        return md_result, pd_result

    for key, value in kwargs.items():
        if isinstance(value, tuple) and len(value) == 2 and callable(value[0]):
            md_kwargs[key], pd_kwargs[key] = value
        else:
            md_kwargs[key] = value
            pd_kwargs[key] = value

    values = execute_callable(
        operation, md_kwargs=md_kwargs, pd_kwargs=pd_kwargs
    )
    if values is not None:
        comparator(*values)


def sort_if_range_partitioning(df1: Any, df2: Any, comparator: Callable = df_equals) -> None:
    """Sort results before comparison when the execution doesn't guarantee order."""
    from modin_tpu.config import RangePartitioning

    if RangePartitioning.get():
        df1 = df1.sort_index() if hasattr(df1, "sort_index") else df1
        df2 = df2.sort_index() if hasattr(df2, "sort_index") else df2
    comparator(df1, df2)


def require_tpu_execution() -> None:
    """Skip the calling test on executions without the TpuOnJax device/IO
    wiring (mirrors assert_no_fallback's behavior for path assertions)."""
    import pytest

    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("TpuOnJax-specific path")
