"""graftstream acceptance: out-of-core windowed execution.

Four layers:

1. the differential pipeline grid — CSV scan -> filter -> reduce/groupby
   with the windowed executor FORCED, bit-exact vs pandas AND vs the
   resident path, including a ragged final window, an all-NaN window, a
   window landing exactly on a record boundary, empty-after-filter
   windows, sort=False / series-groupby / dropna=False legs, and the
   MODIN_TPU_STREAM_MAX_GROUPS degrade;
2. external kernels — the per-window external sort and the spill-aware
   merge-join are bit-identical to the resident device paths (and pandas)
   across dtype/direction/ties/NaN/miss grids;
3. chaos — ``midquery_device_loss`` and ``oom_burst_until_eviction``
   injected MID-STREAM complete bit-exact with recovery.* showing a
   single-WINDOW (not whole-dataset) replay, plus the explicit
   terminal-failure window-replay legs of the loop itself;
4. routing/accounting units — ``decide_residency``, window-size
   derivation, the byte-bounded scan cache (``plan.scan.cache_evict``),
   QueryStats window fields, and graftgate's window-footprint billing.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import (
    DeviceMemoryBudget,
    PlanScanCacheBytes,
    ResilienceBackoffS,
    StreamMaxGroups,
    StreamMode,
    StreamPrefetch,
    StreamWindowBytes,
)
from modin_tpu.logging import add_metric_handler, clear_metric_handler


@pytest.fixture(autouse=True)
def _require_tpu():
    from modin_tpu.utils import get_current_execution

    if get_current_execution() != "TpuOnJax":
        pytest.skip("graftstream requires TpuOnJax")


@pytest.fixture
def metric_counts():
    seen = {}

    def handler(name, value):
        seen[name.replace("modin_tpu.", "", 1)] = (
            seen.get(name.replace("modin_tpu.", "", 1), 0) + value
        )

    add_metric_handler(handler)
    yield seen
    clear_metric_handler(handler)


@pytest.fixture
def windowed():
    """Force the windowed executor at a small window so every test frame
    genuinely streams (multiple windows) without needing huge files."""
    with StreamMode.context("Windowed"), StreamWindowBytes.context(4096):
        yield


def _csv(tmp_path, df, name="stream.csv"):
    path = tmp_path / name
    df.to_csv(path, index=False)
    return str(path)


def _base_df(n=12000, nan_block=False, seed=5):
    rng = np.random.default_rng(seed)
    v = rng.integers(0, 1000, n).astype(np.float64) * 0.5
    if nan_block:
        # a contiguous NaN region wide enough to cover entire windows at
        # the 4 KB test window size (several hundred rows per window)
        v[2000:6000] = np.nan
    return pandas.DataFrame(
        {
            "k": rng.integers(0, 20, n),
            "a": rng.integers(-50, 50, n),
            "v": v,
        }
    )


# ---------------------------------------------------------------------- #
# 1. the differential pipeline grid
# ---------------------------------------------------------------------- #


class TestStreamedPipelines:
    @pytest.mark.parametrize("agg", ["sum", "mean", "min", "max", "count"])
    def test_filter_groupby_bit_exact_and_streamed(
        self, tmp_path, windowed, metric_counts, agg
    ):
        df = _base_df()
        path = _csv(tmp_path, df)
        m = pd.read_csv(path)
        got = getattr(m[m["a"] > 0].groupby("k"), agg)()._to_pandas()
        expect = getattr(df[df["a"] > 0].groupby("k"), agg)()
        pandas.testing.assert_frame_equal(got, expect)
        assert metric_counts.get("stream.window.count", 0) > 1

    @pytest.mark.parametrize("agg", ["sum", "mean", "min", "max", "count", "prod"])
    def test_filter_reduce_bit_exact(self, tmp_path, windowed, agg):
        df = _base_df()
        path = _csv(tmp_path, df)
        m = pd.read_csv(path)
        got = getattr(m[m["a"] > 0][["v", "a"]], agg)()._to_pandas()
        expect = getattr(df[df["a"] > 0][["v", "a"]], agg)()
        pandas.testing.assert_series_equal(got, expect)

    def test_windowed_meets_resident_bit_for_bit(self, tmp_path):
        df = _base_df()
        path = _csv(tmp_path, df)

        def run():
            m = pd.read_csv(path)
            return m[m["a"] > 0].groupby("k").sum()._to_pandas()

        with StreamMode.context("Resident"):
            resident = run()
        with StreamMode.context("Windowed"), StreamWindowBytes.context(4096):
            streamed = run()
        pandas.testing.assert_frame_equal(streamed, resident)

    def test_all_nan_window(self, tmp_path, windowed):
        df = _base_df(nan_block=True)
        path = _csv(tmp_path, df)
        for agg in ("sum", "mean", "min"):
            m = pd.read_csv(path)
            got = getattr(m[["v"]], agg)()._to_pandas()
            pandas.testing.assert_series_equal(got, getattr(df[["v"]], agg)())
        got = pd.read_csv(path).groupby("k").min()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").min())

    def test_skipna_false_with_nans(self, tmp_path, windowed):
        df = _base_df(nan_block=True)
        path = _csv(tmp_path, df)
        for agg in ("sum", "mean", "min", "max"):
            got = getattr(pd.read_csv(path)[["v", "a"]], agg)(
                skipna=False
            )._to_pandas()
            expect = getattr(df[["v", "a"]], agg)(skipna=False)
            pandas.testing.assert_series_equal(got, expect)

    def test_exact_window_boundary(self, tmp_path, metric_counts):
        # fixed-width records: every line is exactly 10 bytes, so a
        # 400-record window target lands PRECISELY on a record boundary
        n = 4000
        rng = np.random.default_rng(3)
        k = rng.integers(0, 9, n)
        v = rng.integers(0, 9999, n)
        path = tmp_path / "fixed.csv"
        with open(path, "w") as f:
            f.write("k,v\n")
            for ki, vi in zip(k, v):
                f.write(f"{ki:04d},{vi:04d}\n")
        df = pandas.read_csv(path)
        with StreamMode.context("Windowed"), StreamWindowBytes.context(
            10 * 400
        ):
            got = pd.read_csv(str(path)).groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").sum())
        assert metric_counts.get("stream.window.count", 0) == (n + 399) // 400

    def test_ragged_final_window(self, tmp_path, windowed, metric_counts):
        # a prime row count guarantees the last byte window is ragged
        df = _base_df(n=10007)
        path = _csv(tmp_path, df)
        got = pd.read_csv(path).groupby("k").count()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").count())
        assert metric_counts.get("stream.window.count", 0) > 1

    def test_sparse_filter_empty_windows(self, tmp_path, windowed):
        df = _base_df()
        path = _csv(tmp_path, df)
        m = pd.read_csv(path)
        got = m[m["a"] > 48].groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(
            got, df[df["a"] > 48].groupby("k").sum()
        )

    def test_sort_false_and_series_groupby(self, tmp_path, windowed):
        df = _base_df()
        path = _csv(tmp_path, df)
        got = pd.read_csv(path).groupby("k", sort=False)["v"].sum()._to_pandas()
        pandas.testing.assert_series_equal(
            got, df.groupby("k", sort=False)["v"].sum()
        )

    def test_groupby_dropna_false_nan_keys(self, tmp_path, windowed):
        df = _base_df()
        df["k"] = df["k"].astype(np.float64)
        df.loc[df.index % 7 == 0, "k"] = np.nan
        path = _csv(tmp_path, df)
        got = (
            pd.read_csv(path).groupby("k", dropna=False).sum()._to_pandas()
        )
        pandas.testing.assert_frame_equal(
            got, df.groupby("k", dropna=False).sum()
        )

    def test_projection_prunes_per_window(self, tmp_path, windowed):
        # pushdown still applies per window: parse only {a, v}
        df = _base_df()
        path = _csv(tmp_path, df)
        m = pd.read_csv(path)
        got = m[m["a"] > 0][["v"]].sum()._to_pandas()
        pandas.testing.assert_series_equal(got, df[df["a"] > 0][["v"]].sum())

    def test_max_groups_degrades_to_resident(
        self, tmp_path, windowed, metric_counts
    ):
        df = _base_df()
        path = _csv(tmp_path, df)
        with StreamMaxGroups.context(5):  # 20 real groups crosses it
            got = pd.read_csv(path).groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").sum())
        assert metric_counts.get("stream.degrade", 0) >= 1

    def test_serial_prefetch_zero(self, tmp_path, metric_counts):
        df = _base_df()
        path = _csv(tmp_path, df)
        with StreamMode.context("Windowed"), StreamWindowBytes.context(
            4096
        ), StreamPrefetch.context(0):
            got = pd.read_csv(path).groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").sum())
        assert metric_counts.get("stream.window.count", 0) > 1
        assert metric_counts.get("stream.prefetch.overlap_s", 0) == 0

    def test_windows_release_device_memory(self, tmp_path, windowed):
        from modin_tpu.core.memory import device_ledger

        df = _base_df()
        path = _csv(tmp_path, df)
        before = device_ledger.total_bytes()
        got = pd.read_csv(path).groupby("k").sum()._to_pandas()
        assert len(got) == 20
        # only the (tiny) result may remain resident — dead windows were
        # deregistered eagerly, not left to GC
        assert device_ledger.total_bytes() - before < 1 << 17

    def test_unsupported_agg_stays_resident(
        self, tmp_path, windowed, metric_counts
    ):
        df = _base_df()
        path = _csv(tmp_path, df)
        got = pd.read_csv(path)[["v"]].median()._to_pandas()
        pandas.testing.assert_series_equal(got, df[["v"]].median())
        assert metric_counts.get("stream.window.count", 0) == 0


# ---------------------------------------------------------------------- #
# 2. external sort & merge-join
# ---------------------------------------------------------------------- #


def _sort_frame(n=9000, key_dtype="float"):
    rng = np.random.default_rng(11)
    if key_dtype == "float":
        key = rng.integers(0, 300, n).astype(np.float64) * 0.5
        key[rng.random(n) < 0.04] = np.nan
    else:
        key = rng.integers(-500, 500, n)
    return pandas.DataFrame(
        {
            "key": key,
            "pay": rng.integers(0, 1000, n),
            "w": rng.integers(0, 50, n).astype(np.float64),
        }
    )


class TestExternalKernels:
    @pytest.mark.parametrize("key_dtype", ["float", "int"])
    @pytest.mark.parametrize("ascending", [True, False])
    def test_external_sort_bit_identical(
        self, windowed, metric_counts, key_dtype, ascending
    ):
        df = _sort_frame(key_dtype=key_dtype)
        mdf = pd.DataFrame(df)
        with StreamMode.context("Resident"):
            resident = mdf.sort_values("key", ascending=ascending)._to_pandas()
        with StreamMode.context("Windowed"), StreamWindowBytes.context(4096):
            streamed = mdf.sort_values("key", ascending=ascending)._to_pandas()
        pandas.testing.assert_frame_equal(streamed, resident)
        pandas.testing.assert_frame_equal(
            streamed,
            df.sort_values("key", ascending=ascending, kind="stable"),
        )
        assert metric_counts.get("stream.window.count", 0) > 1
        assert metric_counts.get("stream.spill.run_bytes", 0) > 0

    def test_external_sort_ignore_index(self, windowed):
        df = _sort_frame(key_dtype="int")
        got = pd.DataFrame(df).sort_values("key", ignore_index=True)._to_pandas()
        pandas.testing.assert_frame_equal(
            got, df.sort_values("key", kind="stable", ignore_index=True)
        )

    def test_external_sort_heavy_ties_stable(self, windowed):
        rng = np.random.default_rng(2)
        df = pandas.DataFrame(
            {"key": rng.integers(0, 3, 8000), "pay": np.arange(8000)}
        )
        got = pd.DataFrame(df).sort_values("key")._to_pandas()
        pandas.testing.assert_frame_equal(
            got, df.sort_values("key", kind="stable")
        )

    def test_multikey_declines_to_resident(self, windowed):
        df = _sort_frame(key_dtype="int")
        got = pd.DataFrame(df).sort_values(["key", "pay"])._to_pandas()
        pandas.testing.assert_frame_equal(
            got, df.sort_values(["key", "pay"], kind="stable")
        )

    @pytest.mark.parametrize("how", ["inner", "left"])
    def test_external_merge_bit_identical(self, windowed, how):
        rng = np.random.default_rng(4)
        left = pandas.DataFrame(
            {"k": rng.integers(0, 150, 9000), "lv": rng.integers(0, 100, 9000)}
        )
        right = pandas.DataFrame(
            {
                "k": rng.integers(0, 150, 3000),
                "rv": rng.integers(0, 100, 3000),
            }
        )
        ml, mr = pd.DataFrame(left), pd.DataFrame(right)
        with StreamMode.context("Resident"):
            resident = ml.merge(mr, on="k", how=how)._to_pandas()
        with StreamMode.context("Windowed"), StreamWindowBytes.context(4096):
            streamed = ml.merge(mr, on="k", how=how)._to_pandas()
        pandas.testing.assert_frame_equal(streamed, resident)
        pandas.testing.assert_frame_equal(
            streamed, left.merge(right, on="k", how=how)
        )

    def test_external_left_merge_misses_promote(self, windowed):
        rng = np.random.default_rng(6)
        left = pandas.DataFrame(
            {"k": rng.integers(0, 200, 8000), "lv": rng.integers(0, 9, 8000)}
        )
        right = pandas.DataFrame(
            {"k": rng.integers(0, 40, 2500), "rv": rng.integers(0, 9, 2500)}
        )
        got = (
            pd.DataFrame(left)
            .merge(pd.DataFrame(right), on="k", how="left")
            ._to_pandas()
        )
        expect = left.merge(right, on="k", how="left")
        pandas.testing.assert_frame_equal(got, expect)
        assert expect["rv"].dtype == np.float64  # misses promoted

    def test_external_merge_nan_keys_match(self, windowed):
        rng = np.random.default_rng(8)
        lk = rng.integers(0, 60, 7000).astype(np.float64)
        lk[rng.random(7000) < 0.03] = np.nan
        rk = rng.integers(0, 60, 2000).astype(np.float64)
        rk[rng.random(2000) < 0.03] = np.nan
        left = pandas.DataFrame({"k": lk, "lv": np.arange(7000)})
        right = pandas.DataFrame({"k": rk, "rv": np.arange(2000)})
        got = (
            pd.DataFrame(left)
            .merge(pd.DataFrame(right), on="k", how="inner")
            ._to_pandas()
        )
        pandas.testing.assert_frame_equal(
            got, left.merge(right, on="k", how="inner")
        )

    def test_external_merge_preserves_string_dtype(self, windowed):
        rng = np.random.default_rng(12)
        left = pandas.DataFrame(
            {"k": rng.integers(0, 60, 7000), "lv": rng.integers(0, 9, 7000)}
        )
        right = pandas.DataFrame(
            {
                "k": rng.integers(0, 60, 2500),
                "tag": pandas.array(
                    rng.choice(["x", "y", "z"], 2500), dtype="string"
                ),
            }
        )
        ml, mr = pd.DataFrame(left), pd.DataFrame(right)
        with StreamMode.context("Resident"):
            resident = ml.merge(mr, on="k", how="inner")._to_pandas()
        with StreamMode.context("Windowed"), StreamWindowBytes.context(4096):
            streamed = ml.merge(mr, on="k", how="inner")._to_pandas()
        # the binding contract is bit-identity WITH THE RESIDENT PATH
        # (dtype included — the miss-free gather must not degrade string
        # columns to object when the resident path would not); values also
        # match pandas, whose extension-dtype preservation is the
        # documented pre-existing str-extension divergence family
        pandas.testing.assert_frame_equal(streamed, resident)
        assert streamed["tag"].dtype == resident["tag"].dtype
        pandas.testing.assert_frame_equal(
            streamed,
            left.merge(right, on="k", how="inner"),
            check_dtype=False,
        )

    def test_external_merge_empty_result(self, windowed):
        left = pandas.DataFrame(
            {"k": np.arange(6000), "lv": np.arange(6000)}
        )
        right = pandas.DataFrame(
            {"k": np.arange(6000) + 10_000_000, "rv": np.arange(6000)}
        )
        got = (
            pd.DataFrame(left)
            .merge(pd.DataFrame(right), on="k", how="inner")
            ._to_pandas()
        )
        pandas.testing.assert_frame_equal(
            got, left.merge(right, on="k", how="inner")
        )


# ---------------------------------------------------------------------- #
# 3. chaos
# ---------------------------------------------------------------------- #


class TestChaos:
    def test_midquery_device_loss_single_window_recovery(
        self, tmp_path, windowed, metric_counts
    ):
        from modin_tpu.testing.faults import midquery_device_loss

        df = _base_df(16000)
        path = _csv(tmp_path, df)
        expect = df[df["a"] > 0].groupby("k").sum()
        with ResilienceBackoffS.context(0.0):
            with midquery_device_loss(after_deploys=8, times=1) as inj:
                m = pd.read_csv(path)
                got = m[m["a"] > 0].groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(got, expect)
        assert inj.injected == 1
        assert metric_counts.get("recovery.device_lost", 0) >= 1
        windows = metric_counts.get("stream.window.count", 0)
        assert windows > 10
        # single-WINDOW recovery: only the live window's columns (plus at
        # most the prefetched neighbor and the handful of result columns)
        # were re-seated — a whole-dataset replay would re-seat one column
        # per window per source column (3 * windows)
        reseats = sum(
            v
            for k, v in metric_counts.items()
            if k.startswith("recovery.reseat.")
        )
        assert 1 <= reseats < windows

    def test_oom_burst_mid_stream_absorbed(
        self, tmp_path, windowed, metric_counts
    ):
        from modin_tpu.testing.faults import oom_burst_until_eviction

        df = _base_df(16000)
        path = _csv(tmp_path, df)
        expect = df[df["a"] > 0].groupby("k").sum()
        with ResilienceBackoffS.context(0.0):
            with oom_burst_until_eviction(spills=1) as inj:
                m = pd.read_csv(path)
                got = m[m["a"] > 0].groupby("k").sum()._to_pandas()
        pandas.testing.assert_frame_equal(got, expect)
        assert inj.injected >= 1
        assert metric_counts.get("memory.device.spill", 0) >= 1
        assert metric_counts.get("stream.window.count", 0) > 10

    def test_terminal_consume_failure_replays_one_window(
        self, tmp_path, windowed, metric_counts
    ):
        from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher
        from modin_tpu.core.execution.resilience import DeviceLost
        from modin_tpu.streaming import executor, windows as stream_windows

        path = _csv(tmp_path, _base_df(8000))
        source = stream_windows.WindowSource(
            TpuCSVDispatcher, {"filepath_or_buffer": path}, 2048
        )
        assert len(source) > 3
        failed = []
        consumed = []

        def consume(index, qc):
            if index == 2 and not failed:
                failed.append(True)
                raise DeviceLost("injected terminal mid-window loss")
            consumed.append(index)

        executor.window_loop(source, consume)
        assert sorted(consumed) == list(range(len(source)))
        assert metric_counts.get("stream.window.replay", 0) == 1
        assert metric_counts.get("stream.window.count", 0) == len(source)

    def test_terminal_prefetch_failure_finishes_serially(
        self, tmp_path, windowed, metric_counts, monkeypatch
    ):
        from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher
        from modin_tpu.core.execution.resilience import DeviceLost
        from modin_tpu.streaming import executor, windows as stream_windows

        path = _csv(tmp_path, _base_df(8000))
        source = stream_windows.WindowSource(
            TpuCSVDispatcher, {"filepath_or_buffer": path}, 2048
        )
        real_parse = source.parse_window
        failed = []

        def flaky_parse(index):
            if index == 3 and not failed:
                failed.append(True)
                raise DeviceLost("injected prefetch-side loss")
            return real_parse(index)

        monkeypatch.setattr(source, "parse_window", flaky_parse)
        consumed = []
        executor.window_loop(source, lambda i, qc: consumed.append(i))
        assert sorted(consumed) == list(range(len(source)))
        assert metric_counts.get("stream.window.replay", 0) == 1

    def test_mid_consume_replay_does_not_double_count(
        self, tmp_path, windowed, metric_counts, monkeypatch
    ):
        """A terminal loss AFTER a window's sum partial was recorded but
        BEFORE its count partial replays the window; partial state is keyed
        by window index, so the replay overwrites instead of appending —
        the mean must stay bit-exact (the old append-based state double-
        counted the window's sum)."""
        import modin_tpu.core.storage_formats.tpu.query_compiler as qcmod
        from modin_tpu.core.execution.resilience import DeviceLost

        df = _base_df(8000)
        path = _csv(tmp_path, df)
        orig = qcmod.TpuQueryCompiler.groupby_agg
        state = {"count_calls": 0, "tripped": False}

        def flaky(self, by, agg_func, *args, **kwargs):
            result = orig(self, by, agg_func, *args, **kwargs)
            if agg_func == "count" and not state["tripped"]:
                state["count_calls"] += 1
                if state["count_calls"] == 1:
                    state["tripped"] = True
                    raise DeviceLost(
                        "injected after the window's sum partial landed"
                    )
            return result

        monkeypatch.setattr(qcmod.TpuQueryCompiler, "groupby_agg", flaky)
        got = pd.read_csv(path).groupby("k").mean()._to_pandas()
        pandas.testing.assert_frame_equal(got, df.groupby("k").mean())
        assert state["tripped"]
        assert metric_counts.get("stream.window.replay", 0) == 1

    def test_non_device_errors_propagate(self, tmp_path, windowed):
        from modin_tpu.core.execution.jax_engine.io import TpuCSVDispatcher
        from modin_tpu.streaming import executor, windows as stream_windows

        path = _csv(tmp_path, _base_df(6000))
        source = stream_windows.WindowSource(
            TpuCSVDispatcher, {"filepath_or_buffer": path}, 2048
        )

        def consume(index, qc):
            if index == 1:
                raise ValueError("not a device problem")

        with pytest.raises(ValueError, match="not a device problem"):
            executor.window_loop(source, consume)


# ---------------------------------------------------------------------- #
# 4. routing & accounting units
# ---------------------------------------------------------------------- #


class TestRoutingAndAccounting:
    def test_decide_residency_forced_and_auto(self, metric_counts):
        from modin_tpu.ops import router

        with StreamMode.context("Resident"):
            assert router.decide_residency("sort", 1 << 60) == "resident"
        with StreamMode.context("Windowed"):
            assert router.decide_residency("sort", 1) == "windowed"
        with StreamMode.context("Auto"):
            if DeviceMemoryBudget.get() is None:  # the tier-1 default
                assert router.decide_residency("sort", 1 << 60) == "resident"
            with DeviceMemoryBudget.context(1 << 20):
                assert router.decide_residency("sort", 1 << 30) == "windowed"
                assert router.decide_residency("sort", 1 << 10) == "resident"
        assert metric_counts.get("router.residency_sort.windowed", 0) >= 2
        assert metric_counts.get("router.residency_sort.resident", 0) >= 3

    def test_decide_residency_self_bytes_discount(self):
        from modin_tpu.ops import router

        with StreamMode.context("Auto"), DeviceMemoryBudget.context(1 << 20):
            # an estimate just under budget fits when the op's own inputs
            # are discounted from the ledger total
            est = (1 << 20) - 1
            assert (
                router.decide_residency("merge", est, self_bytes=est)
                == router.decide_residency("merge", est, self_bytes=est)
            )

    def test_window_bytes_derivation(self):
        from modin_tpu.streaming import windows

        with StreamWindowBytes.context(12345):
            assert windows.window_bytes_for(1) == 12345
        with StreamWindowBytes.context(0):
            with DeviceMemoryBudget.context(1 << 26):
                # budget // (2 * expansion(4) * (1 + prefetch))
                assert windows.window_bytes_for(1) == (1 << 26) // 16
                assert windows.window_bytes_for(0) == (1 << 26) // 8
            if DeviceMemoryBudget.get() is None:  # the tier-1 default
                assert windows.window_bytes_for(1) == windows._MIN_WINDOW_BYTES

    def test_pow2_bucket(self):
        from modin_tpu.streaming.windows import pow2_bucket

        assert pow2_bucket(0) == 1024
        assert pow2_bucket(1000) == 1024
        assert pow2_bucket(1024) == 1024
        assert pow2_bucket(1025) == 2048
        assert pow2_bucket(100_000) == 1 << 17

    def test_scan_cache_evicts_by_bytes(self, tmp_path, metric_counts):
        df = _base_df(4000)
        path = _csv(tmp_path, df)
        with PlanScanCacheBytes.context(1):
            got = pd.read_csv(path)[["v"]].sum()._to_pandas()
        pandas.testing.assert_series_equal(got, df[["v"]].sum())
        # a 1-byte bound evicts every materialized entry immediately
        assert metric_counts.get("plan.scan.cache_evict", 0) >= 1

    def test_scan_cache_zero_disables_caching(self, tmp_path, metric_counts):
        df = _base_df(4000)
        path = _csv(tmp_path, df)
        with PlanScanCacheBytes.context(0):
            got = pd.read_csv(path)[["v"]].sum()._to_pandas()
        pandas.testing.assert_series_equal(got, df[["v"]].sum())
        assert metric_counts.get("plan.scan.cache_evict", 0) == 0
        assert metric_counts.get("plan.scan.cache_hit", 0) == 0

    def test_query_stats_window_fields(self, tmp_path, windowed):
        from modin_tpu.observability import meters as graftmeter

        df = _base_df()
        path = _csv(tmp_path, df)
        with graftmeter.query_stats("stream-test") as stats:
            m = pd.read_csv(path)
            m[m["a"] > 0].groupby("k").sum()._to_pandas()
        assert stats.stream_windows > 1
        assert stats.stream_replays == 0
        assert stats.stream_overlap_s >= 0.0
        rolled = stats.as_dict()
        assert rolled["stream_windows"] == stats.stream_windows
        assert "stream_overlap_s" in rolled
        assert stats.hbm_high_water > 0
        assert "stream:" in stats.summary()

    def test_gate_bills_window_footprint_not_dataset(self):
        from modin_tpu.observability.meters import QueryStats
        from modin_tpu.serving import gate as serving_gate
        from modin_tpu.serving import tenants as _tenants

        streamed = QueryStats("s")
        streamed.est_bytes = 10.0 ** 12  # dataset-scale traffic estimate
        streamed.hbm_high_water = 4096  # the real window footprint
        streamed.stream_windows = 7
        serving_gate._finish_accounting(
            "stream_bill_tenant_a", streamed, 0.1, None
        )
        billed = _tenants.registry.cost_estimate("stream_bill_tenant_a", 0.0)
        assert billed < 10.0 ** 6, billed

        resident = QueryStats("r")
        resident.est_bytes = 10.0 ** 12
        resident.hbm_high_water = 4096
        serving_gate._finish_accounting(
            "stream_bill_tenant_b", resident, 0.1, None
        )
        billed_resident = _tenants.registry.cost_estimate(
            "stream_bill_tenant_b", 0.0
        )
        assert billed_resident > 10.0 ** 9, billed_resident
