"""Device corr/cov tests (masked-matmul kernels, differential vs pandas)."""

import warnings

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import create_test_dfs, df_equals

_rng = np.random.default_rng(13)
N = 2000


@pytest.fixture
def dfs():
    data = {
        "a": _rng.normal(size=N),
        "b": np.where(_rng.random(N) < 0.25, np.nan, _rng.normal(size=N)),
        "i": _rng.integers(-5, 5, N),
        "flag": _rng.random(N) < 0.4,
    }
    return create_test_dfs(data)


def _no_fallback(fn):
    from tests.utils import assert_no_fallback

    return assert_no_fallback(fn)


def test_corr_device(dfs):
    md, pdf = dfs
    df_equals(_no_fallback(lambda: md.corr()), pdf.corr())


def test_cov_device(dfs):
    md, pdf = dfs
    df_equals(_no_fallback(lambda: md.cov()), pdf.cov())


@pytest.mark.parametrize("ddof", [0, 1, 2])
def test_cov_ddof(dfs, ddof):
    md, pdf = dfs
    # pandas ignores ddof when NaNs force the pairwise path — both cases
    df_equals(_no_fallback(lambda: md.cov(ddof=ddof)), pdf.cov(ddof=ddof))
    md2, pdf2 = create_test_dfs({"x": _rng.normal(size=64), "y": _rng.normal(size=64)})
    df_equals(_no_fallback(lambda: md2.cov(ddof=ddof)), pdf2.cov(ddof=ddof))


def test_corr_min_periods(dfs):
    md, pdf = dfs
    df_equals(
        _no_fallback(lambda: md.corr(min_periods=1800)),
        pdf.corr(min_periods=1800),
    )


def test_corr_constant_column():
    md, pdf = create_test_dfs({"a": np.arange(32.0), "const": np.ones(32)})
    df_equals(_no_fallback(lambda: md.corr()), pdf.corr())


def test_corr_non_pearson_falls_back(dfs):
    md, pdf = dfs
    df_equals(md[["a", "b"]].corr(method="spearman"), pdf[["a", "b"]].corr(method="spearman"))


def test_series_corr_cov(dfs):
    md, pdf = dfs
    np.testing.assert_allclose(md["a"].corr(md["b"]), pdf["a"].corr(pdf["b"]))
    np.testing.assert_allclose(md["a"].cov(md["b"]), pdf["a"].cov(pdf["b"]))
