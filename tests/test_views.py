"""graftview acceptance suite: the derived-artifact registry, incremental
maintenance over appended batches, and cross-query sharing.

Covers the PR's tentpole contract:

- whole-result reuse (scalar aggs, nunique/mode/median, groupby tables)
  with results bit-exact vs pandas and identical to the Off path;
- append-only folds (algebraic scalar combines, groupby partial tables,
  dictionary code-table extension) dispatching only the delta;
- eager invalidation under every buffer mutation + honest
  ``not_incremental`` invalidation for non-foldable artifacts;
- ledger-pressure drops ordered derived-first;
- chaos: DeviceLost mid-fold recovers bit-exact with zero
  ``recovery.unrecoverable``;
- the stale-write guard between lookup and commit under concurrent
  buffer mutation.
"""

import threading

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu.config import ViewsMaxGroups, ViewsMode
from modin_tpu.logging.metrics import add_metric_handler, clear_metric_handler
from modin_tpu.views import incremental, registry

from tests.utils import df_equals, require_tpu_execution


@pytest.fixture(autouse=True)
def _tpu_only():
    require_tpu_execution()
    registry.reset()
    yield
    registry.reset()


@pytest.fixture
def metric_log():
    events = []

    def handler(name, value):
        events.append((name, value))

    add_metric_handler(handler)
    yield events
    clear_metric_handler(handler)


def _count(events, name):
    return sum(1 for n, _ in events if n == f"modin_tpu.{name}")


def _count_prefix(events, prefix):
    return sum(1 for n, _ in events if n.startswith(f"modin_tpu.{prefix}"))


def _device_col(mdf, label):
    frame = mdf._query_compiler._modin_frame
    return frame.get_column(list(frame.columns).index(label))


def _frames(n=400, seed=7):
    rng = np.random.default_rng(seed)
    pdf = pandas.DataFrame(
        {
            "i": rng.integers(-1000, 1000, n),
            "f": np.where(rng.random(n) < 0.2, np.nan, rng.normal(size=n)),
            "b": rng.random(n) < 0.5,
        }
    )
    return pd.DataFrame(pdf), pdf


def _tails(n=120, seed=8):
    rng = np.random.default_rng(seed)
    return pandas.DataFrame(
        {
            "i": rng.integers(-1000, 1000, n),
            "f": np.where(rng.random(n) < 0.2, np.nan, rng.normal(size=n)),
            "b": rng.random(n) < 0.5,
        }
    )


class TestWholeResultReuse:
    def test_second_query_is_artifact_hit(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        builds = _count(metric_log, "view.build")
        assert builds >= 3  # one artifact per column
        hits_before = _count(metric_log, "view.hit")
        df_equals(mdf.sum(), pdf.sum())
        assert _count(metric_log, "view.hit") >= hits_before + 3
        assert _count(metric_log, "view.build") == builds  # nothing recomputed

    @pytest.mark.parametrize(
        "op", ["sum", "mean", "min", "max", "count", "prod", "var", "std",
               "median", "any", "all"]
    )
    def test_scalar_ops_cached_and_correct(self, op):
        mdf, pdf = _frames()
        df_equals(getattr(mdf, op)(), getattr(pdf, op)())
        df_equals(getattr(mdf, op)(), getattr(pdf, op)())  # warm

    def test_nunique_mode_cached(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.nunique(), pdf.nunique())
        df_equals(mdf.mode(), pdf.mode())
        hits_before = _count(metric_log, "view.hit")
        df_equals(mdf.nunique(), pdf.nunique())
        df_equals(mdf.mode(), pdf.mode())
        assert _count(metric_log, "view.hit") > hits_before

    def test_groupby_result_cached(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.groupby("b").sum(), pdf.groupby("b").sum())
        hits_before = _count(metric_log, "view.hit")
        df_equals(mdf.groupby("b").sum(), pdf.groupby("b").sum())
        assert _count(metric_log, "view.hit") > hits_before

    def test_cross_thread_sharing(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())  # seed the artifacts on this thread
        results = {}

        def worker():
            results["sum"] = mdf.sum()

        t = threading.Thread(target=worker)
        hits_before = _count(metric_log, "view.hit")
        t.start()
        t.join()
        df_equals(results["sum"], pdf.sum())
        assert _count(metric_log, "view.hit") >= hits_before + 3

    def test_query_stats_rollup(self):
        from modin_tpu.observability import query_stats

        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        with query_stats("warm") as qs:
            df_equals(mdf.sum(), pdf.sum())
        assert qs.view_hits >= 3
        assert "views:" in qs.summary()


class TestIncrementalFolds:
    def _append(self, mdf, pdf, tail):
        mdf2 = pd.concat([mdf, pd.DataFrame(tail)], ignore_index=True)
        pdf2 = pandas.concat([pdf, tail], ignore_index=True)
        return mdf2, pdf2

    @pytest.mark.parametrize("op", ["sum", "count", "min", "max", "prod"])
    def test_fold_bit_exact_int(self, metric_log, op):
        mdf, pdf = _frames()
        getattr(mdf, op)()
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        folds_before = _count(metric_log, "view.fold")
        got = getattr(mdf2, op)()
        assert _count(metric_log, "view.fold") > folds_before
        expect = getattr(pdf2, op)()
        # integer/bool columns: the fold is bit-exact, not just tolerant
        assert got["i"] == expect["i"]
        df_equals(got, expect)

    @pytest.mark.parametrize("op", ["mean", "sum", "min", "max", "count"])
    def test_fold_float_matches_pandas(self, metric_log, op):
        mdf, pdf = _frames()
        getattr(mdf, op)()
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        folds_before = _count(metric_log, "view.fold")
        df_equals(getattr(mdf2, op)(), getattr(pdf2, op)())
        assert _count(metric_log, "view.fold") > folds_before

    def test_fold_matches_views_off(self):
        """The cache must be invisible: Auto-after-append == Off."""
        mdf, pdf = _frames()
        mdf.sum(), mdf.mean(), mdf.min()
        tail = _tails()
        mdf2, pdf2 = self._append(mdf, pdf, tail)
        auto = {op: getattr(mdf2, op)() for op in ("sum", "mean", "min")}
        before = ViewsMode.get()
        ViewsMode.put("Off")
        try:
            registry.reset()
            m_off = pd.DataFrame(pdf2)
            off = {op: getattr(m_off, op)() for op in ("sum", "mean", "min")}
        finally:
            ViewsMode.put(before)
        for op in auto:
            # sum/min fold bit-exact on the int column; mean re-associates
            # the fp accumulation (documented contract) and floats compare
            # at the differential tolerance
            if op != "mean":
                assert auto[op]["i"] == off[op]["i"], op
            df_equals(auto[op], off[op])

    def test_chained_appends_fold_twice(self, metric_log):
        mdf, pdf = _frames()
        mdf.sum()
        mdf2, pdf2 = self._append(mdf, pdf, _tails(seed=21))
        mdf2.sum()
        mdf3, pdf3 = self._append(mdf2, pdf2, _tails(seed=22))
        folds_before = _count(metric_log, "view.fold")
        df_equals(mdf3.sum(), pdf3.sum())
        assert _count(metric_log, "view.fold") > folds_before

    def test_branching_appends_never_cross(self):
        """Two different appends onto one parent, folded from two
        concurrent serving sessions: each branch's fold must answer for
        ITS tail (fresh child tokens prevent contamination).  Dispatch
        rides serving.submit — the collective-safe path for concurrent
        threads on the sharded mesh (PR 9)."""
        import modin_tpu.serving as serving
        from modin_tpu.config import ServingEnabled

        mdf, pdf = _frames()
        mdf.sum()
        tail_a, tail_b = _tails(seed=31), _tails(seed=32)
        mdf_a, pdf_a = self._append(mdf, pdf, tail_a)
        mdf_b, pdf_b = self._append(mdf, pdf, tail_b)
        barrier = threading.Barrier(2, timeout=30)
        results = {}
        serving_before = ServingEnabled.get()
        ServingEnabled.put(True)
        try:

            def run(name, frame):
                barrier.wait()
                results[name] = serving.submit(
                    frame.sum, tenant=name, deadline_ms=0
                )

            ts = [
                threading.Thread(target=run, args=("a", mdf_a)),
                threading.Thread(target=run, args=("b", mdf_b)),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            ServingEnabled.put(serving_before)
        assert results["a"]["i"] == pdf_a.sum()["i"]
        assert results["b"]["i"] == pdf_b.sum()["i"]
        df_equals(results["a"], pdf_a.sum())
        df_equals(results["b"], pdf_b.sum())

    def test_non_incremental_keeps_live_parent_warm(self, metric_log):
        """An append reaching a non-foldable artifact must not destroy the
        LIVE parent's warm answer: the child misses and recomputes, the
        parent keeps hitting."""
        mdf, pdf = _frames()
        df_equals(mdf.median(), pdf.median())
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        inval_before = _count(metric_log, "view.invalidate.not_incremental")
        df_equals(mdf2.median(), pdf2.median())
        assert _count(metric_log, "view.invalidate.not_incremental") == inval_before
        hits_before = _count(metric_log, "view.hit")
        df_equals(mdf.median(), pdf.median())  # the parent is still warm
        assert _count(metric_log, "view.hit") > hits_before

    def test_non_incremental_invalidates_honestly_once_parent_dies(
        self, metric_log
    ):
        import gc

        mdf, pdf = _frames()
        df_equals(mdf.median(), pdf.median())
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        del mdf  # the pre-append frame is gone: the artifact is dead weight
        gc.collect()
        inval_before = _count(metric_log, "view.invalidate.not_incremental")
        df_equals(mdf2.median(), pdf2.median())
        assert _count(metric_log, "view.invalidate.not_incremental") > inval_before

    def test_groupby_folds(self, metric_log):
        mdf, pdf = _frames()
        for agg in ("sum", "count", "mean", "min", "max"):
            getattr(mdf.groupby("b"), agg)()
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        folds_before = _count(metric_log, "view.fold")
        for agg in ("sum", "count", "mean", "min", "max"):
            df_equals(
                getattr(mdf2.groupby("b"), agg)(),
                getattr(pdf2.groupby("b"), agg)(),
            )
        assert _count(metric_log, "view.fold") > folds_before

    def test_groupby_size_and_selection(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.groupby("b").size(), pdf.groupby("b").size())
        df_equals(mdf.groupby("b")["i"].sum(), pdf.groupby("b")["i"].sum())
        mdf2, pdf2 = self._append(mdf, pdf, _tails())
        df_equals(mdf2.groupby("b").size(), pdf2.groupby("b").size())
        df_equals(mdf2.groupby("b")["i"].sum(), pdf2.groupby("b")["i"].sum())

    def test_groupby_bound_declines_large_cardinality(self, metric_log):
        before = ViewsMaxGroups.get()
        ViewsMaxGroups.put(8)
        try:
            rng = np.random.default_rng(5)
            pdf = pandas.DataFrame(
                {"k": rng.integers(0, 64, 500), "v": rng.integers(0, 9, 500)}
            )
            mdf = pd.DataFrame(pdf)
            builds_before = _count(metric_log, "view.build")
            df_equals(mdf.groupby("k").sum(), pdf.groupby("k").sum())
            # 64 groups > bound of 8: no groupby artifact may be cached
            # (the per-column scalar artifacts are a different kind)
            assert not any(
                art.kind == "groupby" for art in registry.live_artifacts()
            ), builds_before
        finally:
            ViewsMaxGroups.put(before)

    def test_dictionary_code_table_extension(self, metric_log):
        pdf = pandas.DataFrame(
            {
                "city": ["lima", "oslo", None, "lima", "oslo", "lima"],
                "n": np.arange(6, dtype=np.int64),
            }
        )
        mdf = pd.DataFrame(pdf)
        # seed the encoding (nunique factorizes the string column)
        df_equals(mdf.nunique(), pdf.nunique())
        tail = pandas.DataFrame(
            {"city": ["pune", "lima", None], "n": np.arange(3, dtype=np.int64)}
        )
        folds_before = _count(metric_log, "view.fold")
        mdf2 = pd.concat([mdf, pd.DataFrame(tail)], ignore_index=True)
        pdf2 = pandas.concat([pdf, tail], ignore_index=True)
        assert _count(metric_log, "view.fold") > folds_before
        # the extended encoding must answer EXACTLY like a fresh factorize
        col = mdf2._query_compiler._modin_frame.get_column(0)
        enc = col._dict_cache
        assert enc is not None and enc is not False
        assert list(enc.categories) == ["lima", "oslo", "pune"]
        assert enc.has_nan
        codes = np.asarray(enc.codes.to_numpy(), dtype=np.float64)
        expect_codes, expect_cats = pandas.factorize(
            np.asarray(pdf2["city"], dtype=object), sort=True,
            use_na_sentinel=True,
        )
        np.testing.assert_array_equal(
            np.where(np.isnan(codes), -1, codes).astype(np.int64), expect_codes
        )
        df_equals(mdf2.nunique(), pdf2.nunique())
        df_equals(
            mdf2.groupby("city").sum(), pdf2.groupby("city").sum()
        )


class TestInvalidation:
    def test_setitem_misses_cleanly(self):
        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        mdf["i"] = mdf["i"] * 2
        pdf["i"] = pdf["i"] * 2
        df_equals(mdf.sum(), pdf.sum())
        df_equals(mdf.mean(), pdf.mean())

    def test_spill_restore_invalidates(self, metric_log):
        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        col = _device_col(mdf, "i")
        assert col.spill() > 0
        assert _count_prefix(metric_log, "view.invalidate.") >= 1
        assert col.raw is not None  # transparent restore
        df_equals(mdf.sum(), pdf.sum())

    def test_reseat_invalidates(self):
        mdf, pdf = _frames()
        df_equals(mdf.max(), pdf.max())
        col = _device_col(mdf, "i")
        col.reseat_from_host()
        assert registry.lookup(col, "reduce", ("max", True, 1, False))[0] == "miss"
        assert registry.lookup(col, "reduce", ("max", True, 1, True))[0] == "miss"
        df_equals(mdf.max(), pdf.max())

    def test_recovery_pass_drops_artifacts(self, metric_log):
        from modin_tpu.core.execution import recovery

        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        assert len(registry.live_artifacts()) >= 3
        recovery.reseat_all("test-views-epoch-bump")
        # the epoch bump makes every artifact stale; queries stay correct
        # and nothing counts unrecoverable
        df_equals(mdf.sum(), pdf.sum())
        assert _count(metric_log, "recovery.unrecoverable") == 0

    def test_pressure_drops_artifacts_before_columns(self):
        from modin_tpu.core.memory import device_ledger

        mdf, pdf = _frames(n=1024)
        df_equals(mdf.median(), pdf.median())  # builds sorted reps
        reps = [
            e for e in device_ledger.live_columns()
            if getattr(e, "is_derived_cache", False)
        ]
        assert reps
        cols = [_device_col(mdf, c) for c in ("i", "f", "b")]
        freed = device_ledger.spill_lru(1)  # tiny target: one entry
        assert freed > 0
        # a derived cache paid the pressure; every real column is resident
        assert all(not c.is_spilled for c in cols)
        df_equals(mdf.median(), pdf.median())


class TestChaos:
    def test_device_lost_mid_fold_recovers_bit_exact(self, metric_log):
        from modin_tpu.testing.faults import midquery_device_loss

        mdf, pdf = _frames()
        mdf.sum()
        tail = _tails()
        mdf2 = pd.concat([mdf, pd.DataFrame(tail)], ignore_index=True)
        pdf2 = pandas.concat([pdf, tail], ignore_index=True)
        # the fold's FIRST dispatch (the tail gather) dies; recovery
        # re-seats and the retry answers bit-exact
        with midquery_device_loss(after_deploys=0, times=1):
            got = mdf2.sum()
        assert got["i"] == pdf2.sum()["i"]
        df_equals(got, pdf2.sum())
        assert _count(metric_log, "recovery.unrecoverable") == 0
        # artifacts from the dead epoch never serve afterwards
        df_equals(mdf2.mean(), pdf2.mean())


class TestStaleWriteGuard:
    def test_store_declines_on_spilled_buffer(self):
        mdf, pdf = _frames()
        df_equals(mdf.sum(), pdf.sum())
        col = _device_col(mdf, "i")
        params = ("sum", True, 1, True)  # sum casts bools in-fusion
        outcome, state, _ = registry.lookup(col, "reduce", params)
        assert outcome == "hit"
        # simulate the racer: the buffer mutates between lookup and commit
        assert col.spill() > 0
        assert registry.store(col, "reduce", params, dict(state)) is False

    def test_concurrent_append_and_spill_stress(self):
        """The PR 9 sorted-rep tear class, graftview edition: one thread
        folds over an appended child while another spills the child's
        buffer.  Every answer must equal pandas; a racer's commit becomes
        a no-op, never a stale artifact."""
        import modin_tpu.serving as serving
        from modin_tpu.config import ServingEnabled

        serving_before = ServingEnabled.get()
        ServingEnabled.put(True)
        try:
            for round_ in range(6):
                registry.reset()
                mdf, pdf = _frames(seed=100 + round_)
                mdf.sum()
                tail = _tails(seed=200 + round_)
                mdf2 = pd.concat([mdf, pd.DataFrame(tail)], ignore_index=True)
                pdf2 = pandas.concat([pdf, tail], ignore_index=True)
                col = _device_col(mdf2, "i")
                barrier = threading.Barrier(2, timeout=30)
                out = {}

                def fold():
                    barrier.wait()
                    out["sum"] = serving.submit(
                        mdf2.sum, tenant="fold", deadline_ms=0
                    )

                def spill():
                    barrier.wait()
                    col.spill()

                ts = [
                    threading.Thread(target=fold),
                    threading.Thread(target=spill),
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                self._check_round(out, pdf2, col)
        finally:
            ServingEnabled.put(serving_before)

    @staticmethod
    def _check_round(out, pdf2, col):
        assert out["sum"]["i"] == pdf2.sum()["i"]
        df_equals(out["sum"], pdf2.sum())
        # whatever the interleaving, no live artifact may claim a
        # buffer the column no longer holds
        for art in registry.live_artifacts():
            if art.token == col._view_token and art.kind == "reduce":
                assert art.source_id == id(col._data)


class TestOffMode:
    def test_off_is_inert_and_identical(self):
        before = ViewsMode.get()
        ViewsMode.put("Off")
        try:
            registry.reset()
            mdf, pdf = _frames()
            df_equals(mdf.sum(), pdf.sum())
            df_equals(mdf.groupby("b").mean(), pdf.groupby("b").mean())
            mdf2 = pd.concat([mdf, pd.DataFrame(_tails())], ignore_index=True)
            pdf2 = pandas.concat([pdf, _tails()], ignore_index=True)
            df_equals(mdf2.sum(), pdf2.sum())
            assert registry.stats()["entries"] == 0
        finally:
            ViewsMode.put(before)
