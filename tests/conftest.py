"""Test configuration.

Mirrors the reference's strategy (SURVEY.md §4): the suite runs once per
execution selected by MODIN_TPU_ENGINE/MODIN_TPU_STORAGE_FORMAT.  Default for
the suite is the Tpu storage format on a virtual 8-device CPU mesh so sharding
and collectives are exercised without TPU hardware
(xla_force_host_platform_device_count=8).
"""

import os

# Must happen before jax import: virtual 8-device CPU mesh for sharding tests.
# Forced (not setdefault): differential tests need exact float64, and TPU f64
# is double-float emulated (~2^-49 relative precision, float32 exponent range).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# audit every dispatcher read for fd leaks throughout the suite (the config
# defaults off in production; see TrackFileLeaks)
os.environ.setdefault("MODIN_TPU_TEST_TRACK_FILE_LEAKS", "True")

import jax  # noqa: E402

# The axon TPU plugin in this image overrides JAX_PLATFORMS from the
# environment; the explicit config update wins.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# XLA:CPU's compiler segfaults on a FRESH compile late in a very long
# process (reproduced deterministically past ~1770 tests: first in an ewm
# scan compile, then — with that test skipped — in the xgboost trainer's;
# every victim passes standalone).  Dropping the accumulated live
# executables every few hundred tests keeps the compiler healthy at the
# cost of some recompilation.
_CLEAR_EVERY = 300
_test_counter = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_jax_cache_clear():
    yield
    _test_counter["n"] += 1
    if _test_counter["n"] % _CLEAR_EVERY == 0:
        jax.clear_caches()


def pytest_addoption(parser):
    parser.addoption(
        "--execution",
        action="store",
        default=None,
        help="storage_format}On{engine to run the suite under (e.g. TpuOnJax, NativeOnNative)",
    )


def pytest_configure(config):
    execution = config.getoption("--execution") or os.environ.get(
        "MODIN_TPU_TEST_EXECUTION", "TpuOnJax"
    )
    import re

    match = re.match(r"^(.*)On(.*)$", execution)
    storage_format, engine = match.groups()
    from modin_tpu.config import Engine, StorageFormat

    StorageFormat.put(storage_format)
    Engine.put(engine)


@pytest.fixture
def enable_benchmark_mode():
    from modin_tpu.config import BenchmarkMode

    with BenchmarkMode.context(True):
        yield
