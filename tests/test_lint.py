"""Tier-1 acceptance for graftlint (modin_tpu/lint/).

Two layers:

1. the real tree: ``python -m modin_tpu.lint modin_tpu/`` must be clean —
   zero non-baselined findings with all five rules active (the PR-1 seam
   invariants are enforced, not aspirational);
2. each rule is unit-tested against small positive AND negative snippets in
   throwaway trees mirroring the package layout, plus the framework's
   pragma and baseline suppression behavior.
"""

import pathlib
import subprocess
import sys
import textwrap

import pytest

from modin_tpu.lint import all_rules, run_lint
from modin_tpu.lint.framework import write_baseline

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
BASELINE = REPO_ROOT / ".graftlint-baseline"

ALL_RULE_IDS = {
    "HOST-SYNC",
    "JIT-HAZARD",
    "FALLBACK-PARITY",
    "EXC-HYGIENE",
    "REGISTRY-DRIFT",
    "LOCK-ORDER",
    "LOCK-BLOCKING",
    "THREAD-HYGIENE",
}


def lint_tree(tmp_path, files, select=None, baseline=None):
    """Materialize ``{relpath: source}`` under tmp_path and lint it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint([tmp_path], root=tmp_path, select=select, baseline=baseline)


def rules_hit(result):
    return {f.rule for f in result.findings}


# ---------------------------------------------------------------------- #
# the real tree
# ---------------------------------------------------------------------- #


def test_all_rules_registered():
    assert ALL_RULE_IDS <= set(all_rules())


def test_full_tree_is_clean():
    result = run_lint(["modin_tpu"], root=REPO_ROOT, baseline=BASELINE)
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        "graftlint violations in modin_tpu/ (fix them, pragma them with a "
        "reason, or — for intentional burn-downs only — baseline them):\n"
        + rendered
    )
    assert not result.stale_baseline, (
        "stale baseline entries (the violation is gone; remove the line): "
        f"{result.stale_baseline}"
    )


def test_cli_runs_clean_and_prints_summary():
    proc = subprocess.run(
        [sys.executable, "-m", "modin_tpu.lint", "modin_tpu/"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint: 0 finding(s)" in proc.stdout


# ---------------------------------------------------------------------- #
# HOST-SYNC
# ---------------------------------------------------------------------- #


def test_host_sync_flags_raw_seam_primitives(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def fetch(x):
                jax.block_until_ready(x).block_until_ready()
                return jax.device_get(x)
            """
        },
        select=["HOST-SYNC"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "device_get" in symbols
    assert "block_until_ready" in symbols


def test_host_sync_flags_device_value_coercion(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax.numpy as jnp
            import numpy as np

            def f(col, n):
                total = jnp.sum(col)
                flag = _jit_prep(n)(col)
                a = float(total)          # BAD: device scalar coercion
                b = bool(flag)            # BAD: jit-output coercion
                c = np.asarray(jnp.cumsum(col))   # BAD: direct asarray
                d = total.item()          # BAD: item() sync
                return a, b, c, d
            """
        },
        select=["HOST-SYNC"],
    )
    lines = sorted(f.line for f in result.findings)
    assert len(result.findings) == 4, [f.render() for f in result.findings]
    assert lines == [8, 9, 10, 11]


def test_host_sync_negative_materialized_and_metadata(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax.numpy as jnp
            import numpy as np
            from modin_tpu.parallel.engine import materialize as _engine_materialize

            def f(col, n):
                total = jnp.sum(col)
                host = _engine_materialize(total)
                a = float(host)                  # ok: host value
                b = int(total.shape[0])          # ok: static metadata
                positions, counts = _engine_materialize(_jit_k(n)(col))
                c = np.asarray(positions[: 3])   # ok: materialized upstream
                is_f = jnp.issubdtype(col.dtype, jnp.floating)
                d = bool(is_f)                   # ok: issubdtype is host
                return a, b, c, d
            """
        },
        select=["HOST-SYNC"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_host_sync_stream_leg_flags_captured_whole_frame(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            from modin_tpu.parallel.engine import materialize
            from modin_tpu.streaming import window_body

            def run(frame, source):
                @window_body
                def consume(index, qc):
                    part = qc.to_numpy()        # ok: the window itself
                    whole = frame.to_numpy()    # BAD: captured frame forced
                    vals = materialize(frame)   # BAD: captured materialize
                    cache = frame.host_cache    # BAD: captured host_cache
                    return part, whole, vals, cache
                return consume
            """
        },
        select=["HOST-SYNC"],
    )
    symbols = {f.symbol for f in result.findings}
    assert symbols == {
        "stream-consume-to_numpy",
        "stream-consume-materialize",
        "stream-consume-host_cache",
    }, [f.render() for f in result.findings]


def test_host_sync_stream_leg_negative(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            from modin_tpu.parallel.engine import materialize
            from modin_tpu.streaming import window_body

            def run(frame, source):
                whole = frame.to_numpy()  # ok: OUTSIDE the window loop

                @window_body
                def consume(index, qc):
                    # the window handed in (and anything derived from it)
                    # is the body's to force
                    child = qc.filtered()
                    vals = child.to_numpy()
                    host = materialize(vals)
                    cache = qc.host_cache
                    for col in child.columns:
                        piece = col.to_numpy()
                    return host, cache, piece
                return consume, whole
            """
        },
        select=["HOST-SYNC"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_host_sync_exempts_seam_modules(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/parallel/engine.py": """
            import jax

            def materialize(refs):
                return jax.device_get(refs)
            """
        },
        select=["HOST-SYNC"],
    )
    assert not result.findings


# ---------------------------------------------------------------------- #
# JIT-HAZARD
# ---------------------------------------------------------------------- #


def test_jit_hazard_positive_all_four_classes(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax
            import jax.numpy as jnp

            _TABLE = {"a": 1}

            def make():
                def fn(x, k):
                    if k > 0:                 # BAD: traced control flow
                        x = x + 1
                    out = jnp.zeros(k)        # BAD: traced shape
                    m = jnp.sum(x)
                    for i in range(m):        # BAD: traced range
                        out = out + i
                    return out + _TABLE["a"]  # BAD: mutable closure
                return jax.jit(fn)
            """
        },
        select=["JIT-HAZARD"],
    )
    symbols = {f.symbol for f in result.findings}
    assert {"fn-branch-if", "fn-shape-zeros", "fn-shape-range", "fn-closure-_TABLE"} <= symbols


def test_jit_hazard_negative_statics_and_metadata(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax
            import jax.numpy as jnp
            from functools import partial

            def make(n, width):
                def fn(x, k):
                    if width > 4:                # ok: closure constant
                        x = x * 2
                    L = x.shape[0]               # ok: static metadata
                    out = jnp.zeros(L) + jnp.zeros(k)   # ok: k is static
                    if jnp.issubdtype(x.dtype, jnp.floating):  # ok: dtype
                        out = out + 1
                    flag = jnp.isnan(x) if n else None
                    if flag is not None:         # ok: identity vs None
                        out = out + flag
                    g = jnp.broadcast_to(x[:, None], out.shape)  # ok: .shape
                    return out, g
                return jax.jit(fn, static_argnums=(1,))

            @partial(jax.jit, static_argnames=("k",))
            def decorated(x, k):
                return jnp.zeros(k) + x          # ok: static by name
            """
        },
        select=["JIT-HAZARD"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_jit_hazard_flags_collective_outside_shard_map(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax
            import jax.numpy as jnp

            def bad_reduce(x):
                # BAD: collective with no shard_map body in sight
                return jax.lax.psum(x, "rows")

            def make():
                def fn(x):
                    # BAD: still outside any shard_map body (plain jit)
                    return jax.lax.all_to_all(
                        x, "rows", split_axis=0, concat_axis=0
                    )
                return jax.jit(fn)
            """
        },
        select=["JIT-HAZARD"],
    )
    symbols = {f.symbol for f in result.findings}
    assert {"collective-psum", "collective-all_to_all"} <= symbols


def test_jit_hazard_collective_inside_shard_map_is_clean(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def make(mesh, P):
                def local_fn(shard):
                    def route(col):
                        # ok: nested inside the shard_map body
                        return jax.lax.all_to_all(
                            col, "rows", split_axis=0, concat_axis=0
                        )
                    total = jax.lax.psum(shard, "rows")  # ok
                    return route(shard) + total
                return jax.jit(shard_map(local_fn, mesh=mesh, in_specs=P))
            """
        },
        select=["JIT-HAZARD"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_jit_hazard_flags_collective_under_traced_conditional(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax
            import jax.numpy as jnp

            def make(mesh):
                def local_fn(shard):
                    if jnp.sum(shard) > 0:  # BAD: traced branch...
                        # ...with a collective inside: per-device branch
                        # divergence deadlocks the rendezvous
                        shard = jax.lax.psum(shard, "rows")
                    return shard
                return shard_map(local_fn, mesh=mesh)
            """
        },
        select=["JIT-HAZARD"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "local_fn-collective-branch-psum" in symbols
    # the plain traced-branch finding fires too (same If, distinct symbol)
    assert "local_fn-branch-if" in symbols


def test_jit_hazard_sees_through_shard_map(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def make(mesh):
                def local_fn(shard, k):
                    if shard > 0:     # BAD: traced branch inside shard_map
                        shard = -shard
                    return shard
                return jax.jit(shard_map(local_fn, mesh=mesh))
            """
        },
        select=["JIT-HAZARD"],
    )
    assert {f.symbol for f in result.findings} == {"local_fn-branch-if"}


def test_jit_hazard_flags_read_after_donated_position(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def run(g, x, y):
                f = jax.jit(g, donate_argnums=(0,))
                out = f(x, y)
                return out + x    # BAD: x's buffer was donated to f
            """
        },
        select=["JIT-HAZARD"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "donated-x" in symbols
    # y was NOT in a donated position
    assert "donated-y" not in symbols


def test_jit_hazard_flags_same_line_read_after_donated_call(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def run(g, x):
                f = jax.jit(g, donate_argnums=(0,))
                return f(x) + x    # BAD: read right after the call consumed x
            """
        },
        select=["JIT-HAZARD"],
    )
    assert "donated-x" in {f.symbol for f in result.findings}


def test_jit_hazard_donation_uses_earliest_consuming_call(tmp_path):
    """ast.walk is BFS: a nested (earlier-in-source) donated call must
    still anchor the consumption point, or a read between two calls slips
    through."""
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def run(g, x, cond):
                f = jax.jit(g, donate_argnums=(0,))
                if cond:
                    f(x)          # nested: consumed HERE first
                probe = x + 1     # BAD: read after the nested donated call
                return f(x), probe
            """
        },
        select=["JIT-HAZARD"],
    )
    lines = {
        f.line for f in result.findings if f.symbol == "donated-x"
    }
    assert 8 in lines, result.findings  # the `probe = x + 1` load


def test_jit_hazard_donation_negative_cases(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def clean_before(g, x):
                pre = x + 1            # read BEFORE the donated call: fine
                f = jax.jit(g, donate_argnums=(0,))
                return f(x) + pre

            def clean_rebind(g, x):
                f = jax.jit(g, donate_argnums=(0,))
                out = f(x)
                x = out * 2            # rebind: the name no longer holds
                return x + 1           # the donated buffer

            def clean_self_rebind(g, x):
                f = jax.jit(g, donate_argnums=(0,))
                x = f(x)               # the idiomatic donation pattern:
                return x + 1           # x now holds the program's OUTPUT

            def clean_undonated(g, x):
                f = jax.jit(g)
                out = f(x)
                return out + x         # no donation anywhere

            def clean_nested_def(g, x):
                f = jax.jit(g, donate_argnums=(0,))
                def later():
                    return f(x)        # consumes only when CALLED
                probe = x + 1          # runs at definition time: clean
                return later, probe

            def clean_exclusive_branches(g, x, cond):
                f = jax.jit(g, donate_argnums=(0,))
                if cond:
                    out = f(x)
                else:
                    out = x + 1        # never runs after f(x): clean
                return out
            """
        },
        select=["JIT-HAZARD"],
    )
    assert not {
        f.symbol for f in result.findings if f.symbol.startswith("donated-")
    }


def test_jit_hazard_donation_nested_and_loop_legs(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def outer(g, x):
                f = jax.jit(g, donate_argnums=(0,))
                def inner(y):
                    out = f(y)
                    return out + y     # BAD: y consumed inside inner
                return inner

            def loop_branches(g, x, conds):
                f = jax.jit(g, donate_argnums=(0,))
                for cond in conds:
                    if cond:
                        out = f(x)
                    else:
                        out = x + 1    # BAD: iteration 2 reads after
                return out             # iteration 1 donated x
            """
        },
        select=["JIT-HAZARD"],
    )
    donated = [f for f in result.findings if f.symbol.startswith("donated-")]
    # the nested hazard reports ONCE (inner's own walk), not once per
    # enclosing function
    inner_hits = [f for f in donated if f.scope.endswith("inner")]
    assert len(inner_hits) == 1, donated
    # the loop keeps the exclusive-branch exemption OFF: flagged
    assert any(f.scope.endswith("loop_branches") for f in donated), donated


# ---------------------------------------------------------------------- #
# FALLBACK-PARITY
# ---------------------------------------------------------------------- #

_RESILIENCE_STUB = """
DEVICE_PATH_FAMILIES = frozenset({"binary", "reduce", "ghost"})
"""


def test_fallback_parity_positive(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/core/execution/resilience.py": _RESILIENCE_STUB,
            "modin_tpu/core/storage_formats/tpu/query_compiler.py": """
            class TpuQueryCompiler:
                def _try_naked(self):          # BAD: no decorator
                    return None

                @device_path("unheard_of")     # BAD: family not registered
                def _try_rogue(self):
                    return None

                @device_path("binary")
                def _try_binary(self, op):
                    return None

                def add(self, other):
                    return self._try_binary("add")   # BAD: no None check,
                                                     # not a forwarder-only use
                """,
        },
        select=["FALLBACK-PARITY"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "undec-_try_naked" in symbols
    assert "unregistered-_try_rogue" in symbols
    # declared-but-unused family in the registry is drift too
    assert "unused-family-ghost" in symbols
    assert "unused-family-unheard_of" not in symbols


def test_fallback_parity_negative_checked_and_forwarded(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/core/execution/resilience.py": """
            DEVICE_PATH_FAMILIES = frozenset({"binary", "reduce"})
            """,
            "modin_tpu/core/storage_formats/tpu/query_compiler.py": """
            class TpuQueryCompiler:
                @device_path("binary")
                def _try_binary(self, op):
                    return None

                @device_path("reduce")
                def _try_reduce(self, op):
                    r = self._try_binary(op)     # ok: _try_ -> _try_ checked
                    if r is not None:
                        return r
                    return None

                def _dispatch(self, op):
                    return self._try_reduce(op)  # ok: forwarder (direct return)

                def sum(self, op):
                    result = self._try_reduce(op)
                    if result is not None:       # ok: checked
                        return result
                    return "pandas"

                def mean(self, op):
                    result = (
                        self._try_reduce(op) if op else None
                    )
                    if result is not None:       # ok: checked through IfExp
                        return result
                    return "pandas"

                def max_(self, op):
                    result = self._dispatch(op)  # ok: forwarder's caller checks
                    if result is not None:
                        return result
                    return "pandas"
                """,
        },
        select=["FALLBACK-PARITY"],
    )
    assert not result.findings, [f.render() for f in result.findings]


# ---------------------------------------------------------------------- #
# EXC-HYGIENE
# ---------------------------------------------------------------------- #


def test_exc_hygiene_positive_and_scope(tmp_path):
    files = {
        "modin_tpu/core/thing.py": """
        def f():
            try:
                g()
            except Exception:      # BAD: audited tree
                pass
            try:
                g()
            except (ValueError, TypeError):   # ok: named semantic types
                pass
        """,
        "modin_tpu/pandas/api.py": """
        def f():
            try:
                g()
            except Exception:      # ok: pandas layer is out of scope
                pass
        """,
    }
    result = lint_tree(tmp_path, files, select=["EXC-HYGIENE"])
    assert [f.path for f in result.findings] == ["modin_tpu/core/thing.py"]
    assert result.findings[0].symbol == "broad-except-f"


def test_exc_hygiene_pragma_suppresses(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/core/thing.py": """
            def probe():
                try:
                    g()
                except Exception:  # graftlint: disable=EXC-HYGIENE -- probe
                    return None
            """
        },
        select=["EXC-HYGIENE"],
    )
    assert not result.findings
    assert len(result.suppressed) == 1


# ---------------------------------------------------------------------- #
# REGISTRY-DRIFT
# ---------------------------------------------------------------------- #

_METRICS_STUB = """
METRICS = (
    ("app.good.*", "counter", "a documented family"),
    ("app.dead.counter", "counter", "declared but never emitted"),
)
"""

_ENVVARS_STUB = """
class Alpha:
    varname = "MODIN_TPU_ALPHA"

class Undocumented:
    varname = "MODIN_TPU_GHOST_KNOB"
"""


def test_registry_drift_positive(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/logging/metrics.py": _METRICS_STUB,
            "modin_tpu/config/envvars.py": _ENVVARS_STUB,
            "docs/ref.md": "app.good and MODIN_TPU_ALPHA are documented.",
            "modin_tpu/work.py": """
            import os

            def f(op):
                emit_metric(f"app.good.{op}", 1)       # ok
                emit_metric("app.unknown.name", 1)     # BAD: undeclared
                return os.environ.get("MODIN_TPU_MYSTERY")   # BAD: undeclared
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "undeclared-metric-app.unknown.name" in symbols
    assert "dead-metric-app.dead.counter" in symbols
    assert "undeclared-envvar-MODIN_TPU_MYSTERY" in symbols
    assert "undocumented-envvar-MODIN_TPU_GHOST_KNOB" in symbols
    # dead pattern is also undocumented; the good family + ALPHA are fine
    assert "undocumented-metric-app.good.*" not in symbols
    assert "undocumented-envvar-MODIN_TPU_ALPHA" not in symbols


def test_registry_drift_metric_kinds(tmp_path):
    """graftmeter leg: kinds must be valid, histogram declarations and
    HISTOGRAM_BUCKETS specs must match one-to-one."""
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/logging/metrics.py": """
            METRICS = (
                ("app.ok.counter", "counter", "fine"),
                ("app.ok.hist", "histogram", "fine, has buckets"),
                ("app.kindless", "an entry still in the 2-tuple shape"),
                ("app.bad.kind", "sketch", "not a meter kind"),
                ("app.hist.nobuckets", "histogram", "no bucket spec"),
            )
            """,
            "modin_tpu/observability/meters.py": """
            HISTOGRAM_BUCKETS = {
                "app.ok.hist": (0.1, 1.0, 10.0),
                "app.orphan.buckets": (1, 2, 4),
            }
            """,
            "modin_tpu/work.py": """
            def f():
                emit_metric("app.ok.counter", 1)
                emit_metric("app.ok.hist", 0.5)
                emit_metric("app.kindless", 1)
                emit_metric("app.bad.kind", 1)
                emit_metric("app.hist.nobuckets", 1)
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "metric-kind-app.kindless" in symbols
    assert "metric-kind-app.bad.kind" in symbols
    assert "histogram-without-buckets-app.hist.nobuckets" in symbols
    assert "buckets-without-histogram-app.orphan.buckets" in symbols
    # well-declared entries are clean on the kind leg
    assert "metric-kind-app.ok.counter" not in symbols
    assert "metric-kind-app.ok.hist" not in symbols
    assert "histogram-without-buckets-app.ok.hist" not in symbols
    assert "buckets-without-histogram-app.ok.hist" not in symbols


def test_registry_drift_metric_kinds_skip_without_meters_module(tmp_path):
    """A snippet tree without observability/meters.py skips the bucket
    cross-check but still validates kinds."""
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/logging/metrics.py": """
            METRICS = (
                ("app.hist", "histogram", "buckets live elsewhere"),
            )
            """,
            "modin_tpu/work.py": """
            def f():
                emit_metric("app.hist", 1)
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "histogram-without-buckets-app.hist" not in symbols
    assert "metric-kind-app.hist" not in symbols


_SPANS_STUB = """
SPANS = (
    ("trace.good.*", "a documented span family"),
    ("trace.dead", "declared but never emitted"),
)
"""


def test_registry_drift_spans_positive(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/observability/spans.py": _SPANS_STUB,
            "docs/ref.md": "trace.good is documented here.",
            "modin_tpu/work.py": """
            from modin_tpu.observability import spans as graftscope

            def f(op):
                with graftscope.span(f"trace.good.{op}"):     # ok (wildcard)
                    pass
                sp = graftscope.start_span("trace.unknown")   # BAD: undeclared
                with span("trace.also_unknown"):              # BAD: bare name too
                    pass
                with graftscope.layer_span(op, "PANDAS-API"): # exempt emitter
                    pass
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "undeclared-span-trace.unknown" in symbols
    assert "undeclared-span-trace.also_unknown" in symbols
    assert "dead-span-trace.dead" in symbols
    # the dead pattern is also undocumented; the good family is fine
    assert "undocumented-span-trace.dead" in symbols
    assert "undocumented-span-trace.good.*" not in symbols


def test_registry_drift_spans_negative(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/observability/spans.py": """
            SPANS = (
                ("trace.good", "documented"),
            )
            """,
            "docs/ref.md": "trace.good is documented.",
            "modin_tpu/work.py": """
            from modin_tpu.observability import spans as graftscope

            def f(name):
                with graftscope.span("trace.good"):
                    pass
                with graftscope.span(name):   # dynamic name: not checkable
                    pass
                obj.ewm(span=7)               # keyword arg, not an emitter
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_registry_drift_negative_docstrings_and_internal_tokens(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/config/envvars.py": """
            class Alpha:
                varname = "MODIN_TPU_ALPHA"
            """,
            "modin_tpu/work.py": '''
            """Module docstring naming MODIN_TPU_NOT_A_READ is fine."""

            def f(i):
                return f"__MODIN_TPU_BT_{i}__"   # mangling token, not a var
            ''',
        },
        select=["REGISTRY-DRIFT"],
    )
    # no docs/ dir -> doc checks skip; no undeclared-var findings either
    assert not result.findings, [f.render() for f in result.findings]


# ---------------------------------------------------------------------- #
# REGISTRY-DRIFT: the LOCKS leg (graftdep)
# ---------------------------------------------------------------------- #

_LOCKS_STUB = """
from typing import Tuple

LOCKS: Tuple[Tuple[str, str, str], ...] = (
    ("app.ok", "lock", "fine"),
    ("app.wrongkind", "rlock", "declared reentrant"),
    ("app.dead", "lock", "declared, never constructed"),
)
LOCK_ORDER: Tuple[Tuple[str, str, str], ...] = ()
"""


def test_registry_drift_locks_positive(tmp_path):
    """Both directions of the LOCKS cross-check, the kind leg, the raw
    threading.Lock leg, and the docs leg — against an AnnAssign registry
    (the real registry's ``LOCKS: Tuple[...] = (...)`` shape)."""
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/concurrency/registry.py": _LOCKS_STUB,
            "docs/ref.md": "app.ok and app.wrongkind are documented.",
            "modin_tpu/work.py": """
            import threading
            from modin_tpu.concurrency import named_lock, named_rlock

            A = named_lock("app.ok")
            B = named_lock("app.wrongkind")    # BAD: declared "rlock"
            C = named_lock("app.ghost")        # BAD: undeclared
            D = threading.Lock()               # BAD: raw, outside concurrency/
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "lock-kind-app.wrongkind" in symbols
    assert "undeclared-lock-app.ghost" in symbols
    assert "raw-lock-Lock" in symbols
    assert "dead-lock-app.dead" in symbols
    assert "undocumented-lock-app.dead" in symbols
    # the well-declared, constructed, documented lock is clean everywhere
    assert not any(s.endswith("app.ok") for s in symbols)


def test_registry_drift_locks_negative(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/concurrency/registry.py": """
            LOCKS = (
                ("app.ok", "lock", "fine"),
                ("app.re", "rlock", "fine"),
            )
            """,
            "modin_tpu/concurrency/lockdep.py": """
            import threading

            def named_lock(name):
                return threading.Lock()   # raw INSIDE concurrency/: exempt
            """,
            "modin_tpu/work.py": """
            from modin_tpu.concurrency import named_lock, named_rlock

            A = named_lock("app.ok")
            B = named_rlock("app.re")

            def make(name):
                return named_lock(name)   # forwarding wrapper: not a site
            """,
        },
        select=["REGISTRY-DRIFT"],
    )
    # no docs/ dir -> the undocumented-lock leg skips too
    assert not result.findings, [f.render() for f in result.findings]


# ---------------------------------------------------------------------- #
# LOCK-ORDER
# ---------------------------------------------------------------------- #

_ORDER_REGISTRY = """
from typing import Tuple

LOCKS: Tuple[Tuple[str, str, str], ...] = (
    ("app.outer", "lock", "x"),
    ("app.inner", "lock", "y"),
)
LOCK_ORDER: Tuple[Tuple[str, str, str], ...] = (
    ("app.outer", "app.inner", "outer admits into inner"),
)
"""


def test_lock_order_flags_declared_contradiction(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/concurrency/registry.py": _ORDER_REGISTRY,
            "modin_tpu/work.py": """
            from modin_tpu.concurrency import named_lock

            OUTER = named_lock("app.outer")
            INNER = named_lock("app.inner")

            def inverted():
                with INNER:
                    with OUTER:      # declared order says outer FIRST
                        pass
            """,
        },
        select=["LOCK-ORDER"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "contradicts-app.inner-app.outer" in symbols


def test_lock_order_declared_nesting_is_clean(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/concurrency/registry.py": _ORDER_REGISTRY,
            "modin_tpu/work.py": """
            from modin_tpu.concurrency import named_lock

            OUTER = named_lock("app.outer")
            INNER = named_lock("app.inner")

            def fine():
                with OUTER:
                    with INNER:      # matches the declared order
                        pass
                with span("not.a.lock"):   # unresolvable: never a lock
                    pass
            """,
        },
        select=["LOCK-ORDER"],
    )
    assert not result.findings, [f.render() for f in result.findings]


def test_lock_order_flags_abba_cycle_across_files(tmp_path):
    """Two files nest the same (undeclared-order) pair in opposite
    directions — the observed graph cycles even with no LOCK_ORDER edge,
    and binding resolution crosses the import graph."""
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/mod_a.py": """
            from modin_tpu.concurrency import named_lock

            X = named_lock("app.x")
            Y = named_lock("app.y")

            def forward():
                with X:
                    with Y:
                        pass
            """,
            "modin_tpu/mod_b.py": """
            from modin_tpu.mod_a import X, Y

            def backward():
                with Y:
                    with X:
                        pass
            """,
        },
        select=["LOCK-ORDER"],
    )
    assert any(f.symbol.startswith("cycle-") for f in result.findings), [
        f.render() for f in result.findings
    ]


def test_lock_order_flags_undeclared_raw_lock(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import threading

            _L = threading.Lock()

            def f():
                with _L:
                    pass
            """,
        },
        select=["LOCK-ORDER"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "undeclared-lock" in symbols


# ---------------------------------------------------------------------- #
# LOCK-BLOCKING
# ---------------------------------------------------------------------- #


def test_lock_blocking_flags_sleep_direct_and_via_one_hop(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import time
            from modin_tpu.concurrency import named_lock

            L = named_lock("app.l")

            def helper():
                time.sleep(1.0)

            def direct():
                with L:
                    time.sleep(0.1)        # BAD: blocking under the lock

            def indirect():
                with L:
                    helper()               # BAD: reachable one hop down
            """,
        },
        select=["LOCK-BLOCKING"],
    )
    hits = [f for f in result.findings if f.symbol == "blocking-app.l-sleep"]
    assert len(hits) == 2, [f.render() for f in result.findings]
    assert any("via helper()" in f.message for f in hits)


def test_lock_blocking_flags_pickle_under_lock(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import pickle
            from modin_tpu.concurrency import named_lock

            L = named_lock("app.l")

            def probe(state):
                with L:
                    return len(pickle.dumps(state))   # the exporter class
            """,
        },
        select=["LOCK-BLOCKING"],
    )
    symbols = {f.symbol for f in result.findings}
    assert "blocking-app.l-pickle" in symbols


def test_lock_blocking_negative_outside_lock_and_timed_get(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import queue
            import time
            from modin_tpu.concurrency import named_lock

            L = named_lock("app.l")
            Q = queue.Queue()

            def snapshot_then_act():
                with L:
                    item = Q.get(timeout=1.0)   # timed get: bounded, legal
                time.sleep(0.1)                 # after release: legal
                return item
            """,
        },
        select=["LOCK-BLOCKING"],
    )
    assert not result.findings, [f.render() for f in result.findings]


# ---------------------------------------------------------------------- #
# THREAD-HYGIENE
# ---------------------------------------------------------------------- #


def test_thread_hygiene_positive_all_three_legs(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import threading

            def worker():
                pass

            def spawn():
                threading.Thread(target=worker).start()
            """,
        },
        select=["THREAD-HYGIENE"],
    )
    symbols = {f.symbol for f in result.findings}
    assert symbols == {
        "unnamed-worker",
        "undaemonized-worker",
        "unseeded-worker",
    }


def test_thread_hygiene_negative_seeded_and_unresolvable(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "modin_tpu/work.py": """
            import threading
            from modin_tpu.observability import meters as graftmeter
            from modin_tpu.observability import spans as graftscope

            def worker(stack, scopes):
                graftscope.seed_thread(stack)
                graftmeter.seed_thread_scopes(scopes)
                try:
                    pass
                finally:
                    graftscope.seed_thread(None)
                    graftmeter.seed_thread_scopes(None)

            def seed_all(stack, scopes):
                graftscope.seed_thread(stack)
                graftmeter.seed_thread_scopes(scopes)

            def hopper():
                seed_all(None, None)    # one same-file call-hop: counts

            def spawn(ext):
                threading.Thread(
                    target=worker, name="modin-tpu-w", daemon=True,
                    args=(graftscope.snapshot_stack(),
                          graftmeter.snapshot_scopes()),
                ).start()
                threading.Thread(
                    target=hopper, name="modin-tpu-h", daemon=True
                ).start()
                threading.Thread(     # cross-module callable: exempt from
                    target=ext.run, name="modin-tpu-x", daemon=True
                ).start()             # the seeding leg, never guessed at
            """,
        },
        select=["THREAD-HYGIENE"],
    )
    assert not result.findings, [f.render() for f in result.findings]


# ---------------------------------------------------------------------- #
# framework: pragmas and baseline
# ---------------------------------------------------------------------- #


def test_pragma_on_preceding_line_suppresses(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            import jax

            def f(x):
                # graftlint: disable=HOST-SYNC
                return jax.device_get(x)
            """
        },
        select=["HOST-SYNC"],
    )
    assert not result.findings
    assert len(result.suppressed) == 1


def test_unused_pragma_is_flagged_on_full_runs(tmp_path):
    result = lint_tree(
        tmp_path,
        {
            "pkg/mod.py": """
            def f():
                # graftlint: disable=HOST-SYNC
                return 1
            """
        },
    )
    assert rules_hit(result) == {"GL-PRAGMA-UNUSED"}


def test_baseline_suppresses_then_goes_stale(tmp_path):
    files = {
        "pkg/mod.py": """
        import jax

        def f(x):
            return jax.device_get(x)
        """
    }
    first = lint_tree(tmp_path, files)
    assert len(first.findings) == 1

    baseline = tmp_path / ".graftlint-baseline"
    write_baseline(baseline, first.findings)
    second = run_lint([tmp_path], root=tmp_path, baseline=baseline)
    assert not second.findings
    assert len(second.baselined) == 1
    assert second.exit_code == 0

    # a --select run never regenerates the entry: it must NOT cry stale
    selected = run_lint(
        [tmp_path], root=tmp_path, select=["JIT-HAZARD"], baseline=baseline
    )
    assert not selected.stale_baseline
    assert selected.exit_code == 0

    # fix the violation: the baseline entry is now stale and fails the run
    (tmp_path / "pkg" / "mod.py").write_text("def f(x):\n    return x\n")
    third = run_lint([tmp_path], root=tmp_path, baseline=baseline)
    assert not third.findings
    assert len(third.stale_baseline) == 1
    assert third.exit_code == 1


def test_unused_pragma_can_be_baselined(tmp_path):
    """--baseline-write must produce a baseline the very next run accepts,
    including GL-PRAGMA-UNUSED findings."""
    files = {
        "pkg/mod.py": """
        def f():
            # graftlint: disable=HOST-SYNC
            return 1
        """
    }
    first = lint_tree(tmp_path, files)
    assert rules_hit(first) == {"GL-PRAGMA-UNUSED"}
    baseline = tmp_path / ".graftlint-baseline"
    write_baseline(baseline, first.findings)
    second = run_lint([tmp_path], root=tmp_path, baseline=baseline)
    assert not second.findings
    assert not second.stale_baseline
    assert second.exit_code == 0


def test_cli_baseline_write_roundtrip(tmp_path):
    mod = tmp_path / "pkg" / "mod.py"
    mod.parent.mkdir(parents=True)
    mod.write_text("import jax\n\ndef f(x):\n    return jax.device_get(x)\n")
    (tmp_path / "pyproject.toml").write_text("")
    baseline = tmp_path / "bl"

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "modin_tpu.lint", str(tmp_path),
             "--root", str(tmp_path), "--baseline", str(baseline), *args],
            capture_output=True,
            text=True,
            cwd=REPO_ROOT,
        )

    red = cli()
    assert red.returncode == 1
    # clickable path:line: RULE format
    assert "pkg/mod.py:4: HOST-SYNC" in red.stdout

    wrote = cli("--baseline-write")
    assert wrote.returncode == 0
    assert baseline.exists()

    green = cli()
    assert green.returncode == 0, green.stdout


def test_parse_failure_is_a_finding_not_a_crash(tmp_path):
    result = lint_tree(tmp_path, {"pkg/bad.py": "def f(:\n"})
    assert rules_hit(result) == {"GL-PARSE"}


def test_unknown_select_rule_raises():
    with pytest.raises(ValueError, match="NO-SUCH-RULE"):
        run_lint(["modin_tpu"], root=REPO_ROOT, select=["NO-SUCH-RULE"])
