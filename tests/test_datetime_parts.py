"""Device datetime component extraction (ops/datetime_parts.py): the full
``.dt`` calendar-component surface differential vs pandas, with NaT
upcasting (int32 -> float64) and predicate (bool, NaT=False) semantics.

Reference extracts these host-side through pandas tslib per partition
(DateTimeDefault); here it is one branchless integer kernel per column.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from tests.utils import assert_no_fallback, df_equals

_rng = np.random.default_rng(61)

_COMPONENTS = [
    "year", "month", "day", "hour", "minute", "second", "microsecond",
    "nanosecond", "dayofweek", "weekday", "day_of_week", "dayofyear",
    "day_of_year", "quarter", "daysinmonth", "days_in_month",
    "is_leap_year", "is_month_start", "is_month_end", "is_quarter_start",
    "is_quarter_end", "is_year_start", "is_year_end",
]


def _ts_series(n=600, nat_frac=0.0):
    base = pandas.to_datetime("1970-01-01")
    s = pandas.Series(
        base
        + pandas.to_timedelta(
            _rng.integers(-3_000_000_000, 3_000_000_000, n), unit="s"
        )
    )
    if nat_frac:
        s = s.copy()
        s[_rng.random(n) < nat_frac] = pandas.NaT
    return s


@pytest.mark.parametrize("name", _COMPONENTS)
def test_component_clean(name):
    s = _ts_series()
    md = pd.Series(s)
    got = assert_no_fallback(lambda: getattr(md.dt, name))
    df_equals(got, getattr(s.dt, name))


@pytest.mark.parametrize(
    "name", ["year", "hour", "dayofweek", "quarter", "is_month_end", "is_leap_year"]
)
def test_component_with_nat(name):
    s = _ts_series(nat_frac=0.07)
    md = pd.Series(s)
    got = assert_no_fallback(lambda: getattr(md.dt, name))
    df_equals(got, getattr(s.dt, name))


@pytest.mark.parametrize("unit", ["s", "ms", "us", "ns"])
def test_units(unit):
    s = pandas.Series(
        pandas.to_datetime(
            ["2021-03-05 13:45:12", "1950-11-30 00:00:01", "2000-02-29 23:59:59"]
        ).as_unit(unit)
    )
    md = pd.Series(s)
    for name in ("year", "second", "microsecond", "is_leap_year", "daysinmonth"):
        df_equals(getattr(md.dt, name), getattr(s.dt, name))


def test_century_boundaries():
    # leap rules: 1900 (no), 2000 (yes), 2100 (no); era boundaries negative
    s = pandas.Series(
        pandas.to_datetime(
            [
                "1900-02-28", "1900-03-01", "2000-02-29", "2100-02-28",
                "1899-12-31", "0099-01-01", "2400-02-29",
            ],
            format="mixed",
        )
    )
    md = pd.Series(s)
    for name in ("year", "month", "day", "dayofyear", "is_leap_year"):
        df_equals(getattr(md.dt, name), getattr(s.dt, name))


def test_tz_aware_falls_back_correct():
    s = pandas.Series(
        pandas.to_datetime(["2021-01-01 12:00", "2021-06-01 12:00"]).tz_localize(
            "US/Eastern"
        )
    )
    md = pd.Series(s)
    df_equals(md.dt.hour, s.dt.hour)


def test_methods_still_fall_back_correct():
    s = _ts_series(n=40)
    md = pd.Series(s)
    df_equals(md.dt.normalize(), s.dt.normalize())
    df_equals(md.dt.month_name(), s.dt.month_name())


class TestTimedeltaComponents:
    """Timedelta fields on device (ops/datetime_parts.td_component): days
    floors toward -inf, remainders are non-negative, NaT upcasts, and
    total_seconds is float64 always — pandas Timedelta field semantics."""

    def _td(self, nat=False, n=500):
        s = pandas.Series(
            pandas.to_timedelta(
                _rng.uniform(-1e6, 1e6, n).round(3), unit="s"
            )
        )
        if nat:
            s = s.copy()
            s[_rng.random(n) < 0.05] = pandas.NaT
        return s

    @pytest.mark.parametrize("name", ["days", "seconds", "microseconds", "nanoseconds"])
    @pytest.mark.parametrize("nat", [False, True])
    def test_fields(self, name, nat):
        s = self._td(nat=nat)
        md = pd.Series(s)
        got = assert_no_fallback(lambda: getattr(md.dt, name))
        df_equals(got, getattr(s.dt, name))

    @pytest.mark.parametrize("nat", [False, True])
    def test_total_seconds(self, nat):
        s = self._td(nat=nat)
        md = pd.Series(s)
        got = assert_no_fallback(lambda: md.dt.total_seconds())
        df_equals(got, s.dt.total_seconds())

    def test_negative_floor_semantics(self):
        s = pandas.Series(pandas.to_timedelta([-3.25, -86400.5, 90061.5], unit="s"))
        md = pd.Series(s)
        for name in ("days", "seconds", "microseconds"):
            df_equals(getattr(md.dt, name), getattr(s.dt, name))
