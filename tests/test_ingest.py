"""graftfeed acceptance suite: continuous ingestion & registered live views.

Covers the tentpole contract:

- the differential grid: every registered-view kind (scalar / groupby /
  filtered / top-k / windowed) x append / upsert / retention-trim,
  asserting maintained == recompute-from-scratch == pandas.  Integer,
  count/min/max, and top-k folds are bit-exact; the float mean fold
  re-associates the fp accumulation (fold order is batch order) and
  compares at the differential tolerance — the same documented contract
  as views/incremental.py;
- typed refusals: non-incrementalizable registrations raise
  ``ViewNotIncrementalizable`` with a stable reason, never silently
  recompute;
- schema validation: dtype/column/key violations raise typed
  ``IngestRejected`` and leave no partial state behind;
- staleness-bounded reads: deferred folding creates real lag; a read
  inside the bound serves the maintained state, outside it forces a
  synchronous fold; both reads and ingest ride the serving admission
  gate under their tenants;
- chaos: DeviceLost under the ingest concat dispatch and ledger-pressure
  artifact drops leave every view bit-exact (testing/faults.py);
- the append-link chain bound (MODIN_TPU_VIEWS_MAX_CHAIN): lookup cost
  stays flat across 1k appends and folds keep resolving past the old
  8-hop horizon;
- the MODIN_TPU_INGEST=0 zero-alloc contract over a real workload.
"""

import numpy as np
import pandas
import pytest

import modin_tpu.pandas as pd
from modin_tpu import ingest
from modin_tpu.config import (
    IngestEnabled,
    IngestFoldEvery,
    IngestRetentionAgeS,
    IngestRetentionRows,
    ViewsMaxChain,
)
from modin_tpu.logging.metrics import add_metric_handler, clear_metric_handler
from modin_tpu.views import registry

from tests.utils import df_equals, require_tpu_execution


@pytest.fixture(autouse=True)
def _ingest_env():
    require_tpu_execution()
    registry.reset()
    ingest.reset()
    IngestEnabled.enable()
    yield
    ingest.reset()
    registry.reset()
    IngestEnabled.disable()
    IngestFoldEvery.put(1)
    IngestRetentionRows.put(0)
    IngestRetentionAgeS.put(0.0)


@pytest.fixture
def metric_log():
    events = []

    def handler(name, value):
        events.append((name, value))

    add_metric_handler(handler)
    yield events
    clear_metric_handler(handler)


def _count(events, name):
    return sum(1 for n, _ in events if n == f"modin_tpu.{name}")


_SCHEMA = {"k": "int64", "i": "int64", "x": "float64", "g": "int64",
           "ts": "float64"}

#: every view kind under test, with its registration plan
_PLANS = {
    "scalar": {"kind": "scalar", "column": "i", "agg": "sum"},
    "scalar_mean": {"kind": "scalar", "column": "x", "agg": "mean"},
    "groupby": {"kind": "groupby", "by": "g", "column": "i", "agg": "sum"},
    "filtered": {
        "kind": "filtered", "column": "i", "agg": "sum",
        "predicate": ("x", ">", 0.0),
    },
    "topk": {"kind": "topk", "column": "x", "k": 7},
    "windowed": {
        "kind": "windowed", "column": "i", "time_column": "ts",
        "agg": "sum", "bucket_s": 5.0,
    },
}

#: integer folds are bit-exact; scalar_mean re-associates fp sums
_BIT_EXACT = {"scalar", "groupby", "filtered", "windowed", "topk"}


def _truth(name, pdf):
    """The pandas ground truth for each registered plan over ``pdf``."""
    if name == "scalar":
        return pdf["i"].sum()
    if name == "scalar_mean":
        return pdf["x"].mean()
    if name == "groupby":
        return pdf.groupby("g")["i"].sum()
    if name == "filtered":
        return pdf["i"][pdf["x"] > 0.0].sum()
    if name == "topk":
        return pdf["x"].nlargest(7, keep="first")
    keys = np.floor(pdf["ts"].to_numpy(dtype=np.float64) / 5.0).astype(
        np.int64
    )
    return pdf["i"].groupby(keys).sum()


def _assert_answer(name, got, want):
    if isinstance(want, pandas.Series):
        got = pandas.Series(got)
        if name in _BIT_EXACT:
            pandas.testing.assert_series_equal(
                got, want, check_names=False, check_index_type=False
            )
        else:
            df_equals(got, want)
    elif name in _BIT_EXACT:
        assert got == want, (name, got, want)
    else:
        assert got == pytest.approx(want, rel=1e-12, abs=1e-12)


def _batch(rng, n, key_start=0):
    return pandas.DataFrame(
        {
            "k": np.arange(key_start, key_start + n, dtype=np.int64),
            "i": rng.integers(-1000, 1000, n),
            "x": rng.normal(size=n),
            "g": rng.integers(0, 5, n),
            "ts": rng.uniform(0.0, 60.0, n),
        }
    )


def _make_feed(name="events", key=None):
    return ingest.create_feed(name, _SCHEMA, key=key)


def _apply_upsert(mirror, up, key="k"):
    """Reference upsert semantics: in-batch last-wins dedup, in-place
    update of stored keys, append of new keys in batch order."""
    up = up.drop_duplicates(subset=[key], keep="last")
    up_map = up.set_index(key)
    out = mirror.copy()
    for pos in out.index[out[key].isin(up_map.index)]:
        k = out.loc[pos, key]
        for col in out.columns:
            if col != key:
                out.loc[pos, col] = up_map.loc[k, col]
    new = up[~up[key].isin(mirror[key])]
    out = pandas.concat([out, new], ignore_index=True)
    return out.astype({c: d for c, d in _SCHEMA.items()})


class TestDifferentialGrid:
    """maintained == recompute-from-scratch == pandas, per kind x mode."""

    @pytest.mark.parametrize("name", sorted(_PLANS))
    @pytest.mark.parametrize("mode", ["append", "upsert", "trim"])
    def test_grid(self, name, mode):
        if mode == "trim":
            IngestRetentionRows.put(70)
        rng = np.random.default_rng(abs(hash((name, mode))) % 2**32)
        feed = _make_feed(key="k" if mode == "upsert" else None)
        feed.register_view(name, _PLANS[name])
        mirror = pandas.DataFrame(
            {c: pandas.Series(dtype=d) for c, d in feed.schema.items()}
        )
        for b in range(5):
            batch = _batch(rng, 24, key_start=b * 24)
            feed.append(batch)
            mirror = pandas.concat([mirror, batch], ignore_index=True)
            if mode == "trim":
                # reference trim: whole oldest 24-row batches drop until
                # the retained row count is back under the bound
                while len(mirror) > 70:
                    mirror = mirror.iloc[24:].reset_index(drop=True)
        if mode == "upsert":
            up = _batch(rng, 30, key_start=96)  # 24 updates + 6 new keys
            feed.upsert(up)
            mirror = _apply_upsert(mirror, up)
        df_equals(feed.frame._to_pandas().reset_index(drop=True), mirror)
        maintained = feed.read(name).value
        _assert_answer(name, maintained, _truth(name, mirror))
        _assert_answer(name, feed.recompute(name), _truth(name, mirror))

    def test_upsert_semantics_exact(self):
        """In-place update (in-batch last-wins) + append of new keys;
        every view kind exact against a hand-built expected frame."""
        feed = _make_feed(key="k")
        kinds = ("scalar", "groupby", "topk", "windowed", "filtered")
        for v in kinds:
            feed.register_view(v, _PLANS[v])
        rng = np.random.default_rng(11)
        b0 = _batch(rng, 40)
        feed.append(b0)
        up = _batch(rng, 20, key_start=30)  # keys 30..39 update, 40..49 new
        up = pandas.concat(
            [up, up.iloc[:1].assign(i=np.int64(999))], ignore_index=True
        )  # duplicate key 30 in-batch: last occurrence wins
        feed.upsert(up)
        expect = _apply_upsert(b0.astype(_SCHEMA), up.astype(_SCHEMA))
        assert expect.loc[30, "i"] == 999
        df_equals(feed.frame._to_pandas().reset_index(drop=True), expect)
        for v in kinds:
            _assert_answer(v, feed.read(v).value, _truth(v, expect))
            _assert_answer(v, feed.recompute(v), _truth(v, expect))

    @pytest.mark.parametrize("agg", ["min", "max", "mean", "sum", "count"])
    def test_filtered_agg_survives_empty_match_batches(self, agg):
        """Regression: a micro-batch matching zero predicate rows folds as
        the identity.  pandas' empty-min/max is NaN, which once poisoned
        the int-dtyped running state and dropped all history before the
        empty batch (batches [3,5] / [] / [9] maintained min=9)."""
        feed = ingest.create_feed(f"empty_{agg}", _SCHEMA)
        feed.register_view("v", {
            "kind": "filtered", "column": "i", "agg": agg,
            "predicate": ("x", ">", 0.0),
        })
        frames = []
        for rows in ([(0, 3, 1.0), (1, 5, 1.0)],   # both match
                     [(2, -7, -1.0)],              # matches nothing
                     [(3, 9, 1.0)]):               # matches
            b = pandas.DataFrame(
                {"k": [r[0] for r in rows], "i": [r[1] for r in rows],
                 "x": [r[2] for r in rows], "g": 0, "ts": 0.0}
            ).astype(_SCHEMA)
            feed.append(b)
            frames.append(b)
        full = pandas.concat(frames, ignore_index=True)
        want = getattr(full["i"][full["x"] > 0.0], agg)()
        assert feed.read("v").value == want  # e.g. min == 3, not 9
        _assert_answer("filtered", feed.recompute("v"), want)

    def test_filtered_minmax_refold_skips_empty_partials(self):
        """The retention refold walks retained partials including the
        empty-batch sentinel; and an all-empty view answers pandas'
        empty-reduction NaN."""
        feed = ingest.create_feed("empty_refold", _SCHEMA,
                                  retention_rows=2)
        feed.register_view("v", {
            "kind": "filtered", "column": "i", "agg": "min",
            "predicate": ("x", ">", 0.0),
        })
        for i, x in [(3, 1.0), (-7, -1.0), (9, 1.0)]:
            feed.append(pandas.DataFrame(
                {"k": [i], "i": [i], "x": [x], "g": [0], "ts": [0.0]}
            ).astype(_SCHEMA))
        # retention (2 rows) trimmed the first batch: retained rows are
        # the non-matching -7 and the matching 9
        assert feed.rows == 2
        assert feed.read("v").value == 9
        none_feed = ingest.create_feed("all_empty", _SCHEMA)
        none_feed.register_view("v", {
            "kind": "filtered", "column": "i", "agg": "min",
            "predicate": ("x", ">", 0.0),
        })
        none_feed.append(pandas.DataFrame(
            {"k": [0], "i": [1], "x": [-1.0], "g": [0], "ts": [0.0]}
        ).astype(_SCHEMA))
        assert np.isnan(none_feed.read("v").value)

    def test_keyless_upsert_rejected_not_keyed(self, metric_log):
        feed = _make_feed()
        with pytest.raises(ingest.IngestRejected) as err:
            feed.upsert(_batch(np.random.default_rng(0), 3))
        assert err.value.reason == "not_keyed"
        assert _count(metric_log, "ingest.reject") == 1
        assert feed.rows == 0

    def test_per_feed_retention_override(self):
        """create_feed(retention_rows=...) bounds one feed while the
        global knob (0 = unbounded) leaves its sibling untouched."""
        bounded = ingest.create_feed("bounded", _SCHEMA, retention_rows=20)
        unbounded = ingest.create_feed("unbounded", _SCHEMA)
        rng = np.random.default_rng(7)
        for b in range(4):
            batch = _batch(rng, 10, key_start=b * 10)
            bounded.append(batch)
            unbounded.append(batch)
        assert bounded.rows == 20  # oldest whole batches trimmed
        assert unbounded.rows == 40

    def test_keyed_append_rejects_duplicates(self, metric_log):
        feed = _make_feed(key="k")
        feed.append(_batch(np.random.default_rng(0), 10))
        dup_in_batch = _batch(np.random.default_rng(1), 4, key_start=100)
        dup_in_batch.loc[3, "k"] = 100
        with pytest.raises(ingest.IngestRejected) as err:
            feed.append(dup_in_batch)
        assert err.value.reason == "duplicate_key"
        with pytest.raises(ingest.IngestRejected) as err:
            feed.append(_batch(np.random.default_rng(2), 4, key_start=8))
        assert err.value.reason == "key_exists"
        assert feed.rows == 10  # rejected batches left no trace
        assert _count(metric_log, "ingest.reject") == 2

    def test_trim_by_age(self):
        IngestRetentionAgeS.put(1e-9)  # everything but the newest expires
        feed = _make_feed()
        feed.register_view("scalar", _PLANS["scalar"])
        rng = np.random.default_rng(3)
        last = None
        for _ in range(4):
            last = _batch(rng, 10)
            feed.append(last)
        # batch-granular age trim keeps only the newest batch
        assert feed.rows == 10
        _assert_answer("scalar", feed.read("scalar").value,
                       _truth("scalar", last.astype(_SCHEMA)))

    def test_trim_survives_deferred_folds(self):
        """Trim racing a fold backlog: pending batches trim away before
        they ever folded; the refold over retained partials stays exact."""
        IngestFoldEvery.put(3)
        IngestRetentionRows.put(40)
        feed = _make_feed()
        for v in ("filtered", "topk", "windowed"):
            feed.register_view(v, _PLANS[v])
        rng = np.random.default_rng(4)
        mirror = pandas.DataFrame(
            {c: pandas.Series(dtype=d) for c, d in feed.schema.items()}
        )
        for b in range(8):
            batch = _batch(rng, 16, key_start=b * 16)
            feed.append(batch)
            mirror = pandas.concat([mirror, batch], ignore_index=True)
            while len(mirror) > 40:
                mirror = mirror.iloc[16:].reset_index(drop=True)
        for v in ("filtered", "topk", "windowed"):
            got = feed.read(v, fresh_within_ms=0.0)  # force the backlog
            _assert_answer(v, got.value, _truth(v, mirror))

    def test_late_rows_fold_into_closed_buckets(self):
        feed = _make_feed()
        view = feed.register_view("windowed", _PLANS["windowed"])
        early = pandas.DataFrame(
            {"k": [0], "i": [5], "x": [0.0], "g": [0], "ts": [3.0]}
        )
        late_bucket = pandas.DataFrame(
            {"k": [1], "i": [7], "x": [0.0], "g": [0], "ts": [55.0]}
        )
        straggler = pandas.DataFrame(
            {"k": [2], "i": [11], "x": [0.0], "g": [0], "ts": [4.0]}
        )
        for b in (early, late_bucket, straggler):
            feed.append(b.astype(_SCHEMA))
        got = feed.read("windowed").value
        full = pandas.concat(
            [early, late_bucket, straggler], ignore_index=True
        ).astype(_SCHEMA)
        _assert_answer("windowed", got, _truth("windowed", full))
        assert view.late_buckets >= 1  # the straggler hit a closed bucket


class TestRefusalsAndSchema:
    @pytest.mark.parametrize(
        "plan,reason",
        [
            ({"kind": "scalar", "column": "x", "agg": "median"},
             "non_foldable_agg"),
            ({"kind": "scalar", "column": "x", "agg": "var"},
             "non_foldable_agg"),
            ({"kind": "groupby", "by": "g", "column": "x", "agg": "nunique"},
             "non_foldable_agg"),
            ({"kind": "scalar", "column": "x", "agg": "summ"},
             "unknown_agg"),
            ({"kind": "windowed", "column": "x", "agg": "prod",
              "bucket_s": 5.0, "time_column": "ts"}, "non_foldable_agg"),
            ({"kind": "filtered", "column": "x",
              "predicate": ("g", ">", 0)}, "row_view_unbounded"),
            ({"kind": "filtered", "column": "x", "agg": "sum",
              "predicate": ("g", "~", 0)}, "bad_predicate"),
            ({"kind": "topk", "column": "x", "k": 0}, "bad_k"),
            ({"kind": "windowed", "column": "x", "agg": "sum",
              "bucket_s": 0, "time_column": "ts"}, "bad_window"),
            ({"kind": "windowed", "column": "x", "agg": "sum",
              "bucket_s": 5.0}, "bad_window"),
            ({"kind": "sorted", "column": "x"}, "unknown_kind"),
            ({"kind": "scalar", "column": "zz", "agg": "sum"},
             "unknown_column"),
        ],
    )
    def test_typed_refusals(self, plan, reason, metric_log):
        feed = _make_feed()
        with pytest.raises(ingest.ViewNotIncrementalizable) as err:
            feed.register_view("bad", plan)
        assert err.value.reason == reason
        assert _count(metric_log, "ingest.view.refused") == 1
        assert feed.views() == []  # nothing half-registered

    def test_schema_rejections(self, metric_log):
        feed = _make_feed()
        ok = _batch(np.random.default_rng(0), 4)
        feed.append(ok)
        cases = [
            (ok.drop(columns=["x"]), "missing_column"),
            (ok.assign(extra=1), "extra_column"),
            (ok.assign(i=["a", "b", "c", "d"]), "dtype"),
            (object(), "unsupported_type"),
            ("", "malformed"),  # EmptyDataError from the CSV parser
            ({"k": [1, 2], "i": [0]}, "malformed"),  # ragged dict
        ]
        for bad, reason in cases:
            with pytest.raises(ingest.IngestRejected) as err:
                feed.append(bad)
            assert err.value.reason == reason, (reason, err.value)
        assert _count(metric_log, "ingest.reject") == len(cases)
        assert feed.rows == 4  # rejected batches left no trace

    def test_safe_casts_accepted(self):
        feed = _make_feed()
        batch = _batch(np.random.default_rng(0), 3)
        batch["x"] = batch["x"].astype(np.float32)  # float32 -> float64
        batch["g"] = batch["g"].astype(np.int32)  # int32 -> int64
        feed.append(batch)
        assert feed.frame._to_pandas()["x"].dtype == np.float64

    def test_csv_and_dict_batches(self):
        feed = _make_feed()
        feed.register_view("s", _PLANS["scalar"])
        feed.append("k,i,x,g,ts\n1,10,0.5,2,3.0\n2,-4,1.5,0,8.0\n")
        feed.append({"k": [3], "i": [7], "x": [2.5], "g": [1], "ts": [11.0]})
        assert feed.rows == 3
        assert feed.read("s").value == 10 - 4 + 7

    def test_create_feed_duplicate_and_lookup(self):
        feed = _make_feed()
        with pytest.raises(ingest.IngestError):
            _make_feed()
        assert ingest.get_feed("events") is feed
        assert ingest.feeds() == ["events"]
        ingest.drop_feed("events")
        assert ingest.feeds() == []


class TestStaleness:
    def test_deferred_fold_creates_lag_and_bound_forces_fold(
        self, metric_log
    ):
        IngestFoldEvery.put(1000)  # never fold on append
        feed = _make_feed()
        feed.register_view("s", _PLANS["scalar"])
        rng = np.random.default_rng(5)
        full = pandas.DataFrame()
        for _ in range(3):
            b = _batch(rng, 8)
            feed.append(b)
            full = pandas.concat([full, b], ignore_index=True)
        assert feed.fold_lag_ms() > 0.0
        # inside an infinite bound: serve the (empty) maintained state
        served = feed.read("s", fresh_within_ms=1e12)
        assert not served.forced and served.covered_rows == 0
        # a zero bound forces the synchronous fold of the backlog
        forced = feed.read("s", fresh_within_ms=0.0)
        assert forced.forced and forced.covered_rows == len(full)
        _assert_answer("scalar", forced.value, _truth("scalar", full))
        assert feed.fold_lag_ms() == 0.0
        assert _count(metric_log, "ingest.read.forced_fold") == 1
        assert _count(metric_log, "ingest.read.served") == 1

    def test_reads_and_ingest_ride_the_admission_gate(self):
        from modin_tpu.config import ServingEnabled
        from modin_tpu.serving.gate import serving_snapshot

        ServingEnabled.put(True)
        try:
            feed = _make_feed()
            feed.register_view("s", _PLANS["scalar"])
            b = _batch(np.random.default_rng(6), 12)
            feed.append(b, tenant="ingestor")
            read = feed.read("s", tenant="reader")
            _assert_answer(
                "scalar", read.value, _truth("scalar", b.astype(_SCHEMA))
            )
            tenants = serving_snapshot()["tenants"]
            assert "ingestor" in tenants and "reader" in tenants
        finally:
            ServingEnabled.put(False)


class TestChaos:
    def test_device_lost_during_ingest_concat(self, metric_log):
        from modin_tpu.testing.faults import midquery_device_loss

        feed = _make_feed()
        for v in ("filtered", "topk", "windowed"):
            feed.register_view(v, _PLANS[v])
        rng = np.random.default_rng(7)
        b = _batch(rng, 16)
        feed.append(b)
        full = b.astype(_SCHEMA)
        tail = _batch(rng, 16, key_start=16)
        # the append's concat dispatch dies mid-flight; recovery re-seats
        # and the retry lands the batch exactly once
        with midquery_device_loss(after_deploys=0, times=1):
            feed.append(tail)
        full = pandas.concat(
            [full, tail.astype(_SCHEMA)], ignore_index=True
        )
        for v in ("filtered", "topk", "windowed"):
            _assert_answer(v, feed.read(v).value, _truth(v, full))
        df_equals(feed.frame._to_pandas().reset_index(drop=True), full)
        assert _count(metric_log, "recovery.unrecoverable") == 0

    def test_ledger_pressure_drop_leaves_views_exact(self):
        from modin_tpu.core.memory import device_ledger

        feed = _make_feed()
        for v in ("filtered", "topk", "windowed"):
            feed.register_view(v, _PLANS[v])
        rng = np.random.default_rng(8)
        full = pandas.DataFrame()
        for _ in range(3):
            b = _batch(rng, 16)
            feed.append(b)
            full = pandas.concat([full, b], ignore_index=True)
        feed.frame.sum()  # seed graftview artifacts on the frame
        device_ledger.spill_lru(1)  # pressure: derived artifacts drop first
        for v in ("filtered", "topk", "windowed"):
            _assert_answer(v, feed.read(v).value, _truth(v, full))
        df_equals(feed.frame.sum(), full.sum())


class _FakeCol:
    """Registry-protocol column stub: drives 1k-append chain mechanics
    without paying 1k device concats."""

    def __init__(self, length):
        self._view_token = None
        self._view_parent = None
        self._data = object()
        self.length = length
        self.is_lazy = False


class TestChainBound:
    def test_lookup_cost_flat_across_1k_appends(self):
        """1k micro-batch appends with a query every 10th: hops-per-lookup
        in the last hundred appends is no worse than in the first — the
        walk is bounded by the query interval, not by total appends."""
        col = _FakeCol(10)
        registry.store(col, "reduce", ("sum",), {"v": 0}, can_fold=True)
        per_block = []
        for block in range(10):
            before = registry.walk_stats()
            for a in range(100):
                child = _FakeCol(col.length + 1)
                registry.note_append(child, col)
                col = child
                if a % 10 == 9:
                    outcome, state, base = registry.lookup(
                        col, "reduce", ("sum",)
                    )
                    assert outcome == "fold", (block, a, outcome)
                    registry.store(
                        col, "reduce", ("sum",), {"v": 0},
                        can_fold=True, folded=True,
                    )
            after = registry.walk_stats()
            per_block.append(
                (after["hops"] - before["hops"])
                / (after["lookups"] - before["lookups"])
            )
        assert per_block[-1] <= per_block[0] + 1.0, per_block
        # bounded by the query interval: <= 10 hops per lookup, always
        assert max(per_block) <= 10.0, per_block

    def test_fold_resolves_past_old_eight_hop_horizon(self):
        """30 artifact-less links deep still folds (the pre-graftfeed
        hardcoded 8-hop walk would have returned miss)."""
        root = _FakeCol(10)
        registry.store(root, "reduce", ("sum",), {"v": 1}, can_fold=True)
        col = root
        for _ in range(30):
            child = _FakeCol(col.length + 1)
            registry.note_append(child, col)
            col = child
        outcome, state, base = registry.lookup(col, "reduce", ("sum",))
        assert outcome == "fold"
        assert base == root.length and state == {"v": 1}

    def test_compaction_respects_max_chain(self, metric_log):
        before = ViewsMaxChain.get()
        ViewsMaxChain.put(4)
        try:
            col = _FakeCol(10)
            for _ in range(12):
                child = _FakeCol(col.length + 1)
                registry.note_append(child, col)
                col = child
            assert _count(metric_log, "view.chain_compact") >= 1
            assert registry.walk_stats()["compactions"] >= 1
        finally:
            ViewsMaxChain.put(before)

    def test_real_frame_appends_stay_foldable(self):
        """Small real-frame leg: periodic queries keep folding (and keep
        the walk bounded) across many concats."""
        pdf = pandas.DataFrame({"a": np.arange(64, dtype=np.int64)})
        mdf = pd.DataFrame(pdf)
        mdf.sum()
        for i in range(30):
            tail = pandas.DataFrame(
                {"a": np.arange(4, dtype=np.int64) + i}
            )
            mdf = pd.concat([mdf, pd.DataFrame(tail)], ignore_index=True)
            pdf = pandas.concat([pdf, tail], ignore_index=True)
            got = mdf.sum()
            assert got["a"] == pdf["a"].sum()
        stats = registry.walk_stats()
        assert stats["hops"] <= stats["lookups"] * 3


class TestOffContract:
    def test_ingest_off_zero_alloc_over_real_workload(self):
        """MODIN_TPU_INGEST=0: a real (non-ingest) workload allocates
        nothing from graftfeed and create_feed refuses."""
        IngestEnabled.disable()
        before = ingest.ingest_alloc_count()
        pdf = pandas.DataFrame(
            {"a": np.arange(200, dtype=np.int64),
             "b": np.random.default_rng(0).normal(size=200)}
        )
        mdf = pd.DataFrame(pdf)
        df_equals(mdf.sum(), pdf.sum())
        mdf2 = pd.concat([mdf, pd.DataFrame(pdf)], ignore_index=True)
        df_equals(
            mdf2.sum(), pandas.concat([pdf, pdf], ignore_index=True).sum()
        )
        assert ingest.ingest_alloc_count() == before
        with pytest.raises(ingest.IngestError):
            ingest.create_feed("nope", {"a": "int64"})
        assert ingest.ingest_alloc_count() == before

    def test_knob_validation(self):
        with pytest.raises(ValueError):
            IngestFoldEvery.put(0)
        with pytest.raises(ValueError):
            IngestRetentionRows.put(-1)
        with pytest.raises(ValueError):
            ViewsMaxChain.put(0)
