"""CSV io benchmarks mirroring the reference suite
(asv_bench/benchmarks/io/csv.py: TimeReadCsvSkiprows,
TimeReadCsvTrueFalseValues, TimeReadCsvNamesDtype) plus the streamed
writer.  Data files are generated once into a temp dir."""

import numpy as np

from ..utils import IO_SHAPES, execute, io_data_dir, make_frame, pd, prepare_csv


class TimeReadCsvSkiprows:
    param_names = ["shape", "skiprows"]
    params = [IO_SHAPES, [None, "lambda_even_rows", "range_uniform", "range_step2"]]

    def setup(self, shape, skiprows):
        self.path = prepare_csv(io_data_dir(), "skiprows", shape, "str_int")
        rows = shape[0]
        self.skiprows = {
            None: None,
            "lambda_even_rows": lambda x: x % 2,
            "range_uniform": np.arange(1, rows // 10),
            "range_step2": np.arange(1, rows, 2),
        }[skiprows]

    def time_skiprows(self, shape, skiprows):
        execute(pd.read_csv(self.path, skiprows=self.skiprows))


class TimeReadCsvTrueFalseValues:
    param_names = ["shape"]
    params = [IO_SHAPES]

    def setup(self, shape):
        self.path = prepare_csv(io_data_dir(), "tfv", shape, "true_false_int")

    def time_true_false_values(self, shape):
        execute(
            pd.read_csv(
                self.path,
                true_values=["Yes", "true"],
                false_values=["No", "false"],
            )
        )


class TimeReadCsvNamesDtype:
    param_names = ["shape", "dtype"]
    params = [IO_SHAPES, ["Int64", "Int64_Timestamp"]]

    def setup(self, shape, dtype):
        kind = "int" if dtype == "Int64" else "int_timestamp"
        self.path = prepare_csv(io_data_dir(), "names", shape, kind)
        cols = shape[1]
        self.names = [f"c{i}" for i in range(cols)]
        if dtype == "Int64":
            self.dtype = {f"c{i}": "Int64" for i in range(cols)}
            self.parse_dates = None
        else:
            self.dtype = {f"c{i}": "Int64" for i in range(2, cols)}
            self.parse_dates = ["c0", "c1"]

    def time_names_dtype(self, shape, dtype):
        kwargs = dict(names=self.names, dtype=self.dtype, skiprows=1)
        if self.parse_dates:
            kwargs["parse_dates"] = self.parse_dates
        execute(pd.read_csv(self.path, **kwargs))


class TimeToCsv:
    param_names = ["shape"]
    params = [IO_SHAPES]

    def setup(self, shape):
        self.df = make_frame(shape, seed=1)
        execute(self.df)

    def time_to_csv(self, shape):
        self.df.to_csv(f"{io_data_dir()}/out.csv")
