"""IO benchmarks (reference asv_bench/benchmarks/io/)."""
