"""Parquet io benchmarks mirroring the reference suite
(asv_bench/benchmarks/io/parquet.py: TimeReadParquet) plus the
chunk-streamed writer.  The read file is written with multiple row
groups so the row-group-parallel path is what gets measured."""

import numpy as np

from ..utils import IO_SHAPES, execute, io_data_dir, make_frame, pd


def _prepare_parquet(shape, n_groups=8, seed=0):
    rows, cols = shape
    path = f"{io_data_dir()}/read_{rows}x{cols}.parquet"
    import os

    if os.path.exists(path):
        return path
    import pandas

    rng = np.random.default_rng(seed)
    data = {f"col{i}": rng.integers(0, 100, rows) for i in range(cols)}
    data["col_s"] = rng.choice(["alpha", "beta", "gamma"], rows)
    pandas.DataFrame(data).to_parquet(
        path, row_group_size=max(rows // n_groups, 1)
    )
    return path


class TimeReadParquet:
    param_names = ["shape"]
    params = [IO_SHAPES]

    def setup(self, shape):
        self.path = _prepare_parquet(shape)

    def time_read_parquet(self, shape):
        execute(pd.read_parquet(self.path))


class TimeToParquet:
    param_names = ["shape"]
    params = [IO_SHAPES]

    def setup(self, shape):
        self.df = make_frame(shape, seed=1)
        execute(self.df)

    def time_to_parquet(self, shape):
        self.df.to_parquet(f"{io_data_dir()}/out.parquet")
