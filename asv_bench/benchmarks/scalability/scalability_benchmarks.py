"""Host<->device boundary benchmarks mirroring the reference suite
(asv_bench/benchmarks/scalability/scalability_benchmarks.py:
TimeFromPandas/TimeToPandas/TimeToNumPy).  The reference varies worker
cpus; here the boundary is the host->HBM upload / gather, so the knob is
the frame shape only (the mesh is fixed for the process)."""

from ..utils import UNARY_SHAPES, execute, make_frame, pd


def _host_frame(shape, seed=0):
    df = make_frame(shape, seed=seed)
    return df._to_pandas() if hasattr(df, "_to_pandas") else df


class TimeFromPandas:
    param_names = ["shape"]
    params = [UNARY_SHAPES]

    def setup(self, shape):
        self.data = _host_frame(shape)
        pd.DataFrame([])  # engine init outside the timed region

    def time_from_pandas(self, shape):
        execute(pd.DataFrame(self.data))


class TimeToPandas:
    param_names = ["shape"]
    params = [UNARY_SHAPES]

    def setup(self, shape):
        self.df = make_frame(shape)
        execute(self.df)

    def time_to_pandas(self, shape):
        # a no-op copy on the pandas baseline keeps the A/B comparable
        df = self.df
        df._to_pandas() if hasattr(df, "_to_pandas") else df.copy()


class TimeToNumPy:
    param_names = ["shape"]
    params = [UNARY_SHAPES]

    def setup(self, shape):
        self.df = make_frame(shape)
        execute(self.df)

    def time_to_numpy(self, shape):
        self.df.to_numpy()
