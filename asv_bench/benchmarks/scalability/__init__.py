"""Scalability benchmarks (reference asv_bench/benchmarks/scalability/)."""
