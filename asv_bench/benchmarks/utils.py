"""Benchmark data shapes and the impl switch.

Reference design: asv_bench/benchmarks/utils/ — MODIN_TPU_ASV_USE_IMPL
selects the implementation under test; MODIN_TPU_TEST_DATASET_SIZE picks the
shape profile; every benchmark calls execute() to force materialization.
"""

import os

import numpy as np

USE_IMPL = os.environ.get("MODIN_TPU_ASV_USE_IMPL", "modin_tpu")
DATASET_SIZE = os.environ.get("MODIN_TPU_TEST_DATASET_SIZE", "Small")

if USE_IMPL == "pandas":
    import pandas as pd
else:
    import modin_tpu.pandas as pd
    from modin_tpu.config import BenchmarkMode

    BenchmarkMode.put(True)

# (rows, cols) profiles mirroring the reference (data_shapes.py:33-59)
UNARY_SHAPES = {
    "Small": [(2_000, 10), (100, 100)],
    "Big": [(5_000, 5_000), (1_000_000, 10)],
}[DATASET_SIZE]
BINARY_SHAPES = {
    "Small": [((2_000, 10), (2_000, 10))],
    "Big": [((5_000, 5_000), (5_000, 5_000)), ((500_000, 20), (1_000_000, 10))],
}[DATASET_SIZE]
GROUPBY_NGROUPS = {"Small": [10, 100], "Big": [100, 10_000]}[DATASET_SIZE]


def make_frame(shape, seed=0, ngroups=None):
    rng = np.random.default_rng(seed)
    rows, cols = shape
    data = {f"col{i}": rng.integers(0, 100, rows) for i in range(cols)}
    if ngroups is not None:
        data["groupby_col"] = rng.integers(0, ngroups, rows)
    return pd.DataFrame(data)


def execute(obj):
    """Force materialization (reference: utils/common.py execute)."""
    qc = getattr(obj, "_query_compiler", None)
    if qc is not None:
        qc.execute()
        return obj
    return obj
