"""Benchmark data shapes and the impl switch.

Reference design: asv_bench/benchmarks/utils/ — MODIN_TPU_ASV_USE_IMPL
selects the implementation under test; MODIN_TPU_TEST_DATASET_SIZE picks the
shape profile; every benchmark calls execute() to force materialization.
"""

import os

import numpy as np

USE_IMPL = os.environ.get("MODIN_TPU_ASV_USE_IMPL", "modin_tpu")
DATASET_SIZE = os.environ.get("MODIN_TPU_TEST_DATASET_SIZE", "Small")

if USE_IMPL == "pandas":
    import pandas as pd
else:
    import modin_tpu.pandas as pd
    from modin_tpu.config import BenchmarkMode

    BenchmarkMode.put(True)

# (rows, cols) profiles mirroring the reference (data_shapes.py:33-59)
UNARY_SHAPES = {
    "Small": [(2_000, 10), (100, 100)],
    "Big": [(5_000, 5_000), (1_000_000, 10)],
}[DATASET_SIZE]
BINARY_SHAPES = {
    "Small": [((2_000, 10), (2_000, 10))],
    "Big": [((5_000, 5_000), (5_000, 5_000)), ((500_000, 20), (1_000_000, 10))],
}[DATASET_SIZE]
GROUPBY_NGROUPS = {"Small": [10, 100], "Big": [100, 10_000]}[DATASET_SIZE]


def make_frame(shape, seed=0, ngroups=None):
    rng = np.random.default_rng(seed)
    rows, cols = shape
    data = {f"col{i}": rng.integers(0, 100, rows) for i in range(cols)}
    if ngroups is not None:
        data["groupby_col"] = rng.integers(0, ngroups, rows)
    return pd.DataFrame(data)


def execute(obj):
    """Force materialization (reference: utils/common.py execute)."""
    qc = getattr(obj, "_query_compiler", None)
    if qc is not None:
        qc.execute()
        return obj
    return obj


# IO shape profiles (reference: asv_bench/benchmarks/utils/data_shapes.py —
# the io suite reads one (rows, cols) profile per size)
IO_SHAPES = {
    "Small": [(10_000, 10)],
    "Big": [(1_000_000, 10)],
}[DATASET_SIZE]


def io_data_dir() -> str:
    """Deterministic per-user scratch dir so generated io files are reused
    across benchmark runs instead of orphaned per-process tempdirs."""
    import getpass
    import pathlib
    import tempfile

    d = (
        pathlib.Path(tempfile.gettempdir())
        / f"modin_tpu_asv_{getpass.getuser()}"
    )
    d.mkdir(parents=True, exist_ok=True)
    return str(d)


def prepare_csv(tmp_dir, name, shape, kind="int", seed=0):
    """Write (once) and return a csv path for the io benchmarks.

    kind: 'int' | 'str_int' (every 3rd column short strings) |
    'true_false_int' (every 3rd column Yes/No/true/false) |
    'int_timestamp' (two ms-resolution datetime columns).
    """
    import pathlib

    rows, cols = shape
    path = pathlib.Path(tmp_dir) / f"{name}_{rows}x{cols}_{kind}.csv"
    if path.exists():
        return str(path)
    rng = np.random.default_rng(seed)
    import pandas

    data = {}
    for i in range(cols):
        if kind == "str_int" and i % 3 == 2:
            data[f"col{i}"] = rng.choice(["alpha", "beta", "gamma-delta"], rows)
        elif kind == "true_false_int" and i % 3 == 2:
            data[f"col{i}"] = rng.choice(["Yes", "No", "true", "false"], rows)
        else:
            data[f"col{i}"] = rng.integers(0, 100, rows)
    df = pandas.DataFrame(data)
    if kind == "int_timestamp":
        stamp = pandas.date_range("2000", periods=rows, freq="ms")
        df["col0"] = stamp
        df["col1"] = stamp
    df.to_csv(path, index=False)
    return str(path)
