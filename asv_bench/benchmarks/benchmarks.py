"""Core benchmarks mirroring the reference suite
(asv_bench/benchmarks/benchmarks.py:42-433): TimeArithmetic,
TimeGroupByDefaultAggregations, TimeGroupByMultiColumn, TimeBinaryOp,
TimeMerge, TimeConcat, TimeSortValues, TimeQuery."""

import numpy as np

from .utils import (
    BINARY_SHAPES,
    GROUPBY_NGROUPS,
    UNARY_SHAPES,
    execute,
    make_frame,
    pd,
)


class TimeArithmetic:
    """Full reference op set (reference benchmarks.py:383-433): sum, count,
    median, nunique, apply, mean, mode, add, mul, mod, abs, aggregate,
    isin, transpose — each under both axis params like the reference."""

    params = [UNARY_SHAPES, [0, 1]]
    param_names = ["shape", "axis"]

    def setup(self, shape, axis):
        self.df = make_frame(shape)
        execute(self.df)

    def time_sum(self, shape, axis):
        execute(self.df.sum(axis=axis))

    def time_count(self, shape, axis):
        execute(self.df.count(axis=axis))

    def time_median(self, shape, axis):
        execute(self.df.median(axis=axis))

    def time_nunique(self, shape, axis):
        execute(self.df.nunique(axis=axis))

    def time_apply(self, shape, axis):
        execute(self.df.apply(lambda df: df.sum(), axis=axis))

    def time_mean(self, shape, axis):
        execute(self.df.mean(axis=axis))

    def time_mode(self, shape, axis):
        execute(self.df.mode(axis=axis))

    def time_add(self, shape, axis):
        execute(self.df.add(2, axis=axis))

    def time_mul(self, shape, axis):
        execute(self.df.mul(2, axis=axis))

    def time_mod(self, shape, axis):
        execute(self.df.mod(2, axis=axis))

    def time_abs(self, shape, axis):
        execute(self.df.abs())

    def time_aggregate(self, shape, axis):
        execute(self.df.aggregate(lambda df: df.sum(), axis=axis))

    def time_is_in(self, shape, axis):
        execute(self.df.isin([0, 2]))

    def time_transpose(self, shape, axis):
        execute(self.df.transpose())


class TimeGroupByDefaultAggregations:
    params = [UNARY_SHAPES, GROUPBY_NGROUPS]
    param_names = ["shape", "ngroups"]

    def setup(self, shape, ngroups):
        self.df = make_frame(shape, ngroups=ngroups)
        execute(self.df)

    def time_groupby_count(self, shape, ngroups):
        execute(self.df.groupby("groupby_col").count())

    def time_groupby_size(self, shape, ngroups):
        execute(self.df.groupby("groupby_col").size())

    def time_groupby_sum(self, shape, ngroups):
        execute(self.df.groupby("groupby_col").sum())

    def time_groupby_mean(self, shape, ngroups):
        execute(self.df.groupby("groupby_col").mean())


class TimeGroupByMultiColumn:
    params = [UNARY_SHAPES]
    param_names = ["shape"]

    def setup(self, shape):
        self.df = make_frame(shape, ngroups=20)
        self.df["groupby_col2"] = self.df["col0"] % 5
        execute(self.df)

    def time_groupby_multi_sum(self, shape):
        execute(self.df.groupby(["groupby_col", "groupby_col2"]).sum())


class TimeBinaryOp:
    params = [BINARY_SHAPES]
    param_names = ["shapes"]

    def setup(self, shapes):
        self.df1 = make_frame(shapes[0], seed=1)
        self.df2 = make_frame(shapes[0], seed=2)
        execute(self.df1), execute(self.df2)

    def time_add(self, shapes):
        execute(self.df1 + self.df2)

    def time_mul(self, shapes):
        execute(self.df1 * self.df2)


class TimeMerge:
    params = [BINARY_SHAPES]
    param_names = ["shapes"]

    def setup(self, shapes):
        self.left = make_frame(shapes[0], seed=3)
        self.right = make_frame((shapes[0][0] // 2, 3), seed=4)
        execute(self.left), execute(self.right)

    def time_merge_inner(self, shapes):
        execute(self.left.merge(self.right, on="col0", how="inner"))

    def time_merge_left(self, shapes):
        execute(self.left.merge(self.right, on="col0", how="left"))


class TimeConcat:
    params = [UNARY_SHAPES]
    param_names = ["shape"]

    def setup(self, shape):
        self.df1 = make_frame(shape, seed=5)
        self.df2 = make_frame(shape, seed=6)
        execute(self.df1), execute(self.df2)

    def time_concat_axis0(self, shape):
        execute(pd.concat([self.df1, self.df2]))


class TimeSortValues:
    params = [UNARY_SHAPES]
    param_names = ["shape"]

    def setup(self, shape):
        self.df = make_frame(shape, seed=7)
        execute(self.df)

    def time_sort_values(self, shape):
        execute(self.df.sort_values("col0", kind="stable"))


class TimeQuery:
    params = [UNARY_SHAPES]
    param_names = ["shape"]

    def setup(self, shape):
        self.df = make_frame(shape, seed=8)
        execute(self.df)

    def time_query(self, shape):
        execute(self.df.query("col0 > 50 & col1 < 30"))
