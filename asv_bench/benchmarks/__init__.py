"""asv benchmark suite (reference: modin/asv_bench/benchmarks/)."""
