"""Run the asv benchmark classes without asv (timeit-style).

Usage: python asv_bench/run_standalone.py [pattern]
"""

import inspect
import itertools
import sys
import time

from benchmarks import benchmarks
from benchmarks.io import csv as io_csv
from benchmarks.io import parquet as io_parquet
from benchmarks.scalability import scalability_benchmarks

_MODULES = [benchmarks, io_csv, io_parquet, scalability_benchmarks]


def _classes():
    for mod in _MODULES:
        for name, cls in inspect.getmembers(mod, inspect.isclass):
            if cls.__module__ == mod.__name__:
                yield name, cls


def run(pattern: str = "") -> None:
    for name, cls in _classes():
        if not name.startswith("Time") or pattern not in name:
            continue
        params = getattr(cls, "params", [[None]])
        if params and not isinstance(params[0], list):
            params = [params]
        for combo in itertools.product(*params):
            instance = cls()
            try:
                instance.setup(*combo)
            except NotImplementedError:
                continue
            for method_name, method in inspect.getmembers(instance, inspect.ismethod):
                if not method_name.startswith("time_"):
                    continue
                method(*combo)  # warm-up
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    method(*combo)
                    best = min(best, time.perf_counter() - t0)
                print(f"{name}.{method_name}{combo}: {best*1000:.2f} ms")


if __name__ == "__main__":
    run(sys.argv[1] if len(sys.argv) > 1 else "")
