"""modin_tpu — a TPU-native distributed dataframe framework.

A drop-in pandas replacement (``import modin_tpu.pandas as pd``) whose hot path
executes as sharded ``jax.Array`` computations on a TPU mesh.  Architecture
surveyed from modin-project/modin (see /root/repo/SURVEY.md): API layer ->
query compiler -> operator algebra -> sharded columnar core frame -> JAX/XLA
engine, with in-process pandas as the correctness backstop for object dtypes
and the long tail of the API.
"""

from __future__ import annotations

from typing import Optional, Tuple

__version__ = "0.1.0"


def set_execution(engine: Optional[str] = None, storage_format: Optional[str] = None) -> Tuple[str, str]:
    """Set the execution (engine, storage format) pair atomically.

    Reference behavior: /root/reference/modin/__init__.py:37-66.
    """
    from modin_tpu.config import Engine, StorageFormat

    old_engine, old_storage_format = None, None
    if engine is not None:
        old_engine = Engine.get()
        Engine.put(engine)
    if storage_format is not None:
        old_storage_format = StorageFormat.get()
        StorageFormat.put(storage_format)
    return old_engine, old_storage_format


def set_backend(backend: str) -> None:
    """Switch the active backend by name ('Tpu', 'Pandas', ...)."""
    from modin_tpu.config import Backend

    execution = Backend.get_execution_for_backend(backend)
    set_execution(engine=execution.engine, storage_format=execution.storage_format)
    Backend.put(backend)
