"""graftview incremental maintenance: append-only fold rules.

The algebraic combiner patterns of "High Performance Dataframes from
Parallel Processing Patterns" (arXiv 2209.06146), applied to the registry's
artifacts: a column grown by ``concat`` is its parent's rows plus an
appended tail, so a cached aggregate over the parent folds the tail's
partial instead of recomputing the whole column.

Exactness contract, stated honestly (docs/architecture.md carries the
decision table):

- ``count`` / ``min`` / ``max`` / ``any`` / ``all`` and integer/bool
  ``sum`` / ``prod`` folds are **bit-exact**: their combines are exactly
  associative (integer addition wraps identically in any order; min/max is
  a total-order fold; the NaN rules compose segment-wise).
- float ``sum`` / ``prod`` and every ``mean`` fold re-associates the
  floating-point accumulation — identical to the recombination contract
  the graftstream window combiners already ship (streaming/executor.py
  ``_REDUCE_COMBINABLE``), and inside the repo's differential-comparison
  tolerance.
- everything else (var/std/sem/skew/kurt, median, quantile, nunique, mode,
  sorted reps) does **not** fold: the registry invalidates those artifacts
  on append with ``view.invalidate.not_incremental`` and the next query
  rebuilds from scratch.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

# graftstream already declares which aggregations recombine exactly from
# partials (its window combiners, arXiv 2209.06146's algebraic patterns);
# the append-only fold sets are the SAME facts, so they derive from the
# one source of truth instead of a drifted copy
from modin_tpu.streaming.executor import (  # noqa: E402
    GROUPBY_COMBINABLE as _STREAM_GROUPBY_COMBINABLE,
    REDUCE_COMBINABLE as _STREAM_REDUCE_COMBINABLE,
)

#: scalar reductions whose artifact state admits an append-only fold
#: (graftstream's window-combinable set plus the pure boolean folds)
FOLDABLE_REDUCES = _STREAM_REDUCE_COMBINABLE | frozenset({"any", "all"})

#: scalar reductions cached as whole results (exact-hit reuse; the
#: non-foldable ones invalidate honestly on append)
CACHEABLE_REDUCES = FOLDABLE_REDUCES | frozenset(
    {"var", "std", "sem", "skew", "kurt", "median"}
)

#: groupby aggregations with an exact (or fp-reassociating, for mean)
#: partial-table combine — graftstream's combinable set plus size
FOLDABLE_GROUPBYS = _STREAM_GROUPBY_COMBINABLE | frozenset({"size"})


def combine_scalar(
    op: str, skipna: bool, old: np.ndarray, tail: np.ndarray
) -> np.ndarray:
    """Fold one column's tail reduction into the cached prefix result.

    ``old``/``tail`` are the 0-d numpy results the device kernel answered
    for each segment under identical (op, skipna) semantics; the combine
    reproduces ``_reduce_one``'s whole-column semantics segment-wise.
    """
    old = np.asarray(old)
    tail = np.asarray(tail)
    if op in ("sum", "count"):
        return np.add(old, tail)
    if op == "prod":
        return np.multiply(old, tail)
    if op == "min":
        if old.dtype.kind == "f" and skipna:
            # skipna: a NaN segment result can only mean all-NaN — fmin
            # lets the other segment answer
            return np.fmin(old, tail)
        return np.minimum(old, tail)  # NaN propagates (skipna=False rule)
    if op == "max":
        if old.dtype.kind == "f" and skipna:
            return np.fmax(old, tail)
        return np.maximum(old, tail)
    if op == "any":
        return np.logical_or(old, tail)
    if op == "all":
        return np.logical_and(old, tail)
    raise ValueError(op)


def combine_mean(
    old_mean: np.ndarray,
    old_k: int,
    tail_mean: np.ndarray,
    tail_k: int,
) -> Tuple[np.ndarray, int]:
    """Fold a (mean, valid-count) pair; NaN segments with k=0 defer to the
    other side, NaN with k>0 (skipna=False poisoning) propagates."""
    k = int(old_k) + int(tail_k)
    if old_k == 0:
        return np.asarray(tail_mean, dtype=np.float64), k
    if tail_k == 0:
        return np.asarray(old_mean, dtype=np.float64), k
    total = np.float64(old_mean) * old_k + np.float64(tail_mean) * tail_k
    return np.float64(total / k), k


# --------------------------------------------------------------------- #
# groupby partial-table combine (host side, graftstream's combiner shapes)
# --------------------------------------------------------------------- #


def _group_levels(pdf) -> list:
    return list(range(pdf.index.nlevels))


def combine_groupby(
    agg: str,
    old: Any,
    tail: Any,
    old_count: Any = None,
    tail_count: Any = None,
) -> Tuple[Any, Any]:
    """Combine two groupby result tables (same columns, key-indexed, sorted,
    dropna=True) into the full-data table.  Returns ``(combined,
    combined_count)`` — the count table is carried only for ``mean``.

    Index union + sort + dtype rules ride on pandas' own concat->groupby,
    which is exactly the recombination the streaming executor's partial
    tables use.
    """
    import pandas

    levels = _group_levels(old)
    if agg in ("sum", "count", "size"):
        combined = pandas.concat([old, tail]).groupby(level=levels, sort=True).sum()
        return combined, None
    if agg in ("min", "max"):
        grouped = pandas.concat([old, tail]).groupby(level=levels, sort=True)
        return (grouped.min() if agg == "min" else grouped.max()), None
    if agg == "mean":
        counts = (
            pandas.concat([old_count, tail_count])
            .groupby(level=levels, sort=True)
            .sum()
        )

        def contribution(means, ks):
            k = ks.to_numpy()
            # an all-NaN group means NaN with k=0: it contributes 0 to the
            # sum instead of poisoning it (the group's NaN re-appears below
            # through the 0/0 division)
            return means.where(k != 0, 0.0) * np.where(k != 0, k, 0)

        sums = pandas.concat(
            [contribution(old, old_count), contribution(tail, tail_count)]
        ).groupby(level=levels, sort=True).sum()
        combined = sums / counts.to_numpy()
        return combined, counts
    raise ValueError(agg)


# --------------------------------------------------------------------- #
# dictionary-encoding code-table extension (append-only concat)
# --------------------------------------------------------------------- #


def extend_dict_encoding(base_col: Any, tail_values: np.ndarray) -> Optional[Any]:
    """The concatenated column's :class:`~modin_tpu.ops.dictionary.DictEncoding`
    built by code-table extension: factorize ONLY the appended tail, union
    the (sorted) category tables, remap the base's device codes through the
    old->union translation (a small device gather — no remap at all when
    the tail introduced no new category), and device-concat the code
    columns.  Returns None whenever the extension cannot reproduce
    ``_encode``'s exact result (unorderable tails, category-count bound),
    leaving the plain lazy re-encode path untouched.
    """
    import pandas

    from modin_tpu.ops import dictionary as _dict
    from modin_tpu.ops.structural import concat_columns

    base_enc = getattr(base_col, "_dict_cache", None)
    if not isinstance(base_enc, _dict.DictEncoding):
        return None
    try:
        tail_codes, tail_cats = pandas.factorize(
            np.asarray(tail_values, dtype=object), sort=True, use_na_sentinel=True
        )
    except TypeError:
        return None
    tail_cats = np.asarray(tail_cats, dtype=object)
    try:
        union, base_map, tail_map = _dict.union_categories(
            base_enc.categories, tail_cats
        )
    except TypeError:
        return None  # unorderable across the two category sets
    if len(union) > _dict._MAX_CATEGORIES:
        return None
    tail_fcodes = tail_codes.astype(np.float64)
    tail_has_nan = bool((tail_codes == -1).any())
    if tail_has_nan:
        tail_fcodes[tail_codes == -1] = np.nan
    if len(tail_map):
        tail_fcodes = np.where(
            np.isnan(tail_fcodes), np.nan, tail_map[
                np.where(np.isnan(tail_fcodes), 0, tail_fcodes).astype(np.int64)
            ]
        )
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn

    base_codes_col = base_enc.codes
    base_raw = base_codes_col.raw
    if len(union) != len(base_enc.categories):
        base_raw = _dict.remap_codes_device(base_raw, base_map)
    tail_codes_col = DeviceColumn.from_numpy(tail_fcodes)
    datas, n_out = concat_columns(
        [[base_raw], [tail_codes_col.data]],
        [base_codes_col.length, len(tail_fcodes)],
    )
    codes_col = DeviceColumn(datas[0], np.dtype(np.float64), length=n_out)
    return _dict.DictEncoding(
        codes_col, union, base_enc.has_nan or tail_has_nan
    )
