"""graftview groupby result caching: small output tables, folded over appends.

``groupby_agg`` consults this module before running the device groupby.
Artifacts cache the **result table** (a pandas frame — bounded by
``MODIN_TPU_VIEWS_MAX_GROUPS``) keyed on the aggregation fingerprint plus
the identity of every participating column; a **fold** reruns the SAME
device groupby on only the appended tail rows and combines the partial
tables host-side with graftstream's combiner shapes
(views/incremental.combine_groupby).

Gates are deliberately tight: internal by-labels only, string aggs the
device path supports, and folding additionally requires sorted
as_index=True dropna=True results over all-device numeric key/value
columns.  Anything outside the gates simply declines — the ordinary device
path (or pandas fallback) runs untouched.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np
import pandas

from modin_tpu.observability import spans as graftscope
from modin_tpu.views import incremental, registry

_KIND = "groupby"


def _hashable(value: Any) -> bool:
    try:
        hash(value)
    except TypeError:
        return False
    return True


def _participants(qc: Any, by: Any, selection: Any, agg_kwargs: dict):
    """(key_positions, value_positions) of the columns this aggregation
    reads, or None when the by/selection shape is outside the cacheable
    gate (external key compilers, unresolvable labels).

    Under ``numeric_only`` the value set mirrors the device path's own
    resolution: non-numeric columns are dropped from the aggregation (so
    they are not part of the result's identity — a frame's object column
    must not block folding a numeric aggregation), while a numeric column
    the device cannot compute makes the device path decline entirely, so
    caching declines too."""
    frame = qc._modin_frame
    if not (isinstance(by, list) and by and all(_hashable(b) for b in by)):
        return None
    key_positions = []
    for label in by:
        pos = frame.column_position(label)
        if len(pos) != 1 or pos[0] < 0:
            return None
        key_positions.append(pos[0])
    if selection is not None:
        sel_list = [selection] if not isinstance(selection, list) else list(selection)
        if not all(_hashable(s) for s in sel_list):
            return None
        value_positions = []
        for label in sel_list:
            pos = frame.column_position(label)
            if len(pos) != 1 or pos[0] < 0:
                return None
            value_positions.append(pos[0])
    else:
        value_positions = [
            i for i in range(frame.num_cols) if i not in key_positions
        ]
    if agg_kwargs.get("numeric_only", False):
        from pandas.api.types import is_numeric_dtype

        kept = []
        for p in value_positions:
            col = frame._columns[p]
            if getattr(col, "is_device", False) and col.pandas_dtype.kind in "biuf":
                kept.append(p)
            elif is_numeric_dtype(col.pandas_dtype):
                return None  # numeric but not device-computable: device path declines
        value_positions = kept
    return key_positions, value_positions


def _col_ident(col: Any) -> Optional[tuple]:
    if getattr(col, "is_device", False):
        if col._data is None or col.is_lazy:
            return None  # spilled/lazy: identity is in flux, don't cache
        return ("d", registry.ensure_token(col), id(col._data), col.length)
    # host columns have no token; id() alone is reusable after GC, so the
    # artifact additionally carries weakref guards (_host_guards) that pin
    # identity to the exact live objects
    return ("h", id(col), id(col.data))


def _host_guards(qc: Any, positions: List[int]) -> tuple:
    """(position-index, weakref-to-column) for every host participant:
    a cached result is valid only while each guard still resolves to the
    very object at that position — CPython id reuse after a GC cannot
    alias a replaced host column into a stale hit."""
    import weakref

    frame = qc._modin_frame
    return tuple(
        (j, weakref.ref(frame._columns[p]))
        for j, p in enumerate(positions)
        if not getattr(frame._columns[p], "is_device", False)
    )


def _host_guards_hold(qc: Any, positions: List[int], guards: Any) -> bool:
    if not guards:
        return True
    frame = qc._modin_frame
    for j, ref in guards:
        if j >= len(positions) or ref() is not frame._columns[positions[j]]:
            return False
    return True


def _fingerprint(
    by: Any, agg_func: str, groupby_kwargs: dict, agg_kwargs: dict,
    drop: Any, series_groupby: Any, selection: Any,
) -> Optional[tuple]:
    gk = tuple(sorted(groupby_kwargs.items())) if groupby_kwargs else ()
    ak = tuple(sorted(agg_kwargs.items())) if agg_kwargs else ()
    sel = tuple(selection) if isinstance(selection, list) else selection
    parts = (agg_func, tuple(by), gk, ak, bool(drop), bool(series_groupby), sel)
    return parts if _hashable(parts) else None


def _anchor(qc: Any, key_positions: List[int], value_positions: List[int]):
    frame = qc._modin_frame
    for p in key_positions + value_positions:
        col = frame._columns[p]
        if getattr(col, "is_device", False) and col._data is not None and not col.is_lazy:
            return col
    return None


def _idents(qc: Any, positions: List[int]) -> Optional[tuple]:
    frame = qc._modin_frame
    out = []
    for p in positions:
        ident = _col_ident(frame._columns[p])
        if ident is None:
            return None
        out.append(ident)
    return tuple(out)


def _rebuild(qc: Any, state: dict) -> Any:
    result = type(qc).from_pandas(state["pdf"])
    if state.get("shape_hint"):
        result._shape_hint = state["shape_hint"]
    return result


def _foldable(
    qc: Any, agg_func: str, groupby_kwargs: dict, key_positions, value_positions
) -> bool:
    if agg_func not in incremental.FOLDABLE_GROUPBYS:
        return False
    if not groupby_kwargs.get("as_index", True):
        return False
    if not groupby_kwargs.get("dropna", True):
        return False
    frame = qc._modin_frame
    for p in key_positions + value_positions:
        col = frame._columns[p]
        if not getattr(col, "is_device", False) or col.pandas_dtype.kind not in "biuf":
            return False
    return True


def _chain_base(col: Any, ident: tuple) -> Optional[int]:
    """The stored ident's length when it is an ancestor of ``col`` along
    the append chain (so col[:length] IS that ancestor's data); else None."""
    if ident[0] != "d":
        return None
    want_token, want_len = ident[1], ident[3]
    link = getattr(col, "_view_parent", None)
    hops = 0
    while link is not None and hops < 8:
        ptok, plen = link
        if ptok == want_token and plen == want_len:
            return plen
        link = registry._parent_links.get(ptok)
        hops += 1
    return None


def groupby_consult(
    qc: Any, by: Any, agg_func: Any, groupby_kwargs: dict, agg_kwargs: dict,
    drop: Any, series_groupby: Any, selection: Any,
) -> Optional[Any]:
    """A cached (or tail-folded) groupby result, or None to run the device
    path.  Called by ``groupby_agg`` before ``_try_device_groupby``."""
    if not isinstance(agg_func, str):
        return None
    got = _participants(qc, by, selection, agg_kwargs)
    if got is None:
        return None
    key_positions, value_positions = got
    fp = _fingerprint(
        by, agg_func, groupby_kwargs, agg_kwargs, drop, series_groupby,
        selection,
    )
    if fp is None:
        return None
    anchor = _anchor(qc, key_positions, value_positions)
    if anchor is None:
        return None
    idents = _idents(qc, key_positions + value_positions)
    if idents is None:
        return None
    positions = key_positions + value_positions
    outcome, state, _base = registry.lookup(anchor, _KIND, fp)
    if (
        outcome == "hit"
        and state.get("idents") == registry.ADOPT_IDENTS
        and state.get("n") == len(qc._modin_frame)
    ):
        # an ingested cross-process artifact (views/exporter.py): adopt
        # this process's column identities on the first exact-length hit
        # — a deliberate in-place rewrite (idempotent: every adopter
        # computes the same values for the same live frame)
        state["idents"] = idents
        state["host_guards"] = _host_guards(qc, positions)
    if (
        outcome == "hit"
        and state.get("idents") == idents
        and _host_guards_hold(qc, positions, state.get("host_guards"))
    ):
        return _rebuild(qc, state)
    if (
        outcome == "fold"
        and _foldable(qc, agg_func, groupby_kwargs, key_positions, value_positions)
    ):
        folded = _fold(
            qc, by, agg_func, groupby_kwargs, agg_kwargs, drop,
            series_groupby, selection, key_positions, value_positions,
            fp, state, idents, anchor,
        )
        if folded is not None:
            return folded
    return None


def _fold(
    qc, by, agg_func, groupby_kwargs, agg_kwargs, drop, series_groupby,
    selection, key_positions, value_positions, fp, state, idents, anchor,
):
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn, TpuDataframe
    from modin_tpu.core.dataframe.tpu.metadata import LazyIndex
    from modin_tpu.ops.structural import gather_columns

    frame = qc._modin_frame
    n = len(frame)
    n0 = state.get("n")
    old_idents = state.get("idents")
    if n0 is None or old_idents is None or len(old_idents) != len(idents):
        return None
    positions = key_positions + value_positions
    for p, old_ident in zip(positions, old_idents):
        col = frame._columns[p]
        if _chain_base(col, old_ident) != n0:
            return None
    n_tail = n - n0
    if n_tail < 0:
        return None
    with graftscope.span(
        "view.fold", layer="QUERY-COMPILER", op=f"groupby.{agg_func}",
        cols=len(positions), base=n0, tail=n_tail,
    ):
        def slice_qc(start, stop):
            m = stop - start
            datas, _ = gather_columns(
                [frame._columns[p].data for p in positions],
                np.arange(start, stop, dtype=np.int64),
            )
            cols = [
                DeviceColumn(d, frame._columns[p].pandas_dtype, length=m)
                for p, d in zip(positions, datas)
            ]
            return type(qc)(
                TpuDataframe(
                    cols,
                    pandas.Index([frame.columns[p] for p in positions]),
                    LazyIndex(pandas.RangeIndex(m), m),
                )
            )

        def run_groupby(sub_qc, agg, kwargs):
            return sub_qc._try_device_groupby(
                list(by), agg, 0, groupby_kwargs, (), kwargs,
                drop, series_groupby, selection,
            )

        if n_tail == 0:
            combined, combined_count = state["pdf"], state.get("count_pdf")
            tail_shape_hint = state.get("shape_hint")
        else:
            old_count = state.get("count_pdf")
            if agg_func == "mean" and old_count is None:
                # lazily built on first fold: the current frame's prefix
                # rows ARE the artifact's source data (append-link
                # invariant), so the count table the stored means pair
                # with comes from exactly those rows — and it is written
                # back to the ancestor artifact so later folds from the
                # same ancestor (other branches, bench reps) skip this
                # O(prefix) dispatch
                prefix_count = run_groupby(slice_qc(0, n0), "count", {})
                if prefix_count is None:
                    return None
                old_count = prefix_count.to_pandas()
                registry.amend_ancestor_state(
                    anchor, _KIND, fp, n0, "count_pdf", old_count,
                    extra_bytes=_pdf_bytes(old_count),
                )
            tail_qc = slice_qc(n0, n)
            tail_result = run_groupby(tail_qc, agg_func, agg_kwargs)
            if tail_result is None:
                return None
            tail_pdf = tail_result.to_pandas()
            tail_shape_hint = getattr(tail_result, "_shape_hint", None)
            tail_count = None
            if agg_func == "mean":
                tail_count_result = run_groupby(tail_qc, "count", {})
                if tail_count_result is None:
                    return None
                tail_count = tail_count_result.to_pandas()
            try:
                combined, combined_count = incremental.combine_groupby(
                    agg_func, state["pdf"], tail_pdf, old_count, tail_count,
                )
            except (ValueError, TypeError):
                return None
    from modin_tpu.config import ViewsMaxGroups

    if len(combined) > int(ViewsMaxGroups.get()):
        # the combined table outgrew the cacheable bound: folding this
        # chain can never succeed again, so drop the ancestor artifact —
        # otherwise every later query would re-pay the wasted tail
        # dispatch before recomputing in full
        registry.invalidate_ancestor(anchor, _KIND, fp, "not_incremental")
        return None
    new_state = {
        "pdf": combined,
        "count_pdf": combined_count,
        "shape_hint": tail_shape_hint or state.get("shape_hint"),
        "idents": idents,
        "host_guards": (),  # the fold gate admits device columns only
        "n": n,
    }
    registry.store(
        anchor, _KIND, fp, new_state, can_fold=True,
        host_bytes=_pdf_bytes(combined) + _pdf_bytes(combined_count),
        folded=True,
    )
    return _rebuild(qc, new_state)


def groupby_record(
    qc: Any, result: Any, by: Any, agg_func: Any, groupby_kwargs: dict,
    agg_kwargs: dict, drop: Any, series_groupby: Any, selection: Any,
) -> None:
    """Cache a freshly computed device-groupby result (bounded tables)."""
    if not isinstance(agg_func, str):
        return
    got = _participants(qc, by, selection, agg_kwargs)
    if got is None:
        return
    key_positions, value_positions = got
    fp = _fingerprint(
        by, agg_func, groupby_kwargs, agg_kwargs, drop, series_groupby,
        selection,
    )
    if fp is None:
        return
    anchor = _anchor(qc, key_positions, value_positions)
    if anchor is None:
        return
    idents = _idents(qc, key_positions + value_positions)
    if idents is None:
        return
    from modin_tpu.config import ViewsMaxGroups

    # bound check BEFORE any materialization: the result frame carries its
    # row count, so a high-cardinality groupby is declined without paying
    # the device->host transfer of a table we would discard anyway
    if len(result._modin_frame) > int(ViewsMaxGroups.get()):
        return
    try:
        # the materialization here is deliberate, not deferred: callers
        # routinely serialize-and-DISCARD results (a weakref-deferred copy
        # would be dead by the warm re-query, silently disabling the
        # cache), and the transfer is bounded by MODIN_TPU_VIEWS_MAX_GROUPS
        # rows — the same bound that keeps the host combine cheap
        pdf = result.to_pandas()
    except Exception:  # caching is best-effort; a result that cannot materialize is simply not cached
        return
    can_fold = _foldable(
        qc, agg_func, groupby_kwargs, key_positions, value_positions
    )
    state = {
        "pdf": pdf,
        # mean's fold needs a per-group valid-count table; it is built
        # LAZILY at first fold time (over the prefix rows, which ARE this
        # frame's rows by the append-link invariant) so the common
        # no-reuse path never pays a second device groupby
        "count_pdf": None,
        "shape_hint": getattr(result, "_shape_hint", None),
        "idents": idents,
        "host_guards": _host_guards(qc, key_positions + value_positions),
        "n": len(qc._modin_frame),
    }
    registry.store(
        anchor, _KIND, fp, state, can_fold=can_fold,
        host_bytes=_pdf_bytes(pdf),
    )


def _pdf_bytes(pdf: Any) -> int:
    if pdf is None:
        return 0
    try:
        return int(pdf.memory_usage(deep=False).sum())
    except Exception:  # byte accounting is budget bookkeeping; an exotic frame estimates flat
        return 1024
