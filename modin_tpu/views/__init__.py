"""graftview — cross-query derived-artifact cache with incremental
maintenance over appended batches.

Three legs (ISSUE 14 / ROADMAP items 3+4):

- **registry.py** — the keyed artifact registry generalizing graftsort's
  sorted-representation cache: whole reduction results, nunique/mode/median
  answers, and small groupby output tables cached per (op fingerprint,
  column identity, device epoch, mesh shape), device payloads ledger-
  tracked as derived (pressure drops them; graftguard never counts them
  unrecoverable);
- **incremental.py** — append-only fold rules: algebraic scalar reductions
  and bounded groupby partial tables absorb a ``concat`` tail instead of
  recomputing, dictionary encodings extend their code tables;
- **reduce_cache.py / groupby_cache.py** — the query-compiler integration
  that consults the registry, dispatches ONLY the appended delta through
  the engine seam, and assembles full-data answers.

``MODIN_TPU_VIEWS=Off`` restores today's behavior bit-for-bit: every hook
gates on the module attribute ``VIEWS_ON`` (one attribute read — the
graftscope zero-overhead-when-off contract).
"""

from __future__ import annotations

from typing import Any

#: fast-path flag: True while MODIN_TPU_VIEWS resolves to Auto.  Every
#: integration hook reads this one attribute before doing ANY views work.
VIEWS_ON: bool = True


def _on_views_mode(param: Any) -> None:
    global VIEWS_ON
    VIEWS_ON = str(param.get()).lower() != "off"


from modin_tpu.config import ViewsMode as _ViewsMode  # noqa: E402

_ViewsMode.subscribe(_on_views_mode)
