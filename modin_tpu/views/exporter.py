"""graftview export/ingest: derived artifacts across process boundaries.

The registry (views/registry.py) keys artifacts by in-process identity —
view token, buffer id, device epoch — none of which survive a process
death.  graftfleet's warm-state recovery needs the *answers* to survive:
when a replica dies and respawns, the coordinator re-warms its datasets
from the manifest (core/execution/recovery.py) and then replays a healthy
survivor's host-state artifacts onto the fresh frames, so the respawned
replica's first queries hit warm instead of re-paying every reduction.

Export is positional: an artifact is shipped as (column position, kind,
params, length, state) with NO token/buffer/epoch stamps — those are
minted fresh by ``registry.store`` on the ingesting side, against the
ingesting process's own columns.  Only host-state artifacts whose state
pickles travel; device payloads (sorted reps) rebuild on demand exactly
as they do after a ledger drop.  Length is re-checked at ingest: a
mismatched frame (the dataset changed between export and ingest) skips
the artifact rather than caching a wrong answer.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List

from modin_tpu.logging.metrics import emit_metric
from modin_tpu.views import registry


def _frame_columns(frame: Any) -> List[Any]:
    """The DeviceColumns of a modin_tpu frame (empty for non-TPU frames)."""
    try:
        mf = frame._query_compiler._modin_frame
        return [mf.get_column(i) for i in range(mf.num_cols)]
    except Exception:
        return []


def export_artifacts(frame: Any) -> List[dict]:
    """Picklable snapshot of ``frame``'s live host-state artifacts.

    One record per exportable artifact: ``{"col": position, "kind": ...,
    "params": ..., "length": ..., "state": ..., "can_fold": ...,
    "host_bytes": ...}``.  Artifacts with a device payload, no host
    state, or unpicklable state are skipped — they rebuild on demand.
    """
    candidates: List[dict] = []
    cols = _frame_columns(frame)
    # snapshot under the lock, serialize OUTSIDE it: the pickle probe on a
    # large host state can take hundreds of ms, and registry.LOCK is THE
    # derived-cache lock every query's hit path contends (LOCK-BLOCKING's
    # snapshot-then-act pattern)
    with registry.LOCK:
        for pos, col in enumerate(cols):
            tok = getattr(col, "_view_token", None)
            if tok is None:
                continue
            for key in registry._by_token.get(tok, ()):
                art = registry._entries.get(key)
                if art is None or not art.live or art.state is None:
                    continue
                if art._payload is not None:
                    continue  # device payloads rebuild; they never travel
                state = art.state
                if isinstance(state, dict) and (
                    "idents" in state or "host_guards" in state
                ):
                    # column identities (buffer ids, weakref guards) are
                    # process-local: ship the ADOPT sentinel instead and
                    # let the consuming layer re-stamp them on its first
                    # exact-length hit (registry.ADOPT_IDENTS)
                    state = dict(state)
                    state["idents"] = registry.ADOPT_IDENTS
                    state["host_guards"] = ()
                candidates.append(
                    {
                        "col": pos,
                        "kind": art.kind,
                        "params": art.params,
                        "length": art.length,
                        "state": state,
                        "can_fold": art.can_fold,
                        "host_bytes": art.host_bytes,
                    }
                )
    records = []
    for record in candidates:
        try:
            pickle.dumps(record)
        except Exception:
            continue  # e.g. a device array inside the state dict
        records.append(record)
    emit_metric("view.export", len(records))
    return records


def ingest_artifacts(frame: Any, records: List[dict]) -> int:
    """Replay exported ``records`` onto ``frame``'s columns.

    Returns how many artifacts were stored.  Records whose column
    position or length does not match the local frame are skipped — an
    exported answer must never be cached against different data.
    """
    cols = _frame_columns(frame)
    ingested = 0
    for record in records:
        pos = record["col"]
        if pos >= len(cols):
            continue
        col = cols[pos]
        if int(record["length"]) != int(col.length):
            continue
        if registry.store(
            col,
            record["kind"],
            record["params"],
            record["state"],
            can_fold=record.get("can_fold", False),
            host_bytes=int(record.get("host_bytes", 0)),
        ):
            ingested += 1
    if ingested:
        emit_metric("view.ingest", ingested)
    return ingested


def export_datasets(frames: Dict[str, Any]) -> Dict[str, List[dict]]:
    """``{dataset: records}`` export over a whole dataset map."""
    return {name: export_artifacts(frame) for name, frame in frames.items()}


def ingest_datasets(
    frames: Dict[str, Any], exported: Dict[str, List[dict]]
) -> int:
    """Ingest a multi-dataset export; returns the total stored count."""
    total = 0
    for name, records in exported.items():
        frame = frames.get(name)
        if frame is not None:
            total += ingest_artifacts(frame, records)
    return total
