"""graftview artifact registry: keyed derived artifacts shared across queries.

Generalizes the graftsort sorted-representation cache (ops/sorted_cache.py)
into a process-global registry of **derived artifacts**: values computed
FROM a column's buffer that later queries on the same buffer epoch can
reuse — whole reduction results (scalar aggs), nunique/mode/median answers,
small groupby output tables, and (through the compatibility shim in
ops/sorted_cache.py) the sorted representations themselves.

Identity model
--------------

Every ``DeviceColumn`` can carry a **view token** — a process-unique int
allocated on first use.  Column objects are immutable in length and are
*replaced*, never grown, by every structural op, so a token names exactly
one (length, logical content) pair... with one deliberate exception:
``concat_rows`` records the appended child's **parent link**
``(parent_token, parent_length)``, because the child's first
``parent_length`` rows are the parent's rows *by construction*.  That link
is what makes incremental maintenance sound: an artifact built from the
parent answers for the child's prefix, and only the appended tail
``[parent_length, child_length)`` needs folding in.  Branches are safe for
free — two different appends onto one parent get two different child
tokens, so a fold committed for one branch can never serve the other.

Artifacts are validated on every lookup against the current device epoch,
mesh-shape key, and the owning buffer's identity (``id(col._data)``), and
the buffer-mutation hooks (spill / restore / re-seat / materialize /
donation) drop a column's artifacts eagerly — the same belt-and-braces
contract the sorted-rep cache has always had.

Memory model
------------

Artifacts holding a device payload register in the ``_DeviceLedger`` as
derived entries (``is_derived_cache``): ledger pressure *drops* them (no
host copy needed — they rebuild on demand), and graftguard reseat passes
drop them instead of replaying lineage, never counting them unrecoverable.
Host-side artifact state (scalar results, small groupby tables) is bounded
by the registry's own LRU: ``MODIN_TPU_VIEWS_MAX_ENTRIES`` entries and
``MODIN_TPU_VIEWS_HOST_BUDGET`` bytes, coldest evicted first.

Concurrency
-----------

One reentrant module lock (shared with the sorted-rep shim) serializes
lookup / store / invalidate, exactly like the PR 9 sorted-rep hardening:
concurrent serving queries legitimately share frames, and a reader must
never observe an artifact torn by a concurrent invalidate.  Folds cannot
hold the lock across a device dispatch, so they run lookup -> compute ->
``store`` with the store re-checking the column's spilled state under the
lock: a buffer mutation between lookup and commit always goes through a
spill (``_data = None``) first, so the re-check makes a racer's commit a
no-op instead of a stale write.  (A spill-then-restore completing entirely
inside the window commits against the restored buffer — safe, because a
restore reproduces the exact same values; column VALUES never mutate in
place.  Any future mutation path that changes values while keeping
``_data`` non-None must add a buffer-identity compare here.)
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from modin_tpu.concurrency import named_rlock
from modin_tpu.logging.metrics import emit_metric

#: THE derived-cache lock (reentrant: invalidation runs under it while the
#: ledger spill / recovery paths call ``Artifact.drop`` directly, and the
#: sorted-rep shim re-enters through the same invalidation hooks)
LOCK = named_rlock("views.registry")

#: sentinel an exported artifact's state carries in place of its
#: process-local column identities (views/exporter.py strips them — ids
#: and weakrefs don't cross a process); the consuming cache layer adopts
#: the ingesting process's own identities on the first exact-length hit
ADOPT_IDENTS = "__adopt__"

_token_counter = 0

#: (token, kind, params) -> DerivedArtifact, insertion order = LRU
_entries: "OrderedDict[Tuple[int, str, Any], Any]" = OrderedDict()
#: token -> set of live _entries keys (O(1) per-column invalidation)
_by_token: Dict[int, set] = {}
_host_bytes_total = 0

#: token -> its append-chain depth (number of parent links below it);
#: maintained by note_append, consulted to trigger chain compaction
_link_depth: Dict[int, int] = {}
#: chain-walk accounting: the satellite regression test proves lookup cost
#: stays flat across 1k appends by watching hops-per-lookup through these
_chain_compactions = 0
_walk_hops_total = 0
_walk_lookups = 0


def ensure_token(col: Any) -> int:
    """``col``'s view token, allocating one on first use (lock held or not —
    allocation is idempotent under the lock)."""
    tok = getattr(col, "_view_token", None)
    if tok is not None:
        return tok
    global _token_counter
    with LOCK:
        tok = col._view_token
        if tok is None:
            _token_counter += 1
            tok = _token_counter
            col._view_token = tok
    return tok


def _max_chain() -> int:
    from modin_tpu.config import ViewsMaxChain

    return int(ViewsMaxChain.get())


def _compact_link_locked(link: Tuple[int, int]) -> Tuple[Tuple[int, int], int]:
    """Follow ``link`` past artifact-less tokens, returning the first link
    whose token holds ANY artifact (or the deepest reachable link) plus the
    number of links skipped.  Sound because prefix-of-prefix is a prefix:
    re-anchoring to a transitive ancestor loses nothing when every skipped
    intermediate token has nothing to serve."""
    skipped = 0
    bound = _max_chain()
    while skipped < bound:
        ptok, _plen = link
        if ptok in _by_token:
            break  # this ancestor holds artifacts: stay reachable
        nxt = _parent_links.get(ptok)
        if nxt is None:
            break
        link = nxt
        skipped += 1
    return link, skipped


def note_append(child: Any, parent: Any) -> None:
    """Record that ``child``'s first ``parent.length`` rows ARE ``parent``'s
    rows (concat_rows).  The child gets its own fresh token; the parent link
    is what fold lookups walk.  Chains deeper than MODIN_TPU_VIEWS_MAX_CHAIN
    are compacted: the child's link re-anchors past artifact-less
    intermediate tokens, so sustained micro-batch ingest (graftfeed) keeps
    the walk O(1) instead of O(appends)."""
    global _chain_compactions
    compacted = 0
    with LOCK:
        ptok = ensure_token(parent)
        ctok = ensure_token(child)
        link = (ptok, int(parent.length))
        depth = _link_depth.get(ptok, 0) + 1
        plink = getattr(parent, "_view_parent", None)
        if plink is not None:
            _note_link_locked(ptok, plink)
        if depth > _max_chain():
            link, skipped = _compact_link_locked(link)
            if skipped:
                depth = _link_depth.get(link[0], 0) + 1
                compacted = 1
                _chain_compactions += 1
        child._view_parent = link
        # record the link by token too, so fold lookups can walk chains
        # whose intermediate column objects have been collected
        _note_link_locked(ctok, link)
        _link_depth[ctok] = depth
    if compacted:
        emit_metric("view.chain_compact", 1)


def _current_epoch() -> int:
    from modin_tpu.core.execution import recovery

    return recovery.current_epoch()


def _mesh_key() -> str:
    from modin_tpu.parallel.mesh import mesh_shape_key

    return mesh_shape_key()


class DerivedArtifact:
    """One cached derived value, ledger-tracked when it holds device data.

    ``state`` is the host-side payload (a dict the producing cache layer
    owns: scalar results, partial tables); ``_payload`` an optional device
    array registered in the device ledger.  ``token``/``length``/
    ``source_id``/``epoch``/``mesh_key`` are the validity stamps;
    ``can_fold`` marks artifacts whose state admits an exact append-only
    combine (views/incremental.py).
    """

    __slots__ = (
        "kind", "params", "token", "length", "source_id", "epoch",
        "mesh_key", "state", "can_fold", "host_bytes", "_payload",
        "_dev_key", "owner_ref", "__weakref__",
    )

    #: recovery marker: reseat passes drop derived caches instead of
    #: replaying lineage for them (core/execution/recovery.py)
    is_derived_cache = True
    is_lazy = False

    def __init__(
        self,
        kind: str,
        params: Any,
        token: int,
        length: int,
        source_id: int,
        state: Optional[dict],
        can_fold: bool = False,
        payload: Any = None,
        host_bytes: int = 0,
    ):
        self.kind = kind
        self.params = params
        self.token = token
        self.length = int(length)
        self.source_id = source_id
        self.epoch = _current_epoch()
        self.mesh_key = _mesh_key()
        self.state = state
        self.can_fold = bool(can_fold)
        self.host_bytes = int(host_bytes)
        self._payload = payload
        self._dev_key = None
        self.owner_ref = None  # weakref to the owning column (set by store)

    @property
    def raw(self) -> Any:
        """Ledger protocol: the device payload this entry accounts for."""
        return self._payload

    @property
    def live(self) -> bool:
        return self.state is not None or self._payload is not None

    def drop(self) -> int:
        """Release payload + state; returns device bytes freed.

        Serialized under the registry lock so a reader holding it can never
        see the artifact torn by a concurrent ledger spill or recovery drop.
        """
        global _host_bytes_total
        with LOCK:
            freed = 0
            if self._payload is not None:
                from modin_tpu.core.memory import device_ledger

                freed = device_ledger.deregister(self)
                self._payload = None
            if self.state is not None:
                self.state = None
                _host_bytes_total -= self.host_bytes
                self.host_bytes = 0
            key = (self.token, self.kind, self.params)
            if _entries.get(key) is self:
                _entries.pop(key, None)
                toks = _by_token.get(self.token)
                if toks is not None:
                    toks.discard(key)
                    if not toks:
                        _by_token.pop(self.token, None)
            return freed

    def spill(self) -> int:
        """Ledger spill protocol: derived data is dropped, not copied out."""
        freed = self.drop()
        if freed:
            emit_metric("view.spill", 1)
        return freed


def _budget_entries() -> int:
    from modin_tpu.config import ViewsMaxEntries

    return int(ViewsMaxEntries.get())


def _budget_host_bytes() -> int:
    from modin_tpu.config import ViewsHostBudget

    return int(ViewsHostBudget.get())


def _enforce_locked() -> int:
    """Evict coldest artifacts past the entry/host-byte budgets (lock
    held); returns the eviction count for the caller to emit OUTSIDE the
    lock (metric fan-out must never run under it — the PR 9 gate-lock
    lesson: one slow handler would stall every thread's cache consult)."""
    max_entries = _budget_entries()
    max_bytes = _budget_host_bytes()
    evicted = 0
    while _entries and (
        len(_entries) > max_entries or _host_bytes_total > max_bytes
    ):
        _key, art = next(iter(_entries.items()))
        art.drop()  # removes itself from _entries/_by_token
        evicted += 1
    return evicted


def _drop_locked(art: Any, reason: str, pending: List[str]) -> None:
    """Drop under the lock, deferring the metric to ``pending`` (emitted
    by the caller after release)."""
    art.drop()
    pending.append(reason)


def _emit_dropped(pending: List[str]) -> None:
    for reason in pending:
        emit_metric(f"view.invalidate.{reason}", 1)


def _valid_locked(art: Any, col: Any) -> Optional[str]:
    """None when ``art`` is an exact live answer for ``col``; otherwise the
    staleness reason ('' = merely not-for-this-column, do not drop)."""
    if not art.live:
        return "dead"
    if art.epoch != _current_epoch():
        return "device_epoch"
    if art.mesh_key != _mesh_key():
        return "mesh_reshape"
    if art.token != getattr(col, "_view_token", None):
        return ""
    if art.length != col.length or art.source_id != id(col._data):
        return "buffer"
    return None


def column_artifact_kinds(col: Any) -> List[str]:
    """Artifact kinds live RIGHT NOW for ``col``'s exact token.

    graftopt's planning probe: no metrics, no LRU touch, no parent-chain
    walk — the plan-time cost model only wants to annotate "a registered
    view already answers this" legs, and a foldable ancestor is not a
    free answer.  Stale entries are left for :func:`lookup` to reap.
    """
    tok = getattr(col, "_view_token", None)
    if tok is None or col._data is None or getattr(col, "is_lazy", False):
        return []
    kinds: List[str] = []
    with LOCK:
        for key in _by_token.get(tok, ()):
            art = _entries.get(key)
            if art is not None and _valid_locked(art, col) is None:
                kinds.append(key[1])
    return kinds


def lookup(
    col: Any, kind: str, params: Any, consume: bool = True
) -> Tuple[str, Optional[dict], int]:
    """Consult the registry for ``col``'s ``(kind, params)`` artifact.

    Returns ``(outcome, state_snapshot, base_length)``:

    - ``("hit", state, col.length)`` — exact live answer for this buffer;
    - ``("fold", state, base_length)`` — an ancestor's artifact whose state
      covers rows ``[0, base_length)``; the caller folds the tail
      ``[base_length, col.length)`` and commits via :func:`store`;
    - ``("miss", None, 0)`` — compute from scratch.

    ``consume=False`` is the planning probe (the router's sorted-rep
    ``peek`` analogue): no hit/miss metrics, no LRU touch — the caller
    decides later whether the answer is actually used and then calls
    :func:`consume_hit`, so a query the router sends to host never counts
    artifact hits it did not serve.

    The state dict returned is the artifact's own; callers must not
    mutate it — folds build a fresh state dict and commit it with
    :func:`store`.
    """
    global _walk_hops_total, _walk_lookups, _chain_compactions
    tok = getattr(col, "_view_token", None)
    if tok is None or col._data is None or getattr(col, "is_lazy", False):
        return ("miss", None, 0)
    pending: List[str] = []
    outcome: Tuple[str, Optional[dict], int] = ("miss", None, 0)
    with LOCK:
        art = _entries.get((tok, kind, params))
        if art is not None:
            why = _valid_locked(art, col)
            if why is None:
                if consume:
                    _entries.move_to_end((tok, kind, params))
                    if art._payload is not None:
                        from modin_tpu.core.memory import device_ledger

                        device_ledger.touch(art)
                outcome = ("hit", art.state, col.length)
            elif why:
                _drop_locked(art, why, pending)
        if outcome[0] == "miss":
            # walk the parent chain for a foldable ancestor artifact
            link = getattr(col, "_view_parent", None)
            hops = 0
            bound = _max_chain()
            passed_clean = True  # every skipped token artifact-free?
            while link is not None and hops < bound:
                ptok, plen = link
                art = _entries.get((ptok, kind, params))
                if art is not None and art.live:
                    if (
                        art.epoch == _current_epoch()
                        and art.mesh_key == _mesh_key()
                        and art.length == plen
                    ):
                        if art.can_fold:
                            _entries.move_to_end((ptok, kind, params))
                            outcome = ("fold", art.state, plen)
                            if hops > 0 and passed_clean:
                                # path compression: every token walked
                                # through holds nothing for ANY kind, so
                                # re-anchoring the column straight to this
                                # ancestor loses no other lookup — the next
                                # walk is one hop
                                col._view_parent = (ptok, plen)
                                _note_link_locked(tok, (ptok, plen))
                                _link_depth[tok] = (
                                    _link_depth.get(ptok, 0) + 1
                                )
                                _chain_compactions += 1
                        else:
                            # honest invalidation: this artifact cannot
                            # absorb an append — name the reason.  Drop it
                            # only once its owning column is gone: a live
                            # parent keeps its warm answer and the child
                            # simply misses.
                            owner = art.owner_ref() if art.owner_ref else None
                            if owner is None:
                                _drop_locked(art, "not_incremental", pending)
                    break
                # follow the chain through columns the registry has seen;
                # parent links of dead intermediate columns are
                # unreachable, which is fine — deeper folds save less
                if ptok in _by_token:
                    passed_clean = False
                link = _parent_links.get(ptok)
                hops += 1
            _walk_hops_total += hops
            _walk_lookups += 1
    # metric fan-out OUTSIDE the lock (user metric handlers can be slow or
    # raise; neither may stall or break other threads' consults)
    _emit_dropped(pending)
    if consume:
        if outcome[0] == "hit":
            emit_metric("view.hit", 1)
        elif outcome[0] == "miss":
            emit_metric("view.miss", 1)
    return outcome


def consume_hit(col: Any, kind: str, params: Any) -> None:
    """Mark a previously peeked (``consume=False``) answer as actually
    served: LRU-touch the entry and emit ``view.hit``.  A no-op when the
    entry was concurrently invalidated — the value the caller already
    holds is still correct, it just no longer warms the cache."""
    tok = getattr(col, "_view_token", None)
    if tok is None:
        return
    touched = False
    with LOCK:
        art = _entries.get((tok, kind, params))
        if art is not None and _valid_locked(art, col) is None:
            _entries.move_to_end((tok, kind, params))
            touched = True
    if touched:
        emit_metric("view.hit", 1)


#: token -> its own (parent_token, parent_length) link, so fold lookups can
#: walk chains even after intermediate column objects are collected.
#: FIFO-bounded: links are two ints, but per-append growth must not be
#: unbounded over a long-lived serving process.
_parent_links: "OrderedDict[int, Tuple[int, int]]" = OrderedDict()
_PARENT_LINKS_MAX = 65536


def _note_link_locked(token: int, link: Tuple[int, int]) -> None:
    _parent_links[token] = link
    while len(_parent_links) > _PARENT_LINKS_MAX:
        old_tok, _ = _parent_links.popitem(last=False)
        _link_depth.pop(old_tok, None)


def store(
    col: Any,
    kind: str,
    params: Any,
    state: dict,
    can_fold: bool = False,
    payload: Any = None,
    host_bytes: int = 0,
    folded: bool = False,
) -> bool:
    """Commit an artifact for ``col``.  Returns False when the column's
    buffer changed since the caller computed (concurrent spill / donation /
    re-seat) — the stale-write guard: the result is still correct for the
    caller to RETURN, it just must not be cached against the new buffer."""
    global _host_bytes_total
    if col._data is None or getattr(col, "is_lazy", False):
        return False
    tok = ensure_token(col)
    with LOCK:
        if col._data is None:  # re-check under the lock (spill raced us)
            return False
        link = getattr(col, "_view_parent", None)
        if link is not None:
            _note_link_locked(tok, link)
        old = _entries.pop((tok, kind, params), None)
        if old is not None:
            old.drop()
        art = DerivedArtifact(
            kind, params, tok, col.length, id(col._data), state,
            can_fold=can_fold, payload=payload, host_bytes=host_bytes,
        )
        art.owner_ref = weakref.ref(col)
        _entries[(tok, kind, params)] = art
        _by_token.setdefault(tok, set()).add((tok, kind, params))
        _host_bytes_total += art.host_bytes
        if payload is not None:
            from modin_tpu.core.memory import device_ledger

            device_ledger.register(art)
        evicted = _enforce_locked()
    if evicted:
        emit_metric("view.evict", evicted)
    if folded:
        emit_metric("view.fold", 1)
    else:
        emit_metric("view.build", 1)
    return True


def invalidate_ancestor(col: Any, kind: str, params: Any, reason: str) -> None:
    """Drop the ancestor artifact a fold for ``col`` would consume — the
    caller discovered folding it can never succeed (e.g. the combined
    groupby table overflows the cacheable bound), so leaving it foldable
    would re-pay the wasted delta dispatch on every later query."""
    link = getattr(col, "_view_parent", None)
    pending: List[str] = []
    with LOCK:
        hops = 0
        bound = _max_chain()
        while link is not None and hops < bound:
            ptok, _plen = link
            art = _entries.get((ptok, kind, params))
            if art is not None and art.live:
                _drop_locked(art, reason, pending)
                break
            link = _parent_links.get(ptok)
            hops += 1
    _emit_dropped(pending)


def amend_ancestor_state(
    col: Any, kind: str, params: Any, base_len: int, key: str, value: Any,
    extra_bytes: int = 0,
) -> None:
    """Record a lazily-built auxiliary ``state[key]`` on the ancestor
    artifact a fold for ``col`` consumed (e.g. the mean fold's per-group
    count table, derived from the ancestor's own rows): later folds from
    the same ancestor then skip re-deriving it.  No-op when the ancestor
    is gone or already carries the key."""
    global _host_bytes_total
    link = getattr(col, "_view_parent", None)
    with LOCK:
        hops = 0
        bound = _max_chain()
        while link is not None and hops < bound:
            ptok, plen = link
            art = _entries.get((ptok, kind, params))
            if art is not None and art.live and art.length == base_len:
                if art.state.get(key) is None:
                    art.state[key] = value
                    art.host_bytes += int(extra_bytes)
                    _host_bytes_total += int(extra_bytes)
                return
            link = _parent_links.get(ptok)
            hops += 1


def invalidate_column(col: Any, reason: str = "buffer") -> None:
    """Drop every artifact registered under ``col``'s token (buffer
    mutation: spill / restore / re-seat / materialize / donation)."""
    tok = getattr(col, "_view_token", None)
    if tok is None:
        return
    pending: List[str] = []
    with LOCK:
        keys = _by_token.get(tok)
        if keys:
            for key in list(keys):
                art = _entries.get(key)
                if art is not None:
                    _drop_locked(art, reason, pending)
    _emit_dropped(pending)


def stats() -> dict:
    """Registry introspection (tests, smoke gates)."""
    with LOCK:
        return {
            "entries": len(_entries),
            "host_bytes": _host_bytes_total,
            "tokens": len(_by_token),
        }


def walk_stats() -> dict:
    """Chain-walk accounting: total lookups that walked the parent chain,
    total hops spent, and chain compactions performed (note_append bound
    + lookup path compression).  The satellite regression test asserts
    hops-per-lookup stays flat across 1k micro-batch appends."""
    with LOCK:
        return {
            "lookups": _walk_lookups,
            "hops": _walk_hops_total,
            "compactions": _chain_compactions,
        }


def live_artifacts() -> List[Any]:
    with LOCK:
        return list(_entries.values())


def reset() -> None:
    """Drop every artifact (tests)."""
    global _chain_compactions, _walk_hops_total, _walk_lookups
    with LOCK:
        for art in list(_entries.values()):
            art.drop()
        _entries.clear()
        _by_token.clear()
        _parent_links.clear()
        _link_depth.clear()
        _chain_compactions = 0
        _walk_hops_total = 0
        _walk_lookups = 0
