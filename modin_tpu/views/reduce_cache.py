"""graftview scalar-reduction caching: whole results, folded over appends.

The query compiler's axis-0 reduction path (``_try_device_reduce``) hands
this module its concrete device columns; per column the registry answers

- **hit** — the identical (op, skipna, ddof, cast_bool) reduction already
  ran on this exact buffer at this device epoch: zero dispatches;
- **fold** — the column grew by an append since the artifact was built:
  ONLY the appended tail is gathered and reduced (both dispatches go
  through the engine seam, so resilience / lineage / graftcost see the
  delta like any other work), then combined by views/incremental.py;
- **miss** — reduced from scratch (one fused dispatch over all missed
  columns, exactly the computation the Off path runs) and cached.

The assembled values are the same numpy scalars the plain path returns;
``MODIN_TPU_VIEWS=Off`` bypasses this module entirely.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from modin_tpu.observability import spans as graftscope
from modin_tpu.views import incremental, registry


def _mean_k(col: Any, n: int, skipna: bool) -> Optional[int]:
    """The valid count the mean artifact must carry, when it is knowable
    without a dispatch: the full length for NaN-free dtypes and for
    skipna=False (where the device mean divides by n)."""
    if col.pandas_dtype.kind != "f" or not skipna:
        return int(n)
    return None


def cached_reduce(
    op: str,
    cols: List[Any],
    n: int,
    skipna: bool,
    ddof: int,
    cast_bool: bool,
) -> Optional[List[np.ndarray]]:
    """Per-column results for ``op`` over ``cols`` using the artifact
    registry, or None to decline (the caller runs the plain path).

    Declines whenever any column is not a concrete resident DeviceColumn —
    lazy chains keep their fusion, spilled columns their restore path.
    """
    from modin_tpu.ops import reductions

    if op not in incremental.CACHEABLE_REDUCES:
        return None
    for c in cols:
        if not getattr(c, "is_device", False) or c._data is None or c.is_lazy:
            return None
    n, skipna, ddof = int(n), bool(skipna), int(ddof)
    params = (op, skipna, ddof, bool(cast_bool))
    can_fold = op in incremental.FOLDABLE_REDUCES

    results: List[Optional[np.ndarray]] = [None] * len(cols)
    misses: List[int] = []
    folds: List[Any] = []  # (i, state, base_len)
    for i, col in enumerate(cols):
        outcome, state, base = registry.lookup(col, "reduce", params)
        if outcome == "hit":
            results[i] = state["r"]
        elif outcome == "fold" and can_fold:
            folds.append((i, state, base))
        else:
            misses.append(i)

    if misses:
        values = reductions.reduce_columns(
            op, [cols[i].data for i in misses], n,
            skipna=skipna, ddof=ddof, cast_bool=cast_bool,
        )
        for i, v in zip(misses, values):
            results[i] = v
            state = {"r": v}
            if op == "mean":
                # the valid count the fold needs: knowable for free on
                # NaN-free dtypes / skipna=False; a float skipna mean
                # stores None and derives it LAZILY at first fold over the
                # prefix rows (the groupby cache's count_pdf discipline) —
                # the cold no-reuse path must stay at one dispatch
                state["k"] = _mean_k(cols[i], n, skipna)
            registry.store(
                cols[i], "reduce", params, state,
                can_fold=can_fold, host_bytes=64,
            )

    if folds:
        _fold_reduces(op, cols, n, skipna, ddof, cast_bool, params, folds, results)

    return [r for r in results]


def _fold_reduces(
    op: str,
    cols: List[Any],
    n: int,
    skipna: bool,
    ddof: int,
    cast_bool: bool,
    params: tuple,
    folds: List[Any],
    results: List[Optional[np.ndarray]],
) -> None:
    """Reduce each fold column's appended tail and combine with its cached
    prefix state; groups columns by base length so one gather + one fused
    reduce serves each append generation."""
    from modin_tpu.ops import reductions
    from modin_tpu.ops.structural import gather_columns

    by_base: dict = {}
    for i, state, base in folds:
        by_base.setdefault(base, []).append((i, state))
    for base, group in by_base.items():
        n_tail = n - base
        idxs = [i for i, _ in group]
        with graftscope.span(
            "view.fold", layer="QUERY-COMPILER", op=op, cols=len(idxs),
            base=base, tail=n_tail,
        ):
            if n_tail == 0:
                tail_values = None
            else:
                tails, _ = gather_columns(
                    [cols[i].data for i in idxs],
                    np.arange(base, n, dtype=np.int64),
                )
                tail_values = reductions.reduce_columns(
                    op, tails, n_tail,
                    skipna=skipna, ddof=ddof, cast_bool=cast_bool,
                )
                tail_counts = None
                base_counts = None
                if op == "mean":
                    need_k = [
                        j for j, i in enumerate(idxs)
                        if _mean_k(cols[i], n_tail, skipna) is None
                    ]
                    if need_k:
                        counted = reductions.reduce_columns(
                            "count", [tails[j] for j in need_k], n_tail,
                            skipna=True,
                        )
                        tail_counts = dict(zip(need_k, counted))
                    # lazily derive the PREFIX counts the cold path did
                    # not pay for: the prefix rows [0, base) ARE the
                    # ancestor's rows (append-link invariant), and the
                    # result is amended back so repeat folds skip it
                    need_k0 = [
                        j for j, (i, st) in enumerate(group)
                        if st.get("k") is None
                    ]
                    if need_k0:
                        prefix, _ = gather_columns(
                            [cols[group[j][0]].data for j in need_k0],
                            np.arange(0, base, dtype=np.int64),
                        )
                        counted0 = reductions.reduce_columns(
                            "count", prefix, base, skipna=True
                        )
                        base_counts = dict(zip(need_k0, counted0))
            for j, (i, state) in enumerate(group):
                if tail_values is None:
                    new_state = dict(state)  # empty tail: the prefix answer
                elif op == "mean":
                    k_tail = _mean_k(cols[i], n_tail, skipna)
                    if k_tail is None:
                        k_tail = int(tail_counts[j])
                    k_base = state["k"]
                    if k_base is None:
                        k_base = int(base_counts[j])
                        registry.amend_ancestor_state(
                            cols[i], "reduce", params, base, "k", k_base
                        )
                    m, k = incremental.combine_mean(
                        state["r"], k_base, tail_values[j], k_tail
                    )
                    new_state = {"r": np.asarray(m), "k": k}
                else:
                    new_state = {
                        "r": incremental.combine_scalar(
                            op, skipna, state["r"], tail_values[j]
                        )
                    }
                results[i] = new_state["r"]
                registry.store(
                    cols[i], "reduce", params, new_state,
                    can_fold=True, host_bytes=64, folded=True,
                )


# --------------------------------------------------------------------- #
# sort-shaped result caches (nunique / mode / median): exact-hit only —
# these are the honestly-non-incrementalizable artifacts
# --------------------------------------------------------------------- #


def sort_reduce_lookup(op: str, params: tuple, cols: List[Any]) -> dict:
    """{column position: cached result} for plain device columns.

    A planning PEEK: no hit metrics, no LRU touch — the router may still
    route the whole op to host, in which case nothing was served.  The
    caller confirms actually-used answers with :func:`sort_reduce_consume`
    after the routing decision."""
    out = {}
    for i, col in enumerate(cols):
        if col is None:
            continue
        outcome, state, _ = registry.lookup(
            col, f"sortred.{op}", params, consume=False
        )
        if outcome == "hit":
            out[i] = state["r"]
    return out


def sort_reduce_consume(op: str, params: tuple, cols: List[Any], used) -> None:
    """Mark the peeked answers at positions ``used`` as served (view.hit
    + LRU touch) — called after the router chose the device side."""
    for i in used:
        if cols[i] is not None:
            registry.consume_hit(cols[i], f"sortred.{op}", params)


def sort_reduce_store(op: str, params: tuple, col: Any, value: Any) -> None:
    registry.store(
        col, f"sortred.{op}", params, {"r": value},
        can_fold=False, host_bytes=_state_bytes(value),
    )


def _state_bytes(value: Any) -> int:
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        return int(nbytes) + 64
    if isinstance(value, tuple):
        return sum(_state_bytes(v) for v in value)
    return 64
