"""Test-support utilities shipped with the library (fault injection, ...)."""

from modin_tpu.testing.faults import (  # noqa: F401
    FaultInjector,
    inject_faults,
    make_device_error,
)
