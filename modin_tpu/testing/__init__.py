"""Test-support utilities shipped with the library (fault injection, ...)."""

from modin_tpu.testing.faults import (  # noqa: F401
    DiskFaultInjector,
    FaultInjector,
    MixedFaultInjector,
    OomBurstInjector,
    ReplicaFaultInjector,
    SequencedFaultInjector,
    concurrent_chaos,
    inject_disk_faults,
    inject_faults,
    make_device_error,
    midquery_device_loss,
    oom_burst_until_eviction,
)
