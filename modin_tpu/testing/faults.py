"""Deterministic fault injection at the ``JaxWrapper`` engine seam.

The resilience layer (modin_tpu/core/execution/resilience.py) is only
trustworthy if its failure handling can be exercised on demand, on any
substrate, without a real device OOM or a yanked TPU tunnel.  This harness
installs a hook at the engine seam — it fires inside every
``JaxWrapper.deploy/put/materialize/wait`` attempt, *under* the resilience
wrapper — raising synthetic but *real-typed* ``XlaRuntimeError``s, or
stalling (slow-kernel), on a deterministic schedule:

    from modin_tpu.testing import inject_faults

    with inject_faults("oom", ops=("materialize",), times=3) as inj:
        df.nlargest(5, "a")          # device path strikes, pandas answers
    assert inj.injected == 3

Because the hook runs inside the attempt, an injected transient fault is
retried by the real backoff loop, a slow-kernel stall trips the real
watchdog, and an OOM strikes the real breaker — the full production path,
minus the hardware.  Faults fire on the first ``times`` matching calls
(after ``skip`` clean ones); no randomness, so a failing sequence replays
exactly.  When the host jaxlib exposes ``XlaRuntimeError`` the harness
raises that very type; otherwise a stand-in with the same name is raised,
which the taxonomy's name-based classification treats identically.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Iterable, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.core.execution import resilience

_FAULT_MESSAGES = {
    "oom": (
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "9437184000 bytes. [injected by modin_tpu.testing.faults]"
    ),
    "device_lost": (
        "UNAVAILABLE: device lost: tunnel heartbeat missed, socket closed "
        "[injected by modin_tpu.testing.faults]"
    ),
    "transient": (
        "DEADLINE_EXCEEDED: operation timed out after 60s "
        "[injected by modin_tpu.testing.faults]"
    ),
}

_ENGINE_OPS = ("deploy", "put", "materialize", "wait")


def _runtime_error_type() -> type:
    """The host runtime's XlaRuntimeError, or a same-named stand-in."""
    try:
        from jax._src.lib import xla_client

        return xla_client.XlaRuntimeError
    except Exception:  # pragma: no cover - depends on host jaxlib
        return type("XlaRuntimeError", (RuntimeError,), {})


def make_device_error(
    kind: str, shard_index: Optional[int] = None
) -> BaseException:
    """A real-typed runtime error whose message classifies as ``kind``
    (one of 'oom', 'device_lost', 'transient').

    ``shard_index`` (device_lost only) names ONE lost mesh row shard in
    the message the way a real runtime names a device; the taxonomy parses
    it back out and graftmesh recovery re-seats only that shard's slices.
    """
    if kind not in _FAULT_MESSAGES:
        raise ValueError(
            f"unknown fault kind {kind!r}; expected one of "
            f"{sorted(_FAULT_MESSAGES)} or 'slow_kernel'"
        )
    message = _FAULT_MESSAGES[kind]
    if shard_index is not None and kind == "device_lost":
        message = f"{message} shard_index={int(shard_index)}"
    return _runtime_error_type()(message)


class FaultInjector:
    """Context manager: fault ``JaxWrapper`` attempts deterministically.

    Parameters
    ----------
    kind : 'oom' | 'device_lost' | 'transient' | 'slow_kernel'
        What each injected fault does.  'slow_kernel' sleeps ``slow_s``
        inside the attempt (completing, but late — visible to the watchdog
        and the breaker's latency budget).
    ops : iterable of {'deploy', 'put', 'materialize', 'wait'}
        Which engine methods the schedule watches.
    times : int or None
        How many matching attempts fault (None = every one while active).
    skip : int
        Matching attempts to let through cleanly before the first fault.
    slow_s : float
        Stall duration for 'slow_kernel'.

    Attributes: ``injected`` (faults fired), ``calls`` (matching attempts
    seen).  Only one injector may be active at a time — deterministic
    schedules do not compose.
    """

    def __init__(
        self,
        kind: str = "transient",
        ops: Iterable[str] = _ENGINE_OPS,
        times: Optional[int] = 1,
        skip: int = 0,
        slow_s: float = 0.05,
        shard_index: Optional[int] = None,
    ):
        if kind != "slow_kernel" and kind not in _FAULT_MESSAGES:
            raise ValueError(f"unknown fault kind {kind!r}")
        unknown = set(ops) - set(_ENGINE_OPS)
        if unknown:
            raise ValueError(f"unknown engine ops {sorted(unknown)}")
        self.kind = kind
        self.ops = frozenset(ops)
        self.times = times
        self.skip = skip
        self.slow_s = slow_s
        self.shard_index = shard_index
        self.injected = 0
        self.calls = 0
        self._lock = named_lock("testing.faults")

    def _hook(self, op: str) -> None:
        if op not in self.ops:
            return
        with self._lock:
            self.calls += 1
            if self.calls <= self.skip:
                return
            if self.times is not None and self.injected >= self.times:
                return
            self.injected += 1
        if self.kind == "slow_kernel":
            time.sleep(self.slow_s)
            return
        raise make_device_error(self.kind, shard_index=self.shard_index)

    def __enter__(self) -> "FaultInjector":
        if resilience._fault_hook is not None:
            raise RuntimeError("another FaultInjector is already active")
        resilience._fault_hook = self._hook
        return self

    def __exit__(self, *exc_info: Any) -> None:
        resilience._fault_hook = None


def inject_faults(
    kind: str = "transient",
    ops: Iterable[str] = _ENGINE_OPS,
    times: Optional[int] = 1,
    skip: int = 0,
    slow_s: float = 0.05,
) -> FaultInjector:
    """Sugar for ``FaultInjector(...)`` — see its docstring."""
    return FaultInjector(kind=kind, ops=ops, times=times, skip=skip, slow_s=slow_s)


# ---------------------------------------------------------------------- #
# sequenced injectors (the graftguard chaos suite)
# ---------------------------------------------------------------------- #


class SequencedFaultInjector(FaultInjector):
    """Scripted multi-phase fault schedule at the engine seam.

    ``steps`` is an ordered list of ``(kind, count)`` pairs; ``kind`` is
    ``'clean'`` (let the attempt through) or any FaultInjector kind, and
    each step consumes ``count`` matching attempts before the schedule
    advances.  After the last step everything runs clean — exactly the
    shape of a real incident: healthy, then a failure window, then healed.

        # DeviceLost mid-query: 4 good deploys, then the device vanishes
        # for 2 dispatches, then the replacement device answers
        with SequencedFaultInjector(
            [("clean", 4), ("device_lost", 2)], ops=("deploy",)
        ) as inj:
            ...

    ``injected`` counts faults fired, ``calls`` matching attempts seen.
    """

    def __init__(
        self,
        steps: Iterable[tuple],
        ops: Iterable[str] = _ENGINE_OPS,
        slow_s: float = 0.05,
        shard_index: Optional[int] = None,
    ):
        super().__init__(
            kind="transient", ops=ops, times=0, slow_s=slow_s,
            shard_index=shard_index,
        )
        self.steps = [(str(kind), int(count)) for kind, count in steps]
        for kind, count in self.steps:
            if kind != "clean" and kind != "slow_kernel" and kind not in _FAULT_MESSAGES:
                raise ValueError(f"unknown fault kind {kind!r} in steps")
            if count < 0:
                raise ValueError(f"negative step count {count} for {kind!r}")
        self._step = 0
        self._step_used = 0

    def _hook(self, op: str) -> None:
        if op not in self.ops:
            return
        with self._lock:
            self.calls += 1
            while (
                self._step < len(self.steps)
                and self._step_used >= self.steps[self._step][1]
            ):
                self._step += 1
                self._step_used = 0
            if self._step >= len(self.steps):
                return  # schedule exhausted: healed
            kind = self.steps[self._step][0]
            self._step_used += 1
            if kind == "clean":
                return
            self.injected += 1
        if kind == "slow_kernel":
            time.sleep(self.slow_s)
            return
        raise make_device_error(kind, shard_index=self.shard_index)


def midquery_device_loss(
    after_deploys: int,
    times: int = 1,
    ops: Iterable[str] = ("deploy",),
    shard_index: Optional[int] = None,
) -> SequencedFaultInjector:
    """DeviceLost mid-query: after ``after_deploys`` successful dispatches
    the next ``times`` attempts raise UNAVAILABLE, then the (replacement)
    device answers — the recovery manager's acceptance scenario.

    ``shard_index`` kills ONE mesh row shard instead of the whole device:
    the error names the shard and graftmesh recovery re-seats only that
    shard's slice of every host-backed column (``recovery.reseat.shard``).
    """
    return SequencedFaultInjector(
        [("clean", after_deploys), ("device_lost", times)], ops=ops,
        shard_index=shard_index,
    )


class OomBurstInjector(FaultInjector):
    """RESOURCE_EXHAUSTED burst that clears once eviction frees memory.

    Matching attempts raise OOM while the device-memory ledger has
    recorded fewer than ``spills`` new spill events since ``__enter__`` —
    the moment evict-then-retry (or admission control) actually spills,
    the modeled memory pressure is gone and every later attempt runs
    clean.  ``max_faults`` bounds the burst as a test-hang backstop.
    """

    def __init__(
        self,
        ops: Iterable[str] = ("deploy",),
        spills: int = 1,
        max_faults: Optional[int] = 25,
    ):
        super().__init__(kind="oom", ops=ops, times=max_faults)
        if spills <= 0:
            raise ValueError(f"spills must be > 0, got {spills}")
        self.spills = spills
        self._baseline = 0

    def __enter__(self) -> "OomBurstInjector":
        from modin_tpu.core.memory import device_ledger

        self._baseline = device_ledger.spill_count()
        return super().__enter__()

    def _hook(self, op: str) -> None:
        if op not in self.ops:
            return
        from modin_tpu.core.memory import device_ledger

        with self._lock:
            self.calls += 1
            if device_ledger.spill_count() - self._baseline >= self.spills:
                return  # eviction freed the memory: pressure cleared
            if self.times is not None and self.injected >= self.times:
                return
            self.injected += 1
        raise make_device_error("oom")


def oom_burst_until_eviction(
    ops: Iterable[str] = ("deploy",),
    spills: int = 1,
    max_faults: Optional[int] = 25,
) -> OomBurstInjector:
    """Sugar for ``OomBurstInjector(...)`` — see its docstring."""
    return OomBurstInjector(ops=ops, spills=spills, max_faults=max_faults)


# ---------------------------------------------------------------------- #
# concurrent injectors (the graftgate serving chaos suite)
# ---------------------------------------------------------------------- #


class MixedFaultInjector(FaultInjector):
    """Interleaved fault kinds under concurrency: the serving chaos shape.

    With N threads running mixed queries, WHICH thread eats a fault is a
    scheduling accident — so this injector is deterministic in the
    *aggregate*, not per thread: every ``period``-th matching attempt
    (process-wide, counted under the injector lock) faults, cycling
    through ``kinds`` in order, until ``times`` faults have fired.  An
    OOM burst and a mid-query DeviceLost therefore land while other
    threads' queries are genuinely in flight — exactly the incident shape
    the serving acceptance suite must survive (every query completes
    bit-exact or fails with a typed serving error; zero hangs).

        with MixedFaultInjector(
            kinds=("oom", "device_lost"), ops=("deploy",), period=5, times=6
        ) as inj:
            ...  # N threads submit queries
        assert inj.injected == 6
    """

    def __init__(
        self,
        kinds: Iterable[str] = ("oom", "device_lost"),
        ops: Iterable[str] = ("deploy",),
        period: int = 5,
        times: Optional[int] = 8,
        slow_s: float = 0.05,
    ):
        super().__init__(kind="transient", ops=ops, times=times, slow_s=slow_s)
        self.kinds = tuple(str(k) for k in kinds)
        if not self.kinds:
            raise ValueError("kinds must name at least one fault kind")
        for kind in self.kinds:
            if kind != "slow_kernel" and kind not in _FAULT_MESSAGES:
                raise ValueError(f"unknown fault kind {kind!r} in kinds")
        if period <= 0:
            raise ValueError(f"period must be > 0, got {period}")
        self.period = period

    def _hook(self, op: str) -> None:
        if op not in self.ops:
            return
        with self._lock:
            self.calls += 1
            if self.calls <= self.skip or self.calls % self.period != 0:
                return
            if self.times is not None and self.injected >= self.times:
                return
            kind = self.kinds[self.injected % len(self.kinds)]
            self.injected += 1
        if kind == "slow_kernel":
            time.sleep(self.slow_s)
            return
        raise make_device_error(kind)


def concurrent_chaos(
    kinds: Iterable[str] = ("oom", "device_lost"),
    ops: Iterable[str] = ("deploy",),
    period: int = 5,
    times: Optional[int] = 8,
) -> MixedFaultInjector:
    """Sugar for ``MixedFaultInjector(...)`` — see its docstring."""
    return MixedFaultInjector(kinds=kinds, ops=ops, period=period, times=times)


# ---------------------------------------------------------------------- #
# process-level injectors (the graftfleet replica chaos suite)
# ---------------------------------------------------------------------- #


class ReplicaFaultInjector:
    """Kill, wedge, and re-crash live graftfleet replicas on demand.

    Unlike the engine-seam injectors above, these faults are real OS
    signals against real supervised processes — the fleet's failure
    detection has to earn every leg:

    - :meth:`kill` — SIGKILL (``kill -9``): the process-exit and
      dead-socket-on-dispatch detection legs;
    - :meth:`hang` — SIGSTOP: the process freezes with its sockets still
      connected (the kernel keeps accepting on its backlog), so only the
      heartbeat-age + liveness-probe-timeout leg can catch it;
    - :meth:`resume` — SIGCONT, for tests that un-wedge a survivor;
    - :meth:`crash_next_respawn` — arm a one-shot crash *inside the next
      respawned replica's warm RPC* (``os._exit(3)`` before any dataset
      loads), proving the coordinator survives a respawn that itself
      dies and retries the slot on the following monitor tick.

        inj = ReplicaFaultInjector(coordinator)
        inj.kill(1)          # replica 1 dies mid-query
        inj.hang(0)          # replica 0 wedges; probe timeout declares it
    """

    def __init__(self, coordinator: Any):
        self.coordinator = coordinator

    def _pid(self, index: int) -> int:
        rep = self.coordinator._replicas[index]
        if rep.pid is None:
            raise RuntimeError(f"replica {index} has no live process")
        return rep.pid

    def kill(self, index: int) -> int:
        """SIGKILL replica ``index``; returns the pid it killed."""
        import os
        import signal as _signal

        pid = self._pid(index)
        os.kill(pid, _signal.SIGKILL)
        return pid

    def hang(self, index: int) -> int:
        """SIGSTOP replica ``index`` (socket stays up, process wedges)."""
        import os
        import signal as _signal

        pid = self._pid(index)
        os.kill(pid, _signal.SIGSTOP)
        return pid

    def resume(self, index: int) -> int:
        """SIGCONT replica ``index`` (undo :meth:`hang`)."""
        import os
        import signal as _signal

        pid = self._pid(index)
        os.kill(pid, _signal.SIGCONT)
        return pid

    def crash_next_respawn(self) -> None:
        """Arm a one-shot crash in the next respawn's warm RPC."""
        self.coordinator._test_crash_next_respawn = True


# ---------------------------------------------------------------------- #
# disk injectors (the graftwal durability suite)
# ---------------------------------------------------------------------- #

_DISK_OPS = (
    "wal.write",
    "wal.fsync",
    "wal.truncate",
    "checkpoint.write",
    "checkpoint.truncate",
)


class DiskFaultInjector:
    """Deterministic disk faults at the graftwal seam
    (``modin_tpu.durability.wal._disk_fault_hook``).

    Every WAL/checkpoint disk operation consults the hook first, so the
    schedule decides exactly WHICH write/fsync/truncate fails and how:

    - ``'enospc'`` — ``OSError(ENOSPC)``: exercises the reclaim-then-
      retry path and the typed ``DurabilityError`` refusal;
    - ``'eio'`` — ``OSError(EIO)``: trips the per-feed breaker into
      memory-only degraded mode (``wal.degraded``);
    - ``'fsync_fail'`` — ``OSError(EIO)`` aimed at fsync ops (an fsync
      that fails is durability already lost: the writer degrades);
    - ``'torn_write'`` — valid for ``wal.write`` only: the first
      ``torn_bytes`` bytes of the record land on disk and the process
      SIGKILLs itself — a REAL torn tail for recovery to truncate;
    - ``'kill'`` — SIGKILL immediately *before* the matching operation:
      mid-batch (``wal.write``), mid-checkpoint (``checkpoint.write``),
      mid-truncate (``wal.truncate`` / ``checkpoint.truncate``) crash
      points for the differential recovery grid.

    Same determinism contract as the engine-seam injectors: faults fire
    on the first ``times`` matching calls after ``skip`` clean ones, one
    injector active at a time.

        with DiskFaultInjector("enospc", ops=("wal.write",)) as inj:
            feed.append(batch)       # reclaim runs, then the retry lands
        assert inj.injected == 1
    """

    def __init__(
        self,
        kind: str = "eio",
        ops: Iterable[str] = ("wal.write",),
        times: Optional[int] = 1,
        skip: int = 0,
        torn_bytes: int = 5,
    ):
        if kind not in ("enospc", "eio", "fsync_fail", "torn_write", "kill"):
            raise ValueError(f"unknown disk fault kind {kind!r}")
        unknown = set(ops) - set(_DISK_OPS)
        if unknown:
            raise ValueError(f"unknown disk ops {sorted(unknown)}")
        if kind == "torn_write" and set(ops) != {"wal.write"}:
            raise ValueError(
                "torn_write is only meaningful for ops=('wal.write',)"
            )
        self.kind = kind
        self.ops = frozenset(ops)
        self.times = times
        self.skip = skip
        self.torn_bytes = int(torn_bytes)
        self.injected = 0
        self.calls = 0
        self._lock = named_lock("testing.faults")

    def _hook(self, op: str) -> Optional[int]:
        if op not in self.ops:
            return None
        with self._lock:
            self.calls += 1
            if self.calls <= self.skip:
                return None
            if self.times is not None and self.injected >= self.times:
                return None
            self.injected += 1
        if self.kind == "enospc":
            import errno

            raise OSError(
                errno.ENOSPC,
                "No space left on device [injected by modin_tpu.testing.faults]",
            )
        if self.kind in ("eio", "fsync_fail"):
            import errno

            raise OSError(
                errno.EIO,
                "Input/output error [injected by modin_tpu.testing.faults]",
            )
        if self.kind == "torn_write":
            return self.torn_bytes  # the writer lands a prefix + SIGKILLs
        # 'kill': die before the operation — nothing of it reaches disk
        import os as _os
        import signal as _signal

        _os.kill(_os.getpid(), _signal.SIGKILL)
        return None  # pragma: no cover - unreachable

    def __enter__(self) -> "DiskFaultInjector":
        from modin_tpu.durability import wal as _wal

        if _wal._disk_fault_hook is not None:
            raise RuntimeError("another DiskFaultInjector is already active")
        _wal._disk_fault_hook = self._hook
        return self

    def __exit__(self, *exc_info: Any) -> None:
        from modin_tpu.durability import wal as _wal

        _wal._disk_fault_hook = None


def inject_disk_faults(
    kind: str = "eio",
    ops: Iterable[str] = ("wal.write",),
    times: Optional[int] = 1,
    skip: int = 0,
    torn_bytes: int = 5,
) -> DiskFaultInjector:
    """Sugar for ``DiskFaultInjector(...)`` — see its docstring."""
    return DiskFaultInjector(
        kind=kind, ops=ops, times=times, skip=skip, torn_bytes=torn_bytes
    )
