"""Picklable database-connection descriptor.

Reference design: modin/db_conn.py — a connection is described (module +
args) rather than held, so parallel readers can each open their own.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence


class UnsupportedDatabaseException(Exception):
    pass


_PSYCOPG_LIB_NAME = "psycopg2"
_SQLALCHEMY_LIB_NAME = "sqlalchemy"
_SQLITE3_LIB_NAME = "sqlite3"


class ModinDatabaseConnection:
    """Distributable descriptor of how to open a DB connection."""

    def __init__(self, lib: str, *args: Any, **kwargs: Any):
        lib = lib.lower()
        if lib not in (_PSYCOPG_LIB_NAME, _SQLALCHEMY_LIB_NAME, _SQLITE3_LIB_NAME):
            raise UnsupportedDatabaseException(f"Unsupported database library {lib}")
        self.lib = lib
        self.args = args
        self.kwargs = kwargs
        self._dialect_is_microsoft_sql_cache: Optional[bool] = None

    def _dialect_is_microsoft_sql(self) -> bool:
        if self._dialect_is_microsoft_sql_cache is None:
            self._dialect_is_microsoft_sql_cache = False
            if self.lib == _SQLALCHEMY_LIB_NAME:
                from sqlalchemy import create_engine

                self._dialect_is_microsoft_sql_cache = create_engine(
                    *self.args, **self.kwargs
                ).driver in ("pymssql", "pyodbc")
        return self._dialect_is_microsoft_sql_cache

    def get_connection(self) -> Any:
        """Open a fresh connection from the descriptor."""
        if self.lib == _PSYCOPG_LIB_NAME:
            import psycopg2

            return psycopg2.connect(*self.args, **self.kwargs)
        if self.lib == _SQLALCHEMY_LIB_NAME:
            from sqlalchemy import create_engine

            return create_engine(*self.args, **self.kwargs).connect()
        import sqlite3

        return sqlite3.connect(*self.args, **self.kwargs)

    def column_names_query(self, query: str) -> str:
        return f"SELECT * FROM ({query}) AS _MODIN_COUNT_QUERY LIMIT 0"

    def row_count_query(self, query: str) -> str:
        return f"SELECT COUNT(*) FROM ({query}) AS _MODIN_COUNT_QUERY"

    def supports_stable_offset_partitioning(self) -> bool:
        """Whether LIMIT/OFFSET windows over independent connections are
        repeatable.  sqlite scans in rowid order; most server engines give no
        stable order without a total ORDER BY, so they read serially (use the
        bounds-based ``experimental.pandas.read_sql`` for parallel reads)."""
        return self.lib == _SQLITE3_LIB_NAME

    def partition_query(self, query: str, limit: int, offset: int) -> str:
        """A query fetching rows [offset, offset+limit) of ``query``."""
        if self._dialect_is_microsoft_sql():
            return (
                f"SELECT * FROM ({query}) AS _MODIN_QUERY ORDER BY(SELECT NULL) "
                f"OFFSET {offset} ROWS FETCH NEXT {limit} ROWS ONLY"
            )
        return (
            f"SELECT * FROM ({query}) AS _MODIN_QUERY "
            f"LIMIT {limit} OFFSET {offset}"
        )
