"""Configuration system: typed env-var parameters with pubsub callbacks.

Reference design: /root/reference/modin/config/__init__.py.
"""

from modin_tpu.config.envvars import (  # noqa: F401
    AsvImplementation,
    AutoSwitchBackend,
    Backend,
    BenchmarkMode,
    CompilationCacheDir,
    CpuCount,
    DeviceCount,
    DevicePutChunkBytes,
    DocModule,
    DynamicPartitioning,
    Engine,
    EnvironmentVariable,
    Float64Policy,
    IsDebug,
    LazyExecution,
    LogFileSize,
    LogMemoryInterval,
    LogMode,
    Memory,
    MeshShape,
    MetricsMode,
    MinColumnPartitionSize,
    MinRowPartitionSize,
    NativePandasMaxRows,
    NativePandasTransferThreshold,
    NPartitions,
    PersistentPickle,
    ProgressBar,
    RangePartitioning,
    ReadSqlEngine,
    StateId,
    StorageFormat,
    TestDatasetSize,
    TpuNumpy,
    TrackFileLeaks,
)
from modin_tpu.config.pubsub import Parameter, ValueSource  # noqa: F401
