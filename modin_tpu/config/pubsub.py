"""Typed configuration parameters with publish/subscribe callbacks.

TPU-native re-design of the reference's config core
(/root/reference/modin/config/pubsub.py:195-520): a ``Parameter`` owns a typed
value sourced from DEFAULT < CONFIG (env var) < SET (runtime), and notifies
subscribers on change.  The subscription mechanism is what lets the factory
dispatcher re-bind the execution backend when ``Engine``/``StorageFormat``
change mid-session.
"""

from __future__ import annotations

import contextlib
import warnings
from enum import IntEnum
from typing import Any, Callable, NamedTuple, Optional


class ValueSource(IntEnum):
    """Where a parameter's current value came from (priority order)."""

    DEFAULT = 0
    GOT_FROM_CFG_SOURCE = 1
    SET_BY_USER = 2


class DeprecationDescriptor(NamedTuple):
    """Marks a parameter (or one of its values) deprecated in favor of another."""

    parameter: type
    new_parameter: Optional[type] = None
    when_removed: Optional[str] = None

    def deprecation_message(self, use_envvar_names: bool = False) -> str:
        name = (
            getattr(self.parameter, "varname", self.parameter.__name__)
            if use_envvar_names
            else self.parameter.__name__
        )
        msg = f"'{name}' is deprecated"
        if self.when_removed:
            msg += f" and will be removed in {self.when_removed}"
        if self.new_parameter is not None:
            new_name = (
                getattr(self.new_parameter, "varname", self.new_parameter.__name__)
                if use_envvar_names
                else self.new_parameter.__name__
            )
            msg += f"; use '{new_name}' instead"
        return msg + "."


class TypeDescriptor(NamedTuple):
    """How to decode/verify a raw (usually string) config value."""

    decode: Callable[[str], Any]
    normalize: Callable[[Any], Any]
    verify: Callable[[Any], bool]
    help: str


def _bool_decode(value: str) -> bool:
    return value.strip().lower() in {"true", "yes", "1", "on"}


def _int_decode(value: str) -> int:
    return int(value.strip())


def _float_decode(value: str) -> float:
    return float(value.strip())


def _str_decode(value: str) -> str:
    return value.strip()


def _tuple_of_ints_decode(value: str) -> tuple:
    return tuple(int(x) for x in value.replace("(", "").replace(")", "").split(",") if x.strip())


class ExactStr(str):
    """Marker type: a string that must not be title-cased/normalized."""


_TYPE_PARAMS = {
    bool: TypeDescriptor(
        decode=_bool_decode,
        normalize=bool,
        verify=lambda v: isinstance(v, bool)
        or (isinstance(v, str) and v.strip().lower() in {"true", "yes", "1", "on", "false", "no", "0", "off"}),
        help="a boolean flag (any of 'true', 'yes', '1', 'on' in any case)",
    ),
    int: TypeDescriptor(
        decode=_int_decode,
        normalize=int,
        verify=lambda v: isinstance(v, int)
        or (isinstance(v, str) and v.strip().lstrip("+-").isdigit()),
        help="an integer value",
    ),
    float: TypeDescriptor(
        decode=_float_decode,
        normalize=float,
        verify=lambda v: isinstance(v, (int, float))
        or (isinstance(v, str) and v.strip().replace(".", "", 1).replace("-", "", 1).isdigit()),
        help="a float value",
    ),
    str: TypeDescriptor(
        decode=_str_decode,
        normalize=lambda v: str(v).strip().title(),
        verify=lambda v: True,
        help="a case-insensitive string value",
    ),
    ExactStr: TypeDescriptor(
        decode=lambda v: v,
        normalize=lambda v: v,
        verify=lambda v: True,
        help="a string value (case preserved)",
    ),
    tuple: TypeDescriptor(
        decode=_tuple_of_ints_decode,
        normalize=lambda v: tuple(int(x) for x in v),
        verify=lambda v: isinstance(v, (tuple, list, str)),
        help="a comma-separated tuple of integers, e.g. '4,2'",
    ),
}


class Parameter:
    """A typed, subscribable configuration parameter.

    Subclasses define ``default``, ``choices`` and ``type``; concrete config
    sources (environment variables) override ``_get_raw_from_config`` /
    ``_check_callbacks``-time behavior.
    """

    choices: Optional[tuple] = None
    type: type = str
    default: Optional[Any] = None
    is_abstract: bool = True
    _deprecation_descriptor: Optional[DeprecationDescriptor] = None

    _value: Any = None
    _value_source: Optional[ValueSource] = None
    _subs: list
    _once: dict

    @classmethod
    def _get_raw_from_config(cls) -> str:
        """Read the raw value from the backing config source; KeyError if unset."""
        raise KeyError(cls.__name__)

    @classmethod
    def get_help(cls) -> str:
        raise NotImplementedError

    def __init_subclass__(cls, type: type = str, abstract: bool = False, **kw):
        super().__init_subclass__(**kw)
        cls.type = type
        cls.is_abstract = abstract
        cls._value = None
        cls._value_source = None
        cls._subs = []
        cls._once = {}

    @classmethod
    def subscribe(cls, callback: Callable) -> None:
        """Register ``callback(cls)``; fired immediately and on every change."""
        cls._subs.append(callback)
        callback(cls)

    @classmethod
    def once(cls, onvalue: Any, callback: Callable) -> None:
        """Run ``callback(cls)`` exactly once, when the value becomes ``onvalue``."""
        onvalue = _TYPE_PARAMS[cls.type].normalize(onvalue)
        if onvalue == cls.get():
            callback(cls)
        else:
            cls._once.setdefault(onvalue, []).append(callback)

    @classmethod
    def _notify(cls) -> None:
        for callback in list(cls._subs):
            callback(cls)
        value = cls._value
        if value in cls._once:
            for callback in cls._once.pop(value):
                callback(cls)

    @classmethod
    def _get_default(cls) -> Any:
        return cls.default

    @classmethod
    def get_value_source(cls) -> ValueSource:
        if cls._value_source is None:
            cls.get()
        return cls._value_source

    @classmethod
    def get(cls) -> Any:
        """Get the current value, resolving from the config source on first access."""
        if cls._deprecation_descriptor is not None:
            warnings.warn(
                cls._deprecation_descriptor.deprecation_message(), FutureWarning
            )
        if cls._value is None:
            # None means "not yet resolved" — a parameter can't legally hold None
            try:
                raw = cls._get_raw_from_config()
            except KeyError:
                cls._value = cls._get_default()
                cls._value_source = ValueSource.DEFAULT
            else:
                if not _TYPE_PARAMS[cls.type].verify(raw):
                    raise ValueError(f"Unsupported raw value for {cls.__name__}: {raw}")
                decoded = _TYPE_PARAMS[cls.type].decode(raw)
                cls._value = cls._normalize_and_check(decoded)
                cls._value_source = ValueSource.GOT_FROM_CFG_SOURCE
        return cls._value

    @classmethod
    def _normalize_and_check(cls, value: Any) -> Any:
        value = _TYPE_PARAMS[cls.type].normalize(value)
        if cls.choices is not None and value not in cls.choices:
            raise ValueError(
                f"Unsupported value '{value}' for {cls.__name__}; "
                f"choose one of {cls.choices}"
            )
        return value

    @classmethod
    def put(cls, value: Any) -> None:
        """Set the value at runtime and notify subscribers."""
        cls._check_new_value_ok(value)
        cls._value = cls._normalize_and_check(value)
        cls._value_source = ValueSource.SET_BY_USER
        cls._notify()

    @classmethod
    def _check_new_value_ok(cls, value: Any) -> None:
        """Hook for subclasses to veto a new value (e.g. engine already started)."""

    @classmethod
    @contextlib.contextmanager
    def context(cls, value: Any):
        """Temporarily set the value within a ``with`` block (reference: pubsub.py:466)."""
        old_value, old_source = cls._value, cls._value_source
        try:
            cls.put(value)
            yield cls
        finally:
            cls._value, cls._value_source = old_value, old_source
            cls._notify()

    @classmethod
    def add_option(cls, choice: Any) -> Any:
        """Extend ``choices`` at runtime (used by the backend registry)."""
        if cls.choices is not None:
            choice = _TYPE_PARAMS[cls.type].normalize(choice)
            if choice not in cls.choices:
                cls.choices = (*cls.choices, choice)
        return choice
