"""Print help for every configuration parameter (``python -m modin_tpu.config``).

Reference behavior: /root/reference/modin/config/__main__.py:53-64.
"""

import modin_tpu.config as cfg
from modin_tpu.config.pubsub import Parameter


def print_config_help() -> None:
    for objname in sorted(dir(cfg)):
        obj = getattr(cfg, objname)
        if (
            isinstance(obj, type)
            and issubclass(obj, Parameter)
            and not obj.is_abstract
        ):
            print(f"{obj.get_help()}\n\tCurrent value: {obj.get()}")  # noqa: T201


if __name__ == "__main__":
    print_config_help()
